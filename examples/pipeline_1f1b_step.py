"""1F1B pipeline training in ~40 lines: a decoder-only LM on a virtual
data×fsdp×pipe mesh with the 1F1B (non-interleaved) schedule — one
forward and one backward per stage per tick (the same code runs
unchanged on a TPU slice).

    python examples/pipeline_1f1b_step.py

The one knob vs GPipe is ``TrainConfig(pp_schedule="1f1b")``: the train step
then runs the manual fused forward/backward engine
(``parallel/pipeline.py pipeline_train_1f1b``) whose activation stash is
bounded at 2·stages−1 microbatches no matter how high ``pp_microbatches``
goes — raise M to shrink the pipeline bubble without growing memory. With
fsdp in the mesh, layer params stay ZeRO-3-sharded at rest and are gathered
one layer at a time inside each stage.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from transformer_tpu.config import MeshConfig, ModelConfig, TrainConfig
from transformer_tpu.parallel import (
    create_sharded_state,
    make_mesh,
    make_sharded_steps,
    put_batch,
)


def main() -> None:
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, pipe=2))
    model_cfg = ModelConfig(
        num_layers=4, d_model=64, num_heads=4, dff=128,
        input_vocab_size=1000, target_vocab_size=1000, max_position=32,
        dtype="float32", decoder_only=True,
    )
    train_cfg = TrainConfig(
        batch_size=16, sequence_length=16, warmup_steps=100,
        pp_microbatches=4, pp_schedule="1f1b",
    )

    state, shardings = create_sharded_state(
        jax.random.PRNGKey(0), model_cfg, train_cfg, mesh
    )
    train_step, eval_step = make_sharded_steps(
        mesh, model_cfg, train_cfg, shardings, donate=False
    )

    r = np.random.default_rng(0)
    tgt = r.integers(1, 1000, (16, 16), dtype=np.int32)
    rng = jax.random.PRNGKey(1)
    for i in range(5):
        state, metrics = train_step(
            state, put_batch(tgt, mesh), put_batch(tgt, mesh), rng
        )
        print(f"step {i + 1}: loss {float(metrics['loss']):.4f}")


if __name__ == "__main__":
    main()
