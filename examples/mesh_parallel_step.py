"""Mesh-parallel training in ~40 lines: one sharded train step on a virtual
8-device CPU mesh (the same code runs unchanged on a TPU slice).

    python examples/mesh_parallel_step.py

Axes are config, not code: change `MeshConfig(data=2, fsdp=2, model=2)` to
any shape (seq/pipe/expert included — see README "Composition matrix") and
the same `make_sharded_steps` builds the right program; XLA inserts the
collectives from the sharding annotations.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from transformer_tpu.config import MeshConfig, ModelConfig, TrainConfig
from transformer_tpu.parallel import (
    create_sharded_state,
    make_mesh,
    make_sharded_steps,
    put_batch,
)


def main() -> None:
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, model=2))
    model_cfg = ModelConfig(
        num_layers=2, d_model=64, num_heads=4, dff=128,
        input_vocab_size=1000, target_vocab_size=1000, max_position=32,
        dtype="float32",
    )
    train_cfg = TrainConfig(batch_size=16, sequence_length=16, warmup_steps=100)

    # Params/optimizer state are INITIALIZED sharded (no host-side full copy);
    # the returned shardings drive the jitted step's in/out specs.
    state, shardings = create_sharded_state(
        jax.random.PRNGKey(0), model_cfg, train_cfg, mesh
    )
    train_step, eval_step = make_sharded_steps(
        mesh, model_cfg, train_cfg, shardings
    )

    r = np.random.default_rng(0)
    src = r.integers(1, 1000, (16, 16), dtype=np.int32)
    tgt = r.integers(1, 1000, (16, 16), dtype=np.int32)
    rng = jax.random.PRNGKey(1)
    for i in range(5):
        state, metrics = train_step(
            state, put_batch(src, mesh), put_batch(tgt, mesh), rng
        )
        print(f"step {i + 1}: loss {float(metrics['loss']):.4f}")
    print("param sharding example:",
          state.params["encoder"]["layers"][0]["ffn"]["in"]["kernel"].sharding)


if __name__ == "__main__":
    main()
