"""Minimal library-API walkthrough: train a tiny seq2seq Transformer on the
bundled corpus, decode a sentence, export, reload, score BLEU.

    JAX_PLATFORMS=cpu python examples/train_tiny_seq2seq.py

Everything here is the same public API the CLIs wrap (`cli/train.py`); this
file exists to show the four moving parts — data, config, trainer, decode —
without the flag system.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from transformer_tpu.config import ModelConfig, TrainConfig
from transformer_tpu.data import load_dataset
from transformer_tpu.train import CheckpointManager, Trainer, create_train_state
from transformer_tpu.train.checkpoint import export_params, load_exported_params
from transformer_tpu.train.decode import translate
from transformer_tpu.train.evaluate import bleu_on_pairs, read_lines

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKDIR = "/tmp/ttpu_example"


def main() -> None:
    os.makedirs(WORKDIR, exist_ok=True)

    # 1. Data: builds (or reloads) subword vocabs, returns static-shape
    #    batched datasets. exclude_test_overlap keeps the bundled 500-pair
    #    test split out of training so eval is honest.
    train_ds, test_ds, src_tok, tgt_tok = load_dataset(
        os.path.join(REPO, "data"),
        os.path.join(WORKDIR, "src_vocab.subwords"),
        os.path.join(WORKDIR, "tgt_vocab.subwords"),
        batch_size=64,
        sequence_length=40,
        target_vocab_size=4096,
        exclude_test_overlap=True,
    )

    # 2. Config: two frozen dataclasses. Every capability is a knob here
    #    (parallel meshes, MoE, GQA, RoPE, windows, quantized export, ...).
    model_cfg = ModelConfig(
        num_layers=2, d_model=128, num_heads=4, dff=512,
        input_vocab_size=src_tok.model_vocab_size,
        target_vocab_size=tgt_tok.model_vocab_size,
        max_position=64,
        dtype="float32",  # bfloat16 on real TPUs
    )
    train_cfg = TrainConfig(
        batch_size=64, sequence_length=40, epochs=2, warmup_steps=500,
        label_smoothing=0.1, ckpt_path=os.path.join(WORKDIR, "ckpt"),
    )

    # 3. Train: jitted donated step, device-side metrics, checkpoint
    #    rotation, restore-before-train (rerunning this script resumes).
    state = create_train_state(jax.random.PRNGKey(0), model_cfg, train_cfg)
    trainer = Trainer(
        model_cfg, train_cfg, state,
        checkpoint=CheckpointManager(train_cfg.ckpt_path, 3),
    )
    trainer.fit(train_ds, test_ds)

    # 4. Decode + export + eval.
    print(translate(
        trainer.state.params, model_cfg, src_tok, tgt_tok,
        ["he goes to school"], max_len=40,
    )[0])
    export_params(
        trainer.state.params, model_cfg, os.path.join(WORKDIR, "model"),
        quantize="int8",  # ~4x smaller artifact, dequantized on load
    )
    reloaded = load_exported_params(
        os.path.join(WORKDIR, "model"), trainer.state.params
    )
    bleu, _ = bleu_on_pairs(
        reloaded, model_cfg, src_tok, tgt_tok,
        read_lines(os.path.join(REPO, "data", "src-test.txt"))[:64],
        read_lines(os.path.join(REPO, "data", "tgt-test.txt"))[:64],
        max_len=40,
    )
    print(f"test BLEU (64 pairs, int8 export): {bleu:.2f}")


if __name__ == "__main__":
    main()
