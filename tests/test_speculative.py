"""Speculative decoding contracts (``transformer_tpu/serve/speculative.py``):
greedy speculative output must be BYTE-IDENTICAL to plain greedy decode —
standalone (``lm_generate_speculative`` vs ``lm_generate``) and through the
continuous scheduler — across both drafters, k in {1, 2, 4}, chunked and
unchunked prefill, and the int8/GQA cache variants. Plus: rejection-sampling
acceptance, rolling-window refusal, O(1) rollback semantics, speculative
telemetry, and the zero-recompile guarantee across varying accept lengths."""

import dataclasses
import io
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import PAD_ID, ModelConfig
from transformer_tpu.data.tokenizer import SubwordTokenizer
from transformer_tpu.models import transformer_init
from transformer_tpu.serve import ContinuousScheduler, ModelDrafter, NgramDrafter
from transformer_tpu.serve.speculative import (
    build_verify_row,
    judge_row,
    speculative_generate,
)
from transformer_tpu.train.decode import lm_generate, lm_generate_speculative

LM = ModelConfig(
    num_layers=2, d_model=16, num_heads=4, dff=32,
    input_vocab_size=48, target_vocab_size=48, max_position=64,
    decoder_only=True, tie_output=True, dtype="float32", dropout_rate=0.0,
)

# Speculation composes with every NON-ROLLING cache variant; rolling-window
# caches are structurally refused (eviction defeats rollback-by-index).
VARIANTS = {
    "base": LM,
    "int8": dataclasses.replace(LM, kv_cache_int8=True),
    "gqa": dataclasses.replace(LM, num_kv_heads=2),
}

PROMPTS = [
    [1, 5, 9, 5, 9, 7],           # repetitive: n-gram drafting lands
    [1, 11, 23, 7],               # irregular: drafts mostly miss
    [1],                          # bare BOS: drafting from nothing
]


class NoDrafter:
    """A drafter that never proposes — speculative machinery reduces to
    plain stepping, which must be EXACTLY plain decoding (incl. sampled
    draws, since bonus picks use the same position-keyed rng folding)."""

    def start(self, prompt_ids):
        return None

    def propose(self, state, context, k):
        return []


def _drafters(params, cfg):
    # The draft model IS the target model here: the ideal drafter (every
    # proposal accepted) — losslessness must hold at both extremes.
    return {
        "ngram": NgramDrafter(),
        "model": ModelDrafter(params, cfg, cfg.max_position + 1, eos_id=2),
    }


@pytest.mark.parametrize("name", sorted(VARIANTS))
@pytest.mark.parametrize("k", [1, 2, 4])
def test_greedy_lossless_standalone(name, k):
    """Greedy lm_generate_speculative == lm_generate, bit for bit, for both
    drafters and chunked/unchunked prefill (the PR's acceptance bar)."""
    cfg = VARIANTS[name]
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    max_new = 10
    for prompt in PROMPTS:
        want = np.asarray(
            lm_generate(
                params, jnp.asarray([prompt], jnp.int32), cfg, max_new,
                eos_id=2,
            )
        )[0]
        for dname, drafter in _drafters(params, cfg).items():
            for chunk in (0, 3):
                got, stats = lm_generate_speculative(
                    params, prompt, cfg, max_new, 2,
                    speculate_k=k, drafter=drafter, prefill_chunk=chunk,
                )
                padded = np.full(max_new, PAD_ID, np.int32)
                padded[: len(got)] = got
                np.testing.assert_array_equal(
                    padded, want,
                    err_msg=f"{name} k={k} drafter={dname} chunk={chunk}",
                )
                assert stats["verify_forwards"] >= 1
                assert 0 <= stats["accepted"] <= stats["drafted"]


def test_sampled_matches_plain_with_no_drafts():
    """With a drafter that never proposes, SAMPLED speculative generation
    must equal plain sampled lm_generate bit for bit: bonus picks fold the
    rng by absolute position exactly like the sequential loop, so the
    machinery itself adds no randomness."""
    cfg = LM
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    prompt = [1, 5, 9, 5, 9, 7]
    kw = dict(sample=True, temperature=0.8, top_k=8, top_p=0.9)
    want = np.asarray(
        lm_generate(
            params, jnp.asarray([prompt], jnp.int32), cfg, 8, eos_id=2,
            rng=jax.random.PRNGKey(7), **kw,
        )
    )[0]
    got, _ = speculative_generate(
        params, cfg, prompt, 8, 2, speculate_k=3, drafter=NoDrafter(),
        seed=7, **kw,
    )
    padded = np.full(8, PAD_ID, np.int32)
    padded[: len(got)] = got
    np.testing.assert_array_equal(padded, want)


def test_sampled_rejection_acceptance_runs():
    """Sampled + a live drafter: rejection-sampling acceptance produces a
    valid stream (distribution-losslessness is the design contract; the
    draw-level contract — no drafts == plain — is pinned above)."""
    cfg = LM
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    got, stats = speculative_generate(
        params, cfg, [1, 5, 9, 5, 9, 7], 10, 2, speculate_k=3,
        drafter=NgramDrafter(), sample=True, temperature=0.9, top_k=8,
        seed=3,
    )
    assert all(0 <= t < cfg.target_vocab_size for t in got)
    assert stats["verify_forwards"] >= 1
    # Deterministic: same seed, same stream.
    again, _ = speculative_generate(
        params, cfg, [1, 5, 9, 5, 9, 7], 10, 2, speculate_k=3,
        drafter=NgramDrafter(), sample=True, temperature=0.9, top_k=8,
        seed=3,
    )
    assert got == again


# --------------------------------------------------------------------------
# scheduler integration


@pytest.fixture(scope="module")
def lm():
    tok = SubwordTokenizer.build_from_corpus(
        ["ab cd ef gh ij kl mn"] * 3, target_vocab_size=300
    )
    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=32, decoder_only=True, tie_output=True,
        dtype="float32", dropout_rate=0.0,
    )
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    return params, cfg, tok


REQS = [
    {"prompt": "ab cd ef gh ij", "max_new": 6},
    {"prompt": "kl", "max_new": 2},
    {"prompt": "ef", "max_new": 0},           # empty-budget edge
    {"prompt": "ab cd", "max_new": 8, "temperature": 0.9, "seed": 3},
    {"prompt": "mn ef cd", "max_new": 1},
    {"prompt": "gh ij kl mn", "max_new": 5, "temperature": 0.7, "top_k": 4,
     "seed": 1},
]


@pytest.mark.parametrize("k", [1, 2, 4])
def test_scheduler_greedy_parity(lm, k):
    """Speculative scheduler == plain scheduler for every GREEDY request
    (byte-identical continuations) under mixed traffic, for both drafters,
    while sampled requests still answer."""
    params, cfg, tok = lm
    plain = ContinuousScheduler(params, cfg, tok, num_slots=2).run(
        [dict(r) for r in REQS]
    )
    for dname, drafter in _drafters(params, cfg).items():
        sched = ContinuousScheduler(
            params, cfg, tok, num_slots=2, speculate_k=k, drafter=drafter
        )
        got = sched.run([dict(r) for r in REQS])
        for i, r in enumerate(REQS):
            assert "continuation" in got[i], (k, dname, got[i])
            if float(r.get("temperature", 0.0)) == 0.0:
                assert got[i] == plain[i], (k, dname, i)
        assert sched.stats["steps"] > 0
        # Slots recycled and the pool drained, like the plain path.
        assert not sched.busy and len(sched._free) == 2


def test_scheduler_no_drafts_full_parity(lm):
    """With a never-proposing drafter the speculative path must reproduce
    the plain scheduler EXACTLY — sampled requests included (bonus picks
    use the same position-keyed folding sequential serving uses)."""
    params, cfg, tok = lm
    plain = ContinuousScheduler(params, cfg, tok, num_slots=2).run(
        [dict(r) for r in REQS]
    )
    got = ContinuousScheduler(
        params, cfg, tok, num_slots=2, speculate_k=3, drafter=NoDrafter()
    ).run([dict(r) for r in REQS])
    assert got == plain


def test_scheduler_mixed_spec_and_chunked_prefill(lm):
    """Per-request "speculate": false rides the same verify step (padded
    row) with identical answers, and chunked prefill (tail-fed prompts)
    composes with speculation."""
    params, cfg, tok = lm
    plain = ContinuousScheduler(params, cfg, tok, num_slots=2).run(
        [dict(REQS[0]), dict(REQS[0]), dict(REQS[1])]
    )
    sched = ContinuousScheduler(
        params, cfg, tok, num_slots=2, speculate_k=2, prefill_chunk=2
    )
    got = sched.run(
        [dict(REQS[0]), dict(REQS[0], speculate=False), dict(REQS[1])]
    )
    assert [g["continuation"] for g in got] == [
        p["continuation"] for p in plain
    ]


def test_scheduler_error_isolation_with_speculation(lm):
    """Admission failures still answer alone and never leak a slot when
    speculation is on (the per-request isolation guarantee)."""
    params, cfg, tok = lm
    good = {"prompt": "ab cd", "max_new": 3}
    over = {"prompt": "ab cd ef gh " * 30, "max_new": 3}
    sched = ContinuousScheduler(params, cfg, tok, num_slots=2, speculate_k=2)
    got = sched.run([dict(good), dict(over), dict(good)])
    assert got[0]["continuation"] == got[2]["continuation"]
    assert "max_position" in got[1]["error"]
    assert len(sched._free) == 2


def test_rolling_window_refused():
    """Rolling-window caches cannot roll back (eviction): the scheduler,
    the standalone loop, and the cache helper itself all refuse."""
    from transformer_tpu.ops.attention import init_cache, rollback_cache

    cfg = dataclasses.replace(LM, attention_window=4)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="roll"):
        speculative_generate(params, cfg, [1, 5], 4, 2, speculate_k=2)
    tok = SubwordTokenizer.build_from_corpus(["ab cd"] * 3, target_vocab_size=280)
    cfg_tok = dataclasses.replace(
        cfg,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
    )
    with pytest.raises(ValueError, match="rolling"):
        ContinuousScheduler(
            transformer_init(jax.random.PRNGKey(0), cfg_tok), cfg_tok, tok,
            num_slots=1, speculate_k=2,
        )
    with pytest.raises(ValueError, match="rolling"):
        rollback_cache(init_cache(1, 8, 2, 4, window=4), 0)


def test_model_drafter_vocab_mismatch_refused_at_construction():
    """A draft model whose vocab differs from the target's must fail at
    startup — a draft token id past the target's (V,) logits would
    otherwise crash the acceptance path mid-serve."""
    cfg = dataclasses.replace(LM, target_vocab_size=64, input_vocab_size=64)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="SHARED tokenizer"):
        ModelDrafter(params, cfg, 33, target_vocab_size=48)
    # Matching vocab constructs fine.
    ModelDrafter(params, cfg, 33, target_vocab_size=64)


# --------------------------------------------------------------------------
# planning/judging units


def test_ngram_drafter_prefers_full_continuations():
    """The drafter returns the most recent match with a FULL k-token
    continuation (a match hugging the context end has nothing after it)."""
    d = NgramDrafter(max_n=2)
    ctx = [1, 7, 8, 9, 5, 7, 8]
    # suffix (7, 8) matches at index 1 with continuation [9, 5].
    assert d.propose(None, ctx, 2) == [9, 5]
    assert d.propose(None, ctx, 1) == [9]
    assert d.propose(None, [1, 2, 3], 2) == []  # no repeat: nothing credible
    assert d.propose(None, [1], 2) == []


def test_build_verify_row_phases():
    """Prompt tail is teacher-forced ahead of drafts; drafts only extend
    the END of the determined history."""
    history = [1, 2, 3, 4, 5]  # prompt_len 5, nothing generated

    class Fixed:
        def propose(self, state, context, k):
            return [9] * k

    # Mid-prompt: forced tokens fill the row before any proposal.
    row, n = build_verify_row(history, 1, 2, Fixed(), None)
    assert row == [2, 3, 4] and n == 0
    # Boundary-straddling: forced tail + proposals.
    row, n = build_verify_row(history, 3, 3, Fixed(), None)
    assert row == [4, 5, 9, 9] and n == 2
    # Generating (history ends at the pending token): all proposals.
    row, n = build_verify_row(history, 4, 2, Fixed(), None)
    assert row == [5, 9, 9] and n == 2


def test_judge_row_accept_reject_bonus():
    picks = {0: 9, 1: 9, 2: 4}
    accept = lambda j, d: (picks[j] == d, picks[j])  # noqa: E731
    bonus = lambda j: picks[j]  # noqa: E731
    # Full accept: every draft matches, bonus appended, all fed kept.
    emitted, keep, acc = judge_row([7, 9, 9], 5, 5, accept, bonus)
    assert (emitted, keep, acc) == ([9, 9, 4], 3, 2)
    # Mismatch at the second draft: its corrected pick is emitted, the
    # rejected tail is dropped (keep < row width).
    emitted, keep, acc = judge_row([7, 9, 8], 5, 5, accept, bonus)
    assert (emitted, keep, acc) == ([9, 9], 2, 1)
    # Entirely inside the prompt: nothing emitted, everything kept.
    emitted, keep, acc = judge_row([7, 9, 9], 0, 10, accept, bonus)
    assert (emitted, keep, acc) == ([], 3, 0)


@pytest.mark.parametrize(
    "temperature,top_k,top_p",
    [(1.0, 0, 1.0), (0.7, 0, 1.0), (1.0, 5, 1.0), (0.9, 0, 0.8),
     (0.8, 6, 0.9), (2.0, 3, 0.5)],
)
def test_filtered_probs_matches_sample_token_distribution(
    monkeypatch, temperature, top_k, top_p
):
    """``filtered_probs`` is the host-side twin of ``sample_token``'s
    truncated distribution — the rejection-sampling acceptance contract
    rests on the two agreeing. Pin them against the PRODUCTION path: grab
    the exact filtered logits ``sample_token`` hands to
    ``jax.random.categorical`` and compare softmax(those) to
    ``filtered_probs`` (a drift in either side's temperature/top-k/top-p
    semantics fails here, not as a silently biased output distribution)."""
    from transformer_tpu.serve.speculative import filtered_probs
    from transformer_tpu.train.decode import sample_token

    rng = np.random.default_rng(0)
    logits = rng.normal(size=(1, 32)).astype(np.float32) * 3.0
    captured = {}
    real = jax.random.categorical

    def spy(key, final_logits, axis=-1):
        captured["logits"] = np.asarray(final_logits, np.float32)
        return real(key, final_logits, axis=axis)

    monkeypatch.setattr(jax.random, "categorical", spy)
    sample_token(
        jnp.asarray(logits), jax.random.PRNGKey(0), sample=True,
        temperature=temperature, top_k=top_k, top_p=top_p,
    )
    device = captured["logits"][0]
    finite = np.isfinite(device)
    want = np.zeros_like(device)
    want[finite] = np.exp(device[finite] - device[finite].max())
    want /= want.sum()
    got = filtered_probs(logits[0], temperature, top_k, top_p)
    np.testing.assert_array_equal(got > 0, finite)  # identical support
    np.testing.assert_allclose(got, want, atol=1e-6)


# --------------------------------------------------------------------------
# telemetry + retrace


def test_speculative_telemetry_inert_and_counted(lm):
    """Telemetry on/off never changes speculative answers; spans carry
    drafted/accepted/forwards; summarize derives tokens-per-forward and
    acceptance rate; spec counters land in the registry."""
    from transformer_tpu.obs import EventLog, Telemetry
    from transformer_tpu.obs.__main__ import summarize_events

    params, cfg, tok = lm
    reqs = [dict(r) for r in REQS[:4]]
    plain = ContinuousScheduler(
        params, cfg, tok, num_slots=2, speculate_k=2
    ).run([dict(r) for r in reqs])
    buf = io.StringIO()
    tel = Telemetry(events=EventLog(buf), interval=0.0)
    sched = ContinuousScheduler(
        params, cfg, tok, num_slots=2, speculate_k=2, telemetry=tel
    )
    got = sched.run([dict(r) for r in reqs])
    assert got == plain  # answers byte-identical, metrics on or off

    events = [json.loads(line) for line in buf.getvalue().splitlines()]
    spans = [e for e in events if e.get("kind") == "serve.request"]
    assert spans and all("forwards" in s for s in spans if s.get("new_tokens"))
    assert any("drafted" in s for s in spans)
    report = summarize_events(events)
    assert report["serve"]["tokens_per_forward"] > 0
    spec = report["serve"]["speculative"]
    assert spec["drafted"] >= spec["accepted"] >= 0
    assert 0.0 <= spec["acceptance_rate"] <= 1.0
    snap = tel.registry.snapshot()
    assert snap["serve_spec_drafted_total"] == spec["drafted"]
    assert snap["serve_spec_accepted_total"] == spec["accepted"]


def test_speculative_zero_recompiles():
    """Acceptance criterion: varying accept lengths mint no new programs on
    the scheduler's speculative hot path (verify/pick/prefill/rollback)."""
    from transformer_tpu.analysis.retrace import speculative_retrace_report

    deltas = speculative_retrace_report(steps=3)
    assert len(deltas) == 4
    bad = [d.to_dict() for d in deltas if not d.within_budget]
    assert not bad, bad


def test_verify_contract_covers_cache_variants():
    """The verify-step cache-parity contract runs for every LM cache
    variant in the fast matrix (plain/int8/rolling/GQA)."""
    from transformer_tpu.analysis import run_contracts

    results = run_contracts("fast")
    verify = {r.config for r in results if r.contract == "verify_cache_parity"}
    assert {"lm_bf16", "lm_int8_cache", "lm_window", "lm_gqa"} <= verify
    assert all(
        r.ok for r in results if r.contract == "verify_cache_parity"
    ), [str(r) for r in results if r.contract == "verify_cache_parity"]


@pytest.mark.slow  # subprocess + timing loop: slow tier
def test_decode_bench_speculative_acceptance():
    """benchmarks/decode_bench.py --speculate_k 4: tokens-per-forward must
    exceed 1.5 (the PR's acceptance bar) and the JSONL row is well-formed."""
    import os
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                      "decode_bench.py"),
         "--reps", "2", "--speculate_k", "4", "--decode_steps", "48"],
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    row = json.loads(out.stdout.strip().splitlines()[-1])
    spec = row["speculative"][0]
    assert spec["k"] == 4
    assert spec["tokens_per_forward"] > 1.5, spec
    assert 0.0 <= spec["acceptance_rate"] <= 1.0
    bench_rows = [
        json.loads(line) for line in out.stderr.splitlines()
        if line.startswith("{")
    ]
    assert any(
        r.get("metric") == "speculative decode tokens-per-forward"
        and r.get("config", {}).get("speculate_k") == 4
        for r in bench_rows
    ), out.stderr[-2000:]
