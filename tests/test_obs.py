"""Unified telemetry (``transformer_tpu/obs``): quantile engine, registry +
Prometheus exposition, JSONL event log, tfevents sink round-trip (framing +
proto decoded back in-test), scheduler span lifecycle (admit mid-flight,
error isolation, monotone timings, byte-identical answers), trainer
instrumentation, CLI flag plumbing, and the summarize report."""

import io
import json
import math
import os
import struct
import subprocess
import sys

import pytest

from transformer_tpu.obs import (
    EventLog,
    MetricsRegistry,
    StreamingHistogram,
    Telemetry,
    read_events,
    timed_call,
)

# --------------------------------------------------------------------------
# quantile engine


def test_streaming_histogram_quantiles_within_bucket_error():
    h = StreamingHistogram()
    for i in range(1, 1001):
        h.observe(i / 1000.0)  # 1ms .. 1s uniform
    # Relative error bound: sqrt(growth) - 1 (geometric bucket midpoint).
    bound = math.sqrt(h.growth) - 1 + 1e-9
    for q, exact in ((0.5, 0.5), (0.95, 0.95), (0.99, 0.99)):
        got = h.quantile(q)
        assert abs(got - exact) / exact <= bound, (q, got)
    assert h.count == 1000
    assert h.min == 0.001 and h.max == 1.0
    assert abs(h.mean - 0.5005) < 1e-9


def test_streaming_histogram_weighted_observe_and_edge_cases():
    h = StreamingHistogram()
    h.observe(0.01, n=99)
    h.observe(10.0)
    assert h.count == 100
    assert h.quantile(0.5) == pytest.approx(0.01, rel=0.05)
    assert h.quantile(1.0) == 10.0  # clamped to observed max
    h.observe(float("nan"))  # ignored, never poisons
    assert h.count == 100
    h.observe(1e-12)  # below lo: clamps into first bucket
    h.observe(1e12)   # above hi: clamps into last bucket
    assert h.count == 102 and h.max == 1e12
    assert StreamingHistogram().snapshot() == {"count": 0}
    assert StreamingHistogram().quantile(0.5) == 0.0


def test_streaming_histogram_buckets_are_ascending_nonempty():
    h = StreamingHistogram()
    for v in (0.001, 0.001, 0.5, 2.0):
        h.observe(v)
    buckets = h.buckets()
    bounds = [b for b, _ in buckets]
    assert bounds == sorted(bounds)
    assert sum(c for _, c in buckets) == h.count


def test_streaming_histogram_counts_out_of_range_samples():
    """Satellite audit: samples outside [lo, hi) clamp into the edge
    buckets (historical behavior) but are now COUNTED and surfaced by
    snapshot() — a mis-ranged histogram announces itself instead of
    silently reporting clamp artifacts as tail quantiles."""
    h = StreamingHistogram()
    h.observe(0.5)
    assert h.underflow == 0 and h.overflow == 0
    assert "underflow" not in h.snapshot()  # in-range: schema unchanged
    h.observe(1e-9, n=3)   # below lo=1e-6
    h.observe(5e4)         # at/above hi=1e4
    h.observe(2e5)
    snap = h.snapshot()
    assert snap["underflow"] == 3 and h.underflow == 3
    assert snap["overflow"] == 2 and h.overflow == 2
    assert snap["count"] == 6
    # Exact side-stats still honest at the tails.
    assert snap["min"] == 1e-9 and snap["max"] == 2e5
    # Boundary semantics: lo is IN range, hi is not.
    h2 = StreamingHistogram(lo=1e-3, hi=1.0)
    h2.observe(1e-3)
    h2.observe(1.0)
    assert h2.underflow == 0 and h2.overflow == 1


def test_streaming_histogram_error_bound_vs_sorted_reference():
    """Satellite: pin the documented quantile error bound (sqrt(growth)-1
    relative) against an exact sorted-reference quantile over a seeded
    non-uniform stream — the bound must hold at every reported
    percentile, not just on uniform data."""
    import random

    rng = random.Random(1234)
    h = StreamingHistogram()
    samples = []
    for _ in range(5000):
        # Log-uniform over ~7 decades of the in-range span: exercises many
        # buckets, including sparse tails.
        v = 10 ** rng.uniform(-5.5, 3.5)
        samples.append(v)
        h.observe(v)
    samples.sort()
    bound = math.sqrt(h.growth) - 1 + 1e-9
    for q in (0.01, 0.25, 0.5, 0.9, 0.95, 0.99, 0.999):
        exact = samples[min(len(samples) - 1, max(0, math.ceil(q * len(samples)) - 1))]
        got = h.quantile(q)
        assert abs(got - exact) / exact <= bound, (q, got, exact)
    assert h.underflow == 0 and h.overflow == 0


# --------------------------------------------------------------------------
# StepTimer reuse (satellite: one quantile implementation, shared stream)


def test_step_timer_histogram_and_summary_percentiles():
    from transformer_tpu.utils.profiling import StepTimer

    t = StepTimer(tokens_per_step=10)
    for _ in range(4):
        t.tick()
    t.sync()
    assert t.histogram.count == 4  # window time attributed per step
    s = t.summary()
    assert "p50" in s and "p95" in s and "p99" in s
    # The registry binds the SAME StreamingHistogram instance — no duplicate
    # quantile accounting between StepTimer and the obs export.
    reg = MetricsRegistry()
    m = reg.histogram("train_step_seconds", hist=t.histogram)
    assert m.hist is t.histogram
    with pytest.raises(ValueError, match="different sample stream"):
        reg.histogram("train_step_seconds", hist=StreamingHistogram())


# --------------------------------------------------------------------------
# registry + Prometheus exposition


def test_registry_kinds_and_validation():
    reg = MetricsRegistry()
    c = reg.counter("req_total")
    c.inc()
    c.inc(2)
    assert c.value == 3
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    g = reg.gauge("depth")
    g.set(4)
    g.dec()
    assert g.value == 3
    assert reg.counter("req_total") is c  # get-or-create
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("req_total")
    with pytest.raises(ValueError, match="not Prometheus-exposable"):
        reg.counter("bad name!")


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.counter("req_total", "requests").inc(5)
    reg.gauge("occupancy").set(0.5)
    h = reg.histogram("lat_seconds", "latency")
    for v in (0.01, 0.02, 0.02, 0.5):
        h.observe(v)
    text = reg.to_prometheus_text()
    assert "# HELP req_total requests" in text
    assert "# TYPE req_total counter" in text
    assert "req_total 5" in text
    assert "occupancy 0.5" in text
    assert "# TYPE lat_seconds histogram" in text
    assert 'lat_seconds_bucket{le="+Inf"} 4' in text
    assert "lat_seconds_count 4" in text
    # Bucket counts are CUMULATIVE and end at the total.
    counts = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("lat_seconds_bucket")
    ]
    assert counts == sorted(counts) and counts[-1] == 4


# --------------------------------------------------------------------------
# event log


def test_event_log_concurrent_writers_parse_back(tmp_path):
    """The EventLog threading contract: N real threads hammering emit()
    produce a log where EVERY line parses back as one JSON event — no torn
    lines, no lost events. (The deterministic-schedule twin of this test
    lives in analysis/schedules.py eventlog_writers; the revert-the-lock
    canary in test_analysis.py shows the explorer catching the torn case.)"""
    import threading

    from transformer_tpu.obs.events import EventLog, read_events

    path = str(tmp_path / "concurrent.jsonl")
    log = EventLog(path)
    writers, per = 8, 100
    start = threading.Barrier(writers)

    def hammer(wid):
        start.wait()
        for i in range(per):
            log.emit("obs.test", writer=wid, seq=i)

    threads = [
        threading.Thread(target=hammer, args=(w,)) for w in range(writers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    raw = [ln for ln in open(path).read().splitlines() if ln]
    assert len(raw) == writers * per
    events = []
    for line in raw:
        events.append(json.loads(line))  # a torn line dies right here
    assert len(read_events(path, "obs.test")) == writers * per
    # every (writer, seq) pair exactly once, in per-writer order
    by_writer = {}
    for ev in events:
        by_writer.setdefault(ev["writer"], []).append(ev["seq"])
    assert set(by_writer) == set(range(writers))
    for seqs in by_writer.values():
        assert seqs == list(range(per))


def test_event_log_round_trip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path)
    log.emit("serve.request", order=1, total_s=0.5)
    log.emit("train.window", steps=10)
    log.close()
    with open(path, "a") as f:
        f.write("{truncated mid-crash\n")  # must not break readers
    events = read_events(path)
    assert [e["kind"] for e in events] == ["serve.request", "train.window"]
    assert all("ts" in e for e in events)
    assert read_events(path, kind="train.window")[0]["steps"] == 10


def test_event_log_survives_unwritable_sink(capsys):
    buf = io.StringIO()
    log = EventLog(buf)
    log.emit("a", x=1)
    buf.close()
    log.emit("b", x=2)  # write to closed file: degrade, never raise
    log.emit("c", x=3)
    log.flush()
    assert "telemetry disabled" in capsys.readouterr().err


# --------------------------------------------------------------------------
# telemetry bundle


def test_telemetry_flush_interval_and_prom_file(tmp_path):
    jsonl = str(tmp_path / "m.jsonl")
    tel = Telemetry(
        events=EventLog(jsonl), prom_path=jsonl + ".prom", interval=3600.0
    )
    tel.registry.counter("x_total").inc()
    assert tel.maybe_flush() is True   # first flush always runs
    assert tel.maybe_flush() is False  # interval gates the second
    assert tel.maybe_flush(force=True) is True
    tel.close()
    snaps = read_events(jsonl, kind="metrics.snapshot")
    assert len(snaps) == 3  # two explicit + close()
    assert snaps[-1]["metrics"]["x_total"] == 1
    assert "x_total 1" in open(jsonl + ".prom").read()
    assert not os.path.exists(jsonl + ".prom.tmp")  # atomic replace


def test_prometheus_http_endpoint():
    import urllib.request

    tel = Telemetry()
    tel.registry.gauge("up").set(1)
    port = tel.start_prometheus_server(0)  # OS-assigned port
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        assert "# TYPE up gauge" in body and "up 1" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope", timeout=10)
    finally:
        tel.close()


def test_timed_call_records_and_forwards():
    reg = MetricsRegistry()
    h, c = reg.histogram("h"), reg.counter("c_total")
    fn = timed_call(lambda x: x + 1, h, c)
    assert fn(41) == 42
    assert h.hist.count == 1 and c.value == 1
    assert fn.__wrapped__(41) == 42  # underlying fn stays reachable


# --------------------------------------------------------------------------
# tfevents sink: decode the wire format back (masked-crc + varint framing)


def _tfrecords(path):
    from transformer_tpu.utils.tensorboard import _masked_crc

    data = open(path, "rb").read()
    records, off = [], 0
    while off < len(data):
        (length,) = struct.unpack("<Q", data[off:off + 8])
        (hcrc,) = struct.unpack("<I", data[off + 8:off + 12])
        assert hcrc == _masked_crc(data[off:off + 8]), "header crc mismatch"
        payload = data[off + 12:off + 12 + length]
        (pcrc,) = struct.unpack("<I", data[off + 12 + length:off + 16 + length])
        assert pcrc == _masked_crc(payload), "payload crc mismatch"
        records.append(payload)
        off += 16 + length
    return records


def _parse_proto(buf):
    """Minimal wire-format parser: field -> list of raw values (varint int,
    fixed32/64 bytes, or length-delimited bytes)."""
    fields, off = {}, 0
    while off < len(buf):
        tag, off = _read_varint(buf, off)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, off = _read_varint(buf, off)
        elif wire == 1:
            val, off = buf[off:off + 8], off + 8
        elif wire == 5:
            val, off = buf[off:off + 4], off + 4
        elif wire == 2:
            n, off = _read_varint(buf, off)
            val, off = buf[off:off + n], off + n
        else:  # pragma: no cover - writer never emits groups
            raise AssertionError(f"unexpected wire type {wire}")
        fields.setdefault(field, []).append(val)
    return fields


def _read_varint(buf, off):
    shift = val = 0
    while True:
        b = buf[off]
        off += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, off
        shift += 7


def _packed_doubles(raw: bytes) -> list:
    return [v for (v,) in struct.iter_unpack("<d", raw)]


def test_tfevents_scalar_and_histogram_round_trip(tmp_path):
    from transformer_tpu.utils.tensorboard import SummaryWriter

    w = SummaryWriter(str(tmp_path))
    w.scalar("loss", 1.25, step=7)
    h = StreamingHistogram()
    for v in (0.001, 0.002, 0.002, 0.4):
        h.observe(v)
    w.histogram("step_time_s", h, step=7)
    w.histogram("empty", StreamingHistogram(), step=7)  # skipped, not written
    w.close()

    records = _tfrecords(w.path)
    assert len(records) == 3  # file_version + scalar + histogram

    version = _parse_proto(records[0])
    assert version[3] == [b"brain.Event:2"]

    scalar_event = _parse_proto(records[1])
    assert scalar_event[2] == [7]  # Event.step varint
    value = _parse_proto(_parse_proto(scalar_event[5][0])[1][0])
    assert value[1] == [b"loss"]
    (loss,) = struct.unpack("<f", value[2][0])
    assert loss == 1.25

    hist_event = _parse_proto(records[2])
    assert hist_event[2] == [7]
    value = _parse_proto(_parse_proto(hist_event[5][0])[1][0])
    assert value[1] == [b"step_time_s"]
    assert 4 not in value  # field 4 is Image — histo MUST be field 5
    histo = _parse_proto(value[5][0])
    (hmin,) = struct.unpack("<d", histo[1][0])
    (hmax,) = struct.unpack("<d", histo[2][0])
    (num,) = struct.unpack("<d", histo[3][0])
    (total,) = struct.unpack("<d", histo[4][0])
    (sum_sq,) = struct.unpack("<d", histo[5][0])
    assert (hmin, hmax, num) == (0.001, 0.4, 4.0)
    assert total == pytest.approx(0.405)
    assert sum_sq == pytest.approx(h.sum_squares)
    limits = _packed_doubles(histo[6][0])
    counts = _packed_doubles(histo[7][0])
    assert len(limits) == len(counts)
    assert sum(counts) == 4.0
    assert limits == sorted(limits)


# --------------------------------------------------------------------------
# scheduler span lifecycle (CPU tiny model)


@pytest.fixture(scope="module")
def lm():
    import jax

    from transformer_tpu.config import ModelConfig
    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.models import transformer_init

    tok = SubwordTokenizer.build_from_corpus(
        ["ab cd ef gh ij kl mn"] * 3, target_vocab_size=300
    )
    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=32, decoder_only=True, tie_output=True,
        dtype="float32", dropout_rate=0.0,
    )
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    return params, cfg, tok


def _scheduler(lm, telemetry, num_slots=2, prefill_chunk=0):
    from transformer_tpu.serve import ContinuousScheduler

    params, cfg, tok = lm
    return ContinuousScheduler(
        params, cfg, tok, num_slots=num_slots, max_total=32,
        default_max_new=4, prefill_chunk=prefill_chunk, telemetry=telemetry,
    )


def test_scheduler_spans_and_byte_identity(lm):
    reqs = [
        {"prompt": "ab cd ef gh ij", "max_new": 6},
        {"prompt": "kl", "max_new": 2},
        {"prompt": "ab cd", "max_new": 8, "temperature": 0.9, "seed": 3},
        {"prompt": "mn ef", "max_new": 3},
        {"prompt": "gh", "max_new": 1},
    ]
    plain = _scheduler(lm, None).run(reqs)
    buf = io.StringIO()
    tel = Telemetry(events=EventLog(buf), interval=0.0)
    instrumented = _scheduler(lm, tel).run(reqs)
    # Metrics on/off must be invisible in the answers (acceptance criterion).
    assert plain == instrumented

    events = [json.loads(line) for line in buf.getvalue().splitlines()]
    spans = [e for e in events if e["kind"] == "serve.request"]
    assert len(spans) == len(reqs)
    for s in spans:
        # Per-request timings are monotone along the request lifecycle.
        assert 0 <= s["queue_s"] <= s["total_s"]
        assert 0 <= s["prefill_s"] <= s["total_s"]
        assert s["queue_s"] + s["prefill_s"] <= s["total_s"] + 1e-9
        assert s["queue_s"] <= s["ttft_s"] <= s["total_s"]
        assert s["new_tokens"] >= 0 and s["prompt_tokens"] > 0
    by_order = {s["order"]: s for s in spans}
    assert by_order[0]["new_tokens"] == 6
    assert by_order[4]["new_tokens"] == 1

    snap = [e for e in events if e["kind"] == "metrics.snapshot"][-1]["metrics"]
    # Admit-mid-flight actually happened: 5 requests through 2 slots.
    assert snap["serve_admissions_total"] == 5
    assert snap["serve_retirements_total"] == 5
    assert snap["serve_slots_total"] == 2
    assert snap["serve_generated_tokens_total"] == sum(
        s["new_tokens"] for s in spans
    )
    assert snap["serve_queue_seconds"]["count"] == 5
    assert snap["serve_request_seconds"]["p95"] > 0


def test_scheduler_spans_cover_chunked_prefill_tail(lm):
    """With --prefill_chunk the un-prefilled prompt tail walks token-by-token
    through the decode loop; the prefill span must close only once the LAST
    prompt token is in cache (incl. the 1-token-tail edge), and timings stay
    monotone. Answers remain byte-identical to the unchunked scheduler."""
    from transformer_tpu.train.decode import prefill_len_for

    _, cfg, tok = lm
    # Prompt lengths around the chunk boundary, so tails of 0 and >=1 tokens
    # (incl. the L == prefill_len + 1 edge) all occur.
    reqs = [
        {"prompt": "ab", "max_new": 2},
        {"prompt": "ab cd", "max_new": 2},
        {"prompt": "ab cd ef", "max_new": 2},
        {"prompt": "ab cd ef gh ij", "max_new": 2},
    ]
    plain = _scheduler(lm, None).run(reqs)
    buf = io.StringIO()
    tel = Telemetry(events=EventLog(buf), interval=0.0)
    chunked = _scheduler(lm, tel, prefill_chunk=2).run(reqs)
    assert plain == chunked
    spans = [
        json.loads(line) for line in buf.getvalue().splitlines()
        if json.loads(line)["kind"] == "serve.request"
    ]
    assert len(spans) == len(reqs)
    tail_fed = 0
    for s in spans:
        assert 0 <= s["prefill_s"] <= s["total_s"]
        assert s["queue_s"] + s["prefill_s"] <= s["total_s"] + 1e-9
        assert s["queue_s"] <= s["ttft_s"] <= s["total_s"]
        L = s["prompt_tokens"]
        if prefill_len_for(L, 2) < L:
            tail_fed += 1
            # Tail steps are real pool steps; a span that closed at dispatch
            # time could not cover them. Weak-but-real floor: the tail-fed
            # prefill span is strictly positive wall time.
            assert s["prefill_s"] > 0
    assert tail_fed >= 1, "no request exercised the chunked tail path"


def test_scheduler_error_isolation_records_error_span(lm):
    _, cfg, _ = lm
    reqs = [
        {"prompt": "ab cd", "max_new": 2},
        {"prompt": "ab " * cfg.max_position, "max_new": 2},  # over-length
        {"prompt": "ef", "max_new": 1},
    ]
    buf = io.StringIO()
    tel = Telemetry(events=EventLog(buf), interval=0.0)
    sched = _scheduler(lm, tel)
    out = sched.run(reqs)
    assert "continuation" in out[0] and "continuation" in out[2]
    assert "error" in out[1] and "max_position" in out[1]["error"]
    events = [json.loads(line) for line in buf.getvalue().splitlines()]
    errs = [e for e in events if e["kind"] == "serve.request" and "error" in e]
    assert len(errs) == 1 and errs[0]["order"] == 1
    assert errs[0]["queue_s"] >= 0
    snap = [e for e in events if e["kind"] == "metrics.snapshot"][-1]["metrics"]
    assert snap["serve_errors_total"] == 1
    assert snap["serve_admissions_total"] == 2  # the poisoned one never admits
    # Pre-answered (routing) errors also count and record a span.
    sched.submit_done({"error": "LM export serves 'prompt', not 'src'"})
    sched.drain_ready()
    tel.maybe_flush(force=True)
    events = [json.loads(line) for line in buf.getvalue().splitlines()]
    snap = [e for e in events if e["kind"] == "metrics.snapshot"][-1]["metrics"]
    assert snap["serve_errors_total"] == 2
    assert snap["serve_requests_total"] == 4


def test_scheduler_zero_recompiles_with_telemetry(lm):
    """Telemetry on the steady-state decode path must not cost a single
    recompile (the retrace-sentinel acceptance criterion, asserted directly
    on the instrumented scheduler)."""
    from transformer_tpu.analysis.retrace import RetraceSentinel
    from transformer_tpu.serve import scheduler as sched_mod

    tel = Telemetry(interval=0.0)
    warm = _scheduler(lm, tel)
    warm.run([{"prompt": "ab cd", "max_new": 3}])
    sentinel = RetraceSentinel()
    sentinel.watch("_pool_step", sched_mod._pool_step, budget=0)
    sentinel.watch("_slot_prefill", sched_mod._slot_prefill, budget=0)
    sentinel.watch("_pick_pool", sched_mod._pick_pool, budget=0)
    sentinel.snapshot()
    for _ in range(3):
        s = _scheduler(lm, tel)
        out = s.run([{"prompt": "ab cd", "max_new": 3}])
        assert "continuation" in out[0]
    sentinel.assert_within_budget()


# --------------------------------------------------------------------------
# trainer instrumentation (tiny CPU run) + summarize report


def _tiny_train(tmp_path, jsonl):
    import jax
    import numpy as np

    from transformer_tpu.config import ModelConfig, TrainConfig
    from transformer_tpu.train import Trainer, create_train_state

    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=64, target_vocab_size=64, max_position=64,
        dropout_rate=0.0, dtype="float32", decoder_only=True,
    )
    tcfg = TrainConfig(
        batch_size=2, sequence_length=8, epochs=2, warmup_steps=10,
        log_every_steps=2, eval_every_steps=0,
    )

    class DS:
        def __len__(self):
            return 4

        def batches(self, epoch):
            r = np.random.default_rng(epoch)
            for _ in range(4):
                ids = r.integers(1, 64, size=(2, 8)).astype(np.int32)
                yield ids, ids

    tel = Telemetry(
        events=EventLog(jsonl), prom_path=jsonl + ".prom", interval=0.0
    )
    state = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
    tr = Trainer(cfg, tcfg, state, telemetry=tel, log_fn=lambda s: None)
    tr.fit(DS(), DS())
    tel.close()
    return tr


def test_trainer_telemetry_and_grad_norm(tmp_path):
    jsonl = str(tmp_path / "train.jsonl")
    tr = _tiny_train(tmp_path, jsonl)
    windows = read_events(jsonl, kind="train.window")
    assert windows, "no train.window events recorded"
    assert sum(w["steps"] for w in windows) == 8  # 2 epochs x 4 steps
    for w in windows:
        assert w["tokens"] > 0 and w["window_s"] >= 0
        assert w["loss"] > 0 and 0 <= w["accuracy"] <= 1
        assert w["grad_norm"] > 0  # the new train-step metric, synced reads
    evals = read_events(jsonl, kind="train.eval")
    assert evals and evals[-1]["loss"] > 0
    compiles = read_events(jsonl, kind="train.compile")
    assert compiles and compiles[-1]["cache_sizes"]["train_step"] >= 1
    prom = open(jsonl + ".prom").read()
    assert "train_grad_norm" in prom and "train_tokens_total" in prom
    assert "train_step_seconds_count" in prom  # StepTimer-backed histogram
    assert tr.step_timer.histogram.count == 8
    # The telemetry-enabled trainer routes dispatches through timed_call —
    # the production path the telemetry_inert contract pins.
    assert tr.train_step.__wrapped__ is not None
    assert tr._m_dispatch.hist.count == 8
    assert "train_dispatch_seconds_count 8" in prom


def test_summarize_cli_on_real_run(tmp_path, capsys, lm):
    """Acceptance: summarize over a short CPU train run AND a serve session
    reports tokens/s, step p50/p95, slot utilization, latency breakdown."""
    from transformer_tpu.obs.__main__ import main as obs_main

    jsonl = str(tmp_path / "run.jsonl")
    _tiny_train(tmp_path, jsonl)
    tel = Telemetry(events=EventLog(jsonl), interval=0.0)
    _scheduler(lm, tel).run(
        [{"prompt": "ab cd", "max_new": 4}, {"prompt": "ef", "max_new": 2}]
    )
    tel.close()

    assert obs_main(["summarize", jsonl]) == 0
    text = capsys.readouterr().out
    assert "tokens/s" in text
    assert "step time: p50" in text and "p95" in text
    assert "slot utilization" in text
    assert "first token" in text and "queue" in text and "total" in text

    assert obs_main(["summarize", jsonl, "--format", "json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["train"]["tokens_per_sec"] is not None
    assert report["train"]["step_seconds"]["p95"] > 0
    assert report["serve"]["requests"] == 2
    assert report["serve"]["spans"]["total_s"]["count"] == 2
    assert "slot_utilization" in report["serve"]

    assert obs_main(["summarize", str(tmp_path / "missing.jsonl")]) == 2


def test_summarize_snapshot_only_serve_log():
    """A serve session killed before any request finished leaves only
    metrics.snapshot events — the report must render, not KeyError."""
    from transformer_tpu.obs.__main__ import render_text, summarize_events

    events = [{
        "ts": 1.0, "kind": "metrics.snapshot",
        "metrics": {"serve_slots_active": 1, "serve_slots_total": 2},
    }]
    report = summarize_events(events)
    text = render_text(report)
    assert "slot utilization" in text and "50.0%" in text


def test_summarize_tolerates_truncated_final_line(tmp_path, capsys):
    """A crash mid-write leaves the log's FINAL line torn — exactly the
    shape a fault-injected sink or an OOM-killed server produces. The
    summarize CLI must report the intact prefix, exit 0, and never raise;
    a snapshot whose metrics payload is not a dict is skipped the same
    way."""
    from transformer_tpu.obs.__main__ import main as obs_main

    jsonl = tmp_path / "crash.jsonl"
    jsonl.write_text(
        json.dumps({"ts": 1.0, "kind": "serve.request", "order": 0,
                    "new_tokens": 3, "total_s": 0.5}) + "\n"
        + json.dumps({"ts": 2.0, "kind": "metrics.snapshot",
                      "metrics": "not-a-dict"}) + "\n"
        + '{"ts": 3.0, "kind": "serve.request", "order": 1, "new_tok'
    )
    assert obs_main(["summarize", str(jsonl)]) == 0
    out = capsys.readouterr().out
    assert "2 events" in out          # torn line skipped, intact ones kept
    assert "1 requests" in out

    # byte-level truncation of a real log tail behaves the same
    real = tmp_path / "real.jsonl"
    real.write_text(
        json.dumps({"ts": 1.0, "kind": "serve.request", "order": 0,
                    "new_tokens": 2, "total_s": 0.25}) + "\n"
        + json.dumps({"ts": 2.0, "kind": "serve.request", "order": 1,
                      "new_tokens": 4, "total_s": 0.5}) + "\n"
    )
    real.write_bytes(real.read_bytes()[:-17])  # tear the final line
    assert obs_main(["summarize", str(real), "--format", "json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["events"] == 1 and report["serve"]["requests"] == 1


def test_summarize_breaker_degraded_time():
    """serve.breaker transitions -> per-breaker opens + degraded seconds
    (open/half-open time between open and the closing transition)."""
    from transformer_tpu.obs.__main__ import render_text, summarize_events

    events = [
        {"ts": 10.0, "kind": "serve.breaker", "name": "speculative",
         "state": "open", "previous": "closed"},
        {"ts": 12.5, "kind": "serve.breaker", "name": "speculative",
         "state": "half_open", "previous": "open"},
        {"ts": 13.0, "kind": "serve.breaker", "name": "speculative",
         "state": "closed", "previous": "half_open"},
        {"ts": 20.0, "kind": "serve.breaker", "name": "prefix_cache",
         "state": "open", "previous": "closed"},
        # never closes: degraded through end-of-log
        {"ts": 26.0, "kind": "metrics.snapshot", "metrics": {}},
    ]
    report = summarize_events(events)
    brk = report["serve"]["breakers"]
    assert brk["speculative"]["opens"] == 1
    assert brk["speculative"]["degraded_s"] == pytest.approx(3.0)
    assert brk["speculative"]["final_state"] == "closed"
    assert brk["prefix_cache"]["degraded_s"] == pytest.approx(6.0)
    assert brk["prefix_cache"]["final_state"] == "open"
    text = render_text(report)
    assert "breakers:" in text and "degraded" in text
    assert "[open]" in text  # still-degraded breakers are called out


def test_summarize_grouped_serve_batches():
    from transformer_tpu.obs.__main__ import render_text, summarize_events

    events = [
        {"ts": 1.0, "kind": "serve.batch", "size": 3, "errors": 1,
         "batch_s": 0.5},
        {"ts": 2.0, "kind": "serve.batch", "size": 2, "errors": 0,
         "batch_s": 0.25},
    ]
    report = summarize_events(events)
    g = report["serve_grouped"]
    assert g["batches"] == 2 and g["requests"] == 5 and g["errors"] == 1
    assert g["batch_s"]["count"] == 2
    text = render_text(report)
    assert "serve (grouped): 5 requests (1 errored) in 2 batches" in text


# --------------------------------------------------------------------------
# CLI flag plumbing smoke (absl flags are process-global -> subprocess)

_FLAGS_SNIPPET = """
import sys, os
from absl import flags
from transformer_tpu.cli.flags import define_flags, flags_to_telemetry
define_flags()
flags.FLAGS(sys.argv)
tel = flags_to_telemetry()
if tel is None:
    print("none")
else:
    tel.registry.counter("smoke_total").inc()
    tel.emit("smoke", ok=True)
    tel.close()
    print("jsonl" if tel.events else "nojsonl", tel.prom_path or "noprom",
          tel.interval)
"""


def _run_flags(*argv):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, "-c", _FLAGS_SNIPPET, *argv],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout.strip()


def test_metrics_flags_default_off():
    assert _run_flags() == "none"


def test_metrics_flags_build_telemetry(tmp_path):
    jsonl = str(tmp_path / "m.jsonl")
    out = _run_flags(f"--metrics_jsonl={jsonl}", "--metrics_interval=2.5")
    assert out == f"jsonl {jsonl}.prom 2.5"
    events = read_events(jsonl)
    kinds = {e["kind"] for e in events}
    assert "smoke" in kinds and "metrics.snapshot" in kinds
    assert "smoke_total 1" in open(jsonl + ".prom").read()


def test_serve_cli_defines_metrics_flags():
    """cli.serve's separate flag surface carries the shared metrics flags
    (the serve CLI is where --metrics_port matters)."""
    snippet = """
import sys
from absl import flags
from transformer_tpu.cli.serve import define_serve_flags
define_serve_flags()
flags.FLAGS(sys.argv)
print(repr(flags.FLAGS.metrics_jsonl), flags.FLAGS.metrics_port,
      flags.FLAGS.metrics_interval)
"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, "-c", snippet, "--metrics_port=9099"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert out.stdout.split() == ["''", "9099", "10.0"]


# --------------------------------------------------------------------------
# lint + contract coverage for the new package


def test_obs_package_lints_clean():
    """Satellite: all three analysis lint families over obs/ are clean
    WITHOUT baseline help (no new grandfathered findings; the package-wide
    tier-1 lint in test_analysis.py covers it against the checked-in
    baseline too). The trace/slo/merge modules ride the same bar."""
    from transformer_tpu.analysis import run_rules
    from transformer_tpu.analysis.concurrency import run_concurrency
    from transformer_tpu.analysis.sharding import run_sharding

    obs_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "transformer_tpu", "obs",
    )
    for run in (run_rules, run_concurrency, run_sharding):
        report = run(paths=[obs_dir])
        assert report.findings == [], (
            run.__name__ + ":\n"
            + "\n".join(str(f) for f in report.findings)
        )
        assert report.files_checked >= 9
    assert {"trace.py", "slo.py", "merge.py"} <= set(os.listdir(obs_dir))


def test_obs_package_is_jax_free():
    """The telemetry-inert guarantee starts at import structure: nothing
    under obs/ may import jax or numpy (quantiles/registry/events run in
    bench wrapper processes and the summarize CLI without a jax tax)."""
    import ast

    obs_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "transformer_tpu", "obs",
    )
    for fname in os.listdir(obs_dir):
        if not fname.endswith(".py"):
            continue
        tree = ast.parse(open(os.path.join(obs_dir, fname)).read())
        for node in ast.walk(tree):
            mods = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                mods = [node.module or ""]
            for mod in mods:
                root = mod.split(".")[0]
                assert root not in ("jax", "jaxlib", "numpy"), (
                    f"{fname} imports {mod}"
                )


def test_telemetry_inert_contract_catches_a_leak():
    """The contract must FAIL (not vacuously pass) when a wrapper adds an
    operation to the traced computation."""
    import re

    import jax
    import jax.numpy as jnp

    def canon(j):
        return re.sub(r"0x[0-9a-f]+", "0x", str(j))

    def f(x):
        return x * 2

    leaky = lambda x: f(x) + 0.0  # noqa: E731 — the 'improved' wrapper
    good = timed_call(f, None, None)
    x = jax.ShapeDtypeStruct((2,), jnp.float32)
    assert canon(jax.make_jaxpr(f)(x)) == canon(jax.make_jaxpr(good)(x))
    assert canon(jax.make_jaxpr(f)(x)) != canon(jax.make_jaxpr(leaky)(x))
