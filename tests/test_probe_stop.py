"""Keep-best / probe-stop rule tests (VERDICT r4 #2): the flagship BLEU
run's stopping logic — consecutive-miss patience, best tracking, JSON
persistence across resumed invocations, and the Trainer.fit callback-stop
hook it rides on."""

import dataclasses

import jax
import numpy as np

from transformer_tpu.config import ModelConfig, TrainConfig
from transformer_tpu.train import CheckpointManager, Trainer, create_train_state
from transformer_tpu.train.probe_stop import ProbeKeepBest

TINY = ModelConfig(
    num_layers=1, d_model=16, num_heads=2, dff=32,
    input_vocab_size=30, target_vocab_size=30, max_position=32,
    dtype="float32", dropout_rate=0.0,
)
TCFG = TrainConfig(batch_size=4, sequence_length=8, epochs=1, warmup_steps=100)


class _FixedBatches:
    """Minimal dataset stub: the same batch ``n`` times per epoch."""

    def __init__(self, n=4, seed=0):
        self.n = n
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
        self.src = np.asarray(jax.random.randint(k1, (4, 8), 1, 30))
        self.tgt = np.asarray(jax.random.randint(k2, (4, 8), 1, 30))

    def __len__(self):
        return self.n

    def batches(self, epoch=0):
        for _ in range(self.n):
            yield self.src, self.tgt


class TestProbeKeepBest:
    def test_first_probe_is_best(self, tmp_path):
        s = ProbeKeepBest(str(tmp_path / "p.json"), patience=2)
        assert s.update(10, 0.21) == "new_best"
        assert s.best_epoch == 10 and s.best_value == 0.21

    def test_stops_after_patience_misses(self, tmp_path):
        s = ProbeKeepBest(str(tmp_path / "p.json"), patience=2)
        assert s.update(10, 1.0) == "new_best"
        assert s.update(14, 2.0) == "new_best"
        assert s.update(18, 1.9) == "continue"
        assert s.update(22, 1.8) == "stop"
        assert s.stopped_epoch == 22
        assert s.best_epoch == 14  # the peak, not the stop point

    def test_recovery_resets_the_window(self, tmp_path):
        """A miss followed by a new best must NOT carry the miss count
        forward — only CONSECUTIVE misses since the best count."""
        s = ProbeKeepBest(str(tmp_path / "p.json"), patience=2)
        s.update(4, 1.0)
        s.update(8, 0.9)          # miss
        assert s.update(12, 1.5) == "new_best"
        assert s.update(16, 1.4) == "continue"  # 1 miss, not 2
        assert s.stopped_epoch is None

    def test_persistence_across_instances(self, tmp_path):
        """The resumable-run pattern: each relay window is a fresh process;
        the decision state must ride the JSON, not the object."""
        path = str(tmp_path / "p.json")
        s = ProbeKeepBest(path, patience=2)
        s.update(10, 2.0)
        s.update(14, 1.9)
        s2 = ProbeKeepBest(path, patience=2)  # "next invocation"
        assert s2.best_epoch == 10 and s2.misses_since_best == 1
        assert s2.update(18, 1.8) == "stop"
        s3 = ProbeKeepBest(path, patience=2)
        assert s3.stopped_epoch == 18  # a stop decided last window holds

    def test_reprobe_same_epoch_replaces(self, tmp_path):
        """A resumed invocation re-probing its restore-point epoch must not
        double-count a miss."""
        s = ProbeKeepBest(str(tmp_path / "p.json"), patience=2)
        s.update(10, 2.0)
        s.update(14, 1.9)
        s.update(14, 1.9)  # same epoch again: replace, not append
        assert s.misses_since_best == 1
        assert len(s.probes) == 2

    def test_min_delta_gates_new_best(self, tmp_path):
        s = ProbeKeepBest(str(tmp_path / "p.json"), patience=3, min_delta=0.1)
        s.update(4, 1.0)
        assert s.update(8, 1.05) == "continue"  # within delta: a miss
        assert s.best_epoch == 4

    def test_patience_zero_never_stops(self, tmp_path):
        s = ProbeKeepBest(str(tmp_path / "p.json"), patience=0)
        s.update(4, 2.0)
        for e in (8, 12, 16, 20):
            assert s.update(e, 1.0) == "continue"
        assert s.stopped_epoch is None
        assert s.best_epoch == 4  # best-tracking still runs (keep-best export)


class TestTrainerCallbackStop:
    def test_truthy_callback_return_stops_fit(self, tmp_path):
        """The hook the probe rule rides on: a truthy epoch_callback return
        ends fit after that epoch, and the epoch's checkpoint is saved even
        off the every-N cadence."""
        tc = dataclasses.replace(
            TCFG, epochs=6, warmup_steps=10, eval_every_steps=0,
            log_every_steps=0, checkpoint_every_epochs=5,
        )
        mgr = CheckpointManager(str(tmp_path), max_to_keep=2, is_primary=True)
        state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        logs, seen = [], []

        def cb(epoch, tr):
            seen.append(epoch)
            return epoch == 1  # stop after the second epoch

        tr = Trainer(TINY, tc, state, checkpoint=mgr, log_fn=logs.append)
        tr.fit(_FixedBatches(n=4, seed=0), epoch_callback=cb)
        assert seen == [0, 1]  # epoch 2..5 never ran
        assert any("stop requested by epoch callback" in l for l in logs)
        # 2 epochs x 4 steps, saved at the stop despite cadence 5:
        assert mgr.all_steps() == [8]
        # No EARLY_STOPPED marker: that file gates the plateau rule only.
        assert not (tmp_path / "EARLY_STOPPED").exists()

    def test_none_return_keeps_training(self):
        tc = dataclasses.replace(
            TCFG, epochs=3, warmup_steps=10, eval_every_steps=0,
            log_every_steps=0,
        )
        state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        seen = []
        tr = Trainer(TINY, tc, state, log_fn=lambda s: None)
        tr.fit(_FixedBatches(n=4, seed=0),
               epoch_callback=lambda e, t: seen.append(e))
        assert seen == [0, 1, 2]  # list.append returns None: no stop
