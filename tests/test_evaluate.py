"""BLEU eval path: decode → detokenize → score must reflect model quality.

The reference computes no translation-quality metric at all (token accuracy
only, ``train.py:140-141``); VERDICT round 1 flagged that utils/bleu.py was
never called outside unit tests. These tests exercise the full path that the
training CLI / cli.evaluate / benchmarks/bleu_run.py now share.
"""

import jax
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig, TrainConfig
from transformer_tpu.data.tokenizer import SubwordTokenizer
from transformer_tpu.train import create_train_state, make_train_step
from transformer_tpu.train.evaluate import bleu_on_pairs, read_lines

SENTENCES = [
    "the cat sat on the mat",
    "a dog ran in the park",
    "the sun is hot today",
    "we eat bread and jam",
    "she reads a long book",
    "he paints the old door",
    "birds sing in the tree",
    "rain falls on the roof",
]


@pytest.fixture(scope="module")
def overfit_setup():
    """Tiny copy-task model trained to memorize 8 sentence pairs."""
    tok = SubwordTokenizer.build_from_corpus(SENTENCES, target_vocab_size=400)
    cfg = ModelConfig(
        num_layers=1, d_model=32, num_heads=2, dff=64,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=32, dtype="float32", dropout_rate=0.0,
    )
    tcfg = TrainConfig(
        batch_size=8, sequence_length=16, warmup_steps=40,
        loss_normalization="tokens",
    )
    width = 16
    ids = np.zeros((8, width), np.int32)
    for i, s in enumerate(SENTENCES):
        e = [tok.bos_id, *tok.encode(s), tok.eos_id]
        ids[i, : len(e)] = e[:width]
    state = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
    step = jax.jit(make_train_step(cfg, tcfg))
    rng = jax.random.PRNGKey(1)
    for _ in range(250):
        state, metrics = step(state, ids, ids, rng)
    assert float(metrics["loss"]) < 0.3
    return state.params, cfg, tok


class TestBleuOnPairs:
    def test_overfit_model_scores_high(self, overfit_setup):
        params, cfg, tok = overfit_setup
        bleu, hyps = bleu_on_pairs(
            params, cfg, tok, tok, SENTENCES, SENTENCES,
            batch_size=4, max_len=16,
        )
        assert len(hyps) == len(SENTENCES)
        assert bleu > 50.0, (bleu, hyps)

    def test_untrained_model_scores_low(self, overfit_setup):
        _, cfg, tok = overfit_setup
        from transformer_tpu.models import transformer_init

        fresh = transformer_init(jax.random.PRNGKey(7), cfg)
        bleu, _ = bleu_on_pairs(
            fresh, cfg, tok, tok, SENTENCES, SENTENCES,
            batch_size=4, max_len=16,
        )
        assert bleu < 10.0

    def test_mismatched_lengths_raise(self, overfit_setup):
        params, cfg, tok = overfit_setup
        with pytest.raises(ValueError, match="line counts"):
            bleu_on_pairs(params, cfg, tok, tok, SENTENCES, SENTENCES[:-1])


def test_read_lines_strips_newlines(tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("a b\nc d\n")
    assert read_lines(str(p)) == ["a b", "c d"]
