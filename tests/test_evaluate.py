"""BLEU eval path: decode → detokenize → score must reflect model quality.

The reference computes no translation-quality metric at all (token accuracy
only, ``train.py:140-141``); VERDICT round 1 flagged that utils/bleu.py was
never called outside unit tests. These tests exercise the full path that the
training CLI / cli.evaluate / benchmarks/bleu_run.py now share.
"""

import jax
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig, TrainConfig
from transformer_tpu.data.tokenizer import SubwordTokenizer
from transformer_tpu.train import create_train_state, make_train_step
from transformer_tpu.train.evaluate import bleu_on_pairs, read_lines

SENTENCES = [
    "the cat sat on the mat",
    "a dog ran in the park",
    "the sun is hot today",
    "we eat bread and jam",
    "she reads a long book",
    "he paints the old door",
    "birds sing in the tree",
    "rain falls on the roof",
]


@pytest.fixture(scope="module")
def overfit_setup():
    """Tiny copy-task model trained to memorize 8 sentence pairs."""
    tok = SubwordTokenizer.build_from_corpus(SENTENCES, target_vocab_size=400)
    cfg = ModelConfig(
        num_layers=1, d_model=32, num_heads=2, dff=64,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=32, dtype="float32", dropout_rate=0.0,
    )
    tcfg = TrainConfig(
        batch_size=8, sequence_length=16, warmup_steps=40,
        loss_normalization="tokens",
    )
    width = 16
    ids = np.zeros((8, width), np.int32)
    for i, s in enumerate(SENTENCES):
        e = [tok.bos_id, *tok.encode(s), tok.eos_id]
        ids[i, : len(e)] = e[:width]
    state = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
    step = jax.jit(make_train_step(cfg, tcfg))
    rng = jax.random.PRNGKey(1)
    for _ in range(250):
        state, metrics = step(state, ids, ids, rng)
    assert float(metrics["loss"]) < 0.3
    return state.params, cfg, tok


class TestBleuOnPairs:
    def test_overfit_model_scores_high(self, overfit_setup):
        params, cfg, tok = overfit_setup
        bleu, hyps = bleu_on_pairs(
            params, cfg, tok, tok, SENTENCES, SENTENCES,
            batch_size=4, max_len=16,
        )
        assert len(hyps) == len(SENTENCES)
        assert bleu > 50.0, (bleu, hyps)

    def test_untrained_model_scores_low(self, overfit_setup):
        _, cfg, tok = overfit_setup
        from transformer_tpu.models import transformer_init

        fresh = transformer_init(jax.random.PRNGKey(7), cfg)
        bleu, _ = bleu_on_pairs(
            fresh, cfg, tok, tok, SENTENCES, SENTENCES,
            batch_size=4, max_len=16,
        )
        assert bleu < 10.0

    def test_mismatched_lengths_raise(self, overfit_setup):
        params, cfg, tok = overfit_setup
        with pytest.raises(ValueError, match="line counts"):
            bleu_on_pairs(params, cfg, tok, tok, SENTENCES, SENTENCES[:-1])

    def test_decode_invariant_to_bucket_width(self, overfit_setup):
        """The early-exit while_loop must leave outputs identical to a much
        wider decode budget: once every row hit EOS the remaining tail is
        structurally PAD, whatever max_len the serve bucket picked."""
        from transformer_tpu.train.decode import greedy_decode

        params, cfg, tok = overfit_setup
        ids = np.zeros((4, 8), np.int32)
        for i, s in enumerate(SENTENCES[:4]):
            e = [tok.bos_id, *tok.encode(s), tok.eos_id][:8]
            ids[i, : len(e)] = e
        narrow = np.asarray(
            greedy_decode(params, jax.numpy.asarray(ids), cfg, 16,
                          tok.bos_id, tok.eos_id)
        )
        wide = np.asarray(
            greedy_decode(params, jax.numpy.asarray(ids), cfg, 48,
                          tok.bos_id, tok.eos_id)
        )
        assert (wide[:, :16] == narrow).all()
        for r in range(len(wide)):  # finished rows: tail is pure PAD
            if (narrow[r] == tok.eos_id).any():
                assert (wide[r, 16:] == 0).all(), wide[r]


class TestBeamSearch:
    """Beam search (capability beyond the reference's greedy-only decode)."""

    def test_shapes_and_pad_after_eos(self, overfit_setup):
        params, cfg, tok = overfit_setup
        from transformer_tpu.train.decode import beam_search_decode

        ids = np.zeros((3, 8), np.int32)
        for i, s in enumerate(SENTENCES[:3]):
            e = [tok.bos_id, *tok.encode(s), tok.eos_id][:8]
            ids[i, : len(e)] = e
        out = np.asarray(
            beam_search_decode(
                params, jax.numpy.asarray(ids), cfg, 12,
                tok.bos_id, tok.eos_id, beam_size=4,
            )
        )
        assert out.shape == (3, 12)
        for row in out:
            seen_eos = False
            for t in row:
                if seen_eos:
                    assert t == 0, row
                if t == tok.eos_id:
                    seen_eos = True

    def test_beam_matches_or_beats_greedy_on_overfit(self, overfit_setup):
        """On a memorized corpus both decoders should recover the targets;
        beam BLEU must be at least greedy BLEU."""
        params, cfg, tok = overfit_setup
        greedy, _ = bleu_on_pairs(
            params, cfg, tok, tok, SENTENCES, SENTENCES,
            batch_size=4, max_len=16,
        )
        beam, hyps = bleu_on_pairs(
            params, cfg, tok, tok, SENTENCES, SENTENCES,
            batch_size=4, max_len=16, beam_size=4,
        )
        assert len(hyps) == len(SENTENCES)
        assert beam >= greedy - 1e-6, (beam, greedy)
        assert beam > 50.0

    def test_beam_one_equals_greedy_path(self, overfit_setup):
        """beam_size=1 must route through greedy (same outputs)."""
        from transformer_tpu.train.decode import translate

        params, cfg, tok = overfit_setup
        a = translate(params, cfg, tok, tok, SENTENCES[:4], max_len=16)
        b = translate(params, cfg, tok, tok, SENTENCES[:4], max_len=16, beam_size=1)
        assert a == b


class TestLMGenerate:
    """Causal-LM generation (decoder-only inference path)."""

    @pytest.fixture(scope="class")
    def lm_setup(self):
        from transformer_tpu.data.pipeline import make_lm_dataset
        from transformer_tpu.train import create_train_state, make_train_step
        from transformer_tpu.config import TrainConfig

        line = "the cat sat on the mat and the dog ran in the park"
        tok = SubwordTokenizer.build_from_corpus([line] * 3, target_vocab_size=330)
        cfg = ModelConfig(
            num_layers=2, d_model=32, num_heads=2, dff=64,
            input_vocab_size=tok.model_vocab_size,
            target_vocab_size=tok.model_vocab_size,
            max_position=64, dtype="float32", dropout_rate=0.0,
            decoder_only=True,
        )
        tcfg = TrainConfig(batch_size=4, sequence_length=16, warmup_steps=40)
        ds = make_lm_dataset([line] * 40, tok, batch_size=4, sequence_length=16)
        state = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
        step = jax.jit(make_train_step(cfg, tcfg))
        rng = jax.random.PRNGKey(1)
        for epoch in range(30):
            for src, tgt in ds.batches(epoch):
                state, m = step(state, src, tgt, rng)
        assert float(m["loss"]) < 0.5, float(m["loss"])
        return state.params, cfg, tok, line

    def test_greedy_continues_memorized_text(self, lm_setup):
        from transformer_tpu.train.decode import generate

        params, cfg, tok, line = lm_setup
        prompt = "the cat sat"
        [out] = generate(params, cfg, tok, prompt, max_new=8)
        # The LM memorized one sentence on repeat: the continuation must
        # start with the true next words.
        assert out.strip().startswith("on the"), out

    def test_batch_and_padding(self, lm_setup):
        from transformer_tpu.train.decode import generate

        params, cfg, tok, _ = lm_setup
        outs = generate(
            params, cfg, tok, ["the cat sat", "the dog ran in"], max_new=6
        )
        assert len(outs) == 2
        assert all(isinstance(o, str) for o in outs)
        # Different prompt lengths (PAD-right) must still continue the
        # second prompt correctly, not from the padded position.
        assert outs[1].strip().startswith("the"), outs

    def test_sampling_is_deterministic_per_seed(self, lm_setup):
        from transformer_tpu.train.decode import generate

        params, cfg, tok, _ = lm_setup
        a = generate(params, cfg, tok, "the", max_new=6, temperature=0.8, seed=7)
        b = generate(params, cfg, tok, "the", max_new=6, temperature=0.8, seed=7)
        assert a == b

    def test_seq2seq_model_rejected(self, lm_setup):
        from transformer_tpu.train.decode import generate

        _, cfg, tok, _ = lm_setup
        import dataclasses

        s2s = dataclasses.replace(cfg, decoder_only=False)
        with pytest.raises(ValueError, match="decoder_only"):
            generate({}, s2s, tok, "x")


def test_read_lines_strips_newlines(tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("a b\nc d\n")
    assert read_lines(str(p)) == ["a b", "c d"]


class TestPerplexity:
    def test_overfit_lm_scores_low(self):
        """An LM overfit on the sentences must assign them far lower
        perplexity than a random-init model; non-decoder models rejected."""
        import dataclasses

        from transformer_tpu.models import transformer_init
        from transformer_tpu.train import create_train_state, make_train_step
        from transformer_tpu.train.evaluate import perplexity_on_lines

        tok = SubwordTokenizer.build_from_corpus(SENTENCES, target_vocab_size=400)
        cfg = ModelConfig(
            num_layers=1, d_model=32, num_heads=2, dff=64,
            input_vocab_size=tok.model_vocab_size,
            target_vocab_size=tok.model_vocab_size,
            max_position=32, dtype="float32", dropout_rate=0.0,
            decoder_only=True,
        )
        tcfg = TrainConfig(batch_size=8, sequence_length=16, warmup_steps=40)
        width = 16
        ids = np.zeros((8, width), np.int32)
        for i, s in enumerate(SENTENCES):
            e = [tok.bos_id, *tok.encode(s), tok.eos_id]
            ids[i, : len(e)] = e[:width]
        state = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
        step = jax.jit(make_train_step(cfg, tcfg))
        rng = jax.random.PRNGKey(1)
        for _ in range(200):
            state, _ = step(state, None, ids, rng)
        ppl_trained, n = perplexity_on_lines(state.params, cfg, tok, SENTENCES)
        assert n > 0
        random_params = transformer_init(jax.random.PRNGKey(9), cfg)
        ppl_random, _ = perplexity_on_lines(random_params, cfg, tok, SENTENCES)
        assert ppl_trained < 3.0 < ppl_random

        s2s = dataclasses.replace(cfg, decoder_only=False)
        with pytest.raises(ValueError, match="decoder_only"):
            perplexity_on_lines(state.params, s2s, tok, SENTENCES)


def test_dump_attention_maps(tmp_path, overfit_setup):
    """The interpretability artifact: per-layer maps for (src, tgt) pairs,
    trimmed to true lengths, rows summing to 1 (softmax)."""
    from transformer_tpu.train.evaluate import dump_attention_maps

    params, cfg, tok = overfit_setup
    out = str(tmp_path / "attn.npz")
    n = dump_attention_maps(
        params, cfg, tok, tok,
        [SENTENCES[0], SENTENCES[1]], [SENTENCES[0], SENTENCES[1]], out,
    )
    assert n == 2
    with np.load(out) as z:
        names = set(z.files)
        assert "s0/src_ids" in names and "s1/tgt_ids" in names
        assert "s0/encoder_layer1" in names
        assert "s0/decoder_layer1_block1" in names
        assert "s0/decoder_layer1_block2" in names
        enc = z["s0/encoder_layer1"]  # (H, S_src, S_src)
        s_src = len(z["s0/src_ids"])
        assert enc.shape == (cfg.num_heads, s_src, s_src)
        np.testing.assert_allclose(enc.sum(-1), 1.0, atol=1e-5)
        cross = z["s0/decoder_layer1_block2"]  # (H, S_tgt, S_src)
        assert cross.shape == (cfg.num_heads, len(z["s0/tgt_ids"]), s_src)
