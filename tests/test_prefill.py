"""Single-pass chunked prefill parity (the serving fast path's correctness
contract): ingesting the prompt through ``transformer_prefill`` — whole or in
chunks, across the int8-quantized, rolling-window, and GQA cache variants —
must reproduce the token-by-token decode loop bit for bit, both in the caches
it leaves behind and in the generations that start from them."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import PAD_ID, ModelConfig
from transformer_tpu.models import transformer_init
from transformer_tpu.models.decoder import (
    decoder_prefill,
    init_decoder_caches,
)
from transformer_tpu.models.transformer import (
    transformer_decode_step,
    transformer_prefill,
)
from transformer_tpu.train.decode import lm_generate, prefill_len_for

LM = ModelConfig(
    num_layers=2, d_model=16, num_heads=4, dff=32,
    input_vocab_size=48, target_vocab_size=48, max_position=64,
    decoder_only=True, tie_output=True, dtype="float32", dropout_rate=0.0,
)

VARIANTS = {
    "base": LM,
    "int8": dataclasses.replace(LM, kv_cache_int8=True),
    "window": dataclasses.replace(LM, attention_window=3),
    "gqa": dataclasses.replace(LM, num_kv_heads=2),
    "window_int8": dataclasses.replace(
        LM, attention_window=3, kv_cache_int8=True
    ),
}


def _prompts(key=0, batch=3, width=7):
    """Ragged PAD-right prompt batch (lens 7/5/4) — the shape generate()
    hands lm_generate."""
    ids = np.array(
        jax.random.randint(jax.random.PRNGKey(key), (batch, width), 3, 40),
        np.int32,
    )
    ids[1, 5:] = PAD_ID
    ids[2, 4:] = PAD_ID
    return jnp.asarray(ids)


@pytest.mark.parametrize("name", sorted(VARIANTS))
@pytest.mark.parametrize("chunk", [0, 3])
def test_prefill_caches_match_stepwise(name, chunk):
    """decoder_prefill must leave the caches (buffers AND index) exactly
    where feeding the same tokens one step at a time leaves them — per
    variant, whole-prompt and ragged-chunked."""
    cfg = VARIANTS[name]
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    toks = _prompts()[:, :4]  # no PAD: pure cache-write comparison
    total = 10

    step_caches = init_decoder_caches(cfg, 3, total)
    for t in range(4):
        logits_step, step_caches = transformer_decode_step(
            params, toks[:, t : t + 1], None, None, step_caches,
            jnp.int32(t), cfg,
        )

    pre_caches = init_decoder_caches(cfg, 3, total)
    x_last, pre_caches = decoder_prefill(
        params["decoder"], toks, None, None, pre_caches, cfg, chunk=chunk
    )
    logits_pre, _ = transformer_prefill(
        params, toks, None, None, init_decoder_caches(cfg, 3, total), 0, cfg,
        chunk=chunk,
    )

    for lc_step, lc_pre in zip(step_caches, pre_caches):
        assert set(lc_step) == set(lc_pre)
        assert int(lc_pre["index"]) == 4
        for k in lc_step:
            a = np.asarray(lc_step[k], np.float32)
            b = np.asarray(lc_pre[k], np.float32)
            if np.asarray(lc_step[k]).dtype == np.int8:
                # int8 codes may flip by ONE step: the chunked forward's
                # last-ulp fp differences can cross a rounding boundary.
                # The dequantized error that admits is below the int8
                # scheme's own quantization noise (pinned by the greedy /
                # sampled bit-parity tests below).
                assert np.max(np.abs(a - b)) <= 1, f"{name} cache[{k}]"
            else:
                np.testing.assert_allclose(
                    a, b, atol=2e-5, err_msg=f"{name} cache[{k}]"
                )
    # The prefill's last-position logits are the decode loop's tick-3 logits.
    np.testing.assert_allclose(
        np.asarray(logits_pre), np.asarray(logits_step), atol=2e-4,
        err_msg=name,
    )


@pytest.mark.parametrize("name", sorted(VARIANTS))
@pytest.mark.parametrize("chunk", [0, 3])
def test_lm_generate_prefill_parity_greedy(name, chunk):
    """Greedy generation from a chunked-prefilled cache is bit-identical to
    the pure token-by-token loop (prefill_len=0)."""
    cfg = VARIANTS[name]
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    ids = _prompts()
    want = lm_generate(params, ids, cfg, 6, eos_id=2)
    got = lm_generate(
        params, ids, cfg, 6, eos_id=2, prefill_len=4, prefill_chunk=chunk
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want), err_msg=name)


@pytest.mark.parametrize("name", sorted(VARIANTS))
def test_lm_generate_prefill_parity_sampled(name):
    """sample=True with a fixed rng: position-keyed rng folding means the
    prefilled path draws the same tokens as the loop, bit for bit."""
    cfg = VARIANTS[name]
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    ids = _prompts(key=1)
    kw = dict(
        rng=jax.random.PRNGKey(7), sample=True, temperature=0.8,
        top_k=8, top_p=0.9,
    )
    want = lm_generate(params, ids, cfg, 6, eos_id=2, **kw)
    got = lm_generate(
        params, ids, cfg, 6, eos_id=2, prefill_len=4, prefill_chunk=3, **kw
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want), err_msg=name)


def test_generate_text_parity(monkeypatch):
    """Text-level end-to-end: generate() with prefill enabled (the default)
    returns the same strings as with prefill forced off."""
    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.train import decode as decode_mod

    tok = SubwordTokenizer.build_from_corpus(
        ["ab cd ef gh ij kl"] * 3, target_vocab_size=280
    )
    cfg = dataclasses.replace(
        LM,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=32,
    )
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    prompts = ["ab cd ef", "gh ij"]
    with_prefill = decode_mod.generate(
        params, cfg, tok, prompts, max_new=5, prefill_chunk=2
    )
    monkeypatch.setattr(decode_mod, "prefill_len_for", lambda *a: 0)
    without = decode_mod.generate(params, cfg, tok, prompts, max_new=5)
    assert with_prefill == without


def test_prefill_len_for_bucketing():
    """Prefill lengths bucket (power of two, or multiples of the chunk) so
    serving compiles a bounded set of prefill signatures."""
    assert prefill_len_for(0) == 0
    assert prefill_len_for(1) == 1
    assert prefill_len_for(7) == 4
    assert prefill_len_for(64) == 64
    assert prefill_len_for(65) == 64
    assert prefill_len_for(65, chunk=16) == 64
    assert prefill_len_for(15, chunk=16) == 8  # under one chunk: pow2 rule
    assert prefill_len_for(33, chunk=16) == 32
    # Chunk COUNTS round to powers of two — O(log) distinct signatures,
    # not O(max_len / chunk).
    assert prefill_len_for(50, chunk=16) == 32  # 3 chunks -> 2 chunks
    assert prefill_len_for(4096, chunk=16) == 4096
    # A typo'd negative chunk flag must behave as "no chunking", never
    # return a negative length (the scheduler slices ids[:n] with it).
    assert prefill_len_for(7, chunk=-2) == 4
    assert prefill_len_for(64, chunk=-2) == 64


def test_prefill_is_single_pass(monkeypatch):
    """The structural claim: a 64-token prompt prefills in ceil(64 / chunk)
    decoder forwards — never 64 sequential decode steps."""
    from transformer_tpu.models import decoder as decoder_mod

    calls = []
    real = decoder_mod.decoder_apply

    def counting(params, ids, *a, **kw):
        calls.append(ids.shape[1])
        return real(params, ids, *a, **kw)

    monkeypatch.setattr(decoder_mod, "decoder_apply", counting)
    cfg = dataclasses.replace(LM, max_position=80)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    toks = jnp.asarray(
        jax.random.randint(jax.random.PRNGKey(2), (1, 64), 3, 40), jnp.int32
    )
    caches = init_decoder_caches(cfg, 1, 70)
    decoder_prefill(params["decoder"], toks, None, None, caches, cfg)
    assert calls == [64]  # one full-width pass
    calls.clear()
    caches = init_decoder_caches(cfg, 1, 70)
    decoder_prefill(params["decoder"], toks, None, None, caches, cfg, chunk=16)
    assert calls == [16, 16, 16, 16]
    calls.clear()
    caches = init_decoder_caches(cfg, 1, 70)
    # chunk <= 0 normalizes to one full-width pass (never an empty loop).
    decoder_prefill(params["decoder"], toks, None, None, caches, cfg, chunk=-2)
    assert calls == [64]


@pytest.mark.slow  # subprocess + timing loop: slow tier
def test_decode_bench_acceptance():
    """benchmarks/decode_bench.py on CPU: prefill ingests prompt tokens at
    >= 3x the incremental-decode rate for the small config, and a 64-token
    prompt compiles to ONE forward (the PR's acceptance bar)."""
    import json
    import os
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                      "decode_bench.py"),
         "--reps", "3"],
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    row = json.loads(out.stdout.strip().splitlines()[-1])
    assert row["prefill_forward_calls"] == 1
    assert row["prefill_vs_decode"] >= 3.0, row


def test_rolling_prefill_chunk_cap():
    """A rolling-window cache caps prefill chunks at its buffer length (a
    wider chunk would evict positions still inside an earlier chunk token's
    band); decoder_prefill splits automatically."""
    cfg = VARIANTS["window"]
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    toks = _prompts()[:, :6]
    caches = init_decoder_caches(cfg, 3, 10)
    assert caches[0]["k"].shape[1] == 3  # rolling buffer = window slots
    # chunk=0 would mean "all 6 at once": must be capped to 3 internally.
    _, caches = decoder_prefill(
        params["decoder"], toks, None, None, caches, cfg, chunk=0
    )
    assert int(caches[0]["index"]) == 6
