"""Fast-tier smoke coverage for the two subsystems the fast path was blind to.

Everything substantial about the Pallas kernel and the sharded engine lives in
the slow tier (test_flash.py, test_distributed.py — interpret-mode sweeps,
8-device parity matrices). Those stay slow; this module adds one MINIMAL
specimen of each so `pytest -m "not slow"` — the tier CI and pre-commit runs
actually exercise — compiles at least one Pallas kernel and one shard_map
collective instead of zero. Shapes are the smallest that still cross the
interesting boundaries (2 blocks per axis for flash; 2 mesh devices for DP).
"""

import jax
import numpy as np
import pytest

from transformer_tpu.config import MeshConfig, ModelConfig, TrainConfig
from transformer_tpu.kernels.flash_attention import flash_attention
from transformer_tpu.ops.attention import dot_product_attention
from transformer_tpu.train import create_train_state, make_train_step


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def test_flash_causal_smoke(rng):
    """Interpret-mode flash forward at 2x2 blocks vs the XLA oracle."""
    import jax.numpy as jnp

    b, s, h, d = 1, 32, 2, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )
    got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    mask = jnp.tril(jnp.ones((s, s), bool))[None, None]
    want, _ = dot_product_attention(q, k, v, mask)
    np.testing.assert_allclose(got, want, atol=2e-6)


def test_flash_grad_smoke(rng):
    """The custom-VJP backward kernel compiles and matches XLA grads."""
    import jax.numpy as jnp

    b, s, h, d = 1, 32, 1, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=16, block_k=16).sum()

    def f_xla(q, k, v):
        mask = jnp.tril(jnp.ones((s, s), bool))[None, None]
        return dot_product_attention(q, k, v, mask)[0].sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_xla = jax.grad(f_xla, argnums=(0, 1, 2))(q, k, v)
    for gf, gx in zip(g_flash, g_xla):
        np.testing.assert_allclose(gf, gx, atol=5e-6)


def test_dp2_parity_smoke():
    """A 2-device data-parallel train step reproduces the single-device loss
    (the full 8-device parity matrix is slow-tier; this pins the shard_map +
    psum path itself into the fast tier)."""
    # Lazy import: transformer_tpu.parallel needs jax.shard_map, which older
    # jax spells differently — a version skew there must skip THIS test, not
    # take the whole module's collection (and the flash/prefill smokes) down.
    # exc_type: the failure here is a plain ImportError (the module exists;
    # the jax attribute doesn't), which importorskip only deprecatedly skips.
    parallel = pytest.importorskip(
        "transformer_tpu.parallel", exc_type=ImportError
    )
    create_sharded_state = parallel.create_sharded_state
    make_mesh = parallel.make_mesh
    make_sharded_steps = parallel.make_sharded_steps
    put_batch = parallel.put_batch
    model = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=32, target_vocab_size=32, max_position=16,
        dtype="float32", dropout_rate=0.0,
    )
    tcfg = TrainConfig(
        batch_size=8, sequence_length=8, warmup_steps=10,
        loss_normalization="tokens",
    )
    ks, kt = jax.random.split(jax.random.PRNGKey(3))
    src = np.asarray(jax.random.randint(ks, (8, 8), 1, 32), np.int32)
    tgt = np.asarray(jax.random.randint(kt, (8, 8), 1, 32), np.int32)
    rng = jax.random.PRNGKey(42)

    state = create_train_state(jax.random.PRNGKey(0), model, tcfg)
    step = jax.jit(make_train_step(model, tcfg))
    _, m_single = step(state, src, tgt, rng)

    mesh = make_mesh(MeshConfig(data=2), devices=jax.devices()[:2])
    sstate, shardings = create_sharded_state(
        jax.random.PRNGKey(0), model, tcfg, mesh
    )
    train_step, _ = make_sharded_steps(mesh, model, tcfg, shardings, donate=False)
    _, m_mesh = train_step(
        sstate, put_batch(src, mesh), put_batch(tgt, mesh), rng
    )
    np.testing.assert_allclose(
        float(m_mesh["loss"]), float(m_single["loss"]), rtol=2e-4
    )


def test_generate_prefill_smoke():
    """generate() with prompt_len > 1 — the serving fast path's single-pass
    chunked prefill (transformer_prefill -> lm_generate) compiles and runs in
    every tier-1 pass, not just the slow serve e2e scenarios. Asserts the
    prompt really went through prefill, not the token-by-token loop."""
    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.models import transformer_init
    from transformer_tpu.train import decode as decode_mod

    tok = SubwordTokenizer.build_from_corpus(
        ["ab cd ef gh"] * 3, target_vocab_size=270
    )
    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=32, decoder_only=True, tie_output=True,
        dtype="float32", dropout_rate=0.0,
    )
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    seen = []
    real = decode_mod.transformer_prefill

    def spy(params, toks, *a, **kw):
        seen.append(toks.shape[1])
        return real(params, toks, *a, **kw)

    decode_mod.transformer_prefill = spy
    try:
        # The spy only fires at trace time: drop any compiled lm_generate
        # from an earlier test so a jit-cache hit can't skip it.
        decode_mod.lm_generate.clear_cache()
        out = decode_mod.generate(params, cfg, tok, ["ab cd ef"], max_new=4)
    finally:
        decode_mod.transformer_prefill = real
    assert len(out) == 1 and isinstance(out[0], str)
    assert seen and seen[0] > 1  # multi-token prompt ingested in one pass
