"""Sharded replicas (``serve/sharded.py``, ``--mesh``): one replica is one
multi-device pjit program.

The contract under test is BYTE parity: params replicate (every device runs
the identical float reduction — splitting reductions is what breaks bitwise
equality), the KV pool shards on its leading storage axis, and all
cross-shard traffic is GSPMD data movement. So a sharded scheduler at mesh
1, 2, or 4 must answer greedy AND seeded-sampled requests identically to
the historical single-device path — across cache variants, chunked prefill,
speculation, and prefix aliasing. Exercised on conftest's 8-virtual-CPU
platform, same as the distributed training tests.
"""

import jax
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig
from transformer_tpu.data.tokenizer import SubwordTokenizer
from transformer_tpu.models import transformer_init
from transformer_tpu.serve import ContinuousScheduler, PrefixCache
from transformer_tpu.serve.sharded import (
    normalize_mesh_spec,
    parse_mesh_spec,
    serving_mesh,
)


def _cfg(tok, **kw) -> ModelConfig:
    base = dict(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=64, decoder_only=True, tie_output=True,
        dtype="float32", dropout_rate=0.0,
    )
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def tok():
    return SubwordTokenizer.build_from_corpus(
        ["ab cd ef gh ij kl mn"] * 3, target_vocab_size=300
    )


# The acceptance matrix rides the same variants as the paged-pool tests:
# bf16 cache, int8 cache, GQA.
VARIANTS = {
    "bf16": dict(dtype="bfloat16"),
    "int8": dict(kv_cache_int8=True),
    "gqa": dict(num_kv_heads=1),
}

# Greedy AND seeded-sampled; wave 2 replays wave 1's prompt as a full
# prefix hit plus a divergent-tail partial hit (aliasing + CoW shard-wise).
WAVES = [
    [
        {"prompt": "ab cd ef gh ij", "max_new": 6},
        {"prompt": "ab cd ef gh kl", "max_new": 5, "temperature": 0.9,
         "seed": 3},
    ],
    [
        {"prompt": "ab cd ef gh ij", "max_new": 6},          # full hit
        {"prompt": "ab cd ef gh mn", "max_new": 4, "temperature": 0.7,
         "top_k": 4, "seed": 1},                             # partial hit
    ],
]


def _answers(params, cfg, tok, *, mesh=None, num_slots=2, **kw):
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=num_slots, max_total=48,
        default_max_new=4, mesh=mesh, **kw,
    )
    out = []
    for wave in WAVES:
        out.extend(
            r.get("continuation") for r in s.run([dict(q) for q in wave])
        )
    return s, out


# --------------------------------------------------------------------------
# mesh-spec parsing


def test_parse_mesh_spec():
    assert parse_mesh_spec(None) is None
    assert parse_mesh_spec("") is None
    assert parse_mesh_spec(2) == 2
    assert parse_mesh_spec("4") == 4
    assert parse_mesh_spec("data=2") == 2
    # One canonical spelling: the replica's announced shape and the
    # supervisor's expectation must never alias into a false mismatch.
    assert normalize_mesh_spec("2") == normalize_mesh_spec("data=2") == "data=2"
    assert normalize_mesh_spec("") is None
    for bad in ("0", "-1", "model=2", "data=2,model=2", "x"):
        with pytest.raises(ValueError, match="mesh"):
            parse_mesh_spec(bad)


def test_serving_mesh_too_few_devices():
    with pytest.raises(ValueError, match="devices"):
        serving_mesh(len(jax.devices()) + 1)


# --------------------------------------------------------------------------
# byte parity: mesh 1/2/4 vs the unsharded path


@pytest.mark.parametrize("variant", sorted(VARIANTS))
def test_mesh_parity_matrix(tok, variant):
    """Paged pool + prefix aliasing + chunked prefill + speculation, greedy
    and seeded-sampled requests: byte-identical answers at mesh 1, 2, 4 vs
    the unsharded scheduler (which also runs a different slot count, so
    parity is not an artifact of identical batching)."""
    cfg = _cfg(tok, **VARIANTS[variant])
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    common = dict(
        prefill_chunk=3, speculate_k=2, kv_layout="paged", kv_block=4,
    )
    _, want = _answers(
        params, cfg, tok, num_slots=2,
        prefix_cache=PrefixCache(cfg, block_tokens=4, budget_mb=8), **common,
    )
    for mesh in (1, 2, 4):
        s, got = _answers(
            params, cfg, tok, mesh=mesh, num_slots=4,
            prefix_cache=PrefixCache(cfg, block_tokens=4, budget_mb=8),
            **common,
        )
        assert got == want, f"mesh={mesh} diverged for {variant}"
        assert s.mesh_size == mesh and s._sharded is not None


def test_mesh_parity_dense(tok):
    """The dense layout shards on the slot axis; same parity contract."""
    cfg = _cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    common = dict(prefill_chunk=3, speculate_k=2)
    _, want = _answers(params, cfg, tok, num_slots=2, **common)
    for mesh in (2, 4):
        _, got = _answers(params, cfg, tok, mesh=mesh, num_slots=4, **common)
        assert got == want, f"mesh={mesh} diverged (dense)"


def test_sharded_layout_placement(tok):
    """The layout the docstring promises: params replicated, pool KV
    sharded on its leading storage axis, block table host-side as ever."""
    cfg = _cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=4, max_total=48, mesh=2,
        kv_layout="paged", kv_block=4,
    )
    p_leaf = jax.tree_util.tree_leaves(s.params)[0]
    assert p_leaf.sharding.is_fully_replicated
    for leaf in jax.tree_util.tree_leaves(s.pool.caches):
        spec = leaf.sharding.spec
        assert spec and spec[0], f"pool leaf not sharded on axis 0: {spec}"
        # Each of the 2 shards holds half the block rows.
        assert len(leaf.sharding.device_set) == 2
    # The paged pool was rounded up to a multiple of the mesh.
    assert jax.tree_util.tree_leaves(s.pool.caches)[0].shape[0] % 2 == 0


# --------------------------------------------------------------------------
# construction guards


def test_sharded_guards(tok):
    cfg = _cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="num_slots"):
        ContinuousScheduler(params, cfg, tok, num_slots=3, mesh=2)
    with pytest.raises(ValueError, match="paged_flash"):
        ContinuousScheduler(
            params, cfg, tok, num_slots=2, mesh=2,
            kv_layout="paged", decode_kernel="paged_flash",
        )


# --------------------------------------------------------------------------
# live-upgrade twin check grows sharding specs


def test_stage_params_refuses_mismatched_mesh(tok):
    """Staging weights committed to a DIFFERENT mesh answers a structured
    refusal (ValueError before anything is scheduled) and serving is
    untouched: no pending swap, and the next request still answers."""
    cfg = _cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=48, mesh=2,
        weight_version="v1",
    )
    want = [
        r.get("continuation")
        for r in s.run([{"prompt": "ab cd ef", "max_new": 4}])
    ]
    # Same structure/shapes/dtypes, but committed to a 4-device mesh:
    # the shape/dtype twin check passes, the sharding twin check must not.
    from jax.sharding import NamedSharding, PartitionSpec

    other = serving_mesh(4)
    wrong = jax.device_put(
        jax.tree.map(np.asarray, params),
        NamedSharding(other, PartitionSpec()),
    )
    with pytest.raises(ValueError, match="sharding"):
        s.stage_params(wrong, "v2")
    assert not s.swap_pending
    got = [
        r.get("continuation")
        for r in s.run([{"prompt": "ab cd ef", "max_new": 4}])
    ]
    assert got == want  # zero serving impact


def test_stage_params_host_arrays_swap_cleanly(tok):
    """The checkpoint-load path: host (numpy) arrays carry no committed
    sharding, so they pass the twin check, get placed onto the serving
    mesh, and the swap changes answers with zero recompiles of the
    sharded twins."""
    cfg = _cfg(tok)
    p1 = transformer_init(jax.random.PRNGKey(0), cfg)
    p2 = jax.tree.map(np.asarray, transformer_init(jax.random.PRNGKey(1), cfg))
    s = ContinuousScheduler(
        params := p1, cfg, tok, num_slots=2, max_total=48, mesh=2,
        weight_version="v1",
    )
    del params
    req = {"prompt": "ab cd ef", "max_new": 4}
    s.run([dict(req)])
    before = s._sharded.pool_step._cache_size()
    s.stage_params(p2, "v2")
    assert s.swap_pending
    out = s.run([dict(req)])  # drain triggers the flip at a step boundary
    assert s.weight_version == "v2" and not s.swap_pending
    assert out[0].get("weight_version") == "v2"
    leaf = jax.tree_util.tree_leaves(s.params)[0]
    assert leaf.sharding.is_fully_replicated  # placed onto the serving mesh
    assert s._sharded.pool_step._cache_size() == before  # zero recompiles
