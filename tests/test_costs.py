"""transformer_tpu.analysis cost model + sharding analysis: hand-computable
canned programs (known FLOPs/bytes), liveness vs donation, the MQA/GQA
KV-bytes argument made numeric, the collective inventory, TPA201-205 corpus
twins, the budget-baseline workflow, CLI exit codes, and — slow-marked —
the two injected-regression canaries (a +1-buffer memory regression and a
stray all_gather) that prove the baseline gate actually detects what it
pins."""

import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.analysis.__main__ import main as analysis_main
from transformer_tpu.analysis.configs import FAST_MATRIX
from transformer_tpu.analysis.costs import (
    CostReport,
    canned_cost_reports,
    compare_to_baseline,
    default_costs_baseline_path,
    kv_cache_bytes,
    load_costs_baseline,
    program_costs,
    write_costs_baseline,
)
from transformer_tpu.analysis.sharding import (
    collective_inventory,
    run_sharding,
)

_FIXTURES = pathlib.Path(__file__).parent / "fixtures"
_SHARD_BAD = str(_FIXTURES / "tpa_shard_bad_corpus.py")
_SHARD_GOOD = str(_FIXTURES / "tpa_shard_good_corpus.py")

_f32 = lambda *shape: jax.ShapeDtypeStruct(shape, np.float32)  # noqa: E731


# --------------------------------------------------------------------------
# the cost model on hand-computable programs


def test_dot_flops_and_bytes():
    """(8,16) @ (16,4) f32: FLOPs = 2*8*16*4 = 1024; bytes moved = the
    dot's operands + result = 512 + 256 + 128 = 896; peak = both inputs
    live + the output = 896 (nothing is donated)."""
    r = program_costs("dot", lambda a, b: a @ b, _f32(8, 16), _f32(16, 4))
    assert r.flops == 1024
    assert r.bytes_moved == 896
    assert r.peak_bytes == 896
    assert r.collectives == {}
    assert r.arg_bytes == 768 and r.out_bytes == 128


def test_batched_dot_flops():
    """Batch dims multiply through: (4,8,16) @ (4,16,4) = 4 * 1024 FLOPs."""
    r = program_costs(
        "bmm",
        lambda a, b: jax.lax.dot_general(
            a, b, (((2,), (1,)), ((0,), (0,)))
        ),
        _f32(4, 8, 16), _f32(4, 16, 4),
    )
    assert r.flops == 4 * 2 * 8 * 16 * 4


def test_reduce_flops_counts_operand():
    r = program_costs("red", lambda a: jnp.sum(a), _f32(32, 4))
    assert r.flops == 128  # one op per reduced element


def test_liveness_chain_vs_donation():
    """y=a+1; z=y+1; w=z+1 over 1KiB buffers. Non-donated: the input is
    caller-held for the whole program, so the worst instant holds a + y + z
    = 3 buffers. Donated: `a` dies after the first add — the worst instant
    holds only 2 buffers. The delta IS one buffer, which is exactly what
    the +1-buffer canary regression looks like."""
    n = 256  # f32 -> 1KiB per buffer
    buf = 4 * n

    def chain(a):
        y = a + 1.0
        z = y + 1.0
        return z + 1.0

    plain = program_costs("chain", chain, _f32(n))
    donated = program_costs("chain_d", chain, _f32(n), donate_argnums=(0,))
    assert plain.peak_bytes == 3 * buf
    assert donated.peak_bytes == 2 * buf
    assert plain.peak_bytes - donated.peak_bytes == buf


def test_donated_buffer_counts_until_last_use():
    """A donated input that is ALSO the last operand read must stay in the
    peak until that read: peak = a + b + out at the dot, not less."""
    r = program_costs(
        "dot_d", lambda a, b: a @ b, _f32(8, 16), _f32(16, 4),
        donate_argnums=(0, 1),
    )
    assert r.peak_bytes == 896  # donation frees nothing before the only use


def test_dead_output_not_held():
    """An intermediate nobody reads dies immediately; it still costs its
    transient allocation at its own equation but does not stack onto later
    peaks."""
    n = 256
    buf = 4 * n

    def f(a):
        _ = a * 2.0  # dead
        return a + 1.0

    r = program_costs("dead", f, _f32(n))
    assert r.peak_bytes == 2 * buf  # a + one live buffer at a time


# --------------------------------------------------------------------------
# KV budgets: the MQA/one-write-head argument, numerically


def test_kv_bytes_mqa_ratio():
    """GQA with n_kv_heads=1 vs full MHA: KV bytes per token shrink by
    exactly num_heads — the one-write-head paper's claim on this repo's
    own cache layout."""
    plain = kv_cache_bytes(FAST_MATRIX["lm_bf16"], 32)
    mqa = kv_cache_bytes(FAST_MATRIX["lm_gqa"], 32)
    heads = FAST_MATRIX["lm_bf16"].num_heads
    assert FAST_MATRIX["lm_gqa"].num_kv_heads == 1
    assert plain["bytes_per_token"] == heads * mqa["bytes_per_token"]
    assert plain["bytes_per_slot"] == heads * mqa["bytes_per_slot"]


def test_kv_bytes_hand_computed():
    """lm_bf16: 2 layers x (k + v) x 32 tokens x 2 kv-heads x 8 head-dim
    x 2 bytes = 4096 bytes/slot, 128 bytes/token."""
    kv = kv_cache_bytes(FAST_MATRIX["lm_bf16"], 32)
    assert kv["bytes_per_slot"] == 4096
    assert kv["bytes_per_token"] == 128


def test_kv_bytes_int8_and_window():
    """int8 stores 1-byte codes + 4-byte fp32 scales per (token, head):
    (2*8*1 + 2*4) = 24 B/token per buffer pair per layer -> 96 B/token
    total; a rolling window bounds the BUFFER, not the per-token cost."""
    int8 = kv_cache_bytes(FAST_MATRIX["lm_int8_cache"], 32)
    window = kv_cache_bytes(FAST_MATRIX["lm_window"], 32)
    assert int8["bytes_per_token"] == 96
    assert window["buffer_tokens"] == 8  # min(window, max_total)
    assert window["bytes_per_slot"] == 4096 // 4


# --------------------------------------------------------------------------
# collective inventory


def test_collective_inventory_attribution():
    from transformer_tpu.analysis.sharding import _mesh_1d
    from transformer_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _mesh_1d("seq", 2)
    if mesh is None:
        pytest.skip("needs >= 2 devices")

    def body(x):
        y = jax.lax.ppermute(x, "seq", [(0, 1), (1, 0)])
        return jax.lax.psum(y, "seq")

    fn = shard_map(
        body, mesh=mesh, in_specs=P("seq"), out_specs=P(None),
        check_vma=False,
    )
    closed = jax.make_jaxpr(fn)(_f32(4, 8))
    inv = collective_inventory(closed, {"seq": 2})
    assert set(inv) == {"ppermute[seq]", "psum[seq]"}
    assert inv["ppermute[seq]"]["count"] == 1
    # per-shard (2,8) f32 = 64B; one ring hop moves the whole shard.
    assert inv["ppermute[seq]"]["bytes"] == 64
    # ring all-reduce: 2*(n-1)/n of the buffer.
    assert inv["psum[seq]"]["bytes"] == 64


def test_scan_weighting_multiplies_collective_counts():
    from transformer_tpu.analysis.sharding import _mesh_1d
    from transformer_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _mesh_1d("seq", 2)
    if mesh is None:
        pytest.skip("needs >= 2 devices")

    def body(x):
        def hop(c, _):
            return jax.lax.ppermute(c, "seq", [(0, 1), (1, 0)]), ()

        out, _ = jax.lax.scan(hop, x, None, length=3)
        return out

    fn = shard_map(
        body, mesh=mesh, in_specs=P("seq"), out_specs=P("seq"),
        check_vma=False,
    )
    inv = collective_inventory(jax.make_jaxpr(fn)(_f32(4, 8)), {"seq": 2})
    assert inv["ppermute[seq]"]["count"] == 3


# --------------------------------------------------------------------------
# canned programs + the checked-in budget baseline (THE CI gate)


@pytest.fixture(scope="module")
def canned():
    """One canned-program sweep shared by the assertions below (the sweep
    is pure — tracing the same abstract programs again yields byte-equal
    reports, pinned by the CLI determinism the baseline gate relies on)."""
    return canned_cost_reports()


def test_canned_programs_cover_acceptance_surface(canned):
    reports, skipped = canned
    names = {r.name for r in reports} | set(skipped)
    for expected in (
        "serve.pool_step[lm_bf16]",
        "serve.pool_step[lm_int8_cache]",
        "serve.pool_step[lm_window]",
        "serve.pool_step[lm_gqa]",
        "serve.slot_prefill[lm_bf16,n=8]",
        "serve.pool_verify[lm_bf16,W=4]",
        "serve.slot_restore[lm_bf16,blocks=4]",
        "train.step[lm_bf16]",
        "parallel.ring_attention[seq=2]",
        "parallel.tp_ffn[model=2]",
    ):
        assert expected in names, f"missing canned program {expected}"
    by_name = {r.name: r for r in reports}
    for name, r in by_name.items():
        assert r.peak_bytes > 0, name
        if name.startswith(("serve.", "train.")):
            # the decode/train hot paths are single-chip: collective-free.
            assert r.collectives == {}, name
    assert by_name["serve.pool_step[lm_bf16]"].flops > 0
    # admission ingests 8 tokens per call vs 1 for a decode step: more
    # arithmetic per byte of weights touched.
    assert (
        by_name["serve.slot_prefill[lm_bf16,n=8]"].intensity
        > by_name["serve.pool_step[lm_bf16]"].intensity
    )
    if "parallel.ring_attention[seq=2]" in by_name:
        inv = by_name["parallel.ring_attention[seq=2]"].collectives
        assert any(k.startswith("ppermute[seq]") for k in inv), inv


def test_checked_in_baseline_matches_current_tree(canned):
    """The budget gate itself: the shipped costs_baseline.json must match
    the shipped code with zero regressions (peak bytes, KV bytes/slot,
    collective sets)."""
    reports, skipped = canned
    base = load_costs_baseline(default_costs_baseline_path())
    assert base, "costs_baseline.json is missing"
    kv = {v: kv_cache_bytes(FAST_MATRIX[v], 32)
          for v in ("lm_bf16", "lm_int8_cache", "lm_window", "lm_gqa")}
    regressions, _ = compare_to_baseline(reports, kv, base, skipped)
    assert regressions == [], "\n".join(regressions)


def test_pool_verify_donates_pool(canned):
    """The verify program's peak must NOT pay for two full pools: the pool
    is donated, so its buffers die as the updated pool is built. A lost
    donation annotation roughly doubles the cache term — assert the peak
    stays under params + 2x pool-cache bytes."""
    reports, _ = canned
    by_name = {r.name: r for r in reports}
    step = by_name["serve.pool_step[lm_bf16]"]
    kv = kv_cache_bytes(FAST_MATRIX["lm_bf16"], 32)
    pool_kv = 2 * kv["bytes_per_slot"]
    assert step.extras["kv_bytes_per_slot"] == kv["bytes_per_slot"]
    assert step.peak_bytes < step.arg_bytes + 2 * pool_kv


# --------------------------------------------------------------------------
# baseline workflow


def _tiny_report(name="prog", peak=1000, flops=10, moved=100, coll=None):
    return CostReport(
        name=name, peak_bytes=peak, flops=flops, bytes_moved=moved,
        collectives=coll or {}, arg_bytes=0, out_bytes=0,
    )


def test_baseline_roundtrip_and_regressions(tmp_path):
    path = str(tmp_path / "budget.json")
    kv = {"lm_bf16": {"bytes_per_slot": 4096, "bytes_per_token": 128,
                      "buffer_tokens": 32, "max_total": 32, "layers": 2}}
    write_costs_baseline([_tiny_report()], kv, path)
    base = load_costs_baseline(path)

    # clean: identical numbers
    regs, _ = compare_to_baseline([_tiny_report()], kv, base)
    assert regs == []

    # +1 buffer: peak regression flagged
    regs, _ = compare_to_baseline([_tiny_report(peak=1000 + 4096)], kv, base)
    assert any("peak_bytes" in r for r in regs)

    # stray collective: flagged
    regs, _ = compare_to_baseline(
        [_tiny_report(coll={"all_gather[fsdp]": {"count": 1, "bytes": 64}})],
        kv, base,
    )
    assert any("stray collective" in r for r in regs)

    # KV growth: flagged
    kv2 = {"lm_bf16": dict(kv["lm_bf16"], bytes_per_slot=8192)}
    regs, _ = compare_to_baseline([_tiny_report()], kv2, base)
    assert any("kv_cache[lm_bf16]" in r for r in regs)

    # improvement: note, not regression
    regs, notes = compare_to_baseline([_tiny_report(peak=500)], kv, base)
    assert regs == [] and any("improved" in n for n in notes)

    # lost coverage: flagged; skipped programs tolerated
    regs, _ = compare_to_baseline([], kv, base)
    assert any("no longer produced" in r for r in regs)
    regs, notes = compare_to_baseline([], kv, base, skipped=["prog"])
    assert regs == [] and any("skipped" in n for n in notes)

    # unbaselined program: flagged
    regs, _ = compare_to_baseline(
        [_tiny_report(), _tiny_report(name="new")], kv, base
    )
    assert any("new" in r and "baseline" in r for r in regs)


# --------------------------------------------------------------------------
# injected-regression canaries: prove the gate detects what it pins


@pytest.mark.slow
def test_canary_one_extra_buffer_is_detected():
    """A 'refactor' of the pool step that keeps one extra live copy of the
    logits (the classic accidental-residency bug) must fail the shipped
    baseline's peak budget."""
    from transformer_tpu.serve import scheduler as sched
    from transformer_tpu.serve.scheduler import abstract_pool_caches
    from transformer_tpu.analysis.costs import _abstract_model

    cfg = FAST_MATRIX["lm_bf16"]
    params = _abstract_model(cfg)
    pool = abstract_pool_caches(cfg, 2, 32)
    toks = jax.ShapeDtypeStruct((2,), np.int32)
    raw = sched._pool_step.__wrapped__

    def leaky(p, c, t):
        logits, caches = raw(p, c, t, cfg)
        # the regression: a second copy of the pool pinned alongside the
        # result (the "stash the old cache for a rollback I never free"
        # shape of bug)
        stash = jax.tree.map(lambda x: x + x.dtype.type(0), caches)
        return logits, caches, stash

    r = program_costs(
        "serve.pool_step[lm_bf16]", leaky, params, pool, toks,
        donate_argnums=(1,),
    )
    base = load_costs_baseline(default_costs_baseline_path())
    regs, _ = compare_to_baseline([r], {}, base)
    assert any(
        "serve.pool_step[lm_bf16]" in x and "peak_bytes" in x for x in regs
    ), regs


@pytest.mark.slow
def test_canary_stray_all_gather_is_detected():
    """A stray all_gather smuggled into the pool step must fail the shipped
    baseline's (empty) collective set for that program."""
    from transformer_tpu.analysis.sharding import _mesh_1d
    from transformer_tpu.parallel.compat import shard_map
    from transformer_tpu.serve import scheduler as sched
    from transformer_tpu.serve.scheduler import abstract_pool_caches
    from transformer_tpu.analysis.costs import _abstract_model
    from jax.sharding import PartitionSpec as P

    mesh = _mesh_1d("data", 2)
    if mesh is None:
        pytest.skip("needs >= 2 devices")
    cfg = FAST_MATRIX["lm_bf16"]
    params = _abstract_model(cfg)
    pool = abstract_pool_caches(cfg, 2, 32)
    toks = jax.ShapeDtypeStruct((2,), np.int32)
    raw = sched._pool_step.__wrapped__

    def gathered(p, c, t):
        logits, caches = raw(p, c, t, cfg)
        spread = shard_map(
            lambda x: jax.lax.all_gather(x, "data"),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False,
        )(logits)
        return spread, caches

    r = program_costs(
        "serve.pool_step[lm_bf16]", gathered, params, pool, toks,
        donate_argnums=(1,), axis_sizes={"data": 2},
    )
    assert r.collectives, "the injected all_gather must be inventoried"
    base = load_costs_baseline(default_costs_baseline_path())
    regs, _ = compare_to_baseline([r], {}, base)
    assert any("stray collective" in x and "all_gather" in x for x in regs), regs


# --------------------------------------------------------------------------
# TPA201-205: corpus twins + package cleanliness + CLI


def test_shard_bad_corpus_fires_every_rule():
    report = run_sharding(paths=[_SHARD_BAD], baseline_path=None)
    assert sorted({f.code for f in report.findings}) == [
        "TPA201", "TPA202", "TPA203", "TPA204", "TPA205",
    ]


def test_shard_good_corpus_clean():
    report = run_sharding(paths=[_SHARD_GOOD], baseline_path=None)
    assert report.findings == [], "\n".join(str(f) for f in report.findings)


def test_shard_package_clean():
    report = run_sharding()  # package + checked-in (empty) baseline
    assert report.findings == [], "\n".join(str(f) for f in report.findings)


def test_shard_suppression_and_baseline(tmp_path):
    import textwrap

    src = textwrap.dedent("""\
        from jax.sharding import Mesh, PartitionSpec as P
        MESH = Mesh(DEVICES, ("data",))
        SPEC = P("bogus")  # tpa: disable=TPA202 — exercised by the test
        OTHER = P("bogus2")
    """)
    f = tmp_path / "m.py"
    f.write_text(src)
    report = run_sharding(paths=[str(f)], baseline_path=None)
    assert [x.code for x in report.findings] == ["TPA202"]  # only OTHER
    # grandfather the remaining finding, then the run is clean
    from transformer_tpu.analysis.baselines import write_baseline

    bl = str(tmp_path / "bl.json")
    write_baseline(report, bl)
    again = run_sharding(paths=[str(f)], baseline_path=bl)
    assert again.findings == [] and len(again.baselined) == 1


def test_cli_sharding_exit_codes(capsys):
    assert analysis_main(["sharding"]) == 0
    assert analysis_main(["sharding", "--paths", _SHARD_BAD]) == 1
    assert analysis_main(["sharding", "--paths", _SHARD_GOOD]) == 0
    capsys.readouterr()


def test_cli_costs_exit_codes_and_json(tmp_path, capsys, canned):
    # clean run against the shipped baseline: exit 0, diffable JSON
    assert analysis_main(["costs", "--format", "json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is True
    assert {p["name"] for p in payload["programs"]} >= {
        "serve.pool_step[lm_bf16]", "train.step[lm_bf16]",
    }
    assert payload["kv_cache"]["lm_bf16"]["bytes_per_slot"] == 4096
    # a baseline with an impossible budget must fail the gate with exit 1
    reports, _ = canned
    kv = {v: kv_cache_bytes(FAST_MATRIX[v], 32)
          for v in ("lm_bf16", "lm_int8_cache", "lm_window", "lm_gqa")}
    tight = str(tmp_path / "tight.json")
    write_costs_baseline(reports, kv, tight)
    data = json.load(open(tight))
    first = next(iter(data["programs"]))
    data["programs"][first]["peak_bytes"] -= 1
    json.dump(data, open(tight, "w"))
    assert analysis_main(["costs", "--baseline", tight]) == 1
    capsys.readouterr()


def test_cli_all_aggregates(capsys):
    # fast subset: the lint families (full `all` incl. costs/contracts/
    # retrace/schedules is the pre-merge gate, exercised under -m slow)
    assert analysis_main(["all", "--only", "rules,sharding"]) == 0
    capsys.readouterr()
    assert analysis_main(["all", "--only", "nosuch"]) == 2
    capsys.readouterr()


@pytest.mark.slow
def test_cli_all_full_gate(capsys):
    assert analysis_main(["all"]) == 0
    capsys.readouterr()


# --------------------------------------------------------------------------
# obs summarize cross-check (prediction vs measured memory)


def test_summarize_memory_vs_prediction():
    from transformer_tpu.obs.__main__ import render_text, summarize_events

    events = [
        {"kind": "train.predicted", "ts": 1.0, "program": "train_step",
         "peak_bytes": 1000, "flops": 5000, "bytes_moved": 2000,
         "tokens_per_step": 16},
        {"kind": "train.memory", "ts": 2.0,
         "devices": {"0": {"bytes_in_use": 900, "peak_bytes_in_use": 1500}}},
    ]
    rep = summarize_events(events)
    pred = rep["train"]["predicted"]
    assert pred["measured_peak_bytes"] == 1500
    assert pred["measured_over_predicted"] == 1.5
    assert "measured/predicted 1.5x" in render_text(rep)
    # tolerant when either side is absent
    only_pred = summarize_events(events[:1])["train"]["predicted"]
    assert "measured_peak_bytes" not in only_pred
    only_mem = summarize_events(events[1:])["train"]
    assert "predicted" not in only_mem and only_mem["memory"]
    # and when the memory payload is malformed
    rep = summarize_events(
        [events[0], {"kind": "train.memory", "ts": 3.0, "devices": "garbled"}]
    )
    assert "measured_peak_bytes" not in rep["train"]["predicted"]


def test_trainer_emits_prediction(tmp_path):
    """A telemetry-enabled fit() leaves one train.predicted event whose
    peak matches the cost model run directly (same config, same trace)."""
    from transformer_tpu.analysis.configs import TINY_TRAIN
    from transformer_tpu.obs import Telemetry
    from transformer_tpu.obs.events import EventLog, read_events
    from transformer_tpu.train.state import create_train_state
    from transformer_tpu.train.trainer import Trainer

    cfg = FAST_MATRIX["lm_bf16"]
    train_cfg = TINY_TRAIN
    state = create_train_state(jax.random.PRNGKey(0), cfg, train_cfg)
    log = tmp_path / "events.jsonl"
    telemetry = Telemetry(events=EventLog(str(log)), interval=0.0)
    trainer = Trainer(cfg, train_cfg, state, telemetry=telemetry,
                     log_fn=lambda *_: None)
    B, L = train_cfg.batch_size, train_cfg.sequence_length
    vocab = cfg.input_vocab_size

    class DS:
        def __len__(self):
            return 2

        def batches(self, epoch):
            r = np.random.default_rng(epoch)
            for _ in range(2):
                ids = r.integers(1, vocab, size=(B, L)).astype(np.int32)
                yield ids, ids

    trainer.fit(DS())
    telemetry.close()
    events = [e for e in read_events(str(log)) if e["kind"] == "train.predicted"]
    assert len(events) == 1
    assert events[0]["program"] == "train_step"
    assert events[0]["peak_bytes"] > 0 and events[0]["flops"] > 0
    assert events[0]["tokens_per_step"] == B * L
    # the exported gauge mirrors the event (one prediction, two surfaces)
    snap = telemetry.registry.snapshot()
    assert snap["train_predicted_peak_bytes"] == events[0]["peak_bytes"]
