"""Aux subsystems (SURVEY §5): profiling/tracing, preemption handling,
determinism audits, and their Trainer integration."""

import os
import signal

import jax
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig, TrainConfig
from transformer_tpu.models import transformer_init
from transformer_tpu.train import CheckpointManager, Trainer, create_train_state
from transformer_tpu.utils import (
    PreemptionGuard,
    Profiler,
    StepTimer,
    annotate,
    tree_checksum,
)

TINY = ModelConfig(
    num_layers=1, d_model=16, num_heads=2, dff=32,
    input_vocab_size=30, target_vocab_size=30, max_position=16,
    dropout_rate=0.0, dtype="float32",
)
TCFG = TrainConfig(
    batch_size=4, sequence_length=8, epochs=1, warmup_steps=10,
    log_every_steps=0, eval_every_steps=0, checkpoint_every_epochs=1,
)


class _OneBatch:
    """Minimal dataset: the same batch, n times per epoch."""

    def __init__(self, n=4, stop_after=None, on_batch=None):
        self.n = n
        self.on_batch = on_batch
        k1, k2 = jax.random.split(jax.random.PRNGKey(0))
        self.src = np.asarray(jax.random.randint(k1, (4, 8), 1, 30))
        self.tgt = np.asarray(jax.random.randint(k2, (4, 8), 1, 30))

    def batches(self, epoch=0):
        for i in range(self.n):
            if self.on_batch is not None:
                self.on_batch(i)
            yield self.src, self.tgt


class TestProfiler:
    def test_trace_produces_dump(self, tmp_path):
        prof = Profiler(str(tmp_path / "prof"), start_step=1, num_steps=2)
        x = jax.numpy.ones((8, 8))
        for step in range(5):
            prof.maybe_trace(step)
            with annotate("matmul"):
                jax.block_until_ready(x @ x)
        prof.stop()
        dumped = []
        for root, _, files in os.walk(tmp_path / "prof"):
            dumped.extend(os.path.join(root, f) for f in files)
        assert dumped, "profiler produced no trace files"

    @pytest.mark.slow
    def test_trainer_integration(self, tmp_path):
        prof = Profiler(str(tmp_path / "prof"), start_step=1, num_steps=2)
        state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        tr = Trainer(TINY, TCFG, state, log_fn=lambda *_: None, profiler=prof)
        tr.fit(_OneBatch(n=4))
        assert prof._done and not prof._active
        assert any(files for _, _, files in os.walk(tmp_path / "prof"))


class TestCompilationCache:
    def test_sets_config_and_persists(self, tmp_path):
        from transformer_tpu.utils import enable_compilation_cache

        old_dir = jax.config.jax_compilation_cache_dir
        old_min = jax.config.jax_persistent_cache_min_compile_time_secs
        old_size = jax.config.jax_persistent_cache_min_entry_size_bytes
        try:
            d = enable_compilation_cache(str(tmp_path / "cache"))
            assert d == str(tmp_path / "cache")
            assert jax.config.jax_compilation_cache_dir == d
            # Sub-second compiles are cheaper to redo than to hash + load;
            # drop both floors here so the smoke jit below persists.
            assert jax.config.jax_persistent_cache_min_compile_time_secs == 1.0
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
            x = np.arange(8.0, dtype=np.float32)
            np.testing.assert_allclose(
                jax.jit(lambda v: v * 3.0 + 1.0)(x), x * 3.0 + 1.0
            )
            assert os.path.isdir(d) and os.listdir(d)  # entry written
        finally:
            jax.config.update("jax_compilation_cache_dir", old_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", old_min)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", old_size)

    def test_env_override(self, tmp_path, monkeypatch):
        from transformer_tpu.utils import enable_compilation_cache

        old_dir = jax.config.jax_compilation_cache_dir
        old_min = jax.config.jax_persistent_cache_min_compile_time_secs
        try:
            monkeypatch.setenv("TRANSFORMER_TPU_JAX_CACHE", str(tmp_path / "env"))
            assert enable_compilation_cache() == str(tmp_path / "env")
        finally:
            jax.config.update("jax_compilation_cache_dir", old_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", old_min)


class TestStepTimer:
    def test_stats(self):
        t = StepTimer(tokens_per_step=100)
        for _ in range(5):
            t.tick()
        assert t.count == 0  # unsynced window: no timing claims yet
        t.sync()  # caller blocked on step outputs here
        assert t.count == 5
        assert t.mean_s > 0.0
        assert t.steps_per_sec > 0
        assert t.tokens_per_sec == pytest.approx(t.steps_per_sec * 100)
        assert "steps/s" in t.summary()

    def test_sync_without_ticks_is_noop(self):
        t = StepTimer()
        t.sync()
        assert t.count == 0

    def test_empty_summary(self):
        assert StepTimer().summary() == "no steps timed"


class TestPreemptionGuard:
    def test_latches_and_restores(self):
        before = signal.getsignal(signal.SIGTERM)
        with PreemptionGuard(signals=(signal.SIGTERM,)) as g:
            assert not g.should_stop
            os.kill(os.getpid(), signal.SIGTERM)
            assert g.should_stop
            assert g.signal_received == signal.SIGTERM
        assert signal.getsignal(signal.SIGTERM) is before

    def test_trainer_checkpoints_on_signal(self, tmp_path):
        """SIGTERM mid-epoch: the loop must save a checkpoint and exit."""
        tcfg = TrainConfig(
            batch_size=4, sequence_length=8, epochs=3, warmup_steps=10,
            log_every_steps=0, eval_every_steps=0,
        )
        state = create_train_state(jax.random.PRNGKey(0), TINY, tcfg)
        ckpt = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
        logs = []

        def send_signal(i):
            if i == 2:  # third batch of the first epoch
                os.kill(os.getpid(), signal.SIGINT)

        tr = Trainer(TINY, tcfg, state, checkpoint=ckpt, log_fn=logs.append)
        tr.fit(_OneBatch(n=8, on_batch=send_signal))
        # Stopped early (3 steps, not 24) and saved.
        assert int(jax.device_get(tr.state.step)) == 3
        assert ckpt.latest_step == 3
        assert any("preemption" in msg for msg in logs)

    @pytest.mark.slow
    def test_resume_after_preemption(self, tmp_path):
        """The saved preemption checkpoint restores at next start."""
        tcfg = TrainConfig(
            batch_size=4, sequence_length=8, epochs=1, warmup_steps=10,
            log_every_steps=0, eval_every_steps=0, checkpoint_every_epochs=5,
        )
        state = create_train_state(jax.random.PRNGKey(0), TINY, tcfg)
        ckpt = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
        tr = Trainer(TINY, tcfg, state, checkpoint=ckpt, log_fn=lambda *_: None)

        def send_signal(i):
            if i == 1:
                os.kill(os.getpid(), signal.SIGINT)

        tr.fit(_OneBatch(n=4, on_batch=send_signal))
        saved_step = ckpt.latest_step
        assert saved_step == 2

        state2 = create_train_state(jax.random.PRNGKey(7), TINY, tcfg)
        logs = []
        tr2 = Trainer(TINY, tcfg, state2, checkpoint=ckpt, log_fn=logs.append)
        tr2.fit(_OneBatch(n=4))
        assert any("restored checkpoint" in m for m in logs)
        assert int(jax.device_get(tr2.state.step)) == saved_step + 4


class TestTreeChecksum:
    def test_equal_trees_equal_checksums(self):
        p1 = transformer_init(jax.random.PRNGKey(0), TINY)
        p2 = transformer_init(jax.random.PRNGKey(0), TINY)
        assert tree_checksum(p1) == tree_checksum(p2)

    def test_different_trees_differ(self):
        p1 = transformer_init(jax.random.PRNGKey(0), TINY)
        p2 = jax.tree.map(lambda x: x + 1e-3, p1)
        assert tree_checksum(p1) != tree_checksum(p2)

    @pytest.mark.slow
    def test_train_determinism_audit(self):
        """Two identical runs of the jitted step must produce bit-identical
        states — the cross-run determinism guarantee the audit relies on."""
        from transformer_tpu.train import make_train_step

        def run():
            state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
            step = jax.jit(make_train_step(TINY, TCFG))
            src = np.asarray(
                jax.random.randint(jax.random.PRNGKey(1), (4, 8), 1, 30)
            )
            tgt = np.asarray(
                jax.random.randint(jax.random.PRNGKey(2), (4, 8), 1, 30)
            )
            for _ in range(3):
                state, _ = step(state, src, tgt, jax.random.PRNGKey(3))
            return tree_checksum(state.params)

        assert run() == run()


class TestConsistency:
    """utils/consistency.py — the SURVEY §5 'race detection' equivalent.
    (The real 2-process positive/negative checks run in
    tests/test_multiprocess.py via multiproc_worker.py.)"""

    def test_fingerprint_detects_change(self):
        from transformer_tpu.utils.consistency import (
            fingerprints_equal,
            tree_fingerprint,
        )

        params = transformer_init(jax.random.PRNGKey(0), TINY)
        a = tree_fingerprint(params)
        b = tree_fingerprint(params)
        assert fingerprints_equal(a, b) == []
        bumped = jax.tree.map(lambda x: x, params)
        bumped["final"]["bias"] = params["final"]["bias"] + 1e-3
        diff = fingerprints_equal(a, tree_fingerprint(bumped))
        assert diff == ["final/bias"], diff

    def test_single_process_consistency_trivially_passes(self):
        from transformer_tpu.utils.consistency import (
            assert_cross_process_consistent,
        )

        params = transformer_init(jax.random.PRNGKey(0), TINY)
        assert_cross_process_consistent(params)  # must not raise

    def test_step_determinism_assert(self):
        from transformer_tpu.train import make_train_step
        from transformer_tpu.utils.consistency import (
            assert_step_deterministic,
        )

        state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        step = jax.jit(make_train_step(TINY, TCFG))  # NOT donated
        src = np.asarray(jax.random.randint(jax.random.PRNGKey(1), (4, 8), 1, 30))
        tgt = np.asarray(jax.random.randint(jax.random.PRNGKey(2), (4, 8), 1, 30))
        assert_step_deterministic(step, state, src, tgt, jax.random.PRNGKey(3))

        calls = []

        def impure(x):
            calls.append(1)
            return np.float32(len(calls)) * np.asarray(x)

        with pytest.raises(RuntimeError, match="nondeterministic"):
            assert_step_deterministic(impure, np.ones(3), label="impure fn")
