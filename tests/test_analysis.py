"""transformer_tpu.analysis: lint rules (each exercised against a known-bad
inline snippet AND its known-good twin), suppression + baseline workflow,
abstract contract checks (fast matrix = tier-1; full matrix = slow), and the
retrace sentinel (zero recompiles across steady-state decode/train steps)."""

import json
import pathlib
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.analysis import run_contracts, run_rules
from transformer_tpu.analysis.__main__ import main as analysis_main
from transformer_tpu.analysis.retrace import RetraceSentinel, leak_checking
from transformer_tpu.analysis.rules import write_baseline

_FIXTURES = pathlib.Path(__file__).parent / "fixtures"
_BAD_CORPUS = str(_FIXTURES / "tpa_bad_corpus.py")
_GOOD_CORPUS = str(_FIXTURES / "tpa_good_corpus.py")

# --------------------------------------------------------------------------
# lint rules: every rule gets a must-flag snippet and a must-not-flag twin


def _lint(tmp_path, source, name="snippet.py", baseline=None):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return run_rules(paths=[str(f)], baseline_path=baseline)


_HEADER = """\
    from functools import partial
    import jax
    import jax.numpy as jnp
    import numpy as np
"""

# (rule, bad snippet, good twin)
_CASES = [
    (
        "TPA001",
        _HEADER + """
    @jax.jit
    def f(x):
        if x > 0:
            return x
        return -x
    """,
        _HEADER + """
    @partial(jax.jit, static_argnames=("n",))
    def f(x, n, mask=None):
        if n > 0:
            return x * n
        if mask is None:
            return x
        if x.shape[0] > 2:
            return x[:2]
        return jnp.where(x > 0, x, -x)
    """,
    ),
    (
        "TPA001",  # while on a value derived from a traced argument
        _HEADER + """
    @jax.jit
    def f(x):
        total = jnp.sum(x)
        while total > 1.0:
            total = total / 2
        return total
    """,
        _HEADER + """
    @jax.jit
    def f(x):
        total = len(x)  # len() is concrete under trace
        while total > 1:
            total //= 2
        return x * total
    """,
    ),
    (
        "TPA002",
        _HEADER + """
    @jax.jit
    def f(x):
        return np.maximum(x, 0.0)
    """,
        _HEADER + """
    @jax.jit
    def f(x):
        steps = np.arange(x.shape[0])  # numpy on concrete shape metadata
        return jnp.maximum(x, 0.0) + jnp.asarray(steps)
    """,
    ),
    (
        "TPA003",
        _HEADER + """
    _CACHE = {}

    @jax.jit
    def f(x):
        return x * _CACHE["scale"]
    """,
        _HEADER + """
    _SCALE = 3.0

    @jax.jit
    def f(x):
        _CACHE = {}  # local, not module state
        _CACHE["scale"] = _SCALE
        return x * _CACHE["scale"]
    """,
    ),
    (
        "TPA004",
        _HEADER + """
    @partial(jax.jit, static_argnames=("num_steps",))
    def f(x, n_steps):
        return x * n_steps
    """,
        _HEADER + """
    @partial(jax.jit, static_argnames=("n_steps",), donate_argnums=(0,))
    def f(x, n_steps):
        return x * n_steps
    """,
    ),
    (
        "TPA005",
        _HEADER + """
    @partial(jax.jit, donate_argnums=(0,))
    def step(state, delta):
        return state + delta

    def drive(state, deltas):
        out = step(state, deltas)
        return state + out  # state's buffer was donated
    """,
        _HEADER + """
    @partial(jax.jit, donate_argnums=(0,))
    def step(state, delta):
        return state + delta

    def drive(state, deltas):
        state = step(state, deltas)
        return state + 1
    """,
    ),
    (
        "TPA006",
        _HEADER + """
    def f(path):
        try:
            return open(path).read()
        except Exception:
            return None
    """,
        _HEADER + """
    def f(path, pool):
        try:
            return open(path).read()
        except OSError:
            return None

    def g(path, pool):
        slot = pool.pop()
        try:
            return open(path)
        except Exception:  # ends in bare raise: cleanup pass-through
            pool.append(slot)
            raise
    """,
    ),
]


@pytest.mark.parametrize(
    "rule,bad,good", _CASES, ids=[f"{c[0]}-{i}" for i, c in enumerate(_CASES)]
)
def test_rule_flags_bad_not_good(tmp_path, rule, bad, good):
    bad_report = _lint(tmp_path, bad, "bad.py")
    assert [f.code for f in bad_report.findings] == [rule], (
        f"expected exactly one {rule}, got "
        f"{[str(f) for f in bad_report.findings]}"
    )
    good_report = _lint(tmp_path, good, "good.py")
    assert good_report.findings == [], [str(f) for f in good_report.findings]


def test_inline_suppression(tmp_path):
    src = _HEADER + """
    @jax.jit
    def f(x):
        if x > 0:  # tpa: disable=TPA001 — fixture: deliberately suppressed
            return x
        return -x
    """
    assert _lint(tmp_path, src).findings == []
    # ...but a different code on that line is NOT covered by the disable
    src_wrong = src.replace("disable=TPA001", "disable=TPA006")
    assert [f.code for f in _lint(tmp_path, src_wrong).findings] == ["TPA001"]


def test_baseline_grandfathers_and_expires(tmp_path):
    src = _HEADER + """
    def f(path):
        try:
            return open(path).read()
        except Exception:
            return None
    """
    report = _lint(tmp_path, src, "mod.py")
    assert len(report.findings) == 1
    baseline = tmp_path / "baseline.json"
    write_baseline(report, str(baseline), reason="grandfathered: fixture")
    again = _lint(tmp_path, src, "mod.py", baseline=str(baseline))
    assert again.findings == [] and len(again.baselined) == 1
    # the fingerprint is line-number-free: prepending code keeps it matched
    shifted = "import os\nimport sys\n" + textwrap.dedent(src)
    (tmp_path / "mod.py").write_text(shifted)
    moved = run_rules(paths=[str(tmp_path / "mod.py")], baseline_path=str(baseline))
    assert moved.findings == [] and len(moved.baselined) == 1


def test_static_argnums_out_of_range(tmp_path):
    src = _HEADER + """
    @partial(jax.jit, static_argnums=(5,))
    def f(x, n):
        return x * n
    """
    assert [f.code for f in _lint(tmp_path, src).findings] == ["TPA004"]


def test_assignment_form_jit_checked(tmp_path):
    src = _HEADER + """
    def _f(x, n):
        return x * n

    f = jax.jit(_f, static_argnames=("m",))
    """
    assert [f.code for f in _lint(tmp_path, src).findings] == ["TPA004"]


def test_cli_modules_exempt_from_tpa006(tmp_path):
    src = _HEADER + """
    def f(path):
        try:
            return open(path).read()
        except Exception:
            return None
    """
    (tmp_path / "cli").mkdir()
    f = tmp_path / "cli" / "serve.py"
    f.write_text(textwrap.dedent(src))
    assert run_rules(paths=[str(tmp_path)]).findings == []


# --------------------------------------------------------------------------
# the shipped tree + CLI surface (the acceptance criteria, in-process)


def test_package_lints_clean():
    report = run_rules()  # default: package + checked-in baseline
    assert report.findings == [], "\n".join(str(f) for f in report.findings)
    # the baseline is real, not vestigial: the grandfathered finding exists
    assert len(report.baselined) >= 1


def test_cli_rules_exit_codes(capsys):
    assert analysis_main(["rules"]) == 0
    assert analysis_main(["rules", "--paths", _BAD_CORPUS]) == 1
    assert analysis_main(["rules", "--paths", _GOOD_CORPUS]) == 0
    capsys.readouterr()


def test_cli_bad_corpus_fires_every_rule(capsys):
    rc = analysis_main(["rules", "--paths", _BAD_CORPUS, "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert sorted(payload["counts"]) == [
        "TPA001", "TPA002", "TPA003", "TPA004", "TPA005", "TPA006",
    ]


def test_cli_json_rules_diffable(capsys):
    assert analysis_main(["rules", "--format", "json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"] == {} and payload["files_checked"] > 50


# --------------------------------------------------------------------------
# contracts


def test_contracts_fast_matrix():
    results = run_contracts("fast")
    failed = [str(r) for r in results if not r.ok]
    assert not failed, "\n".join(failed)
    # the fast matrix must cover all three cache variants + GQA
    configs = {r.config for r in results if r.contract == "cache_parity"}
    assert {"lm_bf16", "lm_int8_cache", "lm_window", "lm_gqa"} <= configs


@pytest.mark.slow
def test_contracts_full_matrix(capsys):
    assert analysis_main(["contracts", "--matrix", "full", "--format", "json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["passed"] == payload["total"] > 50


def test_contract_checker_catches_dtype_drift():
    """The checker itself must FAIL on a broken contract (not vacuously
    pass): a cache whose step path writes a different dtype than prefill."""
    from transformer_tpu.analysis.contracts import _tree_spec

    good = jax.eval_shape(lambda: {"k": jnp.zeros((2, 4), jnp.bfloat16)})
    drifted = jax.eval_shape(lambda: {"k": jnp.zeros((2, 4), jnp.float32)})
    assert _tree_spec(good) != _tree_spec(drifted)


# --------------------------------------------------------------------------
# retrace sentinel


def test_sentinel_counts_recompiles():
    @jax.jit
    def f(x):
        return x * 2

    f(jnp.ones((2,)))  # warmup
    s = RetraceSentinel()
    s.watch("f", f, budget=0)
    s.snapshot()
    f(jnp.ones((2,)))  # same shape: cached
    assert s.violations() == []
    f(jnp.ones((3,)))  # new shape: recompile
    assert [d.name for d in s.violations()] == ["f"]
    with pytest.raises(AssertionError, match="retrace budget"):
        s.assert_within_budget()


def test_sentinel_rejects_unjitted():
    s = RetraceSentinel()
    with pytest.raises(ValueError, match="_cache_size"):
        s.watch("plain", lambda x: x)


def test_leak_checking_raises_on_tracer_leak():
    leaked = []

    @jax.jit
    def f(x):
        leaked.append(x)
        return x + 1

    with pytest.raises(Exception, match="[Ll]eak"):
        with leak_checking():
            f(jnp.ones((2,)))


def test_decode_steady_state_zero_retraces():
    """Acceptance criterion: 0 recompiles across 3 steady-state decode
    steps on the serving hot path (_pool_step / _slot_prefill / pick)."""
    from transformer_tpu.analysis.retrace import decode_retrace_report

    deltas = decode_retrace_report(steps=3)
    assert len(deltas) == 3
    bad = [d.to_dict() for d in deltas if not d.within_budget]
    assert not bad, bad


@pytest.mark.slow
def test_train_steady_state_zero_retraces():
    from transformer_tpu.analysis.retrace import train_retrace_report

    deltas = train_retrace_report(steps=3)
    assert all(d.within_budget for d in deltas), [d.to_dict() for d in deltas]


# --------------------------------------------------------------------------
# epoch-rng dedup satellite


def test_epoch_rng_single_definition():
    from transformer_tpu.data.seeding import epoch_rng

    a = epoch_rng(7, 3).integers(0, 1 << 30, size=8)
    b = epoch_rng(7, 3).integers(0, 1 << 30, size=8)
    c = epoch_rng(7, 4).integers(0, 1 << 30, size=8)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    # matches the historical inline construction bit for bit (checkpointed
    # runs resume with identical shuffles)
    legacy = np.random.default_rng((7, 3)).integers(0, 1 << 30, size=8)
    np.testing.assert_array_equal(a, legacy)


def test_no_inline_epoch_rng_left():
    """The (seed, epoch) construction lives in exactly one module."""
    import pathlib

    import transformer_tpu

    root = pathlib.Path(transformer_tpu.__file__).parent
    offenders = [
        str(p)
        for p in root.rglob("*.py")
        if "default_rng((" in p.read_text() and p.name != "seeding.py"
    ]
    assert offenders == [], offenders
