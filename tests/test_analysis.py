"""transformer_tpu.analysis: lint rules (each exercised against a known-bad
inline snippet AND its known-good twin), suppression + baseline workflow,
abstract contract checks (fast matrix = tier-1; full matrix = slow), and the
retrace sentinel (zero recompiles across steady-state decode/train steps)."""

import json
import pathlib
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.analysis import run_contracts, run_rules
from transformer_tpu.analysis.__main__ import main as analysis_main
from transformer_tpu.analysis.retrace import RetraceSentinel, leak_checking
from transformer_tpu.analysis.rules import write_baseline

_FIXTURES = pathlib.Path(__file__).parent / "fixtures"
_BAD_CORPUS = str(_FIXTURES / "tpa_bad_corpus.py")
_GOOD_CORPUS = str(_FIXTURES / "tpa_good_corpus.py")

# --------------------------------------------------------------------------
# lint rules: every rule gets a must-flag snippet and a must-not-flag twin


def _lint(tmp_path, source, name="snippet.py", baseline=None):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return run_rules(paths=[str(f)], baseline_path=baseline)


_HEADER = """\
    from functools import partial
    import jax
    import jax.numpy as jnp
    import numpy as np
"""

# (rule, bad snippet, good twin)
_CASES = [
    (
        "TPA001",
        _HEADER + """
    @jax.jit
    def f(x):
        if x > 0:
            return x
        return -x
    """,
        _HEADER + """
    @partial(jax.jit, static_argnames=("n",))
    def f(x, n, mask=None):
        if n > 0:
            return x * n
        if mask is None:
            return x
        if x.shape[0] > 2:
            return x[:2]
        return jnp.where(x > 0, x, -x)
    """,
    ),
    (
        "TPA001",  # while on a value derived from a traced argument
        _HEADER + """
    @jax.jit
    def f(x):
        total = jnp.sum(x)
        while total > 1.0:
            total = total / 2
        return total
    """,
        _HEADER + """
    @jax.jit
    def f(x):
        total = len(x)  # len() is concrete under trace
        while total > 1:
            total //= 2
        return x * total
    """,
    ),
    (
        "TPA002",
        _HEADER + """
    @jax.jit
    def f(x):
        return np.maximum(x, 0.0)
    """,
        _HEADER + """
    @jax.jit
    def f(x):
        steps = np.arange(x.shape[0])  # numpy on concrete shape metadata
        return jnp.maximum(x, 0.0) + jnp.asarray(steps)
    """,
    ),
    (
        "TPA003",
        _HEADER + """
    _CACHE = {}

    @jax.jit
    def f(x):
        return x * _CACHE["scale"]
    """,
        _HEADER + """
    _SCALE = 3.0

    @jax.jit
    def f(x):
        _CACHE = {}  # local, not module state
        _CACHE["scale"] = _SCALE
        return x * _CACHE["scale"]
    """,
    ),
    (
        "TPA004",
        _HEADER + """
    @partial(jax.jit, static_argnames=("num_steps",))
    def f(x, n_steps):
        return x * n_steps
    """,
        _HEADER + """
    @partial(jax.jit, static_argnames=("n_steps",), donate_argnums=(0,))
    def f(x, n_steps):
        return x * n_steps
    """,
    ),
    (
        "TPA005",
        _HEADER + """
    @partial(jax.jit, donate_argnums=(0,))
    def step(state, delta):
        return state + delta

    def drive(state, deltas):
        out = step(state, deltas)
        return state + out  # state's buffer was donated
    """,
        _HEADER + """
    @partial(jax.jit, donate_argnums=(0,))
    def step(state, delta):
        return state + delta

    def drive(state, deltas):
        state = step(state, deltas)
        return state + 1
    """,
    ),
    (
        "TPA006",
        _HEADER + """
    def f(path):
        try:
            return open(path).read()
        except Exception:
            return None
    """,
        _HEADER + """
    def f(path, pool):
        try:
            return open(path).read()
        except OSError:
            return None

    def g(path, pool):
        slot = pool.pop()
        try:
            return open(path)
        except Exception:  # ends in bare raise: cleanup pass-through
            pool.append(slot)
            raise
    """,
    ),
    (
        "TPA007",
        _HEADER + """
    def drain(q):
        while True:
            try:
                return q.get_nowait()
            except KeyError:
                continue
    """,
        _HEADER + """
    import time

    def drain(q):
        while True:
            try:
                return q.get_nowait()
            except KeyError:
                time.sleep(0.01)  # backoff bounds the retry rate
                continue

    def drain_bounded(q):
        for _attempt in range(5):  # bounded loop: never flagged
            try:
                return q.get_nowait()
            except KeyError:
                continue
        raise TimeoutError

    def drain_guarded(q, stop):
        while not stop.is_set():  # loop test bounds it
            try:
                return q.get_nowait()
            except KeyError:
                continue

    def drain_escapes(q):
        while True:
            try:
                return q.get_nowait()
            except KeyError:
                if q.closed:
                    raise
                continue
    """,
    ),
]


@pytest.mark.parametrize(
    "rule,bad,good", _CASES, ids=[f"{c[0]}-{i}" for i, c in enumerate(_CASES)]
)
def test_rule_flags_bad_not_good(tmp_path, rule, bad, good):
    bad_report = _lint(tmp_path, bad, "bad.py")
    assert [f.code for f in bad_report.findings] == [rule], (
        f"expected exactly one {rule}, got "
        f"{[str(f) for f in bad_report.findings]}"
    )
    good_report = _lint(tmp_path, good, "good.py")
    assert good_report.findings == [], [str(f) for f in good_report.findings]


def test_inline_suppression(tmp_path):
    src = _HEADER + """
    @jax.jit
    def f(x):
        if x > 0:  # tpa: disable=TPA001 — fixture: deliberately suppressed
            return x
        return -x
    """
    assert _lint(tmp_path, src).findings == []
    # ...but a different code on that line is NOT covered by the disable
    src_wrong = src.replace("disable=TPA001", "disable=TPA006")
    assert [f.code for f in _lint(tmp_path, src_wrong).findings] == ["TPA001"]


def test_baseline_grandfathers_and_expires(tmp_path):
    src = _HEADER + """
    def f(path):
        try:
            return open(path).read()
        except Exception:
            return None
    """
    report = _lint(tmp_path, src, "mod.py")
    assert len(report.findings) == 1
    baseline = tmp_path / "baseline.json"
    write_baseline(report, str(baseline), reason="grandfathered: fixture")
    again = _lint(tmp_path, src, "mod.py", baseline=str(baseline))
    assert again.findings == [] and len(again.baselined) == 1
    # the fingerprint is line-number-free: prepending code keeps it matched
    shifted = "import os\nimport sys\n" + textwrap.dedent(src)
    (tmp_path / "mod.py").write_text(shifted)
    moved = run_rules(paths=[str(tmp_path / "mod.py")], baseline_path=str(baseline))
    assert moved.findings == [] and len(moved.baselined) == 1


def test_static_argnums_out_of_range(tmp_path):
    src = _HEADER + """
    @partial(jax.jit, static_argnums=(5,))
    def f(x, n):
        return x * n
    """
    assert [f.code for f in _lint(tmp_path, src).findings] == ["TPA004"]


def test_assignment_form_jit_checked(tmp_path):
    src = _HEADER + """
    def _f(x, n):
        return x * n

    f = jax.jit(_f, static_argnames=("m",))
    """
    assert [f.code for f in _lint(tmp_path, src).findings] == ["TPA004"]


def test_cli_modules_exempt_from_tpa006(tmp_path):
    src = _HEADER + """
    def f(path):
        try:
            return open(path).read()
        except Exception:
            return None
    """
    (tmp_path / "cli").mkdir()
    f = tmp_path / "cli" / "serve.py"
    f.write_text(textwrap.dedent(src))
    assert run_rules(paths=[str(tmp_path)]).findings == []


# --------------------------------------------------------------------------
# the shipped tree + CLI surface (the acceptance criteria, in-process)


def test_package_lints_clean():
    report = run_rules()  # default: package + checked-in baseline
    assert report.findings == [], "\n".join(str(f) for f in report.findings)
    # the baseline is real, not vestigial: the grandfathered finding exists
    assert len(report.baselined) >= 1


def test_cli_rules_exit_codes(capsys):
    assert analysis_main(["rules"]) == 0
    assert analysis_main(["rules", "--paths", _BAD_CORPUS]) == 1
    assert analysis_main(["rules", "--paths", _GOOD_CORPUS]) == 0
    capsys.readouterr()


def test_cli_bad_corpus_fires_every_rule(capsys):
    rc = analysis_main(["rules", "--paths", _BAD_CORPUS, "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert sorted(payload["counts"]) == [
        "TPA001", "TPA002", "TPA003", "TPA004", "TPA005", "TPA006", "TPA007",
    ]


def test_cli_json_rules_diffable(capsys):
    assert analysis_main(["rules", "--format", "json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"] == {} and payload["files_checked"] > 50


# --------------------------------------------------------------------------
# contracts


def test_contracts_fast_matrix():
    results = run_contracts("fast")
    failed = [str(r) for r in results if not r.ok]
    assert not failed, "\n".join(failed)
    # the fast matrix must cover all three cache variants + GQA
    configs = {r.config for r in results if r.contract == "cache_parity"}
    assert {"lm_bf16", "lm_int8_cache", "lm_window", "lm_gqa"} <= configs


@pytest.mark.slow
def test_contracts_full_matrix(capsys):
    assert analysis_main(["contracts", "--matrix", "full", "--format", "json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["passed"] == payload["total"] > 50


def test_contract_checker_catches_dtype_drift():
    """The checker itself must FAIL on a broken contract (not vacuously
    pass): a cache whose step path writes a different dtype than prefill."""
    from transformer_tpu.analysis.contracts import _tree_spec

    good = jax.eval_shape(lambda: {"k": jnp.zeros((2, 4), jnp.bfloat16)})
    drifted = jax.eval_shape(lambda: {"k": jnp.zeros((2, 4), jnp.float32)})
    assert _tree_spec(good) != _tree_spec(drifted)


# --------------------------------------------------------------------------
# retrace sentinel


def test_sentinel_counts_recompiles():
    @jax.jit
    def f(x):
        return x * 2

    f(jnp.ones((2,)))  # warmup
    s = RetraceSentinel()
    s.watch("f", f, budget=0)
    s.snapshot()
    f(jnp.ones((2,)))  # same shape: cached
    assert s.violations() == []
    f(jnp.ones((3,)))  # new shape: recompile
    assert [d.name for d in s.violations()] == ["f"]
    with pytest.raises(AssertionError, match="retrace budget"):
        s.assert_within_budget()


def test_sentinel_rejects_unjitted():
    s = RetraceSentinel()
    with pytest.raises(ValueError, match="_cache_size"):
        s.watch("plain", lambda x: x)


def test_leak_checking_raises_on_tracer_leak():
    leaked = []

    @jax.jit
    def f(x):
        leaked.append(x)
        return x + 1

    with pytest.raises(Exception, match="[Ll]eak"):
        with leak_checking():
            f(jnp.ones((2,)))


def test_decode_steady_state_zero_retraces():
    """Acceptance criterion: 0 recompiles across 3 steady-state decode
    steps on the serving hot path (_pool_step / _slot_prefill / pick)."""
    from transformer_tpu.analysis.retrace import decode_retrace_report

    deltas = decode_retrace_report(steps=3)
    assert len(deltas) == 3
    bad = [d.to_dict() for d in deltas if not d.within_budget]
    assert not bad, bad


@pytest.mark.slow
def test_train_steady_state_zero_retraces():
    from transformer_tpu.analysis.retrace import train_retrace_report

    deltas = train_retrace_report(steps=3)
    assert all(d.within_budget for d in deltas), [d.to_dict() for d in deltas]


# --------------------------------------------------------------------------
# epoch-rng dedup satellite


def test_epoch_rng_single_definition():
    from transformer_tpu.data.seeding import epoch_rng

    a = epoch_rng(7, 3).integers(0, 1 << 30, size=8)
    b = epoch_rng(7, 3).integers(0, 1 << 30, size=8)
    c = epoch_rng(7, 4).integers(0, 1 << 30, size=8)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    # matches the historical inline construction bit for bit (checkpointed
    # runs resume with identical shuffles)
    legacy = np.random.default_rng((7, 3)).integers(0, 1 << 30, size=8)
    np.testing.assert_array_equal(a, legacy)


def test_no_inline_epoch_rng_left():
    """The (seed, epoch) construction lives in exactly one module."""
    import pathlib

    import transformer_tpu

    root = pathlib.Path(transformer_tpu.__file__).parent
    offenders = [
        str(p)
        for p in root.rglob("*.py")
        if "default_rng((" in p.read_text() and p.name != "seeding.py"
    ]
    assert offenders == [], offenders


# --------------------------------------------------------------------------
# concurrency rules (TPA101-105): every rule gets a must-flag snippet and a
# must-not-flag twin, mirroring the TPA001-006 cases above

from transformer_tpu.analysis.concurrency import run_concurrency  # noqa: E402

_CONC_BAD_CORPUS = str(_FIXTURES / "tpa_conc_bad_corpus.py")
_CONC_GOOD_CORPUS = str(_FIXTURES / "tpa_conc_good_corpus.py")

_CONC_HEADER = """\
    import queue
    import threading
    import time
"""

# (rule, bad snippet, good twin)
_CONC_CASES = [
    (
        "TPA101",  # unguarded shared write
        _CONC_HEADER + """
    class Shared:
        def __init__(self):
            self.state = {}
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self.loop, daemon=True)

        def loop(self):
            while True:
                with self._lock:
                    print(dict(self.state))

        def poke(self):
            self.state["x"] = 1
    """,
        _CONC_HEADER + """
    class Shared:
        def __init__(self):
            self.state = {}
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self.loop, daemon=True)

        def loop(self):
            while True:
                with self._lock:
                    print(dict(self.state))

        def poke(self):
            with self._lock:
                self.state["x"] = 1
    """,
    ),
    (
        "TPA101",  # closure scope: Thread(target=<nested def>)
        _CONC_HEADER + """
    def pump(items):
        out = []

        def worker():
            for x in items:
                out.append(x)

        t = threading.Thread(target=worker)
        t.start()
        out.append("consumer-side")  # racing the worker's appends
        t.join()
        return out
    """,
        _CONC_HEADER + """
    def pump(items):
        out = []
        q = queue.Queue()

        def worker():
            for x in items:
                q.put(x)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        out.append("after-join")  # reads/writes only after the join
        return out
    """,
    ),
    (
        "TPA102",  # inconsistent guard choice
        _CONC_HEADER + """
    class TwoGuards:
        def __init__(self):
            self.n = 0
            self._a = threading.Lock()
            self._b = threading.Lock()
            self._t = threading.Thread(target=self.loop)

        def loop(self):
            with self._a:
                self.n = 1

        def other(self):
            with self._b:
                self.n = 2
    """,
        _CONC_HEADER + """
    class OneGuard:
        def __init__(self):
            self.n = 0
            self._a = threading.Lock()
            self._b = threading.Lock()
            self._t = threading.Thread(target=self.loop)

        def loop(self):
            with self._a:
                self.n = 1

        def other(self):
            with self._a:
                self.n = 2
    """,
    ),
    (
        "TPA103",  # lock-order cycle
        _CONC_HEADER + """
    class ABBA:
        def __init__(self):
            self.x = 0
            self._a = threading.Lock()
            self._b = threading.Lock()
            self._t = threading.Thread(target=self.fwd)

        def fwd(self):
            with self._a:
                with self._b:
                    self.x = 1

        def rev(self):
            with self._b:
                with self._a:
                    self.x = 2
    """,
        _CONC_HEADER + """
    class ABAB:
        def __init__(self):
            self.x = 0
            self._a = threading.Lock()
            self._b = threading.Lock()
            self._t = threading.Thread(target=self.fwd)

        def fwd(self):
            with self._a:
                with self._b:
                    self.x = 1

        def rev(self):
            with self._a:
                with self._b:
                    self.x = 2
    """,
    ),
    (
        "TPA104",  # non-atomic refcount RMW
        _CONC_HEADER + """
    class Refs:
        def __init__(self):
            self.refs = 0
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self.watch)

        def watch(self):
            with self._lock:
                print(self.refs)

        def retain(self):
            self.refs += 1
    """,
        _CONC_HEADER + """
    class Refs:
        def __init__(self):
            self.refs = 0
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self.watch)

        def watch(self):
            with self._lock:
                print(self.refs)

        def retain(self):
            with self._lock:
                self.refs += 1
    """,
    ),
    (
        "TPA105",  # blocking under lock
        _CONC_HEADER + """
    _LOCK = threading.Lock()

    def checkpoint(path, payload):
        with _LOCK:
            with open(path, "w") as f:
                f.write(payload)
    """,
        _CONC_HEADER + """
    _LOCK = threading.Lock()

    def checkpoint(path, payload):
        with _LOCK:
            snapshot = str(payload)
        with open(path, "w") as f:
            f.write(snapshot)
    """,
    ),
]


def _conc_lint(tmp_path, source, name="snippet.py", baseline=None):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return run_concurrency(paths=[str(f)], baseline_path=baseline)


@pytest.mark.parametrize(
    "rule,bad,good", _CONC_CASES,
    ids=[f"{c[0]}-{i}" for i, c in enumerate(_CONC_CASES)],
)
def test_conc_rule_flags_bad_not_good(tmp_path, rule, bad, good):
    bad_report = _conc_lint(tmp_path, bad, "bad.py")
    assert rule in [f.code for f in bad_report.findings], (
        f"expected {rule}, got {[str(f) for f in bad_report.findings]}"
    )
    good_report = _conc_lint(tmp_path, good, "good.py")
    assert good_report.findings == [], [str(f) for f in good_report.findings]


def test_conc_inline_suppression(tmp_path):
    src = _CONC_HEADER + """
    class Shared:
        def __init__(self):
            self.state = {}
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self.loop)

        def loop(self):
            with self._lock:
                print(dict(self.state))

        def poke(self):
            self.state["x"] = 1  # tpa: disable=TPA101 — fixture: suppressed
    """
    assert _conc_lint(tmp_path, src).findings == []


def test_conc_baseline_grandfathers(tmp_path):
    src = _CONC_CASES[0][1]
    report = _conc_lint(tmp_path, src, "mod.py")
    assert len(report.findings) == 1
    baseline = tmp_path / "conc_baseline.json"
    write_baseline(report, str(baseline), reason="grandfathered: fixture")
    again = _conc_lint(tmp_path, src, "mod.py", baseline=str(baseline))
    assert again.findings == [] and len(again.baselined) == 1


def test_conc_sync_objects_not_shared_state(tmp_path):
    """Queues/Events/locks ARE the synchronization — cross-thread use of
    them must not be flagged (the prefetch worker's protocol)."""
    src = _CONC_HEADER + """
    def drive(items):
        q = queue.Queue(maxsize=2)
        stop = threading.Event()

        def worker():
            for x in items:
                if stop.is_set():
                    return
                q.put(x)

        t = threading.Thread(target=worker)
        t.start()
        first = q.get()
        stop.set()
        t.join()
        return first
    """
    assert _conc_lint(tmp_path, src).findings == []


def test_conc_package_clean():
    """The shipped tree holds the concurrency bar: zero unbaselined
    findings (the two justified handoffs are suppressed inline)."""
    report = run_concurrency()
    assert report.findings == [], "\n".join(str(f) for f in report.findings)


def test_cli_concurrency_exit_codes(capsys):
    assert analysis_main(["concurrency"]) == 0
    assert analysis_main(["concurrency", "--paths", _CONC_BAD_CORPUS]) == 1
    assert analysis_main(["concurrency", "--paths", _CONC_GOOD_CORPUS]) == 0
    capsys.readouterr()


def test_cli_conc_bad_corpus_fires_every_rule(capsys):
    rc = analysis_main(
        ["concurrency", "--paths", _CONC_BAD_CORPUS, "--format", "json"]
    )
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert sorted(payload["counts"]) == [
        "TPA101", "TPA102", "TPA103", "TPA104", "TPA105",
    ]


# --------------------------------------------------------------------------
# deterministic interleaving checker


def test_schedules_canned_scenarios_clean():
    """Acceptance criterion: >= 200 distinct interleavings across the
    canned scenarios, zero invariant violations, zero deadlocks."""
    from transformer_tpu.analysis.schedules import run_scenarios

    results = run_scenarios()
    total = sum(r.schedules for r in results)
    assert total >= 200, f"only {total} interleavings explored"
    for r in results:
        assert not r.violations, (r.name, [v.to_dict() for v in r.violations])
        assert not r.deadlocks, r.name
    assert {r.name for r in results} == {
        "prefix_cache_contention", "kv_pool_contention",
        "registry_scrape_vs_create", "prefetch_shutdown",
        "eventlog_writers", "router_dispatch_tables", "supervisor_respawn",
        "rolling_upgrade",
    }


def test_cli_schedules(capsys):
    rc = analysis_main(
        ["schedules", "--scenario", "eventlog_writers",
         "--max-schedules", "8", "--format", "json"]
    )
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["ok"] and payload["total_schedules"] == 8


def test_scheduler_finds_deadlock():
    """An AB/BA lock-order scenario must be driven INTO its deadlock by
    some explored schedule (and reported, not hung)."""
    from transformer_tpu.analysis import schedules as S

    def setup(sched):
        a, b = S.DetLock(sched), S.DetLock(sched)

        def fwd():
            with a:
                sched.switch_point()
                with b:
                    pass

        def rev():
            with b:
                sched.switch_point()
                with a:
                    pass

        return [fwd, rev], None

    scen = S.Scenario(
        name="abba", setup=setup, modules=lambda: [],
        instrument=lambda: [], max_schedules=32,
    )
    result = S.explore(scen)
    assert result.deadlocks > 0
    assert any(v.kind == "deadlock" for v in result.violations)


def test_scheduler_finds_lost_update():
    """A read-modify-write with no lock must lose an update under some
    explored interleaving — the TPA104 bug class, demonstrated live."""
    from transformer_tpu.analysis import schedules as S

    def setup(sched):
        box = {"n": 0}

        def bump():
            for _ in range(2):
                tmp = box["n"]
                sched.switch_point()  # the preemption window
                box["n"] = tmp + 1

        def check():
            assert box["n"] == 4, f"lost update: {box['n']} != 4"

        return [bump, bump], check

    scen = S.Scenario(
        name="lost_update", setup=setup, modules=lambda: [],
        instrument=lambda: [], max_schedules=64,
    )
    result = S.explore(scen)
    assert any(v.kind == "invariant" for v in result.violations)


def test_scheduler_replays_violation_schedule():
    """Every recorded decision trace must REPRODUCE its violation when
    replayed — the property that makes checker reports actionable. The
    scenario deliberately mixes a DetLock (forced single-runnable points)
    into the race so the branch-trace indexing is exercised."""
    from transformer_tpu.analysis import schedules as S

    def setup(sched):
        lock = S.DetLock(sched)
        box = {"n": 0, "log": 0}

        def bump():
            with lock:  # unrelated guarded work: forces blocking points
                box["log"] += 1
            tmp = box["n"]
            sched.switch_point()
            box["n"] = tmp + 1

        def check():
            assert box["n"] == 2, "lost"

        return [bump, bump], check

    scen = S.Scenario(
        name="replay", setup=setup, modules=lambda: [],
        instrument=lambda: [], max_schedules=64,
    )
    result = S.explore(scen)
    bad = [v for v in result.violations if v.kind == "invariant"]
    assert bad
    for v in bad:
        replay = S._run_one(scen, list(v.schedule), None)
        assert any(rv.kind == "invariant" for rv in replay.violations), (
            f"recorded schedule {v.schedule} did not reproduce {v.detail!r}"
        )


@pytest.mark.slow
def test_registry_scrape_canary_catches_unlocked_iteration():
    """Revert-the-lock canary: with the PR 3 registry lock's job undone
    (a lazy, unlocked dict walk in __iter__ — the pre-fix shape), the
    schedule explorer must catch the scrape-vs-lazy-creation race the
    lock exists to prevent."""
    import functools

    import transformer_tpu.obs.registry as regmod
    from transformer_tpu.analysis import schedules as S
    from transformer_tpu.obs.registry import MetricsRegistry

    class UnlockedRegistry(MetricsRegistry):
        def __iter__(self):  # no lock, no snapshot — the reverted bug
            metrics = []
            for name in self._metrics:
                metrics.append(self._metrics[name])
            return iter(sorted(metrics, key=lambda m: m.name))

    scen = S.Scenario(
        name="registry_canary",
        setup=functools.partial(
            S._scenario_registry, registry_factory=UnlockedRegistry
        ),
        modules=lambda: [regmod],
        instrument=lambda: [regmod.__file__, __file__],
        max_schedules=64,
    )
    result = S.explore(scen)
    assert any(
        "dictionary changed size" in v.detail for v in result.violations
    ), [v.to_dict() for v in result.violations]


@pytest.mark.slow
def test_eventlog_canary_catches_unlocked_split_write():
    """Revert-the-lock canary for the event log: an unlocked two-part
    write (payload, then newline — the torn-JSONL shape) must produce an
    interleaving whose output no longer parses line-per-event."""
    import functools

    import transformer_tpu.obs.events as evmod
    from transformer_tpu.analysis import schedules as S
    from transformer_tpu.obs.events import EventLog

    class UnlockedLog(EventLog):
        def emit(self, kind, **fields):  # no lock, split write
            import json as _json
            line = _json.dumps({"kind": kind, **fields})
            self._file.write(line)
            self._file.write("\n")

    scen = S.Scenario(
        name="eventlog_canary",
        setup=functools.partial(S._scenario_eventlog, log_factory=UnlockedLog),
        modules=lambda: [evmod],
        instrument=lambda: [evmod.__file__, __file__],
        max_schedules=64,
    )
    result = S.explore(scen)
    assert any(v.kind == "invariant" for v in result.violations), [
        v.to_dict() for v in result.violations
    ]


# --------------------------------------------------------------------------
# the pre-merge gate (PR 9 satellite): `analysis all` enforced by pytest


def test_analysis_all_cli_gate(request):
    """docs/ANALYSIS.md names `python -m transformer_tpu.analysis all` as
    THE pre-merge gate; this test makes pytest actually enforce it: the
    shelled CLI must exit 0 with ALL EIGHT families run and clean, and the
    --format=json stream must parse (one JSON document per family, headers
    on stderr so stdout stays machine-readable). The subprocess is
    LAUNCHED at collection time (conftest pytest_collection_modifyitems)
    so its ~80s of CPU overlap the single-threaded suite instead of
    extending it; this test collects the result (and is the fallback
    launcher when run in isolation)."""
    proc = getattr(request.config, "_analysis_all_gate", None)
    if proc is None:
        import conftest  # tests/ is on sys.path under pytest

        proc = conftest.launch_analysis_all_gate()
    stdout, stderr = proc.communicate(timeout=580)
    assert proc.returncode == 0, (stdout[-2000:], stderr[-2000:])
    families = {"rules", "concurrency", "sharding", "schedules",
                "contracts", "retrace", "costs", "kernels"}
    headers = {
        line.strip("= ").strip()
        for line in stderr.splitlines()
        if line.startswith("== ") and line.rstrip().endswith("==")
    }
    assert headers == families, headers
    assert "8/8 families clean" in stderr, stderr[-2000:]
    # The stdout stream is a sequence of JSON documents — parse them all.
    decoder = json.JSONDecoder()
    text, idx, docs = stdout, 0, []
    while idx < len(text):
        while idx < len(text) and text[idx].isspace():
            idx += 1
        if idx >= len(text):
            break
        doc, end = decoder.raw_decode(text, idx)
        idx = end
        docs.append(doc)
    assert len(docs) == len(families), (
        f"expected {len(families)} JSON documents, got {len(docs)}"
    )
    # The TPA300 kernel-verifier family must be IN the stream (its doc is
    # the only one carrying a per-kernel VMEM report).
    kernel_docs = [
        d for d in docs
        if isinstance(d, dict) and "kernels" in d and "generation" in d
    ]
    assert len(kernel_docs) == 1, [sorted(d) for d in docs]
    kdoc = kernel_docs[0]
    assert kdoc["ok"] is True
    assert kdoc["kernels"], "kernel verifier reported no sites"
    names = {k["kernel"] for k in kdoc["kernels"]}
    assert {"_fwd_kernel", "_paged_kernel", "_fused_kernel"} <= names, names
