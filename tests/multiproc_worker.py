"""Worker for the real multi-process test (tests/test_multiprocess.py).

Launched twice (process_id 0 and 1) with a shared coordinator address; each
process owns 4 virtual CPU devices, so the global mesh has 8. Exercises the
code paths that single-process tests cannot: ``jax.distributed.initialize``
bring-up, ``make_array_from_process_local_data`` batch assembly from
process-local shards, cross-process collectives in the sharded train step,
and the multi-process sharded-checkpoint barrier protocol.

Prints one JSON line with per-step losses and a restore checksum.
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)


def main() -> None:
    coordinator, pid, workdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]

    from transformer_tpu.parallel.mesh import initialize_distributed

    initialize_distributed(
        coordinator_address=coordinator, num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    import numpy as np

    from transformer_tpu.config import MeshConfig, ModelConfig, TrainConfig
    from transformer_tpu.parallel import (
        create_sharded_state,
        make_mesh,
        make_sharded_steps,
        put_batch,
    )
    from transformer_tpu.train import CheckpointManager
    from transformer_tpu.utils.preemption import tree_checksum

    model_cfg = ModelConfig(
        num_layers=2, d_model=16, num_heads=4, dff=32,
        input_vocab_size=32, target_vocab_size=32, max_position=32,
        dtype="float32", dropout_rate=0.0,
    )
    train_cfg = TrainConfig(
        batch_size=16, sequence_length=8, warmup_steps=10,
        loss_normalization="tokens",
    )
    rng = jax.random.PRNGKey(42)

    def run_steps(mesh_cfg: MeshConfig) -> tuple:
        """Three sharded optimizer steps on a fresh mesh/state; the batches
        are identical by construction across calls (and processes): same
        GLOBAL batch everywhere, each process feeding only its row shard
        (the multi-host data contract, Seq2SeqDataset.shard_index)."""
        mesh = make_mesh(mesh_cfg)
        state, shardings = create_sharded_state(
            jax.random.PRNGKey(0), model_cfg, train_cfg, mesh
        )
        step, _ = make_sharded_steps(
            mesh, model_cfg, train_cfg, shardings, donate=False
        )
        losses = []
        for i in range(3):
            ks, kt = jax.random.split(jax.random.PRNGKey(100 + i))
            src = np.asarray(jax.random.randint(ks, (16, 8), 1, 32), np.int32)
            tgt = np.asarray(jax.random.randint(kt, (16, 8), 1, 32), np.int32)
            lo, hi = pid * 8, (pid + 1) * 8
            state, m = step(
                state,
                put_batch(src[lo:hi], mesh),
                put_batch(tgt[lo:hi], mesh),
                rng,
            )
            losses.append(float(m["loss"]))
        return losses, state

    losses, state = run_steps(MeshConfig(data=4, fsdp=2))

    # Multi-process sharded checkpoint: every process writes its addressable
    # shards; device-backed barriers order clear -> write -> rename.
    mgr = CheckpointManager(os.path.join(workdir, "ckpt"), max_to_keep=2)
    mgr.save(state, step=3)
    restored = mgr.restore(state, step=3)
    checksum = tree_checksum(jax.device_get(restored.params))

    # Hybrid multi-slice mesh (MeshConfig.dcn_data): the data axis spans the
    # two processes as DCN granules (process_is_granule off-TPU), fsdp stays
    # intra-process — the "data over DCN, everything else over ICI" layout.
    # Numerics must match the flat-mesh run on the same batches.
    hlosses, _ = run_steps(MeshConfig(data=4, fsdp=2, dcn_data=2))

    # Cross-process consistency sanitizer (utils/consistency.py — the §5
    # "race detection" equivalent), exercised for real across 2 processes:
    # identical replicated state passes; per-process divergence is caught;
    # legitimately-sharded leaves (fsdp state above) are skipped, not
    # false-positived.
    from transformer_tpu.utils.consistency import (
        assert_cross_process_consistent,
    )

    consistency_ok = True
    try:
        assert_cross_process_consistent(
            {"w": np.arange(8, dtype=np.float32)}, label="same-everywhere"
        )
        assert_cross_process_consistent(state.params, label="sharded-skip")
    except RuntimeError:
        consistency_ok = False
    divergence_caught = False
    try:
        assert_cross_process_consistent(
            {"w": np.arange(8, dtype=np.float32) + pid}, label="diverged"
        )
    except RuntimeError:
        divergence_caught = True

    print(
        json.dumps(
            {
                "pid": pid,
                "losses": [round(l, 6) for l in losses],
                "hybrid_losses": [round(l, 6) for l in hlosses],
                "restore_checksum": checksum,
                "consistency_ok": consistency_ok,
                "divergence_caught": divergence_caught,
                "n_processes": jax.process_count(),
                "n_devices": len(jax.devices()),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
