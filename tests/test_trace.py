"""Request-scoped distributed tracing (obs/trace.py), multi-source merge
(obs/merge.py), and the Perfetto exporter — the PR 9 tentpole.

Covers the acceptance criteria: byte-identical answers and zero
steady-state recompiles with tracing ON, span-tree completeness (every
opened span closes exactly once, parentage acyclic) including under the
fast chaos subset, Chrome trace-event schema round-trip, multi-source
merge with deliberately skewed clocks, and trace attribution on
retry/breaker/fault events.
"""

from __future__ import annotations

import io
import json
import os
import subprocess
import sys
import threading

import pytest

from transformer_tpu.obs import EventLog, Telemetry
from transformer_tpu.obs.merge import (
    estimate_skews,
    filter_events,
    merge_events,
    parse_duration,
)
from transformer_tpu.obs.trace import (
    SpanContext,
    Tracer,
    chrome_trace,
    span_tree,
    traced_call,
)

# --------------------------------------------------------------------------
# SpanContext / traceparent


def test_traceparent_round_trip():
    ctx = SpanContext.new()
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    parsed = SpanContext.from_traceparent(ctx.to_traceparent())
    assert parsed.trace_id == ctx.trace_id
    assert parsed.span_id == ctx.span_id
    child = ctx.child()
    assert child.trace_id == ctx.trace_id and child.span_id != ctx.span_id


@pytest.mark.parametrize("bad", [
    None, 17, "", "not-a-header",
    "00-short-beef-01",
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",      # all-zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",      # all-zero span id
    "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",      # reserved version
    "00-" + "g" * 32 + "-" + "b" * 16 + "-01",      # non-hex
])
def test_traceparent_invalid_degrades_to_none(bad):
    assert SpanContext.from_traceparent(bad) is None


# --------------------------------------------------------------------------
# Tracer mechanics


def _buf_tracer():
    buf = io.StringIO()
    return Tracer(EventLog(buf).emit), buf


def _spans(buf) -> list:
    return [json.loads(line) for line in buf.getvalue().splitlines()]


def test_span_stack_parenting_and_emission():
    tracer, buf = _buf_tracer()
    with tracer.span("outer", lane="train") as outer:
        with tracer.span("inner") as inner:
            assert inner.ctx.trace_id == outer.ctx.trace_id
            assert inner.parent_id == outer.ctx.span_id
    assert tracer.open_count == 0
    events = _spans(buf)
    # inner closes first (emit-on-close), both land with lineage intact.
    assert [e["name"] for e in events] == ["inner", "outer"]
    assert events[0]["parent"] == events[1]["span"]
    assert events[1].get("parent") is None
    assert events[0]["dur_s"] >= 0 and events[0]["t0"] <= events[0]["ts"]
    assert events[1]["lane"] == "train"


def test_span_explicit_parent_beats_stack_and_threads_are_isolated():
    tracer, buf = _buf_tracer()
    root = tracer.start_span("request")
    seen = {}

    def worker():
        # A fresh thread has no current span: a new root starts there.
        with tracer.span("other-thread") as sp:
            seen["ctx"] = sp.ctx
    with tracer.span("step"):
        child = tracer.start_span("explicit", parent=root)
        t = threading.Thread(target=worker)
        t.start()
        t.join()
        child.end()
    root.end()
    assert child.parent_id == root.ctx.span_id        # not the step span
    assert seen["ctx"].trace_id != root.ctx.trace_id  # thread-local stack
    assert tracer.open_count == 0


def test_span_double_end_is_counted_not_fatal():
    tracer, buf = _buf_tracer()
    sp = tracer.start_span("once")
    sp.end()
    sp.end()
    assert tracer.stats["ended"] == 1
    assert tracer.stats["double_end"] == 1
    assert len(_spans(buf)) == 1


def test_span_reserved_attrs_dropped_and_exception_recorded():
    tracer, buf = _buf_tracer()
    with pytest.raises(RuntimeError):
        with tracer.span("boom", trace="shadow!"):
            raise RuntimeError("x")
    ev = _spans(buf)[0]
    assert ev["error"] == "RuntimeError"
    assert len(ev["trace"]) == 32          # the real id, not "shadow!"
    assert tracer.stats["dropped_attrs"] == 1
    assert tracer.open_count == 0


def test_traced_call_wraps_and_records():
    tracer, buf = _buf_tracer()
    calls = []

    def fn(x):
        calls.append(x)
        return x + 1

    wrapped = traced_call(fn, tracer, "unit.call", lane="train")
    assert wrapped.__wrapped__ is fn
    with tracer.span("parent") as parent:
        assert wrapped(41) == 42
    events = _spans(buf)
    assert events[0]["name"] == "unit.call"
    assert events[0]["parent"] == parent.ctx.span_id  # stack parenting
    assert events[0]["lane"] == "train"


# --------------------------------------------------------------------------
# Chrome trace-event export


def test_chrome_trace_schema_and_lanes():
    tracer, buf = _buf_tracer()
    with tracer.span("scheduler.step", lane="scheduler"):
        pass
    with tracer.span("serve.decode", lane="slot3"):
        pass
    doc = chrome_trace(_spans(buf))
    # Round-trips through JSON untouched (the on-disk format).
    doc = json.loads(json.dumps(doc))
    assert doc["displayTimeUnit"] == "ms"
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert len(xs) == 2
    for e in xs:
        assert set(e) >= {"name", "cat", "pid", "tid", "ts", "dur", "args"}
        assert e["ts"] >= 0 and e["dur"] >= 0
    lanes = {
        e["args"]["name"] for e in metas if e["name"] == "thread_name"
    }
    assert lanes == {"scheduler", "slot3"}
    by_lane = {e["args"]["name"]: e["tid"] for e in metas
               if e["name"] == "thread_name"}
    assert by_lane["slot3"] == 13  # slotN -> tid 10+N, stable across runs
    assert doc["otherData"]["spans"] == 2


def test_chrome_trace_ignores_non_span_events():
    doc = chrome_trace([
        {"kind": "serve.request", "order": 1},
        {"kind": "trace.span"},  # malformed: no t0/dur
    ])
    assert doc["traceEvents"] == [] and doc["otherData"]["spans"] == 0


# --------------------------------------------------------------------------
# multi-source merge + clock alignment


def _mk_log(path, events):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


def test_merge_estimates_deliberate_skew(tmp_path):
    """File B's clock runs 123.4s ahead; its spans are children of file A's
    spans via propagated trace context — the merge must recover the skew
    and produce one coherent timeline."""
    skew = 123.4
    t = 1_700_000_000.0
    a_events, b_events = [], []
    for i in range(5):
        trace = f"{i:032x}"
        parent = f"a{i:015x}"
        child = f"b{i:015x}"
        t0 = t + 10 * i
        a_events.append({
            "ts": t0 + 2.0, "kind": "trace.span", "trace": trace,
            "span": parent, "name": "router.request", "lane": "intake",
            "t0": t0, "dur_s": 2.0,
        })
        # True child interval [t0+0.5, t0+1.5], recorded on B's fast clock.
        b_events.append({
            "ts": t0 + 1.5 + skew, "kind": "trace.span", "trace": trace,
            "span": child, "parent": parent, "name": "serve.request",
            "lane": "slot0", "t0": t0 + 0.5 + skew, "dur_s": 1.0,
        })
    b_events.append({"ts": t + 100 + skew, "kind": "serve.request",
                     "order": 0, "total_s": 1.0})
    _mk_log(tmp_path / "router.jsonl", a_events)
    _mk_log(tmp_path / "replica.jsonl", b_events)
    merged, info = merge_events(
        [str(tmp_path / "router.jsonl"), str(tmp_path / "replica.jsonl")]
    )
    assert info["sources"]["router.jsonl"]["skew_s"] == 0.0
    assert abs(info["sources"]["replica.jsonl"]["skew_s"] - skew) < 1e-6
    # After alignment every child nests inside its parent on ONE timeline.
    trees = span_tree(merged)
    checked = 0
    for byid in trees.values():
        for e in byid.values():
            p = e.get("parent")
            if p and p in byid:
                par = byid[p]
                assert par["t0"] <= e["t0"]
                assert e["t0"] + e["dur_s"] <= par["t0"] + par["dur_s"] + 1e-6
                checked += 1
    assert checked == 5
    # Non-span events from the skewed file shifted too, and stay tagged.
    req = [e for e in merged if e["kind"] == "serve.request"][0]
    assert req["source"] == "replica.jsonl"
    assert abs(req["ts"] - (t + 100)) < 1e-6
    # Merged stream is time-sorted.
    ts = [e["ts"] for e in merged]
    assert ts == sorted(ts)


def test_merge_without_cross_links_keeps_clocks(tmp_path):
    _mk_log(tmp_path / "a.jsonl", [{"ts": 10.0, "kind": "x"}])
    _mk_log(tmp_path / "b.jsonl", [{"ts": 99.0, "kind": "y"}])
    merged, info = merge_events(
        [str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")]
    )
    assert [s["skew_s"] for s in info["sources"].values()] == [0.0, 0.0]
    assert [e["ts"] for e in merged] == [10.0, 99.0]


def test_merge_disambiguates_duplicate_basenames(tmp_path):
    (tmp_path / "r0").mkdir()
    (tmp_path / "r1").mkdir()
    _mk_log(tmp_path / "r0" / "m.jsonl", [{"ts": 1.0, "kind": "x"}])
    _mk_log(tmp_path / "r1" / "m.jsonl", [{"ts": 2.0, "kind": "x"}])
    _, info = merge_events(
        [str(tmp_path / "r0" / "m.jsonl"), str(tmp_path / "r1" / "m.jsonl")]
    )
    assert set(info["sources"]) == {"r0/m.jsonl", "r1/m.jsonl"}


def test_estimate_skews_chains_through_islands():
    # file1 linked to file0, file2 linked to file1 only: offsets chain.
    def span(sid, parent, t0, dur):
        return {"kind": "trace.span", "trace": "t" * 32, "span": sid,
                "parent": parent, "t0": t0, "dur_s": dur, "ts": t0 + dur}

    f0 = [span("a" * 16, None, 100.0, 4.0)]
    f1 = [span("b" * 16, "a" * 16, 111.0, 2.0),   # +10 skew vs f0
          span("c" * 16, None, 120.0, 4.0)]
    f2 = [span("d" * 16, "c" * 16, 126.0, 2.0)]   # +5 skew vs f1
    skews = estimate_skews([f0, f1, f2])
    assert skews[0] == 0.0
    assert abs(skews[1] - 10.0) < 1e-6
    assert abs(skews[2] - 15.0) < 1e-6


# --------------------------------------------------------------------------
# time-window filtering


def test_parse_duration_units_and_errors():
    assert parse_duration("90s") == 90.0
    assert parse_duration("5m") == 300.0
    assert parse_duration("2h") == 7200.0
    assert parse_duration("45") == 45.0
    for bad in ("", "abc", "-5s"):
        with pytest.raises(ValueError):
            parse_duration(bad)


def test_filter_events_since_and_last():
    events = [{"ts": float(t), "kind": "x"} for t in (10, 20, 30, 40)]
    events.append({"kind": "no-ts"})
    assert [e["ts"] for e in filter_events(events, since=25)] == [30.0, 40.0]
    # --last measures back from the NEWEST event, not the wall clock.
    assert [e["ts"] for e in filter_events(events, last=15)] == [30.0, 40.0]
    assert [e["ts"] for e in filter_events(events, since=35, last=30)] == [40.0]
    assert filter_events(events) == events  # no filters: untouched, ts-less kept


# --------------------------------------------------------------------------
# the traced scheduler (CPU tiny model)


@pytest.fixture(scope="module")
def lm():
    import jax

    from transformer_tpu.config import ModelConfig
    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.models import transformer_init

    tok = SubwordTokenizer.build_from_corpus(
        ["ab cd ef gh ij kl mn"] * 3, target_vocab_size=300
    )
    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=32, decoder_only=True, tie_output=True,
        dtype="float32", dropout_rate=0.0,
    )
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    return params, cfg, tok


def _scheduler(lm, telemetry, **kw):
    from transformer_tpu.serve import ContinuousScheduler

    params, cfg, tok = lm
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_total", 32)
    kw.setdefault("default_max_new", 4)
    return ContinuousScheduler(params, cfg, tok, telemetry=telemetry, **kw)


def _traced_run(lm, reqs, **kw):
    buf = io.StringIO()
    tel = Telemetry(events=EventLog(buf), interval=0.0, trace=True)
    out = _scheduler(lm, tel, **kw).run(reqs)
    events = [json.loads(line) for line in buf.getvalue().splitlines()]
    return out, events, tel.tracer


def _assert_tree_complete(events, tracer):
    """The acceptance bar: every opened span closed exactly once, every
    parent reference resolves inside its trace, parentage is acyclic."""
    assert tracer.open_count == 0, tracer.open_spans()
    assert tracer.stats["double_end"] == 0
    assert tracer.stats["started"] == tracer.stats["ended"]
    trees = span_tree(events)
    for trace, byid in trees.items():
        for sid, e in byid.items():
            seen = {sid}
            cur = e.get("parent")
            while cur is not None:
                assert cur in byid, (
                    f"span {e['name']} in trace {trace} has dangling "
                    f"parent {cur}"
                )
                assert cur not in seen, f"parent cycle in trace {trace}"
                seen.add(cur)
                cur = byid[cur].get("parent")
    return trees


def test_traced_scheduler_byte_identity_and_complete_trees(lm):
    reqs = [
        {"prompt": "ab cd ef gh ij", "max_new": 6},
        {"prompt": "kl", "max_new": 2},
        {"prompt": "ab cd", "max_new": 8, "temperature": 0.9, "seed": 3},
        {"prompt": "mn ef", "max_new": 3},
        {"prompt": "gh", "max_new": 1},
    ]
    plain = _scheduler(lm, None).run(reqs)
    traced, events, tracer = _traced_run(lm, reqs)
    assert plain == traced  # tracing must be invisible in the answers
    trees = _assert_tree_complete(events, tracer)
    # One complete request tree per request: root + queue/admit/prefill/
    # decode children.
    roots = [
        e for e in events
        if e.get("kind") == "trace.span" and e["name"] == "serve.request"
    ]
    assert len(roots) == len(reqs)
    for root in roots:
        byid = trees[root["trace"]]
        names = {e["name"] for e in byid.values()}
        assert names >= {
            "serve.request", "serve.queue", "serve.admit",
            "serve.prefill", "serve.decode",
        }, names
        assert root["lane"].startswith("slot")
        # Lifecycle children all hang off this request's tree (acyclic is
        # already checked; here: single root).
        parentless = [e for e in byid.values() if "parent" not in e]
        assert len(parentless) == 1
    # serve.request span events carry the same trace ids the span tree has.
    req_events = [e for e in events if e.get("kind") == "serve.request"]
    assert len(req_events) == len(reqs)
    assert {e["trace"] for e in req_events} == {r["trace"] for r in roots}
    # Step spans render on the scheduler lane.
    steps = [
        e for e in events
        if e.get("kind") == "trace.span" and e["name"] == "scheduler.step"
    ]
    assert steps and all(e["lane"] == "scheduler" for e in steps)


def test_traceparent_propagates_from_request(lm):
    incoming = SpanContext.new()
    reqs = [
        {"prompt": "ab cd", "max_new": 2,
         "traceparent": incoming.to_traceparent()},
        {"prompt": "ef", "max_new": 2, "traceparent": "garbage-header"},
    ]
    out, events, tracer = _traced_run(lm, reqs)
    assert all("continuation" in r for r in out)
    roots = [
        e for e in events
        if e.get("kind") == "trace.span" and e["name"] == "serve.request"
    ]
    adopted = [r for r in roots if r["trace"] == incoming.trace_id]
    assert len(adopted) == 1
    # The router's span is the root's parent (it lives in the ROUTER's log;
    # here it dangles locally — exactly what the multi-source merge joins).
    assert adopted[0]["parent"] == incoming.span_id
    # The malformed header degrades to a fresh trace, not an error.
    fresh = [r for r in roots if r["trace"] != incoming.trace_id]
    assert len(fresh) == 1 and "parent" not in fresh[0]


def test_traced_speculative_and_prefix_paths(lm):
    from transformer_tpu.serve import PrefixCache

    params, cfg, tok = lm
    reqs = [
        {"prompt": "ab cd ef gh", "max_new": 6},
        {"prompt": "ab cd ef gh", "max_new": 6},   # prefix re-use
        {"prompt": "kl mn", "max_new": 4},
    ]
    # One slot: the repeated prompt admits only after its twin RETIRED (and
    # fed the trie), so the prefix-restore path actually runs.
    kw = dict(speculate_k=2, prefill_chunk=2, num_slots=1)
    plain = _scheduler(
        lm, None, prefix_cache=PrefixCache(cfg, block_tokens=4), **kw
    ).run(reqs)
    traced, events, tracer = _traced_run(
        lm, reqs, prefix_cache=PrefixCache(cfg, block_tokens=4), **kw
    )
    assert plain == traced
    _assert_tree_complete(events, tracer)
    names = {e["name"] for e in events if e.get("kind") == "trace.span"}
    assert names >= {
        "spec.draft", "spec.verify", "spec.rollback",
        "prefix.match", "prefix.insert",
    }, names
    # The repeated prompt restored blocks: its tree carries the restore.
    assert "prefix.restore" in names


def test_chaos_subset_trees_complete_and_attributed(lm, tmp_path):
    """The fast chaos bar (the ISSUE's acceptance episode): injected
    admission+prefix faults over a speculative + prefix-cache scheduler,
    a queued deadline expiry, and a client cancel — every span still
    closes, every request answers exactly once, retry/breaker/fault
    events carry the victim's trace id, and the log exports to a Perfetto
    trace whose admitted requests are complete span trees."""
    from transformer_tpu.serve import PrefixCache, resilience

    params, cfg, tok = lm
    reqs = [
        {"prompt": "ab cd ef", "max_new": 3},
        {"prompt": "ab cd ef", "max_new": 3},
        {"prompt": "kl", "max_new": 2},
        {"prompt": "mn ef", "max_new": 2},
        {"prompt": "gh ij", "max_new": 2},
        {"prompt": "ab kl", "max_new": 0, "deadline_ms": 0},  # expires queued
    ]
    buf = io.StringIO()
    tel = Telemetry(events=EventLog(buf), interval=0.0, trace=True)
    sched = _scheduler(
        lm, tel,
        prefix_cache=PrefixCache(cfg, block_tokens=4),
        speculate_k=2,
        admission_retries=1, retry_backoff_ms=0.1,
        breaker_threshold=1, breaker_cooldown_s=1000.0,
    )
    plane = resilience.FaultPlane.parse(
        "serve.prefill:p=0.5,seed=11;prefix.match:at=1"
    )
    with resilience.active(plane):
        for r in reqs:
            sched.submit(r)
        cancel_order = sched.submit({"prompt": "ef gh", "max_new": 2})
        assert sched.cancel(cancel_order)
        out = []
        for _ in range(500):
            sched.admit()
            sched.step()
            sched.idle_backoff()
            out.extend(sched.drain_ready())
            if not sched.busy and len(out) == len(reqs) + 1:
                break
    assert len(out) == len(reqs) + 1       # every request answered once
    assert plane.episodes >= 1             # the drill actually fired
    events = [json.loads(line) for line in buf.getvalue().splitlines()]
    _assert_tree_complete(events, tel.tracer)
    req_events = [e for e in events if e.get("kind") == "serve.request"]
    assert len(req_events) == len(reqs) + 1
    by_code = {}
    for e in req_events:
        assert "trace" in e                # injected-fault answers included
        by_code.setdefault(e.get("code"), []).append(e)
    assert by_code.get("deadline"), "queued deadline expiry missing"
    assert by_code.get("cancelled"), "client cancel missing"
    # Retries carry the victim's trace id and a real backoff.
    retries = [e for e in events if e.get("kind") == "serve.retry"]
    if plane.fired.get("serve.prefill", 0):
        assert retries, "prefill faults fired but no serve.retry recorded"
    root_traces = {
        e["trace"] for e in events
        if e.get("kind") == "trace.span" and e["name"] == "serve.request"
    }
    for e in retries:
        assert e["trace"] in root_traces and e["backoff_ms"] >= 0
    # The prefix.match fault (threshold 1) opened the breaker, attributed.
    breakers = [e for e in events if e.get("kind") == "serve.breaker"]
    opened = [e for e in breakers if e["state"] == "open"]
    assert opened and all(e["trace"] in root_traces for e in opened)
    # The speculative path ran under the storm (verify spans present).
    span_names = {
        e["name"] for e in events if e.get("kind") == "trace.span"
    }
    assert "spec.verify" in span_names
    # No slot/pin leaks under the storm.
    assert len(sched._free) == sched.num_slots
    assert sched.prefix_cache.outstanding_refs() == 0
    # And the whole episode exports as a loadable Perfetto document whose
    # admitted requests are complete trees (root + lifecycle children).
    doc = json.loads(json.dumps(chrome_trace(events)))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    # Admitted requests render their root on a slot lane (tid 10+N);
    # never-admitted ones (queued expiry, backpressure) stay on intake.
    admitted = {
        e["args"]["trace"] for e in xs
        if e["name"] == "serve.request" and e["tid"] >= 10
    }
    by_trace = {}
    for e in xs:
        if "trace" in e["args"]:
            by_trace.setdefault(e["args"]["trace"], set()).add(e["name"])
    for trace in admitted:
        if "serve.prefill" in by_trace[trace]:  # reached a slot
            assert {"serve.request", "serve.queue", "serve.admit"} <= by_trace[trace]


def test_traced_scheduler_zero_recompiles(lm):
    """Tracing on the steady-state decode path costs zero recompiles —
    the retrace-sentinel acceptance criterion with spans enabled."""
    from transformer_tpu.analysis.retrace import RetraceSentinel
    from transformer_tpu.serve import scheduler as sched_mod

    tel = Telemetry(interval=0.0, trace=True)
    warm = _scheduler(lm, tel)
    warm.run([{"prompt": "ab cd", "max_new": 3}])
    sentinel = RetraceSentinel()
    sentinel.watch("_pool_step", sched_mod._pool_step, budget=0)
    sentinel.watch("_slot_prefill", sched_mod._slot_prefill, budget=0)
    sentinel.watch("_pick_pool", sched_mod._pick_pool, budget=0)
    sentinel.snapshot()
    for _ in range(3):
        tel2 = Telemetry(interval=0.0, trace=True)
        s = _scheduler(lm, tel2)
        out = s.run([{"prompt": "ab cd", "max_new": 3}])
        assert "continuation" in out[0]
        assert tel2.tracer.open_count == 0
    sentinel.assert_within_budget()


# --------------------------------------------------------------------------
# the traced trainer (tiny CPU run)


def test_traced_trainer_step_and_checkpoint_spans(tmp_path):
    import jax
    import numpy as np

    from transformer_tpu.config import ModelConfig, TrainConfig
    from transformer_tpu.train import Trainer, create_train_state
    from transformer_tpu.train.checkpoint import CheckpointManager

    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=64, target_vocab_size=64, max_position=64,
        dropout_rate=0.0, dtype="float32", decoder_only=True,
    )
    tcfg = TrainConfig(
        batch_size=2, sequence_length=8, epochs=2, warmup_steps=10,
        log_every_steps=2, eval_every_steps=0,
        ckpt_path=str(tmp_path / "ckpt"),
    )

    class DS:
        def __len__(self):
            return 4

        def batches(self, epoch):
            r = np.random.default_rng(epoch)
            for _ in range(4):
                ids = r.integers(1, 64, size=(2, 8)).astype(np.int32)
                yield ids, ids

    jsonl = str(tmp_path / "train.jsonl")
    tel = Telemetry(events=EventLog(jsonl), interval=0.0, trace=True)
    state = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
    tr = Trainer(
        cfg, tcfg, state, telemetry=tel, log_fn=lambda s: None,
        checkpoint=CheckpointManager(tcfg.ckpt_path, max_to_keep=2),
    )
    tr.fit(DS(), DS())
    tel.close()
    assert tel.tracer.open_count == 0, tel.tracer.open_spans()
    with open(jsonl) as f:
        events = [json.loads(line) for line in f]
    spans = [e for e in events if e["kind"] == "trace.span"]
    _assert_tree_complete(events, tel.tracer)
    by_name = {}
    for e in spans:
        by_name.setdefault(e["name"], []).append(e)
    assert len(by_name["train.fit"]) == 1
    fit = by_name["train.fit"][0]
    assert "parent" not in fit and fit["lane"] == "train"
    # One train.step span per dispatch (2 epochs x 4 steps), all under fit.
    assert len(by_name["train.step"]) == 8
    assert {e["parent"] for e in by_name["train.step"]} == {fit["span"]}
    assert {e["trace"] for e in spans} == {fit["trace"]}  # ONE tree
    # Eval + checkpoint spans nest under the fit span too.
    assert by_name["train.eval"]
    assert by_name["ckpt.save"] and by_name["ckpt.restore"]
    assert by_name["ckpt.save"][0]["parent"] == fit["span"]
    # chrome export puts the whole run on the train lane.
    doc = chrome_trace(events)
    lanes = {
        e["args"]["name"] for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert lanes == {"train"}


# --------------------------------------------------------------------------
# CLI round trip on a real traced run


def test_trace_cli_exports_loadable_perfetto_json(lm, tmp_path, capsys):
    from transformer_tpu.obs.__main__ import main

    jsonl = str(tmp_path / "serve.jsonl")
    tel = Telemetry(events=EventLog(jsonl), interval=0.0, trace=True)
    _scheduler(lm, tel).run([
        {"prompt": "ab cd ef", "max_new": 3},
        {"prompt": "kl", "max_new": 2},
    ])
    tel.close()
    out = str(tmp_path / "trace.json")
    assert main(["trace", jsonl, "--out", out]) == 0
    doc = json.load(open(out))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert doc["otherData"]["spans"] == len(xs) and xs
    lanes = {
        e["args"]["name"] for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert "scheduler" in lanes and any(l.startswith("slot") for l in lanes)
    assert "intake" in lanes
    # Request spans nest inside their trace: args keep lineage for the UI.
    roots = [e for e in xs if e["name"] == "serve.request"]
    assert roots and all("trace" in e["args"] for e in roots)
    # summarize over the SAME log still renders (spans don't break it) and
    # reports the span volume.
    assert main(["summarize", jsonl]) == 0
    text = capsys.readouterr().out
    assert "tracing:" in text


def test_summarize_merge_two_live_logs(lm, tmp_path, capsys):
    """Acceptance: `obs summarize --merge` over two concurrently-written
    JSONL files produces one coherent report."""
    from transformer_tpu.obs.__main__ import main

    paths = []
    for i in range(2):
        jsonl = str(tmp_path / f"replica{i}.jsonl")
        tel = Telemetry(events=EventLog(jsonl), interval=0.0, trace=True)
        _scheduler(lm, tel).run([
            {"prompt": "ab cd", "max_new": 2},
            {"prompt": "ef gh", "max_new": 2},
        ])
        tel.close()
        paths.append(jsonl)
    assert main(["summarize", *paths, "--merge", "--format", "json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["serve"]["requests"] == 4        # aggregated across files
    assert set(report["sources"]) == {"replica0.jsonl", "replica1.jsonl"}
    # --last slices the merged timeline without external tooling.
    assert main(["summarize", *paths, "--last", "1h"]) == 0
    assert main(["slo", *paths]) == 0