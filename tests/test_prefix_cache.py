"""Cross-request prefix KV cache contracts (``transformer_tpu/serve/
prefix_cache.py``): greedy AND seeded-sampled answers byte-identical with
the cache on vs off — across speculative k in {0, 4}, chunked/unchunked
prefill, and the int8/GQA cache layouts — plus the block slice/insert
round-trip bit-identity, radix-trie matching, refcounted LRU eviction
under pressure, rolling-window refusals (structured error, no slot leak),
per-request opt-out, telemetry/summarize hit rate, the zero-recompile
guarantee across hit/miss/partial-hit admissions, and the ISSUE acceptance
workload (shared 64-token system prompt, 16 requests, >= 50% of prompt
tokens served from the cache)."""

import dataclasses
import io
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig
from transformer_tpu.data.tokenizer import SubwordTokenizer
from transformer_tpu.models import transformer_init
from transformer_tpu.models.decoder import init_decoder_caches
from transformer_tpu.models.transformer import transformer_prefill
from transformer_tpu.ops.attention import (
    init_cache,
    insert_kv_blocks,
    kv_buffer_keys,
    slice_kv_blocks,
)
from transformer_tpu.serve import ContinuousScheduler, PrefixCache

LM = ModelConfig(
    num_layers=2, d_model=16, num_heads=4, dff=32,
    input_vocab_size=48, target_vocab_size=48, max_position=64,
    decoder_only=True, tie_output=True, dtype="float32", dropout_rate=0.0,
)

# The prefix cache composes with every NON-ROLLING cache variant; rolling
# windows are structurally refused (wrap eviction defeats block restore).
VARIANTS = {
    "base": LM,
    "int8": dataclasses.replace(LM, kv_cache_int8=True),
    "gqa": dataclasses.replace(LM, num_kv_heads=2),
}

_PARAMS: dict[str, object] = {}


def _params(name):
    if name not in _PARAMS:
        _PARAMS[name] = transformer_init(jax.random.PRNGKey(0), VARIANTS[name])
    return _PARAMS[name]


@pytest.fixture(scope="module")
def tok():
    return SubwordTokenizer.build_from_corpus(
        ["ab cd ef gh ij kl mn"] * 3, target_vocab_size=300
    )


def _lm_cfg(tok, **over):
    base = dict(
        num_layers=2, d_model=16, num_heads=4, dff=32,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=64, decoder_only=True, tie_output=True,
        dtype="float32", dropout_rate=0.0,
    )
    return ModelConfig(**{**base, **over})


class IdTok:
    """Tokens ARE ids ("3 17 5" -> [3, 17, 5]) — lets tests state prompt
    token counts exactly (the acceptance workload's 64-token system
    prompt) without a subword vocab blurring the arithmetic."""

    bos_id, eos_id = 1, 2

    def encode(self, text):
        return [int(t) for t in text.split()]

    def decode(self, toks):
        return " ".join(str(t) for t in toks)


# Replays, a partial-prefix variant, a miss, and a seeded-sampled request:
# every admission outcome the trie produces, with mixed decode params.
REQS = [
    {"prompt": "ab cd ef gh ij kl", "max_new": 5},
    {"prompt": "ab cd ef gh ij kl", "max_new": 5},          # full replay
    {"prompt": "ab cd ef gh mn", "max_new": 4},             # shared prefix
    {"prompt": "kl", "max_new": 2},                         # miss
    {"prompt": "ab cd ef gh ij kl mn", "max_new": 6,
     "temperature": 0.9, "seed": 3},                        # seeded sampled
]


# --------------------------------------------------------------------------
# satellite: block slice/insert round trip (ops/attention.py helpers)


@pytest.mark.parametrize("name", sorted(VARIANTS))
def test_store_slice_insert_roundtrip_bit_identical(name):
    """A prefill-stored cache, sliced into blocks and re-inserted into a
    fresh cache, must reproduce the stored rows BIT-IDENTICALLY in every
    buffer of the layout (plain k/v, int8 codes + fp32 scales, GQA head
    counts) — the invariant that makes prefix restore byte-transparent."""
    cfg = VARIANTS[name]
    params = _params(name)
    ids = jnp.asarray([[1, 5, 9, 7, 3, 11, 2, 6]], jnp.int32)
    donor = init_decoder_caches(cfg, 1, 16)
    _, donor = transformer_prefill(params, ids, None, None, donor, 0, cfg)
    block = 4
    for d, fresh in zip(donor, init_decoder_caches(cfg, 1, 16)):
        restored = fresh
        for j in range(2):
            restored = insert_kv_blocks(
                restored, slice_kv_blocks(d, j * block, block), j * block
            )
        for key in kv_buffer_keys(d):
            np.testing.assert_array_equal(
                np.asarray(d[key])[:, :8], np.asarray(restored[key])[:, :8],
                err_msg=f"{name} buffer {key!r} drifted through the "
                "slice->insert round trip",
            )


def test_block_helpers_refuse_rolling_cache():
    """Rolling-window buffers evict absolute-position rows on wrap — both
    helpers refuse them, same policy (and shared guard) as rollback."""
    rolling = init_cache(1, 8, 2, 4, window=4)
    with pytest.raises(ValueError, match="rolling"):
        slice_kv_blocks(rolling, 0, 4)
    with pytest.raises(ValueError, match="rolling"):
        insert_kv_blocks(rolling, {"k": None, "v": None}, 0)


# --------------------------------------------------------------------------
# trie mechanics (host-side, no model)


def _fake_read():
    """Stand-in for the scheduler's jitted slot export: every block is one
    layer of zero k/v rows (the trie never looks inside the arrays)."""

    def read_block(start):
        del start
        return [{
            "k": np.zeros((1, 4, 2, 4), np.float32),
            "v": np.zeros((1, 4, 2, 4), np.float32),
        }]

    return read_block


def test_trie_longest_block_aligned_match():
    pc = PrefixCache(LM, block_tokens=4, budget_mb=1)
    ids = list(range(3, 15))  # 12 tokens = 3 blocks
    pc.insert(ids, 12, _fake_read())
    hit = pc.match(ids)
    assert hit.tokens == 12
    hit.release()
    # Diverging in block 2: only the first block matches.
    other = ids[:4] + [40, 41, 42, 43] + ids[8:]
    hit = pc.match(other)
    assert hit.tokens == 4
    hit.release()
    # Sub-block prefix: no block-aligned match at all.
    hit = pc.match(ids[:3])
    assert hit.tokens == 0
    hit.release()
    # Two prompts share storage for exactly the agreeing blocks.
    assert pc.block_count() == 3
    pc.insert(other, 12, _fake_read())
    assert pc.block_count() == 5  # 1 shared + 2 + 2


def test_trie_refcounted_lru_eviction():
    """Eviction is LRU over UNPINNED CHILDLESS nodes only: a matched
    (pinned) path survives budget pressure; releasing it makes it
    evictable; interior nodes are never evicted from under descendants."""
    pc = PrefixCache(LM, block_tokens=4, budget_mb=1)
    a = [3] * 8   # 2 blocks
    b = [5] * 8
    c = [7] * 8
    pc.insert(a, 8, _fake_read())
    per_block = pc.bytes_used // 2
    pc.budget_bytes = 4 * per_block  # room for 4 blocks total
    pinned = pc.match(a)
    assert pinned.tokens == 8
    pc.insert(b, 8, _fake_read())
    assert pc.block_count() == 4
    # c needs 2 more blocks; a is pinned, so b's LEAF (then b's root block)
    # must be the victims — a survives intact.
    pc.insert(c, 8, _fake_read())
    assert pc.stats["evicted_blocks"] == 2
    survived = pc.match(a)
    assert survived.tokens == 8  # pinned path survived
    survived.release()
    gone = pc.match(b)
    assert gone.tokens == 0      # b was evicted leaf-first
    gone.release()
    pinned.release()
    # Everything unpinned now: re-inserting b evicts the LEAST RECENTLY
    # USED blocks — c's (a was just matched, refreshing its clock).
    pc.insert(b, 8, _fake_read())
    assert pc.stats["evicted_blocks"] == 4
    kept = pc.match(a)
    assert kept.tokens == 8
    kept.release()
    lru_gone = pc.match(c)
    assert lru_gone.tokens == 0
    lru_gone.release()


def test_insert_never_evicts_its_own_descend_path():
    """Regression: extending a chain that fills the whole budget must NOT
    evict the chain node the insert is descending from (which would attach
    the new block to a detached parent — unreachable by any match, yet
    counted in the byte budget forever). The path is pinned during insert,
    so the unfittable tail block is dropped before it is even fetched."""
    pc = PrefixCache(LM, block_tokens=4, budget_mb=1)
    chain = [3] * 8  # 2 blocks
    pc.insert(chain, 8, _fake_read())
    per_block = pc.bytes_used // 2
    pc.budget_bytes = 2 * per_block  # budget exactly the existing chain
    fetches = []

    def counting_read(start):
        fetches.append(start)
        return _fake_read()(start)

    extended = chain + [5] * 4  # one more block past the budget
    evicted = pc.insert(extended, 12, counting_read)
    assert evicted == 0                      # the pinned path survived
    assert fetches == []                     # unfittable block never fetched
    assert pc.stats["blocks"] == 2
    assert pc.bytes_used == 2 * per_block    # no leaked orphan bytes
    hit = pc.match(extended)
    assert hit.tokens == 8                   # chain intact, tail dropped
    hit.release()
    # With an evictable sibling making room, the same insert DOES land:
    # the sibling goes, the descend path still survives.
    pc.budget_bytes = 3 * per_block
    pc.insert([9] * 4, 4, _fake_read())      # unpinned sibling block
    pc.insert(extended, 12, _fake_read())
    assert pc.stats["blocks"] == 3
    full = pc.match(extended)
    assert full.tokens == 12
    full.release()
    gone = pc.match([9] * 4)
    assert gone.tokens == 0                  # the sibling was the victim
    gone.release()


def test_prefix_cache_refuses_rolling_config():
    with pytest.raises(ValueError, match="rolling"):
        PrefixCache(dataclasses.replace(LM, attention_window=8))


# --------------------------------------------------------------------------
# byte-parity: cache on/off across speculation, chunking, layouts


@pytest.mark.parametrize("name", sorted(VARIANTS))
@pytest.mark.parametrize("k", [0, 4])
@pytest.mark.parametrize("chunk", [0, 3])
def test_byte_parity_cache_on_off(tok, name, k, chunk):
    """Greedy and seeded-sampled continuations are byte-identical with the
    prefix cache on vs off — including a second pass over the same prompts
    where every admission is a HIT (restore + suffix prefill, no full
    forward) — across speculative k, prefill chunking, and cache layouts."""
    cfg = _lm_cfg(
        tok,
        kv_cache_int8=VARIANTS[name].kv_cache_int8,
        num_kv_heads=VARIANTS[name].num_kv_heads,
    )
    params = transformer_init(jax.random.PRNGKey(0), cfg)

    def serve(prefix_cache):
        sched = ContinuousScheduler(
            params, cfg, tok, num_slots=2, prefill_chunk=chunk,
            speculate_k=k, prefix_cache=prefix_cache,
        )
        first = sched.run([dict(r) for r in REQS])
        second = sched.run([dict(r) for r in REQS])  # all-hit pass
        return first + second, sched

    want, _ = serve(None)
    pc = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    got, sched = serve(pc)
    assert [g.get("continuation") for g in got] == [
        w.get("continuation") for w in want
    ]
    # The parity is not vacuous: the second pass served real hits.
    assert sched.stats["prefix_hit_tokens"] > 0
    assert pc.stats["blocks"] > 0


def test_opt_out_neither_reads_nor_feeds(tok):
    """cache_prefix=false requests bypass the trie in BOTH directions: no
    restored tokens, no inserted blocks — and the answer is still
    byte-identical (the cache is transparent either way)."""
    cfg = _lm_cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    req = {"prompt": "ab cd ef gh ij kl", "max_new": 4}
    want = ContinuousScheduler(params, cfg, tok, num_slots=1).run(
        [dict(req), dict(req)]
    )
    pc = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    sched = ContinuousScheduler(
        params, cfg, tok, num_slots=1, prefix_cache=pc
    )
    got = sched.run([
        {**req, "cache_prefix": False}, {**req, "cache_prefix": False}
    ])
    assert [g["continuation"] for g in got] == [
        w["continuation"] for w in want
    ]
    assert pc.stats["blocks"] == 0          # nothing fed
    assert sched.stats["prefix_hit_tokens"] == 0  # nothing read


def test_eviction_under_pressure_serving_stays_correct(tok):
    """With a budget of a handful of blocks, a rotating prompt mix forces
    evictions mid-serving; answers stay byte-identical to cache-off and
    the trie stays within budget throughout."""
    cfg = _lm_cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    waves = [
        [{"prompt": "ab cd ef gh ij kl", "max_new": 3}],
        [{"prompt": "mn kl ij gh ef cd", "max_new": 3}],
        [{"prompt": "ef gh ij kl mn ab", "max_new": 3}],
        [{"prompt": "ab cd ef gh ij kl", "max_new": 3}],
    ]
    flat = [dict(r) for wave in waves for r in wave]
    want = ContinuousScheduler(params, cfg, tok, num_slots=1).run(
        [dict(r) for r in flat]
    )
    pc = PrefixCache(cfg, block_tokens=4, budget_mb=1)
    sched = ContinuousScheduler(params, cfg, tok, num_slots=1, prefix_cache=pc)
    got = []
    for wave in waves:
        got.extend(sched.run([dict(r) for r in wave]))
        if pc.stats["blocks"]:
            pc.budget_bytes = pc.bytes_used  # squeeze: next insert evicts
    assert [g["continuation"] for g in got] == [
        w["continuation"] for w in want
    ]
    assert pc.stats["evicted_blocks"] > 0
    assert pc.bytes_used <= pc.budget_bytes


# --------------------------------------------------------------------------
# rolling-window refusals at the scheduler


def test_rolling_server_rejects_explicit_cache_prefix(tok):
    """On an attention_window server, an EXPLICIT cache_prefix=true answers
    with a structured error alone (no slot leak, co-batched requests
    untouched) — mirroring the speculative-rollback refusal. Absent/false
    serves normally."""
    cfg = _lm_cfg(tok, attention_window=4)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    sched = ContinuousScheduler(params, cfg, tok, num_slots=2)
    got = sched.run([
        {"prompt": "ab cd", "max_new": 3},
        {"prompt": "ab cd", "max_new": 3, "cache_prefix": True},
        {"prompt": "ab cd", "max_new": 3, "cache_prefix": False},
    ])
    assert "continuation" in got[0]
    assert "rolling-window" in got[1]["error"]
    assert got[2]["continuation"] == got[0]["continuation"]
    assert len(sched._free) == 2  # the refused request leaked no slot


def test_scheduler_refuses_prefix_cache_on_rolling_config(tok):
    cfg = _lm_cfg(tok, attention_window=4)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    pc = PrefixCache(_lm_cfg(tok), block_tokens=4)  # built for non-rolling
    with pytest.raises(ValueError, match="rolling-window"):
        ContinuousScheduler(params, cfg, tok, num_slots=1, prefix_cache=pc)


# --------------------------------------------------------------------------
# telemetry + summarize


def test_prefix_telemetry_and_summarize_hit_rate(tok):
    from transformer_tpu.obs import EventLog, Telemetry
    from transformer_tpu.obs.__main__ import summarize_events

    cfg = _lm_cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    buf = io.StringIO()
    tel = Telemetry(events=EventLog(buf), interval=0.0)
    pc = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    sched = ContinuousScheduler(
        params, cfg, tok, num_slots=1, prefix_cache=pc, telemetry=tel
    )
    req = {"prompt": "ab cd ef gh ij kl", "max_new": 3}
    sched.run([dict(req)])
    sched.run([dict(req)])  # hit
    assert tel.registry.counter("serve_prefix_hit_tokens_total").value > 0
    events = [
        json.loads(line) for line in buf.getvalue().splitlines() if line
    ]
    spans = [e for e in events if e.get("kind") == "serve.request"]
    assert spans[0]["prefix_hit_tokens"] == 0      # cold miss recorded as 0
    assert spans[1]["prefix_hit_tokens"] > 0       # replay hit
    report = summarize_events(events)
    prefix = report["serve"]["prefix_cache"]
    assert prefix["hit_tokens"] > 0
    assert 0 < prefix["hit_rate"] <= 1


# --------------------------------------------------------------------------
# zero recompiles + the ISSUE acceptance workload


def test_zero_recompiles_across_hit_miss_partial():
    """The canned retrace scenario: after warmup, hit, miss, and
    partial-hit admissions compile ZERO new programs on the watched hot
    paths (step, suffix prefill, restore, export, pick)."""
    from transformer_tpu.analysis.retrace import prefix_cache_retrace_report

    deltas = prefix_cache_retrace_report(steps=2)
    bad = [d for d in deltas if not d.within_budget]
    assert not bad, [
        f"{d.name} compiled {d.compiles} new program(s)" for d in bad
    ]


def test_acceptance_shared_system_prompt_workload():
    """The ISSUE bar: 16 requests sharing a 64-token system prompt over a
    2-slot pool — >= 50% of all prompt tokens restored from the prefix
    cache, greedy answers byte-identical to cache-off, and zero
    steady-state recompiles across the measured workload."""
    from transformer_tpu.analysis.retrace import RetraceSentinel
    from transformer_tpu.serve import scheduler as sched_mod

    tok = IdTok()
    cfg = ModelConfig(
        num_layers=2, d_model=16, num_heads=4, dff=32,
        input_vocab_size=48, target_vocab_size=48, max_position=96,
        decoder_only=True, tie_output=True, dtype="float32",
        dropout_rate=0.0,
    )
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    system = rng.integers(3, 46, 64)
    reqs = [
        {
            "prompt": " ".join(map(str, [*system, *rng.integers(3, 46, 4)])),
            "max_new": 4,
        }
        for _ in range(16)
    ]

    want = ContinuousScheduler(params, cfg, tok, num_slots=2).run(
        [dict(r) for r in reqs]
    )
    pc = PrefixCache(cfg, block_tokens=16, budget_mb=16)

    def serve(batch):
        s = ContinuousScheduler(
            params, cfg, tok, num_slots=2, prefix_cache=pc
        )
        out = s.run([dict(r) for r in batch])
        return out, s

    # Warmup compiles BOTH admission shapes: the first one-request run is
    # a cold miss (full-prefill bucket) and populates the trie; the second
    # re-serves it as a hit (restore + suffix-prefill bucket).
    serve(reqs[:1])
    serve(reqs[:1])
    sentinel = RetraceSentinel()
    for fname in (
        "_pool_step", "_slot_prefill", "_slot_restore",
        "_slot_read_blocks", "_pick_pool",
    ):
        sentinel.watch(fname, getattr(sched_mod, fname), budget=0)
    sentinel.snapshot()
    got, sched = serve(reqs)
    sentinel.assert_within_budget()
    assert [g["continuation"] for g in got] == [
        w["continuation"] for w in want
    ]
    hit_rate = sched.stats["prefix_hit_tokens"] / sched.stats["prompt_tokens"]
    assert hit_rate >= 0.5, f"hit rate {hit_rate:.2%} below the 50% bar"


def test_fast_contract_matrix_covers_prefix_restore():
    """prefix_restore_parity runs in the FAST (tier-1) matrix over the
    plain/int8/GQA LM variants — and excludes the rolling-window config
    the prefix cache refuses."""
    from transformer_tpu.analysis import run_contracts

    results = run_contracts("fast")
    configs = {
        r.config for r in results if r.contract == "prefix_restore_parity"
    }
    assert {"lm_bf16", "lm_int8_cache", "lm_gqa"} <= configs
    assert "lm_window" not in configs
    assert all(
        r.ok for r in results if r.contract == "prefix_restore_parity"
    )


def test_prefix_cache_real_thread_hammer():
    """The PrefixCache threading contract under REAL threads: two workers
    hammer match/insert/release against a 3-block budget (constant LRU
    eviction). After the dust settles every refcount is zero and the byte
    accounting re-derives exactly from the reachable trie — the same
    invariants the deterministic explorer checks interleaving-by-
    interleaving in analysis/schedules.py prefix_cache_contention."""
    import threading

    pc = PrefixCache(LM, block_tokens=2, budget_mb=1)
    blk = np.zeros((1, 2, 2, 2), np.float32)
    pc.budget_bytes = 3 * 2 * blk.nbytes  # 3 blocks: force eviction churn

    def read_block(start):
        return [{"k": blk.copy(), "v": blk.copy()}]

    errors = []
    start = threading.Barrier(2)

    def hammer(prompts):
        try:
            start.wait()
            for _ in range(20):
                for ids in prompts:
                    hit = pc.match(ids[:-1])
                    hit.stacked(16)
                    pc.insert(ids, (len(ids) // 2) * 2, read_block)
                    with pc._lock:
                        for n in hit._nodes:
                            assert n.parent is not None and (
                                n.parent.children.get(n.edge) is n
                            ), "pinned block evicted while referenced"
                    hit.release()
        except Exception as e:  # noqa: BLE001 — collected and re-raised below
            errors.append(e)

    a = threading.Thread(
        target=hammer, args=([[1, 2, 3, 4, 5], [1, 2, 7, 8, 9]],)
    )
    b = threading.Thread(
        target=hammer, args=([[1, 2, 3, 4, 11], [13, 14, 15, 16, 17]],)
    )
    a.start(); b.start(); a.join(); b.join()
    assert not errors, errors
    # refcounts all returned to zero; byte/block accounting exact
    total, blocks = 0, 0
    stack = [pc._root]
    while stack:
        node = stack.pop()
        stack.extend(node.children.values())
        assert node.refs == 0, f"leaked refcount {node.refs}"
        if node.blocks is not None:
            total += node.nbytes
            blocks += 1
    assert total == pc.bytes_used <= pc.budget_bytes
    assert blocks == pc.block_count()
