"""Paged KV memory (``--kv_layout paged``): the block-pool allocator's
refcount/CoW/free-list invariants, byte parity of paged vs dense serving
across cache variants (composed with chunked prefill, speculative decoding,
and prefix reuse incl. the aliased hit path), the zero-copy device-resident
hit contract, pool-exhaustion degradation, and the spill-to-host ladder."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig
from transformer_tpu.data.tokenizer import SubwordTokenizer
from transformer_tpu.kernels.kv_pool import KVPool, KVPoolExhausted
from transformer_tpu.models import transformer_init
from transformer_tpu.serve import ContinuousScheduler, PrefixCache
from transformer_tpu.serve.prefix_cache import PrefixCorruptionError  # noqa: F401


def _cfg(tok, **kw) -> ModelConfig:
    base = dict(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=64, decoder_only=True, tie_output=True,
        dtype="float32", dropout_rate=0.0,
    )
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def tok():
    return SubwordTokenizer.build_from_corpus(
        ["ab cd ef gh ij kl mn"] * 3, target_vocab_size=300
    )


# The acceptance matrix: bf16, int8, GQA (the fourth variant — rolling
# window — REFUSES the paged layout; pinned below).
VARIANTS = {
    "bf16": dict(dtype="bfloat16"),
    "int8": dict(kv_cache_int8=True),
    "gqa": dict(num_kv_heads=1),
}

# Greedy AND seeded-sampled, same prefill bucket per wave (compile-lean),
# with wave 2 replaying wave 1's prompt as a full prefix hit plus a
# divergent-tail partial hit.
WAVES = [
    [
        {"prompt": "ab cd ef gh ij", "max_new": 6},
        {"prompt": "ab cd ef gh kl", "max_new": 5, "temperature": 0.9,
         "seed": 3},
    ],
    [
        {"prompt": "ab cd ef gh ij", "max_new": 6},          # full hit
        {"prompt": "ab cd ef gh mn", "max_new": 4, "temperature": 0.7,
         "top_k": 4, "seed": 1},                             # partial hit
    ],
]


def _serve(params, cfg, tok, waves, **kw):
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=48, default_max_new=4, **kw
    )
    out = []
    for wave in waves:
        out.append([r for r in s.run([dict(q) for q in wave])])
    return s, out


# --------------------------------------------------------------------------
# allocator units


def test_pool_allocator_invariants():
    pool = KVPool(8, 4, num_slots=2, slot_blocks=3)
    assert pool.free_blocks == 7 and pool.used_blocks == 0
    pool.ensure(0, 9)  # 3 blocks
    assert pool.slot_tokens(0) == 12 and pool.used_blocks == 3
    pool.check_consistency()
    # device-tier adoption + rollback-as-truncation
    bid = int(pool.table[0, 0])
    pool.retain(bid)
    assert pool.truncate(0, 5) == 1          # 3 -> 2 blocks
    pool.check_consistency()
    pool.free_slot(0)
    assert pool.used_blocks == 1             # the retained block survives
    # alias the retained block back (a prefix hit) and CoW-split it
    j, got = pool.extend(0, bid=bid)
    assert (j, got) == (0, bid) and pool.refs(bid) == 2
    pairs = pool.make_writable(0, 0, 4)
    assert len(pairs) == 1 and pairs[0][0] == bid
    assert pool.refs(bid) == 1 and pool.stats["cow_splits"] == 1
    pool.check_consistency()
    # unshared blocks never split
    assert pool.make_writable(0, 0, 4) == []
    pool.free_slot(0)
    assert pool.release(bid) and pool.used_blocks == 0
    pool.check_consistency()
    # exhaustion raises (and never corrupts the accounting): fill both
    # slots (6 of 7 allocatable blocks), burn the last free block on one
    # CoW split, then a second split has nowhere to go
    pool.ensure(0, 12)
    pool.ensure(1, 12)
    b0 = int(pool.table[1, 0])
    pool.retain(b0)
    assert len(pool.make_writable(1, 0, 4)) == 1  # consumes the last free
    b1 = int(pool.table[1, 1])
    pool.retain(b1)
    with pytest.raises(KVPoolExhausted):
        pool.make_writable(1, 4, 8)
    pool.check_consistency()
    pool.release(b0)
    pool.release(b1)
    pool.free_slot(0)
    pool.free_slot(1)
    assert pool.used_blocks == 0
    pool.check_consistency()


def test_pool_table_device_upload_cached():
    pool = KVPool(4, 2, num_slots=1, slot_blocks=2)
    t1 = pool.table_device()
    assert pool.table_device() is t1         # clean: no re-upload
    pool.ensure(0, 2)
    t2 = pool.table_device()
    assert t2 is not t1 and int(t2[0, 0]) == int(pool.table[0, 0])


def test_kv_pool_hammer():
    """Real-thread contention: 4 workers drive the full serving lifecycle
    (alloc, retain, truncate, free, alias, CoW) against one pool; the
    accounting must re-derive exactly and every block must come home."""
    pool = KVPool(64, 2, num_slots=4, slot_blocks=4)
    errors = []

    def worker(slot):
        try:
            for i in range(100):
                pool.ensure(slot, 8)
                bid = int(pool.table[slot, 0])
                pool.retain(bid)
                pool.truncate(slot, 3)
                pool.free_slot(slot)
                pool.extend(slot, bid=bid)
                pool.make_writable(slot, 0, 2)
                pool.free_slot(slot)
                pool.release(bid)
        except Exception as e:  # noqa: BLE001 — surfaced via the errors list
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(s,)) for s in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    pool.check_consistency()
    assert pool.used_blocks == 0
    assert pool.stats["cow_splits"] == 400


# --------------------------------------------------------------------------
# byte parity paged vs dense


def _full_stack_parity(tok, variant: str, speculate_k: int) -> None:
    from transformer_tpu.serve import scheduler as sched

    cfg = _cfg(tok, **VARIANTS[variant])
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    common = dict(prefill_chunk=3, speculate_k=speculate_k)
    waves = [list(WAVES[0]), list(WAVES[1])]
    if not speculate_k:
        # The plain path additionally pins a miss-shaped short prompt.
        waves[0] = waves[0] + [{"prompt": "kl", "max_new": 3}]
    _, want = _serve(
        params, cfg, tok, waves,
        prefix_cache=PrefixCache(cfg, block_tokens=4, budget_mb=8), **common,
    )
    cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=48, default_max_new=4,
        prefix_cache=cache, kv_layout="paged", **common,
    )
    step_fn = sched._pool_verify_paged if speculate_k else sched._pool_step_paged
    got = [s.run([dict(q) for q in waves[0]])]
    # Per-STEP programs must never retrace past wave 1 (new prefill
    # length buckets in wave 2 are a bounded compile set, exactly like
    # dense — the full compile-set statement is paged_retrace_report's).
    before = step_fn._cache_size()
    got.append(s.run([dict(q) for q in waves[1]]))
    after = step_fn._cache_size()
    assert got == want, f"paged answers diverged from dense ({variant})"
    assert any(r.get("continuation") for wave in got for r in wave), (
        "vacuous parity: every continuation empty"
    )
    assert after == before, "steady-state recompile on the paged step"
    # wave 2 replays wave 1's prompts: the hits must be device aliases
    assert s.stats["prefix_hit_tokens"] > 0
    assert s.stats["prefix_alias_tokens"] == s.stats["prefix_hit_tokens"]
    assert s.stats["host_restored_tokens"] == 0
    s.pool.alloc.check_consistency()
    assert len(s._free) == 2 and not s._active


# Tier-1/full split (wall-clock budget, same policy as the contract
# matrix): tier-1 runs the bf16 variant composing EVERYTHING (chunked
# prefill + speculative decoding + prefix reuse incl. aliasing) plus a
# non-speculative bf16 pass for the plain step; the int8/GQA byte-parity
# cross product rides the full suite below, with their storage layouts
# still tier-1-pinned by the `paged_alias_parity` contract (analysis
# gate) and the shared `_store_kv`/`kv_buffer_keys` write path.
def test_paged_parity_full_stack(tok):
    """Greedy AND seeded-sampled answers byte-identical paged vs dense,
    composed with chunked prefill, speculative decoding, and prefix
    reuse (incl. the aliased device-resident hit path — wave 2 replays
    wave 1's prompts), at zero steady-state recompiles of the per-step
    program."""
    _full_stack_parity(tok, "bf16", speculate_k=1)


def test_paged_parity_plain_step(tok):
    """The non-speculative pool step (``_pool_step_paged``) byte-matches
    dense, including a miss-shaped short prompt."""
    _full_stack_parity(tok, "bf16", speculate_k=0)


@pytest.mark.slow
@pytest.mark.parametrize("variant", ["int8", "gqa"])
@pytest.mark.parametrize("speculate_k", [0, 1])
def test_paged_parity_variant_matrix(tok, variant, speculate_k):
    """The remaining byte-parity cross product: int8/GQA paged vs dense,
    plain AND speculative (full suite; bf16 rides tier-1)."""
    _full_stack_parity(tok, variant, speculate_k=speculate_k)


# --------------------------------------------------------------------------
# the zero-copy aliased hit contract


def test_aliased_hit_zero_host_copies(tok):
    """A device-resident prefix hit is pure table aliasing: no pool-block
    reads, no host-block writes, no model forwards for the matched
    prefix (prefill_forwards counts only the suffix)."""
    from transformer_tpu.serve import scheduler as sched

    cfg = _cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=48, default_max_new=4,
        prefix_cache=cache, kv_layout="paged",
    )
    warm = s.run([{"prompt": "ab cd ef gh ij", "max_new": 4}])
    reads = []
    real_reader = cache._device_reader
    cache._device_reader = lambda bid: (reads.append(bid), real_reader(bid))[1]
    writes = []
    real_write = sched._pool_write_blocks

    def counting_write(*a, **kw):
        writes.append(1)
        return real_write(*a, **kw)

    sched._pool_write_blocks = counting_write
    try:
        replay = s.run([{"prompt": "ab cd ef gh ij", "max_new": 4}])
    finally:
        sched._pool_write_blocks = real_write
        cache._device_reader = real_reader
    assert replay == warm
    assert s.stats["prefix_alias_tokens"] > 0
    assert not reads, "aliased hit paid a device->host block read"
    assert not writes, "aliased hit paid a host->device block write"


def test_spill_then_host_restore_then_realias(tok):
    """Pool pressure spills device blocks to the host trie (wire format);
    the next hit restores through ONE batched host write, is re-adopted,
    and the hit after that aliases again — identical answers across the
    miss / host-restored / aliased admissions."""
    cfg = _cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=48, default_max_new=4,
        prefix_cache=cache, kv_layout="paged",
    )
    got = [s.run([{"prompt": "ab cd ef gh ij", "max_new": 4}])]
    assert cache.stats["device_blocks"] > 0
    freed = cache.release_device_blocks(1 << 30)  # forced spill
    assert freed > 0 and cache.stats["device_blocks"] == 0
    assert cache.stats["spilled_blocks"] == freed
    got.append(s.run([{"prompt": "ab cd ef gh ij", "max_new": 4}]))
    assert s.stats["host_restored_tokens"] > 0, "spilled hit not host-restored"
    assert cache.stats["device_blocks"] > 0, "host restore not re-adopted"
    alias_before = s.stats["prefix_alias_tokens"]
    got.append(s.run([{"prompt": "ab cd ef gh ij", "max_new": 4}]))
    assert s.stats["prefix_alias_tokens"] > alias_before, (
        "re-adopted block not aliased"
    )
    # miss, host-restored hit, and aliased hit must answer identically
    # (dense-vs-paged parity for this path rides the full-stack matrix)
    assert got[0] == got[1] == got[2]
    s.pool.alloc.check_consistency()


# --------------------------------------------------------------------------
# degradation ladder + refusals


def test_pool_exhaustion_preempts_with_partial(tok):
    """A pool too small for the fleet's used tokens preempts the
    requesting slot with a structured 'resource' answer carrying the
    partial continuation; other requests answer normally and the pool
    accounting survives."""
    cfg = _cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    # 5 allocatable blocks of 4 tokens = 20 tokens for 2 slots: two
    # long-budget requests cannot both finish.
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=48, default_max_new=24,
        kv_layout="paged", kv_block=4, kv_pool_blocks=6,
        admission_retries=0,
    )
    out = s.run([
        {"prompt": "ab cd ef gh ij kl", "max_new": 24},
        {"prompt": "mn ef cd ab kl ij", "max_new": 24},
    ])
    codes = [r.get("code") for r in out]
    assert "resource" in codes, out
    assert any("continuation" in r for r in out) or all(
        r.get("code") == "resource" for r in out
    )
    for r in out:
        if r.get("code") == "resource":
            assert "partial" in r or r.get("error"), r
    assert s.stats["kv_preempted"] >= 1
    s.pool.alloc.check_consistency()
    assert s.pool.alloc.used_blocks == 0 and len(s._free) == 2


def test_admission_exhaustion_answers_transient(tok):
    """A prompt whose prefill alone overflows the pool answers a
    structured 'transient' error (the bounded-retry path) without
    touching co-batched requests or leaking blocks."""
    cfg = _cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=48, default_max_new=2,
        kv_layout="paged", kv_block=4, kv_pool_blocks=4,
        admission_retries=1, retry_backoff_ms=1.0,
    )
    out = s.run([
        {"prompt": "ab cd ef gh ij kl mn " * 4, "max_new": 2},
        {"prompt": "kl", "max_new": 2},
    ])
    assert out[0].get("code") == "transient", out[0]
    assert "continuation" in out[1], out[1]
    s.pool.alloc.check_consistency()
    assert s.pool.alloc.used_blocks == 0


def test_paged_refuses_rolling_window(tok):
    """The windowed-refusal variant: rolling caches evict
    absolute-position rows, so the paged pool refuses them outright."""
    cfg = _cfg(tok, attention_window=8)
    params = jax.eval_shape(
        lambda k: transformer_init(k, cfg), jnp.zeros((2,), jnp.uint32)
    )
    with pytest.raises(ValueError, match="rolling-window"):
        ContinuousScheduler(
            params, cfg, tok, num_slots=2, max_total=48, kv_layout="paged"
        )


# --------------------------------------------------------------------------
# kernels: block-table attention


def test_paged_attention_matches_dense():
    """kernels.flash_attention.paged_attention: the xla impl is bitwise
    identical to the dense cache-path math on the same values; the flash
    impl agrees within kernel tolerance."""
    from transformer_tpu.kernels.flash_attention import paged_attention
    from transformer_tpu.ops.attention import dot_product_attention

    rng = np.random.default_rng(0)
    N, B, H, D, nb = 3, 4, 2, 8, 7
    k_pool = jnp.asarray(rng.standard_normal((nb, B, H, D)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((nb, B, H, D)), jnp.float32)
    table = jnp.asarray([[1, 2, 0], [3, 4, 5], [6, 0, 0]], jnp.int32)
    lengths = jnp.asarray([7, 12, 3], jnp.int32)
    q = jnp.asarray(rng.standard_normal((N, 1, H, D)), jnp.float32)

    dense_k = k_pool[table].reshape(N, 3 * B, H, D)
    dense_v = v_pool[table].reshape(N, 3 * B, H, D)
    mask = (
        jnp.arange(3 * B)[None, None, None, :]
        <= (lengths - 1)[:, None, None, None]
    )
    want, _ = dot_product_attention(q, dense_k, dense_v, mask)
    got = paged_attention(q, k_pool, v_pool, table, lengths)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # width clamp: on a table wider than any slot needs (sink-padded
    # columns), gathering only ceil(max lengths / B) blocks is BITWISE
    # the gather a tightly-sized table would do — so short slots stop
    # paying the nmax-wide gather for free. Against the unclamped wide
    # gather the answers agree to fp32 reassociation (the extra positions
    # carry softmax weight exactly 0.0, but a longer reduction axis lets
    # XLA regroup the partial sums).
    wide = jnp.concatenate([table, jnp.zeros((N, 2), jnp.int32)], axis=1)
    width = -(-int(lengths.max()) // B) * B
    unclamped = paged_attention(q, k_pool, v_pool, wide, lengths)
    clamped = paged_attention(q, k_pool, v_pool, wide, lengths, width=width)
    np.testing.assert_array_equal(np.asarray(clamped), np.asarray(want))
    np.testing.assert_allclose(
        np.asarray(clamped), np.asarray(unclamped), rtol=1e-6, atol=1e-6
    )
    flash = paged_attention(
        q, k_pool, v_pool, table, lengths, impl="flash",
        block_q=8, block_k=8,
    )
    np.testing.assert_allclose(
        np.asarray(flash), np.asarray(want), rtol=2e-5, atol=2e-5
    )


# --------------------------------------------------------------------------
# observability


def test_pool_gauges_and_summarize(tok, tmp_path):
    """serve_kv_pool_used/free_blocks gauges + the alias counter land in
    the metrics snapshots, and ``obs summarize`` renders the
    pool-utilization section with the alias/host split."""
    from transformer_tpu.obs import EventLog, Telemetry
    from transformer_tpu.obs.__main__ import render_text, summarize_events
    from transformer_tpu.obs.events import read_events

    cfg = _cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    path = tmp_path / "serve.jsonl"
    tel = Telemetry(events=EventLog(str(path)), interval=0.0)
    cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=48, default_max_new=4,
        prefix_cache=cache, kv_layout="paged", telemetry=tel,
    )
    s.run([{"prompt": "ab cd ef gh ij", "max_new": 4}])
    s.run([{"prompt": "ab cd ef gh ij", "max_new": 4}])  # aliased hit
    tel.close()
    report = summarize_events(read_events(str(path)))
    kv = report["serve"]["kv_pool"]
    assert kv["used_blocks"] is not None and kv["samples"] > 0
    assert kv["alias_tokens"] > 0 and kv["host_restored_tokens"] == 0
    assert kv["alias_rate"] == 1.0
    text = render_text(report)
    assert "kv pool:" in text and "device-aliased" in text
