"""TPA300 kernel-verifier tests: hand-computed VMEM, per-rule twins, the
seeded corpora, CLI exit codes + baseline workflow, the costs cross-check,
and the package-wide zero-findings pin. Slow canaries prove the verifier
actually DETECTS the three bug classes it exists for."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from transformer_tpu.analysis.costs import pallas_call_flops
from transformer_tpu.analysis.kernels import (
    DEFAULT_GENERATION,
    VMEM_BUDGETS,
    analyze_entries,
    compare_kernels_to_baseline,
    default_kernels_baseline_path,
    program_kernel_vmem,
    run_kernels,
    write_kernels_baseline,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
BAD = os.path.join(FIXTURES, "tpa_kernel_bad_corpus.py")
GOOD = os.path.join(FIXTURES, "tpa_kernel_good_corpus.py")

_ARB = pltpu.TPUCompilerParams(dimension_semantics=("arbitrary",))


def _copy_entry(block_q=8, out_map=None):
    """grid (2,): x (16,128) f32 in blocks of (block_q,128); out either
    grid-varying (default) or pinned to block 0."""

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    def factory():
        def fn(x):
            return pl.pallas_call(
                kern,
                grid=(2,),
                in_specs=[pl.BlockSpec((block_q, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec(
                    (block_q, 128), out_map or (lambda i: (i, 0))
                ),
                out_shape=jax.ShapeDtypeStruct((2 * block_q, 128), jnp.float32),
                compiler_params=_ARB,
                interpret=True,
            )(x)

        return fn, (jax.ShapeDtypeStruct((2 * block_q, 128), jnp.float32),)

    return factory


class TestVmemModel:
    def test_hand_computed_double_buffered(self):
        """Both specs vary over the grid -> 2x block bytes each, no scratch:
        2 * (8*128*4) + 2 * (8*128*4) = 16384."""
        res = analyze_entries({"copy": _copy_entry()}, ast_targets=[])
        assert not res.violations and not res.findings
        (r,) = res.reports
        assert r.predicted_vmem_bytes == 16384
        assert r.vmem_breakdown == {"in[0]": 8192, "out[0]": 8192}
        assert r.grid == (2,) and r.checked_points == 2 and not r.sampled

    def test_hand_computed_with_scratch_and_invariant_out(self):
        """In spec varies (2x), out pinned to one block (1x), fp32 scratch
        counted once: 2*4096 + 4096 + 4096 = 16384."""

        def kern(x_ref, o_ref, acc_ref):
            @pl.when(pl.program_id(0) == 0)
            def _init():
                acc_ref[...] = jnp.zeros_like(acc_ref)

            acc_ref[...] += x_ref[...]

            @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
            def _fin():
                o_ref[...] = acc_ref[...]

        def factory():
            def fn(x):
                return pl.pallas_call(
                    kern,
                    grid=(2,),
                    in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
                    out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                    scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
                    compiler_params=_ARB,
                    interpret=True,
                )(x)

            return fn, (jax.ShapeDtypeStruct((16, 128), jnp.float32),)

        res = analyze_entries({"acc": factory}, ast_targets=[])
        assert not res.violations and not res.findings, (
            res.violations,
            res.findings,
        )
        (r,) = res.reports
        assert r.vmem_breakdown == {
            "in[0]": 8192,
            "out[0]": 4096,
            "scratch[0]": 4096,
        }
        assert r.predicted_vmem_bytes == 16384

    def test_budget_table_generations(self):
        assert VMEM_BUDGETS[DEFAULT_GENERATION] == 16 * 1024 * 1024
        assert VMEM_BUDGETS["v6e"] == 32 * 1024 * 1024

    def test_program_kernel_vmem_hook(self):
        fn, args = _copy_entry()()
        vmem = program_kernel_vmem(fn, *args)
        assert vmem == {"kern": 16384}


class TestRuleTwins:
    """Inline bad/good pairs: each rule fires on the bad twin and stays
    silent on the good one (the full per-rule matrix rides the corpora)."""

    def _codes(self, factory):
        res = analyze_entries({"t": factory}, ast_targets=[])
        assert not res.violations, res.violations
        return sorted({f.code for f in res.findings})

    def test_tpa301_bf16_accumulator(self):
        def kern_bad(x_ref, o_ref, acc_ref):
            @pl.when(pl.program_id(0) == 0)
            def _i():
                acc_ref[...] = jnp.zeros_like(acc_ref)

            acc_ref[...] += x_ref[...].astype(jnp.bfloat16)

            @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
            def _f():
                o_ref[...] = acc_ref[...].astype(jnp.float32)

        def make(dtype, kern):
            def factory():
                def fn(x):
                    return pl.pallas_call(
                        kern,
                        grid=(2,),
                        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
                        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                        scratch_shapes=[pltpu.VMEM((8, 128), dtype)],
                        compiler_params=_ARB,
                        interpret=True,
                    )(x)

                return fn, (jax.ShapeDtypeStruct((16, 128), jnp.float32),)

            return factory

        def kern_good(x_ref, o_ref, acc_ref):
            @pl.when(pl.program_id(0) == 0)
            def _i():
                acc_ref[...] = jnp.zeros_like(acc_ref)

            acc_ref[...] += x_ref[...]

            @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
            def _f():
                o_ref[...] = acc_ref[...]

        assert self._codes(make(jnp.bfloat16, kern_bad)) == ["TPA301"]
        assert self._codes(make(jnp.float32, kern_good)) == []

    def test_tpa303_masked_exp(self):
        def kern_bad(x_ref, o_ref):
            s = jnp.where(x_ref[...] > 0, x_ref[...], -1e30)
            o_ref[...] = jnp.exp(s)

        def kern_good(x_ref, o_ref):
            s = jnp.where(x_ref[...] > 0, x_ref[...], -1e30)
            o_ref[...] = jnp.where(s > -1e29, jnp.exp(s), 0.0)

        def make(kern):
            def factory():
                def fn(x):
                    return pl.pallas_call(
                        kern,
                        grid=(1,),
                        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
                        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
                        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                        interpret=True,
                    )(x)

                return fn, (jax.ShapeDtypeStruct((8, 128), jnp.float32),)

            return factory

        assert self._codes(make(kern_bad)) == ["TPA303"]
        assert self._codes(make(kern_good)) == []

    def test_out_race_detected(self):
        """Out block pinned to (0,0) while the grid has 2 steps, writes
        unguarded, and the revisited axis is declared 'parallel' — both
        the semantics and the write-discipline violations fire."""

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def factory():
            def fn(x):
                return pl.pallas_call(
                    kern,
                    grid=(2,),
                    in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
                    out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                    compiler_params=pltpu.TPUCompilerParams(
                        dimension_semantics=("parallel",)
                    ),
                    interpret=True,
                )(x)

            return fn, (jax.ShapeDtypeStruct((16, 128), jnp.float32),)

        res = analyze_entries({"race": factory}, ast_targets=[])
        assert any("write race" in v for v in res.violations), res.violations
        assert any("unconditionally" in v for v in res.violations), res.violations


class TestCorpora:
    def test_bad_corpus_fires_every_rule(self):
        res = run_kernels(paths=[BAD], compare=False)
        codes = {f.code for f in res.findings}
        assert codes == {"TPA300", "TPA301", "TPA302", "TPA303", "TPA304",
                         "TPA305"}, codes
        assert not res.violations, res.violations

    def test_good_corpus_clean(self):
        res = run_kernels(paths=[GOOD], compare=False)
        assert not res.findings and not res.violations, (
            res.findings,
            res.violations,
        )
        assert res.ok and len(res.reports) == 5

    def test_baseline_roundtrip_in_process(self, tmp_path):
        base = str(tmp_path / "kb.json")
        res = run_kernels(paths=[BAD], compare=False)
        write_kernels_baseline(res, base)
        res2 = run_kernels(paths=[BAD], baseline_path=base)
        assert res2.ok, (res2.findings, res2.violations, res2.regressions)
        assert res2.baselined == len(res.findings) > 0

    def test_vmem_growth_is_a_regression(self, tmp_path):
        base = str(tmp_path / "kb.json")
        small = analyze_entries({"copy": _copy_entry(block_q=8)}, ast_targets=[])
        write_kernels_baseline(small, base)
        big = analyze_entries({"copy": _copy_entry(block_q=16)}, ast_targets=[])
        big = compare_kernels_to_baseline(big, base)
        assert any("predicted_vmem_bytes grew" in g for g in big.regressions), (
            big.regressions
        )
        # Shrinkage is a note, not a failure.
        small2 = analyze_entries(
            {"copy": _copy_entry(block_q=8)}, ast_targets=[]
        )
        write_kernels_baseline(
            analyze_entries({"copy": _copy_entry(block_q=16)}, ast_targets=[]),
            base,
        )
        small2 = compare_kernels_to_baseline(small2, base)
        assert small2.ok and any("improved" in n for n in small2.notes)

    def test_coverage_loss_is_a_regression(self, tmp_path):
        base = str(tmp_path / "kb.json")
        both = analyze_entries(
            {"a": _copy_entry(8), "b": _copy_entry(16)}, ast_targets=[]
        )
        write_kernels_baseline(both, base)
        one = analyze_entries({"a": _copy_entry(8)}, ast_targets=[])
        one = compare_kernels_to_baseline(one, base)
        assert any("coverage lost" in g for g in one.regressions), one.regressions


class TestCli:
    def _run(self, *argv):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "transformer_tpu.analysis", "kernels", *argv],
            capture_output=True,
            text=True,
            timeout=560,
            env=env,
        )

    def test_exit_codes_and_baseline_workflow(self, tmp_path):
        base = str(tmp_path / "kb.json")
        # bad corpus, no baseline -> findings -> exit 1
        p = self._run("--paths", BAD, "--baseline", base)
        assert p.returncode == 1, p.stdout + p.stderr
        # bank it -> exit 0
        p = self._run("--paths", BAD, "--baseline", base, "--update-baseline")
        assert p.returncode == 0, p.stdout + p.stderr
        # rerun against the bank -> clean exit 0, json parses
        p = self._run("--paths", BAD, "--baseline", base, "--format", "json")
        assert p.returncode == 0, p.stdout + p.stderr
        doc = json.loads(p.stdout)
        assert doc["ok"] is True and doc["baselined"] > 0
        # good corpus needs no baseline at all
        p = self._run("--paths", GOOD)
        assert p.returncode == 0, p.stdout + p.stderr


class TestCostsCrossCheck:
    """Satellite: the verifier's per-kernel FLOPs and costs' _walk_eqns_hbm
    pricing share ONE extraction helper — divergence is a hard failure."""

    def _dot_program(self):
        def kern(x_ref, w_ref, o_ref):
            o_ref[...] = jnp.dot(
                x_ref[...], w_ref[...], preferred_element_type=jnp.float32
            )

        def fn(x, w):
            return pl.pallas_call(
                kern,
                grid=(2,),
                in_specs=[
                    pl.BlockSpec((8, 8), lambda i: (i, 0)),
                    pl.BlockSpec((8, 8), lambda i: (0, 0)),
                ],
                out_specs=pl.BlockSpec((8, 8), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((16, 8), jnp.float32),
                interpret=True,
            )(x, w)

        return fn, (
            jax.ShapeDtypeStruct((16, 8), jnp.float32),
            jax.ShapeDtypeStruct((8, 8), jnp.float32),
        )

    def test_hand_computed_dot_flops(self):
        """(8,8)@(8,8) dot = 2*8*8*8 = 1024 flops/step x 2 grid steps."""
        fn, args = self._dot_program()
        closed = jax.make_jaxpr(fn)(*args)
        from transformer_tpu.analysis.kernels import _iter_pallas_eqns

        (eqn,) = list(_iter_pallas_eqns(closed.jaxpr))
        assert pallas_call_flops(eqn) == 2048

    def test_walk_and_helper_agree(self):
        """Total flops from costs' walk == outside-kernel flops + the shared
        helper summed over every pallas_call eqn (no double counting, no
        drift)."""
        from transformer_tpu.analysis.costs import _eqn_flops, _walk_eqns_hbm

        fn, args = self._dot_program()
        closed = jax.make_jaxpr(lambda x, w: fn(x, w) + x)(*args)
        total = 0
        outside = 0
        kernel_sum = 0
        for eqn, w, in_kernel in _walk_eqns_hbm(closed.jaxpr):
            total += w * _eqn_flops(eqn)
            if not in_kernel:
                outside += w * _eqn_flops(eqn)
                if eqn.primitive.name == "pallas_call":
                    kernel_sum += pallas_call_flops(eqn, 1)
        assert kernel_sum == 2048
        assert total == outside + kernel_sum

    def test_package_reports_priced_by_shared_helper(self):
        """Every banked flops_per_call in the shipped baseline must be
        reproduced by the live verifier (compare_kernels_to_baseline notes
        any drift; a clean package run means zero drift notes)."""
        res = run_kernels()
        assert res.ok, (res.findings, res.violations, res.regressions)
        assert not any("drifted" in n for n in res.notes), res.notes
        assert all(
            r.flops_per_call > 0
            for r in res.reports
            if r.kernel in ("_fwd_kernel", "_paged_kernel", "_fused_kernel")
        )


class TestPackagePin:
    def test_package_zero_unbaselined(self):
        """THE pin: the shipped package verifies clean against its checked-in
        baseline — every shipped kernel enumerated, in-bounds over its full
        grid, VMEM banked and under budget."""
        res = run_kernels()
        assert res.ok, (res.findings, res.violations, res.regressions)
        kernels = {r.kernel for r in res.reports}
        assert {
            "_fwd_kernel",
            "_dq_kernel",
            "_dkdv_kernel",
            "_ring_step_kernel",
            "_paged_kernel",
            "_fused_kernel",
        } <= kernels, kernels
        assert all(not r.sampled for r in res.reports)
        assert all(r.fits_budget for r in res.reports)
        assert os.path.exists(default_kernels_baseline_path())

    def test_gqa_variants_enumerated(self):
        res = run_kernels()
        entries = {r.entry for r in res.reports}
        assert "flash.grad[gqa,fp32]" in entries
        assert "paged_flash[gqa,verify]" in entries
        assert any(e.startswith("serve.pool_step_paged_flash") for e in entries)


@pytest.mark.slow
class TestCanaries:
    """Detection proof: each canary is the bug class the verifier exists
    for, planted deliberately and required to be flagged."""

    def test_out_of_bounds_index_map(self):
        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def factory():
            def fn(x):
                return pl.pallas_call(
                    kern,
                    grid=(2,),
                    in_specs=[pl.BlockSpec((8, 128), lambda i: (i + 1, 0))],
                    out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                    out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
                    interpret=True,
                )(x)

            return fn, (jax.ShapeDtypeStruct((16, 128), jnp.float32),)

        res = analyze_entries({"oob": factory}, ast_targets=[])
        assert any("out of bounds" in v for v in res.violations), res.violations

    def test_vmem_blowup(self):
        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def make(rows):
            def factory():
                def fn(x):
                    return pl.pallas_call(
                        kern,
                        grid=(2,),
                        in_specs=[pl.BlockSpec((rows, 1024), lambda i: (i, 0))],
                        out_specs=pl.BlockSpec((rows, 1024), lambda i: (i, 0)),
                        out_shape=jax.ShapeDtypeStruct(
                            (2 * rows, 1024), jnp.float32
                        ),
                        interpret=True,
                    )(x)

                return fn, (
                    jax.ShapeDtypeStruct((2 * rows, 1024), jnp.float32),
                )

            return factory

        # 4096-row f32 blocks, double-buffered in+out = 64 MiB: over any budget.
        res = analyze_entries({"vmem": make(4096)}, ast_targets=[])
        assert any("exceeds v5e budget" in v for v in res.violations), (
            res.violations
        )
        # 20 MiB case: over v5e's 16 MiB, absorbed by v6e's 32 MiB — the
        # budget table is live, not a single constant.
        mid = make(1280)
        res5 = analyze_entries({"vmem": mid}, ast_targets=[])
        assert any("exceeds v5e budget" in v for v in res5.violations)
        res6 = analyze_entries({"vmem": mid}, generation="v6e", ast_targets=[])
        assert not res6.violations, res6.violations

    def test_bf16_accumulator(self):
        res = run_kernels(paths=[BAD], compare=False)
        tpa301 = [f for f in res.findings if f.code == "TPA301"]
        assert tpa301 and tpa301[0].symbol == "_acc_bf16_kernel"
