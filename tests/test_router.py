"""Multi-replica serving tier (``transformer_tpu/serve/router.py`` +
``replica.py``): prefix-affinity/least-loaded dispatch, the order-keyed
at-most-once answer funnel, zero-loss SIGKILL failover with byte parity
against a single-scheduler reference, cross-process trace reconstruction
through the merged per-replica logs, and the prefill/decode KV-block
handoff."""

import json
import os
import signal
import time

import pytest

from transformer_tpu.serve.router import (
    ReplicaLink,
    ReplicaProcess,
    Router,
    affinity_key,
    parse_router_line,
)

# The deterministic test-model bootstrap: every process that builds this
# spec (replica subprocesses AND the in-process reference scheduler) gets
# bit-identical params and vocab, so byte-parity assertions hold across
# process boundaries.
SPEC = {
    "config": {
        "num_layers": 1, "d_model": 16, "num_heads": 2, "dff": 32,
        "max_position": 32, "decoder_only": True, "tie_output": True,
        "dtype": "float32", "dropout_rate": 0.0,
    },
    "seed": 0,
    "corpus": ["ab cd ef gh ij kl mn"] * 3,
    "target_vocab_size": 300,
}

# Two distinct shared system prompts so BOTH replicas draw affinity
# traffic (block-aligned leading tokens differ between the groups, match
# within them).
PROMPT_A = "ab cd ef gh ij"
PROMPT_B = "kl mn ef cd"
REQS = (
    [{"prompt": PROMPT_A, "max_new": 5}] * 5
    + [{"prompt": PROMPT_B, "max_new": 4}] * 5
)
# Long-budget burst aimed (by affinity) at one replica — the kill window:
# 12 requests over 2 slots decode in waves, so the first answers drain
# while most of the burst is still queued or mid-decode on the victim.
BURST = [{"prompt": PROMPT_A, "max_new": 24}] * 12


@pytest.fixture(scope="module")
def lm():
    from transformer_tpu.serve.replica import build_model_from_spec

    return build_model_from_spec(SPEC)


@pytest.fixture(scope="module")
def spec_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("router") / "spec.json"
    path.write_text(json.dumps(SPEC))
    return str(path)


def _reference(lm, reqs):
    from transformer_tpu.serve import ContinuousScheduler

    params, cfg, tok = lm
    return ContinuousScheduler(params, cfg, tok, num_slots=2).run(
        [dict(r) for r in reqs]
    )


def _spawn_router(lm, spec_file, n, tmp_path, *, disaggregate=False,
                  trace=False, extra=()):
    params, cfg, tok = lm
    args = [
        "--model_spec", spec_file, "--serve_slots", "2",
        "--heartbeat_ms", "50", "--prefix_cache_mb", "8",
        "--prefix_block", "4", *extra,
    ]
    links = []
    for i in range(n):
        role = "both"
        if disaggregate:
            role = "prefill" if i == 0 else "decode"
        worker = list(args)
        if trace:
            worker += ["--metrics_jsonl", str(tmp_path / f"replica{i}.jsonl"),
                       "--trace"]
        links.append(ReplicaProcess.spawn(i, worker, role=role))
    telemetry = None
    if trace:
        from transformer_tpu.obs import EventLog, Telemetry

        telemetry = Telemetry(
            events=EventLog(str(tmp_path / "router.jsonl")), trace=True
        )
    router = Router(
        links, encode=tok.encode, bos_id=tok.bos_id, affinity_block=4,
        heartbeat_timeout_s=10.0, disaggregate=disaggregate,
        telemetry=telemetry,
    )
    for link in links:
        link.start_reader(router.inbox)
    return router, telemetry


# --------------------------------------------------------------------------
# the acceptance demo: SIGKILL one of two replicas mid-stream


def test_failover_zero_loss_byte_identical(lm, spec_file, tmp_path):
    """Two CPU replica processes, one SIGKILLed mid-stream: every accepted
    request answers exactly once, greedy answers are byte-identical to a
    single-scheduler run, and the merged router+replica logs reconstruct
    every failed-over request's trace (root on the router, spans on both
    replicas)."""
    from transformer_tpu.serve.router import _rendezvous

    router, telemetry = _spawn_router(lm, spec_file, 2, tmp_path, trace=True)
    params, cfg, tok = lm
    reqs = [*REQS, *BURST]
    want = _reference(lm, reqs)
    deadline = time.time() + 55  # the <60s acceptance bound
    try:
        # Phase 1: warm both replicas (each prompt group pins to its own
        # affine replica) and wait until both have answered something.
        for r in REQS:
            router.submit(dict(r))
        answered = []
        while (
            len(answered) < len(REQS)
            or not all(l.answered >= 1 for l in router.links)
        ) and time.time() < deadline:
            router.pump()
            answered.extend(router.drain_ready())
        assert all(l.answered >= 1 for l in router.links)
        # Phase 2: aim a long-budget burst at PROMPT_A's affine replica;
        # the moment its first burst answers drain (so it has admitted and
        # is mid-stream), SIGKILL it — the rest of the burst is still in
        # flight there and must fail over losslessly.
        key = affinity_key([tok.bos_id, *tok.encode(PROMPT_A)], 4)
        victim = max(router.links, key=lambda l: _rendezvous(key, l.name))
        for r in BURST:
            router.submit(dict(r))
        router.pump(timeout=0)  # dispatch the burst
        assert victim.inflight >= 1
        while len(answered) < len(REQS) + 2 and time.time() < deadline:
            router.pump()
            answered.extend(router.drain_ready())
        assert victim.inflight >= 1, "burst drained before the kill window"
        os.kill(victim.pid(), signal.SIGKILL)
        killed_name = victim.name
        while router.busy and time.time() < deadline:
            router.pump()
            answered.extend(router.drain_ready())
        answered.extend(router.drain_ready())
        # Zero loss, exactly once: every accepted order answered, in
        # arrival order, none with an error.
        assert len(answered) == len(reqs)
        assert router.stats["failovers"] == 1
        assert router.stats["redispatched"] >= 1
        assert all("continuation" in a for a in answered), answered
        # Byte parity with the single-scheduler reference.
        assert [a["continuation"] for a in answered] == [
            w["continuation"] for w in want
        ]
    finally:
        router.shutdown()
        if telemetry is not None:
            telemetry.close()

    # ---- merged fleet trace: root on the router, spans on both replicas.
    from transformer_tpu.obs.merge import merge_events
    from transformer_tpu.obs.trace import span_tree

    paths = [str(tmp_path / "router.jsonl"),
             str(tmp_path / "replica0.jsonl"),
             str(tmp_path / "replica1.jsonl")]
    events, info = merge_events(paths)
    assert set(info["sources"]) == {"router.jsonl", "replica0.jsonl",
                                    "replica1.jsonl"}
    failovers = [e for e in events if e.get("kind") == "route.failover"]
    assert len(failovers) == 1 and failovers[0]["replica"] == killed_name
    victim_traces = failovers[0]["traces"]
    assert victim_traces, "failover carried no victim trace ids"
    trees = span_tree(events)
    victim_src = f"{killed_name}.jsonl"
    survivor_src = next(
        s for s in ("replica0.jsonl", "replica1.jsonl") if s != victim_src
    )
    spans_on_victim = 0
    for trace in victim_traces:
        spans = trees.get(trace, {})
        sources = {s.get("source") for s in spans.values()}
        # Root on the router: the route.request span, parentless.
        roots = [s for s in spans.values()
                 if s.get("parent") is None and s["name"] == "route.request"]
        assert roots and roots[0]["source"] == "router.jsonl", spans
        # The redispatched request completed on the survivor.
        assert survivor_src in sources, sources
        spans_on_victim += victim_src in sources
    # At least the slot-resident victims left spans behind (the event log
    # is line-buffered, so SIGKILL loses nothing already emitted): the
    # merge reconstructs one request's lifecycle across BOTH replicas.
    assert spans_on_victim >= 1
    # Every request that was ever dispatched carries a route.dispatch
    # event with its trace id, and redispatches are marked.
    dispatches = [e for e in events if e.get("kind") == "route.dispatch"]
    assert sum(1 for d in dispatches if d.get("redispatch")) == \
        router.stats["redispatched"]
    # The merged fleet report: per-replica request share + redispatches.
    from transformer_tpu.obs.__main__ import summarize_events

    rep = summarize_events(events)["router"]
    assert rep["requests"] == len(reqs)
    assert rep["redispatches"] == router.stats["redispatched"]
    assert rep["failovers"] == 1
    assert set(rep["replicas"]) == {"replica0", "replica1"}
    assert abs(sum(r["share"] for r in rep["replicas"].values()) - 1.0) < 1e-6
    # The Perfetto export gives the router its own lane and each source
    # its own process row.
    from transformer_tpu.obs.trace import chrome_trace

    doc = chrome_trace(events)
    assert sorted(doc["otherData"]["sources"]) == [
        "replica0.jsonl", "replica1.jsonl", "router.jsonl"
    ]
    lanes = {m["args"]["name"] for m in doc["traceEvents"]
             if m.get("name") == "thread_name"}
    assert "router" in lanes


# --------------------------------------------------------------------------
# disaggregated prefill/decode (subprocess path)


@pytest.mark.slow
def test_disaggregated_prefill_decode(lm, spec_file, tmp_path):
    """--disaggregate: prompts ingest on a prefill-only replica and the KV
    crosses to a decode-only replica as prefix-cache blocks; answers stay
    byte-identical and every request rode a handoff."""
    router, _ = _spawn_router(
        lm, spec_file, 2, tmp_path, disaggregate=True
    )
    reqs = REQS[:4]
    try:
        out = router.run([dict(r) for r in reqs])
    finally:
        router.shutdown()
    want = _reference(lm, reqs)
    assert [o.get("continuation") for o in out] == [
        w["continuation"] for w in want
    ]
    assert router.stats["prefill_handoffs"] == len(reqs)
    # The prefill->decode stage progression is normal request flow: it
    # must consume none of the max_redispatch failover budget and never
    # count as a redispatch in the metrics.
    assert router.stats["redispatched"] == 0


# --------------------------------------------------------------------------
# the handoff block format (in-process: the mechanism under the subprocess)


def test_kv_block_handoff_parity(lm):
    """export_blocks -> JSON wire -> inject_blocks restores the prompt's
    KV into a second scheduler's PrefixCache: the decode side answers
    byte-identically while restoring real prefix tokens without a model
    forward."""
    from transformer_tpu.serve import ContinuousScheduler, PrefixCache
    from transformer_tpu.serve.replica import export_blocks, inject_blocks

    params, cfg, tok = lm
    prompt = "ab cd ef gh ij kl"
    ids = [tok.bos_id, *tok.encode(prompt)]

    prefill_cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    s1 = ContinuousScheduler(
        params, cfg, tok, num_slots=1, prefix_cache=prefill_cache
    )
    assert s1.run([{"prompt": prompt, "max_new": 0}]) == [{"continuation": ""}]
    tokens, payload = export_blocks(prefill_cache, ids)
    assert tokens > 0 and payload
    wire = json.loads(json.dumps(payload))  # the pipe representation

    decode_cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    assert inject_blocks(decode_cache, ids, tokens, wire) == tokens
    s2 = ContinuousScheduler(
        params, cfg, tok, num_slots=1, prefix_cache=decode_cache
    )
    out = s2.run([{"prompt": prompt, "max_new": 6}])
    ref = ContinuousScheduler(params, cfg, tok, num_slots=1).run(
        [{"prompt": prompt, "max_new": 6}]
    )
    assert out[0]["continuation"] == ref[0]["continuation"]
    assert s2.stats["prefix_hit_tokens"] == tokens


# --------------------------------------------------------------------------
# router-core unit tests (in-process fake links)


class _FakeLink(ReplicaLink):
    """In-process replica stand-in: echoes an answer per request unless
    muted; `ok = False` simulates process death."""

    def __init__(self, index, name, answer=True):
        super().__init__(index, name)
        self.sent = []
        self.answer_back = answer
        self.ok = True
        self.router = None

    def alive(self):
        return self.ok  # transport liveness only (the router owns `dead`)

    def send(self, msg):
        if not self.ok:
            raise BrokenPipeError("dead")
        self.sent.append(msg)
        if msg.get("type") == "prefill":
            # Disaggregation stage 1: hand back an (empty) KV payload.
            self.router.inbox.put((self.index, {
                "type": "prefilled", "rid": msg["rid"],
                "tokens": 0, "blocks": [],
            }))
        elif self.answer_back:
            self.router.inbox.put((self.index, {
                "type": "answer", "rid": msg["rid"],
                "resp": {"continuation": self.name},
            }))


def _fake_router(n=2, answer=True, **kw):
    links = [_FakeLink(i, f"f{i}", answer=answer) for i in range(n)]
    router = Router(links, **kw)
    for link in links:
        link.router = router
    return router, links


def test_affinity_pins_shared_prefixes():
    """Same leading blocks -> same replica (warm PrefixCache); the key is
    a pure function of the aligned prefix, so tails never split it."""
    assert affinity_key([1, 2, 3, 4, 5, 6, 7, 8, 9], 4) == \
        affinity_key([1, 2, 3, 4, 5, 6, 7, 8, 200], 4)
    assert affinity_key([1, 2, 3], 4) is None  # shorter than one block
    router, links = _fake_router(
        2, encode=lambda s: [ord(c) % 40 + 3 for c in s], bos_id=1,
        affinity_block=4, affinity_slack=100,
    )
    out = router.run([{"prompt": "shared system prompt, tail %d" % i}
                      for i in range(6)])
    assert len(out) == 6
    # All six rode the same replica: the affinity hash pinned them.
    assert sorted(l.dispatched for l in links) == [0, 6]


def test_least_loaded_fallback_when_affine_overloaded():
    router, links = _fake_router(
        2, answer=False, encode=lambda s: [5] * 10, bos_id=1,
        affinity_block=4, affinity_slack=2,
    )
    for i in range(5):
        router.submit({"prompt": "same prompt"})
    router.pump(timeout=0)
    # Pinned to the affine replica until its unanswered load exceeded the
    # least-loaded peer's by more than the slack (2), then spilled — the
    # gap between the two stays bounded by slack + 1.
    assert all(l.dispatched > 0 for l in links)
    assert abs(links[0].dispatched - links[1].dispatched) <= 3


def test_answer_funnel_at_most_once():
    router, links = _fake_router(1, encode=None)
    order = router.submit({"prompt": "p"})
    router.pump(timeout=0)
    router.pump(timeout=0)
    # A late duplicate (the failover race) is counted and dropped.
    router.inbox.put((0, {"type": "answer", "rid": order,
                          "resp": {"continuation": "dup"}}))
    router.pump(timeout=0)
    out = router.drain_ready()
    assert out == [{"continuation": "f0"}]
    assert router.stats["duplicate_answers"] == 1
    assert router.stats["answered"] == 1


def test_failover_preserves_order_and_bounds_redispatch():
    router, links = _fake_router(
        2, answer=False, encode=None, max_redispatch=1,
    )
    orders = [router.submit({"prompt": "p"}) for _ in range(4)]
    router.pump(timeout=0)
    assert len(router._inflight) == 4
    first = [l for l in links if l.inflight][0]
    survivor = links[1 - first.index]
    victims = sorted(m["rid"] for m in first.sent)
    before = len(survivor.sent)
    first.ok = False  # dies without answering
    router.pump(timeout=0)
    assert router.stats["failovers"] == 1
    # Victims re-dispatched to the survivor in their ORIGINAL order, ahead
    # of nothing (they re-enter at the front of the pending queue).
    assert [m["rid"] for m in survivor.sent[before:]] == victims
    # Survivor dies too: the bounded-redispatch ladder answers a
    # structured transient error instead of looping forever.
    survivor.ok = False
    deadline = time.time() + 10
    while router.busy and time.time() < deadline:
        router.pump(timeout=0)
    out = router.drain_ready()
    assert len(out) == 4
    assert all(o.get("code") == "transient" for o in out), out


def test_late_answer_from_failed_replica_releases_survivor_slot():
    """The failover race's load-accounting arm: a victim's late answer
    must release the slot of the SURVIVOR the order is now assigned to,
    and the survivor's own (duplicate) answer must not double-release."""
    router, links = _fake_router(2, answer=False, encode=None)
    order = router.submit({"prompt": "p"})
    router.pump(timeout=0)
    first = [l for l in links if l.inflight][0]
    survivor = links[1 - first.index]
    first.ok = False
    router.pump(timeout=0)  # failover: redispatched to the survivor
    assert survivor.inflight == 1
    assert router._inflight[order].replica == survivor.index
    # The victim's buffered answer lands AFTER the redispatch and wins.
    router.inbox.put((first.index, {"type": "answer", "rid": order,
                                    "resp": {"continuation": "late"}}))
    router.pump(timeout=0)
    assert router.drain_ready() == [{"continuation": "late"}]
    assert survivor.inflight == 0  # the survivor's load was released
    # The survivor's own answer is the duplicate: dropped, no drift.
    router.inbox.put((survivor.index, {"type": "answer", "rid": order,
                                       "resp": {"continuation": "dup"}}))
    router.pump(timeout=0)
    assert router.stats["duplicate_answers"] == 1
    assert survivor.inflight == 0


def test_heartbeat_timeout_failover_then_revival():
    """A heartbeat-timeout victim whose worker process still runs earns
    its way back through the breaker's half-open probe: a heartbeat newer
    than the death mark revives the link, and its next answered request
    closes the breaker. (Exited/SIGKILLed workers fail ``alive()`` and
    stay dead.)"""
    router, links = _fake_router(
        2, encode=None, heartbeat_timeout_s=0.01, breaker_cooldown_s=0.0,
    )
    lagger = links[0]
    lagger.last_hb = time.monotonic() - 1.0  # a stalled worker
    router.pump(timeout=0)
    assert lagger.dead and router.stats["failovers"] == 1
    assert router.breakers[0].state == "open"
    # The worker wakes up and heartbeats again: half-open revival
    # (cooldown 0 here makes the probe immediate).
    router.inbox.put((0, {"type": "hb", "backlog": 0, "free": 2,
                          "active": 0}))
    router.pump(timeout=0)
    assert not lagger.dead and router.stats["revivals"] == 1
    router.heartbeat_timeout_s = 0.0  # the fakes don't keep heartbeating
    out = router.run([{"prompt": "p"} for _ in range(4)])
    assert len(out) == 4
    assert lagger.dispatched > 0  # the revived link carries traffic again
    assert router.breakers[0].state == "closed"


def test_disaggregate_decode_death_degrades_to_prefill_worker():
    """All decode-capable replicas dead with a prefill-only worker alive:
    the request degrades to a full serve on the prefill worker instead of
    parking forever in the pending queue."""
    links = [_FakeLink(0, "pf"), _FakeLink(1, "dec")]
    links[0].role = "prefill"
    links[1].role = "decode"
    router = Router(links, encode=None, disaggregate=True)
    for link in links:
        link.router = router
    links[1].ok = False  # the decode fleet dies before any dispatch
    router.submit({"prompt": "p"})
    out = []
    deadline = time.time() + 10
    while router.busy and time.time() < deadline:
        router.pump(timeout=0)
        out.extend(router.drain_ready())
    assert out == [{"continuation": "pf"}], \
        "request parked forever with a live prefill worker"
    # Stage 1 rode the prefill protocol; the degraded serve was a full
    # "req" on the same worker.
    assert [m["type"] for m in links[0].sent] == ["prefill", "req"]
    assert router.stats["redispatched"] == 0  # degradation, not failover


def test_submit_done_reserves_order():
    router, _ = _fake_router(1, encode=None)
    a = router.submit({"prompt": "p"})
    b = router.submit_done({"error": "LM export serves 'prompt', not 'src'",
                            "code": "routing"})
    c = router.submit({"prompt": "q"})
    out = router.run([])
    assert (a, b, c) == (0, 1, 2)
    assert len(out) == 3
    assert out[1]["code"] == "routing"
    assert "continuation" in out[0] and "continuation" in out[2]


def test_router_deadline_expires_in_queue():
    router, links = _fake_router(1, answer=False, encode=None)
    router.submit({"prompt": "p", "deadline_ms": 0.0})
    time.sleep(0.002)
    router.pump(timeout=0)
    out = router.drain_ready()
    assert out and out[0].get("code") == "deadline"
    assert router.stats["expired"] == 1


def test_parse_router_line_matches_serve_parity():
    assert parse_router_line("ab cd") == {"prompt": "ab cd"}
    assert parse_router_line('{"prompt": "x", "max_new": 2}') == {
        "prompt": "x", "max_new": 2,
    }
    with pytest.raises(ValueError, match="serves 'prompt', not 'src'"):
        parse_router_line('{"src": "y"}')
    with pytest.raises(ValueError, match="serves 'prompt', not 'fill'"):
        parse_router_line('{"fill": "y"}')
    with pytest.raises(ValueError, match="needs 'src'"):
        parse_router_line('{"beam": 4}')
