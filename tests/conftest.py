"""Test harness: force a virtual 8-device CPU platform.

This is the JAX-native analogue of a fake multi-GPU backend (SURVEY.md §4):
distributed tests build a real ``jax.sharding.Mesh`` over 8 host-platform
devices, so sharding/collective code paths compile and execute without TPU
hardware.

Note: this environment's sitecustomize registers a TPU PJRT plugin in every
interpreter before conftest runs, so setting JAX_PLATFORMS in os.environ here
would be too late — we must flip ``jax.config`` directly (backends initialize
lazily, so this still wins as long as it happens before first use).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight tests (interpret-mode Pallas kernels, 8-device "
        "shard_map, multi-process) — `pytest -m 'not slow'` is the fast "
        "core-parity path (see README)",
    )
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection tests (docs/ROBUSTNESS.md) — a fast "
        "deterministic subset rides tier-1; the full sweep is also marked "
        "slow (`pytest -m chaos` runs every drill)",
    )
    config.addinivalue_line(
        "markers",
        "pallas: Pallas kernel parity/retrace tests (interpret mode on "
        "CPU) — a fast subset rides tier-1; the full variant x block-size "
        "sweep is also marked slow (`pytest -m pallas` runs every kernel "
        "test)",
    )


def launch_analysis_all_gate():
    """The ONE definition of the `analysis all` gate invocation — the
    pre-launch hook below and test_analysis_all_cli_gate's synchronous
    fallback must run the IDENTICAL command or the two paths drift."""
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.Popen(
        [sys.executable, "-m", "transformer_tpu.analysis", "all",
         "--format=json"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        # Lowest priority: the gate soaks IDLE core time next to the
        # single-threaded suite; it must never stretch the suite's own
        # critical path on a small box (tier-1 runs under a hard timeout).
        preexec_fn=lambda: os.nice(19),
    )


def pytest_collection_finish(session):
    """The `analysis all` pre-merge gate (test_analysis.py) shells a
    ~80s-CPU subprocess. pytest itself is single-threaded, so on any
    multi-core box that subprocess can run CONCURRENTLY with the rest of
    the suite instead of serially at the end: launch it the moment
    collection (and marker deselection) confirms the gate test will run,
    and let the test collect the result. The Popen handle rides on the
    config object; the test falls back to launching synchronously when
    run without this hook having fired."""
    if getattr(session.config.option, "collectonly", False):
        return  # --collect-only runs no test: nothing to pre-warm
    if any(
        item.name == "test_analysis_all_cli_gate" for item in session.items
    ):
        session.config._analysis_all_gate = launch_analysis_all_gate()


def pytest_sessionfinish(session, exitstatus):
    """Reap the gate subprocess if the gate test never consumed it (run
    aborted with -x / Ctrl-C): an orphaned 80s-CPU child must not outlive
    the pytest invocation that spawned it."""
    proc = getattr(session.config, "_analysis_all_gate", None)
    if proc is not None and proc.poll() is None:
        proc.kill()
        proc.communicate()
