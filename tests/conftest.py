"""Test harness: force a virtual 8-device CPU platform.

This is the JAX-native analogue of a fake multi-GPU backend (SURVEY.md §4):
distributed tests build a real ``jax.sharding.Mesh`` over 8 host-platform
devices, so sharding/collective code paths compile and execute without TPU
hardware.

Note: this environment's sitecustomize registers a TPU PJRT plugin in every
interpreter before conftest runs, so setting JAX_PLATFORMS in os.environ here
would be too late — we must flip ``jax.config`` directly (backends initialize
lazily, so this still wins as long as it happens before first use).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight tests (interpret-mode Pallas kernels, 8-device "
        "shard_map, multi-process) — `pytest -m 'not slow'` is the fast "
        "core-parity path (see README)",
    )
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection tests (docs/ROBUSTNESS.md) — a fast "
        "deterministic subset rides tier-1; the full sweep is also marked "
        "slow (`pytest -m chaos` runs every drill)",
    )
