"""Native (C++) tokenizer parity: the ctypes-bound trainer/encoder in
transformer_tpu/native must be bit-identical to the pure-Python reference
implementation in transformer_tpu/data/tokenizer.py — same vocabulary, same
id sequences — so either path can serve the pipeline interchangeably."""

from collections import Counter

import numpy as np
import pytest

from transformer_tpu import native
from transformer_tpu.data.tokenizer import (
    SubwordTokenizer,
    _word_to_symbols,
)

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "she sells sea shells by the sea shore",
    "ein Haus am See mit Blick über den Fluß",
    "underscores _like_ this and back\\slashes and <angle> brackets",
    "unicode: Ω μῆνιν ἄειδε θεά 真真好 émigré",
    "numbers 12345 and <0x41> literal byte token text",
] * 3


def _python_train(corpus, target_vocab_size, min_pair_count=2):
    """Run the pure-Python BPE trainer, bypassing the native fast path."""
    all_words = []
    for line in corpus:
        all_words.extend(line.split())
    # Reproduce build_from_corpus's python branch directly: temporarily
    # disable the native library lookup.
    import transformer_tpu.native as nat_mod

    saved = nat_mod._lib
    nat_mod._lib = False
    try:
        tok = SubwordTokenizer.build_from_corpus(
            corpus, target_vocab_size=target_vocab_size, min_pair_count=min_pair_count
        )
    finally:
        nat_mod._lib = saved
    return tok


@pytest.fixture(scope="module")
def lib():
    lib = native.get_lib()
    if lib is None:
        pytest.skip("native library unavailable (no g++?)")
    return lib


class TestNativeTrainerParity:
    @staticmethod
    def _word_freq(corpus):
        wf = Counter()
        for line in corpus:
            wf.update(line.split())
        return wf

    def test_vocab_identical_to_python(self, lib):
        py_tok = _python_train(CORPUS, 500)
        nat = native.NativeTokenizer.train(self._word_freq(CORPUS), 500, 2)
        assert nat is not None
        assert nat.pieces() == py_tok.subwords

    def test_vocab_identical_small_target(self, lib):
        # Target below alphabet size: no merges at all, alphabet order only.
        py_tok = _python_train(CORPUS, 100)
        nat = native.NativeTokenizer.train(self._word_freq(CORPUS), 100, 2)
        assert nat.pieces() == py_tok.subwords

    def test_build_from_corpus_uses_native_and_matches(self, lib):
        tok_auto = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=500)
        tok_py = _python_train(CORPUS, 500)
        assert tok_auto.subwords == tok_py.subwords


class TestNativeEncodeParity:
    @pytest.fixture(scope="class")
    def tok(self):
        return SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=500)

    def _python_encode(self, tok, text):
        ids = []
        for word in text.split():
            ids.extend(tok._encode_symbols(_word_to_symbols(word)))
        return ids

    @pytest.mark.parametrize(
        "text",
        [
            "the quick brown fox",
            "completely unseen zebra words xylophone",
            "unicode Ω 真好 μῆνιν mixed with ascii",
            "under_score \\backslash <angle <0x41> literal",
            "",
            "   ",
            "a",
            "ein Haus am See",
        ],
    )
    def test_encode_matches_python(self, lib, tok, text):
        nat = native.NativeTokenizer.from_pieces(tok.subwords)
        assert nat is not None
        assert nat.encode_words(text.split()) == self._python_encode(tok, text)

    def test_fast_path_active_and_roundtrips(self, lib, tok):
        # The instance-level fast path should engage and decode back exactly.
        assert tok._native_encoder() is not None
        for text in ["the quick brown fox", "unseen Ω _x_ <0x41>"]:
            assert tok.decode(tok.encode(text)) == text

    def test_large_random_text_parity(self, lib, tok):
        import random

        rng = random.Random(0)
        pool = "abcdefghijklmnopqrstuvwxyz_\\<>ΩµßüéА真 0123456789"
        words = [
            "".join(rng.choice(pool) for _ in range(rng.randrange(1, 12)))
            for _ in range(500)
        ]
        text = " ".join(words)
        nat = native.NativeTokenizer.from_pieces(tok.subwords)
        assert nat.encode_words(text.split()) == self._python_encode(tok, text)


class TestIncompleteVocab:
    def test_native_path_disabled_without_byte_tokens(self, lib):
        """A hand-built vocab missing <0xNN> byte tokens must not engage the
        native encoder (whose fallback cannot raise like Python's does)."""
        tok = SubwordTokenizer(["ab", "_"])
        assert tok._native_encoder() is None
        with pytest.raises(KeyError):
            tok.encode("xy")


class TestNativeBatchLoader:
    """C++ prefetching loader vs the Python Seq2SeqDataset path."""

    @pytest.fixture()
    def examples(self):
        rng = np.random.default_rng(0)
        src = [
            rng.integers(1, 50, size=rng.integers(2, 14), dtype=np.int32)
            for _ in range(37)
        ]
        tgt = [
            rng.integers(1, 50, size=rng.integers(2, 12), dtype=np.int32)
            for _ in range(37)
        ]
        return src, tgt

    def _make(self, examples, prefetch, **kw):
        from transformer_tpu.data import Seq2SeqDataset

        src, tgt = examples
        defaults = dict(
            batch_size=8, src_len=10, tgt_len=10, seed=3, prefetch=prefetch
        )
        defaults.update(kw)
        return Seq2SeqDataset(src, tgt, **defaults)

    def test_unshuffled_exactly_matches_python(self, lib, examples):
        """Without shuffling both paths iterate corpus order: batches must be
        bit-identical, including truncation and partial-batch fill rows."""
        for drop in (True, False):
            py = list(
                self._make(examples, False, shuffle=False, drop_remainder=drop).batches(0)
            )
            nat = list(
                self._make(examples, True, shuffle=False, drop_remainder=drop).batches(0)
            )
            assert len(py) == len(nat) and len(py) > 0
            for (ps, pt), (ns, nt) in zip(py, nat):
                np.testing.assert_array_equal(ps, ns)
                np.testing.assert_array_equal(pt, nt)

    def test_shuffled_same_multiset_and_deterministic(self, lib, examples):
        ds = self._make(examples, True, shuffle=True, drop_remainder=False)
        a = list(ds.batches(1))
        b = list(ds.batches(1))
        c = list(ds.batches(2))
        for (xs, xt), (ys, yt) in zip(a, b):  # same (seed, epoch) => same order
            np.testing.assert_array_equal(xs, ys)
            np.testing.assert_array_equal(xt, yt)
        flat = lambda bs: sorted(tuple(r) for s, _ in bs for r in s.tolist())
        assert flat(a) == flat(c)  # epochs permute, never drop/duplicate
        assert [s.tolist() for s, _ in a] != [s.tolist() for s, _ in c]

    def test_sharding_partitions_each_batch(self, lib, examples):
        full = list(self._make(examples, True, shuffle=False).batches(0))
        sh0 = list(
            self._make(examples, True, shuffle=False, shard_index=0, shard_count=2).batches(0)
        )
        sh1 = list(
            self._make(examples, True, shuffle=False, shard_index=1, shard_count=2).batches(0)
        )
        for (fs, _), (s0, _), (s1, _) in zip(full, sh0, sh1):
            np.testing.assert_array_equal(np.concatenate([s0, s1]), fs)

    def test_abandoned_epoch_then_restart(self, lib, examples):
        """Breaking out mid-epoch must not deadlock the next epoch."""
        ds = self._make(examples, True, shuffle=True, drop_remainder=False)
        it = ds.batches(0)
        next(it)
        del it  # consumer walks away with batches still queued
        assert len(list(ds.batches(1))) == len(ds)

    def test_bucketed_prefetch_same_examples_at_bucket_widths(
        self, lib, examples
    ):
        """length_buckets × prefetch (was a documented rejection): the C++
        loader forms batches inside buckets and pads to the bucket width.
        Shuffle order differs from the numpy path by design, so assert the
        semantic contract: every example exactly once, batch widths drawn
        from the bucket set, every row fits its width, deterministic per
        (seed, epoch)."""
        buckets = (6, 8, 14)
        ds = self._make(
            examples, True, src_len=14, tgt_len=14, length_buckets=buckets,
            drop_remainder=False,
        )

        def collect(epoch):
            rows, widths = [], []
            for s, t in ds.batches(epoch):
                assert s.shape[1] == t.shape[1]
                assert s.shape[1] in buckets
                widths.append(s.shape[1])
                for rs, rt in zip(s, t):
                    pair = (tuple(rs[rs != 0]), tuple(rt[rt != 0]))
                    if pair != ((), ()):  # skip all-pad fill rows
                        rows.append(pair)
            return rows, widths

        src, tgt = examples
        corpus = sorted(
            (tuple(s.tolist()), tuple(t.tolist())) for s, t in zip(src, tgt)
        )
        rows, widths = collect(0)
        assert sorted(rows) == corpus
        assert len(set(widths)) > 1  # multiple buckets actually exercised
        rows2, widths2 = collect(0)
        assert rows == rows2 and widths == widths2  # (seed, epoch) determinism
        rows3, _ = collect(1)
        assert rows != rows3  # epochs reshuffle

    def test_bucketed_prefetch_asymmetric_lens(self, lib, examples):
        """src_len != tgt_len with a bucket wider than the narrower side:
        slot and receive buffers must size at max(src_len, tgt_len) — the
        per-side sizing heap-overflowed (caught in review as a real
        free()-corruption abort)."""
        ds = self._make(
            examples, True, src_len=8, tgt_len=14, length_buckets=(6, 14),
            drop_remainder=False,
        )
        src_list, tgt_list = examples
        rows = []
        for s, t in ds.batches(0):
            assert s.shape[1] == t.shape[1] and s.shape[1] in (6, 14)
            for rs, rt in zip(s, t):
                pair = (tuple(rs[rs != 0]), tuple(rt[rt != 0]))
                if pair != ((), ()):
                    rows.append(pair)
        corpus = sorted(
            (tuple(s.tolist()), tuple(t.tolist()))
            for s, t in zip(src_list, tgt_list)
        )
        assert sorted(rows) == corpus

    def test_bucketed_prefetch_trains_through_trainer(self, lib, examples):
        """End-to-end: a bucketed prefetching dataset drives Trainer.fit
        (multiple static shapes reach the jitted step)."""
        import jax

        from transformer_tpu.config import ModelConfig, TrainConfig
        from transformer_tpu.train import Trainer, create_train_state

        src, tgt = examples
        ds = self._make(
            examples, True, src_len=14, tgt_len=14, length_buckets=(6, 8, 14),
        )
        model = ModelConfig(
            num_layers=1, d_model=16, num_heads=2, dff=32,
            input_vocab_size=64, target_vocab_size=64, max_position=16,
            dtype="float32", dropout_rate=0.0,
        )
        tcfg = TrainConfig(
            batch_size=8, sequence_length=14, epochs=1, warmup_steps=10,
            log_every_steps=0,
        )
        state = create_train_state(jax.random.PRNGKey(0), model, tcfg)
        tr = Trainer(model, tcfg, state, log_fn=lambda *_: None)
        tr.fit(ds)
        assert int(jax.device_get(tr.state.step)) == len(ds)


class TestNativeSpeed:
    def test_native_encode_not_slower(self, lib):
        # Sanity only (no strict perf assert on shared CI hosts): native path
        # must at least produce identical output over the whole corpus.
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=500)
        nat = native.NativeTokenizer.from_pieces(tok.subwords)
        for line in CORPUS:
            ids = []
            for w in line.split():
                ids.extend(tok._encode_symbols(_word_to_symbols(w)))
            assert nat.encode_words(line.split()) == ids
