"""Grouped-query / multi-query attention (ModelConfig.num_kv_heads).

From the retrieved-paper list (Shazeer 2019, "Fast Transformer Decoding:
One Write-Head is All You Need"): k/v carry fewer heads than q, shrinking
the decode KV cache and kv parameter count by num_heads/num_kv_heads. No
reference counterpart (the reference is plain MHA, ``Attention.py:36-78``).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig, TrainConfig
from transformer_tpu.ops.attention import dot_product_attention, mha_init

GQA_TINY = ModelConfig(
    num_layers=2, d_model=32, num_heads=4, num_kv_heads=2, dff=64,
    input_vocab_size=50, target_vocab_size=50, max_position=32,
    dtype="float32", dropout_rate=0.0,
)


class TestGroupedDotProductAttention:
    def test_grouped_equals_repeated_kv(self):
        """The grouped einsum must equal plain MHA on kv explicitly repeated
        to full heads — same math, no materialized repeat."""
        B, Sq, Sk, H, Hkv, D = 2, 6, 7, 4, 2, 8
        q = jax.random.normal(jax.random.PRNGKey(0), (B, Sq, H, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, Sk, Hkv, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, Sk, Hkv, D))
        mask = jnp.ones((B, 1, 1, Sk), bool).at[:, :, :, -2:].set(False)
        out_g, w_g = dot_product_attention(q, k, v, mask, return_weights=True)
        reps = H // Hkv
        out_r, w_r = dot_product_attention(
            q, jnp.repeat(k, reps, axis=2), jnp.repeat(v, reps, axis=2),
            mask, return_weights=True,
        )
        np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_r), atol=1e-5)
        assert w_g.shape == (B, H, Sq, Sk)
        np.testing.assert_allclose(np.asarray(w_g), np.asarray(w_r), atol=1e-5)

    def test_kv_params_shrink(self):
        p_mha = mha_init(jax.random.PRNGKey(0), 32, 4)
        p_gqa = mha_init(jax.random.PRNGKey(0), 32, 4, num_kv_heads=1)
        assert p_mha["key"]["kernel"].shape == (32, 4, 8)
        assert p_gqa["key"]["kernel"].shape == (32, 1, 8)
        assert p_gqa["query"]["kernel"].shape == (32, 4, 8)

    def test_full_kv_heads_bitwise_matches_old_init(self):
        """num_kv_heads == num_heads must reproduce the pre-GQA init exactly
        (same glorot shapes and fans), so existing checkpoints stay valid."""
        a = mha_init(jax.random.PRNGKey(3), 32, 4)
        b = mha_init(jax.random.PRNGKey(3), 32, 4, num_kv_heads=4)
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestGqaModel:
    def test_decode_cache_is_smaller(self):
        from transformer_tpu.models.decoder import init_decoder_caches

        caches = init_decoder_caches(GQA_TINY, batch_size=2, max_len=16)
        assert caches[0]["k"].shape == (2, 16, 2, 8)  # kv_heads=2, not 4

    def test_cached_decode_matches_full_forward(self):
        from transformer_tpu.models import transformer_init
        from transformer_tpu.models.decoder import init_decoder_caches
        from transformer_tpu.models.transformer import (
            transformer_apply,
            transformer_decode_step,
        )

        cfg = dataclasses.replace(GQA_TINY, decoder_only=True)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray([[3, 11, 25, 7, 40, 2]], jnp.int32)
        full_logits, _ = transformer_apply(params, None, ids, cfg)
        caches = init_decoder_caches(cfg, batch_size=1, max_len=8)
        for t in range(ids.shape[1]):
            step_logits, caches = transformer_decode_step(
                params, ids[:, t : t + 1], None, None, caches,
                jnp.int32(t), cfg,
            )
            np.testing.assert_allclose(
                np.asarray(step_logits[0]), np.asarray(full_logits[0, t]),
                atol=2e-4,
            )

    @pytest.mark.slow
    def test_seq2seq_gqa_trains_and_translates(self):
        from transformer_tpu.train import create_train_state, make_train_step
        from transformer_tpu.train.decode import greedy_decode

        tc = TrainConfig(batch_size=8, sequence_length=12, warmup_steps=100)
        state = create_train_state(jax.random.PRNGKey(0), GQA_TINY, tc)
        step = jax.jit(make_train_step(GQA_TINY, tc))
        r = np.random.default_rng(0)
        src = jnp.asarray(r.integers(1, 48, (8, 12)), jnp.int32)
        tgt = jnp.asarray(r.integers(1, 48, (8, 12)), jnp.int32)
        rng = jax.random.PRNGKey(1)
        first = None
        for _ in range(40):
            state, m = step(state, src, tgt, rng)
            first = float(m["loss"]) if first is None else first
        assert float(m["loss"]) < first * 0.7
        out = greedy_decode(
            state.params, src[:2], GQA_TINY, bos_id=48, eos_id=49, max_len=6
        )
        assert out.shape == (2, 6)

    def test_flash_matches_xla_with_gqa(self):
        from transformer_tpu.models import transformer_apply, transformer_init

        cfg = dataclasses.replace(GQA_TINY, decoder_only=True, max_position=16)
        cfg_flash = dataclasses.replace(
            cfg, attention_impl="flash", flash_block_q=8, flash_block_k=8
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(1, 48, (2, 16)), jnp.int32
        )
        la, _ = transformer_apply(params, None, ids, cfg)
        lb, _ = transformer_apply(params, None, ids, cfg_flash)
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=2e-4)

    @pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
    def test_flash_kernel_grouped_kv_no_repeat(self):
        """Kernel-level GQA (VERDICT r2 next-#6): flash_attention takes
        (B, S, H_kv, D) kv DIRECTLY — the BlockSpec index maps assign each
        q-head its kv group, nothing repeats kv to full heads — and both the
        forward and all three gradients match the grouped XLA oracle."""
        from transformer_tpu.kernels.flash_attention import flash_attention

        B, S, H, Hkv, D = 2, 16, 4, 2, 8
        kq, kk, kv, kd = jax.random.split(jax.random.PRNGKey(3), 4)
        q = jax.random.normal(kq, (B, S, H, D))
        k = jax.random.normal(kk, (B, S, Hkv, D))
        v = jax.random.normal(kv, (B, S, Hkv, D))
        kv_mask = jnp.ones((B, S), bool).at[:, -3:].set(False)
        do = jax.random.normal(kd, (B, S, H, D))

        def oracle(q, k, v):
            out, _ = dot_product_attention(q, k, v, kv_mask[:, None, None, :])
            return out

        def flash(q, k, v):
            return flash_attention(q, k, v, kv_mask=kv_mask, block_q=8, block_k=8)

        np.testing.assert_allclose(
            np.asarray(flash(q, k, v)), np.asarray(oracle(q, k, v)), atol=1e-5
        )
        loss = lambda f: (lambda *a: jnp.vdot(f(*a), do))  # noqa: E731
        g_f = jax.grad(loss(flash), argnums=(0, 1, 2))(q, k, v)
        g_o = jax.grad(loss(oracle), argnums=(0, 1, 2))(q, k, v)
        for gf, go in zip(g_f, g_o):
            assert gf.shape == go.shape  # kv grads stay at H_kv heads
            np.testing.assert_allclose(np.asarray(gf), np.asarray(go), atol=1e-4)

    @pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
    def test_flash_kernel_mqa_causal_grads(self):
        """Multi-query extreme (H_kv=1) under structural causality."""
        from transformer_tpu.kernels.flash_attention import flash_attention
        from transformer_tpu.ops.masks import make_causal_mask

        B, S, H, D = 2, 24, 4, 8
        kq, kk, kv, kd = jax.random.split(jax.random.PRNGKey(9), 4)
        q = jax.random.normal(kq, (B, S, H, D))
        k = jax.random.normal(kk, (B, S, 1, D))
        v = jax.random.normal(kv, (B, S, 1, D))
        do = jax.random.normal(kd, (B, S, H, D))

        def oracle(q, k, v):
            out, _ = dot_product_attention(q, k, v, make_causal_mask(S))
            return out

        def flash(q, k, v):
            return flash_attention(q, k, v, causal=True, block_q=8, block_k=8)

        np.testing.assert_allclose(
            np.asarray(flash(q, k, v)), np.asarray(oracle(q, k, v)), atol=1e-5
        )
        loss = lambda f: (lambda *a: jnp.vdot(f(*a), do))  # noqa: E731
        g_f = jax.grad(loss(flash), argnums=(0, 1, 2))(q, k, v)
        g_o = jax.grad(loss(oracle), argnums=(0, 1, 2))(q, k, v)
        for gf, go in zip(g_f, g_o):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(go), atol=1e-4)

    def test_rope_composes_with_gqa(self):
        from transformer_tpu.models import transformer_apply, transformer_init

        cfg = dataclasses.replace(
            GQA_TINY, decoder_only=True, position_scheme="rope"
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
        la, _ = transformer_apply(params, None, ids, cfg)
        lb, _ = transformer_apply(params, None, ids[:, ::-1], cfg)
        assert np.isfinite(np.asarray(la)).all()
        assert float(jnp.max(jnp.abs(la[:, -1] - lb[:, -1]))) > 1e-4

    def test_invalid_ratio_rejected(self):
        import pytest

        with pytest.raises(ValueError, match="num_kv_heads"):
            ModelConfig(num_heads=4, num_kv_heads=3)

    @pytest.mark.slow
    def test_distributed_parity_with_single_device(self):
        """GQA under a data×model (TP) mesh: kv kernels shard on their kv-head
        axis when it divides the model axis; loss matches single-device."""
        from transformer_tpu.config import MeshConfig
        from transformer_tpu.parallel import DistributedTrainer, make_mesh
        from transformer_tpu.train import create_train_state, make_train_step

        tc = TrainConfig(batch_size=8, sequence_length=12, warmup_steps=100)
        r = np.random.default_rng(0)
        src = r.integers(1, 48, (8, 12), dtype=np.int32)
        tgt = r.integers(1, 48, (8, 12), dtype=np.int32)
        rng = jax.random.PRNGKey(1)

        mesh = make_mesh(MeshConfig(data=2, model=2), devices=jax.devices()[:4])
        dt = DistributedTrainer(GQA_TINY, tc, mesh)
        kv = dt.state.params["encoder"]["layers"][0]["mha"]["key"]["kernel"]
        assert kv.sharding.spec[1] == "model"  # kv_heads=2 divides model=2
        s_d = dt.state
        for _ in range(3):
            s_d, m_d = dt.train_step(s_d, src, tgt, rng)

        s_1 = create_train_state(jax.random.PRNGKey(tc.seed), GQA_TINY, tc)
        step = jax.jit(make_train_step(GQA_TINY, tc))
        for _ in range(3):
            s_1, m_1 = step(s_1, jnp.asarray(src), jnp.asarray(tgt), rng)
        np.testing.assert_allclose(float(m_d["loss"]), float(m_1["loss"]), rtol=2e-4)
