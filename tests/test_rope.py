"""Rotary position embeddings (ops/positional.py apply_rope,
ModelConfig.position_scheme="rope").

No reference counterpart (the reference is additive-sinusoidal only,
``positionalencoding.py:8-23``) — these tests pin the properties RoPE
promises: norm preservation, shift invariance of attention scores, the
KV-cache decode path matching the full forward, and composition with the
flash kernel and training.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig, TrainConfig
from transformer_tpu.ops.positional import apply_rope

ROPE_TINY = ModelConfig(
    num_layers=2, d_model=32, num_heads=4, dff=64,
    input_vocab_size=50, target_vocab_size=50, max_position=32,
    dtype="float32", dropout_rate=0.0,
    position_scheme="rope", decoder_only=True,
)


class TestApplyRope:
    def test_preserves_norm(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 6, 3, 8))
        y = apply_rope(x, jnp.arange(6))
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(y), axis=-1),
            rtol=1e-5,
        )

    def test_position_zero_is_identity(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 2, 8))
        y = apply_rope(x, jnp.zeros((1,), jnp.int32))
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)

    def test_scores_depend_only_on_relative_distance(self):
        """<rope(q, i), rope(k, j)> must equal <rope(q, i+d), rope(k, j+d)>
        — the property that makes RoPE a relative encoding."""
        q = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 1, 16))
        k = jax.random.normal(jax.random.PRNGKey(3), (1, 1, 1, 16))

        def score(qi, kj):
            qr = apply_rope(q, jnp.array([qi]))
            kr = apply_rope(k, jnp.array([kj]))
            return float(jnp.sum(qr * kr))

        np.testing.assert_allclose(score(5, 3), score(9, 7), rtol=1e-4)
        np.testing.assert_allclose(score(0, 4), score(13, 17), rtol=1e-4)
        assert abs(score(5, 3) - score(5, 4)) > 1e-6  # but distance matters


class TestRopeModel:
    def test_forward_distinguishes_positions(self):
        """With RoPE there is no additive table, so position information must
        arrive via attention: permuting input order must change logits."""
        from transformer_tpu.models import transformer_apply, transformer_init

        params = transformer_init(jax.random.PRNGKey(0), ROPE_TINY)
        ids = jnp.asarray([[5, 6, 7, 8, 9]], jnp.int32)
        rev = ids[:, ::-1]
        la, _ = transformer_apply(params, None, ids, ROPE_TINY)
        lb, _ = transformer_apply(params, None, rev, ROPE_TINY)
        # Same multiset of tokens, different order -> different final logits.
        assert float(jnp.max(jnp.abs(la[:, -1] - lb[:, -1]))) > 1e-4

    def test_cached_decode_matches_full_forward(self):
        """Incremental KV-cache decode (keys stored rotated) must reproduce
        the full-sequence forward logits position by position."""
        from transformer_tpu.models import transformer_init
        from transformer_tpu.models.decoder import init_decoder_caches
        from transformer_tpu.models.transformer import (
            transformer_apply,
            transformer_decode_step,
        )

        params = transformer_init(jax.random.PRNGKey(0), ROPE_TINY)
        ids = jnp.asarray([[3, 11, 25, 7, 40, 2]], jnp.int32)
        full_logits, _ = transformer_apply(params, None, ids, ROPE_TINY)

        caches = init_decoder_caches(ROPE_TINY, batch_size=1, max_len=8)
        for t in range(ids.shape[1]):
            step_logits, caches = transformer_decode_step(
                params, ids[:, t : t + 1], None, None, caches,
                jnp.int32(t), ROPE_TINY,
            )
            np.testing.assert_allclose(
                np.asarray(step_logits[0]),
                np.asarray(full_logits[0, t]),
                atol=2e-4,
            )

    def test_flash_matches_xla_with_rope(self):
        from transformer_tpu.models import transformer_apply, transformer_init

        cfg_flash = dataclasses.replace(
            ROPE_TINY, attention_impl="flash", flash_block_q=8, flash_block_k=8
        )
        params = transformer_init(jax.random.PRNGKey(0), ROPE_TINY)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(1, 48, (2, 16)), jnp.int32
        )
        la, _ = transformer_apply(params, None, ids, ROPE_TINY)
        lb, _ = transformer_apply(params, None, ids, cfg_flash)
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=2e-4)

    def test_training_loss_falls(self):
        from transformer_tpu.train import create_train_state, make_train_step

        tc = TrainConfig(batch_size=8, sequence_length=12, warmup_steps=100)
        state = create_train_state(jax.random.PRNGKey(0), ROPE_TINY, tc)
        step = jax.jit(make_train_step(ROPE_TINY, tc))
        r = np.random.default_rng(0)
        tgt = jnp.asarray(r.integers(1, 48, (8, 12)), jnp.int32)
        rng = jax.random.PRNGKey(1)
        first = None
        for _ in range(40):
            state, m = step(state, None, tgt, rng)
            first = float(m["loss"]) if first is None else first
        assert float(m["loss"]) < first * 0.7

    @pytest.mark.slow
    def test_seq2seq_rope_trains(self):
        """Encoder-decoder with RoPE: encoder self-attn and decoder self-attn
        rotate; cross-attention does not."""
        from transformer_tpu.train import create_train_state, make_train_step

        cfg = dataclasses.replace(ROPE_TINY, decoder_only=False)
        tc = TrainConfig(batch_size=4, sequence_length=10, warmup_steps=100)
        state = create_train_state(jax.random.PRNGKey(0), cfg, tc)
        step = jax.jit(make_train_step(cfg, tc))
        r = np.random.default_rng(1)
        src = jnp.asarray(r.integers(1, 48, (4, 10)), jnp.int32)
        tgt = jnp.asarray(r.integers(1, 48, (4, 10)), jnp.int32)
        state, m = step(state, src, tgt, jax.random.PRNGKey(1))
        assert np.isfinite(float(m["loss"]))
