"""KNOWN-GOOD twin of ``tpa_bad_corpus.py``: the same six shapes written
correctly, plus the laundering/suppression idioms the rules must NOT flag.
`python -m transformer_tpu.analysis rules --paths
tests/fixtures/tpa_good_corpus.py` must exit 0."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_SCALE = 2.0  # immutable module constant: fine to close over


@partial(jax.jit, static_argnames=("n",))
def branch_on_static(x, n):
    if n > 0:  # static argument: concrete at trace time
        return x * n
    if x.shape[0] > 4:  # shape metadata is concrete under trace
        return x[:4]
    return jnp.where(x > 0, x, -x)  # traced condition, traced select


@jax.jit
def jnp_on_tracer(x, mask=None):
    if mask is None:  # identity test against None is concrete
        total = jnp.sum(x)
    else:
        total = jnp.sum(x * mask)
    return x / total


@jax.jit
def reads_constant_state(x):
    rows = np.arange(len(x))  # numpy on concrete (len launders the tracer)
    return x * _SCALE + jnp.asarray(rows)


@partial(jax.jit, static_argnames=("length",))
def fresh_static_name(x, length):
    return x[:length]


@partial(jax.jit, donate_argnums=(0,))
def update_buffer(buf, delta):
    return buf + delta


def donated_rebound(buf, delta):
    buf = update_buffer(buf, delta)  # rebind: the name now owns the result
    return buf + 1


def narrow_handler(path):
    try:
        with open(path) as f:
            return f.read()
    except (OSError, UnicodeDecodeError):  # the failures open/read can raise
        return None


def cleanup_handler(path, pool):
    slot = pool.pop()
    try:
        return open(path)
    except Exception:  # broad but re-raising: a cleanup pass-through
        pool.append(slot)
        raise


def bounded_retry(q, time):
    for _attempt in range(5):  # bounded loop: never flagged
        try:
            return q.get_nowait()
        except KeyError:
            continue
    raise TimeoutError


def backed_off_retry(q, time):
    while True:
        try:
            return q.get_nowait()
        except KeyError:  # backs off: the retry rate is bounded
            time.sleep(0.01)
            continue


def condition_tested_retry(q, stop):
    while not stop.is_set():  # loop test bounds it: never flagged
        try:
            return q.get_nowait()
        except KeyError:
            continue
