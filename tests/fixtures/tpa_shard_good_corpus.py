"""Known-GOOD twin of tpa_shard_bad_corpus.py: the same shapes of code with
the sharding discipline done right — every TPA20x rule must stay silent
here (false positives on this file are rule bugs). Never imported."""

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

DEVICES = jax.devices()

MESH = Mesh(DEVICES, ("data", "model"))


def train_step(state, batch):
    return state


def update(state, grads):
    return state


# Boundary activations pinned on BOTH sides (cf. TPA201).
sharded_step = jax.jit(
    train_step,
    in_shardings=(P("data"), P("data")),
    out_shardings=(P("data"),),
)

# Axis names drawn from the declared vocabulary (cf. TPA202).
ACT_SPEC = P("model", None)

# Donated argument keeps its layout through the step (cf. TPA203).
donating_step = jax.jit(
    update,
    donate_argnums=(0,),
    in_shardings=(P("data"), P(None)),
    out_shardings=(P("data"),),
)


# The serving hot loop stays collective-free (cf. TPA204).
@jax.jit
def _pool_step(params, caches, toks):
    return jnp.ones((toks.shape[0], 8))


# A collective in TRAIN code is fine — TPA204 scopes to the decode loop.
@jax.jit
def all_reduce_grads(grads):
    return jax.lax.psum(grads, "data")


# Large params sharded; only genuinely small tensors replicate (cf. TPA205).
PARTITION_RULES = [
    (r"embedding/table$", P("data", None)),
    (r"ffn/in/kernel$", P("data", "model")),
    (r"ln1/scale$", P(None)),
]
