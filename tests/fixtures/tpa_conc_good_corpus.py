"""KNOWN-GOOD twin of ``tpa_conc_bad_corpus.py``: the same shapes written
with a consistent lock discipline. `python -m transformer_tpu.analysis
concurrency --paths tests/fixtures/tpa_conc_good_corpus.py` must exit 0."""

import queue
import threading
import time


class GuardedCounter:
    """Every access to `hits` takes the one owning lock."""

    def __init__(self):
        self.hits = {}
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self.scrape_loop, daemon=True)
        self._thread.start()

    def scrape_loop(self):
        while True:
            with self._lock:
                snapshot = dict(self.hits)
            print(snapshot)

    def record(self, name):
        with self._lock:
            self.hits[name] = 1


class GuardedRefCounter:
    """The read-modify-write happens inside the lock: no lost updates."""

    def __init__(self):
        self.refs = 0
        self._lock = threading.Lock()
        self._worker = threading.Thread(target=self.drain, daemon=True)

    def drain(self):
        while True:
            with self._lock:
                live = self.refs
            if not live:
                return
            time.sleep(0.01)

    def retain(self):
        with self._lock:
            self.refs += 1


class OneLock:
    """One guard for the shared list, one global acquisition order."""

    def __init__(self):
        self.items = []
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self._loop = threading.Thread(target=self.producer, daemon=True)

    def producer(self):
        with self._lock_a:
            self.items.append(1)
        with self._lock_a:
            with self._lock_b:
                self.items.append(2)

    def consumer(self):
        with self._lock_a:
            self.items.pop()
        with self._lock_a:
            with self._lock_b:  # same A-then-B order as producer
                self.items.clear()


class FastCritical:
    """Blocking work happens outside the critical section; the lock only
    covers the shared mutation."""

    def __init__(self):
        self.pending = []
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._t = threading.Thread(target=self.flush_loop, daemon=True)

    def flush_loop(self):
        while True:
            item = self._q.get()  # block outside the lock
            with self._lock:
                self.pending.append(item)

    def flush_now(self):
        time.sleep(0.5)  # simulate slow work with no lock held
        with self._lock:
            self.pending.clear()
