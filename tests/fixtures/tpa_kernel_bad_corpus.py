"""Seeded BAD corpus for the TPA300 kernel verifier (tests/test_kernel_analysis.py).

Every entry here traces fine and stays in-bounds / under budget — the
point is that each kernel carries exactly one LINT defect (TPA301-305),
plus one module-level pallas_call that no entry covers (TPA300). No
conformance violations: the corpus must survive ``--update-baseline``.
The good twin is tpa_kernel_good_corpus.py.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_ARB = pltpu.TPUCompilerParams(dimension_semantics=("arbitrary",))


# -- TPA301: bf16 accumulator scratch (init/flush discipline is correct) ----
def _acc_bf16_kernel(x_ref, o_ref, acc_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += x_ref[...].astype(jnp.bfloat16)

    @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
    def _fin():
        o_ref[...] = acc_ref[...].astype(jnp.float32)


def entry_acc_bf16():
    def fn(x):
        return pl.pallas_call(
            _acc_bf16_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            scratch_shapes=[pltpu.VMEM((8, 128), jnp.bfloat16)],
            compiler_params=_ARB,
            interpret=True,
        )(x)

    return fn, (jax.ShapeDtypeStruct((16, 128), jnp.float32),)


# -- TPA302: fp32 accumulator with NO init write at all ---------------------
def _no_init_kernel(x_ref, o_ref, acc_ref):
    acc_ref[...] += x_ref[...]

    @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
    def _fin():
        o_ref[...] = acc_ref[...]


def entry_no_init():
    def fn(x):
        return pl.pallas_call(
            _no_init_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
            compiler_params=_ARB,
            interpret=True,
        )(x)

    return fn, (jax.ShapeDtypeStruct((16, 128), jnp.float32),)


# -- TPA303: exp of masked scores without a _MASK_GUARD clamp ---------------
def _masked_exp_kernel(x_ref, m_ref, o_ref):
    s = jnp.where(m_ref[...] > 0, x_ref[...], -1e30)
    o_ref[...] = jnp.exp(s - 1.0)


def entry_masked_exp():
    def fn(x, m):
        return pl.pallas_call(
            _masked_exp_kernel,
            grid=(2,),
            in_specs=[
                pl.BlockSpec((8, 128), lambda i: (i, 0)),
                pl.BlockSpec((8, 128), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
            interpret=True,
        )(x, m)

    return fn, (
        jax.ShapeDtypeStruct((16, 128), jnp.float32),
        jax.ShapeDtypeStruct((16, 128), jnp.int32),
    )


# -- TPA304: lane dim neither 128-aligned nor the full array dim ------------
def _misaligned_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def entry_misaligned():
    def fn(x):
        return pl.pallas_call(
            _misaligned_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((8, 100), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 100), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((16, 200), jnp.float32),
            interpret=True,
        )(x)

    return fn, (jax.ShapeDtypeStruct((16, 200), jnp.float32),)


# -- TPA305: RNG (threefry) inside the kernel body --------------------------
def _rng_kernel(x_ref, o_ref):
    seed = x_ref[0, 0].astype(jnp.uint32)
    key = jax.random.PRNGKey(seed)
    noise = jax.random.uniform(key, x_ref.shape, jnp.float32)
    o_ref[...] = x_ref[...] + noise


def entry_rng():
    def fn(x):
        return pl.pallas_call(
            _rng_kernel,
            grid=(1,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            interpret=True,
        )(x)

    return fn, (jax.ShapeDtypeStruct((8, 128), jnp.float32),)


# -- TPA300: a pallas_call no entry exercises -------------------------------
def orphan_kernel_caller(x):
    def _orphan_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    return pl.pallas_call(
        _orphan_kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        interpret=True,
    )(x)


ANALYSIS_KERNEL_ENTRIES = {
    "acc_bf16": entry_acc_bf16,
    "no_init": entry_no_init,
    "masked_exp": entry_masked_exp,
    "misaligned": entry_misaligned,
    "rng": entry_rng,
}
