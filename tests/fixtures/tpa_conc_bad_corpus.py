"""Seeded KNOWN-BAD corpus for the TPA100-series concurrency rules — one
violation per rule. Parsed by AST only, never imported/executed; `python -m
transformer_tpu.analysis concurrency --paths
tests/fixtures/tpa_conc_bad_corpus.py` must exit NON-zero
(tests/test_analysis.py pins exactly which codes fire). The twin file
``tpa_conc_good_corpus.py`` holds the corrected versions and must pass."""

import queue
import threading
import time


class UnguardedCounter:
    """TPA101: the scrape thread and the recorder share `hits` with no lock
    around the recorder's write."""

    def __init__(self):
        self.hits = {}
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self.scrape_loop, daemon=True)
        self._thread.start()

    def scrape_loop(self):
        while True:
            with self._lock:
                snapshot = dict(self.hits)
            print(snapshot)

    def record(self, name):
        self.hits[name] = 1  # TPA101: unguarded write to lock-guarded state


class RefCounter:
    """TPA104: a non-atomic read-modify-write on a shared refcount."""

    def __init__(self):
        self.refs = 0
        self._worker = threading.Thread(target=self.drain, daemon=True)

    def drain(self):
        while self.refs:
            time.sleep(0.01)

    def retain(self):
        self.refs += 1  # TPA104: two threads can both read the old value


class TwoLocks:
    """TPA102 + TPA103: inconsistent guards and a lock-order cycle."""

    def __init__(self):
        self.items = []
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self._loop = threading.Thread(target=self.producer, daemon=True)

    def producer(self):
        with self._lock_a:
            self.items.append(1)  # guarded by _lock_a ...
        with self._lock_a:
            with self._lock_b:  # ... and A-then-B here ...
                self.items.append(2)

    def consumer(self):
        with self._lock_b:
            self.items.pop()  # TPA102: ... but by _lock_b here
        with self._lock_b:
            with self._lock_a:  # TPA103: B-then-A closes the cycle
                self.items.clear()


class SlowCritical:
    """TPA105: blocking work inside the critical section."""

    def __init__(self):
        self.pending = []
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._t = threading.Thread(target=self.flush_loop, daemon=True)

    def flush_loop(self):
        while True:
            with self._lock:
                item = self._q.get()  # TPA105: queue.get() under the lock
                self.pending.append(item)

    def flush_now(self):
        with self._lock:
            time.sleep(0.5)  # TPA105: sleep while peers contend
            self.pending.clear()
