"""Seeded KNOWN-BAD corpus for the analysis lint rules — one violation per
rule. Parsed by AST only, never imported/executed; `python -m
transformer_tpu.analysis rules --paths tests/fixtures/tpa_bad_corpus.py`
must exit NON-zero (tests/test_analysis.py pins exactly which codes fire).
The twin file ``tpa_good_corpus.py`` holds the corrected versions and must
lint clean."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_CALL_STATS = {}  # mutable module state


@partial(jax.jit, static_argnames=("n",))
def branch_on_traced(x, n):
    if x > 0:  # TPA001: x is traced; this either raises or bakes one branch
        return x * n
    return x


@jax.jit
def numpy_on_tracer(x):
    total = np.sum(x)  # TPA002: numpy materializes the tracer
    return x / total


@jax.jit
def reads_mutable_state(x):
    scale = _CALL_STATS["scale"]  # TPA003: captured at trace time, silently stale
    return x * scale


@partial(jax.jit, static_argnames=("cfg", "max_len"))
def stale_static_name(x, cfg, length):  # TPA004: 'max_len' is not a parameter
    return x[:length]


@partial(jax.jit, donate_argnums=(0,))
def update_buffer(buf, delta):
    return buf + delta


def donated_reuse(buf, delta):
    new = update_buffer(buf, delta)
    return buf + new  # TPA005: buf was donated — its buffer is invalidated


def swallow_everything(path):
    try:
        with open(path) as f:
            return f.read()
    except Exception:  # TPA006: swallows unrelated failures in library code
        return None


def hot_retry(q):
    while True:
        try:
            return q.get_nowait()
        except KeyError:  # TPA007: retries forever with no backoff or bound
            continue
