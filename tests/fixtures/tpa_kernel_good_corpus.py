"""GOOD twin of tpa_kernel_bad_corpus.py — same kernels with the defects
fixed; the verifier must report ZERO findings and ZERO violations here."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_ARB = pltpu.TPUCompilerParams(dimension_semantics=("arbitrary",))


# -- twin of acc_bf16: accumulator widened to fp32 --------------------------
def _acc_f32_kernel(x_ref, o_ref, acc_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += x_ref[...]

    @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
    def _fin():
        o_ref[...] = acc_ref[...]


def entry_acc_f32():
    def fn(x):
        return pl.pallas_call(
            _acc_f32_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
            compiler_params=_ARB,
            interpret=True,
        )(x)

    return fn, (jax.ShapeDtypeStruct((16, 128), jnp.float32),)


# -- twin of no_init: first-grid-step @pl.when init -------------------------
def _init_kernel(x_ref, o_ref, acc_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += x_ref[...]

    @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
    def _fin():
        o_ref[...] = acc_ref[...]


def entry_init():
    def fn(x):
        return pl.pallas_call(
            _init_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
            compiler_params=_ARB,
            interpret=True,
        )(x)

    return fn, (jax.ShapeDtypeStruct((16, 128), jnp.float32),)


# -- twin of masked_exp: guard clamp around the exp -------------------------
def _guarded_exp_kernel(x_ref, m_ref, o_ref):
    s = jnp.where(m_ref[...] > 0, x_ref[...], -1e30)
    o_ref[...] = jnp.where(s > -1e29, jnp.exp(s - 1.0), 0.0)


def entry_guarded_exp():
    def fn(x, m):
        return pl.pallas_call(
            _guarded_exp_kernel,
            grid=(2,),
            in_specs=[
                pl.BlockSpec((8, 128), lambda i: (i, 0)),
                pl.BlockSpec((8, 128), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
            interpret=True,
        )(x, m)

    return fn, (
        jax.ShapeDtypeStruct((16, 128), jnp.float32),
        jax.ShapeDtypeStruct((16, 128), jnp.int32),
    )


# -- twin of misaligned: lane dim padded up to the native 128 ---------------
def _aligned_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def entry_aligned():
    def fn(x):
        return pl.pallas_call(
            _aligned_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((16, 128), jnp.float32),
            interpret=True,
        )(x)

    return fn, (jax.ShapeDtypeStruct((16, 128), jnp.float32),)


# -- twin of rng: noise generated OUTSIDE the kernel ------------------------
def _add_kernel(x_ref, n_ref, o_ref):
    o_ref[...] = x_ref[...] + n_ref[...]


def entry_noise_outside():
    def fn(x, noise):
        return pl.pallas_call(
            _add_kernel,
            grid=(1,),
            in_specs=[
                pl.BlockSpec((8, 128), lambda i: (0, 0)),
                pl.BlockSpec((8, 128), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            interpret=True,
        )(x, noise)

    return fn, (
        jax.ShapeDtypeStruct((8, 128), jnp.float32),
        jax.ShapeDtypeStruct((8, 128), jnp.float32),
    )


ANALYSIS_KERNEL_ENTRIES = {
    "acc_f32": entry_acc_f32,
    "init": entry_init,
    "guarded_exp": entry_guarded_exp,
    "aligned": entry_aligned,
    "noise_outside": entry_noise_outside,
}
