"""Seeded known-BAD sharding corpus: every TPA20x rule must fire at least
once when the CLI lints this file (tests/test_costs.py pins it, alongside
the known-good twin that must stay silent). Never imported — parsed only."""

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

DEVICES = jax.devices()

# Declares the axis vocabulary for this corpus: ("data", "model").
MESH = Mesh(DEVICES, ("data", "model"))


def train_step(state, batch):
    return state


def update(state, grads):
    return state


# TPA201: in_shardings pinned, out_shardings left to GSPMD propagation.
sharded_step = jax.jit(train_step, in_shardings=(P("data"), P("data")))

# TPA202: "modle" is a typo — not in the declared ("data", "model") mesh.
ACT_SPEC = P("modle", None)

# TPA203: argument 0 is donated but re-laid-out data -> model; the donation
# silently degrades to a copy plus a reshard.
donating_step = jax.jit(
    update,
    donate_argnums=(0,),
    in_shardings=(P("data"), P(None)),
    out_shardings=(P("model"),),
)


# TPA204: a collective inside the serving hot loop (_pool_* idiom).
@jax.jit
def _pool_step(params, caches, toks):
    logits = jnp.ones((toks.shape[0], 8))
    return jax.lax.psum(logits, "model")


# TPA205: a large-parameter path (embedding table) fully replicated.
PARTITION_RULES = [
    (r"embedding/table$", P(None, None)),
]
