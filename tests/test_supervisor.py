"""Self-healing fleet (``serve/supervisor.py`` + ``serve/standby.py``):
supervised replica respawn with survivor cache warm-up, crash-loop budget
exhaustion, SLO-burn-driven autoscaling, the router-tier fault points, and
warm-standby router takeover with exactly-once answers across the cutover."""

import io
import json
import os
import signal
import socket
import time

import pytest

from transformer_tpu.obs import EventLog, Telemetry
from transformer_tpu.serve.router import ReplicaLink, ReplicaProcess, Router
from transformer_tpu.serve.supervisor import FleetScaler, Supervisor

# The deterministic test-model bootstrap (tests/test_router.py): every
# process building this spec gets bit-identical params and vocab, so
# byte-parity assertions hold across process boundaries AND respawns.
SPEC = {
    "config": {
        "num_layers": 1, "d_model": 16, "num_heads": 2, "dff": 32,
        "max_position": 32, "decoder_only": True, "tie_output": True,
        "dtype": "float32", "dropout_rate": 0.0,
    },
    "seed": 0,
    "corpus": ["ab cd ef gh ij kl mn"] * 3,
    "target_vocab_size": 300,
}
PROMPT_A = "ab cd ef gh ij"


@pytest.fixture(scope="module")
def lm():
    from transformer_tpu.serve.replica import build_model_from_spec

    return build_model_from_spec(SPEC)


@pytest.fixture(scope="module")
def spec_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("supervisor") / "spec.json"
    path.write_text(json.dumps(SPEC))
    return str(path)


def _reference(lm, reqs):
    from transformer_tpu.serve import ContinuousScheduler

    params, cfg, tok = lm
    return ContinuousScheduler(params, cfg, tok, num_slots=2).run(
        [dict(r) for r in reqs]
    )


def _events(buf: io.StringIO) -> list:
    return [json.loads(line) for line in buf.getvalue().splitlines()]


# --------------------------------------------------------------------------
# the acceptance drill: SIGKILL a replica, the fleet heals back to N


def test_sigkill_heal_soak(lm, spec_file, tmp_path):
    """SIGKILL one of two replicas under a Supervisor: the fleet heals
    back to N — the replacement re-bootstraps from the same --model_spec
    under its old rendezvous name, warms its PrefixCache from the
    survivor, and serves affine traffic again — with zero accepted
    requests lost and answers byte-identical to a single scheduler."""
    params, cfg, tok = lm
    worker = [
        "--model_spec", spec_file, "--serve_slots", "2",
        "--heartbeat_ms", "50", "--prefix_cache_mb", "8",
        "--prefix_block", "4",
    ]
    links = [ReplicaProcess.spawn(i, list(worker)) for i in range(2)]

    def spawn(index, name, role):
        return ReplicaProcess.spawn(index, list(worker), role=role, name=name)

    sup = Supervisor(spawn, backoff_ms=50.0)
    buf = io.StringIO()
    telemetry = Telemetry(events=EventLog(buf))
    router = Router(
        links, encode=tok.encode, bos_id=tok.bos_id, affinity_block=4,
        heartbeat_timeout_s=10.0, telemetry=telemetry, supervisor=sup,
    )
    for link in links:
        link.start_reader(router.inbox)
    reqs = [{"prompt": PROMPT_A, "max_new": 6}] * 6
    want = _reference(lm, reqs)
    deadline = time.time() + 110
    try:
        out = router.run([dict(r) for r in reqs])
        assert [o.get("continuation") for o in out] == [
            w["continuation"] for w in want
        ]
        # PROMPT_A's affine replica owns the warm cache — kill it.
        victim = max(router.links, key=lambda l: l.answered)
        os.kill(victim.pid(), signal.SIGKILL)
        while time.time() < deadline:
            router.pump()
            healthy = [
                l for l in router.links
                if not l.dead and not l.warming and not l.draining
            ]
            if len(healthy) == 2 and sup.stats["respawns"] == 1:
                break
        assert sup.stats["respawns"] == 1, sup.stats
        assert sup.stats["gave_up"] == 0
        # The replacement's PrefixCache was warmed from the survivor over
        # the export/inject wire format before it took traffic.
        assert sup.stats["warmed_tokens"] > 0, sup.stats
        assert sup.heal_times and sup.heal_times[0] > 0
        # Same traffic again: byte parity holds through the respawn, and
        # the replacement (old name, old rendezvous keys) serves it.
        out2 = router.run([dict(r) for r in reqs])
        assert [o.get("continuation") for o in out2] == [
            w["continuation"] for w in want
        ]
        replacement = router.links[victim.index]
        assert replacement is not victim
        assert replacement.name == victim.name
        assert replacement.answered > 0, "replacement took no traffic"
    finally:
        router.shutdown()
        telemetry.maybe_flush(force=True)
    events = _events(buf)
    spawns = [e for e in events if e.get("kind") == "route.spawn"]
    assert len(spawns) == 1
    assert spawns[0]["replica"] == victim.name
    assert spawns[0]["heal_s"] > 0
    assert spawns[0]["warmed_tokens"] == sup.stats["warmed_tokens"]
    # The fleet gauge recovered to N.
    assert telemetry.registry.gauge(
        "route_fleet_size", ""
    ).value == 2
    # The merged report's fleet section renders the heal.
    from transformer_tpu.obs.__main__ import render_text, summarize_events

    fleet = summarize_events(events)["fleet"]
    assert fleet["respawns"] == 1
    assert fleet["time_to_heal_s"]["count"] == 1
    assert fleet["warmed_tokens"] > 0
    assert "fleet:" in render_text(summarize_events(events))


# --------------------------------------------------------------------------
# the acceptance drill: kill the primary router, the standby adopts


def test_router_ha_takeover_exactly_once(lm, spec_file, tmp_path):
    """Kill the primary router mid-stream: the warm standby tails its
    journal, detects heartbeat silence, adopts the inflight table, and
    every in-flight request is answered exactly once — recovered answers
    replayed from replica re-delivery caches, the rest re-owned or
    re-dispatched. A second takeover attempt at the same epoch is
    rejected (the split-brain guard)."""
    from transformer_tpu.serve.standby import Standby

    params, cfg, tok = lm
    worker = [
        "--model_spec", spec_file, "--serve_slots", "2",
        "--heartbeat_ms", "50", "--ha",
    ]
    links = [ReplicaProcess.spawn(i, list(worker)) for i in range(2)]
    primary_log = str(tmp_path / "primary.jsonl")
    telemetry = Telemetry(events=EventLog(primary_log))
    router = Router(
        links, encode=tok.encode, bos_id=tok.bos_id, affinity_block=4,
        heartbeat_timeout_s=10.0, telemetry=telemetry, ha=True,
        ha_heartbeat_s=0.1,
    )
    for link in links:
        link.start_reader(router.inbox)
    reqs = [{"prompt": PROMPT_A, "max_new": 20} for _ in range(8)]
    want = _reference(lm, reqs)
    new_router = None
    try:
        for r in reqs:
            router.submit(dict(r))
        delivered = []
        deadline = time.time() + 110
        while len(delivered) < 2 and time.time() < deadline:
            router.pump()
            delivered.extend(router.drain_ready())
        assert len(router._inflight) + len(router._pending) > 0, (
            "nothing in flight at the cutover — the drill is vacuous"
        )
        telemetry.maybe_flush(force=True)
        # The primary "dies" here: it stops pumping forever. Its pipes
        # stay open — the replicas' epoch guard handles any stragglers.
        standby = Standby(
            primary_log, takeover_after_s=0.5,
            encode=tok.encode, bos_id=tok.bos_id,
            telemetry=Telemetry(
                events=EventLog(str(tmp_path / "standby.jsonl"))
            ),
        )
        new_router = standby.run_until_takeover(poll_s=0.05, timeout=60)
        assert new_router.epoch == 2
        assert len(new_router.links) == 2
        assert (
            standby.stats["recovered_answers"]
            + standby.stats["reowned_inflight"]
            + standby.stats["redispatched"]
        ) > 0, standby.stats
        while new_router.busy and time.time() < deadline:
            new_router.pump()
            delivered.extend(new_router.drain_ready())
        delivered.extend(new_router.drain_ready())
        # Exactly once across the cutover: all 8, no duplicates, byte-
        # identical to the single-scheduler reference.
        assert len(delivered) == len(reqs)
        assert [d.get("continuation") for d in delivered] == [
            w["continuation"] for w in want
        ]
        # Split-brain guard: a takeover with a non-higher epoch is
        # rejected by the replica's control socket.
        port = next(
            l.control_port for l in new_router.links
            if l.control_port is not None
        )
        with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
            wf = s.makefile("w", encoding="utf-8", buffering=1)
            rf = s.makefile("r", encoding="utf-8")
            wf.write(json.dumps(
                {"type": "takeover", "epoch": 2, "inflight": []}
            ) + "\n")
            wf.flush()
            reply = json.loads(rf.readline())
        assert reply["type"] == "rejected" and reply["epoch"] == 2
    finally:
        if new_router is not None:
            new_router.shutdown()
        else:
            router.shutdown()
    # The merged logs reconstruct the cutover: both routers as sources,
    # one route.takeover event, and the fleet summary section reports it.
    from transformer_tpu.obs.__main__ import summarize_events
    from transformer_tpu.obs.merge import merge_events

    events, info = merge_events(
        [primary_log, str(tmp_path / "standby.jsonl")]
    )
    assert set(info["sources"]) == {"primary.jsonl", "standby.jsonl"}
    takeovers = [e for e in events if e.get("kind") == "route.takeover"]
    assert len(takeovers) == 1
    assert takeovers[0]["epoch"] == 2
    assert takeovers[0]["source"] == "standby.jsonl"
    fleet = summarize_events(events)["fleet"]
    assert fleet["takeovers"] == 1
    assert fleet["takeover"]["epoch"] == 2


# --------------------------------------------------------------------------
# crash-loop handling (fake links: fast and deterministic)


class _FakeLink(ReplicaLink):
    def __init__(self, index, name, answer=True):
        super().__init__(index, name)
        self.sent = []
        self.answer_back = answer
        self.ok = True
        self.router = None

    def alive(self):
        return self.ok

    def kill(self):
        self.ok = False

    def send(self, msg):
        if not self.ok:
            raise BrokenPipeError("dead")
        self.sent.append(msg)
        if msg.get("type") == "req" and self.answer_back:
            self.router.inbox.put((self.index, {
                "type": "answer", "rid": msg["rid"],
                "resp": {"continuation": self.name},
            }))
        elif msg.get("type") == "export_state":
            # Survivor warm-up export: nothing cached — the supervisor
            # admits the replacement cold.
            self.router.inbox.put(
                (self.index, {"type": "prefix_state", "entries": []})
            )


def _fake_fleet(n=2, *, supervisor=None, scaler=None, slos=None,
                telemetry=None, **kw):
    links = [_FakeLink(i, f"f{i}") for i in range(n)]
    router = Router(
        links, encode=None, supervisor=supervisor, scaler=scaler,
        slos=slos, telemetry=telemetry, **kw,
    )
    for link in links:
        link.router = router
    return router, links


def test_crash_loop_exhausts_budget_and_serves_n_minus_1():
    """A replica whose bootstrap always fails must exhaust its restart
    budget, trip the breaker, and leave the fleet serving at N-1 with
    zero lost requests — not spin."""
    clk = [0.0]
    spawn_calls = []

    def spawn(index, name, role):
        spawn_calls.append(index)
        raise RuntimeError("bootstrap faults every time")

    sup = Supervisor(
        spawn, max_restarts=3, restart_window_s=1000.0, backoff_ms=0.0,
        clock=lambda: clk[0],
    )
    buf = io.StringIO()
    telemetry = Telemetry(events=EventLog(buf))
    router, links = _fake_fleet(2, supervisor=sup, telemetry=telemetry)
    links[0].ok = False
    router.inbox.put((0, {"type": "exit"}))
    router.pump(timeout=0)
    assert links[0].dead
    for _ in range(20):  # far more polls than the budget allows attempts
        clk[0] += 1.0
        router.pump(timeout=0)
    assert len(spawn_calls) == 3, f"budget not honored: {spawn_calls}"
    assert sup.stats["gave_up"] == 1
    assert sup._slots[0].phase == "gave_up"
    assert router.breakers[0].state == "open"
    # The fleet serves at N-1, losing nothing.
    out = router.run([{"prompt": "p"} for _ in range(4)])
    assert [o["continuation"] for o in out] == ["f1"] * 4
    events = _events(buf)
    gave_up = [e for e in events
               if e.get("kind") == "route.spawn" and e.get("gave_up")]
    assert len(gave_up) == 1 and gave_up[0]["attempts"] == 3


def test_respawn_storm_via_fault_plane():
    """--fault_spec route.spawn episodes drill crash loops
    deterministically: the first two attempts fault, the third succeeds,
    and the replacement is admitted (warm-up skipped: no survivor
    entries) — the same episode replays identically from the spec."""
    from transformer_tpu.serve.resilience import FaultPlane, install

    clk = [0.0]
    spawned = []

    def spawn(index, name, role):
        link = _FakeLink(index, name)
        link.router = router
        spawned.append(link)
        router.inbox.put((index, {"type": "ready", "replica": name}))
        return link

    sup = Supervisor(
        spawn, max_restarts=5, backoff_ms=0.0, clock=lambda: clk[0],
    )
    router, links = _fake_fleet(2, supervisor=sup)
    install(FaultPlane.parse("route.spawn:p=1,times=2,seed=7"))
    try:
        links[0].ok = False
        router.inbox.put((0, {"type": "exit"}))
        router.pump(timeout=0)
        for _ in range(10):
            clk[0] += 1.0
            router.pump(timeout=0)
            if sup.stats["respawns"] == 1:
                break
        assert sup.stats["spawn_failures"] == 2
        assert sup.stats["spawn_attempts"] == 3
        assert sup.stats["respawns"] == 1
        assert sup._slots[0].phase == "up"
        assert router.links[0] is spawned[0]
        assert not router.links[0].dead
    finally:
        install(None)


def test_respawn_refuses_wrong_mesh_shape():
    """A respawned replica that bootstraps at the WRONG mesh shape (stale
    binary, hand-edited argv) is refused loudly — route.mesh_mismatch
    event, killed before warm-up or traffic, one budgeted failure — and
    the next (correct-shape) respawn is admitted. Rides the SIGKILL-heal
    machinery with fake links so the drill is deterministic."""
    clk = [0.0]
    spawned = []

    def spawn(index, name, role):
        link = _FakeLink(index, name)
        link.router = router
        spawned.append(link)
        # First replacement announces data=4 (wrong), the second data=2.
        mesh = "data=4" if len(spawned) == 1 else "data=2"
        router.inbox.put(
            (index, {"type": "ready", "replica": name, "mesh": mesh})
        )
        return link

    sup = Supervisor(
        spawn, max_restarts=5, backoff_ms=0.0, clock=lambda: clk[0],
        expected_mesh="data=2",
    )
    buf = io.StringIO()
    telemetry = Telemetry(events=EventLog(buf))
    router, links = _fake_fleet(2, supervisor=sup, telemetry=telemetry)
    links[0].ok = False
    router.inbox.put((0, {"type": "exit"}))
    router.pump(timeout=0)
    for _ in range(10):
        clk[0] += 1.0
        router.pump(timeout=0)
        if sup.stats["respawns"] == 1:
            break
    # The wrong-shape link was killed without admission; the failure was
    # budgeted (not free) and the correct-shape retry healed the fleet.
    assert not spawned[0].ok and spawned[0].sent == []
    assert spawned[1].ok and router.links[0] is spawned[1]
    assert router.links[0].mesh == "data=2"
    assert sup.stats["spawn_failures"] == 1
    assert sup.stats["respawns"] == 1
    assert sup._slots[0].phase == "up"
    mm = [e for e in _events(buf) if e.get("kind") == "route.mesh_mismatch"]
    assert len(mm) == 1
    assert mm[0]["expected"] == "data=2" and mm[0]["got"] == "data=4"


def test_route_hb_fault_swallows_heartbeats():
    """The route.hb fault point drops replica heartbeats at the router —
    heartbeat-loss storms without real stalls."""
    from transformer_tpu.serve.resilience import FaultPlane, install

    router, links = _fake_fleet(1)
    install(FaultPlane.parse("route.hb:p=1,times=2,seed=3"))
    try:
        for _ in range(3):
            router.inbox.put(
                (0, {"type": "hb", "backlog": 0, "free": 2, "active": 0})
            )
        router.pump(timeout=0)
        assert router.stats["dropped_heartbeats"] == 2
        assert links[0].last_hb is not None  # the third one landed
    finally:
        install(None)


# --------------------------------------------------------------------------
# SLO-driven autoscaling (fake links + scripted burn rates)


class _ScriptedSLO:
    """Duck-typed SLOEngine: maybe_evaluate returns whatever burn the
    test scripts next (None = no evaluation this pump)."""

    def __init__(self):
        self.next_burn = None

    def maybe_evaluate(self):
        if self.next_burn is None:
            return None
        return {
            "ttft_p95": {
                "burn_rate": self.next_burn,
                "breached": self.next_burn > 1.0,
                "windows": {"60s": {"burn_rate": self.next_burn}},
            }
        }

    def record(self, span):
        pass


def test_autoscale_burn_spawns_idle_drains():
    """Sustained ttft_p95 burn > 1 spawns a replica (route.scale up with
    the evidence window); sustained idleness drains the youngest back
    down (drain -> retire), bounded by min_replicas."""
    clk = [0.0]

    def spawn(index, name, role):
        link = _FakeLink(index, name)
        link.router = router
        spawned.append(link)
        router.inbox.put((index, {"type": "ready", "replica": name}))
        return link

    spawned = []
    sup = Supervisor(spawn, backoff_ms=0.0, clock=lambda: clk[0])
    scaler = FleetScaler(
        sustain_s=2.0, idle_s=3.0, max_replicas=2, min_replicas=1,
        cooldown_s=0.0, clock=lambda: clk[0],
    )
    slo = _ScriptedSLO()
    buf = io.StringIO()
    telemetry = Telemetry(events=EventLog(buf))
    router, links = _fake_fleet(
        1, supervisor=sup, scaler=scaler, slos=slo, telemetry=telemetry,
    )
    # ---- burn > 1, sustained: one scale-up (and only one — cap = 2) ----
    slo.next_burn = 2.5
    router.pump(timeout=0)          # starts the sustain clock
    clk[0] += 2.5
    router.pump(timeout=0)          # sustained past sustain_s: spawn
    assert len(spawned) == 1
    assert scaler.stats["scale_up"] == 1
    router.pump(timeout=0)          # "ready" admits the newcomer (cold)
    assert sup._slots[1].phase == "up"
    clk[0] += 5.0
    router.pump(timeout=0)
    assert scaler.stats["scale_up"] == 1, "double-spawned at max_replicas"
    healthy = [l for l in router.links if not l.dead and not l.warming]
    assert len(healthy) == 2
    # ---- burn at 0, fleet idle: drain the youngest back down ----------
    slo.next_burn = 0.0
    router.pump(timeout=0)          # starts the idle clock
    clk[0] += 3.5
    router.pump(timeout=0)          # sustained idle: retire youngest
    router.pump(timeout=0)          # reap: no in-flight work -> shutdown
    assert scaler.stats["scale_down"] == 1
    assert router.links[1].retired
    assert sup.stats["retired"] == 1
    clk[0] += 10.0
    router.pump(timeout=0)
    assert scaler.stats["scale_down"] == 1, "drained below min_replicas"
    # A retired link's EOF is not a failure — and it is never respawned.
    router.inbox.put((1, {"type": "exit"}))
    router.pump(timeout=0)
    assert router.stats["failovers"] == 0
    clk[0] += 10.0
    router.pump(timeout=0)
    assert len(spawned) == 1
    # Traffic still answers on the remaining replica.
    out = router.run([{"prompt": "p"}] * 3)
    assert [o["continuation"] for o in out] == ["f0"] * 3
    events = _events(buf)
    scales = [e for e in events if e.get("kind") == "route.scale"]
    assert [e["direction"] for e in scales] == ["up", "down"]
    assert scales[0]["signal"] == "ttft_p95"
    assert scales[0]["burn_rate"] == 2.5
    assert scales[0]["evidence"], "scale decision carried no evidence"
    assert [e["kind"] for e in events].count("route.retire") == 1


def test_router_answer_funnel_feeds_slo_engine():
    """The replica's per-answer "slo" side channel lands in the router's
    own SLO engine through the answer funnel — the autoscaling signal."""
    recorded = []

    class _Capture(_ScriptedSLO):
        def record(self, span):
            recorded.append(span)

    router, links = _fake_fleet(1, slos=_Capture())
    links[0].answer_back = False
    order = router.submit({"prompt": "p"})
    router.pump(timeout=0)
    router.inbox.put((0, {
        "type": "answer", "rid": order,
        "resp": {"continuation": "x"},
        "slo": {"ttft_s": 0.25, "total_s": 0.5},
    }))
    router.pump(timeout=0)
    assert router.drain_ready() == [{"continuation": "x"}]
    assert len(recorded) == 1
    assert recorded[0]["ttft_s"] == 0.25
    assert recorded[0]["order"] == order


def test_scheduler_span_tap_carries_latency(lm):
    """ContinuousScheduler's span_tap (the replica worker's side channel)
    hands the answer-boundary span — ttft/total/order — to host code
    without needing a telemetry bundle."""
    from transformer_tpu.serve import ContinuousScheduler

    params, cfg, tok = lm
    taps = []
    sched = ContinuousScheduler(
        params, cfg, tok, num_slots=1, span_tap=taps.append,
    )
    out = sched.run([{"prompt": PROMPT_A, "max_new": 3}])
    assert "continuation" in out[0]
    assert len(taps) == 1
    assert taps[0]["order"] == 0
    assert taps[0]["total_s"] > 0
    assert taps[0]["ttft_s"] > 0


# --------------------------------------------------------------------------
# standby internals (pure units: the tail, the floor, the stand-down)


def test_standby_tail_reconstruction(tmp_path):
    from transformer_tpu.serve.standby import Standby

    log = tmp_path / "primary.jsonl"
    clk = [100.0]
    standby = Standby(
        str(log), takeover_after_s=2.0, clock=lambda: clk[0],
    )
    lines = [
        {"kind": "route.intake", "order": 0, "req": {"prompt": "a"},
         "traceparent": None, "ts": 1.0},
        {"kind": "route.intake", "order": 1, "resp": {"error": "x",
                                                      "code": "routing"},
         "ts": 1.0},
        {"kind": "route.hb", "epoch": 3, "ports": {"replica0": 1234},
         "ts": 1.1},
        {"kind": "route.answered", "first": 0, "upto": 0, "n": 1,
         "ts": 1.2},
    ]
    log.write_text("".join(json.dumps(e) + "\n" for e in lines))
    assert standby.poll() == 0.0
    assert standby.epoch == 3
    assert standby.ports == {"replica0": 1234}
    assert standby.delivered_upto == 1  # order 0 reached the client
    # Delivered orders are pruned (bounded standby memory); the order
    # clock still resumes past everything ever seen.
    assert set(standby.intake) == {1}
    assert standby.max_order == 1
    # Torn tail line: buffered, not parsed — until its newline arrives.
    with open(log, "a") as f:
        f.write(json.dumps({"kind": "route.intake", "order": 2,
                            "req": {"prompt": "c"}})[:25])
    clk[0] += 1.0
    assert standby.poll() > 0  # heartbeat silence is accruing
    assert 2 not in standby.intake
    assert not standby.primary_dead
    clk[0] += 5.0
    assert standby.primary_dead


def test_standby_merge_prefers_owner_claim(tmp_path, monkeypatch):
    """Every replica reports every asked rid, so an early peer's
    "unknown" must never block the real owner's later "inflight" claim
    (and "done" beats both): the order is re-owned by its owner exactly
    once, not redispatched."""
    from transformer_tpu.serve.standby import Standby

    log = tmp_path / "primary.jsonl"
    events = [
        {"kind": "route.intake", "order": o, "req": {"prompt": "p"},
         "ts": 1.0}
        for o in (5, 6)
    ] + [{
        "kind": "route.hb", "epoch": 1, "ports": {"a": 1, "b": 2},
        "ts": 1.1,
    }]
    log.write_text("".join(json.dumps(e) + "\n" for e in events))
    standby = Standby(str(log))
    standby.poll()

    class _NoopLink(ReplicaLink):
        def start_reader(self, inbox):
            pass

    def _handshake(index, name, port, ask):
        link = _NoopLink(index, name)
        if name == "a":  # handshaked first (sorted), owns nothing
            return link, {"5": "unknown", "6": "unknown"}, {}
        return link, {
            "5": "inflight",
            "6": "done",
        }, {"6": {"type": "answer", "rid": 6, "resp": {"continuation": "x"}}}

    monkeypatch.setattr(standby, "_handshake",
                        lambda *a: _handshake(*a))
    router = standby.adopt()
    assert standby.stats["reowned_inflight"] == 1
    assert standby.stats["recovered_answers"] == 1
    assert standby.stats["redispatched"] == 0
    assert router._inflight[5].replica == 1  # re-owned by its OWNER
    assert router._done[6] == {"continuation": "x"}
    # The order clock resumes past everything ever seen even though the
    # delivered prefix was pruned from the intake table.
    assert router._next_order == 7


def test_adopted_router_rejournals_for_chained_takeover(
    tmp_path, monkeypatch
):
    """Orders adopted via seed_takeover are re-journaled by the new
    primary (intake records + the delivery floor): a SECOND standby
    tailing the adopted router's journal reconstructs the same
    undelivered set — chained takeovers replay from each log alone."""
    from transformer_tpu.serve.standby import Standby

    log = tmp_path / "primary.jsonl"
    events = [
        {"kind": "route.intake", "order": 0, "req": {"prompt": "a"},
         "ts": 1.0},
        {"kind": "route.intake", "order": 1, "req": {"prompt": "b"},
         "ts": 1.0},
        {"kind": "route.intake", "order": 2,
         "resp": {"error": "bad line", "code": "validation"}, "ts": 1.0},
        {"kind": "route.answered", "first": 0, "upto": 0, "n": 1,
         "ts": 1.1},
        {"kind": "route.hb", "epoch": 1, "ports": {"r0": 7}, "ts": 1.2},
    ]
    log.write_text("".join(json.dumps(e) + "\n" for e in events))
    new_log = str(tmp_path / "adopted.jsonl")
    standby = Standby(
        str(log), telemetry=Telemetry(events=EventLog(new_log)),
    )
    standby.poll()

    class _NoopLink(ReplicaLink):
        def start_reader(self, inbox):
            pass

    monkeypatch.setattr(
        standby, "_handshake",
        lambda index, name, port, ask: (
            _NoopLink(index, name), {"1": "inflight"}, {},
        ),
    )
    router = standby.adopt()
    standby._tel.maybe_flush(force=True)
    chained = Standby(new_log)
    chained.poll()
    assert chained.delivered_upto == 1           # the floor survived
    assert set(chained.intake) == {1, 2}         # adopted orders replay
    assert chained.intake[1]["req"] == {"prompt": "b"}
    assert chained.intake[2]["resp"]["code"] == "validation"
    assert chained.max_order == 2
    assert router._inflight[1].replica == 0      # and the adoption held


def test_failed_scale_up_respects_cooldown():
    """A failed spawn_new re-arms the scale-up cooldown: burn is highest
    exactly when fork is most likely to fail, and an unthrottled retry
    would fork a failing subprocess at pump frequency."""
    clk = [100.0]  # past the fresh scaler's initial cooldown window
    calls = []

    def spawn(index, name, role):
        calls.append(clk[0])
        raise RuntimeError("fork fails under pressure")

    sup = Supervisor(spawn, backoff_ms=0.0, clock=lambda: clk[0])
    scaler = FleetScaler(
        sustain_s=1.0, max_replicas=2, cooldown_s=10.0,
        clock=lambda: clk[0],
    )
    slo = _ScriptedSLO()
    router, links = _fake_fleet(
        1, supervisor=sup, scaler=scaler, slos=slo,
    )
    slo.next_burn = 3.0
    router.pump(timeout=0)              # sustain clock starts
    clk[0] += 1.5
    router.pump(timeout=0)              # sustained: one FAILED attempt
    assert len(calls) == 1
    for _ in range(5):                  # pump frequency >> cooldown
        clk[0] += 0.5
        router.pump(timeout=0)
    assert len(calls) == 1, "failed spawn retried inside the cooldown"
    clk[0] += 10.0
    router.pump(timeout=0)              # cooldown over: one more attempt
    assert len(calls) == 2
    assert sup.stats["spawn_failures"] == 2


def test_standby_stands_down_on_higher_epoch(tmp_path, monkeypatch):
    """TakeoverRejected propagates out of adopt(): another standby won
    the fleet and this one must not serve."""
    from transformer_tpu.serve.standby import Standby, TakeoverRejected

    log = tmp_path / "primary.jsonl"
    log.write_text(json.dumps({
        "kind": "route.hb", "epoch": 1, "ports": {"replica0": 9},
        "ts": 1.0,
    }) + "\n")
    standby = Standby(str(log))
    standby.poll()

    def _reject(index, name, port, ask):
        raise TakeoverRejected("epoch 5 owns the fleet")

    monkeypatch.setattr(standby, "_handshake", _reject)
    with pytest.raises(TakeoverRejected):
        standby.adopt()


def test_summarize_fleet_section_shapes():
    from transformer_tpu.obs.__main__ import render_text, summarize_events

    events = [
        {"kind": "route.spawn", "replica": "r0", "heal_s": 1.5,
         "warmed_tokens": 12, "scale_up": False, "ts": 1.0},
        {"kind": "route.spawn", "replica": "r2", "scale_up": True,
         "warmed_tokens": 0, "heal_s": None, "ts": 2.0},
        {"kind": "route.spawn", "replica": "r1", "gave_up": True,
         "attempts": 3, "ts": 3.0},
        {"kind": "route.scale", "direction": "up", "signal": "ttft_p95",
         "burn_rate": 2.0, "fleet_size": 3,
         "evidence": {"60s": {"burn_rate": 2.0}}, "ts": 2.0},
        {"kind": "route.scale", "direction": "down", "signal": "ttft_p95",
         "burn_rate": 0.0, "replica": "r2", "fleet_size": 2, "ts": 4.0},
        {"kind": "route.retire", "replica": "r2", "ts": 4.1},
        {"kind": "route.takeover", "epoch": 2, "adopted": ["r0", "r1"],
         "failed": [], "recovered_answers": 1, "reowned_inflight": 2,
         "redispatched": 0, "delivered_upto": 3, "ts": 5.0},
    ]
    fleet = summarize_events(events)["fleet"]
    assert fleet["respawns"] == 1
    assert fleet["gave_up"] == 1
    assert fleet["warmed_tokens"] == 12
    assert fleet["scale_ups"] == 1 and fleet["scale_downs"] == 1
    assert fleet["retired"] == 1
    assert fleet["takeovers"] == 1
    assert fleet["time_to_heal_s"]["mean"] == 1.5
    assert fleet["final_fleet_size"] == 2
    assert fleet["takeover"]["reowned_inflight"] == 2
    text = render_text(summarize_events(events))
    assert "fleet:" in text and "respawn" in text and "takeover" in text
