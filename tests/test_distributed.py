"""Distributed-engine tests on the virtual 8-device CPU mesh (SURVEY.md §4):
DP/FSDP/TP-sharded training must match the single-device run numerically."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from transformer_tpu.config import MeshConfig, ModelConfig, TrainConfig
from transformer_tpu.parallel import (
    DistributedTrainer,
    create_sharded_state,
    make_mesh,
    make_sharded_steps,
    put_batch,
)
from transformer_tpu.parallel.sharding import param_partition_spec
from transformer_tpu.train import create_train_state, make_train_step

MODEL = ModelConfig(
    num_layers=2, d_model=16, num_heads=4, dff=32,
    input_vocab_size=32, target_vocab_size=32, max_position=32,
    dtype="float32", dropout_rate=0.0,
)
TCFG = TrainConfig(
    batch_size=16, sequence_length=8, epochs=1, warmup_steps=10,
    loss_normalization="tokens",
)


def _batch(key):
    ks, kt = jax.random.split(jax.random.PRNGKey(key))
    src = np.asarray(jax.random.randint(ks, (16, 8), 1, 32), np.int32)
    tgt = np.asarray(jax.random.randint(kt, (16, 8), 1, 32), np.int32)
    return src, tgt


def _single_device_losses(n_steps=4):
    state = create_train_state(jax.random.PRNGKey(0), MODEL, TCFG)
    step = jax.jit(make_train_step(MODEL, TCFG))
    rng = jax.random.PRNGKey(42)
    losses = []
    for i in range(n_steps):
        src, tgt = _batch(i)
        state, m = step(state, src, tgt, rng)
        losses.append(float(m["loss"]))
    return losses, state


def _mesh_losses(mesh_cfg: MeshConfig, n_steps=4):
    mesh = make_mesh(mesh_cfg)
    state, shardings = create_sharded_state(
        jax.random.PRNGKey(0), MODEL, TCFG, mesh
    )
    train_step, _ = make_sharded_steps(mesh, MODEL, TCFG, shardings, donate=False)
    rng = jax.random.PRNGKey(42)
    losses = []
    for i in range(n_steps):
        src, tgt = _batch(i)
        state, m = train_step(
            state, put_batch(src, mesh), put_batch(tgt, mesh), rng
        )
        losses.append(float(m["loss"]))
    return losses, state


class TestMesh:
    def test_make_mesh_shapes(self):
        mesh = make_mesh(MeshConfig(data=2, fsdp=2, model=2, seq=1))
        assert mesh.shape == {
            "data": 2, "fsdp": 2, "model": 2, "seq": 1, "pipe": 1, "expert": 1
        }

    def test_hybrid_dcn_validation(self):
        """Multi-slice meshes (MeshConfig.dcn_data): divisibility and
        granule-count failures must be loud. (The success path needs real
        multi-granule devices: exercised by tests/test_multiprocess.py.)"""
        with pytest.raises(ValueError, match="dcn_data"):
            make_mesh(MeshConfig(data=4, fsdp=2, dcn_data=3))
        with pytest.raises(ValueError, match="granule"):
            # Single-process CPU = one granule; a 2-slice mesh can't build.
            make_mesh(MeshConfig(data=4, fsdp=2, dcn_data=2))

    def test_device_count_mismatch_raises(self):
        with pytest.raises(ValueError):
            make_mesh(MeshConfig(data=3))


class TestPartitionRules:
    def test_rules_cover_all_params(self):
        mesh = make_mesh(MeshConfig(data=2, fsdp=2, model=2, seq=1))
        state = jax.eval_shape(
            lambda r: create_train_state(r, MODEL, TCFG), jax.random.PRNGKey(0)
        )
        specs = jax.tree_util.tree_map_with_path(
            lambda p, l: param_partition_spec(p, l, mesh), state
        )
        flat = jax.tree_util.tree_leaves_with_path(specs)
        # heads axis (4) divides model=2: attention kernels must be sharded
        sharded = [
            (path, spec)
            for path, spec in flat
            if any(s is not None for s in spec)
        ]
        assert len(sharded) > 10  # params + adam mu/nu all covered
        for path, spec in flat:
            s = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
            if s.endswith("query/kernel"):
                assert spec == P("fsdp", "model", None), (s, spec)

    def test_non_divisible_falls_back_replicated(self):
        mesh = make_mesh(MeshConfig(data=1, fsdp=1, model=8, seq=1))
        # num_heads=4 does not divide model=8 -> replicated on that dim
        class Leaf:
            shape = (16, 4, 4)

        spec = param_partition_spec(
            (jax.tree_util.GetAttrKey("query"), jax.tree_util.GetAttrKey("kernel")),
            Leaf(), mesh,
        )
        assert spec == P("fsdp", None, None)


class TestShardedMultistep:
    @pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
    def test_multistep_matches_sequential_on_mesh(self):
        """steps_per_dispatch over a dp×model mesh: one K-step scanned
        dispatch must match K sequential sharded dispatches."""
        from transformer_tpu.parallel import make_sharded_multistep

        K = 3
        mesh = make_mesh(MeshConfig(data=2, fsdp=2, model=2))
        rng = jax.random.PRNGKey(42)

        state_ref, shardings = create_sharded_state(
            jax.random.PRNGKey(0), MODEL, TCFG, mesh
        )
        step, _ = make_sharded_steps(
            mesh, MODEL, TCFG, shardings, donate=False
        )
        sums = {"loss_sum": 0.0, "weight": 0.0, "correct": 0.0}
        for i in range(K):
            src, tgt = _batch(i)
            state_ref, m = step(
                state_ref, put_batch(src, mesh), put_batch(tgt, mesh), rng
            )
            for k in sums:
                sums[k] += float(m[k])

        state_multi, shardings = create_sharded_state(
            jax.random.PRNGKey(0), MODEL, TCFG, mesh
        )
        multi = make_sharded_multistep(
            mesh, MODEL, TCFG, shardings, donate=False
        )
        srcs = np.stack([_batch(i)[0] for i in range(K)])
        tgts = np.stack([_batch(i)[1] for i in range(K)])
        state_multi, mm = multi(
            state_multi, put_batch(srcs, mesh), put_batch(tgts, mesh), rng
        )

        assert int(state_multi.step) == K
        for k in sums:
            np.testing.assert_allclose(float(mm[k]), sums[k], rtol=2e-4, err_msg=k)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
            ),
            state_ref.params, state_multi.params,
        )


@pytest.mark.slow
class TestParity:
    """Sharded runs must reproduce single-device numbers (the SURVEY.md §4
    'DP-sharded loss/grads match single-device' requirement)."""

    @pytest.fixture(scope="class")
    def single(self):
        return _single_device_losses()

    def test_dp8_matches_single(self, single):
        losses, state = _mesh_losses(MeshConfig(data=8))
        np.testing.assert_allclose(losses, single[0], rtol=2e-4)

    def test_fsdp8_matches_single(self, single):
        losses, _ = _mesh_losses(MeshConfig(data=1, fsdp=8))
        np.testing.assert_allclose(losses, single[0], rtol=2e-4)

    def test_tp_matches_single(self, single):
        losses, _ = _mesh_losses(MeshConfig(data=2, fsdp=1, model=4))
        np.testing.assert_allclose(losses, single[0], rtol=2e-4)

    def test_mixed_mesh_matches_single(self, single):
        losses, _ = _mesh_losses(MeshConfig(data=2, fsdp=2, model=2))
        np.testing.assert_allclose(losses, single[0], rtol=2e-4)

    def test_grad_accum_on_mesh_matches_single(self, single):
        """grad_accum_steps composes with data×fsdp sharding: the scan over
        micro-batches reshapes the sharded batch, and losses must still match
        the plain single-device whole-batch run."""
        import dataclasses

        accum_cfg = dataclasses.replace(TCFG, grad_accum_steps=2)
        mesh = make_mesh(MeshConfig(data=4, fsdp=2))
        state, shardings = create_sharded_state(
            jax.random.PRNGKey(0), MODEL, accum_cfg, mesh
        )
        train_step, _ = make_sharded_steps(
            mesh, MODEL, accum_cfg, shardings, donate=False
        )
        rng = jax.random.PRNGKey(42)
        losses = []
        for i in range(4):
            src, tgt = _batch(i)
            state, m = train_step(
                state, put_batch(src, mesh), put_batch(tgt, mesh), rng
            )
            losses.append(float(m["loss"]))
        np.testing.assert_allclose(losses, single[0], rtol=2e-4)

    def test_decoder_only_on_mesh_matches_single(self):
        """The decoder-only param tree (no encoder/cross_mha/ln2) must shard
        and train on a data×fsdp mesh, matching the single-device run."""
        import dataclasses

        lm_model = dataclasses.replace(MODEL, decoder_only=True)
        batches = [_batch(i) for i in range(3)]

        state = create_train_state(jax.random.PRNGKey(0), lm_model, TCFG)
        step = jax.jit(make_train_step(lm_model, TCFG))
        rng = jax.random.PRNGKey(42)
        want = []
        for src, tgt in batches:
            state, m = step(state, src, tgt, rng)
            want.append(float(m["loss"]))

        mesh = make_mesh(MeshConfig(data=4, fsdp=2))
        sstate, shardings = create_sharded_state(
            jax.random.PRNGKey(0), lm_model, TCFG, mesh
        )
        train_step, _ = make_sharded_steps(
            mesh, lm_model, TCFG, shardings, donate=False
        )
        got = []
        for src, tgt in batches:
            sstate, m = train_step(
                sstate, put_batch(src, mesh), put_batch(tgt, mesh), rng
            )
            got.append(float(m["loss"]))
        np.testing.assert_allclose(got, want, rtol=2e-4)

    def test_bucketed_widths_through_distributed_trainer(self):
        """Length-bucketed batches (two static widths) must run through the
        sharded trainer — one compile per width, same mesh."""
        mesh = make_mesh(MeshConfig(data=4, fsdp=2))

        class DS:
            def batches(self, epoch):
                for i, width in enumerate((8, 6, 8, 6)):
                    ks, kt = jax.random.split(jax.random.PRNGKey(200 + i))
                    src = np.asarray(
                        jax.random.randint(ks, (16, width), 1, 32), np.int32
                    )
                    tgt = np.asarray(
                        jax.random.randint(kt, (16, width), 1, 32), np.int32
                    )
                    yield src, tgt

        trainer = DistributedTrainer(MODEL, TCFG, mesh, log_fn=lambda *_: None)
        trainer.fit(DS())
        assert int(jax.device_get(trainer.state.step)) == 4

    def test_gradients_match_single(self):
        """Grad parity at the raw-gradient level (post-Adam params are the
        wrong thing to compare: for near-zero gradients Adam's g/√v̂ turns
        fp32 reduction-order noise into ±lr sign flips)."""
        from transformer_tpu.models import transformer_apply
        from transformer_tpu.train.loss import masked_cross_entropy
        from transformer_tpu.parallel.sharding import (
            batch_spec, state_shardings,
        )
        from jax.sharding import NamedSharding

        def grad_fn(params, src, tgt):
            def loss_fn(p):
                logits, _ = transformer_apply(
                    p, src, tgt[:, :-1], MODEL, deterministic=True
                )
                loss, _ = masked_cross_entropy(logits, tgt[:, 1:])
                return loss

            return jax.grad(loss_fn)(params)

        params = create_train_state(jax.random.PRNGKey(0), MODEL, TCFG).params
        src, tgt = _batch(0)
        ref = jax.jit(grad_fn)(params, src, tgt)

        mesh = make_mesh(MeshConfig(data=2, fsdp=2, model=2))
        pshard = state_shardings(jax.eval_shape(lambda: params), mesh)
        sharded_params = jax.device_put(params, pshard)
        dsh = NamedSharding(mesh, batch_spec(mesh))
        dist = jax.jit(grad_fn, in_shardings=(pshard, dsh, dsh))(
            sharded_params, put_batch(src, mesh), put_batch(tgt, mesh)
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(ref), jax.tree_util.tree_leaves(dist)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(jax.device_get(b)), rtol=1e-3, atol=1e-5
            )


@pytest.mark.slow
class TestShardedCheckpoint:
    """FSDP-sharded state must round-trip without materializing any full
    array on the host (VERDICT round 1: the full-gather save contradicted
    the sharded-init rationale — >HBM models couldn't be checkpointed)."""

    def _sharded_state(self, mesh_cfg, seed=0):
        mesh = make_mesh(mesh_cfg)
        state, shardings = create_sharded_state(
            jax.random.PRNGKey(seed), MODEL, TCFG, mesh
        )
        return state, shardings

    def test_fsdp8_roundtrip_no_gather(self, tmp_path):
        from transformer_tpu.train import CheckpointManager

        state, _ = self._sharded_state(MeshConfig(data=1, fsdp=8))
        mgr = CheckpointManager(str(tmp_path), max_to_keep=2, is_primary=True)
        path = mgr.save(state, step=7)
        # Sharded layout on disk: per-process shard file, no arrays.npz.
        import os

        files = os.listdir(path)
        assert "shards_p00000.npz" in files
        assert "arrays.npz" not in files

        # No entry of an fsdp-sharded leaf may be full-sized: every stored
        # chunk must be exactly a 1/8 shard (the "no leaf was gathered"
        # assertion, via per-shard entry sizes).
        from transformer_tpu.train.checkpoint import _path_elem

        flat = {
            "/".join(_path_elem(p) for p in pth): leaf
            for pth, leaf in jax.tree_util.tree_flatten_with_path(state)[0]
        }
        emb = flat["params/encoder/embedding/table"]
        assert len(emb.sharding.device_set) == 8
        with np.load(os.path.join(path, "shards_p00000.npz")) as z:
            emb_entries = [n for n in z.files if n.startswith("params/encoder/embedding/table@")]
            assert len(emb_entries) == 8
            for n in emb_entries:
                assert z[n].size == emb.size // 8, (n, z[n].shape, emb.shape)

        # Restore into a differently-seeded sharded state: values must come
        # back exactly, with shardings preserved (no host full copy needed).
        fresh, _ = self._sharded_state(MeshConfig(data=1, fsdp=8), seed=1)
        restored = mgr.restore(fresh, step=7)
        for a, b in zip(
            jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)
        ):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
            )
        for orig, rest in zip(
            jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)
        ):
            if isinstance(orig, jax.Array) and len(orig.sharding.device_set) > 1:
                assert rest.sharding == orig.sharding

    def test_cross_topology_restore(self, tmp_path):
        """A checkpoint saved under fsdp=8 restores into a data=2×fsdp=4
        layout (shard stitching), values intact."""
        from transformer_tpu.train import CheckpointManager

        state, _ = self._sharded_state(MeshConfig(data=1, fsdp=8))
        mgr = CheckpointManager(str(tmp_path), is_primary=True)
        mgr.save(state, step=1)
        other, _ = self._sharded_state(MeshConfig(data=2, fsdp=4), seed=3)
        restored = mgr.restore(other, step=1)
        for a, b in zip(
            jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)
        ):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
            )

    def test_async_sharded_matches_sync(self, tmp_path):
        """Single-process sharded saves also go async (device snapshot sync,
        disk write in the worker) and must produce a byte-equivalent layout."""
        import os

        from transformer_tpu.train import AsyncCheckpointManager, CheckpointManager

        state, _ = self._sharded_state(MeshConfig(data=1, fsdp=8))
        a = AsyncCheckpointManager(str(tmp_path / "async"), is_primary=True)
        s = CheckpointManager(str(tmp_path / "sync"), is_primary=True)
        pa = a.save(state, step=2)
        ps = s.save(state, step=2)
        a.wait()
        assert sorted(os.listdir(pa)) == sorted(os.listdir(ps))
        fresh, _ = self._sharded_state(MeshConfig(data=1, fsdp=8), seed=9)
        ra = a.restore(fresh, step=2)
        rs = s.restore(fresh, step=2)
        for x, y in zip(jax.tree.leaves(ra), jax.tree.leaves(rs)):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y))
            )

    def test_unsharded_state_keeps_legacy_format(self, tmp_path):
        from transformer_tpu.train import CheckpointManager, create_train_state
        import os

        state = create_train_state(jax.random.PRNGKey(0), MODEL, TCFG)
        mgr = CheckpointManager(str(tmp_path), is_primary=True)
        path = mgr.save(state, step=3)
        assert "arrays.npz" in os.listdir(path)
        restored = mgr.restore(state, step=3)
        for a, b in zip(
            jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)
        ):
            np.testing.assert_array_equal(np.asarray(jax.device_get(a)), np.asarray(b))


@pytest.mark.slow
class TestDistributedResume:
    def test_crash_resume_with_sharded_checkpoint(self, tmp_path):
        """Preemption recovery at mesh scale: a DistributedTrainer run that
        checkpointed (sharded format) must resume at the saved step with
        identical params — the restore path reloads device shards directly."""
        from transformer_tpu.train import CheckpointManager
        from transformer_tpu.utils.preemption import tree_checksum

        mesh = make_mesh(MeshConfig(data=4, fsdp=2))
        import dataclasses

        cfg = dataclasses.replace(TCFG, epochs=1, checkpoint_every_epochs=1)

        class DS:
            def __len__(self):
                return 2

            def batches(self, epoch):
                for i in range(2):
                    yield _batch(i)

        t1 = DistributedTrainer(
            MODEL, cfg, mesh,
            checkpoint=CheckpointManager(str(tmp_path), is_primary=True),
            log_fn=lambda *_: None,
        )
        t1.fit(DS())
        assert int(jax.device_get(t1.state.step)) == 2
        saved_sum = tree_checksum(jax.device_get(t1.state.params))
        # The on-disk format is the sharded one (mesh state).
        import os

        ckpts = [d for d in os.listdir(tmp_path) if d.startswith("ckpt_")]
        assert ckpts
        assert any(
            f.startswith("shards_p")
            for f in os.listdir(tmp_path / ckpts[-1])
        )

        # Restart with the SAME config: the run is already complete, so
        # restore-at-start must resume past the final epoch and train zero
        # additional steps (no silent epoch overshoot).
        t2 = DistributedTrainer(
            MODEL, cfg, mesh,
            checkpoint=CheckpointManager(str(tmp_path), is_primary=True),
            log_fn=lambda *_: None,
        )
        restored = t2.checkpoint.restore_latest(t2.state)
        assert restored is not None
        assert int(jax.device_get(restored.step)) == 2
        assert tree_checksum(jax.device_get(restored.params)) == saved_sum
        t2.fit(DS())
        assert int(jax.device_get(t2.state.step)) == 2

        # Extend the plan to 2 epochs: resume trains exactly the remaining
        # epoch, continuing the (seed, epoch) data order.
        import dataclasses as _dc

        t3 = DistributedTrainer(
            MODEL, _dc.replace(cfg, epochs=2), mesh,
            checkpoint=CheckpointManager(str(tmp_path), is_primary=True),
            log_fn=lambda *_: None,
        )
        t3.fit(DS())
        assert int(jax.device_get(t3.state.step)) == 4


@pytest.mark.slow
class TestDistributedTrainer:
    def test_fit_runs_and_matches(self, tmp_path):
        mesh = make_mesh(MeshConfig(data=4, fsdp=2))

        class DS:
            def batches(self, epoch):
                for i in range(3):
                    yield _batch(i)

        trainer = DistributedTrainer(
            MODEL, TCFG, mesh, log_fn=lambda *_: None,
        )
        trainer.fit(DS())
        assert int(jax.device_get(trainer.state.step)) == 3

    def test_batch_divisibility_enforced(self):
        mesh = make_mesh(MeshConfig(data=8))
        bad = TrainConfig(batch_size=12, sequence_length=8, epochs=1)
        with pytest.raises(ValueError):
            DistributedTrainer(MODEL, bad, mesh)


class TestCompositionMatrix:
    """The supported-mesh matrix (parallel/distributed.py module docstring)
    is enforced, not aspirational: the documented pipe×{seq,expert} holes
    reject with a clear error BEFORE any state is allocated, while the
    supported combinations are proven elsewhere (pipe×model/fsdp/data in
    tests/test_pipeline.py, seq in tests/test_sequence_parallel.py, expert
    in tests/test_moe.py)."""

    def test_pipe_seq_rejected(self):
        import dataclasses

        model = dataclasses.replace(MODEL, attention_impl="ring")
        tcfg = TrainConfig(batch_size=4, sequence_length=8, warmup_steps=10)
        mesh = make_mesh(MeshConfig(data=2, pipe=2, seq=2))
        with pytest.raises(ValueError, match="pipe>1 composes"):
            DistributedTrainer(model, tcfg, mesh)

    def test_pipe_expert_rejected(self):
        import dataclasses

        model = dataclasses.replace(MODEL, moe_experts=4, moe_every=1)
        tcfg = TrainConfig(batch_size=8, sequence_length=8, warmup_steps=10)
        mesh = make_mesh(MeshConfig(data=2, pipe=2, expert=2))
        with pytest.raises(ValueError, match="pipe>1 composes"):
            DistributedTrainer(model, tcfg, mesh)

    def test_pipe_model_accepted(self):
        """PP × TP constructs (the full step parity is pinned in
        tests/test_pipeline.py::TestPipelinedTransformer)."""
        tcfg = TrainConfig(batch_size=4, sequence_length=8, warmup_steps=10)
        mesh = make_mesh(MeshConfig(data=2, pipe=2, model=2))
        trainer = DistributedTrainer(MODEL, tcfg, mesh, log_fn=lambda *_: None)
        assert trainer is not None
