"""Fault-tolerant serving (``transformer_tpu/serve/resilience.py``,
docs/ROBUSTNESS.md): the deterministic fault plane, request deadlines /
cancellation / backpressure, the circuit-breaker degradation ladder, and
the seeded chaos drills.

The chaos contract every drill asserts: EVERY request is answered (success
or structured error), zero slots leak, zero prefix-cache pins stay
outstanding, the hot paths compile zero new programs while breakers flip,
and greedy answers return byte-identical once the plane disarms and the
breakers close. The fast subset (fixed seeds, >= 4 fault points) rides
tier-1; the full >= 200-episode sweep across >= 6 points runs under
``-m slow`` (both carry the ``chaos`` marker).
"""

import json
import queue
import threading
import time

import jax
import numpy as np
import pytest

from transformer_tpu.analysis.retrace import RetraceSentinel
from transformer_tpu.config import ModelConfig
from transformer_tpu.data.tokenizer import SubwordTokenizer
from transformer_tpu.models import transformer_init
from transformer_tpu.obs.events import EventLog, read_events
from transformer_tpu.serve import (
    ContinuousScheduler,
    FaultPlane,
    InjectedFault,
    PrefixCache,
    resilience,
)
from transformer_tpu.serve.resilience import (
    CircuitBreaker,
    TransientError,
    backoff_ms,
    classify_error,
)
from transformer_tpu.serve.scheduler import (
    _pick_pool_verify,
    _pool_rollback,
    _pool_verify,
    _slot_prefill,
    _slot_read_blocks,
    _slot_restore,
)


@pytest.fixture(scope="module")
def lm():
    # Deliberately IDENTICAL to tests/test_scheduler.py's fixture: the
    # slot-pool programs cache by shape, so the chaos drills reuse the
    # compiles the parity tests pay for (and vice versa).
    tok = SubwordTokenizer.build_from_corpus(
        ["ab cd ef gh ij kl mn"] * 3, target_vocab_size=300
    )
    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=32, decoder_only=True, tie_output=True,
        dtype="float32", dropout_rate=0.0,
    )
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    return params, cfg, tok


# --------------------------------------------------------------------------
# fault plane: grammar, determinism, installation


def test_fault_spec_grammar():
    plane = FaultPlane.parse(
        "serve.prefill:p=0.25,seed=7;obs.emit:at=2+5;draft.slow:every=3,ms=40;"
        "prefix.corrupt:times=1"
    )
    rules = plane._rules
    assert rules["serve.prefill"].p == 0.25
    assert rules["serve.prefill"].seed == 7
    assert rules["obs.emit"].at == frozenset({2, 5})
    assert rules["draft.slow"].every == 3
    assert rules["draft.slow"].delay_ms == 40.0
    assert rules["prefix.corrupt"].times == 1
    with pytest.raises(ValueError, match="unknown fault point"):
        FaultPlane.parse("serve.prefil:p=1")
    with pytest.raises(ValueError, match="unknown fault_spec key"):
        FaultPlane.parse("serve.prefill:prob=1")
    with pytest.raises(ValueError, match="twice"):
        # Silently keeping only the last clause would run half the drill.
        FaultPlane.parse("obs.emit:at=2;obs.emit:at=5")


def test_fault_schedules_deterministic():
    def fires(spec, calls=50):
        plane = FaultPlane.parse(spec)
        return [
            bool(plane.fire("serve.prefill")) for _ in range(calls)
        ]

    a = fires("serve.prefill:p=0.3,seed=11")
    b = fires("serve.prefill:p=0.3,seed=11")
    c = fires("serve.prefill:p=0.3,seed=12")
    assert a == b, "same seed must replay the same fault episode"
    assert a != c, "a different seed must explore a different schedule"
    assert 0 < sum(a) < 50
    # at / every / times semantics
    at = fires("serve.prefill:at=3+5", calls=6)
    assert at == [False, False, True, False, True, False]
    every = fires("serve.prefill:every=2,times=2", calls=8)
    assert every == [False, True, False, True, False, False, False, False]


def test_disarmed_plane_is_free_and_scoped():
    assert resilience.installed() is None
    resilience.maybe_fail("serve.prefill")  # no plane: pure no-op
    with resilience.active(FaultPlane.parse("serve.prefill:p=1")) as plane:
        assert resilience.installed() is plane
        with pytest.raises(InjectedFault) as e:
            resilience.maybe_fail("serve.prefill")
        assert isinstance(e.value, OSError)       # leaf-site handler shape
        assert isinstance(e.value, TransientError)  # retry-policy shape
    assert resilience.installed() is None
    # leaf-module hooks were cleared with the plane
    from transformer_tpu.data import pipeline
    from transformer_tpu.obs import events
    from transformer_tpu.train import checkpoint

    assert events.fault_hook is None
    assert checkpoint.fault_hook is None
    assert pipeline.fault_hook is None


def test_backoff_deterministic_and_jittered():
    a = backoff_ms(20.0, 0, order=7)
    assert a == backoff_ms(20.0, 0, order=7)
    assert 10.0 <= a < 30.0                      # [0.5, 1.5) x base
    assert 20.0 <= backoff_ms(20.0, 1, order=7) < 60.0  # exponential
    assert backoff_ms(20.0, 0, order=8) != a     # spread across orders


def test_error_taxonomy_classification():
    assert classify_error(InjectedFault("serve.prefill", 1)) == "transient"
    assert classify_error(ValueError("bad")) == "validation"
    assert classify_error(RuntimeError("boom")) == "internal"


# --------------------------------------------------------------------------
# circuit breaker lifecycle (fake clock: deterministic cooldowns)


def test_breaker_ladder():
    clock = [0.0]
    seen = []
    b = CircuitBreaker(
        "x", threshold=2, cooldown_s=10.0, clock=lambda: clock[0],
        on_transition=lambda name, old, new: seen.append((old, new)),
    )
    assert b.allow() and b.state == "closed"
    b.record_failure()
    assert b.state == "closed" and b.allow()     # below threshold
    assert b.record_failure() is True            # K-th consecutive: opens
    assert b.state == "open" and not b.allow()
    clock[0] = 5.0
    assert not b.allow()                         # cooldown not elapsed
    clock[0] = 10.0
    assert b.allow() and b.state == "half_open"  # the probe
    assert b.record_failure() is True            # probe failed: re-open
    assert b.state == "open" and not b.allow()
    clock[0] = 25.0
    assert b.allow()
    b.record_success()                           # probe succeeded
    assert b.state == "closed" and b.allow()
    b.record_failure()
    b.record_success()                           # success resets the streak
    b.record_failure()
    assert b.state == "closed"
    assert seen == [
        ("closed", "open"), ("open", "half_open"), ("half_open", "open"),
        ("open", "half_open"), ("half_open", "closed"),
    ]
    assert b.stats["opens"] == 2 and b.stats["closes"] == 1


def test_breaker_open_ignores_stray_success():
    """A success recorded while OPEN (e.g. another slot's drafter in the
    same scheduler step, admitted before the trip) must NOT close the
    breaker — recovery goes through the half-open probe only, or an
    intermittent fault flaps the breaker every step."""
    clock = [0.0]
    b = CircuitBreaker("x", threshold=1, cooldown_s=10.0, clock=lambda: clock[0])
    assert b.record_failure() is True    # opens
    b.record_success()                   # stray pre-trip success: ignored
    assert b.state == "open" and not b.allow()
    clock[0] = 10.0
    assert b.allow() and b.state == "half_open"
    b.record_success()                   # the PROBE's success closes
    assert b.state == "closed"


class _FlakyFile:
    """A text sink whose next ``fail_next`` writes raise OSError."""

    def __init__(self, fail_next=0):
        self.fail_next = fail_next
        self.lines = []

    def write(self, s):
        if self.fail_next > 0:
            self.fail_next -= 1
            raise OSError("disk full")
        self.lines.append(s)

    def flush(self):
        pass


def test_eventlog_breaker_recovers(capsys):
    clock = [0.0]
    f = _FlakyFile(fail_next=3)
    log = EventLog(
        f,
        breaker=CircuitBreaker(
            "event_sink", threshold=2, cooldown_s=5.0, clock=lambda: clock[0]
        ),
    )
    log.emit("a")          # fail 1
    log.emit("b")          # fail 2: opens, ONE warning
    log.emit("c")          # open: dropped without touching the file
    assert f.fail_next == 1 and not f.lines
    clock[0] = 5.0
    log.emit("d")          # half-open probe: fails, re-opens (no 2nd warn yet)
    clock[0] = 10.0
    log.emit("e")          # probe succeeds: closed, event lands
    log.emit("f")
    assert [json.loads(s)["kind"] for s in f.lines] == ["e", "f"]
    err = capsys.readouterr().err
    assert err.count("sink open") == 2  # one warning per outage, not per fault


def test_eventlog_without_breaker_keeps_historic_contract(capsys):
    f = _FlakyFile(fail_next=1)
    log = EventLog(f)
    log.emit("a")
    log.emit("b")          # sink permanently disabled after first failure
    assert not f.lines
    assert capsys.readouterr().err.count("telemetry disabled") == 1


# --------------------------------------------------------------------------
# request lifecycle: deadlines, cancellation, backpressure, bounded retry


def test_deadline_expires_in_queue(lm):
    params, cfg, tok = lm
    s = ContinuousScheduler(params, cfg, tok, num_slots=2)
    out = s.run([
        {"prompt": "ab cd", "max_new": 3, "deadline_ms": 0},   # pre-expired
        {"prompt": "ab cd", "max_new": 3},                     # untouched
    ])
    assert out[0]["code"] == "deadline" and "error" in out[0]
    assert "continuation" in out[1]
    assert s.stats["deadline_expired"] == 1
    assert len(s._free) == 2


def test_deadline_expires_mid_generation(lm):
    params, cfg, tok = lm
    s = ContinuousScheduler(params, cfg, tok, num_slots=2)
    order = s.submit({"prompt": "ab cd", "max_new": 20, "deadline_ms": 60_000})
    s.admit()
    s.step()
    s.step()
    (slot, st), = s._active.items()
    st.deadline = time.perf_counter() - 1.0  # force expiry at the boundary
    s.step()
    out = s.drain_ready()
    assert out and out[0]["code"] == "deadline"
    assert "partial" in out[0]  # the tokens generated before expiry
    assert order not in s._done and len(s._free) == 2 and not s._active


def test_unparseable_deadline_is_validation_error(lm):
    params, cfg, tok = lm
    s = ContinuousScheduler(params, cfg, tok, num_slots=2)
    out = s.run([{"prompt": "ab cd", "max_new": 2, "deadline_ms": "soon"}])
    assert out[0]["code"] == "validation"


def test_cancel_queued_and_active(lm):
    params, cfg, tok = lm
    s = ContinuousScheduler(params, cfg, tok, num_slots=1)
    o1 = s.submit({"prompt": "ab cd", "max_new": 20})
    o2 = s.submit({"prompt": "ef gh", "max_new": 2})
    s.admit()   # o1 takes the only slot; o2 queued
    s.step()
    assert s.cancel(o2)                  # queued: registered
    assert s.cancel(o1)                  # in-flight: registered
    assert not s.cancel(o1)              # already pending
    assert not s.cancel(999)             # unknown order
    s.step()                             # the loop executes both
    assert not s.cancel(o1)              # already answered
    out = s.drain_ready()
    assert [r["code"] for r in out] == ["cancelled", "cancelled"]
    assert "partial" in out[0]           # in-flight cancel keeps its tokens
    assert len(s._free) == 1 and not s._active and not s.busy
    assert s.stats["cancelled"] == 2
    assert not s.cancel(o2)              # answered AND drained


def test_backpressure_bound(lm):
    params, cfg, tok = lm
    s = ContinuousScheduler(params, cfg, tok, num_slots=1, max_backlog=2)
    for _ in range(5):
        s.submit({"prompt": "ab", "max_new": 1})
    while s.busy:
        s.admit()
        s.step()
    out = s.drain_ready()
    codes = [r.get("code", "ok") for r in out]
    assert codes.count("backpressure") == 3 and codes.count("ok") == 2
    assert s.stats["backpressure"] == 3
    # refused requests still answer at their arrival-order position
    assert len(out) == 5


@pytest.mark.chaos
def test_transient_fault_retries_to_byte_identical_answer(lm):
    params, cfg, tok = lm
    reqs = [{"prompt": "ab cd ef", "max_new": 4}, {"prompt": "kl", "max_new": 2}]
    want = ContinuousScheduler(params, cfg, tok, num_slots=2).run(
        [dict(r) for r in reqs]
    )
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, retry_backoff_ms=1.0
    )
    with resilience.active(FaultPlane.parse("serve.prefill:at=1")) as plane:
        out = s.run([dict(r) for r in reqs])
    assert out == want, "a retried admission must not change the answer"
    assert s.stats["retries"] == 1 and plane.episodes == 1
    assert len(s._free) == 2


@pytest.mark.chaos
def test_persistent_fault_answers_structured_transient(lm):
    params, cfg, tok = lm
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, admission_retries=1,
        retry_backoff_ms=1.0,
    )
    with resilience.active(FaultPlane.parse("serve.prefill:p=1")):
        out = s.run([{"prompt": "ab cd", "max_new": 2}])
    assert out[0]["code"] == "transient" and "InjectedFault" in out[0]["error"]
    assert len(s._free) == 2 and not s.busy


# --------------------------------------------------------------------------
# leaf fault points: prefetch worker, checkpoint commit


@pytest.mark.chaos
def test_prefetch_fault_reraises_at_consumer():
    from transformer_tpu.data.pipeline import _threaded_device_prefetch

    batches = [
        (np.full((2, 2), i, np.int32), np.full((2, 2), i, np.int32))
        for i in range(4)
    ]
    got = []
    with resilience.active(FaultPlane.parse("data.prefetch:at=3")):
        with pytest.raises(InjectedFault):
            for b in _threaded_device_prefetch(iter(batches)):
                got.append(b)
    # the two pre-fault batches arrived, in order, before the re-raise
    assert [int(b[0][0, 0]) for b in got] == [0, 1]


@pytest.mark.chaos
def test_ckpt_write_fault_preserves_previous_checkpoint(tmp_path):
    from transformer_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), max_to_keep=3, is_primary=True)
    state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    mgr.save(state, step=1)
    with resilience.active(FaultPlane.parse("ckpt.write:p=1")):
        with pytest.raises(OSError):
            mgr.save({"w": state["w"] + 1}, step=2)
    # the failed commit left no ckpt_2 and did not disturb ckpt_1
    assert mgr.all_steps() == [1]
    restored = mgr.restore_latest({"w": np.zeros((2, 3), np.float32)})
    np.testing.assert_array_equal(restored["w"], state["w"])


def test_restore_latest_falls_back_past_corrupt_checkpoint(tmp_path, capsys):
    from transformer_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), max_to_keep=5, is_primary=True)
    template = {"w": np.zeros((2, 3), np.float32)}
    for step in (1, 2, 3):
        mgr.save({"w": np.full((2, 3), step, np.float32)}, step=step)
    # Tear the LATEST checkpoint mid-npz (the crash shape atomic rename
    # prevents for OUR writes, but bit rot / partial copies still produce).
    npz = tmp_path / "ckpt_00000003" / "arrays.npz"
    npz.write_bytes(npz.read_bytes()[: npz.stat().st_size // 2])
    restored = mgr.restore_latest(dict(template))
    np.testing.assert_array_equal(restored["w"], np.full((2, 3), 2.0))
    assert "falling back" in capsys.readouterr().err
    # ...and a garbled meta.json on top: falls back once more
    (tmp_path / "ckpt_00000002" / "meta.json").write_text("{torn")
    (tmp_path / "ckpt_00000002" / "arrays.npz").write_bytes(b"not a zip")
    fallbacks = []
    restored = mgr.restore_latest(
        dict(template), on_fallback=lambda step, exc: fallbacks.append(step)
    )
    np.testing.assert_array_equal(restored["w"], np.full((2, 3), 1.0))
    assert fallbacks == [3, 2]
    # explicit-step restore still fails loudly
    with pytest.raises(Exception):
        mgr.restore(dict(template), 3)
    # ...and when EVERY checkpoint fails (the all-steps-unreadable shape of
    # a target/config mismatch), restore_latest re-raises instead of
    # silently restarting from scratch
    (tmp_path / "ckpt_00000001" / "arrays.npz").write_bytes(b"also not a zip")
    with pytest.raises(Exception):
        mgr.restore_latest(dict(template))
    # an EMPTY directory is still the quiet first-run case
    from transformer_tpu.train.checkpoint import CheckpointManager as CM

    empty = CM(str(tmp_path / "fresh"), is_primary=True)
    assert empty.restore_latest(dict(template)) is None


# --------------------------------------------------------------------------
# chaos drills: the fast tier-1 subset and the full sweep


def _chaos_answers_ok(out, n):
    assert len(out) == n, f"only {len(out)}/{n} requests answered"
    for r in out:
        assert ("continuation" in r) or ("error" in r and "code" in r), r


def _pool_invariants(s, cache=None):
    assert sorted(s._free) == list(range(s.num_slots)), "slot leak"
    assert not s._active and not s.busy
    assert s._queued_deadlines == 0, "queued-deadline counter drifted"
    if cache is not None:
        assert cache.outstanding_refs() == 0, "leaked prefix-cache pin"


_CHAOS_REQS = [
    {"prompt": "ab cd ef gh ij kl", "max_new": 4},
    {"prompt": "ab cd ef gh mn", "max_new": 3},
    {"prompt": "kl mn", "max_new": 2},
    {"prompt": "ab cd ef gh ij kl", "max_new": 4},
]


def _chaos_scheduler(params, cfg, tok, cache, telemetry=None):
    return ContinuousScheduler(
        params, cfg, tok, num_slots=2, speculate_k=2, prefix_cache=cache,
        breaker_threshold=2, breaker_cooldown_s=0.0, retry_backoff_ms=1.0,
        telemetry=telemetry,
    )


def _chaos_watch():
    sentinel = RetraceSentinel()
    sentinel.watch("verify", _pool_verify, budget=0)
    sentinel.watch("pick", _pick_pool_verify, budget=0)
    sentinel.watch("prefill", _slot_prefill, budget=0)
    sentinel.watch("restore", _slot_restore, budget=0)
    sentinel.watch("export", _slot_read_blocks, budget=0)
    sentinel.watch("rollback", _pool_rollback, budget=0)
    return sentinel


@pytest.mark.chaos
def test_chaos_fast_subset(lm):
    """Tier-1 chaos drill: fixed seeds, four fault points, one breaker
    round-trip — every request answered, nothing leaks, zero recompiles,
    byte-identical greedy answers once the plane disarms."""
    params, cfg, tok = lm
    cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    s = _chaos_scheduler(params, cfg, tok, cache)
    want = s.run([dict(r) for r in _CHAOS_REQS])   # also populates the trie
    assert all("continuation" in r for r in want)
    s.run([dict(r) for r in _CHAOS_REQS])          # warm the hit paths
    sentinel = _chaos_watch()
    sentinel.snapshot()
    spec = (
        "serve.prefill:p=0.4,seed=3;prefix.match:p=0.4,seed=4;"
        "prefix.corrupt:p=0.5,seed=5;draft.propose:p=0.5,seed=6"
    )
    with resilience.active(FaultPlane.parse(spec)) as plane:
        for _ in range(3):
            out = s.run([dict(r) for r in _CHAOS_REQS])
            _chaos_answers_ok(out, len(_CHAOS_REQS))
    assert plane.episodes >= 8, f"only {plane.episodes} episodes injected"
    assert len({p for p, _ in plane.fired_log}) >= 3
    _pool_invariants(s, cache)
    # recovery: breakers close, greedy answers return byte-identical
    out = s.run([dict(r) for r in _CHAOS_REQS])
    assert out == want, "answers changed after the chaos round"
    assert s.breakers["speculative"].state == "closed"
    assert s.breakers["prefix_cache"].state == "closed"
    sentinel.assert_within_budget()  # 0 recompiles across breaker flips
    _pool_invariants(s, cache)


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_full_sweep(lm, tmp_path):
    """The acceptance sweep: >= 200 injected-fault episodes across >= 6
    distinct injection points, every request answered, zero leaked slots,
    zero outstanding prefix pins, 0 steady-state recompiles, byte-identical
    greedy answers after all breakers close — and the event log survives
    its own injected sink faults as parseable JSONL."""
    from transformer_tpu.obs import Telemetry

    params, cfg, tok = lm
    jsonl = str(tmp_path / "chaos.jsonl")
    telemetry = Telemetry(
        events=EventLog(
            jsonl,
            breaker=CircuitBreaker("event_sink", threshold=2, cooldown_s=0.0),
        ),
        interval=0.0,
    )
    cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    s = _chaos_scheduler(params, cfg, tok, cache, telemetry=telemetry)
    want = s.run([dict(r) for r in _CHAOS_REQS])
    s.run([dict(r) for r in _CHAOS_REQS])
    sentinel = _chaos_watch()
    sentinel.snapshot()
    spec = (
        "serve.prefill:p=0.3,seed=1;prefix.match:p=0.3,seed=2;"
        "prefix.corrupt:p=0.3,seed=3;prefix.insert:p=0.3,seed=4;"
        "draft.propose:p=0.4,seed=5;draft.slow:every=5,ms=1;"
        "obs.emit:p=0.3,seed=6"
    )
    total = 0
    with resilience.active(FaultPlane.parse(spec)) as plane:
        for round_i in range(40):
            reqs = [dict(r) for r in _CHAOS_REQS]
            if round_i % 3 == 0:
                reqs.append({"prompt": "kl", "max_new": 2, "deadline_ms": 0})
            out = s.run(reqs)
            _chaos_answers_ok(out, len(reqs))
            total += len(reqs)
            if plane.episodes >= 220:
                break
        episodes = plane.episodes
        points = {p for p, _ in plane.fired_log}
    assert episodes >= 200, f"only {episodes} episodes over {total} requests"
    assert len(points) >= 6, f"only {sorted(points)} fired"
    _pool_invariants(s, cache)
    # recovery: all breakers close, answers return byte-identical
    out = s.run([dict(r) for r in _CHAOS_REQS])
    assert out == want
    assert s.breakers["speculative"].state == "closed"
    assert s.breakers["prefix_cache"].state == "closed"
    sentinel.assert_within_budget()
    _pool_invariants(s, cache)
    telemetry.close()
    # the log survived its own sink faults: every surviving line parses,
    # and the breaker transitions the sweep caused were recorded
    events = read_events(jsonl)
    assert events, "event log is empty"
    kinds = {e["kind"] for e in events}
    assert "serve.request" in kinds and "serve.breaker" in kinds


@pytest.mark.chaos
def test_hammer_thread_storm(lm):
    """Real-thread fault storm (the ISSUE's hammer): four client threads
    submit mixed deadline/plain requests while the scheduler loop runs
    under injected prefill + prefix faults. No slot leaks, no negative or
    leaked prefix refcounts, every request answered exactly once."""
    params, cfg, tok = lm
    cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, prefix_cache=cache,
        breaker_threshold=2, breaker_cooldown_s=0.0, retry_backoff_ms=1.0,
    )
    n_threads, per = 4, 10

    def client(t):
        for i in range(per):
            req = {"prompt": "ab cd ef gh", "max_new": 2}
            if (t + i) % 4 == 0:
                req["deadline_ms"] = 0     # guaranteed queue expiry
            s.submit(req)

    spec = "serve.prefill:p=0.3,seed=8;prefix.match:p=0.3,seed=9"
    threads = [
        threading.Thread(target=client, args=(t,)) for t in range(n_threads)
    ]
    give_up = time.monotonic() + 120
    with resilience.active(FaultPlane.parse(spec)) as plane:
        for t in threads:
            t.start()
        while any(t.is_alive() for t in threads) or s.busy:
            s.admit()
            s.step()
            s.idle_backoff()
            assert time.monotonic() < give_up, "storm did not drain"
        for t in threads:
            t.join()
        # one last sweep: submissions racing the final busy check
        while s.busy:
            s.admit()
            s.step()
    out = s.drain_ready()
    _chaos_answers_ok(out, n_threads * per)
    _pool_invariants(s, cache)
    # refcounts never went negative: every node's pin balance is exactly 0
    assert cache.outstanding_refs() == 0
    assert plane.episodes > 0


# --------------------------------------------------------------------------
# serve loop integration: structured errors ride the JSONL surface


def test_serve_continuous_carries_error_codes(lm, capsys):
    from transformer_tpu.cli.serve import serve_continuous

    params, cfg, tok = lm
    s = ContinuousScheduler(params, cfg, tok, num_slots=2)
    q: queue.Queue = queue.Queue()
    q.put('{"prompt": "ab cd", "max_new": 2, "deadline_ms": 0}\n')
    q.put('{"prompt": "ab cd", "max_new": 2}\n')
    q.put(None)
    serve_continuous(q, s, cfg)
    lines = [
        json.loads(l) for l in capsys.readouterr().out.strip().splitlines()
    ]
    assert lines[0]["code"] == "deadline"
    assert "continuation" in lines[1]
