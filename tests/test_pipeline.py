"""Pipeline parallelism: the GPipe microbatch schedule over the 'pipe' mesh
axis must be numerically identical (forward AND backward) to running the
layer stack sequentially on one device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import MeshConfig, ModelConfig
from transformer_tpu.models.encoder import (
    embed_prologue,
    encoder_apply,
    encoder_init,
    encoder_layer_apply,
)
from transformer_tpu.models.transformer import transformer_apply, transformer_init
from transformer_tpu.ops.masks import make_padding_mask
from transformer_tpu.parallel import (
    make_mesh,
    pipeline_apply,
    pipelined_transformer_apply,
    stack_layer_params,
    unstack_layer_params,
)

# Heavyweight module (interpret-mode Pallas / 8-device shard_map /
# multi-process): excluded from the fast path, pytest -m 'not slow'.
pytestmark = pytest.mark.slow

CFG = ModelConfig(
    num_layers=4,
    d_model=16,
    num_heads=2,
    dff=32,
    input_vocab_size=64,
    target_vocab_size=64,
    max_position=32,
    dropout_rate=0.0,
    dtype="float32",
)


def _mesh(data=1, pipe=4):
    n = data * pipe
    cfg = MeshConfig(data=data, pipe=pipe)
    return make_mesh(cfg, devices=jax.devices()[:n])


def _ids(key, batch, seq, pad_tail=2):
    ids = jax.random.randint(key, (batch, seq), 1, CFG.input_vocab_size)
    if pad_tail:
        ids = ids.at[:, -pad_tail:].set(0)  # exercise padding masks
    return ids


class TestPipelineApply:
    def _stack_io(self, batch=8, seq=12):
        k = jax.random.PRNGKey(0)
        params = encoder_init(k, CFG)
        ids = _ids(jax.random.PRNGKey(1), batch, seq)
        mask = make_padding_mask(ids, 0)
        x = embed_prologue(params["embedding"], ids, CFG, None, True)
        return params, x, mask

    def _sequential(self, params, x, mask):
        for layer in params["layers"]:
            x, _, _ = encoder_layer_apply(layer, x, mask, CFG, None, True)
        return x

    @pytest.mark.parametrize("data,pipe,mbs", [(1, 4, 4), (2, 4, 2), (1, 2, 4), (1, 1, 2)])
    def test_forward_matches_sequential(self, data, pipe, mbs):
        mesh = _mesh(data, pipe)
        params, x, mask = self._stack_io()
        stacked = stack_layer_params(params["layers"])

        def layer_fn(lp, h, r, m):
            return encoder_layer_apply(lp, h, m, CFG, r, True)[0]

        out = jax.jit(
            lambda s, x, m: pipeline_apply(
                s, layer_fn, x, (m,), mesh=mesh, num_microbatches=mbs
            )
        )(stacked, x, mask)
        ref = self._sequential(params, x, mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_gated_ffn_stack_matches_sequential(self):
        """swiglu layers are homogeneous (every layer carries a gate), so
        they stack and pipeline; forward must match the sequential stack."""
        import dataclasses

        cfg = dataclasses.replace(CFG, ffn_activation="swiglu")
        mesh = _mesh(1, 4)
        k = jax.random.PRNGKey(0)
        params = encoder_init(k, cfg)
        ids = _ids(jax.random.PRNGKey(1), 8, 16)
        mask = make_padding_mask(ids, 0)
        x = embed_prologue(params["embedding"], ids, cfg, None, True)
        stacked = stack_layer_params(params["layers"])

        def layer_fn(lp, h, r, m):
            return encoder_layer_apply(lp, h, m, cfg, r, True)[0]

        out = jax.jit(
            lambda s, x, m: pipeline_apply(
                s, layer_fn, x, (m,), mesh=mesh, num_microbatches=4
            )
        )(stacked, x, mask)
        ref = x
        for layer in params["layers"]:
            ref, _, _ = encoder_layer_apply(layer, ref, mask, cfg, None, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_grads_match_sequential(self):
        mesh = _mesh(1, 4)
        params, x, mask = self._stack_io()
        stacked = stack_layer_params(params["layers"])

        def layer_fn(lp, h, r, m):
            return encoder_layer_apply(lp, h, m, CFG, r, True)[0]

        def loss_pp(s):
            out = pipeline_apply(
                s, layer_fn, x, (mask,), mesh=mesh, num_microbatches=4
            )
            return jnp.sum(out**2)

        def loss_seq(s):
            h = x
            for i in range(CFG.num_layers):
                lp = jax.tree.map(lambda a: a[i], s)
                h, _, _ = encoder_layer_apply(lp, h, mask, CFG, None, True)
            return jnp.sum(h**2)

        g_pp = jax.jit(jax.grad(loss_pp))(stacked)
        g_seq = jax.jit(jax.grad(loss_seq))(stacked)
        for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    def test_stack_unstack_roundtrip(self):
        params = encoder_init(jax.random.PRNGKey(0), CFG)
        stacked = stack_layer_params(params["layers"])
        back = unstack_layer_params(stacked, CFG.num_layers)
        for orig, rt in zip(params["layers"], back):
            for a, b in zip(jax.tree.leaves(orig), jax.tree.leaves(rt)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_num_layers_must_divide_stages(self):
        mesh = _mesh(1, 4)
        cfg3 = ModelConfig(
            num_layers=3, d_model=16, num_heads=2, dff=32,
            input_vocab_size=64, target_vocab_size=64, max_position=32,
            dropout_rate=0.0, dtype="float32",
        )
        params = encoder_init(jax.random.PRNGKey(0), cfg3)
        stacked = stack_layer_params(params["layers"])
        with pytest.raises(ValueError, match="divide"):
            pipeline_apply(
                stacked, lambda lp, h, r: h, jnp.zeros((4, 8, 16)),
                mesh=mesh, num_microbatches=2,
            )


class TestPipelinedTransformer:
    def test_seq2seq_logits_match(self):
        mesh = _mesh(1, 4)
        params = transformer_init(jax.random.PRNGKey(0), CFG)
        inp = _ids(jax.random.PRNGKey(1), 8, 12)
        tar = _ids(jax.random.PRNGKey(2), 8, 10)
        ref, _ = transformer_apply(params, inp, tar, CFG, None, True)
        out = jax.jit(
            lambda p: pipelined_transformer_apply(
                p, inp, tar, CFG, mesh=mesh, num_microbatches=4
            )
        )(params)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    def test_decoder_only_logits_match(self):
        mesh = _mesh(1, 4)
        cfg = ModelConfig(
            num_layers=4, d_model=16, num_heads=2, dff=32,
            input_vocab_size=64, target_vocab_size=64, max_position=32,
            dropout_rate=0.0, dtype="float32", decoder_only=True,
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        tar = _ids(jax.random.PRNGKey(2), 8, 10)
        ref, _ = transformer_apply(params, None, tar, cfg, None, True)
        out = jax.jit(
            lambda p: pipelined_transformer_apply(
                p, None, tar, cfg, mesh=mesh, num_microbatches=4
            )
        )(params)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)

    def test_sharded_train_step_with_pipe_axis(self):
        """--pp wiring: a mesh with pipe>1 must produce a working train/eval
        step whose deterministic eval metrics match the plain SPMD step."""
        from transformer_tpu.config import TrainConfig
        from transformer_tpu.parallel import (
            create_sharded_state,
            make_sharded_steps,
            put_batch,
        )

        mesh_pp = _mesh(2, 4)
        mesh_dp = _mesh(8, 1)
        train_cfg = TrainConfig(
            batch_size=8, sequence_length=12, warmup_steps=10, seed=0
        )
        rng = jax.random.PRNGKey(0)
        src = np.asarray(_ids(jax.random.PRNGKey(1), 8, 12))
        tgt = np.asarray(_ids(jax.random.PRNGKey(2), 8, 10))

        state_pp, sh_pp = create_sharded_state(rng, CFG, train_cfg, mesh_pp)
        step_pp, eval_pp = make_sharded_steps(
            mesh_pp, CFG, train_cfg, sh_pp, donate=False
        )
        state_dp, sh_dp = create_sharded_state(rng, CFG, train_cfg, mesh_dp)
        _, eval_dp = make_sharded_steps(mesh_dp, CFG, train_cfg, sh_dp, donate=False)

        m_pp = eval_pp(state_pp, put_batch(src, mesh_pp), put_batch(tgt, mesh_pp))
        m_dp = eval_dp(state_dp, put_batch(src, mesh_dp), put_batch(tgt, mesh_dp))
        np.testing.assert_allclose(
            float(m_pp["loss"]), float(m_dp["loss"]), rtol=1e-5
        )

        new_state, metrics = step_pp(
            state_pp, put_batch(src, mesh_pp), put_batch(tgt, mesh_pp),
            jax.random.PRNGKey(3),
        )
        assert np.isfinite(float(metrics["loss"]))
        assert int(jax.device_get(new_state.step)) == 1

    def test_pipe_with_model_axis_matches_plain(self):
        """PP × TP (r2 VERDICT next-#7): a mesh with pipe AND model axes.
        The GPipe region goes manual over data/pipe only; the model axis
        stays GSPMD-auto (pipeline_apply(auto_axes)), so stage interiors
        keep their heads/dff tensor sharding — and logits must reproduce
        the plain sequential forward."""
        mesh = make_mesh(
            MeshConfig(data=2, pipe=2, model=2), devices=jax.devices()
        )
        params = transformer_init(jax.random.PRNGKey(0), CFG)
        src = _ids(jax.random.PRNGKey(1), 4, 12)
        tgt = _ids(jax.random.PRNGKey(2), 4, 10)
        ref, _ = transformer_apply(params, src, tgt, CFG)
        out = jax.jit(
            lambda p, s, t: pipelined_transformer_apply(
                p, s, t, CFG, mesh=mesh, num_microbatches=2,
                deterministic=True,
            )
        )(params, src, tgt)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_pipe_model_sharded_train_step(self):
        """End-to-end pipe×model through make_sharded_steps (previously a
        documented rejection): one optimizer step runs and eval parity holds
        against the plain SPMD step."""
        from transformer_tpu.config import TrainConfig
        from transformer_tpu.parallel import (
            create_sharded_state,
            make_sharded_steps,
            put_batch,
        )

        mesh_ppt = make_mesh(
            MeshConfig(data=2, pipe=2, model=2), devices=jax.devices()
        )
        mesh_dp = _mesh(8, 1)
        train_cfg = TrainConfig(
            batch_size=8, sequence_length=12, warmup_steps=10, seed=0
        )
        rng = jax.random.PRNGKey(0)
        src = np.asarray(_ids(jax.random.PRNGKey(1), 8, 12))
        tgt = np.asarray(_ids(jax.random.PRNGKey(2), 8, 10))
        state_ppt, sh_ppt = create_sharded_state(rng, CFG, train_cfg, mesh_ppt)
        step_ppt, eval_ppt = make_sharded_steps(
            mesh_ppt, CFG, train_cfg, sh_ppt, donate=False
        )
        state_dp, sh_dp = create_sharded_state(rng, CFG, train_cfg, mesh_dp)
        _, eval_dp = make_sharded_steps(mesh_dp, CFG, train_cfg, sh_dp, donate=False)
        m_ppt = eval_ppt(
            state_ppt, put_batch(src, mesh_ppt), put_batch(tgt, mesh_ppt)
        )
        m_dp = eval_dp(state_dp, put_batch(src, mesh_dp), put_batch(tgt, mesh_dp))
        np.testing.assert_allclose(
            float(m_ppt["loss"]), float(m_dp["loss"]), rtol=1e-5
        )
        new_state, metrics = step_ppt(
            state_ppt, put_batch(src, mesh_ppt), put_batch(tgt, mesh_ppt),
            jax.random.PRNGKey(3),
        )
        assert np.isfinite(float(metrics["loss"]))
        assert int(jax.device_get(new_state.step)) == 1

    def test_pipe_with_chunked_loss_matches_plain(self):
        """r2 VERDICT next-#5: loss_chunks composes with the GPipe forward —
        the pipelined hidden forward + chunked vocab-projection CE must match
        the plain SPMD monolithic loss."""
        import dataclasses

        from transformer_tpu.config import TrainConfig
        from transformer_tpu.parallel import (
            create_sharded_state,
            make_sharded_steps,
            put_batch,
        )

        mesh_pp = _mesh(2, 4)
        mesh_dp = _mesh(8, 1)
        plain_cfg = TrainConfig(
            batch_size=8, sequence_length=12, warmup_steps=10, seed=0
        )
        chunk_cfg = dataclasses.replace(plain_cfg, loss_chunks=3)
        rng = jax.random.PRNGKey(0)
        src = np.asarray(_ids(jax.random.PRNGKey(1), 8, 12))
        tgt = np.asarray(_ids(jax.random.PRNGKey(2), 8, 10))

        state_pp, sh_pp = create_sharded_state(rng, CFG, chunk_cfg, mesh_pp)
        step_pp, eval_pp = make_sharded_steps(
            mesh_pp, CFG, chunk_cfg, sh_pp, donate=False
        )
        state_dp, sh_dp = create_sharded_state(rng, CFG, plain_cfg, mesh_dp)
        _, eval_dp = make_sharded_steps(
            mesh_dp, CFG, plain_cfg, sh_dp, donate=False
        )
        m_pp = eval_pp(state_pp, put_batch(src, mesh_pp), put_batch(tgt, mesh_pp))
        m_dp = eval_dp(state_dp, put_batch(src, mesh_dp), put_batch(tgt, mesh_dp))
        np.testing.assert_allclose(
            float(m_pp["loss"]), float(m_dp["loss"]), rtol=1e-5
        )
        new_state, metrics = step_pp(
            state_pp, put_batch(src, mesh_pp), put_batch(tgt, mesh_pp),
            jax.random.PRNGKey(3),
        )
        assert np.isfinite(float(metrics["loss"]))
        assert int(jax.device_get(new_state.step)) == 1

    def test_remat_pipelined_matches_plain(self):
        """cfg.remat must apply under the GPipe path too (memory-only lever:
        identical logits)."""
        import dataclasses

        mesh = _mesh(1, 4)
        cfg_r = dataclasses.replace(CFG, remat=True)
        params = transformer_init(jax.random.PRNGKey(0), CFG)
        inp = _ids(jax.random.PRNGKey(1), 8, 12)
        tar = _ids(jax.random.PRNGKey(2), 8, 10)
        want, _ = transformer_apply(params, inp, tar, CFG, None, True)
        out = jax.jit(
            lambda p: pipelined_transformer_apply(
                p, inp, tar, cfg_r, mesh=mesh, num_microbatches=4
            )
        )(params)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-4)

    def test_combined_data_fsdp_pipe_grads(self):
        """data×fsdp×pipe (VERDICT round 1: pipe composed with nothing but
        data): stage params stay fsdp-sharded at rest, gathered per layer
        inside the schedule — grads must still match the sequential model."""
        n = 8
        mesh = make_mesh(
            MeshConfig(data=2, fsdp=2, pipe=2), devices=jax.devices()[:n]
        )
        params = transformer_init(jax.random.PRNGKey(0), CFG)
        inp = _ids(jax.random.PRNGKey(1), 8, 12)
        tar = _ids(jax.random.PRNGKey(2), 8, 10)

        def loss_pp(p):
            logits = pipelined_transformer_apply(
                p, inp, tar, CFG, mesh=mesh, num_microbatches=2
            )
            return jnp.mean(logits**2)

        def loss_ref(p):
            logits, _ = transformer_apply(p, inp, tar, CFG, None, True)
            return jnp.mean(logits**2)

        g_pp = jax.jit(jax.grad(loss_pp))(params)
        g_ref = jax.jit(jax.grad(loss_ref))(params)
        for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3
            )

    def test_fsdp_pipe_trainer_step(self):
        """DistributedTrainer accepts fsdp×pipe meshes (guard lifted) and the
        sharded step trains with finite loss and matching eval metrics."""
        from transformer_tpu.config import TrainConfig
        from transformer_tpu.parallel import (
            create_sharded_state,
            make_sharded_steps,
            put_batch,
        )

        mesh = make_mesh(
            MeshConfig(data=2, fsdp=2, pipe=2), devices=jax.devices()[:8]
        )
        mesh_dp = _mesh(8, 1)
        train_cfg = TrainConfig(
            batch_size=8, sequence_length=12, warmup_steps=10, seed=0
        )
        rng = jax.random.PRNGKey(0)
        src = np.asarray(_ids(jax.random.PRNGKey(1), 8, 12))
        tgt = np.asarray(_ids(jax.random.PRNGKey(2), 8, 10))

        state, sh = create_sharded_state(rng, CFG, train_cfg, mesh)
        step, ev = make_sharded_steps(mesh, CFG, train_cfg, sh, donate=False)
        state_dp, sh_dp = create_sharded_state(rng, CFG, train_cfg, mesh_dp)
        _, ev_dp = make_sharded_steps(mesh_dp, CFG, train_cfg, sh_dp, donate=False)

        m = ev(state, put_batch(src, mesh), put_batch(tgt, mesh))
        m_dp = ev_dp(state_dp, put_batch(src, mesh_dp), put_batch(tgt, mesh_dp))
        np.testing.assert_allclose(
            float(m["loss"]), float(m_dp["loss"]), rtol=1e-5
        )
        new_state, metrics = step(
            state, put_batch(src, mesh), put_batch(tgt, mesh), jax.random.PRNGKey(3)
        )
        assert np.isfinite(float(metrics["loss"]))
        assert int(jax.device_get(new_state.step)) == 1

    def test_combined_data_and_pipe_grads(self):
        """dp×pp: grads of a masked-CE-style loss must match the single-device
        sequential model — the end-to-end guarantee a trainer needs."""
        mesh = _mesh(2, 4)
        params = transformer_init(jax.random.PRNGKey(0), CFG)
        inp = _ids(jax.random.PRNGKey(1), 8, 12)
        tar = _ids(jax.random.PRNGKey(2), 8, 10)

        def loss_pp(p):
            logits = pipelined_transformer_apply(
                p, inp, tar, CFG, mesh=mesh, num_microbatches=2
            )
            return jnp.mean(logits**2)

        def loss_ref(p):
            logits, _ = transformer_apply(p, inp, tar, CFG, None, True)
            return jnp.mean(logits**2)

        g_pp = jax.jit(jax.grad(loss_pp))(params)
        g_ref = jax.jit(jax.grad(loss_ref))(params)
        for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3
            )


class Test1F1B:
    """1F1B schedule (pipeline_train_1f1b): parity with GPipe and with the
    single-device step, plus the tick/stash accounting it exists for."""

    MODEL = ModelConfig(
        num_layers=2, d_model=16, num_heads=2, dff=32,
        input_vocab_size=32, target_vocab_size=32, max_position=16,
        dtype="float32", dropout_rate=0.0, decoder_only=True,
    )

    def _tcfg(self, **kw):
        import dataclasses

        from transformer_tpu.config import TrainConfig

        base = TrainConfig(
            batch_size=8, sequence_length=8, warmup_steps=10,
            loss_normalization="tokens", pp_microbatches=4,
        )
        return dataclasses.replace(base, **kw)

    def _batch(self):
        kt = jax.random.split(jax.random.PRNGKey(3))[1]
        return np.asarray(jax.random.randint(kt, (8, 8), 1, 32), np.int32)

    def test_bubble_accounting(self):
        from transformer_tpu.parallel.pipeline import (
            gpipe_ticks, one_f1b_stash_slots, one_f1b_ticks,
        )

        # GPipe: M + P - 1 ticks per direction; 1F1B: M + 2(P-1) combined
        # F+B ticks; stash: 2P-1 slots independent of M.
        assert gpipe_ticks(8, 4) == 11
        assert one_f1b_ticks(8, 4) == 14
        assert one_f1b_ticks(64, 4) == 70  # bubble amortizes with M...
        assert one_f1b_stash_slots(4) == 7  # ...while the stash stays put
        assert one_f1b_ticks(4, 1) == 4  # P=1 degenerates to grad accum

    @pytest.mark.parametrize("decoder_only", [True, False], ids=["lm", "seq2seq"])
    def test_matches_gpipe_losses(self, decoder_only):
        """Same config, same data: 1f1b and gpipe training losses track each
        other step for step (params are compared via the trajectory, not
        directly — Adam amplifies fp-order gradient noise on near-zero-
        gradient bias leaves into divergent but loss-irrelevant updates)."""
        import dataclasses

        from transformer_tpu.parallel import (
            create_sharded_state, make_sharded_steps, put_batch,
        )

        model = dataclasses.replace(self.MODEL, decoder_only=decoder_only)
        tgt = self._batch()
        src = self._batch() if not decoder_only else tgt
        rng = jax.random.PRNGKey(42)

        def run(schedule, n=3):
            tc = self._tcfg(pp_schedule=schedule)
            mesh = make_mesh(
                MeshConfig(data=2, pipe=2), devices=jax.devices()[:4]
            )
            state, sh = create_sharded_state(
                jax.random.PRNGKey(0), model, tc, mesh
            )
            step, _ = make_sharded_steps(mesh, model, tc, sh, donate=False)
            out = []
            for _ in range(n):
                state, m = step(
                    state, put_batch(src, mesh), put_batch(tgt, mesh), rng
                )
                out.append(float(m["loss"]))
            return out

        np.testing.assert_allclose(run("1f1b"), run("gpipe"), rtol=2e-4)

    @pytest.mark.parametrize(
        "mesh_kwargs,tcfg_kwargs,decoder_only",
        [
            (dict(data=2, pipe=2), dict(), True),
            # fsdp composition: the ZeRO-3 per-layer gather inside the 1f1b
            # stage must still reproduce single-device gradients — the
            # gather's vjp (reduce_scatter) both sums over the fsdp batch
            # shards and re-shards, and the engine must not double-reduce
            # those leaves.
            (
                dict(data=2, fsdp=2, pipe=2),
                dict(batch_size=8, pp_microbatches=2),
                True,
            ),
            # model axis stays GSPMD-auto: stage interiors keep heads/dff
            # sharding through the engine's internal vjps.
            (
                dict(data=2, model=2, pipe=2),
                dict(batch_size=8, pp_microbatches=2),
                True,
            ),
            # the full advertised surface in ONE mesh: fsdp gather x
            # auto-model interiors x manual pipe schedule together.
            (
                dict(fsdp=2, model=2, pipe=2),
                dict(batch_size=8, pp_microbatches=2),
                True,
            ),
            # seq2seq hybrid: decoder stack on the 1f1b engine (encoder
            # output as a gradient stream), encoder stack on GPipe+autodiff.
            (dict(data=2, pipe=2), dict(), False),
            (
                dict(fsdp=2, model=2, pipe=2),
                dict(batch_size=8, pp_microbatches=2),
                False,
            ),
        ],
        ids=[
            "data_pipe", "data_fsdp_pipe", "data_model_pipe",
            "fsdp_model_pipe", "seq2seq_data_pipe", "seq2seq_fsdp_model_pipe",
        ],
    )
    def test_grads_match_single_device(
        self, mesh_kwargs, tcfg_kwargs, decoder_only
    ):
        """One step with SGD(1.0): the param delta IS the gradient, so this
        pins every 1f1b gradient leaf against the plain single-device step,
        for each supported mesh composition and model family."""
        import dataclasses

        import optax

        from transformer_tpu.parallel import create_sharded_state, put_batch
        from transformer_tpu.parallel.distributed import make_1f1b_train_step
        from transformer_tpu.train import create_train_state, make_train_step

        model = dataclasses.replace(self.MODEL, decoder_only=decoder_only)
        tc = self._tcfg(pp_schedule="1f1b", **tcfg_kwargs)
        tgt = self._batch()
        src = self._batch() if not decoder_only else tgt
        rng = jax.random.PRNGKey(42)
        sgd = optax.sgd(1.0)

        state = create_train_state(jax.random.PRNGKey(0), model, tc)
        s2, m_ref = jax.jit(make_train_step(model, tc, tx=sgd))(
            state, src, tgt, rng
        )
        g_ref = jax.tree.map(
            lambda a, b: np.asarray(a) - np.asarray(b), state.params, s2.params
        )

        cfg = MeshConfig(**mesh_kwargs)
        mesh = make_mesh(cfg, devices=jax.devices()[: cfg.num_devices])
        sstate, _ = create_sharded_state(
            jax.random.PRNGKey(0), model, tc, mesh
        )
        step = jax.jit(make_1f1b_train_step(mesh, model, tc, tx=sgd))
        s3, m_1f1b = step(
            sstate, put_batch(src, mesh), put_batch(tgt, mesh), rng
        )
        g_1f1b = jax.tree.map(
            lambda a, b: np.asarray(a) - np.asarray(b), sstate.params, s3.params
        )

        np.testing.assert_allclose(
            float(m_1f1b["loss"]), float(m_ref["loss"]), rtol=1e-5
        )
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_1f1b)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=1e-5, rtol=1e-4
            )

    def _moe(self, **kw):
        import dataclasses

        return dataclasses.replace(self.MODEL, moe_experts=4, **kw)

    def test_moe_grads_match_gpipe(self):
        """MoE aux through the manual backward: SGD-delta leaf parity
        against the GPipe+autodiff step on the SAME mesh and microbatching
        — both make the identical per-microbatch aux approximation, so
        every gradient leaf (incl. router/expert weights) must agree up to
        fp order, and the moe_aux metrics must match."""
        import optax

        from transformer_tpu.parallel import create_sharded_state, put_batch
        from transformer_tpu.parallel.distributed import (
            _pipelined_forward, make_1f1b_train_step,
        )
        from transformer_tpu.train import make_train_step

        model = self._moe()
        tc = self._tcfg(pp_schedule="1f1b")
        mesh = make_mesh(MeshConfig(data=2, pipe=2), devices=jax.devices()[:4])
        tgt = self._batch()
        rng = jax.random.PRNGKey(42)
        sgd = optax.sgd(1.0)
        x = put_batch(tgt, mesh)

        state, _ = create_sharded_state(jax.random.PRNGKey(0), model, tc, mesh)
        gp_step = jax.jit(make_train_step(
            model, self._tcfg(pp_schedule="gpipe"), tx=sgd,
            forward_fn=_pipelined_forward(
                mesh, model, self._tcfg(pp_schedule="gpipe")
            ),
        ))
        s_gp, m_gp = gp_step(state, x, x, rng)
        s_1f, m_1f = jax.jit(make_1f1b_train_step(mesh, model, tc, tx=sgd))(
            state, x, x, rng
        )
        np.testing.assert_allclose(
            float(m_1f["loss"]), float(m_gp["loss"]), rtol=1e-5
        )
        np.testing.assert_allclose(
            float(m_1f["moe_aux"]), float(m_gp["moe_aux"]), rtol=1e-5
        )
        assert float(m_1f["moe_aux"]) > 0.0  # the aux actually fired
        for a, b in zip(
            jax.tree.leaves(jax.tree.map(
                lambda p, q: np.asarray(p) - np.asarray(q),
                state.params, s_gp.params,
            )),
            jax.tree.leaves(jax.tree.map(
                lambda p, q: np.asarray(p) - np.asarray(q),
                state.params, s_1f.params,
            )),
        ):
            np.testing.assert_allclose(b, a, atol=1e-5, rtol=1e-4)

    def test_moe_m1_matches_single_device(self):
        """With ONE microbatch and no batch sharding the per-microbatch aux
        approximation vanishes: the engine must reproduce the single-device
        MoE step exactly — the sharpest pin on the aux gradient seed."""
        import optax

        from transformer_tpu.parallel import create_sharded_state, put_batch
        from transformer_tpu.parallel.distributed import make_1f1b_train_step
        from transformer_tpu.train import create_train_state, make_train_step

        model = self._moe()
        tc = self._tcfg(pp_schedule="1f1b", pp_microbatches=1)
        tgt = self._batch()
        rng = jax.random.PRNGKey(42)
        sgd = optax.sgd(1.0)

        state = create_train_state(jax.random.PRNGKey(0), model, tc)
        s2, m_ref = jax.jit(make_train_step(model, tc, tx=sgd))(
            state, tgt, tgt, rng
        )
        mesh = make_mesh(MeshConfig(data=1, pipe=2), devices=jax.devices()[:2])
        sstate, _ = create_sharded_state(jax.random.PRNGKey(0), model, tc, mesh)
        s3, m_1f = jax.jit(make_1f1b_train_step(mesh, model, tc, tx=sgd))(
            sstate, put_batch(tgt, mesh), put_batch(tgt, mesh), rng
        )
        np.testing.assert_allclose(
            float(m_1f["loss"]), float(m_ref["loss"]), rtol=1e-5
        )
        np.testing.assert_allclose(
            float(m_1f["moe_aux"]), float(m_ref["moe_aux"]), rtol=1e-5
        )
        for a, b in zip(
            jax.tree.leaves(jax.tree.map(
                lambda p, q: np.asarray(p) - np.asarray(q),
                state.params, s2.params,
            )),
            jax.tree.leaves(jax.tree.map(
                lambda p, q: np.asarray(p) - np.asarray(q),
                sstate.params, s3.params,
            )),
        ):
            np.testing.assert_allclose(b, a, atol=1e-5, rtol=1e-4)

    def test_moe_seq2seq_matches_gpipe_losses(self):
        """Seq2seq MoE: decoder aux rides the 1f1b engine, encoder aux
        seeds its GPipe vjp — loss AND moe_aux trajectories must track the
        all-GPipe schedule."""
        from transformer_tpu.parallel import (
            create_sharded_state, make_sharded_steps, put_batch,
        )

        model = self._moe(decoder_only=False)
        tgt = self._batch()
        src = self._batch()
        rng = jax.random.PRNGKey(42)

        def run(schedule, n=3):
            tc = self._tcfg(pp_schedule=schedule)
            mesh = make_mesh(
                MeshConfig(data=2, pipe=2), devices=jax.devices()[:4]
            )
            state, sh = create_sharded_state(
                jax.random.PRNGKey(0), model, tc, mesh
            )
            step, _ = make_sharded_steps(mesh, model, tc, sh, donate=False)
            out = []
            for _ in range(n):
                state, m = step(
                    state, put_batch(src, mesh), put_batch(tgt, mesh), rng
                )
                out.append((float(m["loss"]), float(m["moe_aux"])))
            return out

        a, b = run("1f1b"), run("gpipe")
        np.testing.assert_allclose(
            [x[0] for x in a], [x[0] for x in b], rtol=2e-4
        )
        np.testing.assert_allclose(
            [x[1] for x in a], [x[1] for x in b], rtol=2e-4
        )
        assert all(x[1] > 0 for x in a)

    def test_pipe4_microbatch8(self):
        """Deeper pipe (4 stages, M=8 > stash slots would be under GPipe):
        the ring stash must recycle correctly once M exceeds 2P-1."""
        from transformer_tpu.parallel import (
            create_sharded_state, make_sharded_steps, put_batch,
        )

        tc = self._tcfg(pp_schedule="1f1b", pp_microbatches=8)
        mesh = make_mesh(MeshConfig(data=1, pipe=4), devices=jax.devices()[:4])
        # 4 layers so pipe=4 divides; 8 microbatches of 1 example each.
        import dataclasses

        model = dataclasses.replace(self.MODEL, num_layers=4)
        state, sh = create_sharded_state(jax.random.PRNGKey(0), model, tc, mesh)
        step, _ = make_sharded_steps(mesh, model, tc, sh, donate=False)
        tgt = self._batch()
        rng = jax.random.PRNGKey(42)
        losses = []
        for _ in range(2):
            state, m = step(
                state, put_batch(tgt, mesh), put_batch(tgt, mesh), rng
            )
            losses.append(float(m["loss"]))
        assert losses[1] < losses[0]  # it trains
        assert np.isfinite(losses).all()

    def test_rejections(self):
        import dataclasses

        from transformer_tpu.parallel.distributed import make_1f1b_train_step

        mesh = make_mesh(MeshConfig(data=2, pipe=2), devices=jax.devices()[:4])
        tc = self._tcfg(pp_schedule="1f1b")
        mixed_moe = dataclasses.replace(
            self.MODEL, moe_experts=4, moe_every=2, num_heads=2, dff=32
        )
        with pytest.raises(ValueError, match="homogeneous"):
            make_1f1b_train_step(mesh, mixed_moe, tc)
        with pytest.raises(ValueError, match="loss_chunks"):
            make_1f1b_train_step(
                mesh, self.MODEL, dataclasses.replace(tc, loss_chunks=2)
            )
        with pytest.raises(ValueError, match="grad_accum"):
            make_1f1b_train_step(
                mesh, self.MODEL, dataclasses.replace(tc, grad_accum_steps=2)
            )
        seq_mesh = make_mesh(
            MeshConfig(data=1, seq=2, pipe=2), devices=jax.devices()[:4]
        )
        with pytest.raises(ValueError, match="composes with 'data', 'fsdp'"):
            make_1f1b_train_step(seq_mesh, self.MODEL, tc)
        # Unknown schedule names are rejected at TrainConfig construction.
        with pytest.raises(ValueError, match="pp_schedule"):
            self._tcfg(pp_schedule="zigzag")
