"""SLO specs, burn-rate math, and the streaming engine (obs/slo.py).

The burn-rate cases are HAND-COMPUTED: synthetic request streams with known
good/bad counts inside each window, asserted against exact expected
fractions — the satellite the ISSUE names.
"""

from __future__ import annotations

import io
import json

import pytest

from transformer_tpu.obs import EventLog, MetricsRegistry, Telemetry
from transformer_tpu.obs.slo import (
    DEFAULT_SLOS,
    SLOEngine,
    SLOSpec,
    evaluate_slos,
    parse_slo_spec,
    span_sample,
)

# --------------------------------------------------------------------------
# spec parsing


def test_parse_slo_spec_grammar():
    specs = parse_slo_spec(
        "availability:objective=0.999,windows=60+600;"
        "ttft_p95:threshold=0.5;"
        "acceptance_rate:objective=0.6,name=floor"
    )
    by_name = {s.name: s for s in specs}
    assert by_name["availability"].objective == 0.999
    assert by_name["availability"].windows == (60.0, 600.0)
    # Unset params inherit the default spec for that kind.
    assert by_name["ttft_p95"].threshold_s == 0.5
    assert by_name["ttft_p95"].objective == 0.95
    assert by_name["floor"].kind == "acceptance_rate"
    assert parse_slo_spec("none") == ()
    assert parse_slo_spec("off") == ()


@pytest.mark.parametrize("bad", [
    "nonsense_kind",
    "availability:objective=1.5",
    "availability:objective",                 # not key=value
    "availability:frobnicate=1",
    "ttft_p95:objective=0.95,threshold=0",    # latency SLO needs threshold
    "availability;availability",              # duplicate names
    "availability:windows=0+60",
])
def test_parse_slo_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_slo_spec(bad)


def test_default_slos_are_valid():
    assert {s.kind for s in DEFAULT_SLOS} == {
        "availability", "ttft_p95", "deadline_miss", "acceptance_rate"
    }


# --------------------------------------------------------------------------
# per-span sampling


def test_span_sample_per_kind():
    avail = SLOSpec("a", "availability", 0.99)
    ttft = SLOSpec("t", "ttft_p95", 0.95, threshold_s=0.5)
    dl = SLOSpec("d", "deadline_miss", 0.99)
    acc = SLOSpec("r", "acceptance_rate", 0.5)
    ok = {"order": 1, "ttft_s": 0.2, "total_s": 1.0}
    slow = {"order": 2, "ttft_s": 0.9, "total_s": 1.0}
    err = {"order": 3, "error": "boom", "code": "internal"}
    late = {"order": 4, "error": "deadline_ms elapsed", "code": "deadline"}
    spec_span = {"order": 5, "ttft_s": 0.1, "drafted": 8, "draft_accepted": 6}
    assert span_sample(avail, ok) == (0.0, 1.0)
    assert span_sample(avail, err) == (1.0, 1.0)
    assert span_sample(ttft, ok) == (0.0, 1.0)
    assert span_sample(ttft, slow) == (1.0, 1.0)
    assert span_sample(ttft, err) is None          # no first token: no sample
    assert span_sample(dl, err) == (0.0, 1.0)
    assert span_sample(dl, late) == (1.0, 1.0)
    assert span_sample(acc, ok) is None            # never drafted
    assert span_sample(acc, spec_span) == (2.0, 8.0)


# --------------------------------------------------------------------------
# hand-computed burn rates


def _req(ts, **fields):
    return {"kind": "serve.request", "ts": ts, **fields}


def test_burn_rates_hand_computed_windows():
    """Stream: 20 requests in the last 60s (2 errors), another 30 requests
    60-600s ago (1 error). availability objective 0.99 (budget 0.01):

    - 60s window:  bad 2/20  = 0.10 -> burn 10.0
    - 600s window: bad 3/50  = 0.06 -> burn 6.0
    """
    now = 1_000_000.0
    events = []
    for i in range(20):
        events.append(_req(now - 1 - i * 2.5, order=i,
                           **({"error": "x", "code": "internal"} if i < 2
                              else {"ttft_s": 0.1})))
    for i in range(30):
        events.append(_req(now - 61 - i * 17, order=100 + i,
                           **({"error": "x", "code": "internal"} if i < 1
                              else {"ttft_s": 0.1})))
    spec = SLOSpec("availability", "availability", 0.99, windows=(60.0, 600.0))
    report = evaluate_slos(events, [spec], now=now)
    w = report["slos"]["availability"]["windows"]
    assert w["60s"]["total"] == 20 and w["60s"]["bad"] == 2
    assert w["60s"]["bad_fraction"] == 0.1
    assert w["60s"]["burn_rate"] == 10.0
    assert w["600s"]["total"] == 50 and w["600s"]["bad"] == 3
    assert w["600s"]["bad_fraction"] == 0.06
    assert w["600s"]["burn_rate"] == 6.0
    # Both windows over 1.0 -> breached (the multi-window rule).
    assert report["slos"]["availability"]["breached"] is True


def test_burn_requires_every_window_hot():
    """4 errors burst 90s ago: the 600s window burns, the 60s window is
    clean — NOT a breach (the fast window proves it stopped)."""
    now = 1_000_000.0
    events = [_req(now - 90 - i, order=i, error="x", code="internal")
              for i in range(4)]
    events += [_req(now - 5 - i, order=10 + i, ttft_s=0.1) for i in range(6)]
    spec = SLOSpec("availability", "availability", 0.9, windows=(60.0, 600.0))
    report = evaluate_slos(events, [spec], now=now)
    w = report["slos"]["availability"]["windows"]
    assert w["60s"]["burn_rate"] == 0.0
    assert w["600s"]["burn_rate"] == 4.0  # 4/10 bad over budget 0.1
    assert report["slos"]["availability"]["breached"] is False


def test_ttft_and_acceptance_weighted_math():
    now = 500.0
    events = [
        _req(now - 10, order=0, ttft_s=0.2, drafted=10, draft_accepted=9),
        _req(now - 20, order=1, ttft_s=2.0, drafted=30, draft_accepted=15),
        _req(now - 30, order=2, ttft_s=0.1),
        _req(now - 40, order=3, error="x", code="internal"),  # excluded: no ttft
    ]
    ttft = SLOSpec("ttft", "ttft_p95", 0.95, threshold_s=1.0, windows=(100.0,))
    acc = SLOSpec("acc", "acceptance_rate", 0.5, windows=(100.0,))
    report = evaluate_slos(events, [ttft, acc], now=now)
    wt = report["slos"]["ttft"]["windows"]["100s"]
    assert wt["total"] == 3 and wt["bad"] == 1       # one request over 1s
    assert wt["burn_rate"] == round((1 / 3) / 0.05, 4)
    wa = report["slos"]["acc"]["windows"]["100s"]
    # Token-weighted: 40 drafted, 16 rejected -> 0.4 bad over budget 0.5.
    assert wa["total"] == 40 and wa["bad"] == 16
    assert wa["burn_rate"] == 0.8


def test_no_samples_reports_none_not_breach():
    spec = SLOSpec("availability", "availability", 0.99)
    report = evaluate_slos([], [spec], now=100.0)
    w = report["slos"]["availability"]["windows"]
    assert all(x["burn_rate"] is None for x in w.values())
    assert report["slos"]["availability"]["breached"] is False


# --------------------------------------------------------------------------
# the streaming engine


def test_engine_gauges_and_breach_transition_events():
    clock = [1000.0]
    buf = io.StringIO()
    log = EventLog(buf)
    reg = MetricsRegistry()
    spec = SLOSpec("availability", "availability", 0.9, windows=(60.0, 600.0))
    eng = SLOEngine(
        [spec], registry=reg, emit=log.emit, interval=0.0,
        clock=lambda: clock[0],
    )
    for i in range(8):
        eng.record({"order": i, "ttft_s": 0.1})
    eng.evaluate()
    assert reg.gauge("serve_slo_burn_availability").value == 0.0
    # Now a fault storm: 8 errors -> bad fraction 0.5, burn 5.0 in BOTH
    # windows -> one breach-start event.
    for i in range(8):
        eng.record({"order": 10 + i, "error": "x", "code": "internal"})
    eng.evaluate()
    assert reg.gauge("serve_slo_burn_availability").value == 5.0
    eng.evaluate()  # still breached: no second event
    events = [json.loads(line) for line in buf.getvalue().splitlines()]
    burns = [e for e in events if e["kind"] == "slo.burn"]
    assert len(burns) == 1 and burns[0]["breached"] is True
    assert burns[0]["name"] == "availability"
    assert burns[0]["windows"]["60s"] == 5.0
    # 70s later the fast window is clean; the breach ENDS -> one more event.
    clock[0] += 70.0
    for i in range(4):
        eng.record({"order": 20 + i, "ttft_s": 0.1})
    eng.evaluate()
    events = [json.loads(line) for line in buf.getvalue().splitlines()]
    burns = [e for e in events if e["kind"] == "slo.burn"]
    assert len(burns) == 2 and burns[1]["breached"] is False


def test_engine_prunes_beyond_longest_window():
    clock = [0.0]
    spec = SLOSpec("availability", "availability", 0.9, windows=(10.0,))
    eng = SLOEngine([spec], interval=0.0, clock=lambda: clock[0])
    for i in range(100):
        eng.record({"order": i})
    clock[0] = 1000.0
    eng.evaluate()
    assert len(eng._samples["availability"]) == 0  # memory stays bounded


def test_engine_maybe_evaluate_honors_interval():
    clock = [0.0]
    eng = SLOEngine(
        [SLOSpec("availability", "availability", 0.9)],
        interval=5.0, clock=lambda: clock[0],
    )
    assert eng.maybe_evaluate() is not None   # first call runs
    assert eng.maybe_evaluate() is None       # within the interval
    clock[0] += 6.0
    assert eng.maybe_evaluate() is not None
    assert eng.maybe_evaluate(force=True) is not None


# --------------------------------------------------------------------------
# scheduler integration (CPU tiny model)


@pytest.fixture(scope="module")
def lm():
    import jax

    from transformer_tpu.config import ModelConfig
    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.models import transformer_init

    tok = SubwordTokenizer.build_from_corpus(
        ["ab cd ef gh ij kl mn"] * 3, target_vocab_size=300
    )
    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=32, decoder_only=True, tie_output=True,
        dtype="float32", dropout_rate=0.0,
    )
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    return params, cfg, tok


def _scheduler(lm, telemetry, **kw):
    from transformer_tpu.serve import ContinuousScheduler

    params, cfg, tok = lm
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_total", 32)
    kw.setdefault("default_max_new", 4)
    return ContinuousScheduler(params, cfg, tok, telemetry=telemetry, **kw)


def test_scheduler_slo_gauges_and_byte_identity(lm):
    _, cfg, _ = lm
    reqs = [
        {"prompt": "ab cd ef", "max_new": 3},
        {"prompt": "ab " * cfg.max_position, "max_new": 2},  # over-length
        {"prompt": "kl", "max_new": 2},
    ]
    plain = _scheduler(lm, None).run(reqs)
    buf = io.StringIO()
    tel = Telemetry(events=EventLog(buf), interval=0.0)
    slo_out = _scheduler(
        lm, tel, slos="availability:objective=0.9,windows=60+600"
    ).run(reqs)
    assert plain == slo_out  # SLO accounting is invisible in answers
    events = [json.loads(line) for line in buf.getvalue().splitlines()]
    snap = [e for e in events if e["kind"] == "metrics.snapshot"][-1]["metrics"]
    # 1 error / 3 requests = 0.333 bad over budget 0.1 -> burn ~3.33 in
    # both windows, exported and breached.
    assert snap["serve_slo_burn_availability"] == pytest.approx(3.3333, abs=0.01)
    burns = [e for e in events if e["kind"] == "slo.burn"]
    assert len(burns) == 1 and burns[0]["breached"] is True
    # summarize surfaces the transition.
    from transformer_tpu.obs.__main__ import summarize_events

    report = summarize_events(events)
    assert report["slo_transitions"]["availability"]["breaches"] == 1


def test_scheduler_slos_off_without_spec(lm):
    tel = Telemetry(interval=0.0)
    s = _scheduler(lm, tel)  # no slos=
    assert s._slo is None
    s2 = _scheduler(lm, tel, slos="none")
    assert s2._slo is None


def test_slo_cli_on_real_log(lm, tmp_path, capsys):
    from transformer_tpu.obs.__main__ import main

    jsonl = str(tmp_path / "serve.jsonl")
    tel = Telemetry(events=EventLog(jsonl), interval=0.0)
    _scheduler(lm, tel, slos=DEFAULT_SLOS).run([
        {"prompt": "ab cd", "max_new": 2},
        {"prompt": "ef gh", "max_new": 2},
    ])
    tel.close()
    assert main(["slo", jsonl, "--format", "json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["requests"] == 2
    assert report["slos"]["availability"]["breached"] is False
    avail = report["slos"]["availability"]["windows"]
    assert avail["300s"]["total"] == 2 and avail["300s"]["bad"] == 0
    # --last applies to the slo report too (the satellite).
    assert main(["slo", jsonl, "--last", "1h"]) == 0
    assert main(["slo", jsonl, "--slo_spec", "bogus"]) == 2