"""Continuous-batching scheduler contracts (``transformer_tpu/serve``):
same answers as sequential batch-1 serving under mixed prompt/output lengths,
per-request failure isolation (the ``cli/serve.py`` grouped-path guarantee),
slot recycling, and arrival-order output."""

import jax
import pytest

from transformer_tpu.config import ModelConfig
from transformer_tpu.data.tokenizer import SubwordTokenizer
from transformer_tpu.models import transformer_init
from transformer_tpu.serve import ContinuousScheduler
from transformer_tpu.train.decode import generate


@pytest.fixture(scope="module")
def lm():
    tok = SubwordTokenizer.build_from_corpus(
        ["ab cd ef gh ij kl mn"] * 3, target_vocab_size=300
    )
    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=32, decoder_only=True, tie_output=True,
        dtype="float32", dropout_rate=0.0,
    )
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    return params, cfg, tok


# Mixed prompt lengths, output budgets, and sampling params: the shapes that
# force mid-flight retirement + admission when slots < requests.
REQS = [
    {"prompt": "ab cd ef gh ij", "max_new": 6},
    {"prompt": "kl", "max_new": 2},
    {"prompt": "ef", "max_new": 0},  # empty-budget edge: "" both paths
    {"prompt": "ab cd", "max_new": 8, "temperature": 0.9, "seed": 3},
    {"prompt": "mn ef cd", "max_new": 1},
    {"prompt": "gh ij kl mn", "max_new": 5, "temperature": 0.7, "top_k": 4,
     "seed": 1},
]


def _sequential(params, cfg, tok, reqs):
    """The serve_batch=1 oracle: each request alone through generate()."""
    out = []
    for r in reqs:
        out.append(
            generate(
                params, cfg, tok, [r["prompt"]],
                max_new=r.get("max_new", 64),
                temperature=r.get("temperature", 0.0),
                top_k=r.get("top_k", 0), top_p=r.get("top_p", 1.0),
                seed=r.get("seed", 0),
            )[0]
        )
    return out


def test_matches_sequential_serving(lm):
    """2 slots, 5 requests with mixed prompt/output lengths and sampling
    params: continuous batching returns the same per-request continuations
    as decoding each request alone."""
    params, cfg, tok = lm
    want = _sequential(params, cfg, tok, REQS)
    sched = ContinuousScheduler(params, cfg, tok, num_slots=2)
    got = sched.run([dict(r) for r in REQS])
    assert [g.get("continuation") for g in got] == want
    assert sched.stats["admitted"] == len(REQS)
    assert sched.stats["max_active"] <= 2
    # Slots were actually recycled: 5 admissions through 2 slots.
    assert not sched.busy and len(sched._free) == 2  # pool drained


def test_single_slot_matches_sequential(lm):
    """num_slots=1 degenerates to pure sequential serving — the base case
    the parity claim is anchored to."""
    params, cfg, tok = lm
    reqs = REQS[:3]
    want = _sequential(params, cfg, tok, reqs)
    sched = ContinuousScheduler(params, cfg, tok, num_slots=1)
    got = sched.run([dict(r) for r in reqs])
    assert [g.get("continuation") for g in got] == want


def test_poisoned_request_fails_alone(lm):
    """A poisoned request (over-length prompt / unconvertible field) answers
    with ITS error; co-batched requests still succeed — the isolation
    guarantee the grouped path enforces by per-member retry holds here
    structurally (failures happen at admission, before the pool)."""
    params, cfg, tok = lm
    good = {"prompt": "ab cd", "max_new": 3}
    over = {"prompt": "ab cd ef gh " * 30, "max_new": 3}  # > max_position
    bad_field = {"prompt": "ef gh", "max_new": "four"}
    # Greedy ignores the rng, so even an unconvertible stray seed must not
    # change the answer (grouped-path parity: _signature never coerces it).
    stray_seed = {"prompt": "ab cd", "max_new": 3, "seed": "abc"}
    # An over-vocab top_k would raise inside the jitted pick — it must be
    # rejected at admission, answering alone instead of crashing step()
    # (or leaking the popped slot when the whole prompt prefills).
    big_topk = {"prompt": "ab cd", "max_new": 3, "temperature": 0.8,
                "top_k": 100000}
    sched = ContinuousScheduler(params, cfg, tok, num_slots=2)
    got = sched.run(
        [dict(good), dict(over), dict(bad_field), dict(good),
         dict(stray_seed), dict(big_topk), dict(good)]
    )
    assert got[0]["continuation"] == got[3]["continuation"]
    assert "max_position" in got[1]["error"]
    assert "ValueError" in got[2]["error"] or "int" in got[2]["error"]
    assert "error" not in got[0] and "error" not in got[3]
    assert got[4]["continuation"] == got[0]["continuation"]
    assert "top_k" in got[5]["error"]
    assert got[6]["continuation"] == got[0]["continuation"]
    # The failed admissions never held a slot.
    assert len(sched._free) == 2


def test_straggler_does_not_block_admission(lm):
    """The continuous-batching point: with 2 slots, a long-generation
    straggler and a stream of short requests, short requests are admitted
    and retired while the straggler is still decoding (max_active == 2 and
    total steps < sum of sequential steps)."""
    params, cfg, tok = lm
    reqs = [{"prompt": "ab cd ef gh ij kl", "max_new": 20}] + [
        {"prompt": "mn", "max_new": 1} for _ in range(4)
    ]
    sched = ContinuousScheduler(params, cfg, tok, num_slots=2)
    got = sched.run([dict(r) for r in reqs])
    assert all("continuation" in g for g in got)
    assert sched.stats["max_active"] == 2
    # Step-level interleaving: the pool never ran more total steps than the
    # straggler's own token budget plus a handful of admission edges.
    assert sched.stats["steps"] <= 20 + len(reqs) + 8


def test_arrival_order_output(lm):
    """drain_ready releases responses in ARRIVAL order: a later short
    request that finishes first waits for the earlier straggler (the serve
    loop's stdout contract), and submit_done reserves error positions."""
    params, cfg, tok = lm
    sched = ContinuousScheduler(params, cfg, tok, num_slots=4)
    sched.submit({"prompt": "ab cd ef gh ij", "max_new": 8})
    sched.submit_done({"error": "routing"})
    sched.submit({"prompt": "kl", "max_new": 1})
    early = []
    while sched.busy:
        sched.admit()
        sched.step()
        early.extend(sched.drain_ready())
        if early:
            # Nothing may flush before request 0 (the straggler) answers.
            assert "continuation" in early[0]
    out = early + sched.drain_ready()
    assert len(out) == 3
    assert out[1] == {"error": "routing"}
    assert "continuation" in out[2]


def test_cache_variants_match_sequential(lm):
    """The slot pool composes with the int8-quantized rolling-window cache:
    parity against sequential serving holds for the exotic cache layout
    too (the per-variant prefill math is pinned in test_prefill.py)."""
    import dataclasses

    params_base, cfg, tok = lm
    cfg_v = dataclasses.replace(cfg, kv_cache_int8=True, attention_window=4)
    params = transformer_init(jax.random.PRNGKey(0), cfg_v)
    reqs = [dict(r) for r in REQS[:3]]
    want = _sequential(params, cfg_v, tok, reqs)
    sched = ContinuousScheduler(params, cfg_v, tok, num_slots=2)
    got = sched.run(reqs)
    assert [g.get("continuation") for g in got] == want


def test_malformed_flood_stays_bounded(lm, capsys):
    """Error-answered lines count toward the serve loop's ingest cap: a
    flood of bad lines flushes incrementally instead of accumulating in the
    scheduler's done-buffer (the backpressure contract for invalid input)."""
    import json
    import queue

    from transformer_tpu.cli.serve import serve_continuous

    params, cfg, tok = lm
    sched = ContinuousScheduler(params, cfg, tok, num_slots=2)
    peak = 0
    orig = sched.submit_done

    def spying(resp):
        nonlocal peak
        order = orig(resp)
        peak = max(peak, sched.ready_count)
        return order

    sched.submit_done = spying
    q: queue.Queue = queue.Queue()
    for _ in range(100):
        q.put('{bad\n')
    q.put(None)
    serve_continuous(q, sched, cfg)
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 100
    assert all("error" in json.loads(l) for l in lines)
    assert peak <= 2 * 8  # backlog_cap for num_slots=2


def test_submit_after_shutdown_answers_routing_error(lm):
    """A submission landing after shutdown() answers a structured
    'routing' error at its reserved order instead of queueing into a loop
    nobody drives again — the window the multi-replica router's redispatch
    path can hit on a draining replica. Requests accepted BEFORE the
    shutdown keep their full contract."""
    params, cfg, tok = lm
    sched = ContinuousScheduler(params, cfg, tok, num_slots=2)
    sched.submit({"prompt": "ab cd", "max_new": 3})
    sched.shutdown()
    late = sched.submit({"prompt": "ef gh", "max_new": 3})
    assert late == 1
    while sched.busy:
        sched.admit()
        sched.step()
    out = sched.drain_ready()
    assert len(out) == 2
    assert "continuation" in out[0]  # pre-shutdown request still served
    assert out[1]["code"] == "routing"
    assert "shut down" in out[1]["error"]
    # The refused request never entered the queue or took a slot.
    assert sched.backlog == 0 and len(sched._free) == 2


def test_serve_continuous_loop(lm, capsys):
    """cli.serve's continuous loop end-to-end (in-process): JSONL + raw +
    malformed + wrong-kind lines through the stdin queue; one response per
    line in order, the loop surviving the bad ones."""
    import json
    import queue

    from transformer_tpu.cli.serve import serve_continuous

    params, cfg, tok = lm
    sched = ContinuousScheduler(params, cfg, tok, num_slots=2)
    q: queue.Queue = queue.Queue()
    for line in [
        'ab cd\n',                                  # raw line -> prompt
        '{"prompt": "ef gh", "max_new": 2}\n',
        '{broken json\n',                           # malformed: answered
        '{"src": "wrong kind"}\n',                  # seq2seq key on LM export
        '{"src": "x", "prompt": "y"}\n',  # 'src' wins (grouped-path parity)
        '\n',                                       # blank: skipped
    ]:
        q.put(line)
    q.put(None)
    serve_continuous(q, sched, cfg)
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert len(lines) == 5
    assert "continuation" in lines[0]
    assert "continuation" in lines[1]
    assert "error" in lines[2]
    # Bare message, no exception-type prefix — byte-identical to the
    # grouped path's kind-mismatch answer.
    assert lines[3]["error"] == "LM export serves 'prompt', not 'src'"
    assert lines[4]["error"] == "LM export serves 'prompt', not 'src'"
