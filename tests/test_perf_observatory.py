"""Performance observatory (``obs/profile.py`` + ``obs/flight.py``): the
per-program dispatch profiler with its measured-vs-predicted roofline join
and banked drift bands, the always-on flight recorder with supervisor-
captured postmortems, the ``/healthz`` endpoint, and the event-catalogue
AST gate that keeps docs/OBSERVABILITY.md honest."""

import ast
import io
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from transformer_tpu.obs import EventLog, Telemetry
from transformer_tpu.obs.flight import (
    FlightRecorder,
    flight_path_for,
    load_flight_record,
)
from transformer_tpu.obs.profile import (
    BASELINE_PATH,
    CANNED_PROGRAMS,
    ProgramProfiler,
    band_breaches,
    load_baseline,
    measured_from_events,
    profile_call,
    roofline_ratio,
    roofline_report,
    write_baseline,
)
from transformer_tpu.obs.registry import MetricsRegistry

REPO = Path(__file__).resolve().parents[1]

# The deterministic test-model bootstrap (tests/test_supervisor.py): every
# process building this spec gets bit-identical params and vocab.
SPEC = {
    "config": {
        "num_layers": 1, "d_model": 16, "num_heads": 2, "dff": 32,
        "max_position": 32, "decoder_only": True, "tie_output": True,
        "dtype": "float32", "dropout_rate": 0.0,
    },
    "seed": 0,
    "corpus": ["ab cd ef gh ij kl mn"] * 3,
    "target_vocab_size": 300,
}
PROMPT_A = "ab cd ef gh ij"


@pytest.fixture(scope="module")
def lm():
    from transformer_tpu.serve.replica import build_model_from_spec

    return build_model_from_spec(SPEC)


@pytest.fixture(scope="module")
def spec_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("observatory") / "spec.json"
    path.write_text(json.dumps(SPEC))
    return str(path)


def _scheduler(lm, telemetry, **kw):
    from transformer_tpu.serve import ContinuousScheduler

    params, cfg, tok = lm
    return ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=32, default_max_new=4,
        telemetry=telemetry, **kw,
    )


# --------------------------------------------------------------------------
# the profiler: gauges, drift transitions, the wrapper (no jax)


def test_profiler_gauges_export():
    """Every perf_* family — histogram, token counter, derived measured
    gauges, roofline ratio, and the drift gauge — lands in the registry's
    Prometheus exposition (the acceptance criterion)."""
    reg = MetricsRegistry()
    baseline = {
        "peak_bytes_per_s": 1e6,
        "programs": {"serve.pool_step": {
            "p50_s": 0.001, "band": [0.2, 5.0], "bytes_moved": 1000,
        }},
    }
    prof = ProgramProfiler(registry=reg, baseline=baseline)
    for _ in range(16):
        prof.record("serve.pool_step", 0.001, tokens=2)
    text = reg.to_prometheus_text()
    for metric in (
        "perf_seconds_serve_pool_step_count 16",
        "perf_tokens_total_serve_pool_step 32",
        "perf_measured_tokens_per_s_serve_pool_step",
        "perf_measured_p50_ms_serve_pool_step",
        "perf_measured_bytes_per_s_serve_pool_step",
        "perf_roofline_ratio_serve_pool_step",
        "perf_drift_serve_pool_step",
    ):
        assert metric in text, f"{metric} missing from exposition"
    # The drift gauge carries measured-p50 / banked-p50 — all samples AT
    # the banked p50, so the ratio sits inside the band (histogram-bucket
    # approximation allowed).
    drift = reg.gauge("perf_drift_serve_pool_step").value
    assert 0.2 <= drift <= 5.0
    row = prof.summary()["serve.pool_step"]
    assert row["dispatches"] == 16 and row["tokens"] == 32.0
    assert row["drift"] == pytest.approx(drift, rel=1e-6)
    assert row["roofline_ratio"] > 0
    assert row["tokens_per_s"] > 0


def test_drift_event_fires_on_transition_only():
    """A drifting program emits ONE perf.drift per breach-state
    transition, never per sample (slo.burn's discipline)."""
    events = []
    baseline = {"programs": {"train.step": {
        "p50_s": 0.001, "band": [0.5, 2.0],
    }}}
    prof = ProgramProfiler(
        emit=lambda kind, **f: events.append({"kind": kind, **f}),
        baseline=baseline,
    )
    for _ in range(8):
        prof.record("train.step", 0.001)
    assert events == []  # first judgment lands in band: silence
    for _ in range(64):  # p50 walks 100x out of band — many judged samples
        prof.record("train.step", 0.1)
    drifts = [e for e in events if e["kind"] == "perf.drift"]
    assert len(drifts) == 1, "breach must emit exactly one transition event"
    assert drifts[0]["program"] == "train.step"
    assert drifts[0]["breached"] is True
    assert drifts[0]["ratio"] > 2.0
    assert drifts[0]["band"] == [0.5, 2.0]
    assert prof.stats["drift_events"] == 1
    # A program whose FIRST judgment is already out of band also alerts.
    events2 = []
    prof2 = ProgramProfiler(
        emit=lambda kind, **f: events2.append({"kind": kind, **f}),
        baseline=baseline,
    )
    for _ in range(8):
        prof2.record("train.step", 0.1)
    assert [e["kind"] for e in events2] == ["perf.drift"]
    assert events2[0]["breached"] is True


def test_profile_call_wraps_and_records():
    prof = ProgramProfiler(baseline={})

    def fn(x, y=1):
        return x + y

    wrapped = profile_call(fn, prof, "serve.pool_step", tokens=3)
    assert wrapped.__wrapped__ is fn  # the inertness-contract handle
    assert wrapped(2, y=3) == 5
    assert prof.stats["records"] == 1
    row = prof.summary()["serve.pool_step"]
    assert row["dispatches"] == 1 and row["tokens"] == 3.0


def test_baseline_bank_roundtrip(tmp_path):
    path = str(tmp_path / "bank.json")
    measured = {
        "serve.pool_step": {"p50_s": 0.002},
        "serve.pool_verify": {"p50_s": 0},  # never banked: no honest p50
    }
    preds = {"serve.pool_step": {
        "bytes_moved": 12345, "extras": {"tokens_per_step": 2},
    }}
    doc = write_baseline(path, measured, predictions=preds,
                         peak_bytes_per_s=5e11)
    assert load_baseline(path) == doc
    entry = doc["programs"]["serve.pool_step"]
    assert entry["p50_s"] == 0.002
    assert entry["bytes_moved"] == 12345
    assert entry["tokens_per_step"] == 2
    assert entry["band"] == [0.2, 5.0]
    assert "serve.pool_verify" not in doc["programs"]
    assert doc["peak_bytes_per_s"] == 5e11
    assert load_baseline(str(tmp_path / "missing.json")) == {}


def test_checked_in_baseline_hygiene():
    """The shipped bank freezes predictions (bytes_moved) and bands but
    NEVER absolute p50 seconds — those are per-host, banked only by a
    local ``obs roofline --update`` run."""
    doc = load_baseline()
    assert doc["peak_bytes_per_s"] > 0
    assert doc["programs"], "shipped bank has no programs"
    for name, entry in doc["programs"].items():
        assert name in CANNED_PROGRAMS, name
        assert entry.get("bytes_moved", 0) > 0, name
        lo, hi = entry["band"]
        assert 0 < lo < 1 < hi, name
        assert "p50_s" not in entry, (
            f"{name}: absolute p50 seconds must not ship in the repo bank"
        )


# --------------------------------------------------------------------------
# the offline join + the banked-band CLI workflow


def _episode_events(p50=0.002, count=16, program="serve.pool_step"):
    from transformer_tpu.obs.quantiles import StreamingHistogram

    suffix = program.replace(".", "_")
    h = StreamingHistogram()
    for _ in range(count):
        h.observe(p50)
    return [{
        "kind": "metrics.snapshot", "ts": 1.0,
        "metrics": {
            f"perf_seconds_{suffix}": h.snapshot(),
            f"perf_tokens_total_{suffix}": float(count * 2),
        },
    }]


def test_roofline_report_tolerant_join():
    events = _episode_events()
    # Measured-only: rows appear with timing columns, nothing else.
    rows = roofline_report(events, baseline={})["programs"]
    assert [r["program"] for r in rows] == ["serve.pool_step"]
    assert rows[0]["dispatches"] == 16 and rows[0]["p50_ms"] > 0
    assert "roofline_ratio" not in rows[0] and "drift" not in rows[0]
    # + a costs document: bytes and predicted-tokens columns join in (the
    # lm_bf16 variant wins when several share a base name).
    costs = {"programs": [
        {"name": "serve.pool_step[lm_f32]", "bytes_moved": 7},
        {"name": "serve.pool_step[lm_bf16]", "bytes_moved": 1000,
         "extras": {"tokens_per_step": 2}},
    ]}
    row = roofline_report(
        events, costs=costs, baseline={"peak_bytes_per_s": 1e6},
    )["programs"][0]
    assert row["predicted_bytes_moved"] == 1000
    assert row["roofline_ratio"] == roofline_ratio(
        1000, row["p50_s"], 1e6
    )
    assert row["predicted_tokens_per_s"] == pytest.approx(
        2 / row["p50_s"], rel=1e-3
    )
    assert row["measured_over_predicted_tokens"] > 0
    # + a bank: drift columns judge the band; breaches surface.
    bank = {"peak_bytes_per_s": 1e6, "programs": {
        "serve.pool_step": {"p50_s": row["p50_s"], "band": [0.5, 2.0]},
    }}
    report = roofline_report(events, baseline=bank)
    judged = report["programs"][0]
    assert judged["drift"] == 1.0 and judged["in_band"] is True
    assert band_breaches(report) == []
    bank["programs"]["serve.pool_step"]["p50_s"] = row["p50_s"] / 100
    report = roofline_report(events, baseline=bank)
    assert report["programs"][0]["in_band"] is False
    assert [b["program"] for b in band_breaches(report)] == [
        "serve.pool_step"
    ]


def test_measured_from_events_last_snapshot_wins():
    events = _episode_events(count=16) + _episode_events(count=32)
    measured = measured_from_events(events)
    assert measured["serve.pool_step"]["dispatches"] == 32
    assert measured["serve.pool_step"]["tokens"] == 64.0
    assert measured_from_events([{"kind": "serve.request", "ts": 1.0}]) == {}


def test_roofline_cli_banked_band_workflow(tmp_path, capsys):
    """The acceptance workflow, pinned end to end on a COPY of the
    checked-in bank: pass -> perturb -> --check fails -> --update ->
    pass. (The shipped obs/roofline_baseline.json is never rewritten.)"""
    from transformer_tpu.obs.__main__ import main

    ep = tmp_path / "episode.jsonl"
    ep.write_text("".join(
        json.dumps(e) + "\n" for e in _episode_events()
    ))
    bank = str(tmp_path / "bank.json")
    shutil.copy(BASELINE_PATH, bank)
    # --update banks the measured p50 and freezes the prior bank's
    # predictions next to it (no --costs given).
    assert main(["roofline", str(ep), "--baseline", bank, "--update"]) == 0
    assert "banked 1 program(s)" in capsys.readouterr().out
    banked = load_baseline(bank)["programs"]["serve.pool_step"]
    assert banked["p50_s"] > 0
    assert banked["bytes_moved"] == load_baseline()["programs"][
        "serve.pool_step"]["bytes_moved"]
    # Same episode against its own bank: in band, --check passes.
    assert main(["roofline", str(ep), "--baseline", bank, "--check"]) == 0
    capsys.readouterr()
    # Perturb: the bank remembers a 100x faster program -> breach.
    doc = json.load(open(bank))
    doc["programs"]["serve.pool_step"]["p50_s"] /= 100.0
    with open(bank, "w") as f:
        json.dump(doc, f)
    assert main(["roofline", str(ep), "--baseline", bank, "--check"]) == 1
    err = capsys.readouterr().err
    assert "BAND BREACH serve.pool_step" in err
    # Re-bank on this host: the band heals.
    assert main(["roofline", str(ep), "--baseline", bank, "--update"]) == 0
    assert main(["roofline", str(ep), "--baseline", bank, "--check"]) == 0
    capsys.readouterr()
    # The JSON report carries the judged row.
    assert main(
        ["roofline", str(ep), "--baseline", bank, "--format=json"]
    ) == 0
    report = json.loads(capsys.readouterr().out)
    rows = {r["program"]: r for r in report["programs"]}
    assert rows["serve.pool_step"]["in_band"] is True
    assert rows["serve.pool_step"]["roofline_ratio"] > 0
    # An episode with no profiler stream banks nothing (exit 2).
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"kind": "serve.request", "ts": 1.0}) + "\n")
    assert main(
        ["roofline", str(empty), "--baseline", bank, "--update"]
    ) == 2
    capsys.readouterr()


def test_summarize_reports_perf_section(capsys):
    from transformer_tpu.obs.__main__ import render_text, summarize_events

    report = summarize_events(_episode_events())
    assert report["perf"]["programs"], "summarize dropped the perf section"
    text = render_text(report)
    assert "perf:" in text and "serve.pool_step" in text
    # No profiler stream -> no perf section (the section never lies).
    assert "perf" not in summarize_events(
        [{"kind": "serve.request", "ts": 1.0}]
    )


# --------------------------------------------------------------------------
# the flight recorder (no jax)


def test_flight_ring_bounded_and_routed():
    fr = FlightRecorder(None, capacity=8, snapshots=2)
    for i in range(50):
        fr.record("serve.request", {"order": i})
    fr.record("trace.span", {"name": "x"})
    fr.record("metrics.snapshot", {"metrics": {}})
    rec = fr.snapshot_record()
    assert [e["order"] for e in rec["events"]] == list(range(42, 50))
    assert len(rec["spans"]) == 1 and len(rec["snapshots"]) == 1
    assert rec["recorded"] == 52  # everything seen, ring or not
    assert fr.depth() == 10


def test_flight_dump_file_event_and_salvage(tmp_path):
    emitted = []
    path = flight_path_for(str(tmp_path / "rep.jsonl"))
    assert path.endswith(".jsonl.flight.json")
    fr = FlightRecorder(
        path, emit=lambda kind, **f: emitted.append({"kind": kind, **f}),
    )
    fr.record("serve.request", {"order": 0})
    fr.dump("request")
    loaded = load_flight_record(path)
    assert loaded["reason"] == "request" and loaded["pid"] == os.getpid()
    assert [e["kind"] for e in loaded["events"]] == ["serve.request"]
    assert [e["kind"] for e in emitted] == ["flight.dump"]
    assert emitted[0]["reason"] == "request"
    # Auto dumps persist but stay SILENT (2 Hz must not flood the log).
    emitted.clear()
    fr.autodump_s = 1e-4
    time.sleep(2e-4)
    assert fr.maybe_dump() is True
    assert emitted == []
    assert load_flight_record(path)["reason"] == "auto"
    # Salvage is best-effort by contract: missing / torn / non-flight
    # files load as None, never raise.
    assert load_flight_record(str(tmp_path / "missing.json")) is None
    (tmp_path / "torn.json").write_text('{"events": [')
    assert load_flight_record(str(tmp_path / "torn.json")) is None
    (tmp_path / "other.json").write_text('{"kind": "x"}')
    assert load_flight_record(str(tmp_path / "other.json")) is None


def test_flight_tap_records_then_forwards():
    seen = []
    fr = FlightRecorder(None)
    tapped = fr.tap(lambda kind, **f: seen.append((kind, f)))
    tapped("serve.request", order=1)
    assert seen == [("serve.request", {"order": 1})]
    assert fr.depth() == 1
    assert callable(tapped.__wrapped__)


def test_flight_autodump_outruns_snapshot_interval(tmp_path):
    """The autodump cadence is the flight recorder's own (autodump_s), NOT
    the telemetry snapshot interval: a SIGKILL can't trigger a dump, so
    the on-disk record's staleness bound must not inherit the (much
    longer) sink interval."""
    path = flight_path_for(str(tmp_path / "m.jsonl"))
    tel = Telemetry(interval=1e9)
    tel.arm_flight(path, autodump_s=1e-4)
    tel.emit("serve.request", order=7)
    assert tel.maybe_flush() is True  # the first flush always runs
    os.remove(path)
    tel.emit("serve.request", order=8)
    time.sleep(2e-4)
    assert tel.maybe_flush() is False  # inside the snapshot interval...
    rec = load_flight_record(path)  # ...but the autodump still fired
    assert rec is not None and rec["reason"] == "auto"
    assert any(e["kind"] == "serve.request" for e in rec["events"])


def test_flight_signal_dump_in_subprocess(tmp_path):
    """SIGTERM dumps the ring THEN chains to SIG_DFL (default termination
    survives) — in a subprocess, because the re-raise kills the process."""
    path = flight_path_for(str(tmp_path / "sig.jsonl"))
    code = (
        "import os, signal, sys\n"
        "from transformer_tpu.obs.flight import FlightRecorder\n"
        "fr = FlightRecorder(sys.argv[1])\n"
        "fr.record('serve.request', {'order': 1})\n"
        "fr.install_signal_handlers()\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "raise SystemExit('unreachable: SIG_DFL did not terminate')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code, path],
        cwd=str(REPO), capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == -signal.SIGTERM, (proc.returncode, proc.stderr)
    rec = load_flight_record(path)
    assert rec is not None and rec["reason"] == "signal"
    assert [e["kind"] for e in rec["events"]] == ["serve.request"]


# --------------------------------------------------------------------------
# /healthz beside /metrics


def test_healthz_endpoint(tmp_path):
    buf = io.StringIO()
    tel = Telemetry(events=EventLog(buf))
    tel.arm_profiler(baseline={})
    tel.arm_flight(None)
    tel.profiler.record("serve.pool_step", 0.001, tokens=1)
    tel.emit("serve.request", order=0)
    port = tel.start_prometheus_server(0)
    base = f"http://127.0.0.1:{port}"
    try:
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            assert r.status == 200
            assert "perf_seconds_serve_pool_step" in r.read().decode()
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            assert r.status == 200
            doc = json.loads(r.read())
        assert doc["ok"] is True and doc["pid"] == os.getpid()
        assert doc["uptime_s"] >= 0
        assert doc["sinks"]["event_log"]["broken"] is False
        assert doc["flight"]["depth"] >= 1
        assert doc["profiler"]["records"] == 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/bogus", timeout=10)
        assert ei.value.code == 404
        # A hard-downgraded event sink flips liveness to 503.
        tel.events._broken = True
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/healthz", timeout=10)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())["ok"] is False
    finally:
        tel.close()


# --------------------------------------------------------------------------
# the event-catalogue AST gate


def _emitted_kinds() -> set:
    """Every literal event kind at an emit call site in the package."""
    kinds = set()
    for py in sorted((REPO / "transformer_tpu").rglob("*.py")):
        tree = ast.parse(py.read_text(encoding="utf-8"), filename=str(py))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            name = (
                func.attr if isinstance(func, ast.Attribute)
                else getattr(func, "id", None)
            )
            if name not in ("emit", "emit_event", "_emit"):
                continue
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                kinds.add(a0.value)
    return kinds


def test_event_catalogue_covers_every_emit_site():
    from transformer_tpu.obs.events import EVENT_CATALOGUE

    emitted = _emitted_kinds()
    assert emitted, "the AST sweep found no emit sites — the gate is broken"
    unknown = emitted - set(EVENT_CATALOGUE)
    assert not unknown, (
        f"emit sites use kinds missing from EVENT_CATALOGUE: "
        f"{sorted(unknown)} — add them to obs/events.py AND "
        "docs/OBSERVABILITY.md"
    )
    # This PR's kinds are both emitted somewhere and catalogued.
    for kind in ("perf.drift", "flight.dump", "route.postmortem",
                 "metrics.snapshot"):
        assert kind in emitted, kind
        assert kind in EVENT_CATALOGUE, kind


def test_event_catalogue_documented():
    from transformer_tpu.obs.events import EVENT_CATALOGUE

    docs = (REPO / "docs" / "OBSERVABILITY.md").read_text(encoding="utf-8")
    missing = [k for k in EVENT_CATALOGUE if k not in docs]
    assert not missing, (
        f"catalogued kinds undocumented in docs/OBSERVABILITY.md: {missing}"
    )


# --------------------------------------------------------------------------
# armed observatory vs the scheduler: inertness, retraces, the join


def _armed_telemetry(buf=None):
    tel = Telemetry(
        events=EventLog(buf) if buf is not None else None, interval=0.0,
    )
    tel.arm_profiler()
    tel.arm_flight(None)
    return tel


def test_scheduler_byte_identity_with_observatory_armed(lm):
    """Profiler + flight recorder on the serving path change no answer
    byte — and the dense + paged episodes together give ``obs roofline``
    its >= 4 canned programs (the acceptance floor) from one CPU run."""
    reqs = [
        {"prompt": PROMPT_A, "max_new": 6},
        {"prompt": "kl", "max_new": 2},
        {"prompt": "ab cd", "max_new": 4},
    ]
    plain = _scheduler(lm, None).run([dict(r) for r in reqs])
    buf = io.StringIO()
    tel = _armed_telemetry(buf)
    armed = _scheduler(lm, tel).run([dict(r) for r in reqs])
    assert plain == armed
    paged_plain = _scheduler(lm, None, kv_layout="paged").run(
        [dict(r) for r in reqs]
    )
    paged = _scheduler(lm, tel, kv_layout="paged").run(
        [dict(r) for r in reqs]
    )
    assert paged_plain == paged
    assert tel.profiler.stats["records"] > 0
    assert tel.flight.depth() > 0
    summary = tel.profiler.summary()
    for program in ("serve.pool_step", "serve.slot_prefill",
                    "serve.pool_step_paged", "serve.slot_prefill_paged"):
        assert program in summary, sorted(summary)
        assert summary[program]["dispatches"] > 0
    assert summary["serve.pool_step"]["tokens"] > 0
    # The episode's snapshots reconstruct the same programs offline, and
    # the checked-in bank's frozen predictions give them roofline ratios.
    tel.maybe_flush(force=True)
    events = [json.loads(l) for l in buf.getvalue().splitlines()]
    report = roofline_report(events)
    rows = {r["program"]: r for r in report["programs"]}
    assert len(rows) >= 4
    for program in ("serve.pool_step", "serve.pool_step_paged",
                    "serve.slot_prefill", "serve.slot_prefill_paged"):
        assert rows[program].get("roofline_ratio"), program


def test_scheduler_zero_recompiles_with_observatory_armed(lm):
    """Arming profiler + flight recorder must not cost a single recompile
    on the steady-state decode path (retrace-sentinel criterion)."""
    from transformer_tpu.analysis.retrace import RetraceSentinel
    from transformer_tpu.serve import scheduler as sched_mod

    tel = _armed_telemetry()
    warm = _scheduler(lm, tel)
    warm.run([{"prompt": "ab cd", "max_new": 3}])
    sentinel = RetraceSentinel()
    sentinel.watch("_pool_step", sched_mod._pool_step, budget=0)
    sentinel.watch("_slot_prefill", sched_mod._slot_prefill, budget=0)
    sentinel.watch("_pick_pool", sched_mod._pick_pool, budget=0)
    sentinel.snapshot()
    for _ in range(3):
        s = _scheduler(lm, tel)
        out = s.run([{"prompt": "ab cd", "max_new": 3}])
        assert "continuation" in out[0]
    sentinel.assert_within_budget()
    assert tel.profiler.stats["records"] > 0


# --------------------------------------------------------------------------
# the chaos drill: SIGKILL a replica, the supervisor lands its postmortem


@pytest.mark.chaos
def test_sigkill_postmortem_capture(lm, spec_file, tmp_path):
    """SIGKILL the busy replica of a supervised pair: the fleet heals AND
    the victim's flight record — final serve.request spans included —
    lands in a route.postmortem event; ``obs postmortem`` reconstructs
    the incident from the logs + dumps."""
    import contextlib

    from transformer_tpu.obs.__main__ import main as obs_main
    from transformer_tpu.serve.router import ReplicaProcess, Router
    from transformer_tpu.serve.supervisor import Supervisor

    params, cfg, tok = lm

    def worker_args(i):
        return [
            "--model_spec", spec_file, "--serve_slots", "2",
            "--heartbeat_ms", "50", "--prefix_cache_mb", "8",
            "--prefix_block", "4",
            "--metrics_jsonl", str(tmp_path / f"replica{i}.jsonl"),
        ]

    links = [ReplicaProcess.spawn(i, worker_args(i)) for i in range(2)]

    def spawn(index, name, role):
        return ReplicaProcess.spawn(
            index, worker_args(index), role=role, name=name
        )

    sup = Supervisor(spawn, backoff_ms=50.0)
    router_log = str(tmp_path / "router.jsonl")
    telemetry = Telemetry(events=EventLog(router_log))
    router = Router(
        links, encode=tok.encode, bos_id=tok.bos_id, affinity_block=4,
        heartbeat_timeout_s=10.0, telemetry=telemetry, supervisor=sup,
    )
    for link in links:
        link.start_reader(router.inbox)
    deadline = time.time() + 110
    try:
        out = router.run([{"prompt": PROMPT_A, "max_new": 6}] * 6)
        assert all("continuation" in o for o in out)
        victim = max(router.links, key=lambda l: l.answered)
        victim_name, victim_jsonl = victim.name, victim.metrics_jsonl
        assert victim_jsonl, "spawn did not parse --metrics_jsonl"
        # Ask the victim to dump: the wire reply is the deterministic
        # capture origin (the 0.5 s autodump file backstops a race).
        victim.send({"type": "dump"})
        while victim.flight_record is None and time.time() < deadline:
            router.pump()
        assert victim.flight_record, "victim never shipped its record"
        kinds = [e.get("kind") for e in victim.flight_record["events"]]
        assert "serve.request" in kinds, kinds
        os.kill(victim.pid(), signal.SIGKILL)
        while time.time() < deadline:
            router.pump()
            healthy = [
                l for l in router.links
                if not l.dead and not l.warming and not l.draining
            ]
            if len(healthy) == 2 and sup.stats["respawns"] == 1:
                break
        assert sup.stats["respawns"] == 1, sup.stats
        assert sup.stats["postmortems"] >= 1, sup.stats
    finally:
        router.shutdown()
        telemetry.close()
    events = [json.loads(l) for l in open(router_log, encoding="utf-8")]
    pms = [e for e in events if e.get("kind") == "route.postmortem"]
    assert pms, "no route.postmortem in the router log"
    assert pms[0]["replica"] == victim_name
    assert pms[0]["origin"] in ("wire", "file")
    record = pms[0]["record"]
    finals = [
        e for e in record["events"] if e.get("kind") == "serve.request"
    ]
    assert finals, "captured record carries no serve.request spans"
    assert all(f.get("new_tokens") == 6 for f in finals), finals
    # The CLI reconstructs the incident from the same artifacts.
    inputs = [router_log]
    flight_file = flight_path_for(victim_jsonl)
    if os.path.exists(flight_file):
        inputs.append(flight_file)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert obs_main(["postmortem", *inputs, "--format=json"]) == 0
    report = json.loads(buf.getvalue())
    assert report["postmortems"], report
    row = report["postmortems"][0]
    assert row["replica"] == victim_name
    assert row["final_requests"], row
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert obs_main(["postmortem", *inputs]) == 0
    text = buf.getvalue()
    assert "postmortem(s)" in text and victim_name in text


# --------------------------------------------------------------------------
# the bench acceptance: a real CPU sweep measures what the model predicts


@pytest.mark.slow  # subprocess + two jit sweeps: slow tier
def test_decode_bench_emits_measured_roofline_columns(tmp_path):
    """benchmarks/decode_bench.py on CPU: every sweep row carries
    measured_step_p50_ms and roofline_ratio, and ``obs roofline`` over
    the episode reports >= 4 canned programs (the acceptance bar)."""
    from transformer_tpu.obs.__main__ import main as obs_main

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    jsonl = str(tmp_path / "bench.jsonl")
    out = subprocess.run(
        [sys.executable, str(REPO / "benchmarks" / "decode_bench.py"),
         "--layers", "1", "--d_model", "32", "--heads", "2", "--dff", "64",
         "--vocab", "128", "--prompt_len", "16", "--decode_steps", "8",
         "--reps", "1", "--prefix_requests", "4",
         "--kv_layout", "dense,paged", "--metrics_jsonl", jsonl],
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    row = json.loads(out.stdout.strip().splitlines()[-1])
    for layout_row in row["kv_layouts"]:
        assert layout_row["measured_step_p50_ms"] > 0, layout_row
        assert layout_row["roofline_ratio"] > 0, layout_row
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert obs_main(["roofline", jsonl, "--format=json"]) == 0
    report = json.loads(buf.getvalue())
    canned = [
        r["program"] for r in report["programs"]
        if r["program"] in CANNED_PROGRAMS
    ]
    assert len(canned) >= 4, canned
