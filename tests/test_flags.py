"""CLI flag materialization: --preset folds BASELINE configs into unset
flags; explicitly-passed flags always win. Runs in subprocesses because absl
flags are process-global (a second define_flags() would collide)."""

import os
import subprocess
import sys

_SNIPPET = """
import sys
from absl import flags
from transformer_tpu.cli.flags import (
    define_flags, flags_to_model_config, flags_to_train_config,
)
define_flags()
flags.FLAGS(sys.argv)
m = flags_to_model_config(100, 100)
t = flags_to_train_config()
print(m.num_layers, m.d_model, m.dff, m.num_heads, m.tie_embeddings,
      m.decoder_only, m.attention_impl, t.label_smoothing, t.sequence_length,
      t.batch_size)
"""


def _materialize(*argv: str) -> list[str]:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, "-c", _SNIPPET, *argv],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout.strip().split()


def test_no_preset_keeps_reference_defaults():
    vals = _materialize()
    assert vals == [
        "4", "512", "1024", "4", "False", "False", "xla", "0.0", "50", "64"
    ]


def test_preset_big_applies():
    vals = _materialize("--preset=big")
    assert vals[:4] == ["6", "1024", "4096", "16"]
    assert vals[7] == "0.1"  # label smoothing comes with the big config
    assert vals[9] == "32"  # and the benchmark's batch size


def test_explicit_flag_beats_preset():
    vals = _materialize("--preset=big", "--dff=1234")
    assert vals[2] == "1234"
    assert vals[1] == "1024"  # the rest of the preset still lands


def test_preset_long4k_is_decoder_only_flash():
    vals = _materialize("--preset=long4k")
    assert vals[5] == "True" and vals[6] == "flash"
    assert vals[8] == "4096" and vals[9] == "4"


def test_ffn_activation_flag_list_matches_registry():
    """flags.py keeps a jax-import-free literal; pin it to the op registry."""
    from transformer_tpu.cli.flags import _FFN_ACTIVATION_NAMES
    from transformer_tpu.ops.ffn import FFN_ACTIVATIONS

    assert tuple(_FFN_ACTIVATION_NAMES) == FFN_ACTIVATIONS


def test_presets_match_benchmark_configs():
    """--preset promises the BASELINE benchmark shapes; pin _PRESETS against
    benchmarks/run.py's _configs so the two tables cannot drift."""
    import importlib.util

    from transformer_tpu.cli.flags import _PRESETS

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_run", os.path.join(repo, "benchmarks", "run.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    configs = bench._configs()
    assert set(_PRESETS) == set(configs)
    for name, preset in _PRESETS.items():
        model_cfg, train_cfg, batch, seq = configs[name]
        assert preset["num_layers"] == model_cfg.num_layers, name
        assert preset["d_model"] == model_cfg.d_model, name
        assert preset["num_heads"] == model_cfg.num_heads, name
        assert preset["dff"] == model_cfg.dff, name
        assert preset["batch_size"] == batch, name
        assert preset.get("label_smoothing", 0.0) == train_cfg.label_smoothing, name
        assert preset.get("tie_embeddings", False) == model_cfg.tie_embeddings, name
        assert preset.get("decoder_only", False) == model_cfg.decoder_only, name
        if model_cfg.decoder_only:
            assert preset.get("attention_impl") == model_cfg.attention_impl, name
            assert preset.get("sequence_length") == seq, name
