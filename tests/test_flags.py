"""CLI flag materialization: --preset folds BASELINE configs into unset
flags; explicitly-passed flags always win. Runs in subprocesses because absl
flags are process-global (a second define_flags() would collide)."""

import dataclasses
import os
import subprocess
import sys

import pytest

_SNIPPET = """
import sys
from absl import flags
from transformer_tpu.cli.flags import (
    define_flags, flags_to_model_config, flags_to_train_config,
)
define_flags()
flags.FLAGS(sys.argv)
m = flags_to_model_config(100, 100)
t = flags_to_train_config()
print(m.num_layers, m.d_model, m.dff, m.num_heads, m.tie_embeddings,
      m.decoder_only, m.attention_impl, t.label_smoothing, t.sequence_length,
      t.batch_size)
"""


def _materialize(*argv: str) -> list[str]:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, "-c", _SNIPPET, *argv],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout.strip().split()


def test_no_preset_keeps_reference_defaults():
    vals = _materialize()
    assert vals == [
        "4", "512", "1024", "4", "False", "False", "xla", "0.0", "50", "64"
    ]


def test_preset_big_applies():
    vals = _materialize("--preset=big")
    assert vals[:4] == ["6", "1024", "4096", "16"]
    assert vals[7] == "0.1"  # label smoothing comes with the big config
    assert vals[9] == "32"  # and the benchmark's batch size


def test_explicit_flag_beats_preset():
    vals = _materialize("--preset=big", "--dff=1234")
    assert vals[2] == "1234"
    assert vals[1] == "1024"  # the rest of the preset still lands


def test_preset_long4k_is_decoder_only_flash():
    vals = _materialize("--preset=long4k")
    assert vals[5] == "True" and vals[6] == "flash"
    assert vals[8] == "4096" and vals[9] == "4"


def test_ffn_activation_flag_list_matches_registry():
    """flags.py keeps a jax-import-free literal; pin it to the op registry."""
    from transformer_tpu.cli.flags import _FFN_ACTIVATION_NAMES
    from transformer_tpu.ops.ffn import FFN_ACTIVATIONS

    assert tuple(_FFN_ACTIVATION_NAMES) == FFN_ACTIVATIONS


def test_presets_match_benchmark_configs():
    """--preset promises the BASELINE benchmark shapes; pin _PRESETS against
    benchmarks/run.py's _configs so the two tables cannot drift."""
    import importlib.util

    from transformer_tpu.cli.flags import _PRESETS

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_run", os.path.join(repo, "benchmarks", "run.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    configs = bench._configs()
    assert set(_PRESETS) == set(configs)
    for name, preset in _PRESETS.items():
        model_cfg, train_cfg, batch, seq = configs[name]
        assert preset["num_layers"] == model_cfg.num_layers, name
        assert preset["d_model"] == model_cfg.d_model, name
        assert preset["num_heads"] == model_cfg.num_heads, name
        assert preset["dff"] == model_cfg.dff, name
        assert preset["batch_size"] == batch, name
        assert preset.get("label_smoothing", 0.0) == train_cfg.label_smoothing, name
        assert preset.get("tie_embeddings", False) == model_cfg.tie_embeddings, name
        assert preset.get("decoder_only", False) == model_cfg.decoder_only, name
        if model_cfg.decoder_only:
            assert preset.get("attention_impl") == model_cfg.attention_impl, name
            assert preset.get("sequence_length") == seq, name


@pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
def test_serve_loop_end_to_end(tmp_path):
    """cli.serve: build a tiny export, pipe mixed raw/JSON/bad requests
    through the loop, get one JSONL response per request with the loop
    surviving the malformed one."""
    import json

    build = f"""
import jax
jax.config.update("jax_platforms", "cpu")
from transformer_tpu.config import ModelConfig
from transformer_tpu.models import transformer_init
from transformer_tpu.train.checkpoint import export_params
from transformer_tpu.data.tokenizer import SubwordTokenizer
tok = SubwordTokenizer.build_from_corpus(["ab cd ef gh"] * 3, target_vocab_size=270)
tok.save(r"{tmp_path}/vocab.subwords")
cfg = ModelConfig(num_layers=1, d_model=16, num_heads=2, dff=32,
                  input_vocab_size=tok.model_vocab_size,
                  target_vocab_size=tok.model_vocab_size,
                  max_position=32, dtype="float32", dropout_rate=0.0)
export_params(transformer_init(jax.random.PRNGKey(0), cfg), cfg, r"{tmp_path}/model")
"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, "-c", build],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]

    requests = 'ab cd\n{"src": "ef gh", "beam": 2}\n{"nope": 1}\n'
    out = subprocess.run(
        [sys.executable, "-m", "transformer_tpu.cli.serve",
         "--platform=cpu",
         f"--export_path={tmp_path}/model",
         f"--src_vocab_file={tmp_path}/vocab.subwords",
         f"--tgt_vocab_file={tmp_path}/vocab.subwords",
         "--max_len=4"],
        input=requests, capture_output=True, text=True, timeout=300, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(l) for l in out.stdout.strip().splitlines()]
    assert len(lines) == 3, out.stdout
    assert "translation" in lines[0]
    assert "translation" in lines[1]
    assert "error" in lines[2]


@pytest.mark.slow  # heavyweight: slow tier (test_scheduler.py covers fast)
def test_serve_continuous_end_to_end(tmp_path):
    """cli.serve with a decoder-only export: the continuous-batching path
    (--serve_slots, the LM default) answers mixed prompt requests, a raw
    line, and a malformed line — one JSONL response per request, in order,
    identical to a --serve_slots=0 (grouped) run of the same requests."""
    import json

    build = f"""
import jax
jax.config.update("jax_platforms", "cpu")
from transformer_tpu.config import ModelConfig
from transformer_tpu.models import transformer_init
from transformer_tpu.train.checkpoint import export_params
from transformer_tpu.data.tokenizer import SubwordTokenizer
tok = SubwordTokenizer.build_from_corpus(["ab cd ef gh"] * 3, target_vocab_size=270)
tok.save(r"{tmp_path}/vocab.subwords")
cfg = ModelConfig(num_layers=1, d_model=16, num_heads=2, dff=32,
                  input_vocab_size=tok.model_vocab_size,
                  target_vocab_size=tok.model_vocab_size,
                  max_position=32, decoder_only=True, tie_output=True,
                  dtype="float32", dropout_rate=0.0)
export_params(transformer_init(jax.random.PRNGKey(0), cfg), cfg, r"{tmp_path}/model")
"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, "-c", build],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]

    requests = (
        'ab cd\n'
        '{"prompt": "ef gh", "max_new": 3}\n'
        '{"prompt": "ab", "max_new": 8, "temperature": 0.8, "seed": 2}\n'
        '{broken\n'
    )

    def serve(extra):
        r = subprocess.run(
            [sys.executable, "-m", "transformer_tpu.cli.serve",
             "--platform=cpu",
             f"--export_path={tmp_path}/model",
             f"--tgt_vocab_file={tmp_path}/vocab.subwords",
             "--max_len=4", *extra],
            input=requests, capture_output=True, text=True, timeout=300,
            env=env,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        return [json.loads(l) for l in r.stdout.strip().splitlines()]

    cont = serve(["--serve_slots=2", "--prefill_chunk=4"])
    assert len(cont) == 4
    assert "continuation" in cont[0] and "continuation" in cont[1]
    assert "continuation" in cont[2] and "error" in cont[3]
    # Same answers as the grouped decode-to-completion path.
    grouped = serve(["--serve_slots=0"])
    assert [c.get("continuation") for c in cont[:3]] == [
        g.get("continuation") for g in grouped[:3]
    ]


def test_serve_lines_batches_one_decode_per_group(monkeypatch):
    """>=2 concurrent requests with the same decode signature must go
    through ONE translate() call (the batched-serving contract); different
    signatures split into their own groups; order is preserved and a
    malformed line is answered without a decode."""
    from transformer_tpu.cli import serve as serve_mod
    from transformer_tpu.config import ModelConfig
    from transformer_tpu.train import decode as decode_mod

    calls = []

    def fake_translate(params, cfg, src_tok, tgt_tok, sentences, **kw):
        calls.append((tuple(sentences), kw["beam_size"]))
        return [f"T({s})" for s in sentences]

    monkeypatch.setattr(decode_mod, "translate", fake_translate)
    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=32, target_vocab_size=32, max_position=16,
        decoder_only=False,
    )
    lines = [
        "hello there",                      # greedy group
        '{"src": "b", "beam": 2}',          # beam-2 group
        "not json but raw",                 # greedy group (same signature)
        "{broken json",                     # malformed: answered, no decode
        '{"src": "c", "beam": 2}',          # beam-2 group
    ]
    resp = serve_mod.serve_lines(lines, None, cfg, None, None)
    assert len(calls) == 2  # one decode per signature group
    grouped = {beam: s for s, beam in calls}
    assert grouped[1] == ("hello there", "not json but raw")
    assert grouped[2] == ("b", "c")
    assert resp[0] == {"translation": "T(hello there)"}
    assert resp[1] == {"translation": "T(b)"}
    assert resp[2] == {"translation": "T(not json but raw)"}
    assert "error" in resp[3]
    assert resp[4] == {"translation": "T(c)"}


def test_serve_lines_fill_mask(monkeypatch):
    """Encoder-only exports serve 'fill' requests: raw lines map to fill,
    same-top_k requests batch into ONE fill_mask() call, and kind
    mismatches answer with a routing error."""
    from transformer_tpu.cli import serve as serve_mod
    from transformer_tpu.config import ModelConfig
    from transformer_tpu.train import decode as decode_mod

    calls = []

    def fake_fill(params, cfg, tok, texts, top_k=5, **kw):
        calls.append((tuple(texts), top_k))
        return [
            {"filled": t.replace("[MASK]", "x"), "candidates": [[("x", 0.9)]]}
            for t in texts
        ]

    monkeypatch.setattr(decode_mod, "fill_mask", fake_fill)
    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=32, target_vocab_size=32, max_position=16,
        encoder_only=True,
    )
    resp = serve_mod.serve_lines(
        [
            "a [MASK] c",                    # raw line -> fill
            '{"fill": "d [MASK]", "top_k": 2}',
            '{"fill": "e [MASK]"}',          # default top_k group with [0]
            '{"src": "nope"}',               # wrong kind for this export
        ],
        None, cfg, None, None,
    )
    assert len(calls) == 2  # top_k=5 group (2 reqs) + top_k=2 group
    grouped = {k: t for t, k in calls}
    assert grouped[5] == ("a [MASK] c", "e [MASK]")
    assert grouped[2] == ("d [MASK]",)
    assert resp[0]["filled"] == "a x c"
    assert resp[0]["candidates"] == [[["x", 0.9]]]  # JSON-clean lists
    assert resp[1]["filled"] == "d x"
    assert resp[2]["filled"] == "e x"
    assert "serves 'fill'" in resp[3]["error"]

    # top_k out of range answers THAT request with the validation message.
    resp = serve_mod.serve_lines(
        ['{"fill": "a [MASK]", "top_k": 0}'], None, cfg, None, None
    )
    assert "top_k must be in" in resp[0]["error"]

    # A stray 'fill' key on a seq2seq export must not change routing
    # (unknown keys never did before the fill kind existed).
    seq_cfg = dataclasses.replace(cfg, encoder_only=False)

    def fake_translate(params, c, src_tok, tgt_tok, sentences, **kw):
        return [f"T({s})" for s in sentences]

    monkeypatch.setattr(decode_mod, "translate", fake_translate)
    resp = serve_mod.serve_lines(
        ['{"src": "hello", "fill": "stray"}'], None, seq_cfg, None, None
    )
    assert resp[0] == {"translation": "T(hello)"}


def test_serve_lines_sampled_requests_run_batch1(monkeypatch):
    """Greedy LM requests with one signature batch into ONE generate call;
    SAMPLED requests must each run alone — lm_generate holds one rng for a
    whole batch, so a co-batched sampled request's draws would depend on
    its neighbors (and diverge from the continuous scheduler's per-row
    picks)."""
    from transformer_tpu.cli import serve as serve_mod
    from transformer_tpu.config import ModelConfig
    from transformer_tpu.train import decode as decode_mod

    calls = []

    def fake_generate(params, cfg, tok, prompts, **kw):
        calls.append((tuple(prompts), kw.get("temperature"), kw.get("seed")))
        return [f"G({p})" for p in prompts]

    monkeypatch.setattr(decode_mod, "generate", fake_generate)
    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=32, target_vocab_size=32, max_position=16,
        decoder_only=True, tie_output=True,
    )
    resp = serve_mod.serve_lines(
        [
            '{"prompt": "a"}',                                # greedy group
            '{"prompt": "b", "temperature": 0.8, "seed": 2}', # alone
            '{"prompt": "c", "seed": 7}',  # greedy ignores seed: same group
            '{"prompt": "d", "temperature": 0.8, "seed": 2}', # alone
        ],
        None, cfg, None, None,
    )
    assert [r["continuation"] for r in resp] == [
        "G(a)", "G(b)", "G(c)", "G(d)"
    ]
    greedy = [c for c in calls if c[1] == 0.0]
    sampled = [c for c in calls if c[1] == 0.8]
    assert greedy == [(("a", "c"), 0.0, 0)]
    assert sorted(s[0] for s in sampled) == [("b",), ("d",)]


def test_serve_lines_error_isolation(monkeypatch):
    """A request with an unconvertible field answers with an error (not a
    crash), and a group-poisoning request must not fail its innocent
    co-batched neighbors: the group retries per member."""
    from transformer_tpu.cli import serve as serve_mod
    from transformer_tpu.config import ModelConfig
    from transformer_tpu.train import decode as decode_mod

    def fake_translate(params, cfg, src_tok, tgt_tok, sentences, **kw):
        if "poison" in sentences:
            raise RuntimeError("decode blew up")
        return [f"T({s})" for s in sentences]

    monkeypatch.setattr(decode_mod, "translate", fake_translate)
    cfg = ModelConfig(
        num_layers=1, d_model=16, num_heads=2, dff=32,
        input_vocab_size=32, target_vocab_size=32, max_position=16,
        decoder_only=False,
    )
    resp = serve_mod.serve_lines(
        [
            '{"src": "a", "beam": "four"}',  # unconvertible field
            "good one",
            "poison",                        # fails the batched decode
            "good two",
        ],
        None, cfg, None, None,
    )
    assert "error" in resp[0] and "ValueError" in resp[0]["error"]
    assert resp[1] == {"translation": "T(good one)"}
    assert "error" in resp[2] and "decode blew up" in resp[2]["error"]
    assert resp[3] == {"translation": "T(good two)"}


def test_distributed_cli_rejects_cpu_virtual_bf16(monkeypatch):
    """The known XLA:CPU abort (bf16 + single-process multi-virtual-device
    mesh, docs/ROUND4.md) must be refused with a UsageError BEFORE any
    collective runs — a clear error + message, never a runtime abort. The
    predicate takes jax as a parameter, so pin it in-process with a stub
    (no XLA boot needed)."""
    from absl import app

    from transformer_tpu.cli import distributed_train as dt

    class StubJax:
        def __init__(self, backend="cpu", procs=1, ndev=4):
            self._b, self._p, self._n = backend, procs, ndev

        def default_backend(self):
            return self._b

        def process_count(self):
            return self._p

        def devices(self):
            return [object()] * self._n

    monkeypatch.delenv("TRANSFORMER_TPU_ALLOW_CPU_BF16", raising=False)
    with pytest.raises(app.UsageError, match="float32"):
        dt._reject_cpu_virtual_bf16(StubJax(), "bfloat16")

    # fp32 on the same mesh is the supported path and must pass the guard.
    dt._reject_cpu_virtual_bf16(StubJax(), "float32")

    # bf16 is fine wherever the abort can't happen: real TPU backend,
    # multi-host, or a single device.
    dt._reject_cpu_virtual_bf16(StubJax(backend="tpu"), "bfloat16")
    dt._reject_cpu_virtual_bf16(StubJax(procs=2), "bfloat16")
    dt._reject_cpu_virtual_bf16(StubJax(ndev=1), "bfloat16")

    # The escape hatch re-enables the combination for probing newer XLA.
    monkeypatch.setenv("TRANSFORMER_TPU_ALLOW_CPU_BF16", "1")
    dt._reject_cpu_virtual_bf16(StubJax(), "bfloat16")
