"""Ring attention and Ulysses sequence parallelism vs the single-device oracle.

Runs over a real 8-device 'seq' mesh on the forced CPU platform (conftest) —
the JAX-native fake-multi-device backend of SURVEY.md §4 — asserting the
sequence-parallel implementations match full-sequence attention, forward and
backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import MeshConfig
from transformer_tpu.ops.attention import dot_product_attention
from transformer_tpu.parallel.mesh import make_mesh
from transformer_tpu.parallel.ring_attention import make_sequence_parallel_attention


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(MeshConfig(data=1, fsdp=1, model=1, seq=8))


@pytest.fixture
def qkv():
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(rng.normal(size=(2, 64, 8, 16)), jnp.float32)  # noqa: E731
    q, k, v = mk(), mk(), mk()
    kv_mask = jnp.asarray(rng.integers(0, 2, (2, 64)), bool).at[:, :2].set(True)
    return q, k, v, kv_mask


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
class TestSequenceParallelAttention:
    def test_plain(self, seq_mesh, qkv, impl):
        q, k, v, _ = qkv
        fn = make_sequence_parallel_attention(seq_mesh, impl=impl)
        want, _ = dot_product_attention(q, k, v)
        np.testing.assert_allclose(fn(q, k, v), want, atol=1e-5)

    def test_causal_with_padding(self, seq_mesh, qkv, impl):
        q, k, v, kv_mask = qkv
        fn = make_sequence_parallel_attention(seq_mesh, impl=impl)
        mask = jnp.logical_and(
            jnp.tril(jnp.ones((64, 64), bool))[None, None],
            kv_mask[:, None, None, :],
        )
        want, _ = dot_product_attention(q, k, v, mask)
        np.testing.assert_allclose(
            fn(q, k, v, kv_mask=kv_mask, causal=True), want, atol=1e-5
        )

    def test_grads(self, seq_mesh, qkv, impl):
        q, k, v, kv_mask = qkv
        fn = make_sequence_parallel_attention(seq_mesh, impl=impl)
        mask = jnp.logical_and(
            jnp.tril(jnp.ones((64, 64), bool))[None, None],
            kv_mask[:, None, None, :],
        )

        def f_sp(q, k, v):
            return (fn(q, k, v, kv_mask=kv_mask, causal=True) ** 2).sum()

        def f_ref(q, k, v):
            return (dot_product_attention(q, k, v, mask)[0] ** 2).sum()

        got = jax.grad(f_sp, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, atol=2e-5)


def test_ulysses_rejects_indivisible_heads(seq_mesh):
    """8-way seq axis cannot split 6 heads."""
    fn = make_sequence_parallel_attention(seq_mesh, impl="ulysses")
    x = jnp.zeros((2, 64, 6, 16), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        fn(x, x, x)


def test_ring_under_jit(seq_mesh, qkv):
    q, k, v, _ = qkv
    fn = make_sequence_parallel_attention(seq_mesh, impl="ring")
    jitted = jax.jit(lambda q, k, v: fn(q, k, v, causal=True))
    want, _ = dot_product_attention(
        q, k, v, jnp.tril(jnp.ones((64, 64), bool))[None, None]
    )
    np.testing.assert_allclose(jitted(q, k, v), want, atol=1e-5)
