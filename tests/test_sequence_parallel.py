"""Ring attention and Ulysses sequence parallelism vs the single-device oracle.

Runs over a real 8-device 'seq' mesh on the forced CPU platform (conftest) —
the JAX-native fake-multi-device backend of SURVEY.md §4 — asserting the
sequence-parallel implementations match full-sequence attention, forward and
backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import MeshConfig
from transformer_tpu.ops.attention import dot_product_attention
from transformer_tpu.parallel.mesh import make_mesh
from transformer_tpu.parallel.ring_attention import make_sequence_parallel_attention

# Heavyweight module (interpret-mode Pallas / 8-device shard_map /
# multi-process): excluded from the fast path, pytest -m 'not slow'.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(MeshConfig(data=1, fsdp=1, model=1, seq=8))


@pytest.fixture
def qkv():
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(rng.normal(size=(2, 64, 8, 16)), jnp.float32)  # noqa: E731
    q, k, v = mk(), mk(), mk()
    kv_mask = jnp.asarray(rng.integers(0, 2, (2, 64)), bool).at[:, :2].set(True)
    return q, k, v, kv_mask


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
class TestSequenceParallelAttention:
    def test_plain(self, seq_mesh, qkv, impl):
        q, k, v, _ = qkv
        fn = make_sequence_parallel_attention(seq_mesh, impl=impl)
        want, _ = dot_product_attention(q, k, v)
        np.testing.assert_allclose(fn(q, k, v), want, atol=1e-5)

    def test_causal_with_padding(self, seq_mesh, qkv, impl):
        q, k, v, kv_mask = qkv
        fn = make_sequence_parallel_attention(seq_mesh, impl=impl)
        mask = jnp.logical_and(
            jnp.tril(jnp.ones((64, 64), bool))[None, None],
            kv_mask[:, None, None, :],
        )
        want, _ = dot_product_attention(q, k, v, mask)
        np.testing.assert_allclose(
            fn(q, k, v, kv_mask=kv_mask, causal=True), want, atol=1e-5
        )

    def test_window(self, seq_mesh, qkv, impl):
        """Sliding window through sequence parallelism: ring applies a
        STATIC per-hop band (out-of-band hops stop the ring entirely);
        ulysses passes the band to its per-device flash call. Windows that
        cross chunk boundaries (W=24 vs C=8) and sub-chunk windows (W=5)
        must both match the banded oracle."""
        from transformer_tpu.ops.masks import make_causal_mask

        q, k, v, _ = qkv
        fn = make_sequence_parallel_attention(seq_mesh, impl=impl)
        for w in (5, 24):
            want, _ = dot_product_attention(
                q, k, v, make_causal_mask(64, window=w)
            )
            np.testing.assert_allclose(
                fn(q, k, v, causal=True, window=w), want, atol=1e-5,
                err_msg=f"w={w}",
            )

    def test_window_grads(self, seq_mesh, qkv, impl):
        """Banded backward: ring re-homes dk/dv with one extra permute when
        the window stops the ring early."""
        from transformer_tpu.ops.masks import make_causal_mask

        q, k, v, _ = qkv
        fn = make_sequence_parallel_attention(seq_mesh, impl=impl)
        mask = make_causal_mask(64, window=20)

        def f_sp(q, k, v):
            return (fn(q, k, v, causal=True, window=20) ** 2).sum()

        def f_ref(q, k, v):
            out, _ = dot_product_attention(q, k, v, mask)
            return (out**2).sum()

        got = jax.grad(f_sp, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for g, w_ in zip(got, want):
            np.testing.assert_allclose(g, w_, atol=5e-5)

    def test_grads(self, seq_mesh, qkv, impl):
        q, k, v, kv_mask = qkv
        fn = make_sequence_parallel_attention(seq_mesh, impl=impl)
        mask = jnp.logical_and(
            jnp.tril(jnp.ones((64, 64), bool))[None, None],
            kv_mask[:, None, None, :],
        )

        def f_sp(q, k, v):
            return (fn(q, k, v, kv_mask=kv_mask, causal=True) ** 2).sum()

        def f_ref(q, k, v):
            return (dot_product_attention(q, k, v, mask)[0] ** 2).sum()

        got = jax.grad(f_sp, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, atol=2e-5)


def test_ring_grouped_kv_matches_oracle(seq_mesh):
    """GQA through the ring with NO kv repeat: kv enters at H_kv heads, the
    kernels' index maps assign each q-head its group, and the per-hop
    ppermute payload shrinks by the group factor. Forward and all grads
    must match the grouped XLA oracle (kv grads stay at H_kv heads)."""
    rng = np.random.default_rng(3)
    H, Hkv = 8, 2
    q = jnp.asarray(rng.normal(size=(2, 64, H, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 64, Hkv, 16)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 64, Hkv, 16)), jnp.float32)
    kv_mask = jnp.asarray(rng.integers(0, 2, (2, 64)), bool).at[:, :2].set(True)
    fn = make_sequence_parallel_attention(seq_mesh, impl="ring")
    mask = jnp.logical_and(
        jnp.tril(jnp.ones((64, 64), bool))[None, None],
        kv_mask[:, None, None, :],
    )
    want, _ = dot_product_attention(q, k, v, mask)
    np.testing.assert_allclose(
        fn(q, k, v, kv_mask=kv_mask, causal=True), want, atol=1e-5
    )

    def f_sp(q, k, v):
        return (fn(q, k, v, kv_mask=kv_mask, causal=True) ** 2).sum()

    def f_ref(q, k, v):
        return (dot_product_attention(q, k, v, mask)[0] ** 2).sum()

    got = jax.grad(f_sp, argnums=(0, 1, 2))(q, k, v)
    want_g = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want_g):
        assert g.shape == w.shape
        np.testing.assert_allclose(g, w, atol=2e-5)


def test_ring_bf16_matches_full_attention(seq_mesh):
    """bf16 inputs (the TPU training dtype): ring must agree with plain
    attention at bf16 tolerance — inputs feed the MXU in bf16, accumulation
    stays fp32 (the flash kernel's numerics)."""
    rng = np.random.default_rng(3)
    mk = lambda: jnp.asarray(rng.normal(size=(2, 64, 8, 16)), jnp.bfloat16)  # noqa: E731
    q, k, v = mk(), mk(), mk()
    fn = make_sequence_parallel_attention(seq_mesh, impl="ring")
    got = fn(q, k, v, causal=True)
    want, _ = dot_product_attention(
        q, k, v, jnp.tril(jnp.ones((64, 64), bool))[None, None]
    )
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=3e-2, rtol=3e-2,
    )


def test_ring_bf16_grads_match_full_attention(seq_mesh):
    """bf16 backward: gradients through the ring (bf16 matmul inputs, fp32
    accumulation, p cast before the PV dot) must track the full-attention
    gradients at bf16 tolerance."""
    rng = np.random.default_rng(5)
    mk = lambda: jnp.asarray(rng.normal(size=(2, 64, 8, 16)), jnp.bfloat16)  # noqa: E731
    q, k, v = mk(), mk(), mk()
    fn = make_sequence_parallel_attention(seq_mesh, impl="ring")

    def f_sp(q, k, v):
        return (fn(q, k, v, causal=True).astype(jnp.float32) ** 2).sum()

    def f_ref(q, k, v):
        mask = jnp.tril(jnp.ones((64, 64), bool))[None, None]
        out, _ = dot_product_attention(q, k, v, mask)
        return (out.astype(jnp.float32) ** 2).sum()

    got = jax.grad(f_sp, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(w, np.float32),
            atol=0.15, rtol=0.15,
        )


def test_ulysses_rejects_indivisible_heads(seq_mesh):
    """8-way seq axis cannot split 6 heads."""
    fn = make_sequence_parallel_attention(seq_mesh, impl="ulysses")
    x = jnp.zeros((2, 64, 6, 16), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        fn(x, x, x)


def test_ring_under_jit(seq_mesh, qkv):
    q, k, v, _ = qkv
    fn = make_sequence_parallel_attention(seq_mesh, impl="ring")
    jitted = jax.jit(lambda q, k, v: fn(q, k, v, causal=True))
    want, _ = dot_product_attention(
        q, k, v, jnp.tril(jnp.ones((64, 64), bool))[None, None]
    )
    np.testing.assert_allclose(jitted(q, k, v), want, atol=1e-5)


class TestSeqParallelTraining:
    """Sequence parallelism as a *training path* (VERDICT round 1: ring/
    Ulysses never reached the model or trainer): DistributedTrainer with
    MeshConfig(seq>1) must train and match the single-device run."""

    def _configs(self, attention_impl, decoder_only=False, seq_len=9):
        from transformer_tpu.config import ModelConfig, TrainConfig

        model = ModelConfig(
            num_layers=2, d_model=16, num_heads=4, dff=32,
            input_vocab_size=32, target_vocab_size=32, max_position=32,
            dtype="float32", dropout_rate=0.0,
            attention_impl=attention_impl, decoder_only=decoder_only,
        )
        tcfg = TrainConfig(
            batch_size=8, sequence_length=seq_len, epochs=1, warmup_steps=10,
            loss_normalization="tokens",
        )
        return model, tcfg

    def _batches(self, n, seq_len=9):
        out = []
        for i in range(n):
            ks, kt = jax.random.split(jax.random.PRNGKey(100 + i))
            src = np.asarray(jax.random.randint(ks, (8, seq_len), 1, 32), np.int32)
            tgt = np.asarray(jax.random.randint(kt, (8, seq_len), 1, 32), np.int32)
            out.append((src, tgt))
        return out

    def _single_losses(self, model, tcfg, batches):
        from transformer_tpu.train import create_train_state, make_train_step

        state = create_train_state(jax.random.PRNGKey(0), model, tcfg)
        step = jax.jit(make_train_step(model, tcfg))
        rng = jax.random.PRNGKey(42)
        losses = []
        for src, tgt in batches:
            state, m = step(state, src, tgt, rng)
            losses.append(float(m["loss"]))
        return losses

    def _mesh_losses(self, model, tcfg, batches, mesh_cfg):
        from transformer_tpu.parallel import (
            create_sharded_state, make_mesh, make_sharded_steps, put_batch,
        )

        mesh = make_mesh(mesh_cfg)
        state, shardings = create_sharded_state(
            jax.random.PRNGKey(0), model, tcfg, mesh
        )
        train_step, _ = make_sharded_steps(
            mesh, model, tcfg, shardings, shard_seq=True, donate=False
        )
        rng = jax.random.PRNGKey(42)
        losses = []
        for src, tgt in batches:
            state, m = train_step(
                state,
                put_batch(src, mesh, shard_seq=True),
                put_batch(tgt, mesh, shard_seq=True),
                rng,
            )
            losses.append(float(m["loss"]))
        return losses

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    def test_seq2_matches_single_device(self, impl):
        model, tcfg = self._configs(impl)
        ref_model, _ = self._configs("xla")
        batches = self._batches(3)
        want = self._single_losses(ref_model, tcfg, batches)
        got = self._mesh_losses(
            model, tcfg, batches, MeshConfig(data=4, seq=2)
        )
        np.testing.assert_allclose(got, want, rtol=2e-4)

    def test_seq4_with_fsdp_matches_single_device(self):
        model, tcfg = self._configs("ring")
        ref_model, _ = self._configs("xla")
        batches = self._batches(3)
        want = self._single_losses(ref_model, tcfg, batches)
        got = self._mesh_losses(
            model, tcfg, batches, MeshConfig(data=1, fsdp=2, seq=4)
        )
        np.testing.assert_allclose(got, want, rtol=2e-4)

    def test_decoder_only_long_context_ring(self):
        """The 4k-config shape (BASELINE configs[4]) scaled down: causal LM
        training with the sequence split over the mesh — the multi-chip
        long-context path SURVEY §5 demands."""
        model, tcfg = self._configs("ring", decoder_only=True, seq_len=17)
        ref_model, _ = self._configs("xla", decoder_only=True, seq_len=17)
        batches = self._batches(3, seq_len=17)
        want = self._single_losses(ref_model, tcfg, batches)
        got = self._mesh_losses(
            model, tcfg, batches, MeshConfig(data=1, seq=8)
        )
        np.testing.assert_allclose(got, want, rtol=2e-4)

    def test_gqa_ring_matches_single_device(self):
        """Grouped-query kv rides the ring at H_kv heads (no repeat) inside
        a full training step."""
        import dataclasses

        model, tcfg = self._configs("ring")
        model = dataclasses.replace(model, num_kv_heads=2)
        ref_model = dataclasses.replace(model, attention_impl="xla")
        batches = self._batches(3)
        want = self._single_losses(ref_model, tcfg, batches)
        got = self._mesh_losses(model, tcfg, batches, MeshConfig(data=4, seq=2))
        np.testing.assert_allclose(got, want, rtol=2e-4)

    @pytest.mark.parametrize(
        "impl,kv_heads,mesh_kw",
        [
            # H_kv=2 % model=2 == 0: kv head blocks align with q head
            # blocks — kv rides sharded at H_kv heads.
            ("ring", 2, dict(data=2, model=2, seq=2)),
            # MQA H_kv=1 on model=2: alignment impossible — the repeat
            # fallback in seq_parallel_attention must fire.
            ("ring", 1, dict(data=2, model=2, seq=2)),
            # H_kv=2 % seq=2 == 0: kv all-to-alls at its own head count.
            ("ulysses", 2, dict(data=4, seq=2)),
            # MQA H_kv=1 on seq=2: head all-to-all can't split 1 — repeat
            # fallback again.
            ("ulysses", 1, dict(data=4, seq=2)),
            # Ulysses under a model axis: LOCAL kv heads (2/2 = 1) don't
            # divide seq=2 even though the global count does — the fallback
            # must consult the per-shard head count (review finding).
            ("ulysses", 2, dict(data=2, model=2, seq=2)),
        ],
    )
    def test_grouped_kv_sharding_corners(self, impl, kv_heads, mesh_kw):
        """Every branch of the grouped-kv spec/fallback logic in
        seq_context.seq_parallel_attention, against the single-device
        oracle."""
        import dataclasses

        model, tcfg = self._configs(impl)
        model = dataclasses.replace(model, num_kv_heads=kv_heads)
        ref_model = dataclasses.replace(model, attention_impl="xla")
        batches = self._batches(2)
        want = self._single_losses(ref_model, tcfg, batches)
        got = self._mesh_losses(model, tcfg, batches, MeshConfig(**mesh_kw))
        np.testing.assert_allclose(got, want, rtol=2e-4)

    def test_ring_with_chunked_loss_matches_monolithic(self):
        """r2 VERDICT next-#5: loss_chunks composes with the sequence-
        parallel forward — ring attention + chunked vocab-projection CE (the
        long-context memory lever pair) must match the single-device
        monolithic loss."""
        import dataclasses

        model, tcfg = self._configs("ring", decoder_only=True, seq_len=17)
        ref_model, _ = self._configs("xla", decoder_only=True, seq_len=17)
        tcfg_chunk = dataclasses.replace(tcfg, loss_chunks=4)
        batches = self._batches(3, seq_len=17)
        want = self._single_losses(ref_model, tcfg, batches)
        got = self._mesh_losses(
            model, tcfg_chunk, batches, MeshConfig(data=2, seq=4)
        )
        np.testing.assert_allclose(got, want, rtol=2e-4)

    def test_distributed_trainer_seq_axis(self):
        """End-to-end: DistributedTrainer(MeshConfig(seq=2)) fits."""
        from transformer_tpu.parallel import DistributedTrainer, make_mesh

        model, tcfg = self._configs("ring")
        mesh = make_mesh(MeshConfig(data=4, seq=2))
        batches = self._batches(2)

        class DS:
            def batches(self, epoch):
                yield from batches

        trainer = DistributedTrainer(model, tcfg, mesh, log_fn=lambda *_: None)
        trainer.fit(DS())
        assert int(jax.device_get(trainer.state.step)) == 2

    def test_xla_impl_with_seq_axis_rejected(self):
        from transformer_tpu.parallel import DistributedTrainer, make_mesh

        model, tcfg = self._configs("xla")
        mesh = make_mesh(MeshConfig(data=4, seq=2))
        with pytest.raises(ValueError, match="sequence-parallel"):
            DistributedTrainer(model, tcfg, mesh)

    def test_ring_without_context_raises(self):
        from transformer_tpu.models import transformer_apply, transformer_init

        model, _ = self._configs("ring")
        params = transformer_init(jax.random.PRNGKey(0), model)
        ids = np.ones((2, 8), np.int32)
        with pytest.raises(RuntimeError, match="sequence-parallel context"):
            transformer_apply(params, ids, ids, model)
