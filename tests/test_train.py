"""Training-engine tests (SURVEY.md §4 plan): schedule curve, loss masking,
label smoothing, checkpoint round-trip + rotation, overfit-one-batch
integration, greedy decode EOS semantics, TensorBoard wire format, BLEU."""

import math
import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig, TrainConfig
from transformer_tpu.models import transformer_init
from transformer_tpu.train import (
    CheckpointManager,
    create_train_state,
    greedy_decode,
    make_eval_step,
    make_train_step,
    masked_cross_entropy,
    noam_schedule,
)
from transformer_tpu.train.checkpoint import export_params, load_exported_params
from transformer_tpu.train.decode import translate
from transformer_tpu.utils.bleu import corpus_bleu
from transformer_tpu.utils.tensorboard import SummaryWriter, _masked_crc

TINY = ModelConfig(
    num_layers=1, d_model=16, num_heads=2, dff=32,
    input_vocab_size=30, target_vocab_size=30, max_position=32, dtype="float32",
    dropout_rate=0.0,
)
TCFG = TrainConfig(batch_size=4, sequence_length=8, epochs=1, warmup_steps=100)


class TestSchedule:
    def test_noam_curve(self):
        """Closed-form check: rises linearly to warmup, then decays as
        rsqrt(step) (reference train.py:30-34)."""
        sched = noam_schedule(d_model=512, warmup_steps=4000)
        s = np.asarray([sched(i) for i in [0, 999, 3999, 7999, 99999]])
        # linear region: lr(1000)/lr(4000) ≈ 1000/4000
        np.testing.assert_allclose(s[1] / s[2], 1000 / 4000, rtol=1e-4)
        # peak at warmup boundary
        expected_peak = 512**-0.5 * 4000**-0.5
        np.testing.assert_allclose(s[2], expected_peak, rtol=1e-4)
        # decay region: lr ∝ step^-0.5
        np.testing.assert_allclose(s[3] / s[4], (100000 / 8000) ** 0.5, rtol=1e-3)

    def test_warmup_default_matches_reference(self):
        assert TrainConfig().warmup_steps == 60000

    def test_cosine_curve(self):
        from transformer_tpu.train.schedule import cosine_schedule

        sched = cosine_schedule(1e-3, warmup_steps=100, decay_steps=1000)
        # Linear warmup hits the peak at the boundary.
        np.testing.assert_allclose(float(sched(99)), 1e-3, rtol=1e-5)
        np.testing.assert_allclose(float(sched(49)), 5e-4, rtol=2e-2)
        # Midpoint of the cosine: halfway between peak and floor.
        np.testing.assert_allclose(float(sched(550)), (1e-3 + 1e-4) / 2, rtol=1e-4)
        # Floor (peak/10) at and beyond the horizon.
        np.testing.assert_allclose(float(sched(1000)), 1e-4, rtol=1e-5)
        np.testing.assert_allclose(float(sched(5000)), 1e-4, rtol=1e-5)

    def test_constant_curve(self):
        from transformer_tpu.train.schedule import constant_schedule

        sched = constant_schedule(3e-4, warmup_steps=10)
        np.testing.assert_allclose(float(sched(4)), 1.5e-4, rtol=1e-5)
        np.testing.assert_allclose(float(sched(10)), 3e-4, rtol=1e-6)
        np.testing.assert_allclose(float(sched(9999)), 3e-4, rtol=1e-6)

    def test_cosine_trains_through_config(self):
        import dataclasses

        tc = dataclasses.replace(
            TCFG, lr_schedule="cosine", peak_lr=1e-3,
            warmup_steps=20, lr_decay_steps=200,
        )
        state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        step = jax.jit(make_train_step(TINY, tc))
        r = np.random.default_rng(0)
        src = jnp.asarray(r.integers(1, 28, (4, 8)), jnp.int32)
        tgt = jnp.asarray(r.integers(1, 28, (4, 8)), jnp.int32)
        rng = jax.random.PRNGKey(1)
        first = None
        for _ in range(60):
            state, m = step(state, src, tgt, rng)
            first = float(m["loss"]) if first is None else first
        assert float(m["loss"]) < first * 0.6

    def test_cosine_requires_peak_and_horizon(self):
        with pytest.raises(ValueError, match="peak_lr"):
            TrainConfig(lr_schedule="cosine", lr_decay_steps=10**6)
        with pytest.raises(ValueError, match="lr_decay_steps"):
            TrainConfig(lr_schedule="cosine", peak_lr=1e-3)


class TestLoss:
    def test_pad_positions_contribute_zero(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 10))
        targets = jnp.array([[1, 2, 0, 0], [3, 0, 0, 0]])
        loss, m = masked_cross_entropy(logits, targets)
        assert float(m["weight"]) == 3.0
        # changing logits at pad positions must not change the loss
        logits2 = logits.at[:, 2:, :].add(100.0)
        loss2, _ = masked_cross_entropy(logits2, targets)
        np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-6)

    def test_matches_numpy_oracle(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 5))
        targets = jnp.array([[1, 2, 3], [4, 1, 0]])
        loss, _ = masked_cross_entropy(logits, targets)
        lp = np.asarray(jax.nn.log_softmax(logits, -1), dtype=np.float64)
        t = np.asarray(targets)
        per = -lp[np.arange(2)[:, None], np.arange(3)[None, :], t]
        mask = t != 0
        np.testing.assert_allclose(float(loss), per[mask].mean(), rtol=1e-5)

    def test_batch_normalization_parity(self):
        """'batch' mode reproduces the reference rule: sum/batch_size
        (train.py:88)."""
        logits = jax.random.normal(jax.random.PRNGKey(2), (4, 3, 5))
        targets = jnp.ones((4, 3), jnp.int32)
        loss, m = masked_cross_entropy(
            logits, targets, normalization="batch", batch_size=4
        )
        np.testing.assert_allclose(float(loss), float(m["loss_sum"]) / 4, rtol=1e-6)

    def test_label_smoothing_raises_loss_on_confident_model(self):
        logits = jnp.full((1, 2, 5), -10.0).at[..., 1].set(10.0)
        targets = jnp.ones((1, 2), jnp.int32)
        sharp, _ = masked_cross_entropy(logits, targets)
        smooth, _ = masked_cross_entropy(logits, targets, label_smoothing=0.1)
        assert float(smooth) > float(sharp)


class _FixedBatches:
    """Minimal dataset stub: the same batch ``n`` times per epoch."""

    def __init__(self, n=4, seed=0):
        self.n = n
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
        self.src = np.asarray(jax.random.randint(k1, (4, 8), 1, 30))
        self.tgt = np.asarray(jax.random.randint(k2, (4, 8), 1, 30))

    def __len__(self):
        return self.n

    def batches(self, epoch=0):
        for _ in range(self.n):
            yield self.src, self.tgt


class _VariedBatches:
    """Dataset stub with per-step-DISTINCT batches (so trajectory parity is
    meaningful) and an optional narrower final batch (so the multi-step
    grouper's shape-change flush is exercised)."""

    def __init__(self, n=7, seed=0, narrow_last=False):
        self.n = n
        self.seed = seed
        self.narrow_last = narrow_last

    def __len__(self):
        return self.n

    def batches(self, epoch=0):
        for i in range(self.n):
            k = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch * 1000 + i)
            k1, k2 = jax.random.split(k)
            w = 6 if (self.narrow_last and i == self.n - 1) else 8
            yield (
                np.asarray(jax.random.randint(k1, (4, w), 1, 30)),
                np.asarray(jax.random.randint(k2, (4, w), 1, 30)),
            )


class TestMultistepDispatch:
    @pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
    def test_scan_matches_sequential(self):
        """K optimizer steps inside one jitted scan (steps_per_dispatch)
        must reproduce K separate dispatches: same params, same metric sums
        (pre-reduced on device)."""
        from transformer_tpu.train.trainer import make_multistep_train_step

        K = 4
        rng = jax.random.PRNGKey(3)
        srcs = np.asarray(
            jax.random.randint(jax.random.PRNGKey(1), (K, 4, 8), 1, 30)
        )
        tgts = np.asarray(
            jax.random.randint(jax.random.PRNGKey(2), (K, 4, 8), 1, 30)
        )
        step = make_train_step(TINY, TCFG)

        s_ref = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        jstep = jax.jit(step)
        sums = {"loss_sum": 0.0, "weight": 0.0, "correct": 0.0}
        for i in range(K):
            s_ref, m = jstep(s_ref, srcs[i], tgts[i], rng)
            for k in sums:
                sums[k] += float(m[k])

        s_multi = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        multi = jax.jit(make_multistep_train_step(step))
        s_multi, mm = multi(s_multi, srcs, tgts, rng)

        assert int(s_multi.step) == K
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
            s_ref.params, s_multi.params,
        )
        for k in sums:
            np.testing.assert_allclose(float(mm[k]), sums[k], rtol=1e-5, err_msg=k)
        np.testing.assert_allclose(
            float(mm["loss"]), sums["loss_sum"] / max(sums["weight"], 1.0),
            rtol=1e-5,
        )

    @pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
    def test_trainer_trajectory_parity(self):
        """A full Trainer.fit with steps_per_dispatch=3 over 7 varied batches
        (groups 3+3+1, final batch a different width → shape-change flush)
        must land on the same params and epoch metrics as the plain loop."""
        import dataclasses

        from transformer_tpu.train import Trainer

        def run(spd):
            tc = dataclasses.replace(
                TCFG, epochs=2, warmup_steps=10, steps_per_dispatch=spd,
                eval_every_steps=0, log_every_steps=0,
            )
            state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
            tr = Trainer(TINY, tc, state, log_fn=lambda s: None)
            tr.fit(_VariedBatches(n=7, seed=5, narrow_last=True))
            return tr

        ref, multi = run(1), run(3)
        assert int(multi.state.step) == 14
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
            ref.state.params, multi.state.params,
        )
        np.testing.assert_allclose(
            multi.train_metrics.loss, ref.train_metrics.loss, rtol=1e-5
        )
        np.testing.assert_allclose(
            multi.train_metrics.accuracy, ref.train_metrics.accuracy, rtol=1e-5
        )

    def test_log_eval_boundary_crossing(self):
        """A K-step dispatch that jumps OVER a log/eval boundary must still
        trigger the log/eval (boundary-crossing check, not step % N == 0)."""
        import dataclasses

        from transformer_tpu.train import Trainer

        tc = dataclasses.replace(
            TCFG, epochs=1, warmup_steps=10, steps_per_dispatch=3,
            log_every_steps=5, eval_every_steps=5, eval_max_batches=1,
        )
        state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        logs = []
        tr = Trainer(TINY, tc, state, log_fn=logs.append)
        # 6 identical-shape batches -> dispatches end at steps 3 and 6;
        # step 5 is never hit exactly, but 3->6 crosses it.
        tr.fit(_FixedBatches(n=6, seed=0), _FixedBatches(n=1, seed=7))
        assert any("step 6 " in l for l in logs), logs
        assert any("eval loss" in l for l in logs), logs

    def test_rejects_bad_config(self):
        import dataclasses

        with pytest.raises(ValueError, match="steps_per_dispatch"):
            dataclasses.replace(TCFG, steps_per_dispatch=0)

    def test_rejects_eager_mode(self):
        import dataclasses

        from transformer_tpu.train import Trainer

        tc = dataclasses.replace(
            TCFG, steps_per_dispatch=2, enable_function=False
        )
        state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        tr = Trainer(TINY, tc, state, log_fn=lambda s: None)
        # The guard fires at fit() time, where only the plain eager Trainer
        # lacks a scanned step (DistributedTrainer always jits its own).
        with pytest.raises(ValueError, match="enable_function"):
            tr.fit(_FixedBatches(n=2, seed=0))

    @pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
    def test_batch_normalization_loss_metric(self):
        """Under loss_normalization='batch' the per-dispatch 'loss' must be
        the mean of the K per-step batch-normalized losses, not the
        token-normalized ratio."""
        import dataclasses

        from transformer_tpu.train.trainer import make_multistep_train_step

        cfg = dataclasses.replace(TCFG, loss_normalization="batch")
        K = 3
        srcs = np.asarray(
            jax.random.randint(jax.random.PRNGKey(1), (K, 4, 8), 1, 30)
        )
        tgts = np.asarray(
            jax.random.randint(jax.random.PRNGKey(2), (K, 4, 8), 1, 30)
        )
        rng = jax.random.PRNGKey(3)
        step = make_train_step(TINY, cfg)

        s_ref = create_train_state(jax.random.PRNGKey(0), TINY, cfg)
        jstep = jax.jit(step)
        per_step = []
        for i in range(K):
            s_ref, m = jstep(s_ref, srcs[i], tgts[i], rng)
            per_step.append(float(m["loss"]))

        s_multi = create_train_state(jax.random.PRNGKey(0), TINY, cfg)
        multi = jax.jit(
            make_multistep_train_step(
                step, loss_normalization="batch", batch_size=cfg.batch_size
            )
        )
        _, mm = multi(s_multi, srcs, tgts, rng)
        np.testing.assert_allclose(
            float(mm["loss"]), np.mean(per_step), rtol=1e-5
        )


class TestEarlyStopping:
    def test_stops_when_eval_plateaus(self):
        """Overfitting a fixed batch while evaluating on a DIFFERENT fixed
        batch: eval loss rises/plateaus once the model memorizes, so
        patience=2 must end the run well before the epoch budget."""
        import dataclasses

        from transformer_tpu.train import Trainer

        tc = dataclasses.replace(
            TCFG, epochs=40, warmup_steps=10, early_stop_patience=2,
            eval_every_steps=0, log_every_steps=0,
        )
        state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        logs = []
        tr = Trainer(TINY, tc, state, log_fn=logs.append)
        tr.fit(_FixedBatches(n=8, seed=0), _FixedBatches(n=2, seed=7))
        done = [l for l in logs if "done in" in l]
        assert any("early stop" in l for l in logs), logs[-3:]
        assert len(done) < 40  # stopped before the epoch budget

    @pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
    def test_marker_blocks_relaunch(self, tmp_path):
        """A relaunch after an early stop must not retrain past the stopped
        checkpoint (job-scheduler retries would otherwise overwrite it)."""
        import dataclasses

        from transformer_tpu.train import Trainer

        tc = dataclasses.replace(
            TCFG, epochs=40, warmup_steps=10, early_stop_patience=2,
            eval_every_steps=0, log_every_steps=0, checkpoint_every_epochs=1,
        )
        mgr = CheckpointManager(str(tmp_path), max_to_keep=2, is_primary=True)
        state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        logs = []
        tr = Trainer(TINY, tc, state, checkpoint=mgr, log_fn=logs.append)
        tr.fit(_FixedBatches(n=8, seed=0), _FixedBatches(n=2, seed=7))
        assert any("early stop" in l for l in logs)
        assert (tmp_path / "EARLY_STOPPED").exists()
        saved_steps = mgr.all_steps()

        relaunch_logs = []
        state2 = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        mgr2 = CheckpointManager(str(tmp_path), max_to_keep=2, is_primary=True)
        tr2 = Trainer(TINY, tc, state2, checkpoint=mgr2, log_fn=relaunch_logs.append)
        tr2.fit(_FixedBatches(n=8, seed=0), _FixedBatches(n=2, seed=7))
        assert any("marker present" in l for l in relaunch_logs)
        assert not any("done in" in l for l in relaunch_logs)  # no training
        assert mgr2.all_steps() == saved_steps  # checkpoints untouched

    @pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
    def test_plateau_window_survives_resume(self, tmp_path):
        """Crash-resume keeps the patience window (plateau.json sidecar): a
        run preempted after a plateau epoch must NOT get a fresh window and
        train `patience` extra epochs past the original plateau."""
        import dataclasses

        from transformer_tpu.train import Trainer

        # Warmup so large the LR is ~0: eval loss is bit-identical every
        # epoch, so epoch 1 sets best_eval and every later epoch plateaus.
        def cfg(epochs):
            return dataclasses.replace(
                TCFG, epochs=epochs, warmup_steps=10**9,
                early_stop_patience=2, eval_every_steps=0, log_every_steps=0,
                checkpoint_every_epochs=1,
            )

        mgr = CheckpointManager(str(tmp_path), max_to_keep=2, is_primary=True)
        state = create_train_state(jax.random.PRNGKey(0), TINY, cfg(2))
        logs = []
        tr = Trainer(TINY, cfg(2), state, checkpoint=mgr, log_fn=logs.append)
        tr.fit(_FixedBatches(n=2, seed=0), _FixedBatches(n=1, seed=7))
        # Epoch 1: best. Epoch 2: one plateau epoch. The exhausted epoch
        # budget plays the part of the preemption.
        assert not any("early stop" in l for l in logs)
        assert (tmp_path / "plateau.json").exists()

        mgr2 = CheckpointManager(str(tmp_path), max_to_keep=2, is_primary=True)
        state2 = create_train_state(jax.random.PRNGKey(0), TINY, cfg(40))
        logs2 = []
        tr2 = Trainer(TINY, cfg(40), state2, checkpoint=mgr2, log_fn=logs2.append)
        tr2.fit(_FixedBatches(n=2, seed=0), _FixedBatches(n=1, seed=7))
        assert any("resumed early-stop window" in l for l in logs2), logs2[:3]
        done = [l for l in logs2 if "done in" in l]
        # The persisted window already counts 1 plateau epoch, so ONE more
        # (epoch 3) reaches patience=2 — a fresh window would need two.
        assert len(done) == 1, logs2
        assert any("early stop" in l for l in logs2)

    def test_empty_eval_gives_no_signal(self):
        """A zero-weight eval (empty test split) must not lock best_eval at
        0.0 and fire a spurious stop."""
        import dataclasses

        from transformer_tpu.train import Trainer

        class _Empty:
            def __len__(self):
                return 0

            def batches(self, epoch=0):
                return iter(())

        tc = dataclasses.replace(
            TCFG, epochs=4, warmup_steps=10, early_stop_patience=1,
            eval_every_steps=0, log_every_steps=0,
        )
        state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        logs = []
        tr = Trainer(TINY, tc, state, log_fn=logs.append)
        tr.fit(_FixedBatches(n=2, seed=0), _Empty())
        assert len([l for l in logs if "done in" in l]) == 4
        assert not any("early stop" in l for l in logs)

    @pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
    def test_disabled_runs_all_epochs(self):
        import dataclasses

        from transformer_tpu.train import Trainer

        tc = dataclasses.replace(
            TCFG, epochs=4, warmup_steps=10, early_stop_patience=0,
            eval_every_steps=0, log_every_steps=0,
        )
        state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        logs = []
        tr = Trainer(TINY, tc, state, log_fn=logs.append)
        tr.fit(_FixedBatches(n=2, seed=0), _FixedBatches(n=1, seed=7))
        assert len([l for l in logs if "done in" in l]) == 4
        assert not any("early stop" in l for l in logs)


class TestCheckpointAveraging:
    def test_average_is_elementwise_mean(self, tmp_path):
        """The classic Transformer eval trick: export the mean of the last N
        rotated checkpoints."""
        from transformer_tpu.train.checkpoint import average_checkpoints

        base = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        mgr = CheckpointManager(str(tmp_path), max_to_keep=5, is_primary=True)
        import dataclasses as dc

        scales = [1.0, 2.0, 6.0]
        for i, s in enumerate(scales):
            scaled = dc.replace(
                base, params=jax.tree.map(lambda x: x * s, base.params)
            )
            mgr.save(scaled, step=i)
        avg = average_checkpoints(mgr, base, mgr.all_steps())  # params tree
        want = float(np.mean(scales))
        for a, b in zip(jax.tree.leaves(avg), jax.tree.leaves(base.params)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b) * want, atol=1e-5
            )

    def test_rejects_empty(self, tmp_path):
        from transformer_tpu.train.checkpoint import average_checkpoints

        mgr = CheckpointManager(str(tmp_path), is_primary=True)
        state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        with pytest.raises(ValueError, match="at least one"):
            average_checkpoints(mgr, state, [])


class TestAdamW:
    def test_overfit_one_batch(self):
        import dataclasses

        tc = dataclasses.replace(
            TCFG, optimizer="adamw", weight_decay=0.01, warmup_steps=20
        )
        state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        step = jax.jit(make_train_step(TINY, tc))
        r = np.random.default_rng(0)
        src = jnp.asarray(r.integers(1, 28, (4, 8)), jnp.int32)
        tgt = jnp.asarray(r.integers(1, 28, (4, 8)), jnp.int32)
        rng = jax.random.PRNGKey(1)
        first = last = None
        for _ in range(120):
            state, m = step(state, src, tgt, rng)
            if first is None:
                first = float(m["loss"])
            last = float(m["loss"])
        assert last < 0.5 * first, (first, last)

    def test_decay_hits_matrices_not_vectors(self):
        """With zero gradients, adamw's update is pure decay: matrices
        shrink, vectors (biases, layernorm params) stay untouched."""
        import dataclasses

        from transformer_tpu.train.state import make_optimizer

        tc = dataclasses.replace(
            TCFG, optimizer="adamw", weight_decay=0.1, warmup_steps=1
        )
        state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        tx = make_optimizer(TINY, tc)
        zero_g = jax.tree.map(jnp.zeros_like, state.params)
        opt_state = tx.init(state.params)
        # A few steps past warmup so the schedule LR is nonzero.
        updates = None
        for _ in range(3):
            updates, opt_state = tx.update(zero_g, opt_state, state.params)
        for path, u in jax.tree_util.tree_flatten_with_path(updates)[0]:
            name = "/".join(str(getattr(e, "key", e)) for e in path)
            # Exempt by NAME (qkv biases are 2-D), not rank.
            if np.asarray(u).ndim >= 2 and not name.endswith("bias"):
                assert float(jnp.max(jnp.abs(u))) > 0.0, name
            else:
                np.testing.assert_array_equal(np.asarray(u), 0.0, err_msg=name)

    def test_decay_requires_adamw(self):
        import dataclasses

        with pytest.raises(ValueError, match="weight_decay"):
            dataclasses.replace(TCFG, weight_decay=0.1)


class TestAdafactor:
    def test_overfit_one_batch(self):
        import dataclasses

        tc = dataclasses.replace(TCFG, optimizer="adafactor", warmup_steps=20)
        state = create_train_state(jax.random.PRNGKey(0), TINY, tc)
        step = jax.jit(make_train_step(TINY, tc))
        r = np.random.default_rng(0)
        src = jnp.asarray(r.integers(1, 28, (4, 8)), jnp.int32)
        tgt = jnp.asarray(r.integers(1, 28, (4, 8)), jnp.int32)
        rng = jax.random.PRNGKey(1)
        first = None
        for _ in range(60):
            state, m = step(state, src, tgt, rng)
            first = float(m["loss"]) if first is None else first
        assert float(m["loss"]) < first * 0.6

    def test_state_is_factored(self):
        """The point of Adafactor: optimizer state far smaller than Adam's
        2x-params (factored second moments). Matrices must be >=128 on both
        dims to factor (optax default min_dim_size_to_factor), so this uses a
        model at that scale."""
        import dataclasses

        cfg = dataclasses.replace(
            TINY, d_model=128, dff=256, num_heads=4,
            input_vocab_size=512, target_vocab_size=512,
        )
        tc_a = TCFG
        tc_f = dataclasses.replace(TCFG, optimizer="adafactor")

        def elems(state_field):
            return sum(
                int(np.prod(np.shape(x))) for x in jax.tree.leaves(state_field)
            )

        s_a = create_train_state(jax.random.PRNGKey(0), cfg, tc_a)
        s_f = create_train_state(jax.random.PRNGKey(0), cfg, tc_f)
        n_params = elems(s_a.params)
        assert elems(s_a.opt_state) >= 2 * n_params
        assert elems(s_f.opt_state) < n_params / 2

    def test_rejects_unknown_optimizer(self):
        with pytest.raises(ValueError, match="optimizer"):
            TrainConfig(optimizer="sgd")


class TestTopPSampling:
    def test_nucleus_truncates_tail(self):
        """With a peaked distribution and small top_p, sampling must only
        ever return the top token; with top_p=1.0 the tail stays reachable."""
        from transformer_tpu.train.decode import lm_generate
        from transformer_tpu.models import transformer_init

        cfg = ModelConfig(
            num_layers=1, d_model=16, num_heads=2, dff=32,
            input_vocab_size=30, target_vocab_size=30, max_position=32,
            dtype="float32", dropout_rate=0.0, decoder_only=True,
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jnp.asarray([[28, 5, 9]], jnp.int32)  # BOS-led
        greedy = lm_generate(params, prompt, cfg, 8, eos_id=29)
        nucleus = lm_generate(
            params, prompt, cfg, 8, eos_id=29,
            rng=jax.random.PRNGKey(3), sample=True,
            temperature=1e-3, top_p=0.5,
        )
        # Tiny temperature concentrates all mass on the argmax; the nucleus
        # then contains exactly the top token, so sampling == greedy.
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(nucleus))

    def test_top_p_one_is_unfiltered_sampling(self):
        from transformer_tpu.train.decode import lm_generate
        from transformer_tpu.models import transformer_init

        cfg = ModelConfig(
            num_layers=1, d_model=16, num_heads=2, dff=32,
            input_vocab_size=30, target_vocab_size=30, max_position=32,
            dtype="float32", dropout_rate=0.0, decoder_only=True,
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        prompt = jnp.asarray([[28, 5, 9]], jnp.int32)
        a = lm_generate(
            params, prompt, cfg, 8, eos_id=29,
            rng=jax.random.PRNGKey(7), sample=True, temperature=1.0,
        )
        b = lm_generate(
            params, prompt, cfg, 8, eos_id=29,
            rng=jax.random.PRNGKey(7), sample=True, temperature=1.0,
            top_p=1.0,
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestAsyncCheckpoint:
    """AsyncCheckpointManager: background disk writes, synchronous device
    snapshot (so donated-buffer invalidation can't corrupt a pending save)."""

    def test_roundtrip_matches_sync(self, tmp_path):
        from transformer_tpu.train import AsyncCheckpointManager

        state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        a = AsyncCheckpointManager(str(tmp_path / "async"), max_to_keep=3)
        s = CheckpointManager(str(tmp_path / "sync"), max_to_keep=3)
        a.save(state, step=5)
        s.save(state, step=5)
        a.wait()
        other = create_train_state(jax.random.PRNGKey(1), TINY, TCFG)
        ra = a.restore_latest(other)
        rs = s.restore_latest(other)
        for x, y in zip(jax.tree.leaves(ra), jax.tree.leaves(rs)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_snapshot_survives_donation(self, tmp_path):
        """The state buffers are donated to the next train step immediately
        after save() returns — the checkpoint must hold the OLD values."""
        from transformer_tpu.train import AsyncCheckpointManager

        state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        step = jax.jit(make_train_step(TINY, TCFG), donate_argnums=(0,))
        r = np.random.default_rng(0)
        src = jnp.asarray(r.integers(1, 28, (4, 8)), jnp.int32)
        tgt = jnp.asarray(r.integers(1, 28, (4, 8)), jnp.int32)
        mgr = AsyncCheckpointManager(str(tmp_path), max_to_keep=3)
        before = jax.tree.map(lambda a: np.asarray(a).copy(), state.params)
        mgr.save(state, step=0)
        # Donate the old buffers right away; the pending write must not see it.
        state, _ = step(state, src, tgt, jax.random.PRNGKey(1))
        mgr.wait()
        restored = mgr.restore(
            create_train_state(jax.random.PRNGKey(2), TINY, TCFG), 0
        )
        for x, y in zip(jax.tree.leaves(before), jax.tree.leaves(restored.params)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_sequential_saves_rotate(self, tmp_path):
        from transformer_tpu.train import AsyncCheckpointManager

        state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        mgr = AsyncCheckpointManager(str(tmp_path), max_to_keep=2)
        for i in range(4):
            mgr.save(state, step=i)
        mgr.wait()
        assert mgr.all_steps() == [2, 3]

    def test_worker_failure_surfaces_on_wait(self, tmp_path):
        """A failed background WRITE (ENOSPC, permissions, ...) must re-raise
        from wait(), not vanish with the worker thread."""
        from transformer_tpu.train import AsyncCheckpointManager

        state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        mgr = AsyncCheckpointManager(str(tmp_path / "x"), max_to_keep=2)

        def boom(flat, step):
            raise OSError("disk full")

        mgr._write_replicated = boom
        mgr.save(state, step=0)
        with pytest.raises(OSError, match="disk full"):
            mgr.wait()
        # The failure is consumed: the manager is usable again afterwards.
        del mgr.__dict__["_write_replicated"]
        mgr.save(state, step=1)
        mgr.wait()
        assert mgr.all_steps() == [1]


class TestChunkedLoss:
    """loss_chunks: vocab projection + CE over sequence slices
    (train/loss.py chunked_cross_entropy_from_hidden) — must match the
    monolithic path exactly in loss, metrics, and gradients."""

    def _batch(self, seed=0):
        r = np.random.default_rng(seed)
        src = jnp.asarray(r.integers(1, 28, (4, 9)), jnp.int32)
        tgt = jnp.asarray(r.integers(1, 28, (4, 9)), jnp.int32)
        return src, tgt

    @pytest.mark.parametrize(
        "chunks",
        [2, pytest.param(3, marks=pytest.mark.slow)],  # 3 does not divide S-1=8;
        # the non-dividing case is the slow-tier sweep, chunks=2 the fast specimen
    )
    def test_train_step_matches_monolithic(self, chunks):
        import dataclasses

        src, tgt = self._batch()
        rng = jax.random.PRNGKey(1)
        tc_mono = TCFG
        tc_chunk = dataclasses.replace(TCFG, loss_chunks=chunks)
        s1 = create_train_state(jax.random.PRNGKey(0), TINY, tc_mono)
        s2 = create_train_state(jax.random.PRNGKey(0), TINY, tc_chunk)
        s1, m1 = jax.jit(make_train_step(TINY, tc_mono))(s1, src, tgt, rng)
        s2, m2 = jax.jit(make_train_step(TINY, tc_chunk))(s2, src, tgt, rng)
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)
        for k in ("loss_sum", "weight", "correct"):
            np.testing.assert_allclose(float(m1[k]), float(m2[k]), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_eval_step_matches_monolithic(self):
        import dataclasses

        src, tgt = self._batch(1)
        state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        m1 = jax.jit(make_eval_step(TINY, TCFG))(state, src, tgt)
        tc = dataclasses.replace(TCFG, loss_chunks=4)
        m2 = jax.jit(make_eval_step(TINY, tc))(state, src, tgt)
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)

    @pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
    def test_tied_output_supported(self):
        import dataclasses

        cfg = dataclasses.replace(TINY, tie_embeddings=True, tie_output=True)
        tc = dataclasses.replace(TCFG, loss_chunks=2)
        src, tgt = self._batch(2)
        state = create_train_state(jax.random.PRNGKey(0), cfg, tc)
        state, m = jax.jit(make_train_step(cfg, tc))(state, src, tgt, jax.random.PRNGKey(1))
        assert np.isfinite(float(m["loss"]))

    def test_composes_with_grad_accum(self):
        """Both sequential memory levers at once (r2 VERDICT missing-#3):
        loss_chunks × grad_accum_steps must reproduce the monolithic
        whole-batch trajectory."""
        import dataclasses

        import optax

        tc = dataclasses.replace(TCFG, loss_chunks=2, grad_accum_steps=2)
        r = np.random.default_rng(5)
        src = jnp.asarray(r.integers(1, 28, (8, 8)), jnp.int32)
        tgt = jnp.asarray(r.integers(1, 28, (8, 8)), jnp.int32)
        tgt = tgt.at[:, 6:].set(0)  # pad tail: exercise token weighting
        rng = jax.random.PRNGKey(3)
        # SGD so params reflect raw gradient sums: Adam's m/sqrt(v) would
        # amplify fp32 summation-order noise on near-zero gradients into
        # O(1) relative update differences (the accum-only test compares
        # losses for the same reason).
        from transformer_tpu.train.state import TrainState

        sgd = optax.sgd(0.5)
        params = create_train_state(jax.random.PRNGKey(0), TINY, TCFG).params
        s_ref = TrainState(
            step=jnp.int32(0), params=params, opt_state=sgd.init(params)
        )
        s_c = TrainState(
            step=jnp.int32(0), params=params, opt_state=sgd.init(params)
        )
        step_ref = jax.jit(make_train_step(TINY, TCFG, tx=sgd))
        step_c = jax.jit(make_train_step(TINY, tc, tx=sgd))
        for _ in range(3):
            s_ref, m_ref = step_ref(s_ref, src, tgt, rng)
            s_c, m_c = step_c(s_c, src, tgt, rng)
            np.testing.assert_allclose(
                float(m_c["loss"]), float(m_ref["loss"]), rtol=2e-5
            )
        for a, b in zip(jax.tree.leaves(s_ref.params), jax.tree.leaves(s_c.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_custom_forward_requires_hidden_forward(self):
        """A custom forward_fn without its hidden counterpart must still be
        rejected under loss_chunks — silently materializing (B, S, V) logits
        would OOM exactly where chunking matters."""
        import dataclasses

        tc = dataclasses.replace(TCFG, loss_chunks=2)
        fake_forward = lambda params, s, ti, r, det: None  # noqa: E731
        with pytest.raises(ValueError, match="hidden_forward_fn"):
            make_train_step(TINY, tc, forward_fn=fake_forward)
        with pytest.raises(ValueError, match="hidden_forward_fn"):
            make_eval_step(TINY, tc, forward_fn=fake_forward)


class TestCheckpoint:
    def test_roundtrip(self, tmp_path):
        state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        mgr = CheckpointManager(str(tmp_path), max_to_keep=3, is_primary=True)
        state2 = create_train_state(jax.random.PRNGKey(1), TINY, TCFG)
        mgr.save(state, step=7)
        restored = mgr.restore_latest(state2)
        for a, b in zip(
            jax.tree_util.tree_leaves(state), jax.tree_util.tree_leaves(restored)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_rotation_keeps_max(self, tmp_path):
        state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        mgr = CheckpointManager(str(tmp_path), max_to_keep=2, is_primary=True)
        for s in [1, 2, 3, 4]:
            mgr.save(state, step=s)
        assert mgr.all_steps() == [3, 4]

    def test_restore_latest_none_when_empty(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), max_to_keep=2, is_primary=True)
        assert mgr.restore_latest(None) is None

    def test_shape_mismatch_rejected(self, tmp_path):
        state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        mgr = CheckpointManager(str(tmp_path), is_primary=True)
        mgr.save(state, step=1)
        other = create_train_state(
            jax.random.PRNGKey(0),
            ModelConfig(
                num_layers=1, d_model=32, num_heads=2, dff=32,
                input_vocab_size=30, target_vocab_size=30, max_position=32,
                dtype="float32",
            ),
            TCFG,
        )
        with pytest.raises(ValueError):
            mgr.restore(other, 1)

    def test_export_load(self, tmp_path):
        params = transformer_init(jax.random.PRNGKey(0), TINY)
        export_params(params, TINY, str(tmp_path / "export"))
        template = transformer_init(jax.random.PRNGKey(1), TINY)
        loaded = load_exported_params(str(tmp_path / "export"), template)
        np.testing.assert_array_equal(
            np.asarray(loaded["encoder"]["embedding"]["table"]),
            np.asarray(params["encoder"]["embedding"]["table"]),
        )


class TestTrainStep:
    def test_overfit_one_batch(self):
        """Integration: loss falls by >60% in 150 steps on a fixed batch."""
        tcfg = TrainConfig(
            batch_size=4, sequence_length=8, epochs=1,
            warmup_steps=20, loss_normalization="tokens",
        )
        state = create_train_state(jax.random.PRNGKey(0), TINY, tcfg)
        step = jax.jit(make_train_step(TINY, tcfg))
        src = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 1, 30)
        tgt = jax.random.randint(jax.random.PRNGKey(2), (4, 8), 1, 30)
        rng = jax.random.PRNGKey(3)
        first = last = None
        for _ in range(150):
            state, m = step(state, src, tgt, rng)
            if first is None:
                first = float(m["loss"])
            last = float(m["loss"])
        assert last < 0.4 * first, (first, last)
        assert int(state.step) == 150

    @pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
    def test_grad_accum_matches_whole_batch(self):
        """grad_accum_steps=4 must produce the same optimizer trajectory as
        the whole-batch step (dropout off), for both normalizations."""
        import dataclasses

        for norm in ("tokens", "batch"):
            base = TCFG if TCFG.loss_normalization == norm else dataclasses.replace(
                TCFG, loss_normalization=norm
            )
            accum_cfg = dataclasses.replace(base, grad_accum_steps=4)
            src = jax.random.randint(jax.random.PRNGKey(1), (8, 8), 1, 30)
            tgt = jax.random.randint(jax.random.PRNGKey(2), (8, 8), 1, 30)
            tgt = tgt.at[:, 6:].set(0)  # pad tail: exercise token weighting
            rng = jax.random.PRNGKey(3)

            s_ref = create_train_state(jax.random.PRNGKey(0), TINY, base)
            s_acc = create_train_state(jax.random.PRNGKey(0), TINY, accum_cfg)
            step_ref = jax.jit(make_train_step(TINY, base))
            step_acc = jax.jit(make_train_step(TINY, accum_cfg))
            for _ in range(3):
                s_ref, m_ref = step_ref(s_ref, src, tgt, rng)
                s_acc, m_acc = step_acc(s_acc, src, tgt, rng)
                np.testing.assert_allclose(
                    float(m_acc["loss"]), float(m_ref["loss"]), rtol=2e-5,
                    err_msg=norm,
                )

    def test_grad_accum_must_divide_batch(self):
        import dataclasses

        import pytest

        cfg = dataclasses.replace(TCFG, grad_accum_steps=3)
        state = create_train_state(jax.random.PRNGKey(0), TINY, cfg)
        step = jax.jit(make_train_step(TINY, cfg))
        src = jax.random.randint(jax.random.PRNGKey(1), (8, 8), 1, 30)
        with pytest.raises(ValueError, match="divide"):
            step(state, src, src, jax.random.PRNGKey(2))

    def test_eval_step_deterministic(self):
        state = create_train_state(jax.random.PRNGKey(0), TINY, TCFG)
        eval_step = jax.jit(make_eval_step(TINY, TCFG))
        src = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 1, 30)
        tgt = jax.random.randint(jax.random.PRNGKey(2), (4, 8), 1, 30)
        m1 = eval_step(state, src, tgt)
        m2 = eval_step(state, src, tgt)
        assert float(m1["loss"]) == float(m2["loss"])


class TestGreedyDecode:
    def test_shapes_and_pad_after_eos(self):
        params = transformer_init(jax.random.PRNGKey(0), TINY)
        src = jax.random.randint(jax.random.PRNGKey(1), (3, 6), 1, 30)
        out = np.asarray(greedy_decode(params, src, TINY, 10, bos_id=28, eos_id=29))
        assert out.shape == (3, 10)
        for row in out:
            seen_eos = False
            for t in row:
                if seen_eos:
                    assert t == 0
                if t == 29:
                    seen_eos = True

    def test_translate_accepts_str_and_list(self):
        """The reference's predict(str) decodes one character (quirk §2.3.11);
        both spellings must work here."""
        from transformer_tpu.data.tokenizer import SubwordTokenizer

        tok = SubwordTokenizer.build_from_corpus(
            ["ab cd ef"] * 3, target_vocab_size=270
        )
        cfg = ModelConfig(
            num_layers=1, d_model=16, num_heads=2, dff=32,
            input_vocab_size=tok.model_vocab_size,
            target_vocab_size=tok.model_vocab_size,
            max_position=32, dtype="float32", dropout_rate=0.0,
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        single = translate(params, cfg, tok, tok, "ab cd", max_len=5)
        double = translate(params, cfg, tok, tok, ["ab cd", "ef"], max_len=5)
        assert len(single) == 1 and len(double) == 2
        assert all(isinstance(t, str) for t in double)

    def test_translate_buckets_widths_one_compile(self):
        """Varying source widths/batch sizes within one bucket must reuse one
        compiled executable (the decode-side recompile bomb: reference decode
        re-traces per shape, train.py:109-118; round-1 translate() recompiled
        per source width)."""
        from transformer_tpu.data.tokenizer import SubwordTokenizer
        from transformer_tpu.train.decode import greedy_decode

        tok = SubwordTokenizer.build_from_corpus(
            ["ab cd ef gh ij"] * 3, target_vocab_size=270
        )
        cfg = ModelConfig(
            num_layers=1, d_model=16, num_heads=2, dff=32,
            input_vocab_size=tok.model_vocab_size,
            target_vocab_size=tok.model_vocab_size,
            max_position=32, dtype="float32", dropout_rate=0.0,
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        before = greedy_decode._cache_size()
        # Different sentence counts and raw token widths — all land in the
        # (batch<=1-pow2, width<=16) bucket, so exactly one new compile.
        translate(params, cfg, tok, tok, "ab", max_len=5)
        translate(params, cfg, tok, tok, "ab cd ef", max_len=5)
        translate(params, cfg, tok, tok, "ab cd ef gh ij", max_len=5)
        assert greedy_decode._cache_size() == before + 1

    def test_bucket_rounding(self):
        from transformer_tpu.train.decode import _bucket

        assert _bucket(3, 4096) == 16   # floor
        assert _bucket(17, 4096) == 32  # next pow2
        assert _bucket(100, 64) == 64   # capped
        assert _bucket(5, 4096, floor=1) == 8

    def test_translate_overlong_input_fails_loudly(self):
        """A sentence longer than max_position must raise, not silently
        truncate away its EOS (src_len= opts into explicit truncation)."""
        import pytest

        from transformer_tpu.data.tokenizer import SubwordTokenizer

        tok = SubwordTokenizer.build_from_corpus(
            ["ab cd ef gh"] * 3, target_vocab_size=270
        )
        cfg = ModelConfig(
            num_layers=1, d_model=16, num_heads=2, dff=32,
            input_vocab_size=tok.model_vocab_size,
            target_vocab_size=tok.model_vocab_size,
            max_position=8, dtype="float32", dropout_rate=0.0,
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        long_sentence = "ab cd ef gh " * 8
        with pytest.raises(ValueError, match="max_position"):
            translate(params, cfg, tok, tok, long_sentence, max_len=4)
        # Explicit src_len still allows truncation.
        out = translate(params, cfg, tok, tok, long_sentence, max_len=4, src_len=8)
        assert len(out) == 1


class TestExportRoundTrip:
    def test_export_load_identical_decode(self, tmp_path, monkeypatch):
        """Export → load via the serving CLI path → decode output must be
        identical to decoding with the in-memory params (the reference's
        SavedModel capability, train.py:246, exercised end-to-end)."""
        from transformer_tpu.cli.translate import load_export
        from transformer_tpu.data.tokenizer import SubwordTokenizer
        from transformer_tpu.train.checkpoint import export_params

        tok = SubwordTokenizer.build_from_corpus(
            ["ab cd ef gh"] * 3, target_vocab_size=270
        )
        cfg = ModelConfig(
            num_layers=1, d_model=16, num_heads=2, dff=32,
            input_vocab_size=tok.model_vocab_size,
            target_vocab_size=tok.model_vocab_size,
            max_position=32, dtype="float32", dropout_rate=0.0,
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        export_params(params, cfg, str(tmp_path / "model"))

        loaded_params, loaded_cfg = load_export(str(tmp_path / "model"))
        assert loaded_cfg == cfg
        want = translate(params, cfg, tok, tok, ["ab cd", "ef gh"], max_len=6)
        got = translate(loaded_params, loaded_cfg, tok, tok, ["ab cd", "ef gh"], max_len=6)
        assert want == got


class TestQuantizedExport:
    def _model(self):
        # d_model 64 so the big leaves clear the _Q8_MIN_SIZE threshold.
        cfg = ModelConfig(
            num_layers=1, d_model=64, num_heads=2, dff=128,
            input_vocab_size=300, target_vocab_size=300, max_position=32,
            dtype="float32", dropout_rate=0.0,
        )
        return cfg, transformer_init(jax.random.PRNGKey(0), cfg)

    def test_int8_roundtrip_error_bound(self, tmp_path):
        """Every quantized leaf must come back within half a quantization
        step of its group scale; small leaves (biases, layernorms) must be
        bit-exact."""
        from transformer_tpu.train.checkpoint import (
            _Q8_MIN_SIZE,
            _flatten,
            _q8_group_axes,
            export_params,
            load_exported_params,
        )

        cfg, params = self._model()
        export_params(params, cfg, str(tmp_path / "q"), quantize="int8")
        loaded = load_exported_params(str(tmp_path / "q"), params)
        for (k, want), got in zip(
            _flatten(params).items(),
            _flatten(loaded).values(),
        ):
            want, got = np.asarray(want), np.asarray(got)
            if want.ndim < 2 or want.size < _Q8_MIN_SIZE or k.endswith("/bias"):
                np.testing.assert_array_equal(want, got, err_msg=k)
            else:
                axis = _q8_group_axes(k, want)
                step = np.max(np.abs(want), axis=axis, keepdims=True) / 127.0
                assert np.all(np.abs(want - got) <= step * 0.5 + 1e-8), k

    def test_int8_artifact_smaller(self, tmp_path):
        import os

        from transformer_tpu.train.checkpoint import export_params

        cfg, params = self._model()
        export_params(params, cfg, str(tmp_path / "fp"))
        export_params(params, cfg, str(tmp_path / "q"), quantize="int8")
        fp = os.path.getsize(tmp_path / "fp" / "params.npz")
        q = os.path.getsize(tmp_path / "q" / "params.npz")
        assert q < fp / 2.5, (fp, q)

    def test_quantized_decode_close(self, tmp_path):
        """The serving path must work unchanged on a quantized export, and
        the int8 error must not change a greedy decode of an untrained
        model's argmax chain wildly — compare logits, not strings."""
        from transformer_tpu.models import transformer_apply
        from transformer_tpu.train.checkpoint import (
            export_params,
            load_exported_params,
        )

        cfg, params = self._model()
        export_params(params, cfg, str(tmp_path / "q"), quantize="int8")
        loaded = load_exported_params(str(tmp_path / "q"), params)
        src = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 1, 290)
        tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 1, 290)
        want, _ = transformer_apply(params, src, tgt, cfg, deterministic=True)
        got, _ = transformer_apply(loaded, src, tgt, cfg, deterministic=True)
        err = float(jnp.max(jnp.abs(want - got)))
        spread = float(jnp.max(want) - jnp.min(want))
        assert err < 0.05 * spread, (err, spread)

    def test_rejects_unknown_scheme(self, tmp_path):
        from transformer_tpu.train.checkpoint import export_params

        cfg, params = self._model()
        with pytest.raises(ValueError, match="quantize"):
            export_params(params, cfg, str(tmp_path / "x"), quantize="int4")

    def test_moe_biases_stay_exact(self, tmp_path):
        """Per-expert MoE biases are 2-D and large but additive — they must
        NOT be quantized (bit-exact roundtrip)."""
        from transformer_tpu.train.checkpoint import (
            export_params,
            load_exported_params,
        )

        cfg = ModelConfig(
            num_layers=1, d_model=64, num_heads=2, dff=128,
            input_vocab_size=300, target_vocab_size=300, max_position=32,
            dtype="float32", dropout_rate=0.0,
            moe_experts=8, moe_top_k=2, moe_every=1,
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        export_params(params, cfg, str(tmp_path / "q"), quantize="int8")
        loaded = load_exported_params(str(tmp_path / "q"), params)

        def check(path, want, got):
            key = "/".join(str(getattr(e, "key", getattr(e, "name", e))) for e in path)
            if key.endswith("bias"):
                np.testing.assert_array_equal(
                    np.asarray(want), np.asarray(got), err_msg=key
                )

        jax.tree_util.tree_map_with_path(
            check, params, loaded
        )

    def test_bfloat16_params_quantize(self, tmp_path):
        """bf16 leaves must quantize too (ml_dtypes' bfloat16 is not
        np.floating — matched by dtype name instead)."""
        import os

        from transformer_tpu.train.checkpoint import export_params

        cfg, params = self._model()
        bf16 = jax.tree.map(
            lambda w: np.asarray(w, dtype=jnp.bfloat16.dtype), params
        )
        export_params(bf16, cfg, str(tmp_path / "fp"))
        export_params(bf16, cfg, str(tmp_path / "q"), quantize="int8")
        fp = os.path.getsize(tmp_path / "fp" / "params.npz")
        q = os.path.getsize(tmp_path / "q" / "params.npz")
        assert q < fp / 1.4, (fp, q)  # int8 < bf16 on the big leaves


class TestTensorBoardWriter:
    def test_record_framing_and_crc(self, tmp_path):
        w = SummaryWriter(str(tmp_path))
        w.scalar("loss", 1.5, step=3)
        w.close()
        data = open(w.path, "rb").read()
        # record 1: file_version; record 2: our scalar
        off = 0
        records = []
        while off < len(data):
            (length,) = struct.unpack_from("<Q", data, off)
            (len_crc,) = struct.unpack_from("<I", data, off + 8)
            assert len_crc == _masked_crc(data[off : off + 8])
            payload = data[off + 12 : off + 12 + length]
            (payload_crc,) = struct.unpack_from("<I", data, off + 12 + length)
            assert payload_crc == _masked_crc(payload)
            records.append(payload)
            off += 12 + length + 4
        assert len(records) == 2
        assert b"brain.Event:2" in records[0]
        assert b"loss" in records[1]
        assert struct.pack("<f", 1.5) in records[1]

    def test_crc32c_known_vector(self):
        from transformer_tpu.utils.tensorboard import _crc32c

        # RFC 3720 test vector: 32 zero bytes -> 0x8A9136AA
        assert _crc32c(b"\x00" * 32) == 0x8A9136AA


class TestBleu:
    def test_perfect_match_is_100(self):
        refs = ["the cat sat on the mat", "hello world foo bar"]
        assert corpus_bleu(refs, refs, smooth=False) == pytest.approx(100.0)

    def test_zero_overlap_is_0(self):
        assert corpus_bleu(["a b c d"], ["x y z w"], smooth=False) == 0.0

    def test_brevity_penalty(self):
        refs = ["a b c d e f g h"]
        full = corpus_bleu(refs, ["a b c d e f g h"])
        short = corpus_bleu(refs, ["a b c d"])
        assert short < full
        # BP formula: exp(1 - ref/hyp)
        assert short == pytest.approx(
            100 * math.exp(1 - 8 / 4) * math.exp(
                (math.log(4 / 4) + math.log(4 / 4) + math.log(3 / 3) + math.log(2 / 2)) / 4
            ),
            rel=1e-6,
        )
