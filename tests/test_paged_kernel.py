"""Fused Pallas paged-decode kernels (``--decode_kernel paged_flash``):
interpreter-mode parity of the block-table flash kernel against the XLA
gather oracle across cache variants (bf16/int8/GQA) x speculative verify
rows (S_q = k + 1, per-row offset causality) x fragmented/aliased tables;
end-to-end answer byte-identity through the continuous scheduler (greedy +
seeded sampling, chunked prefill, speculate_k, prefix aliasing incl. the
CoW write-guard path); and the paged_flash retrace budget — zero
steady-state recompiles across alloc/free/alias/spill admissions."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig
from transformer_tpu.data.tokenizer import SubwordTokenizer
from transformer_tpu.kernels.flash_attention import paged_attention
from transformer_tpu.models import transformer_init
from transformer_tpu.ops.attention import _quantize_kv
from transformer_tpu.serve import ContinuousScheduler, PrefixCache

pytestmark = pytest.mark.pallas


def _cfg(tok, **kw) -> ModelConfig:
    base = dict(
        num_layers=2, d_model=16, num_heads=2, dff=32,
        input_vocab_size=tok.model_vocab_size,
        target_vocab_size=tok.model_vocab_size,
        max_position=64, decoder_only=True, tie_output=True,
        dtype="float32", dropout_rate=0.0,
    )
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def tok():
    return SubwordTokenizer.build_from_corpus(
        ["ab cd ef gh ij kl mn"] * 3, target_vocab_size=300
    )


# Same acceptance matrix as the paged-vs-dense parity suite
# (tests/test_kv_pool.py): bf16, int8, GQA; the windowed variant REFUSES
# paged_flash (pinned below) because the kernel carries no band mask.
VARIANTS = {
    "bf16": dict(dtype="bfloat16"),
    "int8": dict(kv_cache_int8=True),
    "gqa": dict(num_kv_heads=1),
}

WAVES = [
    [
        {"prompt": "ab cd ef gh ij", "max_new": 6},
        {"prompt": "ab cd ef gh kl", "max_new": 5, "temperature": 0.9,
         "seed": 3},
    ],
    [
        {"prompt": "ab cd ef gh ij", "max_new": 6},          # full hit
        {"prompt": "ab cd ef gh mn", "max_new": 4, "temperature": 0.7,
         "top_k": 4, "seed": 1},                             # partial hit
    ],
]


# --------------------------------------------------------------------------
# kernel-level parity: paged_flash vs the XLA gather oracle
#
# The oracle ("xla") is bitwise-identical to the dense cache path
# (test_kv_pool.test_paged_attention_matches_dense), so agreement here
# chains to the dense math. The kernel's per-element scores match the
# oracle exactly (the QK contraction is only over D); what differs is the
# softmax/PV reduction ORDER (online accumulation across blocks vs one
# dense reduction), a low-bit effect bounded per compute dtype.

_TOL = {"fp32": 5e-6, "bf16": 3e-2, "int8": 3e-2, "gqa": 3e-2}

_KERNEL_VARIANTS = {
    "fp32": dict(dtype=jnp.float32, h_q=2, h_kv=2, quantized=False),
    "bf16": dict(dtype=jnp.bfloat16, h_q=2, h_kv=2, quantized=False),
    "int8": dict(dtype=jnp.bfloat16, h_q=2, h_kv=2, quantized=True),
    "gqa": dict(dtype=jnp.bfloat16, h_q=4, h_kv=1, quantized=False),
}


def _pool_case(variant: str, s_q: int, block_tokens: int = 8, seed: int = 0):
    """A deliberately hostile pool: 7 blocks, every row filled with random
    data (stale rows hold garbage the mask must hide), fragmented
    out-of-order tables, slot 2 aliasing slot 0's first two blocks (a
    prefix hit / pre-CoW share), unused entries parked on sink block 0,
    and per-slot lengths that end mid-block."""
    spec = _KERNEL_VARIANTS[variant]
    rng = np.random.default_rng(seed)
    d, blocks, n = 8, 7, 3
    table = jnp.asarray(
        [[3, 5, 1, 0], [6, 2, 4, 0], [3, 5, 2, 0]], jnp.int32
    )
    index = jnp.asarray(
        [block_tokens + 2, block_tokens // 2, 2 * block_tokens - 2],
        jnp.int32,
    )
    lengths = index + s_q
    kf = rng.standard_normal((blocks, block_tokens, spec["h_kv"], d))
    vf = rng.standard_normal((blocks, block_tokens, spec["h_kv"], d))
    q = jnp.asarray(
        rng.standard_normal((n, s_q, spec["h_q"], d)), spec["dtype"]
    )
    if spec["quantized"]:
        k, k_scale = _quantize_kv(jnp.asarray(kf, jnp.float32))
        v, v_scale = _quantize_kv(jnp.asarray(vf, jnp.float32))
        return q, k, v, table, lengths, dict(k_scale=k_scale, v_scale=v_scale)
    return (
        q,
        jnp.asarray(kf, spec["dtype"]),
        jnp.asarray(vf, spec["dtype"]),
        table,
        lengths,
        {},
    )


def _assert_kernel_parity(variant: str, s_q: int, block_tokens: int = 8):
    q, k, v, table, lengths, kw = _pool_case(variant, s_q, block_tokens)
    want = paged_attention(q, k, v, table, lengths, impl="xla", **kw)
    got = paged_attention(
        q, k, v, table, lengths, impl="paged_flash", interpret=True, **kw
    )
    assert got.shape == want.shape and got.dtype == want.dtype
    tol = _TOL[variant]
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=tol, atol=tol,
    )


@pytest.mark.parametrize("s_q", [1, 3])
@pytest.mark.parametrize("variant", sorted(_KERNEL_VARIANTS))
def test_kernel_parity_matrix(variant, s_q):
    """paged_flash vs the XLA oracle, per-variant tolerance: decode rows
    (S_q=1) and speculative verify rows (S_q=k+1 — query i attends pool
    positions <= lengths - S_q + i, per-row offset causality the S_q=1
    flash impl cannot express), on fragmented/aliased tables."""
    _assert_kernel_parity(variant, s_q)


@pytest.mark.slow
@pytest.mark.parametrize("block_tokens", [4, 16])
@pytest.mark.parametrize("variant", sorted(_KERNEL_VARIANTS))
def test_kernel_parity_block_sizes(variant, block_tokens):
    """The full sweep: every variant x non-default pool block sizes
    (tier-1 pins block_tokens=8 above), verify-shaped rows throughout."""
    _assert_kernel_parity(variant, 3, block_tokens)


def test_kernel_rejects_untileable_block_tokens():
    """Regression: a pool whose block_tokens neither divides nor is a
    multiple of the dtype's native sublane (bf16 -> 16) used to reach the
    kernel and produce silently wrong tiling; it must be rejected up front
    with an actionable error."""
    q, k, v, table, lengths, kw = _pool_case("bf16", 1, block_tokens=6)
    with pytest.raises(ValueError, match="block_tokens 6 is incompatible"):
        paged_attention(
            q, k, v, table, lengths, impl="paged_flash", interpret=True, **kw
        )
    # The boundary cases stay accepted: divisor of the sublane and an
    # exact multiple of it.
    for ok_bt in (4, 32):
        _assert_kernel_parity("bf16", 1, ok_bt)


def test_kernel_skips_sink_blocks():
    """Out-of-length table entries are never read: rewriting them to
    arbitrary (even out-of-range-of-length) block ids leaves the output
    bit-identical, pinning the stale-row/sink masking the pool's free
    list relies on."""
    q, k, v, table, lengths, kw = _pool_case("bf16", 1)
    base = paged_attention(
        q, k, v, table, lengths, impl="paged_flash", interpret=True, **kw
    )
    hostile = table.at[:, -1].set(jnp.asarray([4, 1, 6], jnp.int32))
    got = paged_attention(
        q, k, v, hostile, lengths, impl="paged_flash", interpret=True, **kw
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(base))


# --------------------------------------------------------------------------
# end-to-end: scheduler answers byte-identical paged_flash vs xla


def _kernel_stack_parity(tok, variant: str, speculate_k: int) -> None:
    """Greedy AND seeded-sampled answers byte-identical between
    --decode_kernel xla and paged_flash on the SAME paged layout, composed
    with chunked prefill, speculative decoding, and prefix reuse (wave 2
    replays wave 1's prompts as aliased device hits; divergent tails
    exercise the CoW write guard), at zero steady-state recompiles of the
    fused per-step program."""
    from transformer_tpu.serve import scheduler as sched

    cfg = _cfg(tok, **VARIANTS[variant])
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    common = dict(
        num_slots=2, max_total=48, default_max_new=4, prefill_chunk=3,
        speculate_k=speculate_k, kv_layout="paged",
    )
    waves = [list(WAVES[0]), list(WAVES[1])]

    s_ref = ContinuousScheduler(
        params, cfg, tok, decode_kernel="xla",
        prefix_cache=PrefixCache(cfg, block_tokens=4, budget_mb=8), **common,
    )
    want = [s_ref.run([dict(q) for q in w]) for w in waves]

    s = ContinuousScheduler(
        params, cfg, tok, decode_kernel="paged_flash",
        prefix_cache=PrefixCache(cfg, block_tokens=4, budget_mb=8), **common,
    )
    step_fn = (
        sched._pool_verify_paged_flash if speculate_k
        else sched._pool_step_paged_flash
    )
    got = [s.run([dict(q) for q in waves[0]])]
    before = step_fn._cache_size()
    got.append(s.run([dict(q) for q in waves[1]]))
    after = step_fn._cache_size()
    assert got == want, f"paged_flash answers diverged from xla ({variant})"
    assert any(r.get("continuation") for wave in got for r in wave), (
        "vacuous parity: every continuation empty"
    )
    assert after == before, "steady-state recompile on the fused step"
    # wave 2 replays wave 1's prompts: the fused path must still serve
    # them as pure device-tier table aliases.
    assert s.stats["prefix_hit_tokens"] > 0
    assert s.stats["prefix_alias_tokens"] == s.stats["prefix_hit_tokens"]
    s.pool.alloc.check_consistency()


def test_kernel_stack_parity_speculative(tok):
    """Tier-1 composition pin: bf16 + speculative verify (the fused
    verify program) + chunked prefill + prefix aliasing."""
    _kernel_stack_parity(tok, "bf16", speculate_k=1)


def test_kernel_stack_parity_plain(tok):
    """Tier-1 pin for the plain fused step (S_q = 1)."""
    _kernel_stack_parity(tok, "bf16", speculate_k=0)


@pytest.mark.slow
@pytest.mark.parametrize("variant", ["int8", "gqa"])
@pytest.mark.parametrize("speculate_k", [0, 1])
def test_kernel_stack_parity_variant_matrix(tok, variant, speculate_k):
    """The remaining answer-parity cross product: int8/GQA x plain and
    speculative (full suite; bf16 rides tier-1)."""
    _kernel_stack_parity(tok, variant, speculate_k=speculate_k)


def test_windowed_config_refuses_paged_flash(tok):
    """The kernel has no sliding-window band mask: attention_window
    configs must be refused at scheduler init, not silently mis-served."""
    cfg = _cfg(tok, attention_window=8)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="paged_flash|attention_window"):
        ContinuousScheduler(
            params, cfg, tok, num_slots=2, max_total=48,
            decode_kernel="paged_flash",
        )


def test_unknown_decode_kernel_rejected(tok):
    cfg = _cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="decode_kernel"):
        ContinuousScheduler(
            params, cfg, tok, num_slots=2, max_total=48,
            decode_kernel="mxu_magic",
        )


# --------------------------------------------------------------------------
# retrace budget: zero steady-state recompiles of the fused step


def test_paged_flash_retrace_budget(tok):
    """Steady-state paged_flash serving across every admission outcome —
    fresh allocs, frees at retirement, device-tier alias hits, and
    spill-to-host followed by batched restore — compiles ZERO new fused
    step/prefill programs after one warmup round (the same budget
    analysis/retrace.paged_retrace_report holds the gather path to);
    greedy answers are byte-identical round over round."""
    from transformer_tpu.analysis.retrace import RetraceSentinel
    from transformer_tpu.serve import scheduler as sched

    cfg = _cfg(tok)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    cache = PrefixCache(cfg, block_tokens=4, budget_mb=8)
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, max_total=48, default_max_new=4,
        prefix_cache=cache, kv_layout="paged", decode_kernel="paged_flash",
    )
    wave = [
        {"prompt": "ab cd ef gh ij"},
        {"prompt": "ab cd ef kl"},
    ]

    def one_round():
        out = s.run([dict(r) for r in wave])       # miss / alias / partial
        # Spill rung: evict every device-tier block to the host trie, then
        # re-serve — hits restore through the batched host write and are
        # re-adopted, so the NEXT round aliases again.
        s.stats["kv_spilled_blocks"] += cache.release_device_blocks(1 << 30)
        out2 = s.run([dict(r) for r in wave])
        s.pool.alloc.check_consistency()
        return [r.get("continuation") for r in out + out2]

    want = one_round()
    assert any(want), "vacuous retrace drill: every continuation empty"
    sentinel = RetraceSentinel()
    sentinel.watch(
        "decode(_pool_step_paged_flash)", sched._pool_step_paged_flash,
        budget=0,
    )
    sentinel.watch(
        "prefill(_slot_prefill_paged)", sched._slot_prefill_paged, budget=0
    )
    sentinel.snapshot()
    for i in range(2):
        assert one_round() == want, f"round {i} changed greedy answers"
    sentinel.assert_within_budget()
