"""REAL multi-process distributed training (SURVEY §2.4 multi-host).

Round-1 VERDICT: the multi-host path was "code-complete but never executed
with >1 process". This test launches two actual OS processes that join one
JAX distributed runtime over a localhost coordinator (4 virtual CPU devices
each → a global 8-device mesh), train data×fsdp steps where each process
feeds only its shard of the global batch, and round-trip a multi-process
sharded checkpoint. Cross-checked against the in-process single-run oracle.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# Heavyweight module (interpret-mode Pallas / 8-device shard_map /
# multi-process): excluded from the fast path, pytest -m 'not slow'.
pytestmark = pytest.mark.slow


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_training_matches_single(tmp_path):
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    env = {
        **os.environ,
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + os.environ.get("PYTHONPATH", "").split(os.pathsep)
        ),
    }
    # The axon TPU hook must not run in workers (it would contend for the
    # tunnel or hang when the relay is down); CPU platform is forced inside
    # the worker itself.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)), "multiproc_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, str(pid), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # A failed/timed-out worker leaves its peer blocked in a collective;
        # never orphan them.
        for p in procs:
            if p.poll() is None:
                p.kill()

    # Both processes observed the same global mesh and identical losses.
    for o in outs:
        assert o["n_processes"] == 2
        assert o["n_devices"] == 8
    assert outs[0]["losses"] == outs[1]["losses"]
    # Both restored identical params from the shared sharded checkpoint.
    assert outs[0]["restore_checksum"] == outs[1]["restore_checksum"]
    # The hybrid multi-slice mesh (data over process-granule "DCN", fsdp
    # intra-process) reproduces the flat-mesh numerics on the same batches
    # (device arrangement must not change the math, only the transport).
    assert outs[0]["hybrid_losses"] == outs[1]["hybrid_losses"]
    np.testing.assert_allclose(
        outs[0]["hybrid_losses"], outs[0]["losses"], atol=2e-5
    )
    # Consistency sanitizer (utils/consistency.py): identical replicated
    # state passes (and fsdp-sharded leaves are skipped, not false-
    # positived), while per-process divergence is detected on BOTH hosts.
    for o in outs:
        assert o["consistency_ok"], o
        assert o["divergence_caught"], o

    # The 2-process run must match the single-process 8-device oracle.
    import jax

    from transformer_tpu.config import MeshConfig, ModelConfig, TrainConfig
    from transformer_tpu.parallel import (
        create_sharded_state,
        make_mesh,
        make_sharded_steps,
        put_batch,
    )

    model_cfg = ModelConfig(
        num_layers=2, d_model=16, num_heads=4, dff=32,
        input_vocab_size=32, target_vocab_size=32, max_position=32,
        dtype="float32", dropout_rate=0.0,
    )
    train_cfg = TrainConfig(
        batch_size=16, sequence_length=8, warmup_steps=10,
        loss_normalization="tokens",
    )
    mesh = make_mesh(MeshConfig(data=4, fsdp=2))
    state, shardings = create_sharded_state(
        jax.random.PRNGKey(0), model_cfg, train_cfg, mesh
    )
    step_fn, _ = make_sharded_steps(
        mesh, model_cfg, train_cfg, shardings, donate=False
    )
    rng = jax.random.PRNGKey(42)
    want = []
    for i in range(3):
        ks, kt = jax.random.split(jax.random.PRNGKey(100 + i))
        src = np.asarray(jax.random.randint(ks, (16, 8), 1, 32), np.int32)
        tgt = np.asarray(jax.random.randint(kt, (16, 8), 1, 32), np.int32)
        state, m = step_fn(state, put_batch(src, mesh), put_batch(tgt, mesh), rng)
        want.append(round(float(m["loss"]), 6))
    np.testing.assert_allclose(outs[0]["losses"], want, rtol=2e-4)
