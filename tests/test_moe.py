"""Mixture-of-Experts FFN + expert parallelism (ops/moe.py).

No reference counterpart (the reference FFN is dense, ``point_ffn.py:3-7``) —
these tests pin the routing semantics the implementation promises: dense-FFN
equivalence at 1 expert, capacity-overflow dropping, renormalized top-k
combining, aux-loss behavior, gradient flow (incl. under remat), and
expert-parallel mesh parity against the single-device step.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import MeshConfig, ModelConfig, TrainConfig
from transformer_tpu.ops.ffn import ffn_apply
from transformer_tpu.ops.moe import expert_capacity, moe_apply, moe_init

# Heavyweight module (interpret-mode Pallas / 8-device shard_map /
# multi-process): excluded from the fast path, pytest -m 'not slow'.
pytestmark = pytest.mark.slow

MOE_TINY = ModelConfig(
    num_layers=2, d_model=32, num_heads=4, dff=64,
    input_vocab_size=50, target_vocab_size=50, max_position=16,
    dtype="float32", dropout_rate=0.0,
    moe_experts=4, moe_top_k=2,
)
TRAIN_TINY = TrainConfig(batch_size=8, sequence_length=12, warmup_steps=100)


def _x(key, b=2, s=10, m=32):
    return jax.random.normal(jax.random.PRNGKey(key), (b, s, m))


class TestMoeOp:
    def test_shapes_and_dtype(self):
        p = moe_init(jax.random.PRNGKey(0), 32, 64, 4)
        x = _x(1).astype(jnp.bfloat16)
        y, aux = moe_apply(p, x, num_experts=4)
        assert y.shape == x.shape and y.dtype == x.dtype
        assert aux.shape == () and aux.dtype == jnp.float32

    def test_one_expert_equals_dense_ffn(self):
        """A 1-expert MoE routes every token (gate exactly 1.0 after the
        softmax over one logit) and must reproduce the dense FFN bit-for-bit
        in fp32 up to summation order."""
        p = moe_init(jax.random.PRNGKey(0), 32, 64, 1)
        x = _x(2)
        y, aux = moe_apply(p, x, num_experts=1, top_k=1, capacity_factor=10.0)
        dense = {
            "in": {"kernel": p["in"]["kernel"][0], "bias": p["in"]["bias"][0]},
            "out": {"kernel": p["out"]["kernel"][0], "bias": p["out"]["bias"][0]},
        }
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(ffn_apply(dense, x)), atol=1e-5
        )
        np.testing.assert_allclose(float(aux), 1.0, atol=1e-6)

    def test_identical_experts_equal_dense(self):
        """With every expert holding the SAME weights, routing becomes
        irrelevant (gates renormalize to 1) — output must equal the dense FFN
        whenever no token overflows capacity."""
        E = 4
        p = moe_init(jax.random.PRNGKey(0), 32, 64, E)
        p = jax.tree.map(lambda a: a, p)
        p["in"]["kernel"] = jnp.broadcast_to(p["in"]["kernel"][:1], p["in"]["kernel"].shape)
        p["out"]["kernel"] = jnp.broadcast_to(p["out"]["kernel"][:1], p["out"]["kernel"].shape)
        x = _x(3)
        y, _ = moe_apply(p, x, num_experts=E, top_k=2, capacity_factor=float(E))
        dense = {
            "in": {"kernel": p["in"]["kernel"][0], "bias": p["in"]["bias"][0]},
            "out": {"kernel": p["out"]["kernel"][0], "bias": p["out"]["bias"][0]},
        }
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(ffn_apply(dense, x)), atol=1e-5
        )

    def test_capacity_overflow_drops_tokens(self):
        """Capacity 1 with a router biased to a single expert: only one token
        slot per row survives; the rest produce zero output (their residual
        path carries them in the full layer)."""
        E, S = 4, 8
        p = moe_init(jax.random.PRNGKey(0), 32, 64, E)
        # Router forced: huge weight toward expert 0, positive activations
        # below make its logit dominate for every token.
        p["router"]["kernel"] = jnp.zeros_like(p["router"]["kernel"]).at[:, 0].set(100.0)
        x = jnp.broadcast_to(jnp.abs(_x(4, b=1, s=1, m=32)) + 0.1, (1, S, 32))
        y, _ = moe_apply(p, x, num_experts=E, top_k=1, capacity_factor=1e-9)
        assert expert_capacity(S, E, 1, 1e-9) == 1
        norms = jnp.linalg.norm(y[0], axis=-1)
        # All S tokens pick expert 0, which has exactly 1 slot: the first
        # token survives, the other S-1 are dropped (zero output).
        assert int(jnp.sum(norms > 1e-7)) == 1
        assert int(jnp.sum(norms <= 1e-7)) == S - 1

    def test_aux_loss_balanced_vs_collapsed(self):
        """Uniform routing gives aux ~= 1; a collapsed router (all tokens to
        one expert) gives aux ~= E."""
        E = 4
        p = moe_init(jax.random.PRNGKey(0), 32, 64, E)
        x = _x(5, b=4, s=32)
        p_uniform = dict(p, router={"kernel": jnp.zeros_like(p["router"]["kernel"])})
        _, aux_u = moe_apply(p_uniform, x, num_experts=E)
        # Zero logits -> uniform probs; ties in top_k pick a single expert,
        # but p_e stays 1/E so aux stays E * sum(f_e / E) = 1.
        np.testing.assert_allclose(float(aux_u), 1.0, atol=1e-5)
        collapsed = jnp.zeros_like(p["router"]["kernel"]).at[:, 2].set(100.0)
        p_collapsed = dict(p, router={"kernel": collapsed})
        # Positive activations => every token's expert-2 logit is large and
        # positive => routing fully collapses.
        _, aux_c = moe_apply(p_collapsed, jnp.abs(x) + 0.1, num_experts=E)
        np.testing.assert_allclose(float(aux_c), float(E), atol=1e-3)

    def test_token_mask_excludes_pads(self):
        """PAD positions must neither claim capacity slots (starving real
        tokens) nor enter the load-balance statistics."""
        E, S, real = 2, 8, 3
        p = moe_init(jax.random.PRNGKey(0), 32, 64, E)
        x = _x(7, b=1, s=S, m=32)
        mask = jnp.arange(S)[None, :] < real  # 3 real tokens, 5 "PADs"
        # Capacity 2/expert: without the mask 8 tokens compete for 4 slots
        # and some REAL tokens can be dropped; with it, 3 real tokens always
        # fit and every masked position outputs exactly zero.
        y, aux = moe_apply(
            p, x, num_experts=E, top_k=1, capacity_factor=0.5, token_mask=mask
        )
        assert expert_capacity(S, E, 1, 0.5) == 2
        norms = jnp.linalg.norm(y[0], axis=-1)
        np.testing.assert_array_equal(np.asarray(norms[real:]), 0.0)
        assert float(jnp.min(norms[:real])) > 1e-7  # no real token dropped
        # Aux statistics over real tokens only: recompute on the real slice.
        _, aux_ref = moe_apply(
            p, x[:, :real], num_experts=E, top_k=1, capacity_factor=0.5
        )
        np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)

    def test_gradients_flow_to_all_param_groups(self):
        p = moe_init(jax.random.PRNGKey(0), 32, 64, 4)
        x = _x(6)

        def loss(p):
            y, aux = moe_apply(p, x, num_experts=4)
            return jnp.sum(y**2) + aux

        g = jax.grad(loss)(p)
        for path, leaf in jax.tree_util.tree_leaves_with_path(g):
            assert np.all(np.isfinite(np.asarray(leaf))), path
        # The router only receives gradient through gates/aux — check nonzero.
        assert float(jnp.abs(g["router"]["kernel"]).sum()) > 0


class TestMoeModel:
    def test_transformer_forward_reports_aux(self):
        from transformer_tpu.models import transformer_apply, transformer_init

        params = transformer_init(jax.random.PRNGKey(0), MOE_TINY)
        ids = jnp.ones((2, 8), jnp.int32)
        logits, attn = transformer_apply(params, ids, ids, MOE_TINY)
        assert logits.shape == (2, 8, 50)
        assert "moe_aux_encoder" in attn and "moe_aux_decoder" in attn
        assert np.isfinite(float(attn["moe_aux_encoder"]))

    def test_moe_every_cadence(self):
        from transformer_tpu.models import transformer_init

        cfg = dataclasses.replace(MOE_TINY, num_layers=4, moe_every=2)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        kinds = ["moe" if "moe" in l else "ffn" for l in params["encoder"]["layers"]]
        assert kinds == ["ffn", "moe", "ffn", "moe"]

    def test_train_step_falls_and_reports_aux(self):
        from transformer_tpu.train import create_train_state, make_train_step

        state = create_train_state(jax.random.PRNGKey(0), MOE_TINY, TRAIN_TINY)
        step = jax.jit(make_train_step(MOE_TINY, TRAIN_TINY))
        r = np.random.default_rng(0)
        src = jnp.asarray(r.integers(1, 48, (8, 12)), jnp.int32)
        tgt = jnp.asarray(r.integers(1, 48, (8, 12)), jnp.int32)
        rng = jax.random.PRNGKey(1)
        first = None
        for _ in range(40):
            state, m = step(state, src, tgt, rng)
            first = float(m["loss"]) if first is None else first
        assert "moe_aux" in m and np.isfinite(float(m["moe_aux"]))
        assert float(m["loss"]) < first * 0.7

    def test_remat_matches_no_remat(self):
        """The aux loss is a real layer output, so grads must agree exactly
        with and without jax.checkpoint around the layers."""
        from transformer_tpu.models import transformer_apply, transformer_init

        cfg_r = dataclasses.replace(MOE_TINY, remat=True)
        params = transformer_init(jax.random.PRNGKey(0), MOE_TINY)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(1, 48, (2, 8)), jnp.int32
        )

        def loss(p, cfg):
            logits, attn = transformer_apply(p, ids, ids, cfg)
            return jnp.sum(logits.astype(jnp.float32) ** 2) * 1e-4 + attn[
                "moe_aux_encoder"
            ]

        g_plain = jax.jit(jax.grad(lambda p: loss(p, MOE_TINY)))(params)
        g_remat = jax.jit(jax.grad(lambda p: loss(p, cfg_r)))(params)
        for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_remat)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_grad_accum_matches_full_batch(self):
        from transformer_tpu.train import create_train_state, make_train_step

        tc1 = TRAIN_TINY
        tc2 = dataclasses.replace(TRAIN_TINY, grad_accum_steps=2)
        r = np.random.default_rng(1)
        src = jnp.asarray(r.integers(1, 48, (8, 12)), jnp.int32)
        tgt = jnp.asarray(r.integers(1, 48, (8, 12)), jnp.int32)
        rng = jax.random.PRNGKey(1)
        s1 = create_train_state(jax.random.PRNGKey(0), MOE_TINY, tc1)
        s2 = create_train_state(jax.random.PRNGKey(0), MOE_TINY, tc2)
        s1, m1 = jax.jit(make_train_step(MOE_TINY, tc1))(s1, src, tgt, rng)
        s2, m2 = jax.jit(make_train_step(MOE_TINY, tc2))(s2, src, tgt, rng)
        # CE metrics identical (routing and capacity are per batch row, so
        # chunking the batch changes nothing in the forward). The aux loss is
        # a nonlinear batch statistic (E * sum f_e p_e over the rows present),
        # so the token-weighted mean of per-chunk values only approximates the
        # whole-batch value — close, not equal.
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
        np.testing.assert_allclose(
            float(m1["moe_aux"]), float(m2["moe_aux"]), rtol=0.05
        )

    def test_decode_works_with_moe(self):
        """KV-cached greedy decode runs through MoE decoder layers (S=1
        routing: one token per row always fits capacity)."""
        from transformer_tpu.models import transformer_init
        from transformer_tpu.train.decode import greedy_decode

        params = transformer_init(jax.random.PRNGKey(0), MOE_TINY)
        src = jnp.asarray([[5, 6, 7, 0]], jnp.int32)
        out = greedy_decode(
            params, src, MOE_TINY, bos_id=48, eos_id=49, max_len=6
        )
        assert out.shape[0] == 1 and out.shape[1] <= 7


class TestExpertParallel:
    def test_mesh_parity_with_single_device(self):
        from transformer_tpu.parallel import DistributedTrainer, make_mesh
        from transformer_tpu.train import create_train_state, make_train_step

        r = np.random.default_rng(0)
        src = r.integers(1, 48, (8, 12), dtype=np.int32)
        tgt = r.integers(1, 48, (8, 12), dtype=np.int32)
        rng = jax.random.PRNGKey(1)

        mesh = make_mesh(MeshConfig(data=2, expert=4))
        dt = DistributedTrainer(MOE_TINY, TRAIN_TINY, mesh)
        s_d = dt.state
        for _ in range(3):
            s_d, m_d = dt.train_step(s_d, src, tgt, rng)

        s_1 = create_train_state(jax.random.PRNGKey(TRAIN_TINY.seed), MOE_TINY, TRAIN_TINY)
        step = jax.jit(make_train_step(MOE_TINY, TRAIN_TINY))
        for _ in range(3):
            s_1, m_1 = step(s_1, jnp.asarray(src), jnp.asarray(tgt), rng)

        np.testing.assert_allclose(float(m_d["loss"]), float(m_1["loss"]), rtol=2e-4)
        np.testing.assert_allclose(
            float(m_d["moe_aux"]), float(m_1["moe_aux"]), rtol=2e-4
        )

    def test_expert_weights_actually_sharded(self):
        from transformer_tpu.parallel import DistributedTrainer, make_mesh

        mesh = make_mesh(MeshConfig(data=2, expert=4))
        dt = DistributedTrainer(MOE_TINY, TRAIN_TINY, mesh)
        kernel = dt.state.params["encoder"]["layers"][0]["moe"]["in"]["kernel"]
        spec = kernel.sharding.spec
        assert spec[0] == "expert", spec
        # 4 experts over expert=4: each shard holds exactly one expert.
        shard = kernel.addressable_shards[0].data
        assert shard.shape[0] == MOE_TINY.moe_experts // 4

    def test_ep_composes_with_tp(self):
        from transformer_tpu.parallel import DistributedTrainer, make_mesh

        mesh = make_mesh(MeshConfig(data=2, model=2, expert=2))
        dt = DistributedTrainer(MOE_TINY, TRAIN_TINY, mesh)
        r = np.random.default_rng(2)
        src = r.integers(1, 48, (8, 12), dtype=np.int32)
        tgt = r.integers(1, 48, (8, 12), dtype=np.int32)
        s, m = dt.train_step(dt.state, src, tgt, jax.random.PRNGKey(1))
        assert np.isfinite(float(m["loss"]))
        kernel = s.params["encoder"]["layers"][0]["moe"]["in"]["kernel"]
        assert kernel.sharding.spec[0] == "expert"

    def test_moe_rejects_heterogeneous_pipeline(self):
        from transformer_tpu.parallel import DistributedTrainer, make_mesh

        cfg = dataclasses.replace(MOE_TINY, num_layers=4, moe_every=2)
        mesh = make_mesh(MeshConfig(data=4, pipe=2))
        with pytest.raises(ValueError, match="homogeneous"):
            DistributedTrainer(cfg, TRAIN_TINY, mesh)

    def test_moe_pipe_rejects_expert_axis(self):
        """pipe>1 with expert>1 must fail with the clean guard, not a
        trace-time shard_map error (expert_mesh constraints cannot fire
        inside the GPipe shard_map)."""
        from transformer_tpu.parallel import DistributedTrainer, make_mesh

        mesh = make_mesh(MeshConfig(data=2, pipe=2, expert=2))
        with pytest.raises(ValueError, match="expert"):
            DistributedTrainer(MOE_TINY, TRAIN_TINY, mesh)

    def test_pipelined_moe_matches_sequential(self):
        """GPipe over a homogeneous MoE stack: logits must match the
        sequential forward exactly; with one microbatch and no data sharding
        the aux loss matches too."""
        from transformer_tpu.models import transformer_apply, transformer_init
        from transformer_tpu.parallel import make_mesh, pipelined_transformer_apply
        from transformer_tpu.train.trainer import _collect_moe_aux

        mesh = make_mesh(MeshConfig(data=1, pipe=2), devices=jax.devices()[:2])
        params = transformer_init(jax.random.PRNGKey(0), MOE_TINY)
        r = np.random.default_rng(3)
        src = jnp.asarray(r.integers(1, 48, (4, 10)), jnp.int32)
        tgt = jnp.asarray(r.integers(1, 48, (4, 10)), jnp.int32)

        logits_pp, aux_pp = jax.jit(
            lambda p: pipelined_transformer_apply(
                p, src, tgt, MOE_TINY, mesh=mesh, num_microbatches=1,
                deterministic=True,
            )
        )(params)
        logits_seq, attn = transformer_apply(params, src, tgt, MOE_TINY)
        np.testing.assert_allclose(
            np.asarray(logits_pp), np.asarray(logits_seq), atol=2e-5
        )
        np.testing.assert_allclose(
            float(aux_pp), float(_collect_moe_aux(attn)), rtol=1e-5
        )

    def test_moe_pipe_trainer_step(self):
        """DistributedTrainer on a data×pipe mesh with a homogeneous MoE
        model: one step trains, reports finite loss and aux."""
        from transformer_tpu.parallel import DistributedTrainer, make_mesh

        mesh = make_mesh(MeshConfig(data=4, pipe=2))
        dt = DistributedTrainer(MOE_TINY, TRAIN_TINY, mesh)
        r = np.random.default_rng(4)
        src = r.integers(1, 48, (8, 12), dtype=np.int32)
        tgt = r.integers(1, 48, (8, 12), dtype=np.int32)
        s, m = dt.train_step(dt.state, src, tgt, jax.random.PRNGKey(1))
        assert np.isfinite(float(m["loss"]))
        assert np.isfinite(float(m["moe_aux"])) and float(m["moe_aux"]) > 0
