"""Live-weights control plane (``serve/upgrade.py``, docs/SERVING.md
"Live-weights rollout"): verified-integrity checkpoint manifests, the
scheduler's two-version param slot (admission-time weights, zero
recompiles), the router-coordinated rolling swap with canary gating and
SLO-driven auto-rollback, and the supervisor's respawn-at-target fix."""

import io
import json
import os
import signal
import time

import numpy as np
import pytest

from transformer_tpu.obs import EventLog, Telemetry
from transformer_tpu.serve.router import ReplicaLink, ReplicaProcess, Router
from transformer_tpu.serve.supervisor import Supervisor
from transformer_tpu.serve.upgrade import (
    UpgradeCoordinator,
    UpgradeError,
    load_checkpoint_params,
    verify_checkpoint,
)

# The deterministic test-model bootstrap (tests/test_router.py): every
# process building this spec gets bit-identical params and vocab, so
# byte-parity assertions hold across process boundaries AND versions.
SPEC = {
    "config": {
        "num_layers": 1, "d_model": 16, "num_heads": 2, "dff": 32,
        "max_position": 32, "decoder_only": True, "tie_output": True,
        "dtype": "float32", "dropout_rate": 0.0,
    },
    "seed": 0,
    "corpus": ["ab cd ef gh ij kl mn"] * 3,
    "target_vocab_size": 300,
}
PROMPT = "ab cd ef gh ij"


@pytest.fixture(scope="module")
def lm():
    from transformer_tpu.serve.replica import build_model_from_spec

    return build_model_from_spec(SPEC)


@pytest.fixture(scope="module")
def lm_new():
    """The upgrade target: the SAME architecture from a different init
    seed — structurally a twin (the zero-recompile precondition), byte-
    different weights (so version tags are testable, not decorative)."""
    from transformer_tpu.serve.replica import build_model_from_spec

    return build_model_from_spec({**SPEC, "seed": 1})


@pytest.fixture(scope="module")
def spec_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("upgrade") / "spec.json"
    path.write_text(json.dumps(SPEC))
    return str(path)


@pytest.fixture(scope="module")
def ckpts(tmp_path_factory, lm, lm_new):
    """(old_dir, new_dir): manifest-bearing param checkpoints of both
    versions, saved through the real CheckpointManager."""
    from transformer_tpu.train.checkpoint import CheckpointManager

    root = tmp_path_factory.mktemp("ckpts")
    old_dir = CheckpointManager(str(root / "old"), is_primary=True).save(
        lm[0], step=1
    )
    new_dir = CheckpointManager(str(root / "new"), is_primary=True).save(
        lm_new[0], step=1
    )
    return old_dir, new_dir


def _reference(model, reqs):
    from transformer_tpu.serve import ContinuousScheduler

    params, cfg, tok = model
    return ContinuousScheduler(params, cfg, tok, num_slots=2).run(
        [dict(r) for r in reqs]
    )


def _events(buf: io.StringIO) -> list:
    return [json.loads(line) for line in buf.getvalue().splitlines()]


# --------------------------------------------------------------------------
# checkpoint manifest: checksummed, atomic, preferred by restore_latest


def test_manifest_digest_names_bytes(tmp_path):
    from transformer_tpu.train.checkpoint import (
        CheckpointManager,
        checkpoint_version,
        verify_manifest,
    )

    mgr = CheckpointManager(str(tmp_path), is_primary=True)
    state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    p1 = mgr.save(state, step=1)
    assert sorted(f for f in os.listdir(p1)) == [
        "arrays.npz", "manifest.json", "meta.json",
    ]
    v1 = verify_manifest(p1)
    assert checkpoint_version(p1) == v1
    # Byte-identical save -> identical digest (the weight_version
    # contract); different bytes -> different digest.
    p2 = mgr.save(state, step=2)
    assert checkpoint_version(p2) == v1
    p3 = mgr.save({"w": state["w"] + 1}, step=3)
    assert checkpoint_version(p3) != v1


def test_manifest_catches_what_the_structural_probe_cannot(tmp_path, capsys):
    """A checkpoint whose arrays were swapped for DIFFERENT same-shaped
    values unpickles fine and passes every shape check — only the crc32
    manifest knows the bytes are wrong. restore_latest must fall back."""
    from transformer_tpu.train.checkpoint import (
        CheckpointIntegrityError,
        CheckpointManager,
        verify_manifest,
    )

    mgr = CheckpointManager(str(tmp_path), is_primary=True)
    good = {"w": np.full((2, 3), 7.0, np.float32)}
    mgr.save(good, step=1)
    p2 = mgr.save({"w": np.full((2, 3), 9.0, np.float32)}, step=2)
    # Swap step 2's arrays for same-shaped different bytes (a mixed copy /
    # silent corruption): the zip is valid, the shapes match the target.
    donor = CheckpointManager(str(tmp_path / "donor"), is_primary=True)
    dpath = donor.save({"w": np.full((2, 3), 5.0, np.float32)}, step=9)
    os.replace(
        os.path.join(dpath, "arrays.npz"), os.path.join(p2, "arrays.npz")
    )
    with pytest.raises(CheckpointIntegrityError):
        verify_manifest(p2)
    restored = mgr.restore_latest({"w": np.zeros((2, 3), np.float32)})
    np.testing.assert_array_equal(restored["w"], good["w"])
    assert "falling back" in capsys.readouterr().err


def test_torn_manifest_falls_back(tmp_path):
    from transformer_tpu.train.checkpoint import (
        CheckpointIntegrityError,
        CheckpointManager,
        load_manifest,
    )

    mgr = CheckpointManager(str(tmp_path), is_primary=True)
    mgr.save({"w": np.full((2,), 1.0, np.float32)}, step=1)
    p2 = mgr.save({"w": np.full((2,), 2.0, np.float32)}, step=2)
    # A half-written manifest (the crash shape the atomic tmp+fsync+rename
    # write prevents for OUR writes, but partial copies still produce).
    with open(os.path.join(p2, "manifest.json"), "w") as f:
        f.write('{"format": "manifest-v1", "arrays": {"w"')
    with pytest.raises(CheckpointIntegrityError):
        load_manifest(p2)
    fallbacks = []
    restored = mgr.restore_latest(
        {"w": np.zeros((2,), np.float32)},
        on_fallback=lambda step, exc: fallbacks.append(step),
    )
    np.testing.assert_array_equal(restored["w"], np.full((2,), 1.0))
    assert fallbacks == [2]


# --------------------------------------------------------------------------
# replica-side verified load + the scheduler's two-version param slot


def test_load_checkpoint_params_verifies_and_matches(lm, lm_new, ckpts):
    params, cfg, tok = lm
    _, new_dir = ckpts
    loaded, version = load_checkpoint_params(new_dir, params)
    assert version == verify_checkpoint(new_dir)[1]
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(loaded),
        jax.tree_util.tree_leaves(lm_new[0]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_load_checkpoint_params_refuses_wrong_spec(tmp_path, lm):
    """A checkpoint of a DIFFERENT architecture must be refused before
    anything is staged — shape/dtype twins are the zero-recompile
    precondition."""
    from transformer_tpu.serve.replica import build_model_from_spec
    from transformer_tpu.train.checkpoint import CheckpointManager

    other_params, _, _ = build_model_from_spec(
        {**SPEC, "config": {**SPEC["config"], "d_model": 32, "dff": 64}}
    )
    path = CheckpointManager(str(tmp_path), is_primary=True).save(
        other_params, step=1
    )
    with pytest.raises(UpgradeError, match="does not match the running"):
        load_checkpoint_params(path, lm[0])


def test_verify_checkpoint_refuses_unmanifested(tmp_path):
    """A checkpoint without a manifest cannot prove byte-consistency
    across N replicas — the control plane refuses it."""
    from transformer_tpu.train.checkpoint import CheckpointManager

    path = CheckpointManager(str(tmp_path), is_primary=True).save(
        {"w": np.zeros((2,), np.float32)}, step=1
    )
    os.unlink(os.path.join(path, "manifest.json"))
    with pytest.raises(UpgradeError, match="no manifest"):
        verify_checkpoint(path)


def test_scheduler_swap_admission_time_weights_zero_recompiles(lm, lm_new):
    """The two-version param slot end to end: requests admitted before
    the stage finish on THEIR weights while admission quiesces, the flip
    lands at the drained step boundary, rollback re-stages the resident
    old pair — all with zero new compiled programs."""
    from transformer_tpu.analysis.retrace import _cache_size
    from transformer_tpu.serve import ContinuousScheduler
    from transformer_tpu.serve import scheduler as smod

    params, cfg, tok = lm
    reqs = [{"prompt": PROMPT, "max_new": 6}] * 2
    want_old = _reference(lm, reqs)
    want_new = _reference(lm_new, reqs)
    assert want_old[0]["continuation"] != want_new[0]["continuation"], (
        "old and new weights answer identically — the tag test is vacuous"
    )
    s = ContinuousScheduler(
        params, cfg, tok, num_slots=2, weight_version="vOLD"
    )
    s.run([dict(r) for r in reqs])  # warmup compiles
    before = {
        "step": _cache_size(smod._pool_step),
        "prefill": _cache_size(smod._slot_prefill),
        "pick": _cache_size(smod._pick_pool),
    }
    # Straddle: admit on vOLD, stage vNEW mid-flight.
    for r in reqs:
        s.submit(dict(r))
    s.admit()
    assert s.active_count == 2
    s.stage_params(lm_new[0], "vNEW")
    # Quiesce: nothing new admits while the stage is pending.
    s.submit({"prompt": PROMPT, "max_new": 6})
    s.admit()
    assert s.active_count == 2
    while s.busy:
        s.admit()
        s.step()
    out = s.drain_ready()
    # The straddling pair answered from its ADMISSION-TIME weights; the
    # quiesced third request answered on the new weights after the flip.
    assert [o["weight_version"] for o in out] == ["vOLD", "vOLD", "vNEW"]
    assert [o["continuation"] for o in out[:2]] == [
        w["continuation"] for w in want_old
    ]
    assert out[2]["continuation"] == want_new[0]["continuation"]
    assert s.weight_version == "vNEW"
    assert s.consume_swap_events() == [{"ok": True, "version": "vNEW"}]
    # Rollback: the old pair never left the device.
    assert s.stage_rollback() == "vOLD"
    s.step()
    assert s.weight_version == "vOLD"
    out = s.run([dict(r) for r in reqs])
    assert [o["continuation"] for o in out] == [
        w["continuation"] for w in want_old
    ]
    after = {
        "step": _cache_size(smod._pool_step),
        "prefill": _cache_size(smod._slot_prefill),
        "pick": _cache_size(smod._pick_pool),
    }
    assert after == before, f"swap minted new programs: {before} -> {after}"


def test_stage_params_refuses_structural_mismatch(lm):
    from transformer_tpu.serve import ContinuousScheduler
    from transformer_tpu.serve.replica import build_model_from_spec

    params, cfg, tok = lm
    s = ContinuousScheduler(params, cfg, tok, num_slots=1)
    other, _, _ = build_model_from_spec(
        {**SPEC, "config": {**SPEC["config"], "d_model": 32, "dff": 64}}
    )
    with pytest.raises(ValueError, match="mismatch|structure"):
        s.stage_params(other, "vBAD")
    assert not s.swap_pending


# --------------------------------------------------------------------------
# fake-link fleet drills (fast, deterministic — the chaos subset)


class _FakeReplica(ReplicaLink):
    """A scripted worker speaking the upgrade protocol: answers carry its
    CURRENT version, upgrade/rollback messages flip it (confirming like a
    drained scheduler would), and ``die_on_upgrade`` simulates a SIGKILL
    after the swap message was delivered but before any confirmation."""

    def __init__(self, index, name, version="vOLD"):
        super().__init__(index, name)
        self.wv = version
        self.cur = version
        self.router = None
        self.ok = True
        self.die_on_upgrade = False
        self.upgrades_seen = []

    def alive(self):
        return self.ok

    def kill(self):
        self.ok = False

    def send(self, msg):
        if not self.ok:
            raise BrokenPipeError("dead")
        kind = msg.get("type")
        if kind == "req":
            self.router.inbox.put((self.index, {
                "type": "answer", "rid": msg["rid"],
                "resp": {"continuation": f"{self.name}:{self.cur}",
                         "weight_version": self.cur},
                "slo": {"ttft_s": 0.01, "total_s": 0.02},
            }))
        elif kind == "upgrade":
            self.upgrades_seen.append(dict(msg))
            if self.die_on_upgrade:
                self.ok = False
                self.router.inbox.put((self.index, {"type": "exit"}))
                return
            self.cur = msg["version"]
            self.router.inbox.put((self.index, {
                "type": "upgrade_staged", "ok": True,
                "version": msg["version"],
            }))
            self.router.inbox.put((self.index, {
                "type": "upgraded", "ok": True, "version": msg["version"],
            }))
        elif kind == "rollback":
            self.cur = "vOLD"
            self.router.inbox.put((self.index, {
                "type": "upgraded", "ok": True, "version": "vOLD",
            }))
        elif kind == "export_state":
            self.router.inbox.put(
                (self.index, {"type": "prefix_state", "entries": []})
            )


def _fake_fleet(n=2, *, upgrader, supervisor=None, telemetry=None, **kw):
    links = [_FakeReplica(i, f"f{i}") for i in range(n)]
    router = Router(
        links, encode=None, upgrader=upgrader, supervisor=supervisor,
        telemetry=telemetry, **kw,
    )
    for link in links:
        link.router = router
    return router, links


def _drive(router, up, until, max_iters=200):
    for _ in range(max_iters):
        router.pump(timeout=0)
        if until():
            return
    raise AssertionError(f"coordinator stuck in state {up.state}")


@pytest.mark.chaos
def test_corrupt_checkpoint_rejected_before_any_replica_swaps(tmp_path):
    """Integrity at the door: a checkpoint whose manifest fails
    verification is refused FLEET-WIDE — a structured `upgrade` error, a
    route.upgrade rejected event, zero swap messages sent, serving
    untouched."""
    from transformer_tpu.train.checkpoint import CheckpointManager

    path = CheckpointManager(str(tmp_path), is_primary=True).save(
        {"w": np.zeros((2,), np.float32)}, step=1
    )
    # Garble the manifest (digest mismatch): real verify_checkpoint runs.
    mpath = os.path.join(path, "manifest.json")
    manifest = json.load(open(mpath))
    manifest["arrays"]["w"]["crc32"] ^= 0xFF
    json.dump(manifest, open(mpath, "w"))

    buf = io.StringIO()
    telemetry = Telemetry(events=EventLog(buf))
    up = UpgradeCoordinator()
    router, links = _fake_fleet(2, upgrader=up, telemetry=telemetry)
    status = router.start_upgrade(str(tmp_path))
    assert status["ok"] is False and status["code"] == "upgrade"
    assert "digest" in status["error"] or "crc32" in status["error"], status
    assert up.state == "idle"
    assert up.stats["rejected"] == 1
    assert all(not l.upgrades_seen for l in links), (
        "a replica was touched by a rejected rollout"
    )
    assert router.weight_target is None
    # Serving is untouched.
    out = router.run([{"prompt": "p"}] * 3)
    assert all("continuation" in o for o in out)
    telemetry.maybe_flush(force=True)
    rejected = [
        e for e in _events(buf)
        if e.get("kind") == "route.upgrade" and e.get("phase") == "rejected"
    ]
    assert len(rejected) == 1 and rejected[0]["error"]


@pytest.mark.chaos
def test_mesh_mismatch_staging_answers_structured_refusal(lm):
    """Sharded-replica twin check on the upgrade wire (serve/sharded.py):
    a replica serving on a 2-device mesh refuses staged weights COMMITTED
    to a different mesh — the real stage_params sharding check raises, the
    worker answers a structured ``upgrade_staged`` refusal (exactly what
    replica.py's _reap_upgrade_load sends), the coordinator aborts fleet-
    wide, and serving is untouched on both the wire and the scheduler."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from transformer_tpu.serve import ContinuousScheduler
    from transformer_tpu.serve.sharded import serving_mesh

    params, cfg, tok = lm
    sched = ContinuousScheduler(
        params, cfg, tok, num_slots=2, mesh=2, weight_version="vOLD"
    )
    req = {"prompt": "ab cd ef", "max_new": 4}
    want = [r.get("continuation") for r in sched.run([dict(req)])]
    # Structural twin of the serving params, but committed to a 4-device
    # mesh: shapes/dtypes pass, the sharding check must not.
    wrong = jax.device_put(
        jax.tree.map(np.asarray, params),
        NamedSharding(serving_mesh(4), PartitionSpec()),
    )

    class _MeshedReplica(_FakeReplica):
        def send(self, msg):
            if msg.get("type") == "upgrade":
                self.upgrades_seen.append(dict(msg))
                try:
                    sched.stage_params(wrong, msg["version"])
                except ValueError as e:
                    self.router.inbox.put((self.index, {
                        "type": "upgrade_staged", "ok": False,
                        "version": msg["version"],
                        "error": f"{type(e).__name__}: {e}",
                    }))
                    return
                raise AssertionError("mismatched-mesh staging was accepted")
            super().send(msg)

    buf = io.StringIO()
    telemetry = Telemetry(events=EventLog(buf))
    up = UpgradeCoordinator(verify=lambda p: (p, "vNEW"))
    links = [_MeshedReplica(0, "f0"), _FakeReplica(1, "f1")]
    router = Router(links, encode=None, upgrader=up, telemetry=telemetry)
    for link in links:
        link.router = router
    assert router.start_upgrade("/ckpt")["ok"]
    _drive(router, up, lambda: up.state in ("failed", "rolled_back"))
    assert up.state == "failed", up.state
    assert up.stats["aborted"] == 1
    assert all(l.cur == "vOLD" for l in links)
    assert router.weight_target is None
    # Zero serving impact: no pending swap, identical answers, and the
    # fleet still serves on the old version.
    assert not sched.swap_pending
    assert [
        r.get("continuation") for r in sched.run([dict(req)])
    ] == want
    out = router.run([{"prompt": "p"}] * 3)
    assert all(o["weight_version"] == "vOLD" for o in out)
    telemetry.maybe_flush(force=True)
    failed = [
        e for e in _events(buf)
        if e.get("kind") == "route.upgrade" and e.get("phase") == "failed"
    ]
    assert len(failed) == 1 and "sharding" in failed[0]["error"]


@pytest.mark.chaos
def test_canary_rollback_on_injected_burn():
    """The auto-rollback ladder: route.canary marks every canary answer
    bad in the per-version SLO split, burn > 1 sustains across the
    windows, and the fleet converges BACK to the old version with the
    burn evidence in route.upgrade rolled_back=true — zero lost
    requests."""
    from transformer_tpu.serve.resilience import FaultPlane, install

    buf = io.StringIO()
    telemetry = Telemetry(events=EventLog(buf))
    up = UpgradeCoordinator(
        canary_window_s=30.0, canary_min_requests=2,
        verify=lambda p: (p, "vNEW"),
    )
    router, links = _fake_fleet(2, upgrader=up, telemetry=telemetry)
    want = router.run([{"prompt": "p"}] * 2)
    assert all(o["weight_version"] == "vOLD" for o in want)
    install(FaultPlane.parse("route.canary:p=1,seed=7"))
    try:
        assert router.start_upgrade("/ckpt")["ok"]
        _drive(router, up, lambda: up.state == "canary")
        out = router.run([{"prompt": "p"}] * 8)
        assert len(out) == 8 and all("continuation" in o for o in out)
        _drive(router, up, lambda: up.state in ("rolled_back", "failed"))
    finally:
        install(None)
    assert up.state == "rolled_back", up.state
    assert up.stats["rollbacks"] == 1
    assert up.stats["injected_canary_burn"] > 0
    assert all(l.wv == "vOLD" and l.cur == "vOLD" for l in links)
    assert router.weight_target is None, (
        "a rolled-back rollout left the respawn target pointing at the "
        "bad version"
    )
    # Post-rollback serving is back on the old weights, nothing lost.
    out = router.run([{"prompt": "p"}] * 3)
    assert all(o["weight_version"] == "vOLD" for o in out)
    telemetry.maybe_flush(force=True)
    events = _events(buf)
    rb = [e for e in events if e.get("rolled_back")]
    assert len(rb) == 1
    assert rb[0]["version"] == "vNEW"
    assert rb[0]["evidence"], "rollback carried no burn evidence"
    assert "burn" in rb[0]["reason"]
    # The canary's pinned slice was deterministic and observed.
    assert up.stats["canary_requests"] > 0


@pytest.mark.chaos
def test_mid_swap_death_respawns_at_target_version():
    """SIGKILL mid-swap: the victim dies after the upgrade message lands
    but before confirming. The rollout continues, and the supervisor
    respawns the index AT THE FLEET'S TARGET VERSION (the 4-arg spawn
    recipe receives Router.weight_target) — the stale-respawn fix."""
    clk = [0.0]
    spawn_targets = []

    def spawn(index, name, role, weight_target=None):
        spawn_targets.append(weight_target)
        link = _FakeReplica(
            index, name,
            version=weight_target[1] if weight_target else "vOLD",
        )
        link.cur = link.wv
        link.router = router
        router.inbox.put((index, {
            "type": "ready", "replica": name, "weight_version": link.wv,
        }))
        return link

    sup = Supervisor(spawn, backoff_ms=0.0, clock=lambda: clk[0])
    up = UpgradeCoordinator(
        canary_window_s=0.0, canary_min_requests=1,
        verify=lambda p: (p, "vNEW"),
    )
    router, links = _fake_fleet(2, upgrader=up, supervisor=sup)
    links[1].die_on_upgrade = True
    assert router.start_upgrade("/ckpt")["ok"]

    def converged():
        clk[0] += 1.0
        return (
            up.state == "done"
            and sup.stats["respawns"] == 1
            and all(not l.dead and l.wv == "vNEW" for l in router.links)
        )

    _drive(router, up, converged)
    assert spawn_targets == [("/ckpt", "vNEW")], spawn_targets
    # The replacement answers at the target version, like the upgraded
    # survivor — byte-consistency per tag holds across the heal.
    out = router.run([{"prompt": "p"}] * 4)
    assert all(o["weight_version"] == "vNEW" for o in out), out
    assert up.stats["rollbacks"] == 0


@pytest.mark.chaos
def test_route_upgrade_fault_aborts_and_rolls_back():
    """The route.upgrade injection point: the SECOND per-replica swap
    dispatch faults, the rollout aborts, and the already-upgraded canary
    rolls back — the fleet is never left half-upgraded."""
    from transformer_tpu.serve.resilience import FaultPlane, install

    up = UpgradeCoordinator(
        canary_window_s=0.0, canary_min_requests=1,
        verify=lambda p: (p, "vNEW"),
    )
    router, links = _fake_fleet(2, upgrader=up)
    install(FaultPlane.parse("route.upgrade:at=2"))
    try:
        assert router.start_upgrade("/ckpt")["ok"]
        _drive(router, up, lambda: up.state in ("failed", "rolled_back"))
    finally:
        install(None)
    assert up.state == "failed", up.state
    assert all(l.cur == "vOLD" for l in links), (
        "abort left a replica on the new weights"
    )
    assert router.weight_target is None
    out = router.run([{"prompt": "p"}] * 3)
    assert all(o["weight_version"] == "vOLD" for o in out)


@pytest.mark.chaos
def test_dead_canary_rolls_back_instead_of_starved_promotion():
    """A canary that dies on the new weights and never recovers must read
    as a ROLLBACK signal: burn stays 0 (failovers answer on old-version
    survivors), so the traffic-starvation escape must not promote the
    crashing version fleet-wide."""
    clk = [100.0]
    up = UpgradeCoordinator(
        canary_window_s=1.0, canary_min_requests=1,
        verify=lambda p: (p, "vNEW"), clock=lambda: clk[0],
    )
    router, links = _fake_fleet(2, upgrader=up)
    assert router.start_upgrade("/ckpt")["ok"]
    _drive(router, up, lambda: up.state == "canary")
    # The canary dies right after its swap; no supervisor, no recovery.
    links[0].ok = False
    router.inbox.put((0, {"type": "exit"}))
    router.pump(timeout=0)
    assert links[0].dead

    def resolved():
        clk[0] += 1.0
        return up.state in ("rolled_back", "failed", "done", "rolling")

    _drive(router, up, resolved)
    assert up.state == "rolled_back", up.state
    assert "did not recover" in up._rollback_reason
    # The survivor was never upgraded; the target is cleared.
    assert links[1].cur == "vOLD"
    assert router.weight_target is None


@pytest.mark.chaos
def test_late_swap_confirmation_after_rollback_converges():
    """A swap confirmation that lands AFTER the rollout rolled back (the
    quiesced flip raced the abort) must be converged back to the old
    version — a half-upgraded fleet is never left behind."""
    from transformer_tpu.serve.resilience import FaultPlane, install

    up = UpgradeCoordinator(
        canary_window_s=30.0, canary_min_requests=1,
        verify=lambda p: (p, "vNEW"),
    )
    router, links = _fake_fleet(2, upgrader=up)
    # Delay replica 0's confirmations: it stages silently and confirms
    # only when the test releases them.
    held = []
    orig_send = links[0].send

    def holding_send(msg, _orig=orig_send):
        if msg.get("type") == "upgrade":
            links[0].upgrades_seen.append(dict(msg))
            links[0].cur = msg["version"]
            held.append({
                "type": "upgraded", "ok": True, "version": msg["version"],
            })
            return
        _orig(msg)

    links[0].send = holding_send
    install(FaultPlane.parse("route.canary:p=1,seed=3"))
    try:
        assert router.start_upgrade("/ckpt")["ok"]
        # replica 0 quiesces and receives the swap but never confirms;
        # drive until the coordinator is waiting in "swap".
        _drive(router, up, lambda: up.state == "swap")
        assert links[0].upgrades_seen
        # Force the rollback decision while the confirmation is in
        # flight (injected canary burn cannot fire yet — the canary never
        # formed — so use the swap-timeout abort path via a late clock).
        up._abort("simulated mid-rollout abort")
        assert up.state in ("rolling_back", "failed")
        # The held confirmation now lands: the coordinator must converge
        # replica 0 back instead of leaving it on vNEW.
        for msg in held:
            router.inbox.put((0, msg))
        _drive(router, up, lambda: up.state in ("failed", "rolled_back"))
    finally:
        install(None)
    assert links[0].cur == "vOLD", (
        "late confirmation left the replica on the new weights"
    )
    assert all(l.cur == "vOLD" for l in links)
    assert up.state == "failed", up.state
    # And a surrendered rollout never resumes from its stale queue.
    for _ in range(5):
        router.pump(timeout=0)
    assert up.state == "failed"
    assert all(l.cur == "vOLD" for l in links)


def test_canary_every_defaults_to_fleet_size():
    """canary_every=0 means 1/fleet-size — the LIVE fleet, not the
    not-yet-converged roster (a respawn that already converged still
    counts toward the canary's fair share)."""
    up = UpgradeCoordinator(verify=lambda p: (p, "vNEW"))
    router, links = _fake_fleet(3, upgrader=up)
    links[2].wv = links[2].cur = "vNEW"  # already converged
    assert router.start_upgrade("/ckpt")["ok"]
    assert up._canary_every == 3, up._canary_every


def test_router_without_coordinator_refuses_upgrade():
    up = None
    links = [_FakeReplica(0, "f0")]
    router = Router(links, encode=None)
    links[0].router = router
    status = router.start_upgrade("/ckpt")
    assert status["ok"] is False and status["code"] == "upgrade"


# --------------------------------------------------------------------------
# the acceptance soak: a real subprocess fleet, rolling swap under live
# traffic, then a post-upgrade SIGKILL heal at the target version


def test_rolling_upgrade_subprocess_soak(lm, lm_new, spec_file, ckpts):
    """The ISSUE acceptance drill: 2 replica processes serving a live
    stream while a verified rolling swap walks the fleet (quiesce ->
    double-buffered swap -> canary -> promote). Every request answers
    exactly once, every answer is tagged with its admission-time
    weight_version, the mixed-version fleet stays byte-consistent per
    tag, and a post-rollout SIGKILL heals at the TARGET version."""
    old_dir, new_dir = ckpts
    old_version = verify_checkpoint(old_dir)[1]
    new_version = verify_checkpoint(new_dir)[1]
    params, cfg, tok = lm
    reqs = [{"prompt": PROMPT, "max_new": 6}] * 14
    want_old = _reference(lm, reqs[:1])[0]["continuation"]
    want_new = _reference(lm_new, reqs[:1])[0]["continuation"]
    assert want_old != want_new

    worker = [
        "--model_spec", spec_file, "--init_ckpt", old_dir,
        "--serve_slots", "2", "--heartbeat_ms", "50",
    ]
    links = [ReplicaProcess.spawn(i, list(worker)) for i in range(2)]

    def spawn(index, name, role, weight_target=None):
        argv = list(worker)
        if weight_target is not None:
            # Replace the bootstrap checkpoint with the fleet's target.
            argv[argv.index("--init_ckpt") + 1] = weight_target[0]
            argv += ["--weight_version", weight_target[1]]
        return ReplicaProcess.spawn(index, argv, role=role, name=name)

    sup = Supervisor(spawn, backoff_ms=50.0)
    up = UpgradeCoordinator(canary_window_s=0.3, canary_min_requests=1)
    buf = io.StringIO()
    telemetry = Telemetry(events=EventLog(buf))
    router = Router(
        links, encode=tok.encode, bos_id=tok.bos_id, affinity_block=4,
        heartbeat_timeout_s=10.0, telemetry=telemetry,
        supervisor=sup, upgrader=up,
    )
    for link in links:
        link.start_reader(router.inbox)

    answered = []
    deadline = time.time() + 110
    try:
        # LIVE traffic in two phases: the first 8 requests flow before
        # (and straddle into) the rollout — all admitted on the old
        # weights; the remaining 6 are held until the canary is serving,
        # so the mixed-version window genuinely carries traffic.
        next_req = 0
        started = False
        while (
            len(answered) < len(reqs) or (started and up.active)
        ) and time.time() < deadline:
            feed_cap = 8 if up.state in ("idle", "quiesce", "swap") else (
                len(reqs)
            )
            while next_req < min(feed_cap, len(reqs)) and router.backlog < 3:
                router.submit(dict(reqs[next_req]))
                next_req += 1
            router.pump()
            answered.extend(router.drain_ready())
            if not started and len(answered) >= 2:
                status = router.start_upgrade(new_dir)
                assert status["ok"], status
                assert status["version"] == new_version
                started = True
        assert up.state == "done", (up.state, up.stats)
        assert len(answered) == len(reqs)
        # Byte-consistency per weight_version tag, zero errors.
        by_version = {}
        for a in answered:
            assert "continuation" in a, f"request errored: {a}"
            by_version.setdefault(a["weight_version"], set()).add(
                a["continuation"]
            )
        assert set(by_version) == {old_version, new_version}, (
            f"expected a mixed-version stream, got {sorted(by_version)}"
        )
        assert by_version[old_version] == {want_old}
        assert by_version[new_version] == {want_new}
        assert router.weight_target == (new_dir, new_version)
        assert all(l.wv == new_version for l in router.links)

        # ---- post-upgrade SIGKILL: the respawn-at-target regression ----
        # Kill the AFFINE owner of the test prompt (most answers) so the
        # replacement — same name, same rendezvous keys — takes traffic.
        victim = max(router.links, key=lambda l: l.answered)
        os.kill(victim.pid(), signal.SIGKILL)
        while time.time() < deadline:
            router.pump()
            if (
                sup.stats["respawns"] == 1
                and len(router.healthy_links) == 2
            ):
                break
        assert sup.stats["respawns"] == 1, sup.stats
        replacement = router.links[victim.index]
        assert replacement is not victim
        assert replacement.wv == new_version, (
            "the replacement resurrected stale weights "
            f"(wv={replacement.wv!r})"
        )
        # The replacement answers byte-identically to upgraded survivors.
        out2 = router.run([dict(r) for r in reqs[:4]])
        assert [o.get("continuation") for o in out2] == [want_new] * 4
        assert all(o["weight_version"] == new_version for o in out2)
        assert replacement.answered > 0, "replacement took no traffic"
    finally:
        router.shutdown()
        telemetry.maybe_flush(force=True)

    events = _events(buf)
    phases = [
        (e.get("phase"), e.get("replica"))
        for e in events if e.get("kind") == "route.upgrade"
    ]
    assert ("started", None) in phases
    assert sum(1 for p, _ in phases if p == "swapped") == 2
    assert any(p == "completed" for p, _ in phases)
    canary = [e for e in events if e.get("kind") == "route.canary"]
    assert [e["phase"] for e in canary] == ["started", "promoted"]
    completed = [
        e for e in events
        if e.get("kind") == "route.upgrade" and e.get("phase") == "completed"
    ]
    assert completed[0]["time_to_upgrade_s"] > 0
    # The merged report renders the upgrade section from the same stream.
    from transformer_tpu.obs.__main__ import render_text, summarize_events

    report = summarize_events(events)
    upgrade = report["upgrade"]
    assert upgrade["completed"] == 1
    assert upgrade["rollbacks"] == 0
    assert upgrade["version"] == new_version
    assert upgrade["canary"]["promoted"] is True
    share = upgrade["per_version_requests"]
    assert old_version in share and new_version in share
    assert "upgrade:" in render_text(report)


# --------------------------------------------------------------------------
# obs + analysis surfaces


def test_summarize_upgrade_section_shapes():
    from transformer_tpu.obs.__main__ import render_text, summarize_events

    events = [
        {"kind": "route.upgrade", "phase": "started", "version": "v2",
         "ckpt": "/c", "replicas": ["r0", "r1"], "ts": 1.0},
        {"kind": "route.dispatch", "order": 0, "replica": "r0",
         "weight_version": "v1", "redispatch": 0, "ts": 1.1},
        {"kind": "route.canary", "phase": "started", "replica": "r0",
         "version": "v2", "every": 2, "window_s": 5.0, "ts": 1.2},
        {"kind": "route.dispatch", "order": 1, "replica": "r0",
         "weight_version": "v2", "redispatch": 0, "ts": 1.3},
        {"kind": "route.upgrade", "phase": "rolled_back",
         "rolled_back": True, "version": "v2",
         "reason": "canary burn > 1 sustained on availability",
         "evidence": {"availability": {"5s": 40.0}}, "ts": 2.0},
    ]
    up = summarize_events(events)["upgrade"]
    assert up["started"] == 1 and up["rollbacks"] == 1
    assert up["rollback"]["evidence"]
    assert up["canary"]["promoted"] is False
    assert up["per_version_requests"]["v1"]["requests"] == 1
    assert up["per_version_requests"]["v2"]["share"] == 0.5
    text = render_text(summarize_events(events))
    assert "upgrade:" in text and "rolled back" in text
    assert "version v1" in text and "version v2" in text


@pytest.mark.slow
def test_upgrade_retrace_zero_recompiles():
    """0 steady-state recompiles across quiesce/swap/rollback — the same
    scenario the `analysis retrace` CLI (and the tier-1 analysis-all
    gate) runs."""
    from transformer_tpu.analysis.retrace import upgrade_retrace_report

    deltas = upgrade_retrace_report(steps=2)
    assert deltas and all(d.within_budget for d in deltas), [
        d.to_dict() for d in deltas
    ]
