"""Layer/stack/assembly tests (L2/L3): residual wiring, variants, KV-cache
decode equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig
from transformer_tpu.models import (
    decoder_apply,
    decoder_init,
    encoder_apply,
    encoder_init,
    transformer_apply,
    transformer_init,
)
from transformer_tpu.models.decoder import init_decoder_caches
from transformer_tpu.models.transformer import transformer_decode_step
from transformer_tpu.ops.masks import make_padding_mask, make_seq2seq_masks

TINY = ModelConfig(
    num_layers=2,
    d_model=16,
    num_heads=2,
    dff=32,
    input_vocab_size=40,
    target_vocab_size=48,
    max_position=64,
    dropout_rate=0.1,
    dtype="float32",
)


def tokens(key, vocab, shape):
    return jax.random.randint(key, shape, 1, vocab)


class TestEncoder:
    def test_output_shape_and_dtype(self):
        params = encoder_init(jax.random.PRNGKey(0), TINY)
        ids = tokens(jax.random.PRNGKey(1), 40, (3, 7))
        out, attn = encoder_apply(params, ids, make_padding_mask(ids), TINY)
        assert out.shape == (3, 7, 16)
        assert attn == {}

    def test_attention_weights_collected(self):
        params = encoder_init(jax.random.PRNGKey(0), TINY)
        ids = tokens(jax.random.PRNGKey(1), 40, (2, 5))
        _, attn = encoder_apply(
            params, ids, make_padding_mask(ids), TINY, return_weights=True
        )
        assert set(attn) == {"encoder_layer1", "encoder_layer2"}
        assert attn["encoder_layer1"].shape == (2, 2, 5, 5)

    def test_dropout_changes_output_only_in_training(self):
        params = encoder_init(jax.random.PRNGKey(0), TINY)
        ids = tokens(jax.random.PRNGKey(1), 40, (2, 5))
        det, _ = encoder_apply(params, ids, None, TINY, deterministic=True)
        det2, _ = encoder_apply(params, ids, None, TINY, deterministic=True)
        np.testing.assert_array_equal(np.asarray(det), np.asarray(det2))
        tr, _ = encoder_apply(
            params, ids, None, TINY, rng=jax.random.PRNGKey(2), deterministic=False
        )
        assert not np.allclose(np.asarray(det), np.asarray(tr))

    def test_padding_position_does_not_affect_others(self):
        """Changing a padded token's embedding input must not change non-pad
        outputs (mask correctness end-to-end)."""
        params = encoder_init(jax.random.PRNGKey(0), TINY)
        ids1 = jnp.array([[5, 6, 7, 0, 0]])
        ids2 = jnp.array([[5, 6, 7, 0, 0]])
        mask = make_padding_mask(ids1)
        out1, _ = encoder_apply(params, ids1, mask, TINY)
        out2, _ = encoder_apply(params, ids2, mask, TINY)
        np.testing.assert_allclose(
            np.asarray(out1[:, :3]), np.asarray(out2[:, :3]), atol=1e-6
        )


class TestDecoder:
    def test_attention_dict_keys_parity(self):
        """Keys follow the reference's decoder_layer{i}_block{1,2} scheme
        (Decoder.py:75-76)."""
        dec = decoder_init(jax.random.PRNGKey(0), TINY)
        enc = encoder_init(jax.random.PRNGKey(1), TINY)
        inp = tokens(jax.random.PRNGKey(2), 40, (2, 6))
        tar = tokens(jax.random.PRNGKey(3), 48, (2, 4))
        enc_mask, combined, cross = make_seq2seq_masks(inp, tar)
        enc_out, _ = encoder_apply(enc, inp, enc_mask, TINY)
        out, attn, _ = decoder_apply(
            dec, tar, enc_out, combined, cross, TINY, return_weights=True
        )
        assert out.shape == (2, 4, 16)
        assert set(attn) == {
            "decoder_layer1_block1",
            "decoder_layer1_block2",
            "decoder_layer2_block1",
            "decoder_layer2_block2",
        }
        assert attn["decoder_layer1_block1"].shape == (2, 2, 4, 4)
        assert attn["decoder_layer1_block2"].shape == (2, 2, 4, 6)

    def test_causality(self):
        """Changing target token t must not change decoder outputs before t."""
        cfg = TINY
        dec = decoder_init(jax.random.PRNGKey(0), cfg)
        enc_out = jax.random.normal(jax.random.PRNGKey(1), (1, 6, 16))
        tar1 = jnp.array([[3, 4, 5, 6]])
        tar2 = jnp.array([[3, 4, 5, 9]])
        _, combined1, _ = make_seq2seq_masks(jnp.ones((1, 6), jnp.int32), tar1)
        _, combined2, _ = make_seq2seq_masks(jnp.ones((1, 6), jnp.int32), tar2)
        out1, _, _ = decoder_apply(dec, tar1, enc_out, combined1, None, cfg)
        out2, _, _ = decoder_apply(dec, tar2, enc_out, combined2, None, cfg)
        np.testing.assert_allclose(
            np.asarray(out1[:, :3]), np.asarray(out2[:, :3]), atol=1e-6
        )


class TestTransformer:
    def test_logits_shape(self):
        params = transformer_init(jax.random.PRNGKey(0), TINY)
        inp = tokens(jax.random.PRNGKey(1), 40, (2, 7))
        tar = tokens(jax.random.PRNGKey(2), 48, (2, 5))
        logits, attn = transformer_apply(params, inp, tar, TINY)
        assert logits.shape == (2, 5, 48)
        assert attn == {}

    def test_jit_compiles_once_for_static_shapes(self):
        params = transformer_init(jax.random.PRNGKey(0), TINY)
        fwd = jax.jit(lambda p, i, t: transformer_apply(p, i, t, TINY)[0])
        inp = tokens(jax.random.PRNGKey(1), 40, (2, 7))
        tar = tokens(jax.random.PRNGKey(2), 48, (2, 5))
        l1 = fwd(params, inp, tar)
        l2 = fwd(params, inp, tar)
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))

    @pytest.mark.slow
    def test_remat_matches_plain(self):
        """cfg.remat must change memory behavior only: forward logits and
        gradients identical to the non-remat model."""
        import dataclasses

        from transformer_tpu.config import TrainConfig
        from transformer_tpu.train import create_train_state, make_train_step

        cfg_plain = dataclasses.replace(TINY, dropout_rate=0.0)
        cfg_remat = dataclasses.replace(cfg_plain, remat=True)
        tcfg = TrainConfig(batch_size=2, sequence_length=8, warmup_steps=10)
        inp = tokens(jax.random.PRNGKey(1), 40, (2, 7))
        tar = tokens(jax.random.PRNGKey(2), 48, (2, 7))

        l_plain, _ = transformer_apply(transformer_init(jax.random.PRNGKey(0), cfg_plain), inp, tar, cfg_plain)
        l_remat, _ = transformer_apply(transformer_init(jax.random.PRNGKey(0), cfg_remat), inp, tar, cfg_remat)
        np.testing.assert_allclose(np.asarray(l_plain), np.asarray(l_remat), atol=1e-6)

        rng = jax.random.PRNGKey(3)
        s_plain = create_train_state(jax.random.PRNGKey(0), cfg_plain, tcfg)
        s_remat = create_train_state(jax.random.PRNGKey(0), cfg_remat, tcfg)
        _, m_plain = jax.jit(make_train_step(cfg_plain, tcfg))(s_plain, inp, tar, rng)
        _, m_remat = jax.jit(make_train_step(cfg_remat, tcfg))(s_remat, inp, tar, rng)
        np.testing.assert_allclose(
            float(m_plain["loss"]), float(m_remat["loss"]), rtol=1e-6
        )

    @pytest.mark.slow  # heavyweight: slow tier (fast tier keeps a specimen)
    def test_remat_dots_policy_matches_full(self):
        """remat_policy='dots' (save matmul outputs, recompute elementwise)
        must produce the same step numerics as the full-recompute policy —
        the policy is a memory/FLOPs dial, never a math change."""
        import dataclasses

        from transformer_tpu.config import TrainConfig
        from transformer_tpu.train import create_train_state, make_train_step

        cfg_full = dataclasses.replace(TINY, dropout_rate=0.0, remat=True)
        cfg_dots = dataclasses.replace(cfg_full, remat_policy="dots")
        tcfg = TrainConfig(batch_size=2, sequence_length=8, warmup_steps=10)
        inp = tokens(jax.random.PRNGKey(1), 40, (2, 7))
        tar = tokens(jax.random.PRNGKey(2), 48, (2, 7))
        rng = jax.random.PRNGKey(3)

        s_full = create_train_state(jax.random.PRNGKey(0), cfg_full, tcfg)
        s_dots = create_train_state(jax.random.PRNGKey(0), cfg_dots, tcfg)
        s_full, m_full = jax.jit(make_train_step(cfg_full, tcfg))(s_full, inp, tar, rng)
        s_dots, m_dots = jax.jit(make_train_step(cfg_dots, tcfg))(s_dots, inp, tar, rng)
        np.testing.assert_allclose(
            float(m_full["loss"]), float(m_dots["loss"]), rtol=1e-6
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6
            ),
            s_full.params, s_dots.params,
        )

        with pytest.raises(ValueError, match="remat_policy"):
            dataclasses.replace(TINY, remat_policy="bogus")

    def test_tied_embeddings_share_table(self):
        cfg = ModelConfig(
            num_layers=1, d_model=16, num_heads=2, dff=32,
            input_vocab_size=40, target_vocab_size=40, max_position=64,
            tie_embeddings=True, tie_output=True, dtype="float32",
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        assert params["encoder"]["embedding"]["table"] is params["decoder"]["embedding"]["table"]
        assert "final" not in params
        inp = tokens(jax.random.PRNGKey(1), 40, (2, 5))
        logits, _ = transformer_apply(params, inp, inp, cfg)
        assert logits.shape == (2, 5, 40)

    def test_tied_embeddings_requires_equal_vocab(self):
        cfg = ModelConfig(
            num_layers=1, d_model=16, num_heads=2, dff=32,
            input_vocab_size=40, target_vocab_size=48, max_position=64,
            tie_embeddings=True,
        )
        with pytest.raises(ValueError):
            transformer_init(jax.random.PRNGKey(0), cfg)

    def test_decoder_only_variant(self):
        cfg = ModelConfig(
            num_layers=2, d_model=16, num_heads=2, dff=32,
            input_vocab_size=48, target_vocab_size=48, max_position=64,
            decoder_only=True, tie_output=True, dtype="float32",
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        assert "encoder" not in params
        assert "cross_mha" not in params["decoder"]["layers"][0]
        toks = tokens(jax.random.PRNGKey(1), 48, (2, 10))
        logits, _ = transformer_apply(params, None, toks, cfg)
        assert logits.shape == (2, 10, 48)

    def test_decoder_only_is_causal_with_padding_mask(self):
        """Regression: causality must hold even when a padding mask is passed
        (the padding mask must be ANDed with causal, not replace it)."""
        cfg = ModelConfig(
            num_layers=2, d_model=16, num_heads=2, dff=32,
            input_vocab_size=48, target_vocab_size=48, max_position=64,
            decoder_only=True, tie_output=True, dtype="float32", dropout_rate=0.0,
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        t1 = jnp.array([[3, 4, 5, 6, 0, 0]])  # padded row: mask is non-trivial
        t2 = jnp.array([[3, 4, 5, 9, 0, 0]])  # token 3 changed
        l1, _ = transformer_apply(params, None, t1, cfg)
        l2, _ = transformer_apply(params, None, t2, cfg)
        np.testing.assert_allclose(
            np.asarray(l1[:, :3]), np.asarray(l2[:, :3]), atol=1e-6
        )

    @pytest.mark.slow
    def test_gradients_flow_everywhere(self):
        params = transformer_init(jax.random.PRNGKey(0), TINY)
        inp = tokens(jax.random.PRNGKey(1), 40, (2, 5))
        tar = tokens(jax.random.PRNGKey(2), 48, (2, 4))

        def loss(p):
            logits, _ = transformer_apply(p, inp, tar, TINY)
            return jnp.mean(logits**2)

        grads = jax.grad(loss)(params)
        flat, _ = jax.tree_util.tree_flatten(grads)
        assert all(bool(jnp.any(g != 0)) for g in flat)


class TestKVCacheDecode:
    def test_cached_decode_matches_full_forward(self):
        """Each cached step's logits must equal the corresponding position of a
        full (non-cached) forward pass — the correctness contract that lets us
        replace the reference's O(S²) re-decode (train.py:109-118)."""
        params = transformer_init(jax.random.PRNGKey(0), TINY)
        inp = tokens(jax.random.PRNGKey(1), 40, (2, 6))
        tar = tokens(jax.random.PRNGKey(2), 48, (2, 5))
        full_logits, _ = transformer_apply(params, inp, tar, TINY)

        from transformer_tpu.models.encoder import encoder_apply as enc_apply

        enc_mask = make_padding_mask(inp)
        enc_out, _ = enc_apply(params["encoder"], inp, enc_mask, TINY)
        caches = init_decoder_caches(TINY, 2, 8)
        # compute_dtype is fp32 in TINY, so caches already match.
        for t in range(5):
            step_logits, caches = transformer_decode_step(
                params, tar[:, t : t + 1], enc_out, enc_mask, caches,
                jnp.array(t, jnp.int32), TINY,
            )
            np.testing.assert_allclose(
                np.asarray(step_logits), np.asarray(full_logits[:, t, :]), atol=2e-4
            )

    def test_windowed_cache_decode_matches_full_forward(self):
        """attention_window: the cached decode's banded prefix mask must
        reproduce the banded training mask — per-position logits equal the
        full (non-cached) windowed forward."""
        import dataclasses

        cfg_w = dataclasses.replace(
            TINY, decoder_only=True, attention_window=3
        )
        params = transformer_init(jax.random.PRNGKey(0), cfg_w)
        tar = tokens(jax.random.PRNGKey(2), 48, (2, 8))
        full_logits, _ = transformer_apply(params, None, tar, cfg_w)

        caches = init_decoder_caches(cfg_w, 2, 9)
        # The cache is a ROLLING buffer: window slots, not max_len.
        assert caches[0]["k"].shape[1] == 3
        for t in range(8):
            step_logits, caches = transformer_decode_step(
                params, tar[:, t : t + 1], None, None, caches,
                jnp.array(t, jnp.int32), cfg_w,
            )
            np.testing.assert_allclose(
                np.asarray(step_logits), np.asarray(full_logits[:, t, :]),
                atol=2e-4, err_msg=f"t={t}",
            )
        # The window must actually bite: a full-attention model differs.
        full_cfg = dataclasses.replace(cfg_w, attention_window=0)
        unwindowed, _ = transformer_apply(params, None, tar, full_cfg)
        assert not np.allclose(
            np.asarray(full_logits[:, -1]), np.asarray(unwindowed[:, -1]),
            atol=1e-5,
        )

    def test_rolling_window_composes_with_int8_cache(self):
        """window × kv_cache_int8: the rolling int8 buffer must track the
        full-precision full-cache windowed oracle within quantization
        tolerance."""
        import dataclasses

        cfg_w = dataclasses.replace(TINY, decoder_only=True, attention_window=3)
        cfg_wq = dataclasses.replace(cfg_w, kv_cache_int8=True)
        params = transformer_init(jax.random.PRNGKey(0), cfg_w)
        tar = tokens(jax.random.PRNGKey(2), 48, (2, 8))

        caches = init_decoder_caches(cfg_w, 2, 9)
        caches_q = init_decoder_caches(cfg_wq, 2, 9)
        assert caches_q[0]["k"].shape[1] == 3
        assert caches_q[0]["k"].dtype == jnp.int8
        for t in range(8):
            fp_logits, caches = transformer_decode_step(
                params, tar[:, t : t + 1], None, None, caches,
                jnp.array(t, jnp.int32), cfg_w,
            )
            q_logits, caches_q = transformer_decode_step(
                params, tar[:, t : t + 1], None, None, caches_q,
                jnp.array(t, jnp.int32), cfg_wq,
            )
            err = float(jnp.max(jnp.abs(fp_logits - q_logits)))
            spread = float(jnp.max(fp_logits) - jnp.min(fp_logits))
            assert err < 0.05 * spread, (t, err, spread)

    def test_window_negative_rejected(self):
        import dataclasses

        with pytest.raises(ValueError, match="attention_window"):
            dataclasses.replace(TINY, attention_window=-1)

    def test_int8_cache_decode_close_to_fp(self):
        """kv_cache_int8: cached decode through the int8 cache must track the
        fp cache's logits within quantization tolerance, and the cache
        buffers must actually be int8."""
        import dataclasses

        cfg_q = dataclasses.replace(TINY, kv_cache_int8=True)
        params = transformer_init(jax.random.PRNGKey(0), TINY)
        inp = tokens(jax.random.PRNGKey(1), 40, (2, 6))
        tar = tokens(jax.random.PRNGKey(2), 48, (2, 5))

        from transformer_tpu.models.encoder import encoder_apply as enc_apply

        enc_mask = make_padding_mask(inp)
        enc_out, _ = enc_apply(params["encoder"], inp, enc_mask, TINY)
        caches_fp = init_decoder_caches(TINY, 2, 8)
        caches_q = init_decoder_caches(cfg_q, 2, 8)
        assert caches_q[0]["k"].dtype == jnp.int8
        assert caches_q[0]["k_scale"].dtype == jnp.float32
        # int8 k/v + fp32 per-row scales must undercut the fp32 cache.
        nbytes = lambda c: sum(  # noqa: E731
            v.nbytes for v in c.values() if hasattr(v, "nbytes")
        )
        assert nbytes(caches_q[0]) < 0.5 * nbytes(caches_fp[0])

        for t in range(5):
            fp_logits, caches_fp = transformer_decode_step(
                params, tar[:, t : t + 1], enc_out, enc_mask, caches_fp,
                jnp.array(t, jnp.int32), TINY,
            )
            q_logits, caches_q = transformer_decode_step(
                params, tar[:, t : t + 1], enc_out, enc_mask, caches_q,
                jnp.array(t, jnp.int32), cfg_q,
            )
            err = float(jnp.max(jnp.abs(fp_logits - q_logits)))
            spread = float(jnp.max(fp_logits) - jnp.min(fp_logits))
            assert err < 0.05 * spread, (t, err, spread)

    def test_int8_cache_greedy_decode_runs(self):
        """End-to-end greedy decode with the int8 cache (the serving path
        behind --kv_cache_int8)."""
        import dataclasses

        from transformer_tpu.train.decode import greedy_decode

        cfg_q = dataclasses.replace(TINY, kv_cache_int8=True)
        params = transformer_init(jax.random.PRNGKey(0), TINY)
        inp = tokens(jax.random.PRNGKey(1), 40, (2, 6))
        out = greedy_decode(
            params, inp, cfg_q, max_len=6, bos_id=1, eos_id=2
        )
        assert out.shape[0] == 2 and out.shape[1] <= 7
