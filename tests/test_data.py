"""Data pipeline tests: tokenizer round-trips, BPE training, dataset batching,
sharding, and parity conventions (BOS/EOS/pad framing)."""

import numpy as np
import pytest

from transformer_tpu.data import (
    Seq2SeqDataset,
    SubwordTokenizer,
    load_dataset,
    read_parallel_corpus,
)

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the quick brown cat sleeps",
    "a lazy dog sleeps all day",
    "the fox and the dog are friends",
    "quick brown foxes jump over lazy dogs",
] * 4


class TestTokenizer:
    def test_roundtrip(self):
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=400)
        for line in CORPUS[:5]:
            assert tok.decode(tok.encode(line)) == line

    def test_roundtrip_unseen_text_via_byte_fallback(self):
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=400)
        text = "zebra Ω 真 underscore_word"
        assert tok.decode(tok.encode(text)) == text

    def test_literal_byte_token_text_roundtrips(self):
        """Regression: literal '<0xNN>' in input text must not be confused
        with the byte-fallback token namespace."""
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=400)
        text = "see <0x41> here < and 0x41 >"
        assert tok.decode(tok.encode(text)) == text

    def test_ids_positive_and_below_vocab_size(self):
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=400)
        ids = tok.encode("the quick fox")
        assert all(1 <= i < tok.vocab_size for i in ids)

    def test_specials_convention(self):
        """BOS=vocab_size, EOS=vocab_size+1, model rows = vocab_size+2 —
        the reference convention (utils.py:137-143, train.py:232-233)."""
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=300)
        assert tok.bos_id == tok.vocab_size
        assert tok.eos_id == tok.vocab_size + 1
        assert tok.model_vocab_size == tok.vocab_size + 2

    def test_bpe_actually_merges(self):
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=500)
        # 'the_' appears 16+ times; BPE should have merged it into one piece.
        ids = tok.encode("the")
        assert len(ids) == 1

    def test_save_load_identical(self, tmp_path):
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=400)
        path = str(tmp_path / "vocab.subwords")
        tok.save(path)
        tok2 = SubwordTokenizer.load(path)
        assert tok2.subwords == tok.subwords
        text = "the quick brown fox"
        assert tok2.encode(text) == tok.encode(text)

    def test_load_rejects_garbage(self, tmp_path):
        p = tmp_path / "bad.subwords"
        p.write_text("not a vocab\nfoo\n")
        with pytest.raises(ValueError):
            SubwordTokenizer.load(str(p))


class TestDataset:
    def _mk(self, n=20, batch=4, **kw):
        src = [np.arange(1, 1 + (i % 5) + 2, dtype=np.int32) for i in range(n)]
        tgt = [np.arange(1, 1 + (i % 7) + 2, dtype=np.int32) for i in range(n)]
        return Seq2SeqDataset(src, tgt, batch_size=batch, src_len=10, tgt_len=12, **kw)

    def test_static_shapes_and_padding(self):
        ds = self._mk()
        for src, tgt in ds.batches(0):
            assert src.shape == (4, 10) and tgt.shape == (4, 12)
            assert src.dtype == np.int32
        # padding is 0 beyond each row's length
        src, tgt = next(ds.batches(0))
        row_lens = (src != 0).sum(1)
        for r, L in enumerate(row_lens):
            assert (src[r, L:] == 0).all()

    def test_shuffle_deterministic_per_epoch(self):
        ds = self._mk()
        a = [s.copy() for s, _ in ds.batches(3)]
        b = [s.copy() for s, _ in ds.batches(3)]
        c = [s.copy() for s, _ in ds.batches(4)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        assert any(not np.array_equal(x, y) for x, y in zip(a, c))

    def test_sharding_partitions_batch(self):
        """Two shards of the same global batch must tile the unsharded batch."""
        full = self._mk(shard_index=0, shard_count=1)
        s0 = self._mk(shard_index=0, shard_count=2)
        s1 = self._mk(shard_index=1, shard_count=2)
        f = next(full.batches(1))[0]
        a = next(s0.batches(1))[0]
        b = next(s1.batches(1))[0]
        np.testing.assert_array_equal(np.concatenate([a, b], 0), f)

    def test_batch_divisibility_enforced(self):
        with pytest.raises(ValueError):
            self._mk(batch=4, shard_count=3)

    def test_drop_remainder(self):
        ds = self._mk(n=10, batch=4)
        assert len(list(ds.batches(0))) == 2  # 10//4, remainder dropped

    def test_partial_tail_batch_same_count_on_all_shards(self):
        """Regression: with drop_remainder=False every shard must yield the
        same number of (static-shape) batches — a short tail is padded with
        empty rows, never skipped on one host (multi-host SPMD would hang)."""
        kw = dict(shuffle=False, drop_remainder=False)
        s0 = self._mk(n=10, batch=4, shard_index=0, shard_count=2, **kw)
        s1 = self._mk(n=10, batch=4, shard_index=1, shard_count=2, **kw)
        b0 = list(s0.batches(0))
        b1 = list(s1.batches(0))
        assert len(b0) == len(b1) == 3
        for (sa, _), (sb, _) in zip(b0, b1):
            assert sa.shape == sb.shape == (2, 10)
        # last batch of shard 1 is entirely padding rows (weight 0)
        assert (b1[-1][0] == 0).all()
        # all real examples appear exactly once across shards
        total_rows = np.concatenate([s for s, _ in b0] + [s for s, _ in b1])
        assert (total_rows != 0).any(axis=1).sum() == 10


class TestLengthBuckets:
    def _mk(self, n=40, batch=4, **kw):
        # max(len(src), len(tgt)) in [3, 10): lands in both buckets of (6, 10)
        src = [np.arange(1, 3 + (i % 5), dtype=np.int32) for i in range(n)]
        tgt = [np.arange(1, 3 + (i % 8), dtype=np.int32) for i in range(n)]
        return Seq2SeqDataset(
            src, tgt, batch_size=batch, src_len=10, tgt_len=10,
            length_buckets=(6, 10), **kw,
        )

    def test_batch_widths_match_buckets_and_cover_all(self):
        ds = self._mk()
        widths = set()
        n_rows = 0
        for src, tgt in ds.batches(0):
            assert src.shape == tgt.shape
            assert src.shape[1] in (6, 10)
            widths.add(src.shape[1])
            # every row fits its bucket (no mid-sentence truncation)
            n_rows += (src != 0).any(axis=1).sum()
        assert widths == {6, 10}  # both buckets actually used
        assert len(list(ds.batches(0))) == len(ds)

    def test_examples_land_in_smallest_fitting_bucket(self):
        ds = self._mk(shuffle=False)
        for src, tgt in ds.batches(0):
            if src.shape[1] == 10:
                # at least one row needs > 6: otherwise it belongs in bucket 6
                longest = np.maximum(
                    (src != 0).sum(axis=1), (tgt != 0).sum(axis=1)
                )
                assert longest.max() > 6

    def test_deterministic_and_epoch_varying(self):
        ds = self._mk()
        a = [(s.copy(), s.shape) for s, _ in ds.batches(2)]
        b = [(s.copy(), s.shape) for s, _ in ds.batches(2)]
        for (x, shx), (y, shy) in zip(a, b):
            assert shx == shy
            np.testing.assert_array_equal(x, y)

    def test_sharding_partitions_bucketed_batch(self):
        full = self._mk(shard_index=0, shard_count=1)
        s0 = self._mk(shard_index=0, shard_count=2)
        s1 = self._mk(shard_index=1, shard_count=2)
        for (f, _), (a, _), (b, _) in zip(
            full.batches(1), s0.batches(1), s1.batches(1)
        ):
            np.testing.assert_array_equal(np.concatenate([a, b], 0), f)

    def test_tail_handling_no_drop(self):
        ds = self._mk(n=10, batch=4, shuffle=False, drop_remainder=False)
        rows = 0
        for src, _ in ds.batches(0):
            rows += (src != 0).any(axis=1).sum()
        assert rows == 10  # every example appears despite bucketed tails

    def test_prefetch_composes(self):
        """Buckets × prefetch now routes through the native loader (or the
        Python bucketed path when native is unavailable) — every example
        still appears exactly once, at a bucket width."""
        ds = self._mk(n=10, batch=4, drop_remainder=False, prefetch=True)
        rows = 0
        for src, tgt in ds.batches(0):
            assert src.shape[1] == tgt.shape[1]
            rows += (src != 0).any(axis=1).sum()
        assert rows == 10

    def test_prefetch_fallback_bit_identical_order(self):
        """Without the native loader, prefetch=True falls back to a Python
        background-thread double-buffer (jax.device_put one batch ahead)
        with a warning — never a hard error — and the batch stream is
        bit-identical to the prefetch=False Python path, flat AND bucketed,
        single-host AND sharded (formerly a multi-host RuntimeError)."""
        import warnings

        for kw in (
            dict(),
            dict(shuffle=False),
            dict(shard_index=1, shard_count=2),
        ):
            plain = self._mk(n=10, batch=4, drop_remainder=False, **kw)
            pre = self._mk(
                n=10, batch=4, drop_remainder=False, prefetch=True, **kw
            )
            pre._native = False  # force "native loader unavailable"
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                got = list(pre.batches(2))
            assert any(
                "double-buffer" in str(w.message) for w in caught
            ), [str(w.message) for w in caught]
            want = list(plain.batches(2))
            assert len(got) == len(want) > 0
            for (a, b), (c, d) in zip(got, want):
                np.testing.assert_array_equal(np.asarray(a), c)
                np.testing.assert_array_equal(np.asarray(b), d)

    def test_prefetch_fallback_early_break_does_not_hang(self):
        """Abandoning the fallback iterator mid-epoch must not deadlock on
        the bounded queue (the worker notices and exits)."""
        import warnings

        pre = self._mk(n=16, batch=4, prefetch=True)
        pre._native = False
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for i, _ in enumerate(pre.batches(0)):
                if i == 1:
                    break  # worker must not block forever on q.put

    def test_prefetch_fallback_joins_worker_on_early_exit(self):
        """Closing the fallback iterator mid-epoch must JOIN the producer
        thread (draining its in-flight device_put), not merely signal it:
        a daemon thread outliving the iterator pins device buffers for the
        rest of the process."""
        import threading

        from transformer_tpu.data.pipeline import _threaded_device_prefetch

        src = [
            (np.full((2,), i, np.int32), np.full((2,), i, np.int32))
            for i in range(8)
        ]
        gen = _threaded_device_prefetch(iter(src), depth=1)
        first = next(gen)
        np.testing.assert_array_equal(np.asarray(first[0]), src[0][0])
        gen.close()  # early exit: break/exception/abandonment all end here
        assert not any(
            t.name == "pipeline-prefetch" and t.is_alive()
            for t in threading.enumerate()
        ), "producer thread outlived the closed iterator"

    def test_prefetch_fallback_joins_worker_on_consumer_exception(self):
        """The same join guarantee when the CONSUMER dies mid-stream (the
        exception unwinds through the generator's finally)."""
        import threading

        from transformer_tpu.data.pipeline import _threaded_device_prefetch

        src = [
            (np.full((2,), i, np.int32), np.full((2,), i, np.int32))
            for i in range(8)
        ]

        def consume():
            for i, _ in enumerate(_threaded_device_prefetch(iter(src), depth=1)):
                if i == 1:
                    raise RuntimeError("consumer died")

        with pytest.raises(RuntimeError, match="consumer died"):
            consume()
        # The traceback can keep the consumer frame (and so the generator)
        # alive past the raise; collect so the generator's finally has run.
        import gc

        gc.collect()
        assert not any(
            t.name == "pipeline-prefetch" and t.is_alive()
            for t in threading.enumerate()
        ), "producer thread survived the consumer's exception"

    def test_overlong_examples_rejected_not_clamped(self):
        """A largest bucket narrower than the data must fail loudly — silent
        clamping would truncate sentences (and their EOS) mid-stream."""
        src = [np.arange(1, 9, dtype=np.int32)]  # length 8 > largest bucket 6
        with pytest.raises(ValueError, match="exceed the largest"):
            Seq2SeqDataset(
                src, src, batch_size=1, src_len=10, tgt_len=10,
                length_buckets=(4, 6),
            )

    @pytest.mark.slow
    def test_trains_through_trainer(self):
        """End-to-end: a jitted train step accepts both bucket widths (one
        compile each, no errors from the changing static shape)."""
        import jax

        from transformer_tpu.config import ModelConfig, TrainConfig
        from transformer_tpu.train import create_train_state, make_train_step

        cfg = ModelConfig(
            num_layers=1, d_model=16, num_heads=2, dff=32,
            input_vocab_size=16, target_vocab_size=16, max_position=16,
            dtype="float32", dropout_rate=0.0,
        )
        tcfg = TrainConfig(batch_size=4, sequence_length=10, warmup_steps=5)
        state = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
        step = jax.jit(make_train_step(cfg, tcfg))
        rng = jax.random.PRNGKey(1)
        for src, tgt in self._mk().batches(0):
            state, m = step(state, src, tgt, rng)
            assert np.isfinite(float(m["loss"]))


class TestLMDataset:
    def _tok(self):
        from transformer_tpu.data.tokenizer import SubwordTokenizer

        return SubwordTokenizer.build_from_corpus(
            ["ab cd ef gh ij kl"] * 3, target_vocab_size=280
        )

    def test_windows_cover_stream_with_bos(self):
        from transformer_tpu.data.pipeline import make_lm_dataset

        tok = self._tok()
        lines = ["ab cd ef", "gh ij", "kl ab cd"] * 4
        ds = make_lm_dataset(lines, tok, batch_size=2, sequence_length=8)
        total = sum(len(tok.encode(l)) + 1 for l in lines)  # +1 per EOS join
        assert ds.num_examples == total // 7  # 7 stream tokens per window
        for src, tgt in ds.batches(0):
            assert src.shape == (2, 8) and tgt.shape == (2, 8)
            np.testing.assert_array_equal(src, tgt)  # LM: src mirrors tgt
            assert (src[:, 0] == tok.bos_id).all()  # BOS leads every window
            assert (src[:, 1:] != 0).all()  # stream windows are dense

    def test_too_short_corpus_raises(self):
        from transformer_tpu.data.pipeline import make_lm_dataset

        tok = self._tok()
        with pytest.raises(ValueError, match="window"):
            make_lm_dataset(["ab"], tok, batch_size=1, sequence_length=512)

    def test_trains_decoder_only(self):
        """The LM dataset drives a decoder-only train step end-to-end."""
        import jax

        from transformer_tpu.config import ModelConfig, TrainConfig
        from transformer_tpu.data.pipeline import make_lm_dataset
        from transformer_tpu.train import create_train_state, make_train_step

        tok = self._tok()
        ds = make_lm_dataset(
            ["ab cd ef gh ij kl"] * 10, tok, batch_size=2, sequence_length=8
        )
        cfg = ModelConfig(
            num_layers=1, d_model=16, num_heads=2, dff=32,
            input_vocab_size=tok.model_vocab_size,
            target_vocab_size=tok.model_vocab_size,
            max_position=16, dtype="float32", dropout_rate=0.0,
            decoder_only=True,
        )
        tcfg = TrainConfig(batch_size=2, sequence_length=8, warmup_steps=5)
        state = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
        step = jax.jit(make_train_step(cfg, tcfg))
        for src, tgt in ds.batches(0):
            state, m = step(state, src, tgt, jax.random.PRNGKey(1))
            assert np.isfinite(float(m["loss"]))
            break


class TestLoadDataset:
    @pytest.fixture()
    def corpus_dir(self, tmp_path):
        (tmp_path / "src-train.txt").write_text("\n".join(CORPUS) + "\n")
        (tmp_path / "tgt-train.txt").write_text(
            "\n".join(line.upper() for line in CORPUS) + "\n"
        )
        return tmp_path

    def test_end_to_end(self, corpus_dir):
        train, test, src_tok, tgt_tok = load_dataset(
            str(corpus_dir),
            str(corpus_dir / "src.subwords"),
            str(corpus_dir / "tgt.subwords"),
            batch_size=4,
            sequence_length=20,
            target_vocab_size=300,
        )
        assert test is None  # no test files — skipped, not an error (vs quirk §2.3.10)
        src, tgt = next(train.batches(0))
        assert src.shape == (4, 20)
        # framing: first non-pad token is BOS, EOS present before padding
        assert (src[:, 0] == src_tok.bos_id).all()
        for row in range(4):
            L = (src[row] != 0).sum()
            assert src[row, L - 1] == src_tok.eos_id
        # vocab persisted: second call loads identical tokenizer
        _, _, src_tok2, _ = load_dataset(
            str(corpus_dir),
            str(corpus_dir / "src.subwords"),
            str(corpus_dir / "tgt.subwords"),
            batch_size=4,
            sequence_length=20,
            target_vocab_size=300,
        )
        assert src_tok2.subwords == src_tok.subwords

    def test_length_filter(self, corpus_dir):
        train, _, _, _ = load_dataset(
            str(corpus_dir),
            str(corpus_dir / "s.subwords"),
            str(corpus_dir / "t.subwords"),
            batch_size=2,
            sequence_length=6,
            target_vocab_size=300,
        )
        # every kept example fits in 6 tokens including BOS/EOS
        assert all(len(a) <= 6 for a in train.src)

    def test_missing_corpus_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            read_parallel_corpus(str(tmp_path), "train")


class TestStreaming:
    """StreamingSeq2SeqDataset: bounded-memory disk streaming with the
    reference's shuffle-buffer semantics (utils.py:77-80,154)."""

    @pytest.fixture()
    def big_corpus_dir(self, tmp_path):
        # 500 distinct lines — "big" relative to the 32-example buffer the
        # tests use, so the memory bound is actually exercised.
        lines = [f"line number {i} with some words" for i in range(500)]
        (tmp_path / "src-train.txt").write_text("\n".join(lines) + "\n")
        (tmp_path / "tgt-train.txt").write_text(
            "\n".join(line.upper() for line in lines) + "\n"
        )
        return tmp_path

    def _toks(self, d):
        train, _, src_tok, tgt_tok = load_dataset(
            str(d), str(d / "src.subwords"), str(d / "tgt.subwords"),
            batch_size=4, sequence_length=24, target_vocab_size=300,
        )
        return train, src_tok, tgt_tok

    def _stream(self, d, src_tok, tgt_tok, **kw):
        from transformer_tpu.data.streaming import StreamingSeq2SeqDataset

        args = dict(
            batch_size=4, sequence_length=24, buffer_size=32, seed=0
        )
        args.update(kw)
        return StreamingSeq2SeqDataset(str(d), src_tok, tgt_tok, **args)

    def test_memory_bound_is_structural(self, big_corpus_dir):
        """Peak resident examples never exceeds buffer_size + batch_size —
        the guarantee that makes >RAM corpora trainable."""
        train, src_tok, tgt_tok = self._toks(big_corpus_dir)
        ds = self._stream(big_corpus_dir, src_tok, tgt_tok, buffer_size=32)
        n = sum(1 for _ in ds.batches(0))
        assert n > 0
        assert 0 < ds.peak_resident_examples <= 32 + 4
        assert ds.num_examples == 500  # line count needs no tokenization

    def test_same_example_multiset_as_memory_path(self, big_corpus_dir):
        """Streaming must deliver exactly the in-memory epoch's examples
        (different order — buffered shuffle vs full permutation)."""
        train, src_tok, tgt_tok = self._toks(big_corpus_dir)
        ds = self._stream(
            big_corpus_dir, src_tok, tgt_tok, drop_remainder=False
        )

        def rows(batches):
            out = set()
            for src, tgt in batches:
                for r in range(src.shape[0]):
                    if src[r].any():
                        out.add((src[r].tobytes(), tgt[r].tobytes()))
            return out

        mem = rows(
            Seq2SeqDataset(
                train.src, train.tgt, batch_size=4, src_len=24, tgt_len=24,
                drop_remainder=False,
            ).batches(0)
        )
        assert rows(ds.batches(0)) == mem

    def test_deterministic_per_seed_epoch(self, big_corpus_dir):
        _, src_tok, tgt_tok = self._toks(big_corpus_dir)
        a = self._stream(big_corpus_dir, src_tok, tgt_tok)
        b = self._stream(big_corpus_dir, src_tok, tgt_tok)
        for (sa, ta), (sb, tb) in zip(a.batches(3), b.batches(3)):
            np.testing.assert_array_equal(sa, sb)
            np.testing.assert_array_equal(ta, tb)
        first = next(a.batches(4))[0]
        assert not np.array_equal(first, next(b.batches(3))[0])

    def test_sharding_slices_one_global_stream(self, big_corpus_dir):
        """Two shards must see disjoint halves of the same global batches —
        the multi-host contract (identical (seed, epoch) keying)."""
        _, src_tok, tgt_tok = self._toks(big_corpus_dir)
        full = self._stream(big_corpus_dir, src_tok, tgt_tok)
        s0 = self._stream(
            big_corpus_dir, src_tok, tgt_tok, shard_index=0, shard_count=2
        )
        s1 = self._stream(
            big_corpus_dir, src_tok, tgt_tok, shard_index=1, shard_count=2
        )
        for (fs, _), (a, _), (b, _) in zip(
            full.batches(1), s0.batches(1), s1.batches(1)
        ):
            np.testing.assert_array_equal(np.concatenate([a, b]), fs)

    def test_unshuffled_preserves_file_order(self, big_corpus_dir):
        _, src_tok, tgt_tok = self._toks(big_corpus_dir)
        ds = self._stream(big_corpus_dir, src_tok, tgt_tok, shuffle=False)
        first_src, _ = next(ds.batches(0))
        want = np.asarray(
            [src_tok.bos_id, *src_tok.encode("line number 0 with some words"),
             src_tok.eos_id],
            dtype=np.int32,
        )
        np.testing.assert_array_equal(first_src[0, : len(want)], want)

    def test_load_dataset_streaming_mode(self, big_corpus_dir):
        """load_dataset(streaming=True) swaps the train split for the
        streaming reader (vocabs must pre-exist) and trains end to end."""
        from transformer_tpu.data.streaming import StreamingSeq2SeqDataset

        with pytest.raises(FileNotFoundError, match="vocab"):
            load_dataset(
                str(big_corpus_dir / "does-not-exist-yet"),
                str(big_corpus_dir / "no.subwords"),
                str(big_corpus_dir / "no.subwords"),
                batch_size=4, sequence_length=24, streaming=True,
            )
        self._toks(big_corpus_dir)  # builds + persists the vocabs
        train, test, src_tok, tgt_tok = load_dataset(
            str(big_corpus_dir),
            str(big_corpus_dir / "src.subwords"),
            str(big_corpus_dir / "tgt.subwords"),
            batch_size=4, sequence_length=24,
            streaming=True, buffer_size=32,
        )
        assert isinstance(train, StreamingSeq2SeqDataset)
        assert test is None
        src, tgt = next(train.batches(0))
        assert src.shape == (4, 24) and tgt.shape == (4, 24)
        assert (src[:, 0] == src_tok.bos_id).all()


class TestTfdsCompat:
    """tfds-format .subwords importer (data/tfds_compat.py): the tokenizer
    comparability bridge to vocabularies saved by real reference runs."""

    # A hand-built tfds-style vocabulary: multi-char merges first, then the
    # single-char alphabet incl. the escape machinery chars (tfds's build
    # always emits those), exactly as SubwordTextEncoder.save_to_file lays
    # a file out.
    PIECES = [
        "the_", "quick_", "bro", "wn_", "fox", "es_",
        "a", "b", "c", "d", "e", "f", "h", "i", "k", "n", "o", "q",
        "r", "s", "t", "u", "w", "x", "_", "\\", ";", ".",
    ] + list("0123456789")

    @pytest.fixture()
    def vocab_file(self, tmp_path):
        p = tmp_path / "ref.subwords"
        lines = ["### SubwordTextEncoder", "### Metadata: {}"]
        lines += [
            "'" + s.replace("\\", "\\\\").replace("\n", "\\n") + "'"
            for s in self.PIECES
        ]
        p.write_text("\n".join(lines) + "\n")
        return str(p)

    def test_load_and_id_space(self, vocab_file):
        from transformer_tpu.data.tfds_compat import TfdsSubwordTokenizer

        tok = TfdsSubwordTokenizer.load(vocab_file)
        n = len(self.PIECES)
        assert tok.subwords == self.PIECES  # file order == id order (1-based)
        assert tok.vocab_size == 1 + n + 256  # pad + subwords + byte fallback
        assert tok.bos_id == tok.vocab_size
        assert tok.eos_id == tok.vocab_size + 1
        assert tok.model_vocab_size == tok.vocab_size + 2
        # id 1 is the first file line, the tfds layout BLEU comparability
        # depends on.
        assert tok.encode("the")[:1] == [1]

    def test_roundtrip(self, vocab_file):
        from transformer_tpu.data.tfds_compat import TfdsSubwordTokenizer

        tok = TfdsSubwordTokenizer.load(vocab_file)
        for text in (
            "the quick brown fox",
            "the quick the quick",
            "foxes run under_scores and back\\slashes",  # escape chars
            "punct. at ends.",
            "unicode: über café",  # chars outside the alphabet
            "digits 0123 and ; semicolons",
        ):
            ids = tok.encode(text)
            assert all(0 < i < tok.vocab_size for i in ids)
            assert tok.decode(ids) == text, text

    def test_greedy_longest_match(self, vocab_file):
        from transformer_tpu.data.tfds_compat import TfdsSubwordTokenizer

        tok = TfdsSubwordTokenizer.load(vocab_file)
        # "the" must take the merged piece "the_", not t-h-e singles.
        assert tok.encode("the") == [1]
        # "foxes" = "fox" + "es_" (greedy prefix), not single chars.
        assert tok.encode("foxes") == [
            self.PIECES.index("fox") + 1, self.PIECES.index("es_") + 1
        ]

    def test_transparent_via_subword_load(self, vocab_file):
        """SubwordTokenizer.load must sniff the tfds header and return the
        compat tokenizer, so every CLI --*_vocab_file accepts reference
        vocabularies unchanged."""
        from transformer_tpu.data.tfds_compat import TfdsSubwordTokenizer

        tok = SubwordTokenizer.load(vocab_file)
        assert isinstance(tok, TfdsSubwordTokenizer)
        assert tok.decode(tok.encode("the quick")) == "the quick"

    def test_save_roundtrips_file(self, vocab_file, tmp_path):
        from transformer_tpu.data.tfds_compat import TfdsSubwordTokenizer

        tok = TfdsSubwordTokenizer.load(vocab_file)
        out = str(tmp_path / "resaved.subwords")
        tok.save(out)
        tok2 = TfdsSubwordTokenizer.load(out)
        assert tok2.subwords == tok.subwords

    def test_byte_fallback_ids(self, vocab_file):
        from transformer_tpu.data.tfds_compat import TfdsSubwordTokenizer

        tok = TfdsSubwordTokenizer.load(vocab_file)
        n = len(self.PIECES)
        # A char in no subword and outside the alphabet escapes to \<ord>;
        # whose digits/backslash/semicolon ARE in the vocab — ids stay in
        # the subword range. But a vocab missing those would byte-fall-back;
        # simulate by encoding a char whose escape digits exist: verify the
        # escape produces a decodable id sequence either way.
        ids = tok.encode("café")
        assert tok.decode(ids) == "café"
        assert all(0 < i < tok.vocab_size for i in ids)
        assert n  # silence unused warning
