"""Data pipeline tests: tokenizer round-trips, BPE training, dataset batching,
sharding, and parity conventions (BOS/EOS/pad framing)."""

import numpy as np
import pytest

from transformer_tpu.data import (
    Seq2SeqDataset,
    SubwordTokenizer,
    load_dataset,
    read_parallel_corpus,
)

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the quick brown cat sleeps",
    "a lazy dog sleeps all day",
    "the fox and the dog are friends",
    "quick brown foxes jump over lazy dogs",
] * 4


class TestTokenizer:
    def test_roundtrip(self):
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=400)
        for line in CORPUS[:5]:
            assert tok.decode(tok.encode(line)) == line

    def test_roundtrip_unseen_text_via_byte_fallback(self):
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=400)
        text = "zebra Ω 真 underscore_word"
        assert tok.decode(tok.encode(text)) == text

    def test_literal_byte_token_text_roundtrips(self):
        """Regression: literal '<0xNN>' in input text must not be confused
        with the byte-fallback token namespace."""
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=400)
        text = "see <0x41> here < and 0x41 >"
        assert tok.decode(tok.encode(text)) == text

    def test_ids_positive_and_below_vocab_size(self):
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=400)
        ids = tok.encode("the quick fox")
        assert all(1 <= i < tok.vocab_size for i in ids)

    def test_specials_convention(self):
        """BOS=vocab_size, EOS=vocab_size+1, model rows = vocab_size+2 —
        the reference convention (utils.py:137-143, train.py:232-233)."""
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=300)
        assert tok.bos_id == tok.vocab_size
        assert tok.eos_id == tok.vocab_size + 1
        assert tok.model_vocab_size == tok.vocab_size + 2

    def test_bpe_actually_merges(self):
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=500)
        # 'the_' appears 16+ times; BPE should have merged it into one piece.
        ids = tok.encode("the")
        assert len(ids) == 1

    def test_save_load_identical(self, tmp_path):
        tok = SubwordTokenizer.build_from_corpus(CORPUS, target_vocab_size=400)
        path = str(tmp_path / "vocab.subwords")
        tok.save(path)
        tok2 = SubwordTokenizer.load(path)
        assert tok2.subwords == tok.subwords
        text = "the quick brown fox"
        assert tok2.encode(text) == tok.encode(text)

    def test_load_rejects_garbage(self, tmp_path):
        p = tmp_path / "bad.subwords"
        p.write_text("not a vocab\nfoo\n")
        with pytest.raises(ValueError):
            SubwordTokenizer.load(str(p))


class TestDataset:
    def _mk(self, n=20, batch=4, **kw):
        src = [np.arange(1, 1 + (i % 5) + 2, dtype=np.int32) for i in range(n)]
        tgt = [np.arange(1, 1 + (i % 7) + 2, dtype=np.int32) for i in range(n)]
        return Seq2SeqDataset(src, tgt, batch_size=batch, src_len=10, tgt_len=12, **kw)

    def test_static_shapes_and_padding(self):
        ds = self._mk()
        for src, tgt in ds.batches(0):
            assert src.shape == (4, 10) and tgt.shape == (4, 12)
            assert src.dtype == np.int32
        # padding is 0 beyond each row's length
        src, tgt = next(ds.batches(0))
        row_lens = (src != 0).sum(1)
        for r, L in enumerate(row_lens):
            assert (src[r, L:] == 0).all()

    def test_shuffle_deterministic_per_epoch(self):
        ds = self._mk()
        a = [s.copy() for s, _ in ds.batches(3)]
        b = [s.copy() for s, _ in ds.batches(3)]
        c = [s.copy() for s, _ in ds.batches(4)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
        assert any(not np.array_equal(x, y) for x, y in zip(a, c))

    def test_sharding_partitions_batch(self):
        """Two shards of the same global batch must tile the unsharded batch."""
        full = self._mk(shard_index=0, shard_count=1)
        s0 = self._mk(shard_index=0, shard_count=2)
        s1 = self._mk(shard_index=1, shard_count=2)
        f = next(full.batches(1))[0]
        a = next(s0.batches(1))[0]
        b = next(s1.batches(1))[0]
        np.testing.assert_array_equal(np.concatenate([a, b], 0), f)

    def test_batch_divisibility_enforced(self):
        with pytest.raises(ValueError):
            self._mk(batch=4, shard_count=3)

    def test_drop_remainder(self):
        ds = self._mk(n=10, batch=4)
        assert len(list(ds.batches(0))) == 2  # 10//4, remainder dropped

    def test_partial_tail_batch_same_count_on_all_shards(self):
        """Regression: with drop_remainder=False every shard must yield the
        same number of (static-shape) batches — a short tail is padded with
        empty rows, never skipped on one host (multi-host SPMD would hang)."""
        kw = dict(shuffle=False, drop_remainder=False)
        s0 = self._mk(n=10, batch=4, shard_index=0, shard_count=2, **kw)
        s1 = self._mk(n=10, batch=4, shard_index=1, shard_count=2, **kw)
        b0 = list(s0.batches(0))
        b1 = list(s1.batches(0))
        assert len(b0) == len(b1) == 3
        for (sa, _), (sb, _) in zip(b0, b1):
            assert sa.shape == sb.shape == (2, 10)
        # last batch of shard 1 is entirely padding rows (weight 0)
        assert (b1[-1][0] == 0).all()
        # all real examples appear exactly once across shards
        total_rows = np.concatenate([s for s, _ in b0] + [s for s, _ in b1])
        assert (total_rows != 0).any(axis=1).sum() == 10


class TestLoadDataset:
    @pytest.fixture()
    def corpus_dir(self, tmp_path):
        (tmp_path / "src-train.txt").write_text("\n".join(CORPUS) + "\n")
        (tmp_path / "tgt-train.txt").write_text(
            "\n".join(line.upper() for line in CORPUS) + "\n"
        )
        return tmp_path

    def test_end_to_end(self, corpus_dir):
        train, test, src_tok, tgt_tok = load_dataset(
            str(corpus_dir),
            str(corpus_dir / "src.subwords"),
            str(corpus_dir / "tgt.subwords"),
            batch_size=4,
            sequence_length=20,
            target_vocab_size=300,
        )
        assert test is None  # no test files — skipped, not an error (vs quirk §2.3.10)
        src, tgt = next(train.batches(0))
        assert src.shape == (4, 20)
        # framing: first non-pad token is BOS, EOS present before padding
        assert (src[:, 0] == src_tok.bos_id).all()
        for row in range(4):
            L = (src[row] != 0).sum()
            assert src[row, L - 1] == src_tok.eos_id
        # vocab persisted: second call loads identical tokenizer
        _, _, src_tok2, _ = load_dataset(
            str(corpus_dir),
            str(corpus_dir / "src.subwords"),
            str(corpus_dir / "tgt.subwords"),
            batch_size=4,
            sequence_length=20,
            target_vocab_size=300,
        )
        assert src_tok2.subwords == src_tok.subwords

    def test_length_filter(self, corpus_dir):
        train, _, _, _ = load_dataset(
            str(corpus_dir),
            str(corpus_dir / "s.subwords"),
            str(corpus_dir / "t.subwords"),
            batch_size=2,
            sequence_length=6,
            target_vocab_size=300,
        )
        # every kept example fits in 6 tokens including BOS/EOS
        assert all(len(a) <= 6 for a in train.src)

    def test_missing_corpus_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            read_parallel_corpus(str(tmp_path), "train")
