"""Unit tests for core ops (L1) against NumPy oracles (SURVEY.md §4 plan)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig
from transformer_tpu.ops import (
    dot_product_attention,
    ffn_apply,
    ffn_init,
    make_causal_mask,
    make_padding_mask,
    make_seq2seq_masks,
    mha_apply,
    mha_init,
    sinusoidal_positional_encoding,
)
from transformer_tpu.ops.attention import init_cache
from transformer_tpu.ops.masks import NEG_INF, attention_bias
from transformer_tpu.ops.nn import layernorm_apply, layernorm_init


class TestPositionalEncoding:
    def test_matches_closed_form(self):
        """Oracle: the reference formula (positionalencoding.py:4-23) in NumPy —
        block layout [sin(angles at even channels), cos(angles at odd channels)]."""
        max_pos, d_model = 64, 16
        table = np.asarray(sinusoidal_positional_encoding(max_pos, d_model))
        pos = np.arange(max_pos)[:, None]
        i = np.arange(d_model)[None, :]
        angles = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
        expected = np.concatenate([np.sin(angles[:, 0::2]), np.cos(angles[:, 1::2])], axis=-1)
        np.testing.assert_allclose(table, expected, atol=1e-5)

    def test_sized_by_positions_not_vocab(self):
        table = sinusoidal_positional_encoding(128, 32)
        assert table.shape == (128, 32)

    def test_position_zero_is_sin0_cos0(self):
        table = np.asarray(sinusoidal_positional_encoding(4, 8))
        np.testing.assert_allclose(table[0, :4], 0.0, atol=1e-7)  # sin(0)
        np.testing.assert_allclose(table[0, 4:], 1.0, atol=1e-7)  # cos(0)


class TestMasks:
    def test_padding_mask(self):
        ids = jnp.array([[5, 3, 0, 0], [1, 0, 2, 0]])
        mask = make_padding_mask(ids)
        assert mask.shape == (2, 1, 1, 4)
        np.testing.assert_array_equal(
            np.asarray(mask[:, 0, 0, :]),
            [[True, True, False, False], [True, False, True, False]],
        )

    def test_causal_mask(self):
        mask = np.asarray(make_causal_mask(4)[0, 0])
        expected = np.tril(np.ones((4, 4), dtype=bool))
        np.testing.assert_array_equal(mask, expected)

    def test_seq2seq_masks_semantics(self):
        """Parity with reference create_masks (positionalencoding.py:37-52):
        combined = causal AND target-padding; cross mask uses *source* padding."""
        inp = jnp.array([[7, 8, 0]])
        tar = jnp.array([[4, 0, 5]])
        enc, combined, cross = make_seq2seq_masks(inp, tar)
        assert enc.shape == (1, 1, 1, 3)
        assert combined.shape == (1, 1, 3, 3)
        assert cross.shape == (1, 1, 1, 3)
        np.testing.assert_array_equal(np.asarray(enc[0, 0, 0]), [True, True, False])
        np.testing.assert_array_equal(np.asarray(cross[0, 0, 0]), [True, True, False])
        # Row 2 (query pos 2): causal allows 0,1,2 but key pos 1 is pad.
        np.testing.assert_array_equal(np.asarray(combined[0, 0, 2]), [True, False, True])
        # Row 0: only key 0.
        np.testing.assert_array_equal(np.asarray(combined[0, 0, 0]), [True, False, False])

    def test_attention_bias(self):
        mask = jnp.array([[True, False]])
        bias = np.asarray(attention_bias(mask, jnp.float32))
        assert bias[0, 0] == 0.0 and bias[0, 1] == NEG_INF


def _numpy_attention(q, k, v, allowed=None):
    """fp64 NumPy oracle for softmax(qk^T/sqrt(d))v over (B,S,H,D) layout."""
    q, k, v = (np.asarray(t, dtype=np.float64) for t in (q, k, v))
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
    if allowed is not None:
        logits = np.where(np.asarray(allowed), logits, -1e9)
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", w, v)


class TestDotProductAttention:
    def test_matches_numpy_oracle(self):
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (2, 5, 3, 8))
        k = jax.random.normal(kk, (2, 7, 3, 8))
        v = jax.random.normal(kv, (2, 7, 3, 8))
        out, _ = dot_product_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), _numpy_attention(q, k, v), atol=1e-5)

    def test_masking_blocks_positions(self):
        key = jax.random.PRNGKey(1)
        q = jax.random.normal(key, (1, 2, 1, 4))
        k = jax.random.normal(key, (1, 3, 1, 4))
        v = jax.random.normal(key, (1, 3, 1, 4))
        mask = jnp.array([True, True, False])[None, None, None, :]
        out, w = dot_product_attention(q, k, v, mask, return_weights=True)
        np.testing.assert_allclose(np.asarray(w[..., 2]), 0.0, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(out), _numpy_attention(q, k, v, mask), atol=1e-5
        )

    def test_weights_sum_to_one(self):
        q = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 2, 8))
        _, w = dot_product_attention(q, q, q, return_weights=True)
        np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, atol=1e-6)

    def test_bf16_inputs_fp32_softmax(self):
        q = jax.random.normal(jax.random.PRNGKey(3), (1, 8, 2, 16), dtype=jnp.bfloat16)
        out, _ = dot_product_attention(q, q, q)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float64),
            _numpy_attention(q, q, q),
            atol=2e-2,
        )


class TestMultiHeadAttention:
    def test_shapes_and_param_structure(self):
        cfg = ModelConfig(d_model=32, num_heads=4, input_vocab_size=10, target_vocab_size=10)
        params = mha_init(jax.random.PRNGKey(0), cfg.d_model, cfg.num_heads)
        assert params["query"]["kernel"].shape == (32, 4, 8)
        assert params["out"]["kernel"].shape == (4, 8, 32)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 32))
        out, w, _ = mha_apply(params, x, x, return_weights=True)
        assert out.shape == (2, 6, 32)
        assert w.shape == (2, 4, 6, 6)

    def test_divisibility_asserted(self):
        with pytest.raises(ValueError):
            ModelConfig(d_model=30, num_heads=4)

    def test_cache_prefill_chunk_is_causal(self):
        """Regression: writing a multi-token chunk into the cache must stay
        causal — query i may not attend new positions > i."""
        d_model, heads, seq = 16, 2, 6
        params = mha_init(jax.random.PRNGKey(0), d_model, heads)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, seq, d_model))
        full, _, _ = mha_apply(params, x, x, causal=True)
        cache = init_cache(1, seq, heads, d_model // heads, dtype=jnp.float32)
        chunk, _, cache = mha_apply(params, x[:, :4], x[:, :4], cache=cache)
        np.testing.assert_allclose(np.asarray(full[:, :4]), np.asarray(chunk), atol=1e-5)
        step, _, cache = mha_apply(params, x[:, 4:], x[:, 4:], cache=cache)
        np.testing.assert_allclose(np.asarray(full[:, 4:]), np.asarray(step), atol=1e-5)

    def test_causal_flag_combines_with_padding_mask(self):
        """causal=True must AND with a provided mask, not be skipped."""
        d_model, heads = 8, 1
        params = mha_init(jax.random.PRNGKey(0), d_model, heads)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 4, d_model))
        pad_mask = jnp.ones((1, 1, 1, 4), jnp.bool_)
        _, w, _ = mha_apply(params, x, x, pad_mask, causal=True, return_weights=True)
        w = np.asarray(w[0, 0])
        assert np.allclose(np.triu(w, k=1), 0.0, atol=1e-6), "future positions attended"

    def test_cache_decode_matches_full_attention(self):
        """Greedy-decode equivalence: attending step-by-step through a KV cache
        must equal causal attention over the full sequence."""
        d_model, heads, seq = 16, 2, 5
        params = mha_init(jax.random.PRNGKey(0), d_model, heads)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, seq, d_model))
        full, _, _ = mha_apply(params, x, x, causal=True)

        cache = init_cache(1, seq, heads, d_model // heads, dtype=jnp.float32)
        outs = []
        for t in range(seq):
            step, _, cache = mha_apply(params, x[:, t : t + 1], x[:, t : t + 1], cache=cache)
            outs.append(step)
        incremental = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(np.asarray(full), np.asarray(incremental), atol=1e-5)


class TestFFN:
    def test_matches_numpy_oracle(self):
        params = ffn_init(jax.random.PRNGKey(0), 8, 16)
        assert "gate" not in params  # ungated default matches the reference
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 8))
        out = ffn_apply(params, x)
        h = np.maximum(np.asarray(x) @ np.asarray(params["in"]["kernel"]) + np.asarray(params["in"]["bias"]), 0)
        expected = h @ np.asarray(params["out"]["kernel"]) + np.asarray(params["out"]["bias"])
        np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)

    def test_swiglu_matches_numpy_oracle(self):
        """Gated variant (Shazeer 2020): act(x W_gate) * (x W_in) W_out."""
        params = ffn_init(jax.random.PRNGKey(0), 8, 16, activation="swiglu")
        assert set(params) == {"in", "out", "gate"}
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 8))
        out = ffn_apply(params, x, activation="swiglu")
        xn = np.asarray(x, np.float64)
        g = xn @ np.asarray(params["gate"]["kernel"]) + np.asarray(params["gate"]["bias"])
        silu = g * (1.0 / (1.0 + np.exp(-g)))  # x * sigmoid(x)
        h = silu * (xn @ np.asarray(params["in"]["kernel"]) + np.asarray(params["in"]["bias"]))
        expected = h @ np.asarray(params["out"]["kernel"]) + np.asarray(params["out"]["bias"])
        np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)

    @pytest.mark.slow
    def test_swiglu_model_trains(self):
        from transformer_tpu.config import ModelConfig, TrainConfig
        from transformer_tpu.train import create_train_state, make_train_step

        cfg = ModelConfig(
            num_layers=2, d_model=32, num_heads=4, dff=64,
            input_vocab_size=50, target_vocab_size=50, max_position=16,
            dtype="float32", dropout_rate=0.0, ffn_activation="swiglu",
        )
        tc = TrainConfig(batch_size=8, sequence_length=12, warmup_steps=100)
        state = create_train_state(jax.random.PRNGKey(0), cfg, tc)
        step = jax.jit(make_train_step(cfg, tc))
        r = np.random.default_rng(0)
        src = jnp.asarray(r.integers(1, 48, (8, 12)), jnp.int32)
        tgt = jnp.asarray(r.integers(1, 48, (8, 12)), jnp.int32)
        rng = jax.random.PRNGKey(1)
        first = None
        for _ in range(40):
            state, m = step(state, src, tgt, rng)
            first = float(m["loss"]) if first is None else first
        assert float(m["loss"]) < first * 0.7

    def test_moe_rejects_gated_activation(self):
        import pytest

        from transformer_tpu.config import ModelConfig

        with pytest.raises(ValueError, match="ungated"):
            ModelConfig(moe_experts=4, ffn_activation="swiglu")


class TestLayerNorm:
    def test_matches_numpy_oracle(self):
        params = layernorm_init(16)
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 16)) * 3 + 1
        out = np.asarray(layernorm_apply(params, x))
        xn = np.asarray(x, dtype=np.float64)
        expected = (xn - xn.mean(-1, keepdims=True)) / np.sqrt(
            xn.var(-1, keepdims=True) + 1e-6
        )
        np.testing.assert_allclose(out, expected, atol=1e-4)
        np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)
        np.testing.assert_allclose(out.std(-1), 1.0, atol=1e-2)
