"""Encoder-only masked-LM family (``ModelConfig.encoder_only`` +
``TrainConfig.objective="mlm"``): masking statistics, learning, eval
determinism, validation, and the sharded-step composition.

No reference counterpart (the reference is translation-only,
``README.md:1-5``) — this pins the framework's third model family the way
test_train pins the causal two.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from transformer_tpu.config import PAD_ID, ModelConfig, TrainConfig
from transformer_tpu.models import transformer_apply, transformer_init
from transformer_tpu.train import create_train_state, make_eval_step, make_train_step
from transformer_tpu.train.mlm import mask_tokens

VOCAB = 41  # 40 real ids + the reserved top id (40) for [MASK]
CFG = ModelConfig(
    num_layers=2, d_model=32, num_heads=4, dff=64,
    input_vocab_size=VOCAB, target_vocab_size=VOCAB,
    max_position=16, dropout_rate=0.0, dtype="float32",
    encoder_only=True, tie_output=True,
)
TCFG = TrainConfig(
    batch_size=8, sequence_length=12, warmup_steps=20,
    lr_schedule="constant", peak_lr=3e-3, objective="mlm",
    log_every_steps=0, eval_every_steps=0,
)


def _batch():
    """Each row is one repeated token id (3 + row): masked positions are
    trivially predictable from the unmasked context, so learning is fast
    and failures point at the objective plumbing, not model capacity."""
    tok = np.arange(3, 11, dtype=np.int32)[:, None]
    x = np.broadcast_to(tok, (8, 12)).copy()
    x[:, -2:] = PAD_ID  # a pad tail, so the PAD-exclusion paths execute
    return x


class TestMasking:
    def test_stats_and_determinism(self):
        rng = jax.random.PRNGKey(0)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(1, VOCAB - 1, (64, 128)),
            jnp.int32,
        )
        masked, labels = mask_tokens(tokens, rng, VOCAB, mask_rate=0.15)
        masked2, labels2 = mask_tokens(tokens, rng, VOCAB, mask_rate=0.15)
        np.testing.assert_array_equal(masked, masked2)  # same rng, same mask
        np.testing.assert_array_equal(labels, labels2)
        sel = np.asarray(labels != PAD_ID)
        frac = sel.mean()
        assert 0.12 < frac < 0.18, frac  # ~15% of positions selected
        # Selected positions: labels carry the ORIGINAL token.
        np.testing.assert_array_equal(
            np.asarray(labels)[sel], np.asarray(tokens)[sel]
        )
        # Unselected positions pass through unchanged.
        np.testing.assert_array_equal(
            np.asarray(masked)[~sel], np.asarray(tokens)[~sel]
        )
        m = np.asarray(masked)[sel]
        orig = np.asarray(tokens)[sel]
        frac_mask = (m == VOCAB - 1).mean()
        frac_keep = (m == orig).mean()
        assert 0.72 < frac_mask < 0.88, frac_mask  # ~80% [MASK]
        assert 0.05 < frac_keep < 0.16, frac_keep  # ~10% kept
        assert (m != PAD_ID).all()  # random draws never produce PAD

    def test_pad_positions_never_selected(self):
        tokens = jnp.asarray(_batch())
        masked, labels = mask_tokens(tokens, jax.random.PRNGKey(1), VOCAB)
        pad = np.asarray(tokens) == PAD_ID
        np.testing.assert_array_equal(np.asarray(labels)[pad], PAD_ID)
        np.testing.assert_array_equal(np.asarray(masked)[pad], PAD_ID)

    def test_excluded_ids_never_selected_nor_injected(self):
        """BOS/EOS exclusion (ADVICE r4): specials are never prediction
        targets and the 10% random-replacement draw never injects them —
        while every non-excluded real id can still be drawn (the
        order-statistics remap skips, not truncates)."""
        bos, eos = VOCAB - 3, VOCAB - 2  # the framework layout: mask_id-2/-1
        rng = np.random.default_rng(1)
        base = rng.integers(1, VOCAB - 1, (64, 128)).astype(np.int32)
        base[:, 0] = bos  # specials present in every row
        base[:, 70] = eos
        tokens = jnp.asarray(base)
        masked, labels = mask_tokens(
            tokens, jax.random.PRNGKey(2), VOCAB, excluded_ids=(bos, eos)
        )
        labels, masked = np.asarray(labels), np.asarray(masked)
        special = (base == bos) | (base == eos)
        np.testing.assert_array_equal(labels[special], PAD_ID)  # not targets
        np.testing.assert_array_equal(masked[special], base[special])
        # Replacement draws: positions where masked differs from both the
        # original and [MASK] are the 10% random draws — none may be a
        # special, and collectively they should cover other high ids (the
        # remap shifts past the excluded band rather than clipping it).
        drawn = masked[(masked != base) & (masked != VOCAB - 1)]
        assert drawn.size > 0
        assert not np.isin(drawn, [bos, eos, PAD_ID]).any()

    def test_excluding_whole_vocab_rejected(self):
        with pytest.raises(ValueError, match="no real tokens"):
            mask_tokens(
                jnp.ones((2, 4), jnp.int32), jax.random.PRNGKey(0), 4,
                excluded_ids=(1, 2),  # vocab 4: mask=3, real ids {1,2}
            )

    def test_train_step_auto_excludes_bos_eos(self):
        """The trainer's auto default ((mask_id-2, mask_id-1)) reaches
        mask_tokens: a batch of ONLY specials+pad yields zero selected
        positions, so the masked-CE weight (= selected count) is 0."""
        from transformer_tpu.train.trainer import _prepare_batch

        bos, eos = VOCAB - 3, VOCAB - 2
        tgt = jnp.asarray(
            np.array([[bos, eos] * 6] * 8, dtype=np.int32)
        )
        inp, labels, _ = _prepare_batch(CFG, TCFG, tgt, jax.random.PRNGKey(3))
        np.testing.assert_array_equal(np.asarray(labels), PAD_ID)
        np.testing.assert_array_equal(np.asarray(inp), np.asarray(tgt))


class TestEncoderOnlyModel:
    def test_init_and_forward_shapes(self):
        params = transformer_init(jax.random.PRNGKey(0), CFG)
        assert set(params) == {"encoder"}  # no decoder tower, tied head
        logits, _ = transformer_apply(params, None, jnp.asarray(_batch()), CFG)
        assert logits.shape == (8, 12, VOCAB)

    def test_untied_head(self):
        cfg = dataclasses.replace(CFG, tie_output=False)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        assert set(params) == {"encoder", "final"}
        logits, _ = transformer_apply(params, None, jnp.asarray(_batch()), cfg)
        assert logits.shape == (8, 12, VOCAB)

    def test_both_towers_rejected(self):
        with pytest.raises(ValueError, match="mutually exclusive"):
            dataclasses.replace(CFG, decoder_only=True)

    def test_no_decode_path(self):
        from transformer_tpu.train.decode import translate

        params = transformer_init(jax.random.PRNGKey(0), CFG)

        class _Tok:
            bos_id, eos_id = 1, 2

            def encode(self, s):
                return [3]

        with pytest.raises(ValueError, match="no autoregressive decode"):
            translate(params, CFG, _Tok(), _Tok(), "x")


class TestMlmTraining:
    def test_learns_and_eval_deterministic(self):
        state = create_train_state(jax.random.PRNGKey(0), CFG, TCFG)
        step = jax.jit(make_train_step(CFG, TCFG))
        x = jnp.asarray(_batch())
        rng = jax.random.PRNGKey(7)
        first = None
        for _ in range(150):
            state, m = step(state, x, x, rng)
            if first is None:
                first = float(m["loss"])
        last = float(m["loss"])
        assert last < first / 4, (first, last)
        acc = float(m["correct"]) / max(float(m["weight"]), 1.0)
        assert acc > 0.9, acc  # masked repeated-token prediction is easy

        ev = jax.jit(make_eval_step(CFG, TCFG))
        e1, e2 = ev(state, x, x), ev(state, x, x)
        assert float(e1["loss"]) == float(e2["loss"])  # constant eval masks
        assert float(e1["weight"]) > 0  # some positions were scored

        # Fill-mask round trip: mask one position, the trained model must
        # recover the original token (row token = 3 + row index).
        probe = jnp.asarray(_batch()).at[0, 4].set(VOCAB - 1)
        logits, _ = transformer_apply(state.params, None, probe, CFG)
        assert int(jnp.argmax(logits[0, 4])) == 3

    def test_objective_family_cross_validation(self):
        causal_cfg = dataclasses.replace(CFG, encoder_only=False)
        with pytest.raises(ValueError, match="go together"):
            make_train_step(causal_cfg, TCFG)
        with pytest.raises(ValueError, match="go together"):
            make_train_step(CFG, dataclasses.replace(TCFG, objective="causal"))
        with pytest.raises(ValueError, match="go together"):
            make_eval_step(causal_cfg, TCFG)

    def test_grad_accum_matches_plain(self):
        """MLM + gradient accumulation: same masks (same step rng), so the
        accumulated update must equal the whole-batch one."""
        sgd = optax.sgd(1.0)
        x = jnp.asarray(_batch())
        rng = jax.random.PRNGKey(3)
        state = create_train_state(jax.random.PRNGKey(0), CFG, TCFG)
        s1, m1 = jax.jit(make_train_step(CFG, TCFG, tx=sgd))(state, x, x, rng)
        accum_cfg = dataclasses.replace(TCFG, grad_accum_steps=2)
        s2, m2 = jax.jit(make_train_step(CFG, accum_cfg, tx=sgd))(
            state, x, x, rng
        )
        np.testing.assert_allclose(
            float(m2["loss"]), float(m1["loss"]), rtol=1e-5
        )
        for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=1e-5, rtol=1e-4
            )


class _CharTok:
    """a..z -> ids 3..28; decode inverts. Enough tokenizer surface for the
    fill_mask text API (encode/decode/bos_id/eos_id)."""

    bos_id, eos_id = 1, 2

    def encode(self, s):
        return [3 + (ord(c) - ord("a")) for c in s if c != " "]

    def decode(self, ids):
        return "".join(chr(ord("a") + int(i) - 3) for i in ids)


class TestFillMask:
    def test_rejects_non_encoder_and_missing_marker(self):
        from transformer_tpu.train.decode import fill_mask

        tok = _CharTok()
        causal = dataclasses.replace(CFG, encoder_only=False)
        with pytest.raises(ValueError, match="encoder_only"):
            fill_mask(transformer_init(jax.random.PRNGKey(0), causal),
                      causal, tok, "a[MASK]b")
        params = transformer_init(jax.random.PRNGKey(0), CFG)
        with pytest.raises(ValueError, match="marker"):
            fill_mask(params, CFG, tok, "no masks here")

    def test_fills_trained_token(self):
        """Train on repeated-letter rows, then the text API must recover a
        masked letter from its context — candidates exclude PAD/[MASK]."""
        from transformer_tpu.train.decode import fill_mask

        tok = _CharTok()
        state = create_train_state(jax.random.PRNGKey(0), CFG, TCFG)
        step = jax.jit(make_train_step(CFG, TCFG))
        x = jnp.asarray(_batch())
        for _ in range(150):
            state, _ = step(state, x, x, jax.random.PRNGKey(7))
        # _batch row tokens are ids 3..10 == letters 'a'..'h'.
        out = fill_mask(
            state.params, CFG, tok, ["bbbb[MASK]bbbbb", "cc[MASK]c[MASK]ccc"],
            top_k=3,
        )
        assert out[0]["filled"] == "bbbbbbbbbb"
        assert len(out[0]["candidates"]) == 1
        assert out[0]["candidates"][0][0][0] == "b"  # top candidate text
        assert len(out[1]["candidates"]) == 2
        assert out[1]["filled"] == "cccccccc"
        for cands in out[0]["candidates"] + out[1]["candidates"]:
            assert len(cands) == 3
            probs = [p for _, p in cands]
            assert all(0.0 <= p <= 1.0 for p in probs)
            assert probs == sorted(probs, reverse=True)


@pytest.mark.slow
class TestMlmSharded:
    def test_dp2_matches_single_device(self):
        """objective='mlm' through make_sharded_steps on a data=2 mesh:
        same per-step masks (replicated rng), so loss must match the
        single-device step."""
        from transformer_tpu.config import MeshConfig
        from transformer_tpu.parallel import (
            create_sharded_state, make_mesh, make_sharded_steps, put_batch,
        )

        x = _batch()
        rng = jax.random.PRNGKey(5)
        state = create_train_state(jax.random.PRNGKey(0), CFG, TCFG)
        _, m_ref = jax.jit(make_train_step(CFG, TCFG))(
            state, jnp.asarray(x), jnp.asarray(x), rng
        )
        mesh = make_mesh(MeshConfig(data=2), devices=jax.devices()[:2])
        sstate, sh = create_sharded_state(jax.random.PRNGKey(0), CFG, TCFG, mesh)
        step, _ = make_sharded_steps(mesh, CFG, TCFG, sh, donate=False)
        _, m_sh = step(sstate, put_batch(x, mesh), put_batch(x, mesh), rng)
        np.testing.assert_allclose(
            float(m_sh["loss"]), float(m_ref["loss"]), rtol=1e-5
        )

    def test_pipe_mesh_rejected(self):
        from transformer_tpu.config import MeshConfig
        from transformer_tpu.parallel import make_mesh
        from transformer_tpu.parallel.distributed import make_sharded_steps
        from transformer_tpu.parallel import create_sharded_state

        mesh = make_mesh(
            MeshConfig(data=1, pipe=2), devices=jax.devices()[:2]
        )
        _, sh = create_sharded_state(jax.random.PRNGKey(0), CFG, TCFG, mesh)
        with pytest.raises(ValueError, match="encoder_only"):
            make_sharded_steps(mesh, CFG, TCFG, sh)
