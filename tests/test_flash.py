"""Pallas flash-attention kernel vs the XLA oracle.

Runs in Pallas interpret mode on the CPU test platform (conftest), so the
kernel logic — online softmax, block masking, custom VJP — is checked exactly,
not modulo MXU rounding. The oracle is ``ops.attention.dot_product_attention``,
itself validated against NumPy in test_ops.py.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transformer_tpu.config import ModelConfig
from transformer_tpu.kernels.flash_attention import flash_attention
from transformer_tpu.models import transformer_apply, transformer_init
from transformer_tpu.ops.attention import dot_product_attention

# Heavyweight module (interpret-mode Pallas / 8-device shard_map /
# multi-process): excluded from the fast path, pytest -m 'not slow'.
pytestmark = pytest.mark.slow


def _qkv(rng, b=2, s=64, h=2, d=32, dtype=jnp.float32):
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), dtype)  # noqa: E731
    return mk(), mk(), mk()


@pytest.fixture
def rng():
    return np.random.default_rng(0)


class TestForward:
    def test_no_mask(self, rng):
        q, k, v = _qkv(rng)
        got = flash_attention(q, k, v, block_q=32, block_k=32)
        want, _ = dot_product_attention(q, k, v)
        np.testing.assert_allclose(got, want, atol=2e-6)

    def test_causal(self, rng):
        q, k, v = _qkv(rng)
        got = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
        mask = jnp.tril(jnp.ones((64, 64), bool))[None, None]
        want, _ = dot_product_attention(q, k, v, mask)
        np.testing.assert_allclose(got, want, atol=2e-6)

    def test_sliding_window(self, rng):
        """window=W must equal the banded causal mask oracle — including
        windows that are not block-aligned (tile-interior banding) and
        smaller than a block (whole tiles skipped below the band)."""
        from transformer_tpu.ops.masks import make_causal_mask

        q, k, v = _qkv(rng)
        for w in (5, 32, 48):
            got = flash_attention(
                q, k, v, causal=True, window=w, block_q=32, block_k=32
            )
            want, _ = dot_product_attention(
                q, k, v, make_causal_mask(64, window=w)
            )
            np.testing.assert_allclose(got, want, atol=2e-6, err_msg=f"w={w}")

    def test_window_grads_match_xla(self, rng):
        from transformer_tpu.ops.masks import make_causal_mask

        q, k, v = _qkv(rng)
        mask = make_causal_mask(64, window=20)

        def f_flash(q, k, v):
            out = flash_attention(
                q, k, v, causal=True, window=20, block_q=32, block_k=32
            )
            return (out**2).sum()

        def f_xla(q, k, v):
            out, _ = dot_product_attention(q, k, v, mask)
            return (out**2).sum()

        got = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(f_xla, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, atol=5e-5)

    def test_window_requires_causal(self, rng):
        q, k, v = _qkv(rng, s=32)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, window=8)

    def test_padding_and_causal(self, rng):
        q, k, v = _qkv(rng)
        kv_mask = jnp.asarray(rng.integers(0, 2, (2, 64)), bool).at[:, :4].set(True)
        got = flash_attention(q, k, v, kv_mask=kv_mask, causal=True, block_q=32, block_k=32)
        mask = jnp.logical_and(
            jnp.tril(jnp.ones((64, 64), bool))[None, None],
            kv_mask[:, None, None, :],
        )
        want, _ = dot_product_attention(q, k, v, mask)
        np.testing.assert_allclose(got, want, atol=2e-6)

    def test_fully_masked_rows_are_finite(self, rng):
        """A row whose keys are all padding must not produce NaN (the
        exp(MASKED-MASKED)=1 pitfall of online softmax)."""
        q, k, v = _qkv(rng, s=32)
        kv_mask = jnp.zeros((2, 32), bool)  # everything padded
        got = flash_attention(q, k, v, kv_mask=kv_mask, block_q=16, block_k=16)
        assert bool(jnp.isfinite(got).all())

    def test_cross_attention_lengths(self, rng):
        """S_q != S_k (decoder cross-attention shape)."""
        q = jnp.asarray(rng.normal(size=(2, 16, 2, 32)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(2, 64, 2, 32)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, 64, 2, 32)), jnp.float32)
        kv_mask = jnp.asarray(rng.integers(0, 2, (2, 64)), bool).at[:, 0].set(True)
        got = flash_attention(q, k, v, kv_mask=kv_mask, block_q=16, block_k=32)
        want, _ = dot_product_attention(q, k, v, kv_mask[:, None, None, :])
        np.testing.assert_allclose(got, want, atol=2e-6)

    def test_non_divisible_block_clamps(self, rng):
        """Requested block larger than / not dividing S falls back to a divisor."""
        q, k, v = _qkv(rng, s=48)
        got = flash_attention(q, k, v, block_q=128, block_k=128)
        want, _ = dot_product_attention(q, k, v)
        np.testing.assert_allclose(got, want, atol=2e-6)

    @pytest.mark.parametrize("s", [63, 65, 117])
    def test_awkward_lengths_pad_internally(self, rng, s):
        """Lengths with no 8-aligned divisor (e.g. 4095 after the
        teacher-forcing shift) must pad internally, not pick a lane-illegal
        block: results still match the oracle exactly, causal and not."""
        q, k, v = _qkv(rng, s=s)
        got = flash_attention(q, k, v, block_q=32, block_k=32)
        want, _ = dot_product_attention(q, k, v)
        np.testing.assert_allclose(got, want, atol=2e-6)

        got_c = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
        mask = jnp.tril(jnp.ones((s, s), bool))[None, None]
        want_c, _ = dot_product_attention(q, k, v, mask)
        np.testing.assert_allclose(got_c, want_c, atol=2e-6)

    def test_awkward_length_grads(self, rng):
        q, k, v = _qkv(rng, s=65)

        def f_flash(q, k, v):
            return (flash_attention(q, k, v, causal=True, block_q=32, block_k=32) ** 2).sum()

        def f_xla(q, k, v):
            mask = jnp.tril(jnp.ones((65, 65), bool))[None, None]
            return (dot_product_attention(q, k, v, mask)[0] ** 2).sum()

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_xla, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=5e-5)

    def test_bfloat16(self, rng):
        q, k, v = _qkv(rng, dtype=jnp.bfloat16)
        got = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
        assert got.dtype == jnp.bfloat16
        mask = jnp.tril(jnp.ones((64, 64), bool))[None, None]
        want, _ = dot_product_attention(q, k, v, mask)
        np.testing.assert_allclose(
            got.astype(jnp.float32), want.astype(jnp.float32), atol=2e-2
        )

    def test_jit_compatible(self, rng):
        q, k, v = _qkv(rng, s=32)
        fn = jax.jit(
            lambda q, k, v: flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        )
        want, _ = dot_product_attention(
            q, k, v, jnp.tril(jnp.ones((32, 32), bool))[None, None]
        )
        np.testing.assert_allclose(fn(q, k, v), want, atol=2e-6)


class TestBackward:
    def test_grads_match_xla(self, rng):
        q, k, v = _qkv(rng)
        kv_mask = jnp.asarray(rng.integers(0, 2, (2, 64)), bool).at[:, :4].set(True)
        mask = jnp.logical_and(
            jnp.tril(jnp.ones((64, 64), bool))[None, None],
            kv_mask[:, None, None, :],
        )

        def f_flash(q, k, v):
            out = flash_attention(q, k, v, kv_mask=kv_mask, causal=True, block_q=32, block_k=32)
            return (out**2).sum()

        def f_xla(q, k, v):
            out, _ = dot_product_attention(q, k, v, mask)
            return (out**2).sum()

        got = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(f_xla, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, atol=5e-5)

    def test_grads_no_mask(self, rng):
        q, k, v = _qkv(rng, s=32)

        def f_flash(q, k, v):
            return flash_attention(q, k, v, block_q=16, block_k=16).sum()

        def f_xla(q, k, v):
            return dot_product_attention(q, k, v)[0].sum()

        got = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(f_xla, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(g, w, atol=5e-5)

    def test_grads_bf16_match_xla(self, rng):
        """The bf16 training path (matmul inputs stay bf16, fp32 accum):
        kernel gradients must track the XLA-attention gradients at bf16
        tolerance — guards the backward-pass casts, not just the forward."""
        q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(rng, s=32))

        def f_flash(q, k, v):
            out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
            return (out.astype(jnp.float32) ** 2).sum()

        def f_xla(q, k, v):
            mask = jnp.tril(jnp.ones((32, 32), bool))[None, None]
            out, _ = dot_product_attention(q, k, v, mask)
            return (out.astype(jnp.float32) ** 2).sum()

        got = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(f_xla, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            assert g.dtype == jnp.bfloat16
            np.testing.assert_allclose(
                np.asarray(g, np.float32), np.asarray(w, np.float32),
                atol=0.15, rtol=0.15,
            )


class TestModelIntegration:
    """attention_impl='flash' must be a drop-in swap for 'xla'."""

    def _cfgs(self):
        cfg_xla = ModelConfig(
            num_layers=2, d_model=32, num_heads=2, dff=64,
            input_vocab_size=40, target_vocab_size=40, max_position=32,
            dtype="float32", dropout_rate=0.0,
        )
        cfg_flash = dataclasses.replace(
            cfg_xla, attention_impl="flash", flash_block_q=8, flash_block_k=8
        )
        return cfg_xla, cfg_flash

    def _batch(self, rng):
        src = jnp.asarray(rng.integers(1, 40, (4, 16)), jnp.int32).at[:, 12:].set(0)
        tgt = jnp.asarray(rng.integers(1, 40, (4, 16)), jnp.int32).at[:, 10:].set(0)
        return src, tgt

    def test_seq2seq_forward_parity(self, rng):
        cfg_xla, cfg_flash = self._cfgs()
        params = transformer_init(jax.random.PRNGKey(0), cfg_xla)
        src, tgt = self._batch(rng)
        want, _ = transformer_apply(params, src, tgt, cfg_xla)
        got, _ = transformer_apply(params, src, tgt, cfg_flash)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_seq2seq_grad_parity(self, rng):
        cfg_xla, cfg_flash = self._cfgs()
        params = transformer_init(jax.random.PRNGKey(0), cfg_xla)
        src, tgt = self._batch(rng)

        def loss(p, cfg):
            logits, _ = transformer_apply(p, src, tgt, cfg)
            logp = jax.nn.log_softmax(logits)
            msk = tgt != 0
            nll = -jnp.take_along_axis(logp, tgt[..., None], -1)[..., 0]
            return (nll * msk).sum() / msk.sum()

        g_xla = jax.grad(loss)(params, cfg_xla)
        g_flash = jax.grad(loss)(params, cfg_flash)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5), g_xla, g_flash
        )

    def test_decoder_only_parity(self, rng):
        cfg_xla, cfg_flash = self._cfgs()
        cfg_xla = dataclasses.replace(cfg_xla, decoder_only=True)
        cfg_flash = dataclasses.replace(cfg_flash, decoder_only=True)
        params = transformer_init(jax.random.PRNGKey(1), cfg_xla)
        _, tgt = self._batch(rng)
        want, _ = transformer_apply(params, None, tgt, cfg_xla)
        got, _ = transformer_apply(params, None, tgt, cfg_flash)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_flash_with_remat_grads(self, rng):
        """The long-context combination (flash kernel + cfg.remat): the
        custom-vjp kernel under jax.checkpoint must still produce gradients
        matching the plain xla model."""
        cfg_xla, cfg_flash = self._cfgs()
        cfg_fr = dataclasses.replace(
            cfg_flash, decoder_only=True, remat=True
        )
        cfg_ref = dataclasses.replace(cfg_xla, decoder_only=True)
        params = transformer_init(jax.random.PRNGKey(1), cfg_ref)
        _, tgt = self._batch(rng)

        def loss(p, cfg):
            logits, _ = transformer_apply(p, None, tgt, cfg)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        g_ref = jax.jit(lambda p: jax.grad(loss)(p, cfg_ref))(params)
        g_fr = jax.jit(lambda p: jax.grad(loss)(p, cfg_fr))(params)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5), g_ref, g_fr
        )
