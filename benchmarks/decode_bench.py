"""Serving-path microbenchmark: prefill tokens/sec vs incremental decode.

CPU-runnable on purpose — serving-perf PRs need a number even while the TPU
relay is down (bench.py measures the training hot path on real hardware; this
measures the SHAPE of the serving hot path, which survives the platform: the
prompt phase is matmul-rich and batched, the decode phase is one
bandwidth-bound step per token, per "Fast Transformer Decoding" (Shazeer,
arXiv:1911.02150)).

    JAX_PLATFORMS=cpu python benchmarks/decode_bench.py

Prints ONE JSON line:

    {"prefill_tokens_per_sec": ..., "decode_tokens_per_sec": ...,
     "decode_steps_per_sec": ..., "prefill_vs_decode": ...,
     "prefill_forward_calls": ...}

``prefill_vs_decode`` is the headline: how many times faster the single-pass
chunked prefill ingests a prompt token than the token-by-token decode loop
does. ``prefill_forward_calls`` pins the structural claim — a 64-token
prompt compiles to ceil(prompt_len / chunk) decoder forwards, not 64
sequential steps. ``--prefix_reuse`` adds the cross-request dimension: a
repeated-system-prompt workload through the continuous scheduler with the
prefix KV cache on vs off, reporting the prompt-token hit rate and the
prefill forwards the trie restore saved (greedy answers asserted identical).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class _IdTok:
    """Tokens ARE ids ("3 17 5" -> [3, 17, 5]): the scheduler needs only
    encode/decode/bos/eos, and a real subword vocab would just blur the
    token accounting the scheduler sweeps report."""

    bos_id, eos_id = 1, 2

    def encode(self, text):
        return [int(t) for t in text.split()]

    def decode(self, toks):
        return " ".join(str(t) for t in toks)


def _system_prompt_requests(rng, vocab: int, prompt_len: int, n: int):
    """The repeated-system-prompt workload both scheduler sweeps serve:
    every request carries one shared system prompt plus a 4-id tail."""
    system = rng.integers(3, vocab - 2, prompt_len)
    return [
        {
            "prompt": " ".join(
                map(str, [*system, *rng.integers(3, vocab - 2, 4)])
            ),
            "max_new": 4,
        }
        for _ in range(n)
    ]


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--prompt_len", type=int, default=64)
    p.add_argument("--decode_steps", type=int, default=32)
    p.add_argument("--chunk", type=int, default=0,
                   help="prefill chunk size (0 = whole prompt in one forward)")
    p.add_argument("--speculate_k", type=str, default="",
                   help="comma-separated speculative lookahead sweep (e.g. "
                        "'2,4'): per k, decode batch-1 speculatively with "
                        "the n-gram drafter and report tokens/s, "
                        "tokens-per-forward, and draft acceptance rate")
    p.add_argument("--prefix_reuse", action="store_true",
                   help="run a repeated-system-prompt workload through the "
                        "continuous scheduler with the cross-request prefix "
                        "cache on vs off, reporting prompt-token hit rate "
                        "and prefill forwards saved")
    p.add_argument("--prefix_requests", type=int, default=16,
                   help="requests in the --prefix_reuse workload (each = "
                        "shared system prompt + small unique tail)")
    p.add_argument("--prefix_block", type=int, default=16,
                   help="prefix-cache block granularity for --prefix_reuse")
    p.add_argument("--kv_layout", type=str, default="",
                   help="comma-separated KV layout sweep ('dense,paged'): "
                        "run the repeated-system-prompt workload through "
                        "the continuous scheduler per layout and report "
                        "tokens/s, predicted peak bytes, KV bytes/slot, "
                        "and max concurrent slots before OOM-by-budget "
                        "(answers asserted byte-identical across layouts)")
    p.add_argument("--decode_kernel", type=str, default="",
                   help="comma-separated decode-kernel sweep "
                        "('xla,paged_flash'): per KV-cache variant "
                        "(bf16/int8/gqa), run the repeated-system-prompt "
                        "workload through the paged continuous scheduler "
                        "with each kernel and report tokens/s plus the cost "
                        "model's predicted_bytes_moved and the kernel "
                        "verifier's predicted_vmem_bytes for the batched "
                        "pool step (answers asserted byte-identical across "
                        "kernels)")
    p.add_argument("--tpu", action="store_true",
                   help="demand real-Pallas (interpret=False) decode-kernel "
                        "rows: on a TPU backend the sweep rows compile the "
                        "kernels for the MXU; anywhere else a "
                        "bench.relay_probe fallback row records that the "
                        "hardware row is still pending while the "
                        "interpret-mode rows ride along")
    p.add_argument("--kv_pool_mb", type=float, default=0.0,
                   help="device-memory budget (MiB) the --kv_layout "
                        "max-slots column is computed against (0 = the "
                        "dense pool's own footprint, so the column reads "
                        "as 'how many more slots fit in the same memory')")
    p.add_argument("--rows_out", type=str, default="",
                   help="append bench_rows.jsonl-compatible rows for the "
                        "--speculate_k / --prefix_reuse sweeps to this file "
                        "('' = print them to stderr; stdout stays one "
                        "summary JSON line)")
    p.add_argument("--metrics_jsonl", type=str, default="",
                   help="append obs telemetry events for the scheduler "
                        "sweeps to this JSONL (each sweep row's final "
                        "metrics.snapshot carries its per-program perf_* "
                        "profiler metrics) — the episode `python -m "
                        "transformer_tpu.obs roofline` replays ('' = no "
                        "event log; the profiler still runs and the "
                        "measured_* columns still populate)")
    p.add_argument("--mesh", type=str, default="",
                   help="comma-separated serving mesh sizes (e.g. '1,2,4'): "
                        "run the repeated-system-prompt workload through a "
                        "--mesh N ContinuousScheduler per size, dense AND "
                        "paged, reporting per-mesh tokens/s + the predicted "
                        "cross-shard collective bytes per decode step "
                        "(answers asserted byte-identical to the unsharded "
                        "scheduler); grows a virtual CPU device platform "
                        "when the host has too few devices")
    p.add_argument("--reps", type=int, default=5,
                   help="timed repetitions (best-of is reported)")
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--d_model", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--dff", type=int, default=512)
    p.add_argument("--vocab", type=int, default=8192)
    args = p.parse_args()

    # The --mesh sweep needs >= max(mesh) devices, and XLA only honours the
    # virtual-device flag if it is in the environment BEFORE jax is imported
    # — so grow XLA_FLAGS here, between argparse and the import below.
    mesh_sizes = [int(x) for x in args.mesh.split(",") if x.strip()]
    if any(m < 1 for m in mesh_sizes):
        p.error("--mesh sizes must be >= 1")
    if mesh_sizes:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={max(mesh_sizes)}"
            ).strip()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from transformer_tpu.config import ModelConfig
    from transformer_tpu.models import transformer_init
    from transformer_tpu.models.decoder import init_decoder_caches
    from transformer_tpu.models.transformer import (
        transformer_decode_step,
        transformer_prefill,
    )

    total = args.prompt_len + args.decode_steps + 1
    cfg = ModelConfig(
        num_layers=args.layers, d_model=args.d_model, num_heads=args.heads,
        dff=args.dff, input_vocab_size=args.vocab, target_vocab_size=args.vocab,
        max_position=total, decoder_only=True, tie_output=True,
        dtype="float32", dropout_rate=0.0,
    )
    dev = jax.devices()[0]
    print(f"decode bench on {dev.platform}:{dev.device_kind}", file=sys.stderr)
    params = transformer_init(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(1, args.vocab - 2, (args.batch, args.prompt_len)),
        jnp.int32,
    )

    calls = [0]
    prefill = jax.jit(
        lambda params, prompt, caches: transformer_prefill(
            params, prompt, None, None, caches, 0, cfg, chunk=args.chunk
        ),
        static_argnames=(),
    )

    # Count the decoder forwards the prefill TRACES to (the structural
    # O(prompt_len / chunk) claim) by intercepting decoder_apply once.
    from transformer_tpu.models import decoder as decoder_mod

    real_apply = decoder_mod.decoder_apply

    def counting_apply(*a, **kw):
        calls[0] += 1
        return real_apply(*a, **kw)

    decoder_mod.decoder_apply = counting_apply
    try:
        caches0 = init_decoder_caches(cfg, args.batch, total)
        logits, caches = prefill(params, prompt, caches0)
        jax.block_until_ready(logits)
    finally:
        decoder_mod.decoder_apply = real_apply
    prefill_calls = calls[0]

    best = float("inf")
    for _ in range(args.reps):
        caches0 = init_decoder_caches(cfg, args.batch, total)
        t0 = time.perf_counter()
        logits, caches = prefill(params, prompt, caches0)
        jax.block_until_ready(logits)
        best = min(best, time.perf_counter() - t0)
    prefill_tok_s = args.batch * args.prompt_len / best

    # Incremental decode: one bandwidth-bound step per token from the
    # prefilled cache (greedy feedback keeps the loop honest — each step
    # consumes the previous step's output, like serving does).
    step = jax.jit(
        lambda params, tok, caches, pos: transformer_decode_step(
            params, tok, None, None, caches, pos, cfg
        )
    )
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
    _, warm = step(params, tok, caches, jnp.int32(args.prompt_len))
    jax.block_until_ready(warm[0]["k"])

    best_dec = float("inf")
    for _ in range(args.reps):
        t, c = tok, caches
        t0 = time.perf_counter()
        for i in range(args.decode_steps):
            logits_i, c = step(params, t, c, jnp.int32(args.prompt_len + i))
            t = jnp.argmax(logits_i, axis=-1).astype(jnp.int32)[:, None]
        jax.block_until_ready(t)
        best_dec = min(best_dec, time.perf_counter() - t0)
    decode_steps_s = args.decode_steps / best_dec
    decode_tok_s = args.batch * args.decode_steps / best_dec

    # Cost-model predictions (analysis/costs.py, abstract trace — no device
    # execution): the decode step's peak live-buffer bytes next to its
    # measured tokens/s, so bench_rows.jsonl ties prediction to measurement
    # and a memory regression shows up in the same file as a speed one.
    from transformer_tpu.analysis.costs import program_costs

    def _costs(fn, *abstract_args, donate_argnums=()):
        return program_costs(
            "bench", fn, *abstract_args, donate_argnums=donate_argnums
        )

    def _predict(fn, *abstract_args, donate_argnums=()):
        return _costs(fn, *abstract_args, donate_argnums=donate_argnums).peak_bytes

    # Measured side of the roofline (obs/profile.py): each scheduler sweep
    # row runs with a FRESH telemetry bundle + profiler (its own registry),
    # so measured_step_p50_ms is that row's own number rather than an
    # aggregate across variants; every bundle appends its final
    # metrics.snapshot to the same --metrics_jsonl, which is exactly the
    # episode `python -m transformer_tpu.obs roofline` joins against the
    # cost model.
    from transformer_tpu.obs import EventLog, Telemetry
    from transformer_tpu.obs.profile import roofline_ratio

    def _sweep_telemetry():
        events = EventLog(args.metrics_jsonl) if args.metrics_jsonl else None
        tel = Telemetry(events=events, interval=1e9)
        tel.arm_profiler()
        return tel

    def _measured_step(tel, program):
        """Pull ``program``'s measured row from the bundle's profiler, then
        close the bundle (forcing the final metrics.snapshot flush)."""
        row = tel.profiler.summary().get(program) or {}
        tel.close()
        return row

    decode_peak = _predict(
        lambda p, t, c, pos: transformer_decode_step(
            p, t, None, None, c, pos, cfg
        ),
        params, tok, caches, jnp.int32(0),
    )

    # ---- speculative decoding sweep (batch-1, n-gram drafter) -------------
    # Headline: tokens emitted per target-model VERIFY forward — the number
    # speculation exists to push past 1.0 (incremental decode's ceiling).
    # The prompt tiles a short motif so prompt-lookup drafting has honest
    # traction (the repetitive-text regime it is built for).
    speculative = []
    ks = [int(x) for x in args.speculate_k.split(",") if x.strip()]
    if ks:
        from transformer_tpu.serve.speculative import (
            NgramDrafter,
            speculative_generate,
        )

        from transformer_tpu.models.transformer import transformer_verify

        motif = rng.integers(1, args.vocab - 2, 8)
        spec_prompt = [int(motif[i % 8]) for i in range(args.prompt_len)]
        for k in ks:
            if k < 1:
                continue
            verify_peak = _predict(
                lambda p, t, c, pos: transformer_verify(p, t, c, pos, cfg),
                params,
                jnp.zeros((1, k + 1), jnp.int32),
                init_decoder_caches(cfg, 1, total),
                jnp.int32(0),
            )
            stats = {}
            toks: list = []
            best_spec = float("inf")
            for _ in range(args.reps):
                t0 = time.perf_counter()
                toks, stats = speculative_generate(
                    params, cfg, spec_prompt, args.decode_steps, eos_id=-1,
                    speculate_k=k, drafter=NgramDrafter(),
                    prefill_chunk=args.chunk,
                )
                best_spec = min(best_spec, time.perf_counter() - t0)
            tpf = len(toks) / max(stats["verify_forwards"], 1)
            acc = stats["accepted"] / max(stats["drafted"], 1)
            speculative.append({
                "k": k,
                "tokens_per_sec": round(len(toks) / best_spec, 1),
                "tokens_per_forward": round(tpf, 3),
                "acceptance_rate": round(acc, 4),
                "verify_forwards": stats["verify_forwards"],
                "new_tokens": len(toks),
                "predicted_peak_bytes": verify_peak,
            })

    # ---- cross-request prefix reuse (continuous scheduler) ----------------
    # Headline: the fraction of prompt tokens served from stored KV blocks
    # instead of a prefill forward, on the workload the prefix cache exists
    # for — every request carrying the same system prompt plus a small
    # unique tail (docs/SERVING.md "Cross-request prefix KV cache").
    prefix = None
    if args.prefix_reuse:
        from transformer_tpu.serve import ContinuousScheduler, PrefixCache
        from transformer_tpu.serve.scheduler import (
            _pool_step,
            abstract_pool_caches,
        )

        pool_peak = _predict(
            lambda p, c, t: _pool_step.__wrapped__(p, c, t, cfg),
            params,
            abstract_pool_caches(cfg, 2, total),
            jnp.zeros((2,), jnp.int32),
            donate_argnums=(1,),  # mirrors _pool_step's jit (and the budget)
        )

        tok = _IdTok()
        reqs = _system_prompt_requests(
            rng, args.vocab, args.prompt_len, args.prefix_requests
        )

        results = {}
        for label, cache in (
            ("off", None),
            ("on", PrefixCache(
                cfg, block_tokens=args.prefix_block, budget_mb=64)),
        ):
            sched = ContinuousScheduler(
                params, cfg, tok, num_slots=2,
                prefill_chunk=args.chunk, prefix_cache=cache,
            )
            t0 = time.perf_counter()
            out = sched.run([dict(r) for r in reqs])
            wall = time.perf_counter() - t0
            assert all("continuation" in r for r in out), out
            results[label] = {
                "answers": [r["continuation"] for r in out],
                "wall_s": wall,
                **{k: sched.stats[k] for k in (
                    "prompt_tokens", "prefix_hit_tokens", "prefill_forwards",
                )},
            }
        assert results["on"]["answers"] == results["off"]["answers"], (
            "prefix cache changed greedy answers"
        )
        on, off = results["on"], results["off"]
        prefix = {
            "requests": args.prefix_requests,
            "system_prompt_tokens": args.prompt_len,
            "block_tokens": args.prefix_block,
            "prompt_tokens": on["prompt_tokens"],
            "prefix_hit_tokens": on["prefix_hit_tokens"],
            "hit_rate": round(
                on["prefix_hit_tokens"] / on["prompt_tokens"], 4
            ),
            "prefill_forwards": on["prefill_forwards"],
            "prefill_forwards_saved": (
                off["prefill_forwards"] - on["prefill_forwards"]
            ),
            "wall_s_on": round(on["wall_s"], 3),
            "wall_s_off": round(off["wall_s"], 3),
            "predicted_peak_bytes": pool_peak,
        }

    # ---- paged vs dense KV layout (continuous scheduler) ------------------
    # Headline: KV bytes/slot and max concurrent slots under one device
    # budget — the paged pool bounds resident KV by USED tokens, so the
    # same memory admits more slots; answers are byte-identical either
    # way (asserted) and tokens/s rides along for the CPU shape check.
    kv_layouts = [x.strip() for x in args.kv_layout.split(",") if x.strip()]
    layout_rows = []
    if kv_layouts:
        from transformer_tpu.analysis.costs import kv_cache_bytes, kv_pool_bytes
        from transformer_tpu.serve import ContinuousScheduler
        from transformer_tpu.serve.scheduler import (
            _pool_step,
            _pool_step_paged,
            abstract_paged_pool,
            abstract_pool_caches,
        )

        ltok = _IdTok()
        lreqs = _system_prompt_requests(
            np.random.default_rng(1), args.vocab, args.prompt_len,
            args.prefix_requests,
        )
        slots = 2
        block = args.prefix_block
        used_tokens = args.prompt_len + 4 + 4 + 1  # prompt + tail + gen + bos
        used_blocks = -(-used_tokens // block)
        # Serving provisions max_total for the WORST-case request (4x this
        # workload's typical length here); dense reserves that many rows
        # per slot up front, paged pays only for the blocks a request
        # actually touches — exactly the waste the cost model prices.
        serve_total = 4 * total
        slot_blocks = -(-serve_total // block)
        dense_kv = kv_cache_bytes(cfg, serve_total)
        budget_bytes = (
            args.kv_pool_mb * (1 << 20)
            if args.kv_pool_mb
            else slots * dense_kv["bytes_per_slot"]
        )
        answers = {}
        for layout in kv_layouts:
            ltel = _sweep_telemetry()
            sched = ContinuousScheduler(
                params, cfg, ltok, num_slots=slots,
                prefill_chunk=args.chunk, kv_layout=layout, kv_block=block,
                max_total=serve_total, telemetry=ltel,
            )
            t0 = time.perf_counter()
            out = sched.run([dict(r) for r in lreqs])
            wall = time.perf_counter() - t0
            assert all("continuation" in r for r in out), out
            answers[layout] = [r["continuation"] for r in out]
            new_tokens = sum(
                len(ltok.encode(r["continuation"])) for r in out
            )
            if layout == "paged":
                pool_blocks = 1 + slots * slot_blocks
                kv = kv_pool_bytes(cfg, serve_total, slots, pool_blocks, block)
                raw = _costs(
                    lambda p, c, tb, ix, t: _pool_step_paged.__wrapped__(
                        p, c, tb, ix, t, cfg, block, serve_total
                    ),
                    params,
                    *abstract_paged_pool(
                        cfg, slots, serve_total, pool_blocks, block
                    ),
                    jnp.zeros((slots,), jnp.int32),
                    donate_argnums=(1,),
                )
                # Paged residency is per USED block: one slot costs
                # used_blocks x block-bytes (+ its table row) — the
                # budget admits proportionally more concurrent slots.
                block_bytes = kv["pool_bytes"] / max(1, kv["pool_blocks"])
                max_slots = int(budget_bytes // (used_blocks * block_bytes))
                bytes_per_slot = int(used_blocks * block_bytes)
            else:
                raw = _costs(
                    lambda p, c, t: _pool_step.__wrapped__(p, c, t, cfg),
                    params,
                    abstract_pool_caches(cfg, slots, serve_total),
                    jnp.zeros((slots,), jnp.int32),
                    donate_argnums=(1,),
                )
                max_slots = int(budget_bytes // dense_kv["bytes_per_slot"])
                bytes_per_slot = dense_kv["bytes_per_slot"]
            step_prog = (
                "serve.pool_step_paged" if layout == "paged"
                else "serve.pool_step"
            )
            measured = _measured_step(ltel, step_prog)
            step_p50_ms = measured.get("p50_ms")
            step_ratio = roofline_ratio(
                raw.bytes_moved, measured.get("p50_s") or 0.0
            )
            assert step_p50_ms, (
                f"kv_layout={layout}: no measured {step_prog} dispatches — "
                "the profiler should have clocked every pool step"
            )
            assert step_ratio, (
                f"kv_layout={layout}: roofline_ratio missing "
                f"(bytes_moved={raw.bytes_moved}, measured={measured})"
            )
            layout_rows.append({
                "kv_layout": layout,
                "tokens_per_sec": round(new_tokens / wall, 1) if wall else None,
                "wall_s": round(wall, 3),
                "predicted_peak_bytes": raw.peak_bytes,
                "predicted_bytes_moved": raw.bytes_moved,
                "measured_step_p50_ms": step_p50_ms,
                "roofline_ratio": step_ratio,
                "kv_bytes_per_slot": bytes_per_slot,
                "max_slots_in_budget": max_slots,
                "budget_bytes": int(budget_bytes),
                "used_tokens_per_slot": used_tokens,
            })
        first = kv_layouts[0]
        for layout in kv_layouts[1:]:
            assert answers[layout] == answers[first], (
                f"kv_layout={layout} changed answers vs {first}"
            )

    # ---- decode kernel sweep (paged continuous scheduler) -----------------
    # Headline: tokens/s per kernel next to the cost model's
    # predicted_bytes_moved for the batched pool step — the fused
    # paged_flash path exists to cut the gathered-view HBM pass, so the
    # prediction that justifies it lands in the same row as the
    # measurement. On CPU the kernels run in Pallas interpret mode (shape
    # check, not a speed claim); --tpu marks the interpret=False rows that
    # light up when the relay returns.
    kernels = [x.strip() for x in args.decode_kernel.split(",") if x.strip()]
    if args.tpu and not kernels:
        kernels = ["xla", "paged_flash"]
    kernel_rows = []
    relay_row = None
    if kernels:
        from transformer_tpu.serve import ContinuousScheduler
        from transformer_tpu.serve.scheduler import (
            _pool_step_paged,
            _pool_step_paged_flash,
            abstract_paged_pool,
        )

        on_tpu = dev.platform == "tpu"
        if args.tpu and not on_tpu:
            # Same contract as bench.py's banked-row fallback: the pending
            # hardware measurement is recorded as an explicit probe row
            # instead of silently missing from the round's diff.
            relay_row = {
                "metric": "bench.relay_probe",
                "value": None,
                "unit": "row",
                "config": {
                    "pending_metric": "decode kernel tokens/s",
                    "decode_kernel": kernels,
                    "kv_layout": "paged",
                    "interpret": False,
                },
                "stale_reason": "TPU backend unavailable (relay down); "
                                "real-Pallas decode-kernel rows pending",
                "device": f"{dev.platform}:{dev.device_kind}",
                "vs_baseline": None,
            }
        cache_variants = {
            "bf16": {},
            "int8": {"kv_cache_int8": True},
            "gqa": {"num_kv_heads": max(1, args.heads // 2)},
        }
        kslots = 2
        kblock = args.prefix_block
        kreqs = _system_prompt_requests(
            np.random.default_rng(2), args.vocab, args.prompt_len,
            args.prefix_requests,
        )
        ktok = _IdTok()
        # Workload rows per slot: bos + system prompt + 4-id tail + 4
        # generated; pad so tiny smoke configs never trip the prompt-length
        # validator.
        ktotal = max(total, args.prompt_len + 16)
        slot_blocks = -(-ktotal // kblock)
        pool_blocks = 1 + kslots * slot_blocks
        for vname, overrides in cache_variants.items():
            vcfg = ModelConfig(
                num_layers=args.layers, d_model=args.d_model,
                num_heads=args.heads, dff=args.dff,
                input_vocab_size=args.vocab, target_vocab_size=args.vocab,
                max_position=ktotal, decoder_only=True, tie_output=True,
                dtype="bfloat16", dropout_rate=0.0, **overrides,
            )
            vparams = transformer_init(jax.random.PRNGKey(0), vcfg)
            vanswers = {}
            for kernel in kernels:
                ktel = _sweep_telemetry()
                sched = ContinuousScheduler(
                    vparams, vcfg, ktok, num_slots=kslots,
                    prefill_chunk=args.chunk, kv_layout="paged",
                    kv_block=kblock, max_total=ktotal, decode_kernel=kernel,
                    telemetry=ktel,
                )
                t0 = time.perf_counter()
                out = sched.run([dict(r) for r in kreqs])
                wall = time.perf_counter() - t0
                assert all("continuation" in r for r in out), out
                vanswers[kernel] = [r["continuation"] for r in out]
                new_tokens = sum(
                    len(ktok.encode(r["continuation"])) for r in out
                )
                kernel_vmem = {}
                if kernel == "paged_flash":
                    step_fn = lambda p, c, tb, ix, t, vcfg=vcfg: (  # noqa: E731
                        _pool_step_paged_flash.__wrapped__(
                            p, c, tb, ix, t, vcfg, kblock, False
                        )
                    )
                    step_args = (
                        vparams,
                        *abstract_paged_pool(
                            vcfg, kslots, ktotal, pool_blocks, kblock
                        ),
                        jnp.zeros((kslots,), jnp.int32),
                    )
                    raw = _costs(step_fn, *step_args, donate_argnums=(1,))
                    # The verifier's per-grid-step VMEM model for each
                    # Pallas kernel in the step; kernels run sequentially,
                    # so the program's kernel-VMEM high-water mark is the
                    # max, not the sum.
                    from transformer_tpu.analysis.kernels import (
                        program_kernel_vmem,
                    )

                    kernel_vmem = program_kernel_vmem(step_fn, *step_args)
                else:
                    raw = _costs(
                        lambda p, c, tb, ix, t, vcfg=vcfg: (
                            _pool_step_paged.__wrapped__(
                                p, c, tb, ix, t, vcfg, kblock, ktotal
                            )
                        ),
                        vparams,
                        *abstract_paged_pool(
                            vcfg, kslots, ktotal, pool_blocks, kblock
                        ),
                        jnp.zeros((kslots,), jnp.int32),
                        donate_argnums=(1,),
                    )
                step_prog = (
                    "serve.pool_step_paged_flash" if kernel == "paged_flash"
                    else "serve.pool_step_paged"
                )
                measured = _measured_step(ktel, step_prog)
                step_p50_ms = measured.get("p50_ms")
                step_ratio = roofline_ratio(
                    raw.bytes_moved, measured.get("p50_s") or 0.0
                )
                assert step_p50_ms, (
                    f"{vname}/{kernel}: no measured {step_prog} dispatches"
                )
                assert step_ratio, (
                    f"{vname}/{kernel}: roofline_ratio missing "
                    f"(bytes_moved={raw.bytes_moved}, measured={measured})"
                )
                kernel_rows.append({
                    "cache_variant": vname,
                    "decode_kernel": kernel,
                    "tokens_per_sec": (
                        round(new_tokens / wall, 1) if wall else None
                    ),
                    "wall_s": round(wall, 3),
                    "predicted_bytes_moved": raw.bytes_moved,
                    "predicted_peak_bytes": raw.peak_bytes,
                    "measured_step_p50_ms": step_p50_ms,
                    "roofline_ratio": step_ratio,
                    "predicted_vmem_bytes": (
                        max(kernel_vmem.values()) if kernel_vmem else 0
                    ),
                    "predicted_vmem_by_kernel": kernel_vmem,
                    "interpret": kernel == "paged_flash" and not on_tpu,
                })
                if kernel_vmem:
                    per = ", ".join(
                        f"{k}={v}" for k, v in sorted(kernel_vmem.items())
                    )
                    print(
                        f"[decode_bench] {vname}/{kernel}: "
                        f"predicted_vmem_bytes={max(kernel_vmem.values())} "
                        f"({per})",
                        file=sys.stderr,
                    )
            base = kernels[0]
            for kernel in kernels[1:]:
                assert vanswers[kernel] == vanswers[base], (
                    f"decode_kernel={kernel} changed answers vs {base} "
                    f"({vname})"
                )

    # ---- sharded replica sweep (--mesh) -----------------------------------
    # One replica = one multi-device pjit program (serve/sharded.py): params
    # replicated over a 1-D "data" mesh, pool KV sharded on its leading
    # storage axis (dense: slot rows, paged: block rows).  Per mesh size the
    # row pairs measured tokens/s with the layout's PREDICTED cross-shard
    # collective bytes per decode step: dense is collective-free by
    # construction (the compiled-HLO gate in analysis/sharding.py enforces
    # it), and paged pays for the gathered-view rows that live on other
    # shards — view_bytes * (m - 1) / m.  Answers are asserted byte-identical
    # to the unsharded scheduler per layout, greedy AND seeded-sampled.
    mesh_rows = []
    if mesh_sizes:
        from transformer_tpu.analysis.costs import kv_cache_bytes
        from transformer_tpu.serve import ContinuousScheduler

        assert jax.device_count() >= max(mesh_sizes), (
            f"--mesh {max(mesh_sizes)} needs >= that many devices, got "
            f"{jax.device_count()} — the XLA_FLAGS bootstrap above only "
            "works if no conflicting xla_force_host_platform_device_count "
            "was already set"
        )
        mtok = _IdTok()
        mreqs = _system_prompt_requests(
            np.random.default_rng(2), args.vocab, args.prompt_len, 8
        )
        msampled = 0
        for i, r in enumerate(mreqs):
            r["max_new"] = args.decode_steps
            if i % 3 == 2:
                r.update(temperature=0.8, top_k=8, seed=1000 + i)
                msampled += 1
        mslots = 4  # divisible by every mesh size the sweep targets (1/2/4)
        m_total = args.prompt_len + 4 + 1 + args.decode_steps
        view_bytes = mslots * kv_cache_bytes(cfg, m_total)["bytes_per_slot"]
        for layout in ("dense", "paged"):
            want = None
            for m in [None, *mesh_sizes]:
                sched = ContinuousScheduler(
                    params, cfg, mtok, num_slots=mslots,
                    prefill_chunk=args.chunk, kv_layout=layout,
                    kv_block=args.prefix_block, max_total=m_total,
                    mesh=m,
                )
                t0 = time.perf_counter()
                out = sched.run([dict(r) for r in mreqs])
                wall = time.perf_counter() - t0
                assert all("continuation" in r for r in out), out
                got = [r["continuation"] for r in out]
                if m is None:
                    want = got
                    continue
                assert got == want, (
                    f"mesh={m} ({layout}) changed answers vs the unsharded "
                    "scheduler"
                )
                new_tokens = sum(len(mtok.encode(c)) for c in got)
                mesh_rows.append({
                    "mesh": f"data={m}",
                    "kv_layout": layout,
                    "tokens_per_sec": (
                        round(new_tokens / wall, 1) if wall else None
                    ),
                    "wall_s": round(wall, 3),
                    "predicted_collective_bytes_per_step": (
                        0 if layout == "dense"
                        else int(view_bytes * (m - 1) / m)
                    ),
                    "byte_parity": True,
                    "slots": mslots,
                    "requests": len(mreqs),
                    "sampled_requests": msampled,
                })

    print(json.dumps({
        "prefill_tokens_per_sec": round(prefill_tok_s, 1),
        "decode_tokens_per_sec": round(decode_tok_s, 1),
        "decode_steps_per_sec": round(decode_steps_s, 1),
        "prefill_vs_decode": round(prefill_tok_s / decode_tok_s, 2),
        "prefill_forward_calls": prefill_calls,
        "predicted_peak_bytes": decode_peak,
        "batch": args.batch,
        "prompt_len": args.prompt_len,
        "decode_steps": args.decode_steps,
        "chunk": args.chunk,
        "device": f"{dev.platform}:{dev.device_kind}",
        **({"speculative": speculative} if speculative else {}),
        **({"prefix_reuse": prefix} if prefix else {}),
        **({"kv_layouts": layout_rows} if layout_rows else {}),
        **({"decode_kernels": kernel_rows} if kernel_rows else {}),
        **({"mesh_sweep": mesh_rows} if mesh_rows else {}),
    }))

    if kernel_rows or relay_row:
        rows = [
            json.dumps({
                "metric": "decode kernel tokens/s",
                "value": r["tokens_per_sec"],
                "unit": "tokens/sec",
                "config": {
                    "layers": args.layers, "d_model": args.d_model,
                    "heads": args.heads, "dff": args.dff,
                    "prompt_len": args.prompt_len,
                    "cache_variant": r["cache_variant"],
                    "decode_kernel": r["decode_kernel"],
                    "kv_layout": "paged",
                    "block_tokens": args.prefix_block,
                    "interpret": r["interpret"],
                },
                "predicted_bytes_moved": r["predicted_bytes_moved"],
                "predicted_peak_bytes": r["predicted_peak_bytes"],
                "predicted_vmem_bytes": r["predicted_vmem_bytes"],
                "measured_step_p50_ms": r["measured_step_p50_ms"],
                "roofline_ratio": r["roofline_ratio"],
                "device": f"{dev.platform}:{dev.device_kind}",
                "vs_baseline": None,
            })
            for r in kernel_rows
        ]
        if relay_row is not None:
            rows.append(json.dumps(relay_row))
        if args.rows_out:
            with open(args.rows_out, "a", encoding="utf-8") as f:
                f.write("\n".join(rows) + "\n")
        else:
            for row in rows:
                print(row, file=sys.stderr)

    if layout_rows:
        rows = [
            json.dumps({
                "metric": "kv layout max concurrent slots in budget",
                "value": r["max_slots_in_budget"],
                "unit": "slots",
                "config": {
                    "layers": args.layers, "d_model": args.d_model,
                    "heads": args.heads, "dff": args.dff,
                    "prompt_len": args.prompt_len,
                    "kv_layout": r["kv_layout"],
                    "block_tokens": args.prefix_block,
                    "budget_bytes": r["budget_bytes"],
                },
                "tokens_per_sec": r["tokens_per_sec"],
                "kv_bytes_per_slot": r["kv_bytes_per_slot"],
                "predicted_peak_bytes": r["predicted_peak_bytes"],
                "predicted_bytes_moved": r["predicted_bytes_moved"],
                "measured_step_p50_ms": r["measured_step_p50_ms"],
                "roofline_ratio": r["roofline_ratio"],
                "device": f"{dev.platform}:{dev.device_kind}",
                "vs_baseline": None,
            })
            for r in layout_rows
        ]
        if args.rows_out:
            with open(args.rows_out, "a", encoding="utf-8") as f:
                f.write("\n".join(rows) + "\n")
        else:
            for row in rows:
                print(row, file=sys.stderr)

    if prefix:
        row = json.dumps({
            "metric": "prefix cache prompt-token hit rate",
            "value": prefix["hit_rate"],
            "unit": "fraction",
            "config": {
                "layers": args.layers, "d_model": args.d_model,
                "heads": args.heads, "dff": args.dff,
                "prompt_len": args.prompt_len,
                "requests": args.prefix_requests,
                "block_tokens": args.prefix_block,
                "chunk": args.chunk,
            },
            "prefill_forwards_saved": prefix["prefill_forwards_saved"],
            "prefix_hit_tokens": prefix["prefix_hit_tokens"],
            "predicted_peak_bytes": prefix["predicted_peak_bytes"],
            "device": f"{dev.platform}:{dev.device_kind}",
            "vs_baseline": None,
        })
        if args.rows_out:
            with open(args.rows_out, "a", encoding="utf-8") as f:
                f.write(row + "\n")
        else:
            print(row, file=sys.stderr)

    if speculative:
        # bench_rows.jsonl-compatible rows: one per sweep point, so rounds
        # can diff speculative throughput like any other bench metric.
        rows = [
            json.dumps({
                "metric": "speculative decode tokens-per-forward",
                "value": s["tokens_per_forward"],
                "unit": "tokens/forward",
                "config": {
                    "layers": args.layers, "d_model": args.d_model,
                    "heads": args.heads, "dff": args.dff,
                    "prompt_len": args.prompt_len,
                    "decode_steps": args.decode_steps,
                    "speculate_k": s["k"], "drafter": "ngram",
                },
                "tokens_per_sec": s["tokens_per_sec"],
                "acceptance_rate": s["acceptance_rate"],
                "predicted_peak_bytes": s["predicted_peak_bytes"],
                "device": f"{dev.platform}:{dev.device_kind}",
                "vs_baseline": None,
            })
            for s in speculative
        ]
        if args.rows_out:
            with open(args.rows_out, "a", encoding="utf-8") as f:
                f.write("\n".join(rows) + "\n")
        else:
            for row in rows:
                print(row, file=sys.stderr)

    if mesh_rows:
        rows = [
            json.dumps({
                "metric": "sharded decode tokens/s",
                "value": r["tokens_per_sec"],
                "unit": "tokens/sec",
                "config": {
                    "layers": args.layers, "d_model": args.d_model,
                    "heads": args.heads, "dff": args.dff,
                    "prompt_len": args.prompt_len,
                    "decode_steps": args.decode_steps,
                    "mesh": r["mesh"],
                    "kv_layout": r["kv_layout"],
                    "slots": r["slots"],
                    "requests": r["requests"],
                    "sampled_requests": r["sampled_requests"],
                },
                "predicted_collective_bytes_per_step": (
                    r["predicted_collective_bytes_per_step"]
                ),
                "byte_parity": r["byte_parity"],
                "wall_s": r["wall_s"],
                "device": f"{dev.platform}:{dev.device_kind}",
                "vs_baseline": None,
            })
            for r in mesh_rows
        ]
        if args.rows_out:
            with open(args.rows_out, "a", encoding="utf-8") as f:
                f.write("\n".join(rows) + "\n")
        else:
            for row in rows:
                print(row, file=sys.stderr)


if __name__ == "__main__":
    main()
