#!/bin/bash
# One healthy-window capture sequence: quick atomic rows first, then hand
# off to the watchdog (BLEU passes + extras). Run from repo root.
cd "$(dirname "$0")/.." || exit 1
trap 'rm -f .tpu_busy' EXIT
log() { echo "$(date +%F_%T) $*" >>watch_tpu.log; }
log "capture_window: starting (rows+attr first, then watchdog)"
for c in big tied long4k; do
  grep -q "\"metric\": \"$c train throughput\", \"value\"" bench_rows.jsonl 2>/dev/null && continue
  ss -tln | grep -q ':8082 ' || { log "relay down before $c; aborting to watchdog"; break; }
  touch .tpu_busy
  log "row: $c"
  timeout 2400 python benchmarks/run.py --configs "$c" >>bench_rows.jsonl 2>>bench_run.err
  rc=$?
  [ "$rc" -ne 0 ] && echo "{\"metric\": \"$c train throughput\", \"error\": \"capture: rc=$rc\"}" >>bench_rows.jsonl
  log "row $c done rc=$rc"
  rm -f .tpu_busy
done
for m in fwd smallvocab; do
  grep -q "\"metric\": \"base train throughput \\[$m\\]\", \"value\"" bench_attr.jsonl 2>/dev/null && continue
  ss -tln | grep -q ':8082 ' || break
  touch .tpu_busy
  log "attr: $m"
  timeout 2400 python benchmarks/run.py --configs base --modes "$m" >>bench_attr.jsonl 2>>bench_run.err
  rc=$?
  [ "$rc" -ne 0 ] && echo "{\"metric\": \"base train throughput [$m]\", \"error\": \"capture: rc=$rc\"}" >>bench_attr.jsonl
  log "attr $m done rc=$rc"
  rm -f .tpu_busy
done
log "capture_window: handing off to watchdog"
exec bash benchmarks/watch_and_run.sh
