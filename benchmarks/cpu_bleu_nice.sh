#!/bin/bash
# Run a CPU-fallback BLEU convergence run that YIELDS the single host core
# to TPU measurements: while the watchdog holds .tpu_busy, the training
# process is SIGSTOPped (a paused trainer cannot skew TPU timing loops on
# this 1-core host). CAVEAT: bleu_run's published train_seconds is
# wall-clock, so pause time inflates it — total paused seconds are logged
# to the err file for correction. Resumable like every bleu_run
# invocation. Usage: benchmarks/cpu_bleu_nice.sh <config> <epochs> <out> <err>
cd "$(dirname "$0")/.." || exit 1
CFG=${1:-medium}; EPOCHS=${2:-60}; OUT=${3:-bleu_${CFG}_ls_cpu.jsonl}; ERR=${4:-bleu_${CFG}_ls.err}
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  nice -n 10 python benchmarks/bleu_run.py --config "$CFG" --epochs "$EPOCHS" \
  --vocab 8192 --dtype float32 --warmup 1000 --label_smoothing 0.1 \
  --bleu_every 10 >>"$OUT" 2>>"$ERR" &
PID=$!
# Never leave the trainer orphaned in stopped state: a SIGSTOPped process
# cannot even receive SIGTERM until continued.
trap 'kill -CONT "$PID" 2>/dev/null' EXIT INT TERM
echo "bleu $CFG run pid $PID" >>"$ERR"
STOPPED=0
PAUSED_S=0
while kill -0 "$PID" 2>/dev/null; do
  if [ -e .tpu_busy ] && [ "$STOPPED" = 0 ]; then
    kill -STOP "$PID"; STOPPED=1
  elif [ ! -e .tpu_busy ] && [ "$STOPPED" = 1 ]; then
    kill -CONT "$PID"; STOPPED=0
  fi
  [ "$STOPPED" = 1 ] && PAUSED_S=$((PAUSED_S + 15))
  sleep 15
done
wait "$PID"
echo "bleu $CFG run exited rc=$? (paused ~${PAUSED_S}s total; subtract from train_seconds)" >>"$ERR"
