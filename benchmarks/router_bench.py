"""Multi-replica router microbenchmark: the ROADMAP scale-out numbers.

CPU-runnable (the relay-down policy decode_bench.py set): a
repeated-system-prompt workload — every request carries one of a few
shared system prompts plus a small unique tail — through the real
subprocess serving tier (``cli.router``'s building blocks: one
``serve/router.py`` Router over N ``serve/replica.py`` workers), swept
across 1/2/4 replicas.

    JAX_PLATFORMS=cpu python benchmarks/router_bench.py

Prints ONE summary JSON line per replica count and appends
``bench_rows.jsonl``-compatible rows (``--rows_out``) carrying the
acceptance numbers:

- **router p99 queue latency** (submit -> first dispatch) — the router
  must not become the serialization point as replicas multiply;
- **per-replica prefix hit rate** — prefix-affinity dispatch is what
  keeps the per-replica ``PrefixCache`` warm, so the hit rate should
  survive scale-out instead of diluting 1/N;
- **redispatch count** — with ``--kill`` (default when replicas > 1) one
  replica is SIGKILLed mid-workload: every accepted request must still
  answer (zero loss), and the row pins how many rode the failover path.
- **time-to-heal** — with ``--heal`` (default) an extra soak runs the
  2-replica fleet under a Supervisor, SIGKILLs one replica mid-run, and
  rows the death-to-readmission seconds plus how many requests the
  surviving fleet answered during the gap (the self-healing tier's
  acceptance numbers, docs/SERVING.md "Self-healing fleet").
- **time-to-upgrade** — with ``--upgrade`` (default) another soak rolls a
  manifest-verified checkpoint swap across the fleet MID-RUN (quiesce ->
  double-buffered swap -> canary window -> promote): the row records the
  rollout wall time, requests served during it, the canary's request
  share, and zero lost/errored requests (the live-weights control
  plane's acceptance numbers, docs/SERVING.md "Live-weights rollout").
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPEC = {
    "config": {
        "num_layers": 2, "d_model": 32, "num_heads": 2, "dff": 64,
        "max_position": 96, "decoder_only": True, "tie_output": True,
        "dtype": "float32", "dropout_rate": 0.0,
    },
    "seed": 0,
    "corpus": ["ab cd ef gh ij kl mn op qr st uv wx"] * 3,
    "target_vocab_size": 300,
}
WORDS = SPEC["corpus"][0].split()


def _workload(n_requests: int, n_systems: int, system_words: int):
    """Repeated-system-prompt requests: request i carries system prompt
    ``i % n_systems`` plus a 2-word unique-ish tail."""
    reqs = []
    for i in range(n_requests):
        s = i % n_systems
        system = " ".join(
            WORDS[(s + j) % len(WORDS)] for j in range(system_words)
        )
        tail = f"{WORDS[i % len(WORDS)]} {WORDS[(i * 5 + 1) % len(WORDS)]}"
        reqs.append({"prompt": f"{system} {tail}", "max_new": 4})
    return reqs


def _p(q: list[float], frac: float) -> float:
    if not q:
        return 0.0
    s = sorted(q)
    return s[min(len(s) - 1, int(frac * len(s)))]


_STEP_COST_CACHE: dict = {}


def _pool_step_bytes(kv_layout: str, slots: int, kv_block: int) -> int:
    """Cost-model bytes_moved for the replicas' batched pool step — the
    program the workers' profilers clock — on the SPEC model at replica
    defaults (max_total = max_position + 1, full paged provisioning), so
    the measured p50 and the prediction describe the same dispatch."""
    key = (kv_layout, slots, kv_block)
    if key in _STEP_COST_CACHE:
        return _STEP_COST_CACHE[key]
    import jax.numpy as jnp

    from transformer_tpu.analysis.costs import program_costs
    from transformer_tpu.serve.replica import build_model_from_spec

    params, cfg, _ = build_model_from_spec(SPEC)
    max_total = cfg.max_position + 1
    if kv_layout == "paged":
        from transformer_tpu.serve.scheduler import (
            _pool_step_paged,
            abstract_paged_pool,
        )

        slot_blocks = -(-max_total // kv_block)
        pool_blocks = 1 + slots * slot_blocks
        raw = program_costs(
            "bench",
            lambda p, c, tb, ix, t: _pool_step_paged.__wrapped__(
                p, c, tb, ix, t, cfg, kv_block, max_total
            ),
            params,
            *abstract_paged_pool(
                cfg, slots, max_total, pool_blocks, kv_block
            ),
            jnp.zeros((slots,), jnp.int32),
            donate_argnums=(1,),
        )
    else:
        from transformer_tpu.serve.scheduler import (
            _pool_step,
            abstract_pool_caches,
        )

        raw = program_costs(
            "bench",
            lambda p, c, t: _pool_step.__wrapped__(p, c, t, cfg),
            params,
            abstract_pool_caches(cfg, slots, max_total),
            jnp.zeros((slots,), jnp.int32),
            donate_argnums=(1,),
        )
    _STEP_COST_CACHE[key] = raw.bytes_moved
    return raw.bytes_moved


def run_sweep(n_replicas: int, args, spec_path: str) -> dict:
    from transformer_tpu.serve.replica import build_model_from_spec
    from transformer_tpu.serve.router import ReplicaProcess, Router

    _, _, tok = build_model_from_spec(SPEC)
    worker = [
        "--model_spec", spec_path,
        "--serve_slots", str(args.slots),
        "--prefix_cache_mb", "32",
        "--prefix_block", str(args.prefix_block),
        "--kv_layout", getattr(args, "kv_layout", "dense"),
        "--heartbeat_ms", "100",
    ]
    # Per-replica metrics JSONL: arms each worker's profiler (+ flight
    # recorder), so the shutdown report carries the measured per-program
    # perf rows the roofline columns join against.
    obs_dir = tempfile.mkdtemp(prefix="router_bench_obs_")
    links = [
        ReplicaProcess.spawn(
            i,
            worker + [
                "--metrics_jsonl", os.path.join(obs_dir, f"replica{i}.jsonl"),
            ],
        )
        for i in range(n_replicas)
    ]
    router = Router(
        links, encode=tok.encode, bos_id=tok.bos_id,
        affinity_block=args.prefix_block, heartbeat_timeout_s=10.0,
    )
    for link in links:
        link.start_reader(router.inbox)

    reqs = _workload(args.requests, max(1, n_replicas), args.system_words)
    kill = args.kill and n_replicas > 1
    t0 = time.perf_counter()
    for r in reqs:
        router.submit(dict(r))
    answered = []
    killed = False
    deadline = time.time() + 300
    while router.busy and time.time() < deadline:
        router.pump()
        answered.extend(router.drain_ready())
        if kill and not killed and len(answered) >= args.requests // 4:
            victim = max(router.links, key=lambda l: l.inflight)
            if victim.inflight > 0:
                os.kill(victim.pid(), signal.SIGKILL)
                killed = True
    answered.extend(router.drain_ready())
    wall = time.perf_counter() - t0
    ok = sum(1 for a in answered if "continuation" in a)

    # Per-replica prefix accounting from the workers' shutdown reports.
    for link in router.links:
        if not link.dead:
            try:
                link.send({"type": "shutdown"})
            except (OSError, ValueError):
                pass
    stats_deadline = time.time() + 15
    while time.time() < stats_deadline and any(
        l.final_stats is None and not l.dead for l in router.links
    ):
        router.pump(timeout=0.05)
    per_replica = {}
    for link in router.links:
        st = link.final_stats or {}
        prompt = int(st.get("prompt_tokens", 0))
        hit = int(st.get("prefix_hit_tokens", 0))
        per_replica[link.name] = {
            "requests": link.answered,
            "prefix_hit_rate": round(hit / prompt, 4) if prompt else None,
            "prefill_forwards": st.get("prefill_forwards"),
            # Paged workers (--kv_layout paged): hit tokens restored by
            # device-side block-table ALIASING (zero host copies) vs
            # through a host block write.
            "prefix_alias_tokens": st.get("prefix_alias_tokens"),
            "host_restored_tokens": st.get("host_restored_tokens"),
            "killed": link.dead,
        }
    # Measured-vs-predicted roofline for the batched pool step, from the
    # workers' final perf reports (median p50 across the surviving
    # replicas) joined against the cost model's bytes_moved.
    from transformer_tpu.obs.profile import roofline_ratio

    step_prog = (
        "serve.pool_step_paged" if args.kv_layout == "paged"
        else "serve.pool_step"
    )
    step_p50s = []
    for link in router.links:
        perf = (link.final_perf or {}).get(step_prog) or {}
        per_replica[link.name]["measured_step_p50_ms"] = perf.get("p50_ms")
        if perf.get("p50_s"):
            step_p50s.append(perf["p50_s"])
    step_bytes = _pool_step_bytes(args.kv_layout, args.slots, args.prefix_block)
    step_p50_s = (
        sorted(step_p50s)[len(step_p50s) // 2] if step_p50s else None
    )
    router.shutdown()
    return {
        "replicas": n_replicas,
        "requests": len(reqs),
        "answered": len(answered),
        "answered_ok": ok,
        "wall_s": round(wall, 3),
        "requests_per_sec": round(len(reqs) / wall, 2),
        "queue_p50_s": round(_p(router.queue_latencies, 0.50), 6),
        "queue_p99_s": round(_p(router.queue_latencies, 0.99), 6),
        "redispatch_count": router.stats["redispatched"],
        "failovers": router.stats["failovers"],
        "killed_one": killed,
        "predicted_bytes_moved": step_bytes,
        "measured_step_p50_ms": (
            round(step_p50_s * 1e3, 6) if step_p50_s else None
        ),
        "roofline_ratio": roofline_ratio(step_bytes, step_p50_s or 0.0),
        "per_replica": per_replica,
    }


def run_mesh_parity(args, spec_path: str) -> dict:
    """Sharded-replica byte-parity soak (serve/sharded.py, ``--mesh``):
    the SAME workload — greedy AND seeded-sampled requests — through
    single-replica fleets at mesh 1/2/4 must answer byte-identically to
    an UNSHARDED replica. Each worker grows its own virtual CPU platform
    from ``--mesh`` (replica.py appends xla_force_host_platform_device_count
    before importing jax), so the sweep runs on any host."""
    from transformer_tpu.serve.replica import build_model_from_spec
    from transformer_tpu.serve.router import ReplicaProcess, Router

    _, _, tok = build_model_from_spec(SPEC)
    reqs = _workload(16, 2, args.system_words)
    for i, r in enumerate(reqs):
        if i % 3 == 0:  # every third request is seeded-sampled
            r.update(temperature=0.8, top_k=8, seed=i)
    slots = 4  # divides every mesh in the sweep

    def serve(mesh):
        worker = [
            "--model_spec", spec_path,
            "--serve_slots", str(slots),
            "--heartbeat_ms", "100",
        ]
        if mesh:
            worker += ["--mesh", str(mesh)]
        link = ReplicaProcess.spawn(0, worker)
        router = Router(
            [link], encode=tok.encode, bos_id=tok.bos_id,
            heartbeat_timeout_s=30.0,
        )
        link.start_reader(router.inbox)
        t0 = time.perf_counter()
        out = router.run([dict(r) for r in reqs])
        wall = time.perf_counter() - t0
        reported = link.mesh
        router.shutdown()
        return [o.get("continuation") for o in out], wall, reported

    want, _, base_mesh = serve(None)
    assert base_mesh is None and all(c is not None for c in want), want
    meshes = {}
    for mesh in (1, 2, 4):
        got, wall, reported = serve(mesh)
        assert got == want, (
            f"mesh={mesh} answers diverged from the unsharded replica"
        )
        assert reported == f"data={mesh}", (
            f"replica announced mesh {reported!r}, expected data={mesh}"
        )
        meshes[str(mesh)] = {
            "mesh": f"data={mesh}",
            "wall_s": round(wall, 3),
            "requests_per_sec": round(len(reqs) / wall, 2),
            "byte_parity": True,
        }
    return {
        "requests": len(reqs),
        "sampled_requests": sum(1 for r in reqs if "temperature" in r),
        "meshes": meshes,
    }


def run_heal(args, spec_path: str) -> dict:
    """The self-healing soak: 2 supervised replicas, SIGKILL one mid-run,
    measure death -> readmission and what the gap cost."""
    from transformer_tpu.serve.replica import build_model_from_spec
    from transformer_tpu.serve.router import ReplicaProcess, Router
    from transformer_tpu.serve.supervisor import Supervisor

    _, _, tok = build_model_from_spec(SPEC)
    worker = [
        "--model_spec", spec_path,
        "--serve_slots", str(args.slots),
        "--prefix_cache_mb", "32",
        "--prefix_block", str(args.prefix_block),
        "--kv_layout", getattr(args, "kv_layout", "dense"),
        "--heartbeat_ms", "100",
    ]
    n_replicas = 2
    # Per-replica metrics JSONL: the victim's flight recorder autodumps
    # next to it, which is what the supervisor's postmortem capture
    # salvages after the SIGKILL (respawns for the same index reuse the
    # path — the event log appends, the dump is rewritten).
    obs_dir = tempfile.mkdtemp(prefix="router_heal_obs_")

    def _argv(i):
        return list(worker) + [
            "--metrics_jsonl", os.path.join(obs_dir, f"replica{i}.jsonl"),
        ]

    links = [ReplicaProcess.spawn(i, _argv(i)) for i in range(n_replicas)]

    def spawn(index, name, role):
        return ReplicaProcess.spawn(index, _argv(index), role=role, name=name)

    sup = Supervisor(spawn, backoff_ms=50.0)
    router = Router(
        links, encode=tok.encode, bos_id=tok.bos_id,
        affinity_block=args.prefix_block, heartbeat_timeout_s=10.0,
        supervisor=sup,
    )
    for link in links:
        link.start_reader(router.inbox)

    reqs = _workload(args.requests, n_replicas, args.system_words)
    t0 = time.perf_counter()
    for r in reqs:
        router.submit(dict(r))
    answered = []
    killed = False
    gap_served = 0
    deadline = time.time() + 300
    while (
        router.busy or (killed and sup.stats["respawns"] < 1)
    ) and time.time() < deadline:
        router.pump()
        fresh = router.drain_ready()
        answered.extend(fresh)
        if killed and sup.stats["respawns"] < 1:
            # The gap: between the SIGKILL and the replacement's
            # admission, the surviving fleet carries the whole workload.
            gap_served += len(fresh)
        if not killed and len(answered) >= args.requests // 4:
            victim = max(router.links, key=lambda l: l.inflight)
            if victim.inflight > 0:
                os.kill(victim.pid(), signal.SIGKILL)
                killed = True
    answered.extend(router.drain_ready())
    wall = time.perf_counter() - t0
    router.shutdown()
    heal_s = sup.heal_times[0] if sup.heal_times else None
    return {
        "mode": "heal",
        "replicas": n_replicas,
        "requests": len(reqs),
        "answered": len(answered),
        "answered_ok": sum(1 for a in answered if "continuation" in a),
        "wall_s": round(wall, 3),
        "killed_one": killed,
        "time_to_heal_s": None if heal_s is None else round(heal_s, 3),
        "served_during_gap": gap_served,
        "warmed_tokens": sup.stats["warmed_tokens"],
        "respawns": sup.stats["respawns"],
        "postmortems": sup.stats["postmortems"],
        "redispatch_count": router.stats["redispatched"],
    }


def run_upgrade(args, spec_path: str) -> dict:
    """The live-weights soak: roll a verified checkpoint swap across a
    2-replica fleet mid-workload; every request answers, tagged by the
    weight_version that served it, with zero recompiles replica-side."""
    import tempfile as _tempfile

    from transformer_tpu.serve.replica import build_model_from_spec
    from transformer_tpu.serve.router import ReplicaProcess, Router
    from transformer_tpu.serve.supervisor import Supervisor
    from transformer_tpu.serve.upgrade import UpgradeCoordinator
    from transformer_tpu.train.checkpoint import CheckpointManager

    old_params, _, tok = build_model_from_spec(SPEC)
    # The upgrade artifact: the SAME architecture initialized from a
    # different seed, saved with the checksummed manifest — byte-different
    # weights, structurally a twin (the zero-recompile precondition). The
    # fleet also BOOTSTRAPS from a manifest-verified checkpoint of the
    # old weights, so every answer is version-tagged end to end.
    new_params, _, _ = build_model_from_spec({**SPEC, "seed": 1})
    old_root = _tempfile.mkdtemp(prefix="upgrade_old_")
    old_dir = CheckpointManager(old_root, is_primary=True).save(
        old_params, step=1
    )
    ckpt_root = _tempfile.mkdtemp(prefix="upgrade_ckpt_")
    ckpt_dir = CheckpointManager(ckpt_root, is_primary=True).save(
        new_params, step=1
    )

    worker = [
        "--model_spec", spec_path,
        "--init_ckpt", old_dir,
        "--serve_slots", str(args.slots),
        "--prefix_cache_mb", "32",
        "--prefix_block", str(args.prefix_block),
        "--kv_layout", getattr(args, "kv_layout", "dense"),
        "--heartbeat_ms", "100",
    ]
    n_replicas = 2
    links = [ReplicaProcess.spawn(i, list(worker)) for i in range(n_replicas)]

    def spawn(index, name, role, weight_target=None):
        argv = list(worker)
        if weight_target is not None:
            argv += ["--init_ckpt", weight_target[0],
                     "--weight_version", weight_target[1]]
        return ReplicaProcess.spawn(index, argv, role=role, name=name)

    sup = Supervisor(spawn, backoff_ms=50.0)
    up = UpgradeCoordinator(canary_window_s=0.5, canary_min_requests=1)
    router = Router(
        links, encode=tok.encode, bos_id=tok.bos_id,
        affinity_block=args.prefix_block, heartbeat_timeout_s=10.0,
        supervisor=sup, upgrader=up,
    )
    for link in links:
        link.start_reader(router.inbox)

    reqs = _workload(args.requests, n_replicas, args.system_words)
    t0 = time.perf_counter()
    # LIVE traffic, not a pre-loaded batch: keep a bounded window of
    # requests outstanding so the rollout quiesces replicas against a
    # stream (and the canary window has traffic to judge), the shape a
    # production swap actually runs under.
    window = max(2, args.slots)
    next_req = 0
    answered = []
    started = False
    t_up0 = t_up1 = None
    rollout_served = 0
    deadline = time.time() + 300
    while (
        len(answered) < len(reqs) or (started and up.active)
    ) and time.time() < deadline:
        while next_req < len(reqs) and router.backlog < window:
            router.submit(dict(reqs[next_req]))
            next_req += 1
        router.pump()
        fresh = router.drain_ready()
        answered.extend(fresh)
        if started and up.active:
            rollout_served += len(fresh)
        if not started and len(answered) >= args.requests // 4:
            status = router.start_upgrade(ckpt_root)
            assert status.get("ok"), f"upgrade refused: {status}"
            started = True
            t_up0 = time.perf_counter()
        if started and t_up1 is None and not up.active:
            t_up1 = time.perf_counter()
    answered.extend(router.drain_ready())
    wall = time.perf_counter() - t0
    if started and t_up1 is None and not up.active:
        t_up1 = time.perf_counter()
    router.shutdown()
    versions: dict = {}
    for a in answered:
        v = a.get("weight_version")
        if v is not None:
            versions[v] = versions.get(v, 0) + 1
    return {
        "mode": "upgrade",
        "replicas": n_replicas,
        "requests": len(reqs),
        "answered": len(answered),
        "answered_ok": sum(1 for a in answered if "continuation" in a),
        "wall_s": round(wall, 3),
        "upgrade_state": up.state,
        "version": up.target_version,
        "time_to_upgrade_s": (
            None if t_up0 is None or t_up1 is None
            else round(t_up1 - t_up0, 3)
        ),
        "served_during_rollout": rollout_served,
        "canary_requests": up.stats["canary_requests"],
        "canary_share": (
            round(up.stats["canary_requests"] / rollout_served, 4)
            if rollout_served else None
        ),
        "rollbacks": up.stats["rollbacks"],
        "per_version_answers": versions,
        "ckpt": ckpt_dir,
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replica_counts", type=str, default="1,2,4")
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--system_words", type=int, default=8,
                   help="shared system-prompt length in words")
    p.add_argument("--slots", type=int, default=2)
    p.add_argument("--prefix_block", type=int, default=4)
    p.add_argument("--kv_layout", choices=("dense", "paged"), default="dense",
                   help="replica KV storage; 'paged' makes repeated-system-"
                        "prompt hits device-side block-table aliases "
                        "(prefix_alias_tokens > 0 in the row)")
    p.add_argument("--kill", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="SIGKILL one replica mid-workload (replicas > 1) "
                        "to pin the zero-loss failover numbers")
    p.add_argument("--heal", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="run the supervised-respawn soak: SIGKILL one of "
                        "2 supervised replicas mid-run and row the "
                        "time-to-heal + requests served during the gap")
    p.add_argument("--upgrade", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="run the live-weights soak: roll a verified "
                        "checkpoint swap across 2 replicas mid-run and "
                        "row time-to-upgrade, requests served during the "
                        "rollout, and the canary share")
    p.add_argument("--mesh_parity", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="run the sharded-replica soak: the same greedy + "
                        "seeded-sampled workload through --mesh 1/2/4 "
                        "single-replica fleets, byte-parity asserted "
                        "against an unsharded replica, one row per mesh")
    p.add_argument("--rows_out", type=str, default="",
                   help="append bench_rows.jsonl-compatible rows here "
                        "('' = print them to stderr)")
    args = p.parse_args()

    import jax

    dev = jax.devices()[0]
    device = f"{dev.platform}:{dev.device_kind}"
    fd, spec_path = tempfile.mkstemp(suffix=".json")
    with os.fdopen(fd, "w") as f:
        json.dump(SPEC, f)
    rows = []
    try:
        for n in [int(x) for x in args.replica_counts.split(",") if x.strip()]:
            result = run_sweep(n, args, spec_path)
            print(json.dumps(result))
            assert result["answered"] == result["requests"], (
                "router lost requests"
            )
            assert result["measured_step_p50_ms"], (
                f"no measured pool-step p50 from the fleet: {result}"
            )
            assert result["roofline_ratio"], (
                f"roofline_ratio missing: {result}"
            )
            hit_rates = [
                r["prefix_hit_rate"]
                for r in result["per_replica"].values()
                if r["prefix_hit_rate"] is not None
            ]
            alias_tokens = sum(
                int(r.get("prefix_alias_tokens") or 0)
                for r in result["per_replica"].values()
            )
            rows.append(json.dumps({
                "metric": "router p99 queue latency",
                "value": result["queue_p99_s"],
                "unit": "s",
                "config": {
                    "replicas": n, "slots": args.slots,
                    "requests": args.requests,
                    "system_words": args.system_words,
                    "prefix_block": args.prefix_block,
                    "kv_layout": args.kv_layout,
                    "killed_one": result["killed_one"],
                },
                "requests_per_sec": result["requests_per_sec"],
                "prefix_hit_rate_per_replica": hit_rates,
                # The aliased hit path: > 0 means repeated system prompts
                # were restored device-side with zero host<->device copies
                # (paged workers only; dense workers report 0).
                "prefix_alias_tokens": alias_tokens,
                "redispatch_count": result["redispatch_count"],
                "failovers": result["failovers"],
                "predicted_bytes_moved": result["predicted_bytes_moved"],
                "measured_step_p50_ms": result["measured_step_p50_ms"],
                "roofline_ratio": result["roofline_ratio"],
                "device": device,
                "vs_baseline": None,
            }))
        if args.mesh_parity:
            result = run_mesh_parity(args, spec_path)
            print(json.dumps(result))
            for r in result["meshes"].values():
                assert r["byte_parity"], f"mesh parity broken: {result}"
                rows.append(json.dumps({
                    "metric": "router mesh requests/s",
                    "value": r["requests_per_sec"],
                    "unit": "req/s",
                    "config": {
                        "replicas": 1, "slots": 4, "mesh": r["mesh"],
                        "requests": result["requests"],
                        "sampled_requests": result["sampled_requests"],
                    },
                    # Asserted, not aspirational: the run aborts above if a
                    # sharded fleet's bytes diverge from the unsharded one.
                    "byte_parity": r["byte_parity"],
                    "wall_s": r["wall_s"],
                    "device": device,
                    "vs_baseline": None,
                }))
        if args.heal:
            result = run_heal(args, spec_path)
            print(json.dumps(result))
            assert result["answered"] == result["requests"], (
                "heal soak lost requests"
            )
            assert result["respawns"] == 1, (
                f"fleet did not heal: {result}"
            )
            rows.append(json.dumps({
                "metric": "router time-to-heal",
                "value": result["time_to_heal_s"],
                "unit": "s",
                "config": {
                    "replicas": result["replicas"], "slots": args.slots,
                    "requests": args.requests,
                    "system_words": args.system_words,
                    "prefix_block": args.prefix_block,
                },
                "served_during_gap": result["served_during_gap"],
                "warmed_tokens": result["warmed_tokens"],
                "redispatch_count": result["redispatch_count"],
                # Supervisor-captured crash forensics: how many dead
                # replicas left a salvageable flight record this soak.
                "postmortems": result["postmortems"],
                "device": device,
                "vs_baseline": None,
            }))
        if args.upgrade:
            result = run_upgrade(args, spec_path)
            print(json.dumps(result))
            assert result["answered"] == result["requests"], (
                "upgrade soak lost requests"
            )
            assert result["answered_ok"] == result["requests"], (
                f"upgrade soak had errored requests: {result}"
            )
            assert result["upgrade_state"] == "done", (
                f"rollout did not complete: {result}"
            )
            rows.append(json.dumps({
                "metric": "router time-to-upgrade",
                "value": result["time_to_upgrade_s"],
                "unit": "s",
                "config": {
                    "replicas": result["replicas"], "slots": args.slots,
                    "requests": args.requests,
                    "system_words": args.system_words,
                    "prefix_block": args.prefix_block,
                },
                "served_during_rollout": result["served_during_rollout"],
                "canary_share": result["canary_share"],
                "rollbacks": result["rollbacks"],
                "per_version_answers": result["per_version_answers"],
                "device": device,
                "vs_baseline": None,
            }))
    finally:
        os.unlink(spec_path)
    if args.rows_out:
        with open(args.rows_out, "a", encoding="utf-8") as f:
            f.write("\n".join(rows) + "\n")
    else:
        for row in rows:
            print(row, file=sys.stderr)


if __name__ == "__main__":
    main()
