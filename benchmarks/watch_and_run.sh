#!/bin/bash
# Poll the TPU relay and capture all pending round measurements when it's up.
#
# The TPU chip is reached through a local relay (port 8082) that dies for long
# stretches and can only be restarted by the harness (see
# .claude/skills/verify/SKILL.md). This watchdog turns "poll the port and grab
# TPU measurements when it's up" into an unattended loop:
#
#   nohup benchmarks/watch_and_run.sh &
#
# Each pass runs AT MOST ONE measurement, re-probing relay health in between,
# so a relay that flaps mid-window costs one measurement, not all. The BLEU
# convergence run comes FIRST (it is the north-star metric) and is
# incremental: each pass trains at most 8 more epochs from its own
# checkpoints (bleu_run.py --epoch_budget), so progress accumulates across
# flaky windows instead of restarting a 40-epoch run. Measurements already
# recorded (a "value"/"bleu" line in the output files) are never re-run.
# A .tpu_busy lockfile is held while a measurement is in flight so other
# shells can avoid starting CPU-heavy work that would starve the single host
# core during a timing loop.
cd "$(dirname "$0")/.." || exit 1
trap 'rm -f .tpu_busy' EXIT  # never leak the busy marker if killed mid-run
LOG=watch_tpu.log
ROWS=bench_rows.jsonl
ATTR=bench_attr.jsonl
BLEU=bleu_out.jsonl
EXTRA=bench_extras.jsonl
ERR=bench_run.err
log() { echo "$(date +%F_%T) $*" >>"$LOG"; }

# Required measurements stop being retried after this many recorded
# failures, so one persistently broken config cannot keep the watchdog
# alive (and re-burning 2400s timeouts) forever.
MAX_ERRORS=3

missing_rows() {
  local out="" c
  for c in big tied long4k; do
    grep -q "\"metric\": \"$c train throughput\", \"value\"" "$ROWS" 2>/dev/null \
      && continue
    [ "$(error_count "$c train throughput" "$ROWS")" -ge "$MAX_ERRORS" ] && continue
    out="$out,$c"
  done
  echo "${out#,}"
}

missing_attr() {
  # full is covered by the rows/BASELINE base measurement; fwd + smallvocab
  # are the attribution modes (backward share, vocab-projection share).
  local out="" m
  for m in fwd smallvocab; do
    grep -q "\"metric\": \"base train throughput \\[$m\\]\", \"value\"" "$ATTR" 2>/dev/null \
      && continue
    [ "$(error_count "base train throughput [$m]" "$ATTR")" -ge "$MAX_ERRORS" ] && continue
    out="$out,$m"
  done
  echo "${out#,}"
}

bleu_missing() { ! grep -q '"bleu"' "$BLEU" 2>/dev/null; }

bleu_done_or_exhausted() {
  # Done, or the incremental run has failed 4 times — the same cap the
  # measurement branch applies, so the exit condition can't demand a BLEU
  # line the branch will never again try to produce.
  ! bleu_missing || [ "$(error_count 'base BLEU run' "$BLEU")" -ge 4 ]
}

extra_metric() {
  # Extra item -> the metric string its value/error lines carry.
  case "$1" in
    repbase) echo "base train throughput" ;;
    reptiny) echo "tiny train throughput" ;;
    decode|decodeq8) echo "base decode throughput [$1]" ;;
    ldecode) echo "long4k decode throughput [decode]" ;;
    ldecodeq8) echo "long4k decode throughput [decodeq8]" ;;
    fb256) echo "long4k train throughput [fb256]" ;;
    fb512) echo "long4k train throughput [fb512]" ;;
    xla4k) echo "long4k train throughput [b1xs4096] [xla]" ;;
    fl4k1) echo "long4k train throughput [b1xs4096]" ;;
    *) echo "base train throughput [$1]" ;;
  esac
}

error_count() {
  # Recorded "error" lines for one metric in one jsonl file (0 when the
  # file does not exist yet). -F: metric text contains [].
  local n
  n=$(grep -cF "\"metric\": \"$1\", \"error\"" "$2" 2>/dev/null || true)
  echo "${n:-0}"
}

value_count() {
  local n
  n=$(grep -cF "\"metric\": \"$1\", \"value\"" "$2" 2>/dev/null || true)
  echo "${n:-0}"
}

record_failure() {
  # Append a synthetic error line when a measurement subprocess died without
  # reaching run.py's own error handler (timeout kill, OOM, segfault) —
  # otherwise exhaustion/least-failed accounting never sees the attempt.
  echo "{\"metric\": \"$1\", \"error\": \"watchdog: subprocess rc=$3\"}" >>"$2"
}

missing_extras() {
  # Optional perf A/Bs for the MFU analysis, captured only after the
  # required measurements: chunked-CE vs monolithic on base, a batch-256
  # MFU-ceiling probe, and repeat base/tiny rows so BASELINE.md can report
  # medians over >=3 observations (the r1/r2 rows are the other points).
  local out=""
  grep -qF '"metric": "base train throughput [chunks=4]", "value"' "$EXTRA" 2>/dev/null \
    || out="$out,chunks=4"
  grep -qF '"metric": "base train throughput [b256xs64]", "value"' "$EXTRA" 2>/dev/null \
    || out="$out,b256xs64"
  grep -qF '"metric": "base train throughput [deviceloop]", "value"' "$EXTRA" 2>/dev/null \
    || out="$out,deviceloop"
  grep -qF '"metric": "base train throughput [multistep]", "value"' "$EXTRA" 2>/dev/null \
    || out="$out,multistep"
  grep -qF '"metric": "base decode throughput [decode]", "value"' "$EXTRA" 2>/dev/null \
    || out="$out,decode"
  grep -qF '"metric": "base decode throughput [decodeq8]", "value"' "$EXTRA" 2>/dev/null \
    || out="$out,decodeq8"
  grep -qF '"metric": "long4k decode throughput [decode]", "value"' "$EXTRA" 2>/dev/null \
    || out="$out,ldecode"
  grep -qF '"metric": "long4k decode throughput [decodeq8]", "value"' "$EXTRA" 2>/dev/null \
    || out="$out,ldecodeq8"
  grep -qF '"metric": "long4k train throughput [fb256]", "value"' "$EXTRA" 2>/dev/null \
    || out="$out,fb256"
  grep -qF '"metric": "long4k train throughput [fb512]", "value"' "$EXTRA" 2>/dev/null \
    || out="$out,fb512"
  grep -qF '"metric": "long4k train throughput [b1xs4096] [xla]", "value"' "$EXTRA" 2>/dev/null \
    || out="$out,xla4k"
  grep -qF '"metric": "long4k train throughput [b1xs4096]", "value"' "$EXTRA" 2>/dev/null \
    || out="$out,fl4k1"
  [ "$(value_count "base train throughput" "$EXTRA")" -ge 2 ] || out="$out,repbase"
  [ "$(value_count "tiny train throughput" "$EXTRA")" -ge 2 ] || out="$out,reptiny"
  echo "${out#,}"
}

extras_done_or_exhausted() {
  # Extras are OPTIONAL: they must not keep the watchdog alive forever.
  # Done, or every still-missing extra has already failed twice.
  local x c
  x=$(missing_extras)
  [ -z "$x" ] && return 0
  IFS=, read -ra _xarr <<<"$x"
  for c in "${_xarr[@]}"; do
    [ "$(error_count "$(extra_metric "$c")" "$EXTRA")" -ge 2 ] || return 1
  done
  return 0
}

pick_extra() {
  # Least-failed missing extra that is not yet exhausted (one persistently
  # failing extra must neither starve the rest nor loop forever). Empty
  # when every missing extra has failed out.
  local x c n best="" best_n=-1
  x=$(missing_extras)
  [ -z "$x" ] && return
  IFS=, read -ra _xarr <<<"$x"
  for c in "${_xarr[@]}"; do
    n=$(error_count "$(extra_metric "$c")" "$EXTRA")
    [ "$n" -ge 2 ] && continue  # exhausted: stop retrying it
    if [ "$best_n" -lt 0 ] || [ "$n" -lt "$best_n" ]; then
      best="$c"; best_n="$n"
    fi
  done
  echo "$best"
}

pick_least_failed() {
  # args: jsonl-file, metric-suffix-template items... — choose the item with
  # the fewest recorded "error" lines, so one persistently failing config
  # cannot starve the rest (ties: first). Template "%s" is the item.
  local file=$1 tmpl=$2; shift 2
  local best="" best_n=-1 c n metric
  for c in "$@"; do
    # shellcheck disable=SC2059
    metric=$(printf "$tmpl" "$c")
    n=$(error_count "$metric" "$file")
    if [ "$best_n" -lt 0 ] || [ "$n" -lt "$best_n" ]; then
      best="$c"; best_n="$n"
    fi
  done
  echo "$best"
}

log "watchdog started (pid $$)"
while :; do
  R=$(missing_rows)
  A=$(missing_attr)
  if [ -z "$R" ] && [ -z "$A" ] && bleu_done_or_exhausted && extras_done_or_exhausted; then
    log "all measurements captured (or exhausted); exiting"
    break
  fi
  if ! ss -tln | grep -q ':8082 '; then
    sleep 45
    continue
  fi
  log "relay up (missing rows=[$R] attr=[$A] bleu=$(bleu_missing && echo pending || echo done)); probing"
  if ! timeout 120 python -c 'import jax, jax.numpy as jnp; print(float(jnp.ones((256, 256)).sum()))' >>"$LOG" 2>&1; then
    log "probe failed; backing off"
    sleep 120
    continue
  fi
  touch .tpu_busy
  if bleu_missing && [ "$(error_count 'base BLEU run' "$BLEU")" -lt 4 ]; then
    # North star first (two rounds overdue). Incremental: <=8 epochs per
    # pass, resumes from its own checkpoints, emits progress lines until
    # the final {"bleu": ...} line lands.
    log "running BLEU convergence pass (8-epoch budget, resumable, keep-best)"
    # --bleu_every 4 --stop_patience 2: keep the best-probe params and stop
    # after two consecutive non-improving probes (the CPU ladder showed BLEU
    # peaking then dropping — a fixed 40-epoch budget can buy memorization).
    # The probe cadence is 4 (not 10) so the stop rule can see the peak
    # within the ~24 epochs that remain after the banked 16.
    timeout 3600 python benchmarks/bleu_run.py --config base --epochs 40 \
      --bleu_every 4 --stop_patience 2 --epoch_budget 8 --label_smoothing 0.1 \
      >>"$BLEU" 2>>bleu_run.err
    rc=$?
    [ "$rc" -ne 0 ] && record_failure "base BLEU run" "$BLEU" "$rc"
    log "BLEU pass done (rc=$rc)"
  elif [ -n "$R" ]; then
    # One config per pass (relay re-probed between measurements), choosing
    # the least-failed missing config so a bad one can't starve the rest.
    IFS=, read -ra RARR <<<"$R"
    PICK=$(pick_least_failed "$ROWS" "%s train throughput" "${RARR[@]}")
    log "running throughput row: $PICK"
    timeout 2400 python benchmarks/run.py --configs "$PICK" >>"$ROWS" 2>>"$ERR"
    rc=$?
    [ "$rc" -ne 0 ] && record_failure "$PICK train throughput" "$ROWS" "$rc"
    log "row pass done (rc=$rc)"
  elif [ -n "$A" ]; then
    IFS=, read -ra AARR <<<"$A"
    PICK=$(pick_least_failed "$ATTR" "base train throughput [%s]" "${AARR[@]}")
    log "running base attribution: $PICK"
    timeout 2400 python benchmarks/run.py --configs base --modes "$PICK" >>"$ATTR" 2>>"$ERR"
    rc=$?
    [ "$rc" -ne 0 ] && record_failure "base train throughput [$PICK]" "$ATTR" "$rc"
    log "attribution pass done (rc=$rc)"
  else
    PICK=$(pick_extra)
    if [ -z "$PICK" ]; then
      # Everything actionable is done or exhausted but some branch above
      # disagrees transiently; never busy-loop on the probe.
      rm -f .tpu_busy
      sleep 60
      continue
    fi
    rc=0
    case "$PICK" in
      "chunks=4")
        log "running extra: base chunked-CE A/B"
        timeout 2400 python benchmarks/run.py --configs base --loss_chunks 4 >>"$EXTRA" 2>>"$ERR"
        rc=$?
        [ "$rc" -ne 0 ] && record_failure "base train throughput [chunks=4]" "$EXTRA" "$rc"
        ;;
      "b256xs64")
        log "running extra: base batch-256 MFU probe"
        timeout 2400 python benchmarks/run.py --configs base --batch 256 >>"$EXTRA" 2>>"$ERR"
        rc=$?
        [ "$rc" -ne 0 ] && record_failure "base train throughput [b256xs64]" "$EXTRA" "$rc"
        ;;
      deviceloop)
        log "running extra: base device-loop dispatch-overhead probe"
        timeout 2400 python benchmarks/run.py --configs base --modes deviceloop >>"$EXTRA" 2>>"$ERR"
        rc=$?
        [ "$rc" -ne 0 ] && record_failure "base train throughput [deviceloop]" "$EXTRA" "$rc"
        ;;
      multistep)
        log "running extra: base steps_per_dispatch production-path A/B"
        timeout 2400 python benchmarks/run.py --configs base --modes multistep >>"$EXTRA" 2>>"$ERR"
        rc=$?
        [ "$rc" -ne 0 ] && record_failure "base train throughput [multistep]" "$EXTRA" "$rc"
        ;;
      decode|decodeq8)
        log "running extra: base greedy-decode throughput [$PICK]"
        timeout 2400 python benchmarks/run.py --configs base --modes "$PICK" >>"$EXTRA" 2>>"$ERR"
        rc=$?
        [ "$rc" -ne 0 ] && record_failure "base decode throughput [$PICK]" "$EXTRA" "$rc"
        ;;
      ldecode|ldecodeq8)
        M=${PICK#l}
        log "running extra: long4k LM-decode throughput [$M]"
        timeout 2400 python benchmarks/run.py --configs long4k --modes "$M" --steps 3 >>"$EXTRA" 2>>"$ERR"
        rc=$?
        [ "$rc" -ne 0 ] && record_failure "long4k decode throughput [$M]" "$EXTRA" "$rc"
        ;;
      fb256|fb512)
        B=${PICK#fb}
        log "running extra: long4k flash tile sweep [$PICK]"
        timeout 2400 python benchmarks/run.py --configs long4k --flash_block "$B" >>"$EXTRA" 2>>"$ERR"
        rc=$?
        [ "$rc" -ne 0 ] && record_failure "long4k train throughput [$PICK]" "$EXTRA" "$rc"
        ;;
      xla4k)
        # batch 1, not the config's 4: the xla path materializes (B,H,S,S)
        # fp32 scores PLUS per-layer softmax residuals for backward — at
        # batch 4 that alone exceeds 16 GB HBM. The flash side of the A/B
        # (fl4k1) runs the same batch-1 shape so the comparison is exact.
        log "running extra: long4k flash-vs-xla A/B [xla side, batch 1]"
        timeout 2400 python benchmarks/run.py --configs long4k --batch 1 --attn_impl xla >>"$EXTRA" 2>>"$ERR"
        rc=$?
        [ "$rc" -ne 0 ] && record_failure "long4k train throughput [b1xs4096] [xla]" "$EXTRA" "$rc"
        ;;
      fl4k1)
        log "running extra: long4k flash-vs-xla A/B [flash side, batch 1]"
        timeout 2400 python benchmarks/run.py --configs long4k --batch 1 >>"$EXTRA" 2>>"$ERR"
        rc=$?
        [ "$rc" -ne 0 ] && record_failure "long4k train throughput [b1xs4096]" "$EXTRA" "$rc"
        ;;
      repbase)
        log "running extra: base repeat row (variance/median)"
        timeout 2400 python benchmarks/run.py --configs base >>"$EXTRA" 2>>"$ERR"
        rc=$?
        [ "$rc" -ne 0 ] && record_failure "base train throughput" "$EXTRA" "$rc"
        ;;
      reptiny)
        log "running extra: tiny repeat row (variance/median)"
        timeout 2400 python benchmarks/run.py --configs tiny >>"$EXTRA" 2>>"$ERR"
        rc=$?
        [ "$rc" -ne 0 ] && record_failure "tiny train throughput" "$EXTRA" "$rc"
        ;;
    esac
    log "extras pass done (rc=$rc)"
  fi
  rm -f .tpu_busy
done
