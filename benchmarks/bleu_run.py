"""Convergence run: train on the bundled corpus and publish corpus BLEU.

The BASELINE.json north star is "eval BLEU on src/tgt" — this script is the
committed reproduction command behind the BLEU number in BASELINE.md:

    python benchmarks/bleu_run.py [--config base|small|tiny] [--epochs N]

Trains on data/src-train.txt → tgt-train.txt (10k pairs, the corpus the
reference bundles), greedy-decodes the bundled 500-pair test split, and
prints one JSON line: {"metric": "...", "bleu": ..., "epochs": ..., ...}.

Notes on the setup (documented so the number is interpretable):
- warmup defaults to 2000, not the reference's 60000 (``train.py:22``): on a
  10k-pair corpus an epoch is ~150 steps, so a 60k-step warmup would keep the
  LR near zero for the entire run.
- the test split is drawn from the tail of the training corpus
  (data/README.md) because the reference ships no test files. By default the
  run HOLDS THOSE PAIRS OUT of training (``--holdout 1`` →
  ``load_dataset(exclude_test_overlap=True)``) so the reported BLEU is
  genuinely out-of-sample; ``--holdout 0`` reproduces the in-sample behavior.
- the run is RESUMABLE: it restores from its own workdir checkpoints, and
  ``--epoch_budget N`` trains at most N epochs per invocation, printing a
  progress JSON line (no "bleu" key) until the target epoch count is reached
  — the relay watchdog calls it repeatedly so flaky tunnel windows accumulate
  progress instead of restarting a 40-epoch run from scratch.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One shapes table for every consumer (score_ckpt.py imports it): drift
# between the trainer's architecture and a scorer's would restore cleanly
# into the wrong model whenever param shapes happen to match (num_heads).
CONFIG_SHAPES = {
    "tiny": dict(num_layers=2, d_model=128, num_heads=4, dff=512),
    "small": dict(num_layers=2, d_model=256, num_heads=8, dff=1024),
    "medium": dict(num_layers=4, d_model=256, num_heads=8, dff=1024),
    "base": dict(num_layers=6, d_model=512, num_heads=8, dff=2048),
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--config", default="base", choices=["tiny", "small", "medium", "base"],
        help="tiny/small/medium are CPU-fallback scales (medium = 4L/256, "
        "the next capacity step of the capacity+smoothing recipe the r3 2x2 "
        "showed compounds); base is the headline Transformer-base run",
    )
    ap.add_argument("--epochs", type=int, default=40)
    ap.add_argument("--warmup", type=int, default=2000)
    ap.add_argument("--seq_len", type=int, default=50)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=2**15)
    ap.add_argument("--bleu_max_len", type=int, default=64)
    ap.add_argument(
        "--holdout", type=int, default=1,
        help="1 (default): exclude the test pairs from training so BLEU is "
        "out-of-sample; 0: train on the full corpus (in-sample BLEU)",
    )
    ap.add_argument(
        "--epoch_budget", type=int, default=0,
        help="train at most this many epochs THIS invocation, then print a "
        "progress line and exit (0 = train to --epochs in one go); the run "
        "resumes from its checkpoints either way",
    )
    ap.add_argument(
        "--dtype", default="bfloat16", choices=["bfloat16", "float32"],
        help="compute dtype (float32 is much faster on the CPU fallback "
        "path, where bf16 matmuls are emulated)",
    )
    ap.add_argument(
        "--label_smoothing", type=float, default=0.0,
        help="label smoothing for the convergence run. Default 0 keeps the "
        "published CPU-fallback numbers reproducible by their committed "
        "commands; the watchdog's base run passes 0.1 (the standard NMT "
        "setting, Vaswani et al.) explicitly.",
    )
    ap.add_argument(
        "--native_loader", type=int, default=1,
        help="1 (default): assemble batches in the C++ prefetching loader "
        "(composes with the length buckets), overlapping host batch "
        "assembly with device steps; 0: Python batcher",
    )
    ap.add_argument(
        "--bleu_every", type=int, default=0,
        help="also score a 64-pair BLEU probe every N epochs during "
        "training (0 = end-of-run only)",
    )
    ap.add_argument(
        "--stop_patience", type=int, default=0,
        help="with --bleu_every: stop after this many consecutive probes "
        "without a new best BLEU, keep the best probe's params as the "
        "scored model (0 = train the full --epochs budget; best-params "
        "tracking still runs). The bundled-corpus ladder showed BLEU "
        "peaking then DROPPING (small+smoothing: 2.34 at epoch 60 -> 2.08 "
        "at 70), so a fixed budget can overshoot into memorization.",
    )
    ap.add_argument(
        "--workdir", default="",
        help="vocab/checkpoint directory; default derives from the run "
        "parameters so different corpora/configs never share stale vocabs "
        "or restore each other's checkpoints",
    )
    ap.add_argument(
        "--data_dir", default=os.path.join(REPO, "data"),
        help="corpus directory (override for smoke tests on subsets)",
    )
    args = ap.parse_args()
    if not args.workdir:
        import hashlib

        # Every training-relevant knob is in the key: a rerun with ANY
        # different parameter gets a fresh dir, so restore-before-train can
        # only ever resume an identical interrupted run — never silently
        # continue a different one and misreport "epochs".
        key = hashlib.md5(
            f"{os.path.abspath(args.data_dir)}|{args.config}|{args.vocab}|"
            f"{args.seq_len}|{args.epochs}|{args.warmup}|{args.batch}|"
            f"h{args.holdout}|{args.dtype}|ls{args.label_smoothing}".encode()
        ).hexdigest()[:10]
        # Repo-local, NOT /tmp: the round-4 run lost 16 banked epochs when
        # /tmp was wiped between rounds. .bleu_runs/ is gitignored (the
        # base-config state is ~1.1 GB) but survives on the repo volume.
        args.workdir = os.path.join(REPO, ".bleu_runs", f"bleu_run_{key}")
    # Fail before training, not after: the scoring split must exist.
    for name in ("src-test.txt", "tgt-test.txt"):
        path = os.path.join(args.data_dir, name)
        if not os.path.exists(path):
            raise SystemExit(
                f"missing {path}: the BLEU run needs a test split "
                "(data/README.md describes the bundled one)"
            )
    # Persist the run parameters next to the checkpoints: scorers
    # (benchmarks/score_ckpt.py) read holdout/config from here instead of
    # trusting their own flags, so an in-sample run can never be mislabeled
    # "held out" in the evidence JSONL by a default argument.
    os.makedirs(args.workdir, exist_ok=True)
    with open(os.path.join(args.workdir, "args.json"), "w") as f:
        json.dump(vars(args), f, indent=1)

    import jax

    from transformer_tpu.config import ModelConfig, TrainConfig
    from transformer_tpu.data import load_dataset
    from transformer_tpu.train import (
        AsyncCheckpointManager,
        Trainer,
        create_train_state,
        export_params,
        load_exported_params,
    )
    from transformer_tpu.train.evaluate import bleu_on_pairs, read_lines
    from transformer_tpu.train.probe_stop import ProbeKeepBest
    from transformer_tpu.utils import enable_compilation_cache

    # Each watchdog pass is a fresh process: without a persistent cache it
    # re-pays the ~210 s base-model compile before training a single step.
    enable_compilation_cache()
    dev = jax.devices()[0]
    print(f"training on {dev.platform}:{dev.device_kind}", file=sys.stderr)

    # Length buckets: most bundled-corpus sentences are far shorter than 50
    # tokens; three widths cut padding FLOPs roughly in half at the cost of
    # three compiles.
    buckets = (24, 36, args.seq_len) if args.seq_len >= 48 else ()
    train_ds, test_ds, src_tok, tgt_tok = load_dataset(
        args.data_dir,
        os.path.join(args.workdir, "src_vocab.subwords"),
        os.path.join(args.workdir, "tgt_vocab.subwords"),
        batch_size=args.batch,
        sequence_length=args.seq_len,
        target_vocab_size=args.vocab,
        seed=0,
        length_buckets=buckets,
        exclude_test_overlap=bool(args.holdout),
        prefetch=bool(args.native_loader),
    )
    if args.holdout:
        print(
            f"holdout: training on {train_ds.num_examples} pairs "
            "(test pairs excluded)",
            file=sys.stderr,
        )
    if len(train_ds) == 0:
        # batch_size > surviving examples (the length filter drops pairs
        # longer than --seq_len after tokenization): every epoch would be
        # zero steps and the run would "finish" untrained.
        raise SystemExit(
            f"no full batches: {train_ds.num_examples} examples survive the "
            f"seq_len={args.seq_len} length filter but batch_size="
            f"{args.batch} (drop_remainder) needs at least one full batch"
        )
    shapes = CONFIG_SHAPES[args.config]
    model_cfg = ModelConfig(
        **shapes,
        input_vocab_size=src_tok.model_vocab_size,
        target_vocab_size=tgt_tok.model_vocab_size,
        max_position=max(args.seq_len, args.bleu_max_len, 64),
        dropout_rate=0.1,
        dtype=args.dtype,
    )
    # Peek at the latest checkpoint STEP (metadata only — Trainer.fit does
    # the actual restore) to learn how far a previous invocation got, so
    # --epoch_budget can cap THIS invocation's work while the target epoch
    # count stays the contract for when BLEU is finally scored.
    # Async: the npz write happens off the training thread, so each save
    # costs only the device->host snapshot (the dominant per-epoch overhead
    # observed through the tunnel is the sync fetch + write of the ~1.1 GB
    # base-config state).
    ckpt = AsyncCheckpointManager(os.path.join(args.workdir, "ckpt"), 2)
    steps_per_epoch = max(len(train_ds), 1)
    done_epochs = min((ckpt.latest_step or 0) // steps_per_epoch, args.epochs)
    target_epochs = (
        min(args.epochs, done_epochs + args.epoch_budget)
        if args.epoch_budget
        else args.epochs
    )
    # Keep-best / stop accounting is persisted in the workdir, so the
    # decision survives the per-relay-window invocation pattern: a stop
    # decided two windows ago still skips training now and goes straight
    # to scoring the best snapshot.
    stopper = ProbeKeepBest(
        os.path.join(args.workdir, "probe_bleu.json"),
        patience=args.stop_patience,
    )
    best_dir = os.path.join(args.workdir, "best")
    # The rule only acts when THIS invocation enables it: probes need
    # --bleu_every, stopping needs --stop_patience. A rerun with the rule
    # disabled (the flags are outside the workdir hash) must train the full
    # budget, not silently honor a marker from a differently-flagged run.
    probing = args.bleu_every > 0
    stopping = probing and args.stop_patience > 0
    if stopping and stopper.stopped_epoch is not None:
        print(
            f"probe-stop marker present (stopped after epoch "
            f"{stopper.stopped_epoch}, best {stopper.best_value} at epoch "
            f"{stopper.best_epoch}); skipping training",
            file=sys.stderr,
        )
        target_epochs = done_epochs
    elif done_epochs:
        print(
            f"resuming: {done_epochs}/{args.epochs} epochs done, training to "
            f"{target_epochs} this invocation",
            file=sys.stderr,
        )
    train_cfg = TrainConfig(
        batch_size=args.batch,
        sequence_length=args.seq_len,
        epochs=target_epochs,
        warmup_steps=args.warmup,
        ckpt_path=os.path.join(args.workdir, "ckpt"),
        eval_every_steps=0,  # end-of-epoch metrics only; BLEU at the end
        # Every SECOND epoch is a resume point: per-save cost through the
        # tunnel is minutes (state snapshot), so saving every epoch doubled
        # the run's wall clock for one epoch of extra resume granularity.
        # Pass boundaries (epoch_budget multiples) still always save.
        checkpoint_every_epochs=2,
        label_smoothing=args.label_smoothing,
    )
    state = create_train_state(jax.random.PRNGKey(0), model_cfg, train_cfg)
    trainer = Trainer(
        model_cfg, train_cfg, state,
        checkpoint=ckpt,
        log_fn=lambda msg: print(msg, file=sys.stderr),
    )
    src_lines = read_lines(os.path.join(args.data_dir, "src-test.txt"))
    ref_lines = read_lines(os.path.join(args.data_dir, "tgt-test.txt"))

    callback = None
    probe_s = [0.0]  # probe decode time (incl. its compile) is NOT training
    if args.bleu_every:
        def callback(epoch, tr):
            if (epoch + 1) % args.bleu_every:
                return False
            t = time.perf_counter()
            probe, _ = bleu_on_pairs(
                tr.state.params, model_cfg, src_tok, tgt_tok,
                src_lines[:64], ref_lines[:64],
                batch_size=args.batch, max_len=args.bleu_max_len,
            )
            # Export BEFORE recording the new best, and atomically (tmp dir
            # + per-file os.replace): a tunnel death mid-export must never
            # leave probe_bleu.json claiming best@N while best/ holds the
            # previous peak's params or a truncated npz. Crash before the
            # record: this probe is simply re-run next invocation.
            if stopper.would_be_best(probe):
                # Snapshot ONLY the params (export format, ~1/3 the size of
                # a full train-state checkpoint): the rotating keep-2
                # checkpoint window will have discarded this epoch by the
                # time a later probe proves it was the peak.
                tmp_dir = best_dir + ".tmp"
                export_params(tr.state.params, model_cfg, tmp_dir)
                os.makedirs(best_dir, exist_ok=True)
                for name in ("params.npz", "config.json"):
                    os.replace(
                        os.path.join(tmp_dir, name),
                        os.path.join(best_dir, name),
                    )
                os.rmdir(tmp_dir)
            decision = stopper.update(epoch + 1, probe)
            probe_s[0] += time.perf_counter() - t
            print(
                f"epoch {epoch + 1}: probe BLEU {probe:.2f} [{decision}; "
                f"best {stopper.best_value:.2f} @ {stopper.best_epoch}]",
                file=sys.stderr,
            )
            return decision == "stop"

    t0 = time.perf_counter()
    try:
        trainer.fit(train_ds, test_ds, epoch_callback=callback)
    finally:
        # fit's own epilogue waits on async saves, but only if it is
        # reached: a raise mid-epoch (tunnel failure) must not lose an
        # in-flight background checkpoint write on top of it.
        ckpt.wait()
    train_s = time.perf_counter() - t0 - probe_s[0]
    stopped = stopping and stopper.stopped_epoch is not None
    if not stopped and target_epochs < args.epochs:
        # Budget-limited invocation: report progress (NO "bleu" key — the
        # watchdog keeps re-invoking until the final line lands) and stop.
        progress = {
            "metric": f"{args.config} BLEU run progress",
            "epochs_done": target_epochs,
            "epochs_target": args.epochs,
            "train_seconds": round(train_s, 1),
            "device": f"{dev.platform}:{dev.device_kind}",
        }
        if stopper.best_epoch is not None:
            progress["probe_best"] = stopper.best_value
            progress["probe_best_epoch"] = stopper.best_epoch
        print(json.dumps(progress), flush=True)
        return
    # Final scoring: the run either trained its full budget or the probe
    # rule stopped it. Score the BEST probe's params when a snapshot
    # exists — the ladder's peak-then-drop curves are exactly the case
    # where final != best.
    early_stopped = stopped
    epochs_trained = (
        min(stopper.stopped_epoch, args.epochs) if early_stopped
        else args.epochs
    )
    score_params = trainer.state.params
    scored = "final"
    if probing and stopper.best_epoch is not None and os.path.isdir(best_dir):
        score_params = load_exported_params(best_dir, trainer.state.params)
        scored = f"best@{stopper.best_epoch}"
    t1 = time.perf_counter()
    bleu, hyps = bleu_on_pairs(
        score_params, model_cfg, src_tok, tgt_tok,
        src_lines, ref_lines,
        batch_size=args.batch, max_len=args.bleu_max_len,
        log_fn=lambda msg: print(msg, file=sys.stderr),
    )
    eval_s = time.perf_counter() - t1
    for src, hyp, ref in list(zip(src_lines, hyps, ref_lines))[:3]:
        print(f"SRC {src}\nHYP {hyp}\nREF {ref}\n", file=sys.stderr)
    row = {
        "metric": (
            f"{args.config} corpus BLEU (bundled test split, greedy, "
            + ("held out" if args.holdout else "in-sample")
            + ")"
        ),
        "bleu": round(bleu, 2),
        "n_pairs": len(src_lines),
        "epochs": epochs_trained,
        "epochs_budget": args.epochs,
        "scored": scored,
        "vocab": args.vocab,
        "dtype": args.dtype,
        "label_smoothing": args.label_smoothing,
        "holdout": bool(args.holdout),
        "train_seconds": round(train_s, 1),
        "eval_seconds": round(eval_s, 1),
        "device": f"{dev.platform}:{dev.device_kind}",
    }
    if early_stopped:
        row["early_stopped"] = True
        row["probe_best"] = stopper.best_value
        row["probe_best_epoch"] = stopper.best_epoch
    print(json.dumps(row), flush=True)

    # The greedy headline is committed above; now rescore the SAME model
    # with the two quality levers validated at tiny scale (BASELINE.md):
    # beam-4 and checkpoint averaging. Extra JSON lines, best-effort — a
    # decode failure here must not cost the recorded headline.
    def _rescore(tag: str, p, beam: int) -> None:
        try:
            t = time.perf_counter()
            b, _ = bleu_on_pairs(
                p, model_cfg, src_tok, tgt_tok, src_lines, ref_lines,
                batch_size=args.batch, max_len=args.bleu_max_len,
                beam_size=beam,
            )
            print(
                json.dumps(
                    {
                        "metric": f"{args.config} corpus BLEU [{tag}]",
                        "bleu": round(b, 2),
                        "n_pairs": len(src_lines),
                        "holdout": bool(args.holdout),
                        "eval_seconds": round(time.perf_counter() - t, 1),
                    }
                ),
                flush=True,
            )
        except Exception as e:  # noqa: BLE001
            print(f"rescore [{tag}] failed: {e!r}", file=sys.stderr)

    _rescore("beam4", score_params, beam=4)
    steps = ckpt.all_steps()[-2:]
    if len(steps) > 1:
        from transformer_tpu.train.checkpoint import average_checkpoints

        # trainer.state is the live template (the init-time `state` buffers
        # were donated into the jitted step).
        avg = average_checkpoints(ckpt, trainer.state, steps)
        _rescore(f"avg{len(steps)}+greedy", avg, beam=1)
        _rescore(f"avg{len(steps)}+beam4", avg, beam=4)


if __name__ == "__main__":
    main()
