"""Benchmark suite: train-step throughput for every BASELINE.json config.

``bench.py`` at the repo root stays the driver contract (one JSON line for
the flagship config); this runner measures all five configs and prints one
JSON line each, for filling in BASELINE.md:

    python benchmarks/run.py [--steps N] [--configs tiny,base,...]

Configs (BASELINE.json "configs"):
  tiny   2L Transformer-tiny (the CPU smoke config)
  base   6L d_model=512 8H dff=2048 (Vaswani base)
  big    6L d_model=1024 16H dff=4096 + label smoothing 0.1
  tied   base + tied src/tgt embeddings + tied output projection
  long4k 4096-token decoder-only causal LM with flash attention

Throughput counts *target* tokens per optimizer step (batch × (seq−1)):
the unit BLEU-side throughput is quoted in; src+tgt would double-count the
same sentence pair.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _configs():
    from transformer_tpu.config import ModelConfig, TrainConfig

    # (model_cfg, train_cfg, batch, seq) per benchmark point.
    out = {}
    out["tiny"] = (
        ModelConfig(
            num_layers=2, d_model=128, num_heads=4, dff=512,
            input_vocab_size=32002, target_vocab_size=32002,
            max_position=64, dtype="bfloat16",
        ),
        TrainConfig(batch_size=64, sequence_length=64, warmup_steps=4000),
        64, 64,
    )
    out["base"] = (
        ModelConfig(
            num_layers=6, d_model=512, num_heads=8, dff=2048,
            input_vocab_size=32002, target_vocab_size=32002,
            max_position=64, dtype="bfloat16",
        ),
        TrainConfig(batch_size=64, sequence_length=64, warmup_steps=4000),
        64, 64,
    )
    out["big"] = (
        ModelConfig(
            num_layers=6, d_model=1024, num_heads=16, dff=4096,
            input_vocab_size=32002, target_vocab_size=32002,
            max_position=64, dtype="bfloat16",
        ),
        TrainConfig(
            batch_size=32, sequence_length=64, warmup_steps=4000,
            label_smoothing=0.1,
        ),
        32, 64,
    )
    out["tied"] = (
        ModelConfig(
            num_layers=6, d_model=512, num_heads=8, dff=2048,
            input_vocab_size=32002, target_vocab_size=32002,
            max_position=64, dtype="bfloat16",
            tie_embeddings=True, tie_output=True,
        ),
        TrainConfig(batch_size=64, sequence_length=64, warmup_steps=4000),
        64, 64,
    )
    out["long4k"] = (
        ModelConfig(
            num_layers=6, d_model=512, num_heads=8, dff=2048,
            input_vocab_size=32002, target_vocab_size=32002,
            max_position=4096, dtype="bfloat16",
            decoder_only=True, attention_impl="flash",
        ),
        TrainConfig(batch_size=4, sequence_length=4096, warmup_steps=4000),
        4, 4096,
    )
    return out


def bench_config(
    name: str, n_steps: int = 20, mode: str = "full", profile_dir: str = "",
    loss_chunks: int = 1, batch_override: int = 0, seq_override: int = 0,
    flash_block: int = 0, attn_impl: str = "",
) -> dict:
    """One measurement. ``mode`` attributes step time without trace tooling:

    - full:       the real train step (forward + backward + Adam)
    - fwd:        eval step only — isolates the backward+optimizer share
    - smallvocab: train step with a 2k-row OUTPUT vocab (input embedding
                  untouched) — isolates the vocab-projection/CE share
                  (32k-vocab logits matmul is the prime MFU suspect at seq 64)
    - deviceloop: all n_steps run inside ONE jitted lax.scan, so the host
                  dispatches once — (full − deviceloop) throughput is the
                  per-step dispatch/tunnel overhead share, the prime
                  suspect for the low measured MFU at batch 64 × seq 64
                  (BASELINE.md r2 analysis). Same math as `full`: the scan
                  carries the donated state through real optimizer steps.
    - multistep:  the production dispatch-amortization path
                  (TrainConfig.steps_per_dispatch / trainer.
                  make_multistep_train_step): n_steps DISTINCT batches
                  stacked into one (K,B,S) transfer, K optimizer steps per
                  dispatch — what `--steps_per_dispatch K` buys a real
                  training run (deviceloop is its upper bound).

    ``loss_chunks > 1`` additionally runs the chunked vocab-projection/CE
    path (TrainConfig.loss_chunks) for A/B against the monolithic loss.

    Serving-side modes (the reference has no working decode to measure,
    SURVEY §2.3.2/.11 — these rows are framework-only):
    - decode:    KV-cached greedy decode, generated tokens/sec.
    - decodeq8:  same with the int8 KV cache (--kv_cache_int8 A/B).
    """
    import dataclasses

    import jax
    import numpy as np

    from transformer_tpu.train import (
        create_train_state,
        make_eval_step,
        make_train_step,
    )
    from transformer_tpu.utils import enable_compilation_cache

    # One subprocess per measurement (backend-poisoning isolation) means
    # every row re-compiles; the persistent cache makes repeat rows and
    # A/B variants pay compile once per distinct executable.
    enable_compilation_cache()

    model_cfg, train_cfg, batch, seq = _configs()[name]
    if mode in ("decode", "decodeq8"):
        return _bench_decode(name, model_cfg, batch, seq, n_steps, mode)
    if batch_override or seq_override:
        # MFU-ceiling probes: the BASELINE shapes are fixed for comparability,
        # but utilization scales with tokens/step — overrides find the knee.
        batch = batch_override or batch
        seq = seq_override or seq
        model_cfg = dataclasses.replace(
            model_cfg, max_position=max(model_cfg.max_position, seq)
        )
        train_cfg = dataclasses.replace(
            train_cfg, batch_size=batch, sequence_length=seq
        )
    if loss_chunks > 1:
        train_cfg = dataclasses.replace(train_cfg, loss_chunks=loss_chunks)
    if flash_block:
        # Flash-kernel tile sweep (long4k): the 128 default was chosen for
        # VMEM safety, not measured; bigger k-tiles amortize the per-tile
        # loop overhead at 4096 if they fit.
        model_cfg = dataclasses.replace(
            model_cfg, flash_block_q=flash_block, flash_block_k=flash_block
        )
    if attn_impl:
        # Attention-impl A/B (the flash kernel has to EARN its 763 lines):
        # long4k with attention_impl="xla" materializes the (B,H,S,S) fp32
        # scores the way the reference does — if XLA's own lowering matches
        # the Pallas kernel on-chip, flash should not be the default.
        model_cfg = dataclasses.replace(model_cfg, attention_impl=attn_impl)
    if mode == "smallvocab":
        model_cfg = dataclasses.replace(model_cfg, target_vocab_size=2048)
    dev = jax.devices()[0]
    state = create_train_state(jax.random.PRNGKey(0), model_cfg, train_cfg)
    rng = jax.random.PRNGKey(1)
    r = np.random.default_rng(0)
    top = min(32000, model_cfg.target_vocab_size - 2)
    if mode == "multistep":
        # The PRODUCTION dispatch-amortization path (TrainConfig.
        # steps_per_dispatch): distinct stacked batches, one (K,B,S) host
        # transfer, K real optimizer steps per dispatch — unlike deviceloop
        # (same batch re-scanned), this is what a training run would see.
        src = jax.device_put(
            r.integers(1, top, (n_steps, batch, seq), dtype=np.int32)
        )
        tgt = jax.device_put(
            r.integers(1, top, (n_steps, batch, seq), dtype=np.int32)
        )
    else:
        src = jax.device_put(r.integers(1, top, (batch, seq), dtype=np.int32))
        tgt = jax.device_put(r.integers(1, top, (batch, seq), dtype=np.int32))

    # Donated-state step except for tied-weight configs: donation aliases one
    # buffer into two consumers there, which the TPU backend rejects at
    # EXECUTION time — and a failed donated execution wedges the tunnel's
    # claim lease (see .claude/skills/verify/SKILL.md), so decide statically
    # rather than probing by running a doomed step.
    donate = not (model_cfg.tie_embeddings or model_cfg.tie_output)
    if mode == "fwd":
        eval_step = jax.jit(make_eval_step(model_cfg, train_cfg))
        step = lambda state, src, tgt, rng: (state, eval_step(state, src, tgt))  # noqa: E731
    elif mode == "deviceloop":
        inner = make_train_step(model_cfg, train_cfg)

        def scan_steps(state, src, tgt, rng):
            def body(s, _):
                return inner(s, src, tgt, rng)

            state, ms = jax.lax.scan(body, state, None, length=n_steps)
            # The last step's metrics are a scan output: fetching them still
            # blocks on the whole device loop (VALUE-fetch sync contract).
            return state, jax.tree.map(lambda x: x[-1], ms)

        step = jax.jit(scan_steps, donate_argnums=(0,) if donate else ())
    elif mode == "multistep":
        from transformer_tpu.train.trainer import make_multistep_train_step

        step = jax.jit(
            make_multistep_train_step(make_train_step(model_cfg, train_cfg)),
            donate_argnums=(0,) if donate else (),
        )
    else:
        step = jax.jit(
            make_train_step(model_cfg, train_cfg),
            donate_argnums=(0,) if donate else (),
        )
    if not donate:
        print(f"{name}: tied weights, benchmarking undonated", file=sys.stderr)

    warmups = 2 if mode in ("deviceloop", "multistep") else 3  # compile + settle
    for _ in range(warmups):
        state, metrics = step(state, src, tgt, rng)
    # Synchronize via a VALUE fetch, not block_until_ready: on tunneled/
    # remote PJRT backends block_until_ready can return before device
    # execution finishes, inflating throughput ~10x. float() cannot lie.
    float(metrics["loss"])

    import contextlib

    ctx = (
        jax.profiler.trace(profile_dir) if profile_dir else contextlib.nullcontext()
    )
    with ctx:
        t0 = time.perf_counter()
        if mode in ("deviceloop", "multistep"):
            # ONE dispatch covering all n_steps optimizer steps on device.
            state, metrics = step(state, src, tgt, rng)
            final_loss = float(metrics["loss"])
            dt = time.perf_counter() - t0
        else:
            for _ in range(n_steps):
                state, metrics = step(state, src, tgt, rng)
            final_loss = float(metrics["loss"])
            dt = time.perf_counter() - t0
    assert final_loss == final_loss, "NaN loss"  # keep the fetch load-bearing

    tokens_per_step = batch * (seq - 1)
    value = tokens_per_step * n_steps / dt
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(state.params))
    tag = (
        (f" [{mode}]" if mode != "full" else "")
        + (f" [chunks={loss_chunks}]" if loss_chunks > 1 else "")
        + (f" [b{batch}xs{seq}]" if batch_override or seq_override else "")
        + (f" [fb{flash_block}]" if flash_block else "")
        + (f" [{attn_impl}]" if attn_impl else "")
    )
    return {
        "metric": f"{name} train throughput" + tag,
        "value": round(value, 1),
        "unit": "tokens/sec/chip",
        "config": {
            "layers": model_cfg.num_layers,
            "d_model": model_cfg.d_model,
            "heads": model_cfg.num_heads,
            "dff": model_cfg.dff,
            "batch": batch,
            "seq": seq,
            "decoder_only": model_cfg.decoder_only,
            "params_millions": round(n_params / 1e6, 1),
        },
        "step_ms": round(dt / n_steps * 1e3, 2),
        "device": f"{dev.platform}:{dev.device_kind}",
        "vs_baseline": None,  # reference publishes no numbers (BASELINE.md)
    }


def _bench_decode(
    name: str, model_cfg, batch: int, seq: int, n_iters: int, mode: str
) -> dict:
    """Greedy-decode throughput: generated tokens/sec with the KV cache
    (fp, or int8 when mode == 'decodeq8'). EOS is set outside the vocab so
    every row decodes the full max_len — deterministic token counts."""
    import dataclasses
    import time as _time

    import jax
    import numpy as np

    from transformer_tpu.train.decode import greedy_decode

    if mode == "decodeq8":
        model_cfg = dataclasses.replace(model_cfg, kv_cache_int8=True)
    # Serving shape: decode length = the config's training sequence length,
    # batch capped so the long4k cache fits comfortably.
    batch = min(batch, 32)
    max_len = min(seq, 128)
    src_len = min(seq, 64)
    dev = jax.devices()[0]
    from transformer_tpu.models import transformer_init

    params = transformer_init(jax.random.PRNGKey(0), model_cfg)
    r = np.random.default_rng(0)
    if model_cfg.decoder_only:
        # Long-context LM continuation — the int8-KV-cache showcase shape:
        # a long prompt fills the cache (prefill rides the same scan), then
        # generation attends over the whole context every step.
        from transformer_tpu.train.decode import lm_generate

        batch = min(batch, 4)
        prompt_len = min(seq // 2, 2048)
        max_len = min(seq - prompt_len, 512)
        prompt = jax.device_put(
            r.integers(
                1, model_cfg.target_vocab_size - 2, (batch, prompt_len),
                dtype=np.int32,
            )
        )
        run = lambda: lm_generate(  # noqa: E731
            params, prompt, model_cfg, max_new=max_len,
            eos_id=model_cfg.target_vocab_size + 7,  # unreachable: full rows
        )
        src_len = prompt_len
    else:
        src = jax.device_put(
            r.integers(
                1, model_cfg.input_vocab_size - 2, (batch, src_len),
                dtype=np.int32,
            )
        )
        run = lambda: greedy_decode(  # noqa: E731
            params, src, model_cfg, max_len=max_len,
            bos_id=model_cfg.target_vocab_size - 2,
            eos_id=model_cfg.target_vocab_size + 7,  # unreachable: full-length rows
        )
    out = run()
    np.asarray(out)  # VALUE-fetch sync (block_until_ready lies via tunnel)
    t0 = _time.perf_counter()
    for _ in range(n_iters):
        out = run()
    np.asarray(out)
    dt = _time.perf_counter() - t0
    value = batch * max_len * n_iters / dt
    return {
        "metric": f"{name} decode throughput [{mode}]",
        "value": round(value, 1),
        "unit": "generated tokens/sec/chip",
        "config": {
            "batch": batch, "src_len": src_len, "max_len": max_len,
            "kv_cache_int8": model_cfg.kv_cache_int8,
        },
        "ms_per_token": round(dt / (max_len * n_iters) * 1e3, 3),
        # Serving view of the same measurement (cli.serve --serve_batch
        # aggregates concurrent requests into exactly this shape): each
        # decode completes `batch` requests together, so p50 request
        # latency = one decode's wall time.
        "requests_per_sec": round(batch * n_iters / dt, 2),
        "p50_request_ms": round(dt / n_iters * 1e3, 1),
        "device": f"{dev.platform}:{dev.device_kind}",
        "vs_baseline": None,  # reference decode is broken (SURVEY §2.3.2/.11)
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument(
        "--configs", default="tiny,base,big,tied,long4k",
        help="comma-separated subset",
    )
    ap.add_argument(
        "--modes", default="full",
        help="comma-separated subset of full,fwd,smallvocab,deviceloop,"
        "multistep (step-time attribution; deviceloop = all steps in one "
        "jitted scan of ONE batch, isolating per-step dispatch overhead; "
        "multistep = the production steps_per_dispatch path: distinct "
        "stacked batches, one transfer + K steps per dispatch)",
    )
    ap.add_argument(
        "--profile_dir", default="",
        help="capture a jax.profiler trace of the timing loop into this dir",
    )
    ap.add_argument(
        "--loss_chunks", type=int, default=1,
        help="A/B the chunked vocab-projection/CE path (TrainConfig."
        "loss_chunks); 1 = monolithic loss",
    )
    ap.add_argument(
        "--batch", type=int, default=0,
        help="override the config's batch size (MFU-ceiling probes; 0 = keep)",
    )
    ap.add_argument(
        "--seq", type=int, default=0,
        help="override the config's sequence length (0 = keep)",
    )
    ap.add_argument(
        "--flash_block", type=int, default=0,
        help="override flash_block_q/k (flash-kernel tile sweep; 0 = keep)",
    )
    ap.add_argument(
        "--attn_impl", default="",
        help="override ModelConfig.attention_impl (flash-vs-xla A/B at "
        "long4k; empty = keep the config's impl)",
    )
    args = ap.parse_args()
    names = [n.strip() for n in args.configs.split(",") if n.strip()]
    modes = [m.strip() for m in args.modes.split(",") if m.strip()]
    known = {
        "full", "fwd", "smallvocab", "deviceloop", "multistep",
        "decode", "decodeq8",
    }
    bad = [m for m in modes if m not in known]
    if bad:  # an unknown mode would silently time the full step mislabeled
        ap.error(f"unknown mode(s) {bad}; choose from {sorted(known)}")

    if len(names) * len(modes) > 1:
        # One subprocess per measurement: a backend error (e.g. a rejected
        # donated execution) can poison the TPU client for the process.
        import subprocess

        for name in names:
            for mode in modes:
                subprocess.run(
                    [sys.executable, __file__, "--steps", str(args.steps),
                     "--configs", name, "--modes", mode,
                     "--profile_dir", args.profile_dir,
                     "--loss_chunks", str(args.loss_chunks),
                     "--batch", str(args.batch), "--seq", str(args.seq),
                     "--flash_block", str(args.flash_block),
                     "--attn_impl", args.attn_impl],
                    check=False,
                )
        return

    name, mode = names[0], modes[0]
    print(f"benchmarking {name} [{mode}]...", file=sys.stderr)
    try:
        print(
            json.dumps(
                bench_config(
                    name, args.steps, mode, args.profile_dir,
                    loss_chunks=args.loss_chunks,
                    batch_override=args.batch, seq_override=args.seq,
                    flash_block=args.flash_block, attn_impl=args.attn_impl,
                )
            ),
            flush=True,
        )
    except Exception as e:  # record the failure as a JSON line
        # Same tag as the success path, so failures attribute to the right
        # mode/variant in the rows file (the watchdog's least-failed
        # selection greps these exact strings).
        shapes = ""
        if args.batch or args.seq:
            b, s = _configs()[name][2:]
            shapes = f" [b{args.batch or b}xs{args.seq or s}]"
        tag = (
            (f" [{mode}]" if mode != "full" else "")
            + (f" [chunks={args.loss_chunks}]" if args.loss_chunks > 1 else "")
            + shapes
            + (f" [fb{args.flash_block}]" if args.flash_block else "")
            + (f" [{args.attn_impl}]" if args.attn_impl else "")
        )
        print(
            json.dumps(
                {"metric": f"{name} train throughput{tag}", "error": str(e)}
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
