"""Score ANY bleu_run checkpoint (including an in-flight run's latest) on
the held-out test split, without touching the training process.

    python benchmarks/score_ckpt.py --workdir .bleu_runs/bleu_run_<hash> \
        --config small [--dtype float32] [--step N] [--beam 4]

Prints one JSON line: {"metric": ..., "bleu": ..., "step": ..., ...}.
Exists because resumable runs only self-score at their final epoch target
(``bleu_run.py``): when a relay outage or round boundary lands mid-run, the
partial convergence is still checkpointed — this recovers a real number
from it. Reconstructs the model EXACTLY as bleu_run does (same shapes
table, the run's own workdir vocabs, same specials).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", required=True, help="the bleu_run workdir")
    ap.add_argument(
        "--config", default=None,
        choices=["tiny", "small", "medium", "base"],
        help="default: read from the run's own args.json (falls back to "
        "'small' for pre-args.json workdirs) — the scorer must rebuild the "
        "run's architecture, not its own default's",
    )
    ap.add_argument("--dtype", default=None)
    ap.add_argument("--step", type=int, default=0, help="0 = latest")
    ap.add_argument("--beam", type=int, default=1)
    ap.add_argument("--seq_len", type=int, default=None,
                    help="the run's --seq_len (sizes the positional table); "
                    "default: from the run's args.json")
    ap.add_argument("--holdout", type=int, default=-1,
                    help="-1 (default): read the run's own --holdout from "
                    "the args.json bleu_run persists in its workdir (emits "
                    "null if the run predates that file) — the label is "
                    "derived from the run, not from this scorer's flags, so "
                    "an in-sample run can't be mislabeled held-out by a "
                    "default; 0/1 override explicitly")
    ap.add_argument("--best", action="store_true",
                    help="score the run's keep-best params snapshot "
                    "(workdir/best, written by --stop_patience/--bleu_every "
                    "probes) instead of a checkpoint step")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--bleu_max_len", type=int, default=None)
    ap.add_argument("--data_dir", default=os.path.join(REPO, "data"))
    args = ap.parse_args()

    # Model-shaping parameters default to the RUN'S OWN (args.json, written
    # by bleu_run next to the vocabs): a scorer default that disagrees with
    # the run would restore garbage (wrong architecture) or mis-size the
    # positional table. Explicit flags still override; pre-args.json
    # workdirs fall back to the historical defaults.
    run_args = {}
    run_args_path = os.path.join(args.workdir, "args.json")
    if os.path.exists(run_args_path):
        with open(run_args_path) as f:
            run_args = json.load(f)
    for name, fallback in (
        ("config", "small"), ("dtype", "float32"), ("seq_len", 50),
        ("batch", 64), ("bleu_max_len", 64),
    ):
        if getattr(args, name) is None:
            setattr(args, name, run_args.get(name, fallback))

    import jax

    from transformer_tpu.config import ModelConfig, TrainConfig
    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.train import CheckpointManager, create_train_state
    from transformer_tpu.train.evaluate import bleu_on_pairs, read_lines
    from transformer_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    src_tok = SubwordTokenizer.load(os.path.join(args.workdir, "src_vocab.subwords"))
    tgt_tok = SubwordTokenizer.load(os.path.join(args.workdir, "tgt_vocab.subwords"))
    from bleu_run import CONFIG_SHAPES  # benchmarks/ sibling: one table

    shapes = CONFIG_SHAPES[args.config]
    model_cfg = ModelConfig(
        **shapes,
        input_vocab_size=src_tok.model_vocab_size,
        target_vocab_size=tgt_tok.model_vocab_size,
        max_position=max(args.seq_len, args.bleu_max_len, 64),
        dropout_rate=0.1,
        dtype=args.dtype,
    )
    state = create_train_state(
        jax.random.PRNGKey(0), model_cfg,
        TrainConfig(batch_size=args.batch, sequence_length=args.seq_len, warmup_steps=2000),
    )
    # The holdout label comes from the run itself (args.json, persisted by
    # bleu_run next to the vocabs) unless explicitly overridden: a scorer
    # flag default must not be able to label an in-sample run "held out".
    holdout: bool | None = bool(args.holdout) if args.holdout >= 0 else None
    if holdout is None and "holdout" in run_args:
        holdout = bool(run_args["holdout"])

    if args.best:
        from transformer_tpu.train import load_exported_params

        if args.step:
            raise SystemExit(
                "--best scores the keep-best snapshot (no checkpoint step); "
                "drop --step or drop --best"
            )
        best_dir = os.path.join(args.workdir, "best")
        if not os.path.isdir(best_dir):
            raise SystemExit(f"no keep-best snapshot at {best_dir}")
        params = load_exported_params(best_dir, state.params)
        probe_path = os.path.join(args.workdir, "probe_bleu.json")
        best_epoch = None
        if os.path.exists(probe_path):
            with open(probe_path) as f:
                best_epoch = json.load(f).get("best_epoch")
        which = (
            f"best snapshot (epoch {best_epoch})" if best_epoch
            else "best snapshot"
        )
        step = 0
    else:
        ckpt = CheckpointManager(os.path.join(args.workdir, "ckpt"), 2)
        step = args.step or ckpt.latest_step
        if not step:
            raise SystemExit(f"no checkpoints in {args.workdir}/ckpt")
        params = ckpt.restore(state, step).params
        which = f"ckpt step {step}"
    src_lines = read_lines(os.path.join(args.data_dir, "src-test.txt"))
    ref_lines = read_lines(os.path.join(args.data_dir, "tgt-test.txt"))
    t0 = time.perf_counter()
    bleu, _ = bleu_on_pairs(
        params, model_cfg, src_tok, tgt_tok, src_lines, ref_lines,
        batch_size=args.batch, max_len=args.bleu_max_len,
        beam_size=args.beam,
    )
    print(
        json.dumps(
            {
                "metric": f"{args.config} corpus BLEU [{which}"
                + (f", beam{args.beam}" if args.beam > 1 else ", greedy")
                + "]",
                "bleu": round(bleu, 2),
                "n_pairs": len(src_lines),
                "step": int(step),
                "holdout": holdout,
                "eval_seconds": round(time.perf_counter() - t0, 1),
                "device": f"{jax.devices()[0].platform}",
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
