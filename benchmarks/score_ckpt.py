"""Score ANY bleu_run checkpoint (including an in-flight run's latest) on
the held-out test split, without touching the training process.

    python benchmarks/score_ckpt.py --workdir /tmp/bleu_run_<hash> \
        --config small [--dtype float32] [--step N] [--beam 4]

Prints one JSON line: {"metric": ..., "bleu": ..., "step": ..., ...}.
Exists because resumable runs only self-score at their final epoch target
(``bleu_run.py``): when a relay outage or round boundary lands mid-run, the
partial convergence is still checkpointed — this recovers a real number
from it. Reconstructs the model EXACTLY as bleu_run does (same shapes
table, the run's own workdir vocabs, same specials).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", required=True, help="the bleu_run workdir")
    ap.add_argument(
        "--config", default="small",
        choices=["tiny", "small", "medium", "base"],
    )
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--step", type=int, default=0, help="0 = latest")
    ap.add_argument("--beam", type=int, default=1)
    ap.add_argument("--seq_len", type=int, default=50,
                    help="the run's --seq_len (sizes the positional table)")
    ap.add_argument("--holdout", type=int, default=1,
                    help="the run's --holdout (recorded in the output; a "
                    "--holdout 0 run's score is IN-sample)")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--bleu_max_len", type=int, default=64)
    ap.add_argument("--data_dir", default=os.path.join(REPO, "data"))
    args = ap.parse_args()

    import jax

    from transformer_tpu.config import ModelConfig, TrainConfig
    from transformer_tpu.data.tokenizer import SubwordTokenizer
    from transformer_tpu.train import CheckpointManager, create_train_state
    from transformer_tpu.train.evaluate import bleu_on_pairs, read_lines
    from transformer_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    src_tok = SubwordTokenizer.load(os.path.join(args.workdir, "src_vocab.subwords"))
    tgt_tok = SubwordTokenizer.load(os.path.join(args.workdir, "tgt_vocab.subwords"))
    from bleu_run import CONFIG_SHAPES  # benchmarks/ sibling: one table

    shapes = CONFIG_SHAPES[args.config]
    model_cfg = ModelConfig(
        **shapes,
        input_vocab_size=src_tok.model_vocab_size,
        target_vocab_size=tgt_tok.model_vocab_size,
        max_position=max(args.seq_len, args.bleu_max_len, 64),
        dropout_rate=0.1,
        dtype=args.dtype,
    )
    state = create_train_state(
        jax.random.PRNGKey(0), model_cfg,
        TrainConfig(batch_size=args.batch, sequence_length=args.seq_len, warmup_steps=2000),
    )
    ckpt = CheckpointManager(os.path.join(args.workdir, "ckpt"), 2)
    step = args.step or ckpt.latest_step
    if not step:
        raise SystemExit(f"no checkpoints in {args.workdir}/ckpt")
    state = ckpt.restore(state, step)
    src_lines = read_lines(os.path.join(args.data_dir, "src-test.txt"))
    ref_lines = read_lines(os.path.join(args.data_dir, "tgt-test.txt"))
    t0 = time.perf_counter()
    bleu, _ = bleu_on_pairs(
        state.params, model_cfg, src_tok, tgt_tok, src_lines, ref_lines,
        batch_size=args.batch, max_len=args.bleu_max_len,
        beam_size=args.beam,
    )
    print(
        json.dumps(
            {
                "metric": f"{args.config} corpus BLEU [ckpt step {step}"
                + (f", beam{args.beam}" if args.beam > 1 else ", greedy")
                + "]",
                "bleu": round(bleu, 2),
                "n_pairs": len(src_lines),
                "step": int(step),
                "holdout": bool(args.holdout),
                "eval_seconds": round(time.perf_counter() - t0, 1),
                "device": f"{jax.devices()[0].platform}",
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
