"""transformer_tpu.analysis — JAX-aware static analysis for this codebase.

Three passes, one CLI (``python -m transformer_tpu.analysis``):

- :mod:`.rules` — AST lint rules (TPA001–TPA006) for the silent-bug classes
  jit-heavy code grows: traced-value branches, numpy-on-tracer, mutable
  closure state, stale ``static_argnames``, donated-buffer reuse, broad
  exception swallowing in library modules. Inline ``# tpa: disable=`` and a
  checked-in baseline (``analysis/baseline.json``) handle grandfathering.
- :mod:`.contracts` — abstract shape/dtype contract checks over the public
  entry points via ``jax.eval_shape``/``jax.make_jaxpr``: f32 softmax,
  prefill/step cache-layout parity across all cache variants, mask
  broadcastability, residual-dtype stability, decode output shapes,
  optimizer dtype preservation. No device execution.
- :mod:`.retrace` — compile-count sentinel (``_cache_size`` accounting)
  failing when the steady-state decode/train hot paths retrace beyond a
  declared budget, plus ``jax.checking_leaks`` wiring.

Everything here is import-light: importing the package costs nothing until a
pass actually runs (the lint rules never import the modules they analyze).
"""

from transformer_tpu.analysis.contracts import ContractResult, run_contracts
from transformer_tpu.analysis.retrace import RetraceSentinel, leak_checking
from transformer_tpu.analysis.rules import (
    RULES,
    Finding,
    RulesReport,
    run_rules,
)

__all__ = [
    "RULES",
    "Finding",
    "RulesReport",
    "run_rules",
    "ContractResult",
    "run_contracts",
    "RetraceSentinel",
    "leak_checking",
]
