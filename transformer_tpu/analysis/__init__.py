"""transformer_tpu.analysis — JAX-aware static analysis for this codebase.

Three passes, one CLI (``python -m transformer_tpu.analysis``):

- :mod:`.rules` — AST lint rules (TPA001–TPA006) for the silent-bug classes
  jit-heavy code grows: traced-value branches, numpy-on-tracer, mutable
  closure state, stale ``static_argnames``, donated-buffer reuse, broad
  exception swallowing in library modules. Inline ``# tpa: disable=`` and a
  checked-in baseline (``analysis/baseline.json``) handle grandfathering.
- :mod:`.concurrency` — concurrency rules (TPA101–TPA105) over the host
  threading surface: thread-root inference, shared-state guard discipline,
  lock-order cycles, non-atomic RMW, blocking-under-lock. Same suppression
  workflow, separate baseline (``analysis/concurrency_baseline.json``).
- :mod:`.schedules` — the dynamic counterpart: a deterministic cooperative
  scheduler that explores thread interleavings over canned serving-tier
  scenarios (prefix-cache contention, registry scrape, prefetch shutdown,
  event-log writers), asserting invariants under every explored schedule.
- :mod:`.contracts` — abstract shape/dtype contract checks over the public
  entry points via ``jax.eval_shape``/``jax.make_jaxpr``: f32 softmax,
  prefill/step cache-layout parity across all cache variants, mask
  broadcastability, residual-dtype stability, decode output shapes,
  optimizer dtype preservation. No device execution.
- :mod:`.retrace` — compile-count sentinel (``_cache_size`` accounting)
  failing when the steady-state decode/train hot paths retrace beyond a
  declared budget, plus ``jax.checking_leaks`` wiring.
- :mod:`.costs` — the jaxpr resource cost model: donation-aware peak
  live-buffer bytes, dot/conv/reduce FLOPs, bytes moved, arithmetic
  intensity, and KV-cache budgets per cache variant, gated against
  checked-in budgets (``analysis/costs_baseline.json``).
- :mod:`.sharding` — the collective inventory for ``shard_map`` programs
  (kind, mesh axis, scan-weighted count, estimated comm bytes) plus
  sharding lints TPA201–TPA205 (unconstrained boundary shardings,
  mesh-axis typos, donation/layout mismatches, collectives in the decode
  hot loop, replicated large params); baseline
  ``analysis/sharding_baseline.json``.
- :mod:`.baselines` — the shared finding/fingerprint/suppression/baseline
  plumbing every lint family rides.

Everything here is import-light: importing the package costs nothing until a
pass actually runs (the lint rules never import the modules they analyze).
"""

from transformer_tpu.analysis.baselines import Finding, RulesReport
from transformer_tpu.analysis.concurrency import (
    CONCURRENCY_RULES,
    run_concurrency,
)
from transformer_tpu.analysis.contracts import ContractResult, run_contracts
from transformer_tpu.analysis.costs import (
    CostReport,
    kv_cache_bytes,
    program_costs,
    run_costs,
)
from transformer_tpu.analysis.retrace import RetraceSentinel, leak_checking
from transformer_tpu.analysis.rules import (
    RULES,
    run_rules,
)
from transformer_tpu.analysis.schedules import (
    ScenarioResult,
    explore,
    run_scenarios,
)
from transformer_tpu.analysis.sharding import (
    SHARDING_RULES,
    collective_inventory,
    run_sharding,
)

__all__ = [
    "RULES",
    "CONCURRENCY_RULES",
    "SHARDING_RULES",
    "Finding",
    "RulesReport",
    "run_rules",
    "run_concurrency",
    "run_sharding",
    "CostReport",
    "program_costs",
    "kv_cache_bytes",
    "run_costs",
    "collective_inventory",
    "ScenarioResult",
    "explore",
    "run_scenarios",
    "ContractResult",
    "run_contracts",
    "RetraceSentinel",
    "leak_checking",
]
