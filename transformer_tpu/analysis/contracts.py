"""Abstract shape/dtype contract checking — no device execution.

Every check traces public entry points with ``jax.eval_shape`` or
``jax.make_jaxpr`` over abstract ``ShapeDtypeStruct`` inputs (even the
parameter pytree is abstract: ``transformer_init`` is itself eval_shape'd),
so the whole suite is CPU-safe, allocation-free, and fast enough for tier-1.
This is the Mesh-TensorFlow lesson (PAPERS.md) applied to this repo: the
invariants the code PROMISES in its docstrings become machine-checked
contracts that fail at trace time, rounds before a TPU would have noticed.

Contracts:

- **cache_parity** — prefill and incremental decode must produce caches
  with identical pytree structure, shapes, AND dtypes for every cache
  variant (plain bf16, int8+scales, rolling window, GQA). A drift here is
  the classic silent serving bug: the slot pool admits via prefill but
  steps incrementally, so a mismatch poisons every request after the first.
- **verify_cache_parity** — a speculative verify forward (one S_q = k+1
  call through ``transformer_verify``) must leave caches structurally
  indistinguishable from k+1 repeated incremental steps, and return
  per-position logits — the speculative scheduler interleaves the two
  paths (plus index rollback) over one slot pool.
- **prefix_restore_parity** — a slot cache rebuilt from prefix-cache KV
  blocks (``ops.attention.slice_kv_blocks`` → ``insert_kv_blocks``) must
  equal a chunk-prefilled cache in structure, shape, and dtype across
  plain/int8/GQA layouts: cache-hit admissions prefill the unmatched
  suffix INTO the restored cache, so restore/prefill drift poisons every
  hit.
- **softmax_f32** — ``dot_product_attention`` promises its softmax runs in
  fp32 even under bf16 compute (``ops/attention.py``); checked by walking
  the jaxpr of the forward for ``exp`` equations and asserting their
  operands are f32.
- **residual_dtype** — the residual stream must stay in
  ``cfg.compute_dtype`` end to end (no silent bf16→f32 promotion that would
  double HBM traffic and MXU pressure).
- **mask_broadcast** — padding/causal/cache-prefix masks must broadcast
  against (B, H, S_q, S_k) attention logits.
- **decode_shapes** — greedy/beam/LM decode return (B, max_len)/(B,
  max_new) int32 ids.
- **train_step_dtypes** — one abstract optimizer step preserves every
  parameter's dtype (param_dtype, not compute dtype) and advances ``step``.
- **telemetry_inert** — the obs instrumentation wrappers
  (``obs.telemetry.timed_call`` composed with ``obs.trace.traced_call`` —
  exactly what the Trainer installs around its jitted step dispatches when
  telemetry/tracing are on) must produce a jaxpr BYTE-IDENTICAL to the
  uninstrumented twin's for the train step AND the serving pool step, slot
  prefill, and speculative verify programs (tracing-on vs. tracing-off;
  the scheduler's own span recording is inline host code at step
  boundaries): telemetry records host-side scalars and can never leak an
  operation into traced code.
- **fault_plane_inert** — an ARMED fault plane (``serve.resilience``)
  must leave the serving hot paths' jaxprs byte-identical to the
  disarmed twin's: injection points live in host code between dispatches
  (admission, drafter calls, sink writes), never inside a trace. Any
  future "optimization" that threads a fault flag into a jitted function
  — minting a recompile per breaker flip, the exact bug the
  ``resilience_retrace_report`` budget guards at runtime — fails here
  abstractly first. The check also proves the plane is LIVE while armed
  (a fired point raises), so the identity is not vacuous.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from transformer_tpu.analysis.configs import TINY_TRAIN, matrix
from transformer_tpu.config import ModelConfig

_KEY = jax.ShapeDtypeStruct((2,), np.uint32)  # abstract PRNGKey


@dataclasses.dataclass(frozen=True)
class ContractResult:
    contract: str
    config: str
    ok: bool
    detail: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        mark = "PASS" if self.ok else "FAIL"
        return f"{mark} {self.contract}[{self.config}] {self.detail}"


def _ids(batch: int, length: int) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct((batch, length), np.int32)


def abstract_params(cfg: ModelConfig):
    """The parameter pytree as ShapeDtypeStructs — nothing is allocated."""
    from transformer_tpu.models.transformer import transformer_init

    return jax.eval_shape(lambda k: transformer_init(k, cfg), _KEY)


def _tree_spec(tree) -> list[tuple[str, tuple, str]]:
    """Canonical (path, shape, dtype) list for structure+layout comparison."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return [
        (jax.tree_util.keystr(path), tuple(leaf.shape), str(leaf.dtype))
        for path, leaf in flat
    ]


# --------------------------------------------------------------------------
# individual contracts (each returns a detail string or raises AssertionError)


def check_cache_parity(cfg: ModelConfig, batch: int = 2, n: int = 4) -> str:
    """Prefill-built caches and step-built caches must be indistinguishable
    in structure, shape, and dtype (the serving scheduler mixes the two
    paths over one slot pool)."""
    from transformer_tpu.models.decoder import (
        init_decoder_caches,
        precompute_cross_kvs,
    )
    from transformer_tpu.models.encoder import encoder_apply
    from transformer_tpu.models.transformer import (
        transformer_decode_step,
        transformer_prefill,
    )
    from transformer_tpu.ops.masks import make_padding_mask

    params = abstract_params(cfg)
    total = 16

    def encoder_state(params, tokens):
        # Seq2seq decode attends a (static) encoder output through
        # precomputed cross K/Vs — the same wiring greedy_decode uses.
        if cfg.decoder_only:
            return None, None, None
        enc_mask = make_padding_mask(tokens)
        enc_out, _ = encoder_apply(params["encoder"], tokens, enc_mask, cfg)
        return enc_out, enc_mask, precompute_cross_kvs(
            params["decoder"], enc_out, cfg
        )

    def prefill_path(params, tokens):
        enc_out, enc_mask, cross_kvs = encoder_state(params, tokens)
        caches = init_decoder_caches(cfg, batch, total)
        _, caches = transformer_prefill(
            params, tokens, enc_out, enc_mask, caches, 0, cfg,
            cross_kvs=cross_kvs,
        )
        return caches

    def step_path(params, tokens):
        enc_out, enc_mask, cross_kvs = encoder_state(params, tokens)
        caches = init_decoder_caches(cfg, batch, total)
        for i in range(n):
            _, caches = transformer_decode_step(
                params, tokens[:, i : i + 1], enc_out, enc_mask, caches, i,
                cfg, cross_kvs=cross_kvs,
            )
        return caches

    tokens = _ids(batch, n)
    via_prefill = jax.eval_shape(prefill_path, params, tokens)
    via_steps = jax.eval_shape(step_path, params, tokens)
    a, b = _tree_spec(via_prefill), _tree_spec(via_steps)
    assert a == b, (
        "prefill and incremental step disagree on cache layout/dtype:\n"
        f"  prefill: {a}\n  steps:   {b}"
    )
    # The variant-specific storage promises, stated explicitly:
    leaf = {path: (shape, dtype) for path, shape, dtype in a}
    k_path = next(p for p in leaf if p.endswith("['k']"))
    if cfg.kv_cache_int8:
        assert leaf[k_path][1] == "int8", f"int8 cache stores k as {leaf[k_path][1]}"
        scale_path = next(p for p in leaf if p.endswith("['k_scale']"))
        assert leaf[scale_path][1] == "float32", "int8 scales must be fp32"
    else:
        assert leaf[k_path][1] == str(cfg.compute_dtype), (
            f"cache k dtype {leaf[k_path][1]} != compute dtype {cfg.compute_dtype}"
        )
    buf_len = leaf[k_path][0][1]
    if cfg.attention_window:
        expected = min(cfg.attention_window, total)
        assert buf_len == expected, (
            f"rolling cache buffer is {buf_len} slots, want {expected}"
        )
    else:
        assert buf_len == total, f"cache buffer {buf_len} != max_len {total}"
    kv_heads = leaf[k_path][0][2]
    assert kv_heads == cfg.kv_heads, (
        f"cache carries {kv_heads} kv heads, config says {cfg.kv_heads}"
    )
    return f"{len(a)} cache leaves identical across prefill/step"


def check_verify_cache_parity(cfg: ModelConfig, batch: int = 2, k: int = 3) -> str:
    """One speculative verify forward (S_q = k + 1 through
    ``transformer_verify``) and ``k + 1`` repeated incremental steps must
    leave caches with identical pytree structure, shapes, AND dtypes — the
    speculative scheduler interleaves verify forwards, single-token steps,
    and index rollback over ONE slot pool, so any layout drift between the
    paths poisons every request that follows a mixed step. Verify must
    also return per-position logits (B, k + 1, V) whose dtype matches the
    step path's — the acceptance rule compares them position by position."""
    from transformer_tpu.models.decoder import init_decoder_caches
    from transformer_tpu.models.transformer import (
        transformer_decode_step,
        transformer_verify,
    )

    total = 16
    params = abstract_params(cfg)

    def verify_path(params, tokens):
        caches = init_decoder_caches(cfg, batch, total)
        return transformer_verify(params, tokens, caches, 0, cfg)

    def step_path(params, tokens):
        caches = init_decoder_caches(cfg, batch, total)
        logits = None
        for i in range(k + 1):
            logits, caches = transformer_decode_step(
                params, tokens[:, i : i + 1], None, None, caches, i, cfg
            )
        return logits, caches

    tokens = _ids(batch, k + 1)
    v_logits, via_verify = jax.eval_shape(verify_path, params, tokens)
    s_logits, via_steps = jax.eval_shape(step_path, params, tokens)
    a, b = _tree_spec(via_verify), _tree_spec(via_steps)
    assert a == b, (
        "speculative verify and repeated incremental steps disagree on "
        f"cache layout/dtype:\n  verify: {a}\n  steps:  {b}"
    )
    want = (batch, k + 1, cfg.target_vocab_size)
    assert v_logits.shape == want, (
        f"verify logits are {v_logits.shape}, want per-position {want}"
    )
    assert v_logits.dtype == s_logits.dtype, (
        f"verify logits dtype {v_logits.dtype} != step logits dtype "
        f"{s_logits.dtype} — the acceptance comparison would mix dtypes"
    )
    return (
        f"{len(a)} cache leaves identical across verify/{k + 1} steps; "
        f"logits {want} {v_logits.dtype}"
    )


def check_prefix_restore_parity(
    cfg: ModelConfig, batch: int = 1, blocks: int = 2, block: int = 4
) -> str:
    """A slot cache rebuilt from prefix-cache blocks (``slice_kv_blocks`` →
    ``insert_kv_blocks`` round trip, index advanced to the restored width)
    must be structurally indistinguishable — pytree structure, shapes, AND
    dtypes — from one chunk-prefilled over the same tokens: the scheduler
    prefills the unmatched SUFFIX into the restored cache and then decodes
    incrementally, so any layout drift between restore and prefill poisons
    every cache-hit request. Traced abstractly (eval_shape) across
    plain/int8/GQA layouts; rolling-window configs are excluded (the prefix
    cache refuses them at construction)."""
    from transformer_tpu.models.decoder import init_decoder_caches
    from transformer_tpu.models.transformer import transformer_prefill
    from transformer_tpu.ops.attention import insert_kv_blocks, slice_kv_blocks

    total = 16
    n = blocks * block
    params = abstract_params(cfg)

    def prefill_path(params, tokens):
        caches = init_decoder_caches(cfg, batch, total)
        _, caches = transformer_prefill(
            params, tokens, None, None, caches, 0, cfg, chunk=block
        )
        return caches

    def restore_path(params, tokens):
        donor = prefill_path(params, tokens)
        fresh = init_decoder_caches(cfg, batch, total)
        out = []
        for d, c in zip(donor, fresh):
            for j in range(blocks):
                c = insert_kv_blocks(
                    c, slice_kv_blocks(d, j * block, block), j * block
                )
            out.append(dict(c, index=jnp.asarray(n, jnp.int32)))
        return out

    tokens = _ids(batch, n)
    a = _tree_spec(jax.eval_shape(prefill_path, params, tokens))
    b = _tree_spec(jax.eval_shape(restore_path, params, tokens))
    assert a == b, (
        "trie-restored and chunk-prefilled caches disagree on "
        f"layout/dtype:\n  prefill: {a}\n  restore: {b}"
    )
    return (
        f"{len(a)} cache leaves identical across restore/prefill "
        f"({blocks}x{block}-token blocks)"
    )


def check_paged_alias_parity(
    cfg: ModelConfig, num_slots: int = 2, max_total: int = 16, block: int = 4
) -> str:
    """Paged-KV structural parity (the aliased-restore sibling of
    ``prefix_restore_parity``): (1) the per-slot views the paged step
    gathers through the block tables must be pytree/shape/dtype identical
    to the DENSE slot pool the model forward was written against — the
    precondition of byte-identical answers across ``--kv_layout``; (2) a
    restore through the pool — the host-block scatter write (an ALIASED
    device-tier hit is a pure table op and cannot perturb the pool by
    construction) — must leave the pool structurally indistinguishable
    from a chunked prefill over the same tokens, across plain/int8/GQA
    layouts (rolling windows are refused by the paged pool)."""
    import numpy as np

    from transformer_tpu.serve.scheduler import (
        _paged_views,
        _pool_write_blocks,
        _slot_prefill_paged,
        abstract_paged_pool,
        abstract_pool_caches,
    )

    pool_blocks = 1 + num_slots * (-(-max_total // block))
    pool, table, index = abstract_paged_pool(
        cfg, num_slots, max_total, pool_blocks, block
    )
    dense = abstract_pool_caches(cfg, num_slots, max_total)
    views = jax.eval_shape(
        lambda p, t, i: _paged_views(p, t, i, max_total), pool, table, index
    )
    a, b = _tree_spec(views), _tree_spec(dense)
    assert a == b, (
        "gathered paged views diverge from the dense slot pool:\n"
        f"  dense: {b}\n  paged: {a}"
    )

    params = abstract_params(cfg)
    n_blocks, n = 2, 2 * block
    i32 = lambda *shape: jax.ShapeDtypeStruct(shape, np.int32)  # noqa: E731
    after_prefill = jax.eval_shape(
        lambda p, c, tb, s, pr, st: _slot_prefill_paged(
            p, c, tb, s, pr, st, cfg, block, block, max_total
        )[1],
        params, pool, table, i32(), _ids(1, n), i32(),
    )
    host_blocks = [
        {
            key: jax.ShapeDtypeStruct(
                (n_blocks, block) + leaf.shape[2:], leaf.dtype
            )
            for key, leaf in layer.items()
        }
        for layer in pool
    ]
    after_restore = jax.eval_shape(
        _pool_write_blocks, pool, i32(n_blocks), host_blocks
    )
    p_spec = _tree_spec(after_prefill)
    r_spec = _tree_spec(after_restore)
    assert p_spec == r_spec == _tree_spec(list(pool)), (
        "restore and chunked prefill disagree on the pool layout:\n"
        f"  prefill: {p_spec}\n  restore: {r_spec}"
    )
    return (
        f"{len(a)} view leaves dense-identical; pool layout stable across "
        f"restore/prefill ({n_blocks}x{block}-token blocks)"
    )


def _walk_eqns(jaxpr) -> Iterable:
    """Every equation, recursing through pjit/scan/while/cond sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _as_jaxprs(v):
                yield from _walk_eqns(sub)


def _as_jaxprs(v) -> Iterable:
    if isinstance(v, jax.core.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, jax.core.Jaxpr):
        yield v
    elif isinstance(v, (list, tuple)):
        for item in v:
            yield from _as_jaxprs(item)


def check_softmax_f32(cfg: ModelConfig, batch: int = 2, length: int = 8) -> str:
    """Every ``exp`` in the forward jaxpr (softmax is the only exp in a
    relu/bf16 config) must consume f32 — the documented f32-softmax
    contract of ``dot_product_attention``."""
    from transformer_tpu.models.transformer import transformer_apply

    params = abstract_params(cfg)
    inp = None if (cfg.decoder_only or cfg.encoder_only) else _ids(batch, length)
    jaxpr = jax.make_jaxpr(
        lambda p, i, t: transformer_apply(p, i, t, cfg)
    )(params, inp, _ids(batch, length))
    exps = [e for e in _walk_eqns(jaxpr.jaxpr) if e.primitive.name == "exp"]
    assert exps, "no exp equation found — did softmax disappear from the forward?"
    bad = [
        str(e.invars[0].aval.dtype)
        for e in exps
        if e.invars[0].aval.dtype != jnp.float32
    ]
    assert not bad, (
        f"{len(bad)}/{len(exps)} exp ops run outside f32 ({sorted(set(bad))}) "
        f"under compute dtype {cfg.dtype} — the f32-softmax contract is broken"
    )
    return f"all {len(exps)} exp ops in f32"


def check_residual_dtype(cfg: ModelConfig, batch: int = 2, length: int = 8) -> str:
    """The pre-projection residual stream stays in the compute dtype — a
    silent promotion to f32 would double decode HBM traffic."""
    from transformer_tpu.models.transformer import transformer_hidden_apply

    params = abstract_params(cfg)
    inp = None if (cfg.decoder_only or cfg.encoder_only) else _ids(batch, length)
    hidden, _ = jax.eval_shape(
        lambda p, i, t: transformer_hidden_apply(p, i, t, cfg),
        params, inp, _ids(batch, length),
    )
    assert hidden.dtype == cfg.compute_dtype, (
        f"residual stream is {hidden.dtype}, compute dtype is "
        f"{cfg.compute_dtype} — silent promotion"
    )
    assert hidden.shape == (batch, length, cfg.d_model)
    return f"hidden (B,S,{cfg.d_model}) stays {hidden.dtype}"


def check_mask_broadcast(cfg: ModelConfig, batch: int = 2, length: int = 8) -> str:
    """All mask builders must broadcast against (B, H, S_q, S_k) logits."""
    from transformer_tpu.ops.masks import (
        make_cache_prefix_mask,
        make_causal_mask,
        make_padding_mask,
    )

    logits_shape = (batch, cfg.num_heads, length, length)

    def build(ids):
        return (
            make_padding_mask(ids),
            make_causal_mask(length, window=cfg.attention_window),
            make_cache_prefix_mask(jnp.int32(0), length, length),
        )

    pad, causal, prefix = jax.eval_shape(build, _ids(batch, length))
    for name, m in (("padding", pad), ("causal", causal), ("prefix", prefix)):
        assert m.dtype == jnp.bool_, f"{name} mask dtype {m.dtype} != bool"
        try:
            np.broadcast_shapes(m.shape, logits_shape)
        except ValueError as e:
            raise AssertionError(
                f"{name} mask {m.shape} does not broadcast to logits "
                f"{logits_shape}: {e}"
            ) from None
    return f"padding/causal/prefix masks broadcast to {logits_shape}"


def check_decode_shapes(cfg: ModelConfig, batch: int = 2) -> str:
    """Decode entry points return (B, max_len)/(B, max_new) int32 ids."""
    params = abstract_params(cfg)
    max_len = 6
    if cfg.decoder_only:
        from transformer_tpu.train.decode import lm_generate

        out = jax.eval_shape(
            lambda p, ids: lm_generate.__wrapped__(
                p, ids, cfg, max_len, eos_id=2, prefill_len=4
            ),
            params, _ids(batch, 5),
        )
        assert out.shape == (batch, max_len) and out.dtype == jnp.int32, (
            f"lm_generate -> {out.shape} {out.dtype}, want ({batch}, {max_len}) int32"
        )
        return f"lm_generate -> ({batch}, {max_len}) int32"
    from transformer_tpu.train.decode import beam_search_decode, greedy_decode

    greedy = jax.eval_shape(
        lambda p, src: greedy_decode.__wrapped__(p, src, cfg, max_len, 1, 2),
        params, _ids(batch, 5),
    )
    beam = jax.eval_shape(
        lambda p, src: beam_search_decode.__wrapped__(
            p, src, cfg, max_len, 1, 2, beam_size=2
        ),
        params, _ids(batch, 5),
    )
    for name, out in (("greedy_decode", greedy), ("beam_search_decode", beam)):
        assert out.shape == (batch, max_len) and out.dtype == jnp.int32, (
            f"{name} -> {out.shape} {out.dtype}, want ({batch}, {max_len}) int32"
        )
    return f"greedy+beam -> ({batch}, {max_len}) int32"


def check_train_step_dtypes(cfg: ModelConfig) -> str:
    """One abstract optimizer step: parameter dtypes preserved exactly
    (param_dtype — the optimizer must not let compute-dtype activations
    bleed into the master weights), metrics scalar f32, step advanced."""
    from transformer_tpu.train.state import TrainState, make_optimizer
    from transformer_tpu.train.trainer import make_train_step

    train_cfg = TINY_TRAIN
    if cfg.encoder_only:
        train_cfg = dataclasses.replace(train_cfg, objective="mlm")
    step_fn = make_train_step(cfg, train_cfg)
    params = abstract_params(cfg)

    def init_and_step(params, src, tgt, rng):
        tx = make_optimizer(cfg, train_cfg)
        state = TrainState(
            step=jnp.int32(0), params=params, opt_state=tx.init(params)
        )
        return step_fn(state, src, tgt, rng)

    B, L = train_cfg.batch_size, train_cfg.sequence_length
    new_state, metrics = jax.eval_shape(
        init_and_step, params, _ids(B, L), _ids(B, L), _KEY
    )
    before = _tree_spec(params)
    after = _tree_spec(new_state.params)
    assert before == after, (
        "optimizer step changed parameter shapes/dtypes:\n"
        f"  before: {before}\n  after:  {after}"
    )
    assert new_state.step.dtype == jnp.int32
    loss = metrics["loss"]
    assert loss.shape == () and loss.dtype == jnp.float32, (
        f"loss metric is {loss.shape} {loss.dtype}, want scalar f32"
    )
    return f"{len(after)} param leaves dtype-stable through the optimizer step"


def check_telemetry_inert(cfg: ModelConfig) -> str:
    """Instrumented and uninstrumented step functions must trace to
    byte-identical jaxprs. The instrumented twin is built with the real
    wrappers the telemetry-enabled Trainer installs around its step
    dispatches — ``obs.telemetry.timed_call`` feeding a live registry
    histogram + counter, COMPOSED with ``obs.profile.profile_call``
    recording into a live ProgramProfiler (the roofline sentinel) and
    ``obs.trace.traced_call`` opening a real span on a live tracer (the
    ``--trace`` stack, spans emitted through a live FlightRecorder tap
    into a real in-memory EventLog); the serving pool step, slot prefill,
    and speculative verify programs are traced through the same wrappers.
    Any
    future 'improvement' that lets a recorded value flow back into the
    computation — or adds so much as a ``convert_element_type`` to the
    trace — fails here, rounds before a byte-identity serving test would
    catch it on hardware. (The scheduler's own span recording is inline
    host code at step boundaries; its inertness is pinned by the
    byte-identity + zero-recompile tests in tests/test_obs.py and
    tests/test_trace.py.)"""
    import io

    from transformer_tpu.obs import MetricsRegistry
    from transformer_tpu.obs.events import EventLog
    from transformer_tpu.obs.flight import FlightRecorder
    from transformer_tpu.obs.profile import ProgramProfiler, profile_call
    from transformer_tpu.obs.telemetry import timed_call
    from transformer_tpu.obs.trace import Tracer, traced_call
    from transformer_tpu.train.state import TrainState, make_optimizer
    from transformer_tpu.train.trainer import make_train_step

    import re

    reg = MetricsRegistry()
    span_sink = io.StringIO()
    # Both PR-18 subsystems armed exactly as production arms them: the
    # flight recorder taps the tracer's emit path (every span rides the
    # ring), the profiler records through the registry.
    flight = FlightRecorder(None, capacity=64)
    tracer = Tracer(flight.tap(EventLog(span_sink).emit))
    profiler = ProgramProfiler(registry=reg)

    def canon(jaxpr) -> str:
        # custom_jvp equations print closure thunks with their memory
        # address (`jvp_jaxpr_thunk=<function ... at 0x...>`); two traces of
        # IDENTICAL programs differ there. Mask addresses, compare the rest
        # byte-for-byte.
        return re.sub(r"0x[0-9a-f]+", "0x", str(jaxpr))

    def twins(fn):
        # The exact production composition: traced_call outermost around
        # profile_call around timed_call
        # (trainer._wrap_steps_for_dispatch_timing order).
        wrapped = timed_call(
            fn, reg.histogram("contract_seconds"), reg.counter("contract_total")
        )
        wrapped = profile_call(wrapped, profiler, "contract.step")
        wrapped = traced_call(wrapped, tracer, "contract.step")
        return fn, wrapped

    checked = []

    # -- train step ---------------------------------------------------------
    train_cfg = TINY_TRAIN
    if cfg.encoder_only:
        train_cfg = dataclasses.replace(train_cfg, objective="mlm")
    step_fn = make_train_step(cfg, train_cfg)
    params = abstract_params(cfg)

    def driver(step):
        def init_and_step(params, src, tgt, rng):
            tx = make_optimizer(cfg, train_cfg)
            state = TrainState(
                step=jnp.int32(0), params=params, opt_state=tx.init(params)
            )
            return step(state, src, tgt, rng)

        return init_and_step

    B, L = train_cfg.batch_size, train_cfg.sequence_length
    plain, wrapped = twins(step_fn)
    a = canon(jax.make_jaxpr(driver(plain))(params, _ids(B, L), _ids(B, L), _KEY))
    b = canon(jax.make_jaxpr(driver(wrapped))(params, _ids(B, L), _ids(B, L), _KEY))
    assert a == b, "timed_call changed the TRAIN step jaxpr — telemetry leaked into traced code"
    checked.append("train_step")

    # -- serving pool step / prefill / verify (decoder-only exports) --------
    if cfg.decoder_only:
        from transformer_tpu.serve.scheduler import (
            _pool_step,
            _pool_verify,
            _slot_prefill,
            abstract_pool_caches,
        )

        slots, total = 2, 16
        pool = abstract_pool_caches(cfg, slots, total)
        toks = jax.ShapeDtypeStruct((slots,), np.int32)
        step_raw = _pool_step.__wrapped__
        plain, wrapped = twins(lambda p, c, t: step_raw(p, c, t, cfg))
        a = canon(jax.make_jaxpr(plain)(params, pool, toks))
        b = canon(jax.make_jaxpr(wrapped)(params, pool, toks))
        assert a == b, (
            "telemetry wrappers changed the POOL step jaxpr — telemetry "
            "leaked into traced serving code"
        )
        checked.append("pool_step")
        prefill_raw = _slot_prefill.__wrapped__
        prompt = jax.ShapeDtypeStruct((1, 8), np.int32)
        scalar = jax.ShapeDtypeStruct((), np.int32)
        plain, wrapped = twins(
            lambda p, c, s, pr, st: prefill_raw(p, c, s, pr, st, cfg, 0)
        )
        a = canon(jax.make_jaxpr(plain)(params, pool, scalar, prompt, scalar))
        b = canon(jax.make_jaxpr(wrapped)(params, pool, scalar, prompt, scalar))
        assert a == b, (
            "telemetry wrappers changed the SLOT prefill jaxpr — telemetry "
            "leaked into traced serving code"
        )
        checked.append("slot_prefill")
        if not cfg.attention_window:
            # Verify rides the same S_q>1 cache-write path rollback needs;
            # rolling-window configs refuse speculation, so the program
            # does not exist for them.
            verify_raw = _pool_verify.__wrapped__
            rows = jax.ShapeDtypeStruct((slots, 3), np.int32)
            plain, wrapped = twins(lambda p, c, t: verify_raw(p, c, t, cfg))
            a = canon(jax.make_jaxpr(plain)(params, pool, rows))
            b = canon(jax.make_jaxpr(wrapped)(params, pool, rows))
            assert a == b, (
                "telemetry wrappers changed the VERIFY jaxpr — telemetry "
                "leaked into traced serving code"
            )
            checked.append("pool_verify")
    assert reg.histogram("contract_seconds").hist.count >= len(checked), (
        "the instrumented twin never recorded — the contract exercised a "
        "dead wrapper"
    )
    assert tracer.stats["ended"] >= len(checked) and tracer.open_count == 0, (
        "the traced twin never opened/closed a span — the tracing side of "
        "the contract is vacuous"
    )
    assert "trace.span" in span_sink.getvalue(), (
        "the tracer's spans never reached the event log"
    )
    assert profiler.stats["records"] >= len(checked), (
        "the profiled twin never recorded — the profiler side of the "
        "contract is vacuous"
    )
    assert flight.depth() > 0 and flight.dump("request")["spans"], (
        "the tracer's spans never rode the flight-recorder ring"
    )
    return (
        "jaxpr-identical twins (timed+profiled+traced, flight armed): "
        f"{', '.join(checked)}"
    )


def check_fault_plane_inert(cfg: ModelConfig) -> str:
    """Armed-vs-disarmed fault-plane twins of the serving hot paths must
    trace to byte-identical jaxprs (see module docstring): the plane is
    host-side by construction, and this contract keeps it that way."""
    import re

    from transformer_tpu.serve import resilience
    from transformer_tpu.serve.scheduler import (
        _pool_step,
        _slot_prefill,
        abstract_pool_caches,
    )

    def canon(jaxpr) -> str:
        return re.sub(r"0x[0-9a-f]+", "0x", str(jaxpr))

    params = abstract_params(cfg)
    slots, total = 2, 16
    pool = abstract_pool_caches(cfg, slots, total)
    toks = jax.ShapeDtypeStruct((slots,), np.int32)
    prompt = jax.ShapeDtypeStruct((1, 8), np.int32)
    slot = jax.ShapeDtypeStruct((), np.int32)
    start = jax.ShapeDtypeStruct((), np.int32)
    step_raw = _pool_step.__wrapped__
    prefill_raw = _slot_prefill.__wrapped__

    def trace_all():
        a = canon(jax.make_jaxpr(
            lambda p, c, t: step_raw(p, c, t, cfg))(params, pool, toks))
        b = canon(jax.make_jaxpr(
            lambda p, c, s, pr, st: prefill_raw(p, c, s, pr, st, cfg, 0)
        )(params, pool, slot, prompt, start))
        return a, b

    plane = resilience.FaultPlane.parse("serve.prefill:p=1")
    disarmed = trace_all()
    with resilience.active(plane):
        armed = trace_all()
        # Non-vacuous: the armed plane really fires at its host-side site.
        fired = False
        try:
            resilience.maybe_fail("serve.prefill")
        except resilience.InjectedFault:
            fired = True
        assert fired, "armed fault plane never fired — the contract is vacuous"
    assert disarmed[0] == armed[0], (
        "an armed fault plane changed the POOL step jaxpr — injection "
        "leaked into traced serving code"
    )
    assert disarmed[1] == armed[1], (
        "an armed fault plane changed the SLOT prefill jaxpr — injection "
        "leaked into traced serving code"
    )
    return "jaxpr-identical armed/disarmed twins: pool_step, slot_prefill"


# --------------------------------------------------------------------------
# driver

_CONTRACTS: list[tuple[str, Callable[[ModelConfig], str], Callable[[ModelConfig], bool]]] = [
    ("cache_parity", check_cache_parity, lambda c: not c.encoder_only),
    # Speculation serves the LM path only; the structural parity still
    # covers every cache variant (plain/int8/rolling/GQA) — rolling caches
    # can't ROLL BACK, but their verify writes must still match steps.
    ("verify_cache_parity", check_verify_cache_parity, lambda c: c.decoder_only),
    # The prefix cache refuses rolling-window caches (absolute-position
    # rows are evicted on wrap), so the restore/prefill structural parity
    # applies to every OTHER LM cache variant: plain, int8, GQA.
    (
        "prefix_restore_parity",
        check_prefix_restore_parity,
        lambda c: c.decoder_only and not c.attention_window,
    ),
    # The paged pool refuses rolling windows for the same reason the
    # prefix cache does; every other LM cache variant must gather views
    # dense-identical and keep the pool layout stable across restore and
    # prefill.
    (
        "paged_alias_parity",
        check_paged_alias_parity,
        lambda c: c.decoder_only and not c.attention_window,
    ),
    ("softmax_f32", check_softmax_f32, lambda c: True),
    ("residual_dtype", check_residual_dtype, lambda c: True),
    ("mask_broadcast", check_mask_broadcast, lambda c: True),
    ("decode_shapes", check_decode_shapes, lambda c: not c.encoder_only),
    ("train_step_dtypes", check_train_step_dtypes, lambda c: True),
    ("telemetry_inert", check_telemetry_inert, lambda c: True),
    # Fault injection serves the continuous-batching (decoder-only) tier;
    # the armed/disarmed jaxpr identity covers its two hot-path shapes.
    ("fault_plane_inert", check_fault_plane_inert, lambda c: c.decoder_only),
]


def run_contracts(matrix_name: str = "fast") -> list[ContractResult]:
    """Trace every applicable (contract, config) pair; failures are captured
    as results, never raised (the CLI exits non-zero when any ``ok`` is
    False)."""
    results: list[ContractResult] = []
    for cfg_name, cfg in matrix(matrix_name).items():
        for contract_name, fn, applies in _CONTRACTS:
            if not applies(cfg):
                continue
            try:
                detail = fn(cfg)
                ok = True
            except AssertionError as e:
                detail, ok = str(e), False
            results.append(
                ContractResult(
                    contract=contract_name, config=cfg_name, ok=ok, detail=detail
                )
            )
    return results


def summarize(results: list[ContractResult]) -> str:
    failed = [r for r in results if not r.ok]
    lines = [str(r) for r in (failed or results)]
    lines.append(
        f"{len(results) - len(failed)}/{len(results)} contracts hold"
        + ("" if not failed else f" — {len(failed)} FAILED")
    )
    return "\n".join(lines)
