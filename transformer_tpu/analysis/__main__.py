"""``python -m transformer_tpu.analysis`` — the static-analysis CLI.

Subcommands (all CPU-safe; exit code 0 = clean, 1 = findings/violations):

- ``rules [--paths P ...] [--baseline FILE] [--update-baseline]`` — AST lint
  rules TPA001–TPA007 over the package (or explicit paths).
- ``concurrency [--paths P ...] [--baseline FILE] [--update-baseline]`` —
  concurrency rules TPA101–TPA105 (thread-root inference, shared-state
  guards, lock-order cycles, blocking-under-lock) over the same surface.
- ``sharding [--paths P ...] [--baseline FILE] [--update-baseline]`` —
  sharding lints TPA201–TPA205 (unconstrained boundary shardings, mesh-axis
  typos, donation/layout mismatches, collectives in the decode hot loop,
  replicated large params).
- ``schedules [--max-schedules N] [--seed S] [--scenario NAME ...]`` — the
  deterministic interleaving checker: cooperatively explores thread
  schedules over canned serving-tier scenarios, asserting their invariants
  under every explored interleaving.
- ``contracts [--matrix fast|full]`` — abstract shape/dtype contract checks
  via ``jax.eval_shape``/``jax.make_jaxpr`` (no device execution).
- ``retrace [--steps N]`` — compile-count sentinel over the steady-state
  decode and train hot paths (0 new programs allowed after warmup).
- ``costs [--baseline FILE] [--update-baseline]`` — the jaxpr cost model:
  peak live-buffer bytes (donation-aware liveness), FLOPs, bytes moved,
  arithmetic intensity, and the collective inventory for every canned
  program, gated against ``analysis/costs_baseline.json`` budgets.
- ``kernels [--paths P ...] [--baseline FILE] [--update-baseline]
  [--generation G]`` — the TPA300 Pallas kernel verifier: grid/BlockSpec
  conformance + index-map bounds enumerated over every grid, a
  per-grid-step VMEM footprint model gated against
  ``analysis/kernels_baseline.json``, and kernel-safety lints TPA301–305
  — all abstract, zero device execution.
- ``all [--only FAMILY,...]`` — every family above (8 families) with ONE
  aggregate exit code: the pre-merge gate (docs/ANALYSIS.md).

``--format=json`` emits machine-readable output on every subcommand so
rounds can diff finding counts like a bench (``bench.py`` row style).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _ensure_cpu_devices(n: int = 8) -> None:
    """Give jax-backed subcommands the same virtual 8-CPU-device platform
    tests/conftest.py forces, so the sharded canned programs (costs /
    sharding inventory) trace identically under the CLI and under pytest.
    XLA reads the flags at backend initialization, which is lazy — so this
    works even though importing ``transformer_tpu.analysis`` already
    imported jax, as long as nothing has asked for devices yet. If a
    backend IS already up with fewer devices, the multi-device programs are
    skipped (and reported as such) rather than traced at different
    shapes."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    try:
        # This environment may pre-register accelerator PJRT plugins via
        # sitecustomize; flipping the config keeps the analyses CPU-only
        # regardless (mirrors tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized on some platform; use as-is


def _emit(payload: dict, text: str, fmt: str) -> None:
    print(json.dumps(payload, indent=2, sort_keys=True) if fmt == "json" else text)


def _lint_command(args: argparse.Namespace, run_fn, default_baseline_fn) -> int:
    """Shared driver for the two lint families (rules / concurrency):
    baseline resolution, --update-baseline, report emission, exit code."""
    from transformer_tpu.analysis.rules import write_baseline

    baseline = args.baseline
    if baseline is None and not args.paths:
        baseline = default_baseline_fn()
    report = run_fn(paths=args.paths or None, baseline_path=baseline)
    if args.update_baseline:
        path = baseline or default_baseline_fn()
        write_baseline(report, path)
        print(
            f"baselined {len(report.findings) + len(report.baselined)} "
            f"finding(s) -> {path}"
        )
        return 0
    lines = [str(f) for f in report.findings]
    lines.append(
        f"{len(report.findings)} finding(s) across {report.files_checked} "
        f"file(s) ({len(report.baselined)} baselined)"
    )
    _emit(report.to_dict(), "\n".join(lines), args.format)
    return 1 if report.findings else 0


def _cmd_rules(args: argparse.Namespace) -> int:
    from transformer_tpu.analysis.rules import default_baseline_path, run_rules

    return _lint_command(args, run_rules, default_baseline_path)


def _cmd_concurrency(args: argparse.Namespace) -> int:
    from transformer_tpu.analysis.concurrency import (
        default_concurrency_baseline_path,
        run_concurrency,
    )

    return _lint_command(args, run_concurrency, default_concurrency_baseline_path)


def _cmd_sharding(args: argparse.Namespace) -> int:
    from transformer_tpu.analysis.sharding import (
        default_sharding_baseline_path,
        run_sharding,
    )

    return _lint_command(args, run_sharding, default_sharding_baseline_path)


def _cmd_costs(args: argparse.Namespace) -> int:
    _ensure_cpu_devices()
    from transformer_tpu.analysis.costs import (
        default_costs_baseline_path,
        run_costs,
        summarize,
        write_costs_baseline,
    )

    baseline = args.baseline or default_costs_baseline_path()
    result = run_costs(baseline_path=baseline, compare=not args.update_baseline)
    if args.update_baseline:
        # Programs skipped on this host (insufficient devices) keep their
        # existing budget entries — updating from a small host must not
        # silently drop the sharded collective budgets from CI.
        from transformer_tpu.analysis.costs import load_costs_baseline

        keep = {
            name: entry
            for name, entry in load_costs_baseline(baseline)
            .get("programs", {})
            .items()
            if name in result.skipped
        }
        write_costs_baseline(result.reports, result.kv, baseline, keep=keep)
        for name in result.skipped:
            print(
                f"warning: {name} skipped on this host — "
                + ("existing budget carried forward"
                   if name in keep else "NO budget exists for it"),
                file=sys.stderr,
            )
        print(
            f"budgeted {len(result.reports)} program(s) + "
            f"{len(result.kv)} kv variant(s)"
            + (f" (+{len(keep)} carried forward)" if keep else "")
            + f" -> {baseline}"
        )
        return 0
    _emit(result.to_dict(), summarize(result), args.format)
    return 0 if result.ok else 1


def _cmd_kernels(args: argparse.Namespace) -> int:
    _ensure_cpu_devices()
    from transformer_tpu.analysis.kernels import (
        default_kernels_baseline_path,
        run_kernels,
        summarize_kernels,
        write_kernels_baseline,
    )

    baseline = args.baseline
    if baseline is None and not args.paths:
        baseline = default_kernels_baseline_path()
    result = run_kernels(
        paths=args.paths or None,
        baseline_path=baseline,
        compare=not args.update_baseline,
        generation=getattr(args, "generation", None),
    )
    if args.update_baseline:
        path = baseline or default_kernels_baseline_path()
        if result.violations:
            # Conformance/race/budget breaches are never baselineable.
            for v in result.violations:
                print(f"VIOLATION: {v}", file=sys.stderr)
            return 1
        write_kernels_baseline(result, path)
        print(
            f"banked {len(result.reports)} kernel(s), grandfathered "
            f"{len(result.findings)} finding(s) -> {path}"
        )
        return 0
    _emit(result.to_dict(), summarize_kernels(result), args.format)
    return 0 if result.ok else 1


def _cmd_all(args: argparse.Namespace) -> int:
    """Every analysis family, one aggregate exit code — the pre-merge gate."""
    _ensure_cpu_devices()
    ns = argparse.Namespace(
        paths=None, baseline=None, update_baseline=False,
        format=args.format, matrix="fast", steps=3,
        scenario=None, max_schedules=64, seed=0,
    )
    families = {
        "rules": _cmd_rules,
        "concurrency": _cmd_concurrency,
        "sharding": _cmd_sharding,
        "schedules": _cmd_schedules,
        "contracts": _cmd_contracts,
        "retrace": _cmd_retrace,
        "costs": _cmd_costs,
        "kernels": _cmd_kernels,
    }
    only = (
        [f.strip() for f in args.only.split(",") if f.strip()]
        if args.only else list(families)
    )
    unknown = [f for f in only if f not in families]
    if unknown:
        print(f"unknown famil{'y' if len(unknown) == 1 else 'ies'}: "
              f"{', '.join(unknown)} (choose from {', '.join(families)})",
              file=sys.stderr)
        return 2
    # In text mode each family gets a header; in json mode the output is a
    # stream of family JSON objects (headers/summary ride stderr so the
    # stream stays machine-readable).
    info = sys.stdout if args.format == "text" else sys.stderr
    results: dict[str, int] = {}
    for name in only:
        print(f"== {name} ==", file=info)
        results[name] = families[name](ns)
    failed = sorted(name for name, rc in results.items() if rc != 0)
    print(
        f"{len(results) - len(failed)}/{len(results)} families clean"
        + (f" — FAILED: {', '.join(failed)}" if failed else ""),
        file=info,
    )
    return 1 if failed else 0


def _cmd_schedules(args: argparse.Namespace) -> int:
    from transformer_tpu.analysis.schedules import run_scenarios

    results = run_scenarios(
        names=args.scenario or None,
        max_schedules=args.max_schedules,
        seed=args.seed,
    )
    ok = all(not r.violations and not r.deadlocks for r in results)
    total = sum(r.schedules for r in results)
    lines = []
    for r in results:
        status = "PASS" if not r.violations and not r.deadlocks else "FAIL"
        lines.append(
            f"{status} {r.name}: {r.schedules} schedule(s) explored, "
            f"{len(r.violations)} violation(s), {r.deadlocks} deadlock(s)"
        )
        for v in r.violations[:5]:
            lines.append(f"  - {v.kind}: {v.detail}")
    lines.append(f"{total} interleaving(s) explored across {len(results)} scenario(s)")
    payload = {
        "ok": ok,
        "total_schedules": total,
        "scenarios": [r.to_dict() for r in results],
    }
    _emit(payload, "\n".join(lines), args.format)
    return 0 if ok else 1


def _cmd_contracts(args: argparse.Namespace) -> int:
    from transformer_tpu.analysis.configs import describe, matrix
    from transformer_tpu.analysis.contracts import run_contracts, summarize

    results = run_contracts(args.matrix)
    payload = {
        "matrix": args.matrix,
        "configs": {
            name: describe(cfg) for name, cfg in matrix(args.matrix).items()
        },
        "passed": sum(r.ok for r in results),
        "total": len(results),
        "results": [r.to_dict() for r in results],
    }
    _emit(payload, summarize(results), args.format)
    return 0 if all(r.ok for r in results) else 1


def _cmd_retrace(args: argparse.Namespace) -> int:
    _ensure_cpu_devices()  # the sharded scenario needs a >= 2-device mesh
    from transformer_tpu.analysis.retrace import (
        decode_retrace_report,
        paged_retrace_report,
        prefix_cache_retrace_report,
        resilience_retrace_report,
        sharded_retrace_report,
        speculative_retrace_report,
        train_retrace_report,
        upgrade_retrace_report,
    )

    deltas = (
        decode_retrace_report(steps=args.steps)
        + speculative_retrace_report(steps=args.steps)
        + prefix_cache_retrace_report(steps=args.steps)
        + paged_retrace_report(steps=args.steps)
        + resilience_retrace_report(steps=args.steps)
        + upgrade_retrace_report(steps=args.steps)
        + train_retrace_report(steps=args.steps)
        + sharded_retrace_report(steps=args.steps)
    )
    ok = all(d.within_budget for d in deltas)
    text = "\n".join(
        f"{'PASS' if d.within_budget else 'FAIL'} {d.name}: "
        f"{d.compiles} recompile(s) over {args.steps} steady-state steps "
        f"(budget {d.budget})"
        for d in deltas
    )
    payload = {
        "steps": args.steps,
        "ok": ok,
        "watches": [d.to_dict() for d in deltas],
    }
    _emit(payload, text, args.format)
    return 0 if ok else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m transformer_tpu.analysis",
        description="JAX-aware static analysis: lint rules, abstract "
        "shape/dtype contracts, retrace sentinel",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_rules = sub.add_parser("rules", help="AST lint rules (TPA001-TPA006)")
    p_rules.add_argument(
        "--paths", nargs="*", default=None,
        help="files/dirs to lint (default: the transformer_tpu package)",
    )
    p_rules.add_argument(
        "--baseline", default=None,
        help="baseline JSON (default: analysis/baseline.json for package lints)",
    )
    p_rules.add_argument(
        "--update-baseline", action="store_true",
        help="grandfather every current finding into the baseline file",
    )

    p_conc = sub.add_parser(
        "concurrency", help="concurrency lint rules (TPA101-TPA105)"
    )
    p_conc.add_argument(
        "--paths", nargs="*", default=None,
        help="files/dirs to analyze (default: the transformer_tpu package)",
    )
    p_conc.add_argument(
        "--baseline", default=None,
        help="baseline JSON (default: analysis/concurrency_baseline.json "
        "for package runs)",
    )
    p_conc.add_argument(
        "--update-baseline", action="store_true",
        help="grandfather every current finding into the baseline file",
    )

    p_shard = sub.add_parser(
        "sharding", help="sharding lint rules (TPA201-TPA205)"
    )
    p_shard.add_argument(
        "--paths", nargs="*", default=None,
        help="files/dirs to analyze (default: the transformer_tpu package)",
    )
    p_shard.add_argument(
        "--baseline", default=None,
        help="baseline JSON (default: analysis/sharding_baseline.json "
        "for package runs)",
    )
    p_shard.add_argument(
        "--update-baseline", action="store_true",
        help="grandfather every current finding into the baseline file",
    )

    p_costs = sub.add_parser(
        "costs", help="jaxpr cost model: peak bytes / FLOPs / collectives "
        "vs. budget baselines"
    )
    p_costs.add_argument(
        "--baseline", default=None,
        help="budget JSON (default: analysis/costs_baseline.json)",
    )
    p_costs.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the budget baseline with the current numbers",
    )

    p_kern = sub.add_parser(
        "kernels", help="Pallas kernel verifier (TPA300-TPA305): grid/"
        "BlockSpec conformance, VMEM budgets, safety lints"
    )
    p_kern.add_argument(
        "--paths", nargs="*", default=None,
        help="modules declaring ANALYSIS_KERNEL_ENTRIES to verify "
        "(default: the package's canned kernel entries)",
    )
    p_kern.add_argument(
        "--baseline", default=None,
        help="baseline JSON (default: analysis/kernels_baseline.json "
        "for package runs)",
    )
    p_kern.add_argument(
        "--update-baseline", action="store_true",
        help="bank current VMEM/FLOPs numbers and grandfather lint findings",
    )
    p_kern.add_argument(
        "--generation", choices=("v4", "v5e", "v5p", "v6e"), default=None,
        help="TPU generation for the VMEM budget (default v5e)",
    )

    p_all = sub.add_parser(
        "all", help="run every analysis family; one aggregate exit code "
        "(the pre-merge gate)"
    )
    p_all.add_argument(
        "--only", default=None,
        help="comma-separated family subset (rules,concurrency,sharding,"
        "schedules,contracts,retrace,costs,kernels)",
    )

    p_sched = sub.add_parser(
        "schedules", help="deterministic interleaving checker (canned scenarios)"
    )
    p_sched.add_argument(
        "--scenario", nargs="*", default=None,
        help="scenario names to run (default: all canned scenarios)",
    )
    p_sched.add_argument(
        "--max-schedules", type=int, default=64,
        help="bounded-exhaustive schedule cap per scenario (default 64)",
    )
    p_sched.add_argument(
        "--seed", type=int, default=0,
        help="seed for random-schedule mode (scenarios with > 2 threads)",
    )

    p_contracts = sub.add_parser(
        "contracts", help="abstract shape/dtype contract checks (eval_shape)"
    )
    p_contracts.add_argument(
        "--matrix", choices=("fast", "full"), default="fast",
        help="config matrix: fast = tier-1 set, full = architectural spread",
    )

    p_retrace = sub.add_parser(
        "retrace", help="compile-count sentinel over decode/train hot paths"
    )
    p_retrace.add_argument(
        "--steps", type=int, default=3,
        help="steady-state iterations after warmup (default 3)",
    )

    for p in (
        p_rules, p_conc, p_shard, p_costs, p_kern, p_all, p_sched,
        p_contracts, p_retrace,
    ):
        p.add_argument(
            "--format", choices=("text", "json"), default="text",
            help="output format (json is diff-able across rounds)",
        )

    args = parser.parse_args(argv)
    return {
        "rules": _cmd_rules,
        "concurrency": _cmd_concurrency,
        "sharding": _cmd_sharding,
        "costs": _cmd_costs,
        "kernels": _cmd_kernels,
        "all": _cmd_all,
        "schedules": _cmd_schedules,
        "contracts": _cmd_contracts,
        "retrace": _cmd_retrace,
    }[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
